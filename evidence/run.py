"""Quality-evidence harness: run the full drivers on the seeded synthetic corpus
and commit the numbers (evidence/results.json + evidence/RESULTS.md).

The reference ships its evidence in-repo (starspace/train.log:115-121 — early
stopping loss 0.018963 @ epoch 16 — and the uci_*_embed.txt dumps, plus the
AUROC comparison in prepare_starspace_formatted_data.ipynb cells 9-13). This
repo's mount has no real UCI parquet (/root/reference/.MISSING_LARGE_BLOBS), so
the committed record is the seeded synthetic-corpus equivalent: the full
online-mining driver (12 AUROCs), the precomputed-triplet driver, and the
native StarSpace baseline, with the quality claims asserted, not just printed:

  * encoded embeddings must beat BOTH chance and the tf-idf representation on
    the mined Category label, train and validate splits (the reference's
    headline comparison);
  * the StarSpace baseline must converge to a finite early-stopping loss.

Reproduce:  python evidence/run.py          (TPU when the tunnel is alive)
            python evidence/run.py --cpu    (force CPU: sets the platform
                                             before jax import AND via
                                             jax.config — the env var alone is
                                             ignored by the axon site hook)
(runs the drivers in a scratch dir; rewrites evidence/{results.json,RESULTS.md})
"""

import datetime
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

SEED = 0
MAIN_ARGS = [
    "--model_name", "evidence", "--synthetic", "--validation",
    "--num_epochs", "25", "--train_row", "1500", "--validate_row", "400",
    "--max_features", "2000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--triplet_strategy", "batch_all", "--alpha", "1.0",
    "--corr_type", "masking", "--corr_frac", "0.3", "--seed", str(SEED),
]
# alpha 10 / 40 epochs / corr_frac 0.1 is the round-4 sweep frontier
# (evidence/triplet_sweep.json): the three-tower objective reconstructs
# org/pos/neg jointly, so the heavy masking (0.3) the online-mining driver
# prefers drowns the margin gradient here — at 0.1 the same model goes from
# losing to binary counts to beating tfidf
TRIPLET_ARGS = [
    "--model_name", "evidence_triplet", "--synthetic", "--validation",
    "--num_epochs", "40", "--train_row", "800", "--validate_row", "200",
    "--max_features", "2000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5", "--alpha", "10.0",
    "--corr_type", "masking", "--corr_frac", "0.1", "--seed", str(SEED),
]
# trains on the EXACT split the online-mining stage saved (--from_artifacts is
# appended at run time with that stage's data dir), the way the reference
# notebook exports the DAE run's own split — so the three-way
# DAE/tfidf/StarSpace table is one corpus by construction
STARSPACE_ARGS = [
    "--model_name", "evidence_ss",
    "--max_features", "2000", "--dim", "50", "--epochs", "30",
    "--threads", "4", "--seed", str(SEED),
]
# the reference driver's other mining label (main_autoencoder.py:180-198
# exposes label=story|category_publish_name): same generator/schedule as
# MAIN_ARGS but mined on `story`, 1000/300 splits with 4x oversampling (only
# ~35% of synthetic articles carry a story, and the driver filters to
# story-valid rows exactly like the reference) — documents the
# Category/Story trade-off
STORY_ARGS = [a for a in MAIN_ARGS]
STORY_ARGS[STORY_ARGS.index("evidence")] = "evidence_story"
STORY_ARGS[STORY_ARGS.index("--train_row") + 1] = "1000"
STORY_ARGS[STORY_ARGS.index("--validate_row") + 1] = "300"
# alpha 30 is the round-4 sweep frontier (evidence/story_sweep.json, 13
# configs over alpha/corr_frac/epochs/compress_factor): the story slices are
# only 50 words, so the margin term needs far more weight than Category mining
# for the embedding to hold story geometry on the validate split
STORY_ARGS[STORY_ARGS.index("--alpha") + 1] = "30.0"
STORY_ARGS += ["--label", "story", "--synthetic_oversample", "4.0"]
# same corpus as MAIN_ARGS by construction (the evidence check claims it);
# the routed mixture gets a longer schedule — each expert sees ~1/E of the
# rows per epoch, and 25 epochs leaves the mixture at 0.58 AUROC (measured)
# while 60 converges it to ~0.79
assert MAIN_ARGS[0] == "--model_name"
MOE_ARGS = (["--model_name", "evidence_moe"] + MAIN_ARGS[2:]
            + ["--n_experts", "4", "--eval_reps", "encoded"])
MOE_ARGS[MOE_ARGS.index("--num_epochs") + 1] = "60"
# the reference's headline workload shape: 8000 rows x 10000 features -> 500
# (main_autoencoder.py:50 compress_factor 20, :60 batch 10%), bf16 compute,
# streaming eval tail
REFSCALE_ARGS = [
    "--model_name", "evidence_refscale", "--synthetic",
    "--synthetic_vocab", "12000", "--validation",
    "--num_epochs", "50", "--train_row", "8000", "--validate_row", "2000",
    "--max_features", "10000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--triplet_strategy", "batch_all", "--alpha", "1.0",
    "--corr_type", "masking", "--corr_frac", "0.3",
    "--compute_dtype", "bfloat16", "--streaming_eval", "--seed", str(SEED),
]
# the headline workload shape mined on STORY (VERDICT r4 item 3: the
# story-mining knob that rescued Story at small scale had never been run at
# reference scale). Same shape/schedule as REFSCALE_ARGS; alpha 30 is the
# story-sweep frontier; 3x oversampling fills the story-valid splits (~35%
# of synthetic rows carry a story)
REFSTORY_ARGS = [a for a in REFSCALE_ARGS]
REFSTORY_ARGS[REFSTORY_ARGS.index("evidence_refscale")] = "evidence_refstory"
REFSTORY_ARGS[REFSTORY_ARGS.index("--alpha") + 1] = "30.0"
REFSTORY_ARGS += ["--label", "story", "--synthetic_oversample", "3.0"]
# the triplet recipe keyed on STORY instead of category (net-new --label
# story on the triplet driver): the reference's per-category pos/neg mapping
# carries no Story signal by construction (positives are same-CATEGORY
# neighbors, datasets/articles.py:83-128), which is why the category-keyed
# triplet run's Story cell sits at chance; this stage proves the same triplet
# machinery carries Story when the mapping is keyed on it. alpha 30 /
# corr 0.3 is the round-5 grid frontier (evidence/triplet_story_keyed.json)
TRIPLET_STORY_ARGS = [a for a in TRIPLET_ARGS]
TRIPLET_STORY_ARGS[TRIPLET_STORY_ARGS.index("evidence_triplet")] = (
    "evidence_triplet_story")
TRIPLET_STORY_ARGS[TRIPLET_STORY_ARGS.index("--alpha") + 1] = "30.0"
TRIPLET_STORY_ARGS[TRIPLET_STORY_ARGS.index("--corr_frac") + 1] = "0.3"
TRIPLET_STORY_ARGS += ["--label", "story", "--synthetic_oversample", "4.0"]
# BASELINE config 5: stacked 2-layer DAE pretrain -> GRU user-state RNN over
# per-user article-embedding sequences (the paper pipeline the reference never
# implemented) — held-out pairwise rank accuracy vs the 0.5 chance level and
# interest-category top-1 vs ~1/8 chance
USER_ARGS = [
    "--model_name", "evidence_user", "--seed", str(SEED),
    "--n_articles", "1200", "--max_features", "1500",
    "--stacked_layers", "128,64", "--finetune_epochs", "2", "--dae_epochs", "5",
    "--n_users", "2500", "--seq_len", "20", "--gru_epochs", "15",
]


CACHE = os.path.join(HERE, ".stage_cache.json")


def _fingerprint():
    """Stage results are only reusable for the exact driver args + seed + CODE
    that produced them — a cache from an edited configuration or an edited
    repo must invalidate, or stale numbers would be committed under the new
    flags/code. Code state = HEAD + a stable hash of the working-tree diff
    (PROGRESS.jsonl excluded: the round driver rewrites it every few minutes,
    and its churn must not invalidate an otherwise-identical resume)."""
    import hashlib
    import subprocess

    def git(*argv):
        return subprocess.run(["git", *argv], cwd=REPO, capture_output=True,
                              text=True).stdout

    try:
        head = git("rev-parse", "HEAD").strip()
        diff = git("diff", "HEAD", "--", ".", ":(exclude)PROGRESS.jsonl")
        names = "\n".join(l for l in git("status", "--porcelain").splitlines()
                          if "PROGRESS.jsonl" not in l)
        code = hashlib.sha256((diff + names).encode()).hexdigest()
    except OSError:
        head, code = "nogit", "nogit"
    return json.dumps([head, code, SEED, MAIN_ARGS, TRIPLET_ARGS,
                       STARSPACE_ARGS, STORY_ARGS, MOE_ARGS, REFSCALE_ARGS,
                       USER_ARGS, TRIPLET_STORY_ARGS, REFSTORY_ARGS])


def _load_cache():
    try:
        with open(CACHE) as f:
            cache = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}  # absent, or truncated by a kill mid-write: start fresh
    if cache.get("fingerprint") != _fingerprint():
        print("stage cache is from a different configuration; ignoring it")
        return {}
    return cache


def _read_trajectory(metrics_dir, tags):
    """Per-TRAIN-STEP series {tag: [values]} from a MetricsWriter
    metrics.jsonl (the estimator logs scalars once per batch,
    models/estimator.py:442; records are ordered by step)."""
    out = {t: [] for t in tags}
    last_step = None
    with open(os.path.join(metrics_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("tag") not in out or "value" not in rec:
                continue
            step = rec.get("step")
            if step is not None and last_step is not None and step < last_step:
                # MetricsWriter appends: a step reset means an earlier fit's
                # records precede this one (e.g. a reused results dir). Keep
                # only the final monotonic run so first-vs-last-decile checks
                # never compare across runs.
                out = {t: [] for t in tags}
            if step is not None:
                last_step = step
            out[rec["tag"]].append(round(float(rec["value"]), 6))
    return out


STAGE_PROVENANCE = {}  # name -> {platform, run_id}; collected per main() run


def _staged(name, fn, platform="?", run_id="?"):
    """Stage-level resume: each completed stage's outputs persist to
    evidence/.stage_cache.json, so a mid-run TPU-tunnel hang (observed: the
    tunnel can die for hours mid-stage) only costs the stage in flight — rerun
    and the finished stages reload. Stages are seed-deterministic, so cached
    results are the same numbers a fresh run would commit. Delete the cache
    file (or let a successful run do it) to force everything fresh.

    Every stage records WHICH platform and run produced it; the committed
    record reports per-stage provenance, and a record whose stages span
    platforms/runs says so instead of claiming the header platform for all
    (the round-2 record spliced CPU stages into a TPU header — never again)."""
    cache = _load_cache()
    stages = cache.setdefault("stages", {})
    if name in stages:
        entry = stages[name]
        prov = entry.get("provenance", {"platform": "unknown",
                                        "run_id": "unknown"})
        print(f"== {name} == (cached: platform={prov['platform']} "
              f"run={prov['run_id']})")
        STAGE_PROVENANCE[name] = prov
        return entry["out"]
    print(f"== {name} ==")
    out = fn()
    prov = {"platform": platform, "run_id": run_id}
    stages[name] = {"out": out, "provenance": prov}
    STAGE_PROVENANCE[name] = prov
    cache["fingerprint"] = _fingerprint()
    tmp = CACHE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cache, f)
    os.replace(tmp, CACHE)  # atomic: a kill mid-dump can't truncate the cache
    return out


# the reference commits its evidence figures (the AUROC-comparison notebook's
# persisted outputs); these are ours — a small committed subset of the driver's
# ROC/boxplot PNGs, refreshed by every full evidence run
FIGURES = ("similarity_boxplot_encoded(Category)",
           "similarity_boxplot_encoded_validate(Category)",
           "similarity_boxplot_tfidf_validate(Category)")


def _export_figures(plot_dir, stage, platform):
    """Copy the stage's headline ROC/boxplot figures into evidence/figures/
    (tracked), with a provenance sidecar naming the run that produced them.
    Stale figures from earlier runs of the same stage are pruned so the tracked
    set never mixes runs; a missing source PNG is logged, not silently skipped."""
    import shutil

    fig_dir = os.path.join(HERE, "figures")
    os.makedirs(fig_dir, exist_ok=True)
    copied = []
    for name in FIGURES:
        src = os.path.join(plot_dir, name + ".png")
        if not os.path.exists(src):
            print(f"figures: WARNING — {stage} produced no {name}.png; "
                  "not exported")
            continue
        dst = f"{stage}_{name}.png"
        shutil.copyfile(src, os.path.join(fig_dir, dst))
        copied.append(dst)
    for f in os.listdir(fig_dir):
        if (f.startswith(stage + "_") and f.endswith(".png")
                and f not in copied):
            os.remove(os.path.join(fig_dir, f))
            print(f"figures: pruned stale {f} (not produced by this run)")
    prov = os.path.join(fig_dir, f"{stage}.provenance.txt")
    if copied:
        stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
        with open(prov, "w") as f:
            print(f"stage={stage} platform={platform} seed={SEED} "
                  f"generated={stamp}", file=f)
            for c in copied:
                print(c, file=f)
    elif os.path.exists(prov):
        # this run produced no figures and the pruning above removed the old
        # ones — a surviving sidecar would list files that no longer exist
        os.remove(prov)
        print(f"figures: removed stale {stage}.provenance.txt "
              "(no figures produced by this run)")
    return copied


def _check_figures(stage, names):
    """A stage resumed from cache exports nothing — verify its previously
    exported figures are still on disk, so RESULTS.md can't claim figures that
    a clean wiped."""
    fig_dir = os.path.join(HERE, "figures")
    missing = [n for n in names if not os.path.exists(os.path.join(fig_dir, n))]
    if missing:
        print(f"figures: WARNING — {stage} resumed from cache but its "
              f"exported figures are missing from evidence/figures/: {missing}."
              " Delete evidence/.stage_cache.json and rerun to regenerate.")


# ISSUE 11 satellite: the bench-trajectory regression gate. Named figures a
# new round must not silently lose; these are higher-is-better (qps,
# articles/s, speedup, recall). serve_ivf_* figures join dynamically once a
# record carries them.
BENCH_TRAJECTORY_METRICS = ("serve_queries_per_sec",
                            "fit_pipelined_articles_per_sec",
                            "train_articles_per_sec",
                            "fleet_qps",
                            # r20: the shadow-sampling leg and both devprof
                            # overhead-race legs are real throughputs — a
                            # round that quietly slows them regressed even
                            # if the overhead FRACTIONS still pass their
                            # gates (the fraction only compares legs of the
                            # same record)
                            "fleet_qps_shadow",
                            "profile_overhead_bare_aps",
                            "profile_overhead_instrumented_aps",
                            # r20 autotuner race: tuned-over-default speedup
                            # per side; >=1.0 by construction, so a DROP
                            # means the tuner stopped finding (or keeping)
                            # its wins
                            "serve_autotuned_speedup",
                            "train_autotuned_speedup")
# ISSUE 12: fleet latency/shed figures gate in the OPPOSITE direction — a
# p99 or shed-rate that GROWS >tolerance vs the prior same-platform record is
# the regression. Zero-valued bases (e.g. a 0.0 shed rate) never form a
# ratio: the base search below requires base > 0, so those pass by absence.
BENCH_TRAJECTORY_LOWER_IS_BETTER = ("fleet_p99_ms", "fleet_shed_rate",
                                    "rollout_inflight_p95_ms",
                                    # r16 sharded-IVF figures: per-replica
                                    # bytes of the shared corpus, and the
                                    # cross-shard merge's row-count overhead
                                    # — both regress by GROWING
                                    "serve_corpus_bytes_per_replica",
                                    "serve_ivf_sharded_merge_overhead_frac")
BENCH_REGRESSION_TOLERANCE = 0.15  # >15% drop vs prior same-platform fails
# ISSUE 14: the observability layer must be near-free on the serving path —
# the instrumented leg of the bench's tracing race (span tracing + metric
# registries on, same trace, same hedged router config) may cost at most
# this fraction of the bare fleet_qps.
FLEET_TRACING_OVERHEAD_MAX = 0.03
# ISSUE 18: the devprof instrument() wrapper must be free while profiling is
# DISABLED — the bench races the same compiled train step bare vs wrapped
# (fenced best-of-N both legs, telemetry/devprof.measure), and the wrapped
# leg may cost at most this fraction of the bare throughput.
PROFILE_OVERHEAD_MAX = 0.01
# ISSUE 19: shadow re-scoring must stay off the request critical path — the
# bench races the same Zipf trace through the same warmed replicas with
# 100% shadow sampling on, and the shadow leg may trail the bare fleet_qps
# by at most this fraction. Tighter than tracing: the exact re-score rides
# the scorer's own thread strictly after every primary reply resolves.
SHADOW_OVERHEAD_MAX = 0.02
# ISSUE 20: the measured tile-config autotuner must never ship a loss — the
# default config is always candidate 0 of its own race and the winner is
# the fenced best-of-N minimum, so tuned-over-default speedup < 1.0 is a
# broken measurement, not a lost race. CPU records carry no figure (the
# Pallas interpreter measures nothing real) and pass by absence.
AUTOTUNED_SPEEDUP_MIN = 1.0


def _bench_history():
    """Committed bench records, oldest first: every BENCH_r*.json `parsed`
    record plus the TPU sidecar (evidence/bench_tpu.json) as the most recent
    TPU entry. Records without a usable extra dict (e.g. r01 predates the
    extra block) are skipped, never fatal — the gate reads history, it does
    not demand one."""
    import glob

    hist = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
        except (OSError, ValueError):
            continue
        extra = parsed.get("extra") or {}
        if extra:
            hist.append((os.path.basename(path), extra))
    try:
        with open(os.path.join(HERE, "bench_tpu.json")) as f:
            extra = json.load(f)["record"].get("extra") or {}
        if extra:
            hist.append(("evidence/bench_tpu.json", extra))
    except (OSError, ValueError, KeyError):
        pass
    return hist


def _bench_trajectory_gate():
    """(ok, detail) for the regression check: the LATEST bench record must
    hold every named metric within BENCH_REGRESSION_TOLERANCE of the most
    recent PRIOR record from the SAME platform that carries it. CPU and TPU
    rounds interleave in the history, so cross-platform ratios (~100x) are
    never formed. Missing metrics or platforms pass with a note — the gate
    fails only on a measured drop, never on absent history."""
    hist = _bench_history()
    if len(hist) < 2:
        return True, (f"no comparable bench history ({len(hist)} usable "
                      "record(s); need >= 2) — nothing to gate")
    latest_name, latest = hist[-1]
    platform = latest.get("platform")
    metrics = list(BENCH_TRAJECTORY_METRICS) + sorted(
        k for k in latest
        if k.startswith("serve_ivf_") and isinstance(latest[k], (int, float)))
    metrics += list(BENCH_TRAJECTORY_LOWER_IS_BETTER)
    drops, compared, uncovered = [], [], []
    for m in metrics:
        lower_is_better = m in BENCH_TRAJECTORY_LOWER_IS_BETTER
        now = latest.get(m)
        if not isinstance(now, (int, float)):
            uncovered.append(m)
            continue
        base = next((e[m] for _, e in reversed(hist[:-1])
                     if e.get("platform") == platform
                     and isinstance(e.get(m), (int, float)) and e[m] > 0),
                    None)
        if base is None:
            uncovered.append(m)
            continue
        # one orientation for the threshold: ratio > 1 is always "better",
        # so a lower-is-better metric inverts (base over now). A latency
        # that drops to 0.0 would divide by zero AND is suspicious enough to
        # surface as a drop rather than a win.
        if lower_is_better and float(now) <= 0.0:
            drops.append(f"{m} collapsed to {now} vs prior {base} "
                         "(zero latency/shed reads as a broken figure)")
            continue
        ratio = (float(base) / float(now) if lower_is_better
                 else float(now) / float(base))
        compared.append(f"{m} {ratio:.3f}x")
        if ratio < 1.0 - BENCH_REGRESSION_TOLERANCE:
            drops.append(f"{m} {now} vs prior {base} ({ratio:.3f}x"
                         + (", lower is better)" if lower_is_better else ")"))
    if drops:
        return False, (f"{latest_name} ({platform}) regressed >"
                       f"{BENCH_REGRESSION_TOLERANCE:.0%} vs prior "
                       f"same-platform records: " + "; ".join(drops))
    detail = (f"{latest_name} ({platform}) vs prior same-platform records: "
              + (", ".join(compared) if compared
                 else "no overlapping metrics"))
    if uncovered:
        detail += (" [no comparable history for: " + ", ".join(uncovered)
                   + " — pass by absence, not by measurement]")
    return True, detail


def _overhead_race_gate(bare_field, loaded_field, max_overhead, *,
                        race_name, bare_label, loaded_label):
    """Shared pass-by-absence gate for the bench's instrumentation races.

    Three gates ride this one shape (tracing, profiling-off, shadow): the
    LATEST bench record carrying both legs of a race must keep the loaded
    leg's throughput within `max_overhead` of the bare leg's. A history
    without the race (records predating it) is a note, not a failure —
    "absent record passes, present record must meet the threshold". The
    gate fails only on a measured slowdown; it never recomputes anything.

    :param bare_field: extra-dict field of the uninstrumented leg (> 0).
    :param loaded_field: extra-dict field of the instrumented leg (> 0).
    :param max_overhead: max allowed `1 - loaded / bare` fraction.
    :param race_name: short race id for the pass-by-absence note.
    :param bare_label: human label for the bare figure in the detail line.
    :param loaded_label: human label for the loaded figure.
    """
    hist = _bench_history()
    for name, extra in reversed(hist):
        bare, loaded = extra.get(bare_field), extra.get(loaded_field)
        if (isinstance(bare, (int, float)) and bare > 0
                and isinstance(loaded, (int, float)) and loaded > 0):
            overhead = 1.0 - float(loaded) / float(bare)
            ok = overhead <= max_overhead
            return ok, (f"{name}: {loaded_label} {loaded} vs {bare_label} "
                        f"{bare} — overhead {overhead:.2%} "
                        f"{'<=' if ok else '>'} {max_overhead:.0%}")
    return True, (f"no bench record carries the {race_name} race yet — "
                  "pass by absence, not by measurement")


def _fleet_tracing_overhead_gate():
    """(ok, detail): the latest bench record carrying both legs of the
    tracing race must keep `fleet_qps_traced` within
    FLEET_TRACING_OVERHEAD_MAX of `fleet_qps` (pre-r14 histories pass by
    absence)."""
    return _overhead_race_gate(
        "fleet_qps", "fleet_qps_traced", FLEET_TRACING_OVERHEAD_MAX,
        race_name="fleet_qps_traced", bare_label="fleet_qps",
        loaded_label="fleet_qps_traced (tracing on)")


def _profile_overhead_gate():
    """(ok, detail): the latest bench record carrying both legs of the
    devprof race must keep the instrumented-disabled train-step throughput
    within PROFILE_OVERHEAD_MAX of the bare leg (pre-r18 histories pass by
    absence). The zero-host-sync half of the contract is pinned by the
    fetch-count + compile_guard regression test in tests/test_profile.py."""
    return _overhead_race_gate(
        "profile_overhead_bare_aps", "profile_overhead_instrumented_aps",
        PROFILE_OVERHEAD_MAX,
        race_name="devprof overhead", bare_label="bare aps",
        loaded_label="instrumented-disabled aps")


def _shadow_overhead_gate():
    """(ok, detail): the latest bench record carrying both legs of the
    shadow race must keep `fleet_qps_shadow` (100% shadow sampling, exact
    re-score on the scorer's own thread) within SHADOW_OVERHEAD_MAX of
    `fleet_qps` (pre-r19 histories pass by absence). The never-blocks /
    never-reorders half of the contract is pinned by tests/test_shadow.py."""
    return _overhead_race_gate(
        "fleet_qps", "fleet_qps_shadow", SHADOW_OVERHEAD_MAX,
        race_name="fleet_qps_shadow", bare_label="fleet_qps",
        loaded_label="fleet_qps_shadow (100% sampling)")


def _autotuned_speedup_gate():
    """(ok, detail): the latest bench record carrying the autotuner race
    (ISSUE 20, `_bench_tuning`) must show `serve_autotuned_speedup` and
    `train_autotuned_speedup` >= AUTOTUNED_SPEEDUP_MIN. The race's default
    config is always candidate 0 and the winner is the measured minimum, so
    a figure below 1.0 means the race itself is broken (unfenced timing,
    compile pollution), not that the tuner merely failed to win — exactly
    what this gate exists to make loud. CPU rounds emit no figure and pass
    by absence (the interpreter measures nothing real); the
    bitwise-parity-before-admission half of the contract is pinned by
    tests/test_tuning.py."""
    hist = _bench_history()
    for name, extra in reversed(hist):
        figures = {m: extra[m] for m in ("serve_autotuned_speedup",
                                         "train_autotuned_speedup")
                   if isinstance(extra.get(m), (int, float))}
        if not figures:
            continue
        bad = {m: v for m, v in figures.items()
               if v < AUTOTUNED_SPEEDUP_MIN}
        shown = ", ".join(f"{m} {v}" for m, v in sorted(figures.items()))
        if bad:
            return False, (f"{name}: {shown} — autotuned speedup below "
                           f"{AUTOTUNED_SPEEDUP_MIN} means the measured race "
                           "is broken (default is always a candidate)")
        return True, f"{name}: {shown} >= {AUTOTUNED_SPEEDUP_MIN}"
    return True, ("no bench record carries the autotuner race yet — "
                  "pass by absence, not by measurement")


def main(argv=None):
    t0 = time.time()
    argv = sys.argv[1:] if argv is None else argv
    if "--cpu" in argv:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import uuid

    import jax

    if "--cpu" in argv:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    run_id = uuid.uuid4().hex[:12]
    print(f"evidence run on platform={platform} run_id={run_id}")

    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import (
        main as main_autoencoder)
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet import (
        main as main_triplet)
    from dae_rnn_news_recommendation_tpu.cli.main_starspace import (
        main as main_starspace)
    from dae_rnn_news_recommendation_tpu.cli.main_user_model import (
        main as main_user_model)

    scratch = tempfile.mkdtemp(prefix="evidence_")
    cwd = os.getcwd()
    os.chdir(scratch)
    try:
        def staged(name, fn):
            return _staged(name, fn, platform=platform, run_id=run_id)

        def _main_stage():
            model, out = main_autoencoder(MAIN_ARGS)
            return {"aurocs": out,
                    "data_dir": os.path.abspath(model.data_dir),
                    "figures": _export_figures(model.plot_dir, "online",
                                               platform)}

        main_out = staged("online-mining driver", _main_stage)
        aurocs = main_out["aurocs"]
        _check_figures("online-mining driver", main_out.get("figures", []))
        story_aurocs = staged("online-mining driver (story label)",
                              lambda: main_autoencoder(STORY_ARGS)[1])

        def _triplet_stage():
            # reference-parity record (main_autoencoder_triplet.py:249-321):
            # the full 12-AUROC table plus the anchor/pos/neg reconstruction
            # and margin loss trajectory from the train metrics stream
            model, out = main_triplet(TRIPLET_ARGS)
            traj = _read_trajectory(
                os.path.join(model.tf_summary_dir, "train"),
                ("cost", "autoencoder_loss", "triplet_loss",
                 "autoencoder_loss_anchor", "autoencoder_loss_pos",
                 "autoencoder_loss_neg"))
            return {"aurocs": out, "loss_trajectory": traj}

        tri = staged("precomputed-triplet driver", _triplet_stage)
        tri_aurocs, tri_traj = tri["aurocs"], tri["loss_trajectory"]
        tri_story_aurocs = staged(
            "precomputed-triplet driver (story-keyed mapping)",
            lambda: main_triplet(TRIPLET_STORY_ARGS)[1])

        def _ss():
            # the cached online-mining stage may reference a scratch dir a
            # previous run created; if the OS wiped it, the split can't be
            # reproduced piecemeal — force a uniform rerun
            art = main_out["data_dir"]
            if not os.path.exists(os.path.join(art, "article.snappy.parquet")):
                raise RuntimeError(
                    f"online-mining artifacts missing from {art} (stage cache "
                    "references a wiped scratch dir); delete "
                    "evidence/.stage_cache.json and rerun for a uniform record")
            result, ss_aurocs = main_starspace(
                STARSPACE_ARGS + ["--from_artifacts", art])
            return {"best_val_error": float(result["best_val_error"]),
                    "epoch_errors": [float(v) for v in result["epoch_errors"]],
                    "aurocs": ss_aurocs}

        ss = _staged("native StarSpace baseline (same split as online-mining)",
                     _ss, platform=platform, run_id=run_id)
        ss_result, ss_aurocs = ss, ss["aurocs"]
        moe_aurocs = staged("mixture-of-denoisers (4 experts, net-new family)",
                            lambda: main_autoencoder(MOE_ARGS)[1])

        def _ref():
            t_ref = time.time()
            model, out = main_autoencoder(REFSCALE_ARGS)
            # jaxcheck: disable=R2 (whole-pipeline wall clock, not a device timing: `out` holds host-side auroc floats, so everything is fetched)
            return {"aurocs": out, "wall": time.time() - t_ref,
                    "figures": _export_figures(model.plot_dir, "refscale",
                                               platform)}

        ref = staged("reference-scale run (8000 x 10000 -> 500, bf16, "
                     "streaming eval)", _ref)
        ref_aurocs, t_ref = ref["aurocs"], ref["wall"]
        _check_figures("reference-scale run", ref.get("figures", []))

        def _refstory():
            t_rs = time.time()
            _, out = main_autoencoder(REFSTORY_ARGS)
            # jaxcheck: disable=R2 (whole-pipeline wall clock, not a device timing: `out` holds host-side auroc floats, so everything is fetched)
            return {"aurocs": out, "wall": time.time() - t_rs}

        refstory = staged("reference-scale run, story-mined "
                          "(8000 x 10000 -> 500, bf16)", _refstory)
        refstory_aurocs = refstory["aurocs"]

        user = staged("user model (stacked DAE -> GRU, config 5)",
                      lambda: main_user_model(USER_ARGS)[1])

        def _chaos():
            # ISSUE 6 acceptance: 8 distinct seeded fault plans (preemption,
            # feed death, torn commit, transient I/O, post-crash truncation),
            # each ending in a completed resumed run whose final params are
            # bitwise-identical (CPU) to the fault-free run, with every fault
            # and retry in the run manifest
            from dae_rnn_news_recommendation_tpu.reliability.chaos import (
                chaos_soak)

            out = chaos_soak(os.path.join(scratch, "chaos"), n_plans=8,
                             log=print)
            return {"n_ok": out["n_ok"], "n_plans": out["n_plans"],
                    "all_ok": out["all_ok"],
                    "plans": [{"seed": r.plan["seed"], "ok": r.ok,
                               "bitwise": r.bitwise, "allclose": r.allclose,
                               "restarts": r.restarts,
                               "n_injected": len(r.injected),
                               "n_retries": len(r.retries),
                               "manifest_recorded": bool(r.manifest_faults),
                               "detail": r.detail,
                               "duration_s": round(r.duration_s, 2)}
                              for r in out["results"]]}

        chaos_out = staged("chaos soak (8 seeded fault plans, crash-exact "
                           "resume)", _chaos)

        def _chaos_serve():
            # ISSUE 8 acceptance: seeded fault plans x overload traces
            # against the full serving stack (serve/chaos_serve.py). Each
            # plan asserts in-process: every submitted request ends in
            # EXACTLY one of {reply, explicit shed, explicit error}; an
            # injected serve.swap fault rolls back with the OLD corpus still
            # serving; p95 stays within SLA even in degraded mode.
            from dae_rnn_news_recommendation_tpu.serve import chaos_serve_soak

            out = chaos_serve_soak(n_plans=6, n_requests=48, log=print)
            return {"n_ok": out["n_ok"], "n_plans": out["n_plans"],
                    "all_ok": out["all_ok"],
                    "plans": [{"seed": r.seed, "ok": r.ok,
                               "detail": r.detail,
                               "n_submitted": r.n_submitted,
                               "n_replied": r.n_replied,
                               "n_shed": r.n_shed,
                               "n_errors": r.n_errors,
                               "n_unresolved": r.n_unresolved,
                               "p95_ms": r.p95_ms,
                               "degraded": r.degraded,
                               "swap_faulted": r.swap_faulted,
                               "swap_rolled_back": r.swap_rolled_back,
                               "served_after_swap": r.served_after_swap,
                               "n_post_warm_compiles": r.n_post_warm_compiles,
                               "n_injected": len(r.injected),
                               "n_retries": len(r.retries),
                               "duration_s": round(r.duration_s, 2)}
                              for r in out["results"]]}

        chaos_serve_out = staged("chaos-serve soak (6 seeded fault plans x "
                                 "overload traces)", _chaos_serve)

        def _chaos_shard():
            # ISSUE 13 acceptance: mesh-sharded serving under shard loss
            # (serve/chaos_serve.py chaos-shard plans). Four seeded families
            # — shard lost under load, shard lost inside an append's prepare
            # phase, and a prepare-crash in each swap flavor — over fp32 and
            # int8 corpora. Each plan audits in-harness: exactly one outcome
            # per request with a coverage fraction on every reply, zero torn
            # cross-shard reads (a concurrent reader samples slot/shard
            # version stamps throughout), a version ledger whose promotes
            # carry uniform shard stamps, bitwise slot equality vs the
            # fault-free reference after recovery, and zero post-warmup
            # compiles.
            from dae_rnn_news_recommendation_tpu.serve import chaos_shard_soak

            out = chaos_shard_soak(n_plans=4, n_requests=24, log=print)
            return {"n_ok": out["n_ok"], "n_plans": out["n_plans"],
                    "all_ok": out["all_ok"],
                    "plans": [{"seed": r.seed, "family": r.family,
                               "dtype": r.dtype, "ok": r.ok,
                               "detail": r.detail,
                               "n_submitted": r.n_submitted,
                               "n_replied": r.n_replied,
                               "n_partial": r.n_partial,
                               "min_coverage": r.min_coverage,
                               "final_version": r.final_version,
                               "bitwise_recovered": r.bitwise_recovered,
                               "n_read_samples": r.n_read_samples,
                               "n_post_warm_compiles": r.n_post_warm_compiles,
                               "n_injected": len(r.injected),
                               "duration_s": round(r.duration_s, 2)}
                              for r in out["results"]]}

        # the shard plans need a mesh: >= 2 devices (the 8-virtual-device CPU
        # mesh in tests comes from an XLA flag this harness does not force)
        if len(jax.devices()) >= 2:
            chaos_shard_out = staged("chaos-shard soak (4 seeded shard-loss/"
                                     "prepare-crash plans, sharded corpus)",
                                     _chaos_shard)
        else:
            chaos_shard_out = None
            print("chaos-shard soak skipped: needs >= 2 devices "
                  f"(have {len(jax.devices())}); run under "
                  "XLA_FLAGS=--xla_force_host_platform_device_count=8 or on "
                  "a multi-device accelerator to capture it")

        def _chaos_churn():
            # ISSUE 10 acceptance: 6 seeded fault plans against the
            # continuous-refresh loop (reliability/chaos_churn.py), one per
            # refresh.* family plus a train.step preemption INSIDE the
            # fine-tune. Each plan asserts in-harness: the served corpus
            # version sequence is monotonic and matches the fault-free
            # reference session, every promoted slot passed the health gate,
            # every rollback left a verified version serving, and the final
            # params are bitwise-identical on CPU (crash-exact fine-tune
            # resume). The recall probe then measures bf16/int8 recall@10 on
            # a TRAINED churned corpus — the figure the serve_int8 floor is
            # calibrated against (docs/serving.md).
            from dae_rnn_news_recommendation_tpu.reliability.chaos_churn \
                import chaos_churn_soak, churned_recall_probe

            out = chaos_churn_soak(os.path.join(scratch, "chaos_churn"),
                                   seeds=range(6), log=print)
            recall = churned_recall_probe(
                os.path.join(scratch, "churn_recall"))
            return {"n_ok": out["n_ok"], "n_plans": out["n_plans"],
                    "all_ok": out["all_ok"],
                    "plans": [{"seed": r.plan["seed"], "ok": r.ok,
                               "bitwise": r.bitwise, "allclose": r.allclose,
                               "restarts": r.restarts,
                               "rollbacks": r.rollbacks,
                               "n_injected": len(r.injected),
                               "n_retries": len(r.retries),
                               "versions": r.versions,
                               "versions_monotonic": (
                                   r.versions == list(
                                       range(1, len(r.versions) + 1))
                                   and r.versions == r.ref_versions),
                               "n_finetunes": r.n_finetunes,
                               "detail": r.detail,
                               "duration_s": round(r.duration_s, 2)}
                              for r in out["results"]],
                    "recall": recall}

        chaos_churn_out = staged("chaos-churn soak (6 seeded refresh fault "
                                 "plans + trained-corpus recall probe)",
                                 _chaos_churn)
    finally:
        os.chdir(cwd)

    # provenance honesty: the committed record claims ONE platform only when
    # every stage was actually produced by one platform (and ideally one run)
    stage_platforms = {p["platform"] for p in STAGE_PROVENANCE.values()}
    stage_runs = {p["run_id"] for p in STAGE_PROVENANCE.values()}
    uniform = len(stage_platforms) == 1 and len(stage_runs) == 1
    platform_claim = (stage_platforms.pop() if len(stage_platforms) == 1
                      else "mixed(" + ",".join(sorted(stage_platforms)) + ")")

    # ------------------------------------------------------------ assertions
    checks = {}

    def check(name, ok, detail):
        checks[name] = {"pass": bool(ok), "detail": detail}
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}: {detail}")

    # jaxcheck/threadcheck/meshcheck self-clean as an explicit, exit-code-
    # gated stage (previously only indirect via tier-1): the evidence record
    # must not be producible from a tree the repo's own analyzer rejects.
    # One invocation selecting all three families pins the full catalog —
    # adding a family without gating it here is impossible.
    from dae_rnn_news_recommendation_tpu.analysis.__main__ import (
        main as _jaxcheck_main)
    _jaxcheck_rc = _jaxcheck_main(["--select", "R,C,S"])
    check("jaxcheck_self_clean", _jaxcheck_rc == 0,
          f"python -m dae_rnn_news_recommendation_tpu.analysis --select "
          f"R,C,S exit code {_jaxcheck_rc} (0 = zero unsuppressed findings, "
          f"R1-R14 + C1-C5 + S1-S5)")

    enc_tr = aurocs["similarity_boxplot_encoded(Category)"]
    enc_vl = aurocs["similarity_boxplot_encoded_validate(Category)"]
    tfidf_tr = aurocs["similarity_boxplot_tfidf(Category)"]
    tfidf_vl = aurocs["similarity_boxplot_tfidf_validate(Category)"]
    check("encoded_beats_chance_train", enc_tr > 0.65,
          f"encoded(Category) train AUROC {enc_tr:.4f} > 0.65")
    check("encoded_beats_chance_validate", enc_vl > 0.65,
          f"encoded(Category) validate AUROC {enc_vl:.4f} > 0.65")
    check("encoded_beats_tfidf_train", enc_tr > tfidf_tr,
          f"encoded {enc_tr:.4f} > tfidf {tfidf_tr:.4f} (Category, train)")
    check("encoded_beats_tfidf_validate", enc_vl > tfidf_vl,
          f"encoded {enc_vl:.4f} > tfidf {tfidf_vl:.4f} (Category, validate)")
    tri_enc_vl = tri_aurocs["similarity_boxplot_encoded_validate(Category)"]
    tri_bin_vl = tri_aurocs["similarity_boxplot_binary_count_validate(Category)"]
    check("triplet_encoded_meets_sweep_frontier", tri_enc_vl > 0.60,
          f"triplet encoded(Category) validate AUROC {tri_enc_vl:.4f} > 0.60 "
          "(threshold = worst seed of the 3-seed spread [0.602, 0.8096], "
          "mean 0.7193, evidence/seed_spread.json; the r4 sweep frontier "
          "0.7462 is this record's seed-0 draw)")
    check("triplet_encoded_beats_binary_validate", tri_enc_vl > tri_bin_vl,
          f"triplet encoded {tri_enc_vl:.4f} > binary_count {tri_bin_vl:.4f} "
          "(Category, validate — holds on the 3-seed means too, 0.7193 vs "
          "0.6166, though the worst seed is a near-tie 0.6020 vs 0.6036; "
          "the pos/neg mapping is built per category, reference "
          "similar_articles)")
    # VERDICT r4 item 4: the category-keyed triplet recipe's Story cell sits
    # at chance BY CONSTRUCTION — the reference's similar_articles positives
    # are same-CATEGORY neighbors (datasets/articles.py:83-128), so no
    # gradient ever pulls same-story pairs together; the cell is noise around
    # 0.5, not a defect. Bounded here; the story-keyed stage next proves the
    # machinery carries Story when the mapping is keyed on it.
    tri_sto_vl = tri_aurocs["similarity_boxplot_encoded_validate(Story)"]
    check("triplet_story_chance_by_construction",
          0.40 <= tri_sto_vl <= 0.62,
          f"category-keyed triplet encoded(Story) validate {tri_sto_vl:.4f} "
          "within the chance band [0.40, 0.62] (the per-category pos/neg "
          "mapping carries no Story signal by construction — reference "
          "datasets/articles.py:83-128; 3-seed spread [0.4254, 0.4852], "
          "evidence/seed_spread.json)")
    ts_enc_vl = tri_story_aurocs["similarity_boxplot_encoded_validate(Story)"]
    check("triplet_story_keyed_carries_story",
          ts_enc_vl > 0.60 and ts_enc_vl > tri_sto_vl,
          f"story-keyed triplet encoded(Story) validate {ts_enc_vl:.4f} > "
          f"0.60 and > the category-keyed {tri_sto_vl:.4f} (net-new --label "
          "story mapping; grid frontier 0.6444, "
          "evidence/triplet_story_keyed.json)")
    tl = tri_traj.get("triplet_loss", [])
    if len(tl) >= 2:
        # per-step values are noisy; compare first- vs last-decile means
        k = max(1, len(tl) // 10)
        tl_head = sum(tl[:k]) / k
        tl_tail = sum(tl[-k:]) / k
        check("triplet_margin_loss_decreases", tl_tail < tl_head,
              f"margin loss first-decile mean {tl_head:.4f} -> last-decile "
              f"mean {tl_tail:.4f} over {len(tl)} train steps")
    else:
        check("triplet_margin_loss_decreases", False,
              f"trajectory too short: {tl}")
    # the reference driver's OTHER label (main_autoencoder.py:180-198): mining
    # on story must lift the story-label AUROC the category-mined run trades
    # away (VERDICT r2 weak-4: story quality was unchecked)
    sto_enc_vl = story_aurocs["similarity_boxplot_encoded_validate(Story)"]
    sto_tfidf_vl = story_aurocs["similarity_boxplot_tfidf_validate(Story)"]
    cat_run_story_vl = aurocs["similarity_boxplot_encoded_validate(Story)"]
    check("story_mined_encoded_beats_category_mined_on_story",
          sto_enc_vl > cat_run_story_vl,
          f"story-mined encoded(Story) validate {sto_enc_vl:.4f} > "
          f"category-mined run's {cat_run_story_vl:.4f} (the mining label "
          "steers which similarity the embedding learns; holds on the "
          "3-seed means too — 0.6466 vs 0.6116 — though not at every "
          "individual seed, evidence/seed_spread.json)")
    sto_bin_vl = story_aurocs["similarity_boxplot_binary_count_validate(Story)"]
    tfidf_note = (f"tfidf {sto_tfidf_vl:.4f} "
                  + ("stays ahead" if sto_tfidf_vl > sto_enc_vl else "beaten"))
    # VERDICT r4 item 5: checks are calibrated to the measured 3-seed spread
    # (evidence/seed_spread.json), not this record's single draw. The spread
    # shows story-mined encoded (0.6466 +- 0.021 over seeds 0/1/2) is
    # statistically indistinguishable from binary counts (0.6506 +- 0.007) at
    # this corpus size — the earlier seed-0-only "encoded beats binary" claim
    # does not survive the spread and is retired honestly.
    check("story_mined_encoded_matches_binary_within_spread",
          sto_enc_vl >= sto_bin_vl - 0.05,
          f"story-mined encoded(Story) validate {sto_enc_vl:.4f} >= "
          f"binary_count {sto_bin_vl:.4f} - 0.05 (one-sided: not worse than "
          "binary beyond seed noise; 3-seed means 0.6466 vs 0.6506, "
          f"evidence/seed_spread.json; {tfidf_note} — 27-config plateau "
          "~0.67, evidence/story_sweep.json + story_sweep2.json)")
    check("story_mined_encoded_above_chance", sto_enc_vl > 0.62,
          f"story-mined encoded(Story) validate {sto_enc_vl:.4f} > 0.62 "
          "(worst seed of the 3-seed spread is 0.6254, "
          "evidence/seed_spread.json; chance 0.5)")
    # three-way on ONE split (StarSpace trains on the online-mining stage's
    # saved artifacts): the reference notebook's cells 9-13 comparison
    ss_vl = ss_aurocs["starspace_validate"]
    check("threeway_encoded_vs_starspace_validate", enc_vl >= ss_vl,
          f"DAE encoded {enc_vl:.4f} >= StarSpace {ss_vl:.4f} "
          "(Category, validate, same split by construction)")
    moe_vl = moe_aurocs["similarity_boxplot_encoded_validate(Category)"]
    check("moe_encoded_beats_tfidf_validate",
          moe_vl > 0.65 and moe_vl > tfidf_vl,
          f"4-expert mixture encoded {moe_vl:.4f} > tfidf {tfidf_vl:.4f} "
          "(Category, validate; EXPERIMENTAL family — the iso-epoch sweep "
          "shows it does not match the single DAE at any schedule: 0.8040@60 "
          "/ 0.7904@100 / 0.7824@150 epochs vs 0.8477, "
          "evidence/moe_iso_epoch.json; kept as the expert-parallelism demo, "
          "claiming only the tfidf comparison)")
    ref_enc = ref_aurocs["similarity_boxplot_encoded_validate(Category)"]
    ref_tfidf = ref_aurocs["similarity_boxplot_tfidf_validate(Category)"]
    check("refscale_encoded_beats_tfidf",
          ref_enc > 0.6 and ref_enc > ref_tfidf,
          f"reference-scale encoded {ref_enc:.4f} > tfidf {ref_tfidf:.4f} "
          f"(Category, validate; {t_ref:.0f}s end to end)")
    # VERDICT r4 item 3: the story-mining knob at the headline workload shape
    rs_enc = refstory_aurocs["similarity_boxplot_encoded_validate(Story)"]
    rs_bin = refstory_aurocs["similarity_boxplot_binary_count_validate(Story)"]
    rs_cat_run = ref_aurocs["similarity_boxplot_encoded_validate(Story)"]
    check("refstory_story_mining_lifts_story_at_scale",
          rs_enc > rs_cat_run,
          f"refscale story-mined encoded(Story) validate {rs_enc:.4f} > the "
          f"category-mined refscale run's {rs_cat_run:.4f} (the mining-label "
          "knob works at the headline shape too)")
    check("refstory_encoded_vs_binary",
          rs_enc > rs_bin,
          f"refscale story-mined encoded(Story) validate {rs_enc:.4f} > "
          f"binary_count {rs_bin:.4f} (the r4 verdict's bar)")
    rs_tfidf = refstory_aurocs["similarity_boxplot_tfidf_validate(Story)"]
    check("refstory_encoded_beats_tfidf_on_story",
          rs_enc > rs_tfidf and rs_enc > 0.85,
          f"refscale story-mined encoded(Story) validate {rs_enc:.4f} > "
          f"tfidf {rs_tfidf:.4f} and > 0.85 (calibration run measured "
          "0.9332 vs 0.8422, evidence/refstory_calibration.json — at the "
          "headline shape the learned embedding beats raw tf-idf on BOTH "
          "labels, Category when category-mined and Story when story-mined; "
          "the small-corpus story plateau is a data-size effect, not a "
          "model limit)")
    import numpy as np

    ss_loss = float(ss_result["best_val_error"])
    ss_epoch = int(np.argmin(ss_result["epoch_errors"]))
    check("starspace_converged", np.isfinite(ss_loss),
          f"early stopping loss {ss_loss:.6f} @ epoch {ss_epoch}")
    u_ci = user.get("rank_accuracy_ci95", 0.0)
    check("user_rank_above_chance", user["rank_accuracy"] - u_ci > 0.6,
          f"held-out pairwise rank accuracy {user['rank_accuracy']:.4f} "
          f"± {u_ci:.4f} (95% CI over {user['n_users_eval']} users) "
          "lower bound > 0.6 (chance 0.5)")
    # ISSUE 5 acceptance: large-batch MINED training sustains real MXU
    # utilization. TPU-gated — bench.py's mined-big corner is TPU-only by
    # design (the CPU record carries an explicit skip note instead), so a
    # CPU evidence run asserts nothing it cannot measure. Reads the
    # committed bench sidecar: the figure must come from a real hardware
    # bench round, not be recomputed ad hoc here.
    if platform == "tpu":
        bench_extra = {}
        try:
            with open(os.path.join(HERE, "bench_tpu.json")) as f:
                bench_extra = json.load(f)["record"]["extra"] or {}
        except (OSError, ValueError, KeyError):
            pass
        mined_mfu = bench_extra.get("train_mined_big_mfu")
        check("train_mined_big_mfu_floor",
              mined_mfu is not None and float(mined_mfu) >= 0.09,
              (f"bench sidecar train_mined_big_mfu {mined_mfu} >= 0.09 "
               "(B=8192 batch_all via the auto mining dispatch — the batch "
               "the dense cube could never run)") if mined_mfu is not None
              else ("evidence/bench_tpu.json has no train_mined_big_mfu — "
                    "the sidecar predates the mined-big corner; rerun "
                    "bench.py on TPU to capture it"))
        # ISSUE 7 acceptance, all from the committed bench sidecar (a real
        # hardware round, not an ad-hoc recompute):
        #   * the compressed wire format beats padded-CSR bytes/article;
        #   * the overlapped packed feed keeps fit_pipelined within 2x of the
        #     raw train step with feed_stall_fraction <= 0.05;
        #   * post-warm epochs of the device-resident epoch cache ship ~0
        #     bytes over the link.
        # best lossless-for-this-corpus mode (the bench pool is 0/1, so
        # binary qualifies); plain f32 merely breaks even at the pool's
        # uniform density (16-bit gaps ≈ uint16 indices) by design
        wire_b = bench_extra.get("feed_wire_bytes_per_article_best",
                                 bench_extra.get("feed_wire_bytes_per_article"))
        wire_mode = bench_extra.get("feed_wire_best_mode", "f32")
        csr_b = bench_extra.get("feed_padded_csr_bytes_per_article")
        check("feed_wire_compresses_the_feed",
              wire_b is not None and csr_b is not None
              and float(wire_b) < float(csr_b),
              (f"bench sidecar wire ({wire_mode}) {wire_b} B/article < "
               f"padded-CSR {csr_b} (delta/bit-packed indices + value "
               f"elision/quantization, ops/wire.py)")
              if wire_b is not None else
              ("evidence/bench_tpu.json has no feed_wire_bytes_per_article — "
               "the sidecar predates the wire-format corner; rerun bench.py "
               "on TPU to capture it"))
        pipe_aps = bench_extra.get("fit_pipelined_articles_per_sec")
        tr_aps = bench_extra.get("train_articles_per_sec")
        stall = bench_extra.get("feed_stall_fraction")
        check("fit_pipelined_within_2x_of_train",
              None not in (pipe_aps, tr_aps, stall)
              and float(pipe_aps) * 2 >= float(tr_aps)
              and float(stall) <= 0.05,
              (f"bench sidecar fit_pipelined {pipe_aps} aps within 2x of the "
               f"raw train step {tr_aps} aps with feed_stall_fraction "
               f"{stall} <= 0.05") if None not in (pipe_aps, tr_aps, stall)
              else ("evidence/bench_tpu.json lacks fit_pipelined/train/stall "
                    "figures; rerun bench.py on TPU to capture them"))
        cache_rec = bench_extra.get("wire_cache")
        cache_ok = (isinstance(cache_rec, dict)
                    and cache_rec.get("post_warm_feed_bytes") == 0
                    and cache_rec.get("n_batches", 0) > 0)
        check("wire_cache_zero_h2d_post_warm", cache_ok,
              (f"bench sidecar wire_cache: {cache_rec.get('n_batches')} "
               f"batches pinned ({cache_rec.get('pinned_mbytes')} MB), "
               f"post-warm epochs staged {cache_rec.get('post_warm_feed_bytes')}"
               " bytes over the link (warm epoch: "
               f"{cache_rec.get('warm_epoch_feed_bytes')})")
              if isinstance(cache_rec, dict) and "n_batches" in cache_rec else
              (f"evidence/bench_tpu.json wire_cache record unusable: "
               f"{cache_rec!r}; rerun bench.py on TPU to capture it"))
    n_bitwise = sum(1 for pl in chaos_out["plans"] if pl["bitwise"])
    n_recorded = sum(1 for pl in chaos_out["plans"] if pl["manifest_recorded"])
    check("chaos_soak_crash_exact_resume",
          chaos_out["all_ok"] and n_recorded == chaos_out["n_plans"],
          f"{chaos_out['n_ok']}/{chaos_out['n_plans']} seeded fault plans "
          f"recovered ({n_bitwise} bitwise-identical to the fault-free run"
          + (", the CPU bar" if platform == "cpu" else
             "; allclose is the bar off-CPU")
          + f"); {n_recorded}/{chaos_out['n_plans']} run manifests record "
          "their faults — zero silent recoveries")
    sv_plans = chaos_serve_out["plans"]
    n_leak = sum(1 for pl in sv_plans
                 if pl["n_replied"] + pl["n_shed"] + pl["n_errors"]
                 != pl["n_submitted"] or pl["n_unresolved"] > 0)
    check("chaos_serve_reply_or_shed",
          chaos_serve_out["all_ok"] and n_leak == 0,
          f"{chaos_serve_out['n_ok']}/{chaos_serve_out['n_plans']} serve "
          "fault plans passed; every submitted request ended in exactly one "
          "of reply/shed/error across all plans — zero unresolved futures, "
          "zero silent drops"
          + ("" if n_leak == 0 else f" (OUTCOME LEAK in {n_leak} plans)"))
    sv_swap = [pl for pl in sv_plans if pl["swap_faulted"]]
    check("chaos_serve_swap_rollback",
          bool(sv_swap) and all(pl["swap_rolled_back"]
                                and pl["served_after_swap"]
                                for pl in sv_swap),
          (f"{len(sv_swap)} plans injected serve.swap faults; every one "
           "rolled back (version unchanged, swap_rollback recorded) with "
           "the old corpus still answering the post-swap probe")
          if sv_swap else
          "no plan exercised serve.swap — the 6-family round-robin should "
          "always include seed 4's swap-fatal plan")
    if chaos_shard_out is not None:
        sh_plans = chaos_shard_out["plans"]
        n_sh_bitwise = sum(1 for pl in sh_plans if pl["bitwise_recovered"])
        n_sh_compiles = sum(pl["n_post_warm_compiles"] for pl in sh_plans)
        check("chaos_shard_consistent",
              chaos_shard_out["all_ok"]
              and n_sh_bitwise == chaos_shard_out["n_plans"]
              and n_sh_compiles == 0,
              f"{chaos_shard_out['n_ok']}/{chaos_shard_out['n_plans']} "
              "chaos-shard plans passed (families: "
              + ", ".join(sorted({pl["family"] for pl in sh_plans}))
              + f"); {n_sh_bitwise} recovered the sharded slot bitwise from "
              "the host mirror, every degraded reply carried its coverage, "
              "zero torn cross-shard reads, "
              f"{n_sh_compiles} post-warmup compiles")
    cc_plans = chaos_churn_out["plans"]
    n_cc_mono = sum(1 for pl in cc_plans if pl["versions_monotonic"])
    n_cc_bitwise = sum(1 for pl in cc_plans if pl["bitwise"])
    check("chaos_churn_version_monotonic",
          chaos_churn_out["all_ok"] and n_cc_mono == chaos_churn_out["n_plans"],
          f"{chaos_churn_out['n_ok']}/{chaos_churn_out['n_plans']} refresh "
          f"fault plans passed; {n_cc_mono}/{chaos_churn_out['n_plans']} "
          "promoted strictly monotonic version sequences matching the "
          "fault-free reference session (every promoted slot health-gated, "
          "every rollback left a verified version serving); "
          f"{n_cc_bitwise} plans resumed the fine-tune bitwise-identical"
          + (" (the CPU bar)" if platform == "cpu" else
             "; allclose is the bar off-CPU"))
    cc_recall = chaos_churn_out["recall"]
    tr_int8 = cc_recall["trained"]["int8"]
    # Floor raised from 0.98 to 0.99 (r10): 0.98 was calibrated on
    # init-params embeddings, an order-statistics worst case where the
    # rank-10/11 cosine gap sits inside the int8 noise bound. On a TRAINED
    # churned corpus the gaps are set by topic structure instead: measured
    # int8 0.9969 / bf16 0.9984 at the probe shape (1024+4x64 rows,
    # 256->32), vs 0.9953 for init params at the SAME shape — docs/serving.md
    # has the full rationale.
    check("churn_trained_int8_recall",
          tr_int8 is not None and float(tr_int8) >= 0.99,
          f"trained churned corpus (v{cc_recall['corpus_version']}, "
          f"{cc_recall['corpus_rows']} rows) int8 recall@10 {tr_int8} "
          ">= 0.99 vs fp32 ranking "
          f"(bf16 {cc_recall['trained']['bfloat16']}; init-params worst "
          f"case at the same shape: {cc_recall['init_params']}; "
          f"shape {cc_recall['shape']})")
    if platform == "tpu":
        serve_qps = bench_extra.get("serve_queries_per_sec")
        serve_p95 = bench_extra.get("serve_latency_p95_ms")
        check("serve_bench_recorded",
              serve_qps is not None and float(serve_qps) > 0
              and serve_p95 is not None and float(serve_p95) > 0,
              (f"bench sidecar serve_queries_per_sec {serve_qps} with "
               f"p50/p95 {bench_extra.get('serve_latency_p50_ms')}/"
               f"{serve_p95} ms (admission->microbatch->device->reply, "
               "fenced per batch)") if serve_qps is not None else
              ("evidence/bench_tpu.json has no serve_queries_per_sec — the "
               "sidecar predates the serving corner; rerun bench.py on TPU "
               "to capture it"))
        # ISSUE 9 acceptance, from the committed bench sidecar: the fused
        # Pallas scorer beats the r07 materializing path >= 1.5x at the
        # record corpus, and the int8 resident corpus compresses >= ~3x while
        # preserving fp32 ranking (recall floor rationale below)
        speedup = bench_extra.get("serve_fused_speedup")
        check("serve_fused_speedup",
              speedup is not None and float(speedup) >= 1.5,
              (f"bench sidecar serve_fused_speedup {speedup}x >= 1.5x "
               f"(fused {serve_qps} qps vs unfused "
               f"{bench_extra.get('serve_queries_per_sec_unfused')} qps at "
               f"corpus {bench_extra.get('serve_corpus_rows')})")
              if speedup is not None else
              ("evidence/bench_tpu.json has no serve_fused_speedup — the "
               "sidecar predates the fused-scorer corner; rerun bench.py on "
               "TPU to capture it"))
        int8_ratio = bench_extra.get("serve_int8_bytes_ratio")
        recalls = bench_extra.get("serve_recall_at_10_vs_fp32") or {}
        int8_recall = recalls.get("int8") if isinstance(recalls, dict) else None
        # The bench-sidecar floor stays 0.98: the bench corpus is
        # init-params embeddings (near-isotropic), so the median rank-10/11
        # cosine gap (~1.2e-3) sits within ~2x of the int8 score-noise bound
        # (~6e-4) — an order-statistics worst case where even bf16 measures
        # 0.997. The AUTHORITATIVE recall floor is now the trained-corpus
        # measurement above (churn_trained_int8_recall, floor 0.99, r10):
        # production serves trained embeddings, and the churn probe measures
        # those directly on every evidence run (docs/serving.md).
        check("serve_int8_corpus",
              int8_ratio is not None and float(int8_ratio) <= 0.35
              and int8_recall is not None and float(int8_recall) >= 0.98,
              (f"bench sidecar int8 corpus holds {int8_ratio}x the fp32 "
               f"resident bytes (<= 0.35x) at recall@10 {int8_recall} "
               ">= 0.98 vs fp32 "
               f"(bytes: {bench_extra.get('serve_corpus_bytes')})")
              if int8_ratio is not None else
              ("evidence/bench_tpu.json has no serve_int8_bytes_ratio — the "
               "sidecar predates the quantized-corpus corner; rerun bench.py "
               "on TPU to capture it"))
    # ISSUE 11 satellite: bench-trajectory regression gate over the committed
    # bench history. Gate only — it recomputes nothing; it reads the
    # BENCH_r*.json trajectory (+ the TPU sidecar) and fails the evidence run
    # if the latest record dropped a named figure >15% vs its own platform's
    # prior records. Runs on every platform: the history is committed JSON.
    traj_ok, traj_detail = _bench_trajectory_gate()
    check("bench_trajectory_no_regression", traj_ok, traj_detail)
    # ISSUE 14: serving observability must be near-free — the bench races the
    # same Zipf trace through an instrumented router (span tracing + metric
    # registries) and the traced qps may trail the bare qps by at most 3%.
    trace_ok, trace_detail = _fleet_tracing_overhead_gate()
    check("fleet_tracing_overhead_lt_3pct", trace_ok, trace_detail)
    # ISSUE 18: always-on profiling hooks (devprof.instrument on the train
    # step) must cost nothing while profiling is disabled — one predicate per
    # call, no clocks, no fences. The bench measures both legs fenced
    # (devprof.measure); this gate reads the committed race like the tracing
    # gate above. The zero-host-sync half of the contract is pinned by the
    # fetch-count + compile_guard regression test in tests/test_profile.py.
    prof_ok, prof_detail = _profile_overhead_gate()
    check("profile_overhead_lt_1pct", prof_ok, prof_detail)
    # ISSUE 19: shadow re-scoring (serve/shadow.py) samples live replies and
    # re-scores them with the exact path on its own thread — the bench races
    # the same trace with 100% sampling on, and the shadow leg may trail the
    # bare qps by at most 2%. Same pass-by-absence shape as the two gates
    # above (_overhead_race_gate).
    shadow_ok, shadow_detail = _shadow_overhead_gate()
    check("shadow_overhead_lt_2pct", shadow_ok, shadow_detail)
    # ISSUE 20: the measured autotuner race (bench _bench_tuning) must show
    # tuned-over-default >= 1.0 on any record that carries it — below 1.0
    # the race's own measurement discipline is broken (the default always
    # races). CPU histories pass by absence.
    tuned_ok, tuned_detail = _autotuned_speedup_gate()
    check("autotuned_speedup_ge_1", tuned_ok, tuned_detail)
    check("user_category_top1", user["category_top1_accuracy"] > 0.6,
          f"interest-category top-1 {user['category_top1_accuracy']:.4f} > 0.6 "
          "(chance ~1/8; scored against 5-candidate category means — one "
          "random candidate made the metric hostage to a single draw; "
          "measured 0.884 at the round-4 calibration)")

    # jaxcheck: disable=R2 (end-to-end harness wall clock for the whole evidence run; every stage fetches its aurocs to host before this point)
    payload = {
        "generated": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "platform": platform_claim,
        "repro_command": ("python evidence/run.py --cpu" if "--cpu" in argv
                          or platform == "cpu" else "python evidence/run.py"),
        "run_id": run_id,
        "uniform_provenance": uniform,
        "stage_provenance": dict(sorted(STAGE_PROVENANCE.items())),
        "seed": SEED,
        "wall_seconds": round(time.time() - t0, 1),
        "commands": {
            "main_autoencoder": MAIN_ARGS,
            "main_autoencoder_story": STORY_ARGS,
            "main_autoencoder_triplet": TRIPLET_ARGS,
            "main_starspace": STARSPACE_ARGS + ["--from_artifacts",
                                                "<online-mining data_dir>"],
            "main_autoencoder_moe": MOE_ARGS,
            "main_autoencoder_refscale": REFSCALE_ARGS,
            "main_autoencoder_refstory": REFSTORY_ARGS,
            "main_autoencoder_triplet_story": TRIPLET_STORY_ARGS,
            "main_user_model": USER_ARGS,
        },
        "aurocs_online_mining": {k: float(v) for k, v in sorted(aurocs.items())},
        "aurocs_story_mined": {k: float(v)
                               for k, v in sorted(story_aurocs.items())},
        "aurocs_refscale": {k: float(v) for k, v in sorted(ref_aurocs.items())},
        "refscale_wall_seconds": round(t_ref, 1),
        "aurocs_triplet": {k: float(v) for k, v in sorted(tri_aurocs.items())},
        "aurocs_triplet_story_keyed": {
            k: float(v) for k, v in sorted(tri_story_aurocs.items())},
        "aurocs_refstory": {
            k: float(v) for k, v in sorted(refstory_aurocs.items())},
        "refstory_wall_seconds": round(refstory["wall"], 1),
        "triplet_loss_trajectory": tri_traj,
        "aurocs_moe": {k: float(v) for k, v in sorted(moe_aurocs.items())},
        "aurocs_starspace": {k: float(v) for k, v in sorted(ss_aurocs.items())},
        "starspace": {"best_loss": ss_loss, "best_epoch": ss_epoch},
        "user_model": dict(user),
        "chaos_soak": chaos_out,
        "chaos_serve_soak": chaos_serve_out,
        "chaos_shard_soak": chaos_shard_out,
        "chaos_churn_soak": chaos_churn_out,
        "checks": checks,
    }
    # the 3-seed spread behind the calibrated thresholds rides along in the
    # record (full per-seed AUROCs in evidence/seed_spread.json)
    try:
        with open(os.path.join(HERE, "seed_spread.json")) as f:
            payload["seed_spread_summary"] = json.load(f)["summary"]
    except (FileNotFoundError, KeyError, json.JSONDecodeError):
        pass
    with open(os.path.join(HERE, "results.json"), "w") as f:
        json.dump(payload, f, indent=2)

    _write_md(payload)
    if os.path.exists(CACHE):  # a complete run owes nothing to partial state
        os.remove(CACHE)
    n_fail = sum(not c["pass"] for c in checks.values())
    print(f"evidence: {len(checks) - n_fail}/{len(checks)} checks passed; "
          f"artifacts in evidence/ ({payload['wall_seconds']}s)")
    return 1 if n_fail else 0


def _cat_story_table(aurocs, reps=("tfidf", "binary_count", "encoded")):
    """The standard representation x split Category/Story markdown table."""
    lines = ["| representation | split | Category | Story |",
             "|---|---|---|---|"]
    for rep in reps:
        for split, sfx in (("train", ""), ("validate", "_validate")):
            cat = aurocs[f"similarity_boxplot_{rep}{sfx}(Category)"]
            sto = aurocs[f"similarity_boxplot_{rep}{sfx}(Story)"]
            lines.append(f"| {rep} | {split} | {cat:.4f} | {sto:.4f} |")
    return lines


def _write_md(p):
    lines = [
        "# Quality evidence (seeded synthetic corpus)",
        "",
        f"Generated {p['generated']} on platform `{p['platform']}`, "
        f"seed {p['seed']}, {p['wall_seconds']}s wall, run `{p['run_id']}`.",
        "",
        ("**Every stage below was produced by this single run on this single "
         "platform** (per-stage provenance in results.json)."
         if p.get("uniform_provenance") else
         "**WARNING: stages in this record come from different runs or "
         "platforms** — see `stage_provenance` in results.json for which; "
         "rerun `python evidence/run.py` after deleting "
         "`evidence/.stage_cache.json` for a uniform record."),
        "",
        "Reproduce: `" + p.get("repro_command", "python evidence/run.py")
        + "` (exact driver flags recorded in results.json).",
        "",
        "The real UCI parquet is stripped from this environment "
        "(`/root/reference/.MISSING_LARGE_BLOBS`), so this is the seeded "
        "synthetic-corpus record — the same shape of evidence the reference "
        "commits in `starspace/train.log` and its AUROC-comparison notebook. "
        "Headline ROC/boxplot figures from the runs are committed under "
        "`evidence/figures/` (provenance sidecars name the producing run).",
        "",
        "## Online-mining driver: 12 AUROCs",
        "",
    ]
    a = p["aurocs_online_mining"]
    lines += _cat_story_table(a)
    lines += [
        "",
        "The DAE is trained with `batch_all` online mining on the Category "
        "label; the claim under test (reference notebook cells 9-13) is that "
        "the learned 100-dim embedding beats the 2000-dim tf-idf "
        "representation on that label's related-vs-unrelated AUROC.",
        "",
        "## Three-way comparison: tfidf vs DAE vs StarSpace (one split)",
        "",
        "StarSpace trains on the online-mining run's saved article split "
        "(`--from_artifacts`), the way the reference notebook exports the DAE "
        "run's own split (prepare_starspace_formatted_data.ipynb cells 3-13) "
        "— all four rows below score the same 1500-train/400-validate "
        "articles on the Category label:",
        "",
        "| representation | train AUROC | validate AUROC |",
        "|---|---|---|",
    ]
    s = p["aurocs_starspace"]
    for label, tr_v, vl_v in (
        ("tf-idf (2000-dim)",
         a["similarity_boxplot_tfidf(Category)"],
         a["similarity_boxplot_tfidf_validate(Category)"]),
        ("binary counts (2000-dim)",
         a["similarity_boxplot_binary_count(Category)"],
         a["similarity_boxplot_binary_count_validate(Category)"]),
        ("DAE encoded (100-dim, batch_all)",
         a["similarity_boxplot_encoded(Category)"],
         a["similarity_boxplot_encoded_validate(Category)"]),
        ("StarSpace (50-dim, native trainer)",
         s["starspace_train"], s["starspace_validate"]),
    ):
        lines.append(f"| {label} | {tr_v:.4f} | {vl_v:.4f} |")
    st = p["aurocs_story_mined"]
    lines += [
        "",
        "(Same-split is guaranteed by construction — StarSpace reads the "
        "saved parquets. Its own tf-idf columns — train "
        f"{s['tfidf_train']:.4f} / validate {s['tfidf_validate']:.4f} — "
        "differ from the table's because the reference notebook's StarSpace "
        "flow vectorizes binary bag-of-words before tf-idf while the main "
        "driver tf-idfs raw counts; both variants lose to the DAE.)",
        "",
        "## Story-mined run (`--label story`)",
        "",
        "Same generator, mined on the reference driver's other label "
        "(main_autoencoder.py:180-198) with alpha 30 — the round-4 sweep "
        "frontier (evidence/story_sweep.json: 13 configs over alpha/"
        "corr_frac/epochs/compress_factor; the 50-word story slices need a "
        "far heavier margin term than Category mining). The driver filters "
        "to story-valid rows exactly like the reference, so this run trains "
        "on the story-carrying subset (1000 train / 300 validate, 4x "
        "oversampled generation). "
        "Mining steers the embedding geometry: the category-mined run above "
        f"scores {a['similarity_boxplot_encoded_validate(Story)']:.4f} on "
        "Story validate where this story-mined run reaches "
        f"{st['similarity_boxplot_encoded_validate(Story)']:.4f}; conversely "
        "this run's Category validate "
        f"({st['similarity_boxplot_encoded_validate(Category)']:.4f}) gives "
        "back some of the category-mined run's "
        f"{a['similarity_boxplot_encoded_validate(Category)']:.4f} — the "
        "mining label is the knob, and the framework exposes both.",
        "",
    ]
    lines += _cat_story_table(st)
    lines += [
        "",
        "## Reference-scale run (8000 x 10000 -> 500, bf16, streaming eval)",
        "",
        f"The reference's headline workload shape end to end in "
        f"{p['refscale_wall_seconds']}s (50 epochs of batch_all mining + "
        "histogram-streaming AUROC eval, figures included):",
        "",
    ]
    lines += _cat_story_table(p["aurocs_refscale"])
    rs = p.get("aurocs_refstory")
    if rs:
        lines += [
            "",
            "## Reference-scale run, story-mined (`--label story`, 8000 x "
            "10000 -> 500, bf16)",
            "",
            "The headline workload shape mined on STORY (alpha 30, the "
            "story-sweep frontier; 3x oversampled generation fills the "
            f"story-valid splits) in {p.get('refstory_wall_seconds', 0)}s — "
            "the story-mining knob at reference scale:",
            "",
        ]
        lines += _cat_story_table(rs)
    m = p["aurocs_moe"]
    lines += [
        "",
        "## Mixture-of-denoisers (--n_experts 4, net-new family — "
        "EXPERIMENTAL)",
        "",
        "Same corpus as the online-mining run above, routed across 4 expert "
        "DAEs (Switch-style top-1 gating) on a 60-epoch schedule. "
        "**Experimental / expert-parallelism demo**: the iso-epoch sweep "
        "(evidence/moe_iso_epoch.json) shows the mixture does not match the "
        "single DAE at any schedule (0.8040@60 / 0.7904@100 / 0.7824@150 "
        "epochs vs the single DAE's 0.8477 — each expert trains on a ~1/4 "
        "data shard, and longer schedules overfit the shards rather than "
        "close the gap). It beats tfidf, and that is all its check claims:",
        "",
        "| representation | split | Category | Story |",
        "|---|---|---|---|",
    ]
    for split, sfx in (("train", ""), ("validate", "_validate")):
        cat = m[f"similarity_boxplot_encoded{sfx}(Category)"]
        sto = m[f"similarity_boxplot_encoded{sfx}(Story)"]
        lines.append(f"| encoded (4-expert MoE) | {split} | {cat:.4f} | {sto:.4f} |")
    t = p["aurocs_triplet"]
    lines += [
        "",
        "## Precomputed-triplet driver",
        "",
        "Per-category pos/neg article mapping (reference similar_articles) "
        "-> three aligned matrices -> triplet DAE; the eval tail matches the "
        "reference driver's full coverage "
        "(main_autoencoder_triplet.py:249-321):",
        "",
    ]
    if "similarity_boxplot_tfidf(Category)" in t:
        lines += _cat_story_table(t)
    else:
        # pre-round-4 record shape (train-only, mined label only): reachable
        # only when rendering an older committed results.json (the provenance
        # test uses the committed record as its template); a live run always
        # produces the 12-key shape
        lines += ["| representation | AUROC |", "|---|---|"]
        lines += [f"| {k} | {v:.4f} |" for k, v in t.items()]
    tsb = p.get("aurocs_triplet_story_keyed")
    if tsb:
        lines += [
            "",
            "The Story column above sits at chance BY CONSTRUCTION: the "
            "reference's per-category mapping makes positives same-CATEGORY "
            "neighbors (datasets/articles.py:83-128), so no gradient pulls "
            "same-story pairs together. Keying the same recipe on the story "
            "column instead (net-new `--label story` on this driver) makes "
            "the triplet path carry Story:",
            "",
        ]
        lines += _cat_story_table(tsb)
    tj = p.get("triplet_loss_trajectory", {})
    if tj.get("triplet_loss"):
        first, last = tj["triplet_loss"][0], tj["triplet_loss"][-1]
        lines += [
            "",
            f"Loss trajectory over {len(tj['triplet_loss'])} train steps "
            f"(one record per batch; full per-step series in results.json): "
            f"margin {first:.4f} -> {last:.4f}; anchor/pos/neg "
            "reconstruction " + " / ".join(
                f"{tj[k][0]:.2f}->{tj[k][-1]:.2f}"
                for k in ("autoencoder_loss_anchor", "autoencoder_loss_pos",
                          "autoencoder_loss_neg") if tj.get(k)) + ".",
        ]
    lines += [
        "",
        "## Native StarSpace baseline",
        "",
        f"Early-stopping loss **{p['starspace']['best_loss']:.6f}** at epoch "
        f"{p['starspace']['best_epoch']} "
        "(reference format: starspace/train.log:115-121).",
        "",
        "| comparison | AUROC |",
        "|---|---|",
    ]
    for k, v in p["aurocs_starspace"].items():
        lines.append(f"| {k} | {v:.4f} |")
    u = p["user_model"]
    lines += [
        "",
        "## User model (BASELINE config 5: stacked DAE -> GRU)",
        "",
        "The paper pipeline the reference never implemented: stacked 2-layer "
        "DAE pretraining (128,64) + joint fine-tune, GRU user states over "
        "simulated browse sessions, held-out users:",
        "",
        f"- pairwise rank accuracy **{u['rank_accuracy']:.4f} ± "
        f"{u.get('rank_accuracy_ci95', 0.0):.4f}** (95% CI over held-out "
        "users; chance 0.5)",
        f"- interest-category top-1 **{u['category_top1_accuracy']:.4f}** "
        "(chance ~1/8)",
        f"- {u['n_users_eval']} held-out users, seq_len {u['seq_len']}, "
        f"{u['d_embed']}-dim embeddings",
    ]
    ch = p.get("chaos_soak")
    if ch:
        lines += [
            "",
            "## Chaos soak (reliability subsystem)",
            "",
            f"{ch['n_ok']}/{ch['n_plans']} seeded fault plans — preemption "
            "mid-epoch, feed-worker death, torn checkpoint commit, transient "
            "I/O, post-crash truncation — each driven to a completed resumed "
            "run (docs/reliability.md). On CPU the resumed params must be "
            "bitwise-identical to the fault-free run's; every injected fault "
            "and retry is recorded in the run manifest:",
            "",
            "| plan | ok | bitwise | restarts | faults | retries | s |",
            "|---|---|---|---|---|---|---|",
        ]
        for pl in ch["plans"]:
            lines.append(
                f"| {pl['seed']} | {pl['ok']} | {pl['bitwise']} | "
                f"{pl['restarts']} | {pl['n_injected']} | {pl['n_retries']} | "
                f"{pl['duration_s']} |")
    cs = p.get("chaos_serve_soak")
    if cs:
        lines += [
            "",
            "## Chaos-serve soak (serving subsystem)",
            "",
            f"{cs['n_ok']}/{cs['n_plans']} seeded fault plans x overload "
            "traces against the deadline-aware serving stack "
            "(docs/serving.md): every submitted request ends in exactly one "
            "of reply / explicit shed / explicit error, injected serve.swap "
            "faults roll back with the old corpus still serving, and p95 "
            "stays within SLA even in degraded mode:",
            "",
            "| plan | ok | replied | shed | errors | swap fault | rolled "
            "back | p95 ms | s |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for pl in cs["plans"]:
            lines.append(
                f"| {pl['seed']} | {pl['ok']} | {pl['n_replied']} | "
                f"{pl['n_shed']} | {pl['n_errors']} | {pl['swap_faulted']} | "
                f"{pl['swap_rolled_back']} | {pl['p95_ms']} | "
                f"{pl['duration_s']} |")
    csh = p.get("chaos_shard_soak")
    if csh:
        lines += [
            "",
            "## Chaos-shard soak (mesh-sharded serving)",
            "",
            f"{csh['n_ok']}/{csh['n_plans']} seeded shard fault plans "
            "against the mesh-sharded corpus (docs/serving.md): shard lost "
            "under load / inside an append's prepare / prepare-crash per "
            "swap flavor, fp32 and int8. Each plan must quarantine, serve "
            "partial_corpus with coverage on every reply, refuse swaps "
            "while degraded, recover the slot bitwise from the host mirror, "
            "and show zero torn cross-shard reads and zero post-warmup "
            "compiles:",
            "",
            "| plan | family | dtype | ok | partial | min cov | bitwise | "
            "compiles | s |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for pl in csh["plans"]:
            lines.append(
                f"| {pl['seed']} | {pl['family']} | {pl['dtype']} | "
                f"{pl['ok']} | {pl['n_partial']} | {pl['min_coverage']} | "
                f"{pl['bitwise_recovered']} | {pl['n_post_warm_compiles']} | "
                f"{pl['duration_s']} |")
    cc = p.get("chaos_churn_soak")
    if cc:
        lines += [
            "",
            "## Chaos-churn soak (continuous refresh)",
            "",
            f"{cc['n_ok']}/{cc['n_plans']} seeded fault plans against the "
            "refresh loop — supervisor death at ingest/encode/fine-tune, "
            "swap crash inside the corpus, transient encode, preemption "
            "INSIDE the warm-start fine-tune (docs/reliability.md). Each "
            "plan must promote a strictly monotonic, health-gated version "
            "sequence matching its fault-free reference session and resume "
            "the fine-tune bitwise-exact on CPU:",
            "",
            "| plan | ok | bitwise | monotonic | restarts | rollbacks | "
            "faults | versions | s |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for pl in cc["plans"]:
            lines.append(
                f"| {pl['seed']} | {pl['ok']} | {pl['bitwise']} | "
                f"{pl['versions_monotonic']} | {pl['restarts']} | "
                f"{pl['rollbacks']} | {pl['n_injected']} | "
                f"{pl['versions']} | {pl['duration_s']} |")
        rc = cc.get("recall")
        if rc:
            lines += [
                "",
                f"Trained-corpus recall probe ({rc['shape']}): int8 "
                f"recall@10 **{rc['trained']['int8']}** / bf16 "
                f"**{rc['trained']['bfloat16']}** vs fp32 ranking on the "
                f"churned v{rc['corpus_version']} corpus; init-params worst "
                f"case at the same shape {rc['init_params']} — the basis "
                "for the 0.99 evidence floor (docs/serving.md).",
            ]
    lines += ["", "## Checks", ""]
    for name, c in p["checks"].items():
        lines.append(f"- **{'PASS' if c['pass'] else 'FAIL'}** {name}: {c['detail']}")
    lines.append("")
    with open(os.path.join(HERE, "RESULTS.md"), "w") as f:
        f.write("\n".join(lines))


if __name__ == "__main__":
    sys.exit(main())
