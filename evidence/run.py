"""Quality-evidence harness: run the full drivers on the seeded synthetic corpus
and commit the numbers (evidence/results.json + evidence/RESULTS.md).

The reference ships its evidence in-repo (starspace/train.log:115-121 — early
stopping loss 0.018963 @ epoch 16 — and the uci_*_embed.txt dumps, plus the
AUROC comparison in prepare_starspace_formatted_data.ipynb cells 9-13). This
repo's mount has no real UCI parquet (/root/reference/.MISSING_LARGE_BLOBS), so
the committed record is the seeded synthetic-corpus equivalent: the full
online-mining driver (12 AUROCs), the precomputed-triplet driver, and the
native StarSpace baseline, with the quality claims asserted, not just printed:

  * encoded embeddings must beat BOTH chance and the tf-idf representation on
    the mined Category label, train and validate splits (the reference's
    headline comparison);
  * the StarSpace baseline must converge to a finite early-stopping loss.

Reproduce:  JAX_PLATFORMS= python evidence/run.py
(runs the drivers in a scratch dir; rewrites evidence/{results.json,RESULTS.md})
"""

import datetime
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

SEED = 0
MAIN_ARGS = [
    "--model_name", "evidence", "--synthetic", "--validation",
    "--num_epochs", "25", "--train_row", "1500", "--validate_row", "400",
    "--max_features", "2000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--triplet_strategy", "batch_all", "--alpha", "1.0",
    "--corr_type", "masking", "--corr_frac", "0.3", "--seed", str(SEED),
]
TRIPLET_ARGS = [
    "--model_name", "evidence_triplet", "--synthetic",
    "--num_epochs", "15", "--train_row", "800", "--validate_row", "0",
    "--max_features", "2000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--corr_type", "masking", "--corr_frac", "0.3", "--seed", str(SEED),
]
STARSPACE_ARGS = [
    "--model_name", "evidence_ss", "--synthetic",
    "--train_row", "800", "--validate_row", "300",
    "--max_features", "2000", "--dim", "50", "--epochs", "30",
    "--threads", "4", "--seed", str(SEED),
]
# same corpus as MAIN_ARGS by construction (the evidence check claims it);
# the routed mixture gets a longer schedule — each expert sees ~1/E of the
# rows per epoch, and 25 epochs leaves the mixture at 0.58 AUROC (measured)
# while 60 converges it to ~0.79
assert MAIN_ARGS[0] == "--model_name"
MOE_ARGS = (["--model_name", "evidence_moe"] + MAIN_ARGS[2:]
            + ["--n_experts", "4", "--eval_reps", "encoded"])
MOE_ARGS[MOE_ARGS.index("--num_epochs") + 1] = "60"
# the reference's headline workload shape: 8000 rows x 10000 features -> 500
# (main_autoencoder.py:50 compress_factor 20, :60 batch 10%), bf16 compute,
# streaming eval tail
REFSCALE_ARGS = [
    "--model_name", "evidence_refscale", "--synthetic",
    "--synthetic_vocab", "12000", "--validation",
    "--num_epochs", "50", "--train_row", "8000", "--validate_row", "2000",
    "--max_features", "10000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--triplet_strategy", "batch_all", "--alpha", "1.0",
    "--corr_type", "masking", "--corr_frac", "0.3",
    "--compute_dtype", "bfloat16", "--streaming_eval", "--seed", str(SEED),
]
# BASELINE config 5: stacked 2-layer DAE pretrain -> GRU user-state RNN over
# per-user article-embedding sequences (the paper pipeline the reference never
# implemented) — held-out pairwise rank accuracy vs the 0.5 chance level and
# interest-category top-1 vs ~1/7 chance
USER_ARGS = [
    "--model_name", "evidence_user", "--seed", str(SEED),
    "--n_articles", "1200", "--max_features", "1500",
    "--stacked_layers", "128,64", "--finetune_epochs", "2", "--dae_epochs", "5",
    "--n_users", "300", "--seq_len", "12", "--gru_epochs", "15",
]


CACHE = os.path.join(HERE, ".stage_cache.json")


def _fingerprint():
    """Stage results are only reusable for the exact driver args + seed + CODE
    that produced them — a cache from an edited configuration or an edited
    repo must invalidate, or stale numbers would be committed under the new
    flags/code. Code state = HEAD + a stable hash of the working-tree diff
    (PROGRESS.jsonl excluded: the round driver rewrites it every few minutes,
    and its churn must not invalidate an otherwise-identical resume)."""
    import hashlib
    import subprocess

    def git(*argv):
        return subprocess.run(["git", *argv], cwd=REPO, capture_output=True,
                              text=True).stdout

    try:
        head = git("rev-parse", "HEAD").strip()
        diff = git("diff", "HEAD", "--", ".", ":(exclude)PROGRESS.jsonl")
        names = "\n".join(l for l in git("status", "--porcelain").splitlines()
                          if "PROGRESS.jsonl" not in l)
        code = hashlib.sha256((diff + names).encode()).hexdigest()
    except OSError:
        head, code = "nogit", "nogit"
    return json.dumps([head, code, SEED, MAIN_ARGS, TRIPLET_ARGS,
                       STARSPACE_ARGS, MOE_ARGS, REFSCALE_ARGS, USER_ARGS])


def _load_cache():
    try:
        with open(CACHE) as f:
            cache = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}  # absent, or truncated by a kill mid-write: start fresh
    if cache.get("fingerprint") != _fingerprint():
        print("stage cache is from a different configuration; ignoring it")
        return {}
    return cache


def _staged(name, fn):
    """Stage-level resume: each completed stage's outputs persist to
    evidence/.stage_cache.json, so a mid-run TPU-tunnel hang (observed: the
    tunnel can die for hours mid-stage) only costs the stage in flight — rerun
    and the finished stages reload. Stages are seed-deterministic, so cached
    results are the same numbers a fresh run would commit. Delete the cache
    file (or let a successful run do it) to force everything fresh."""
    cache = _load_cache()
    stages = cache.setdefault("stages", {})
    if name in stages:
        print(f"== {name} == (cached from a previous partial run)")
        return stages[name]
    print(f"== {name} ==")
    out = fn()
    stages[name] = out
    cache["fingerprint"] = _fingerprint()
    tmp = CACHE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cache, f)
    os.replace(tmp, CACHE)  # atomic: a kill mid-dump can't truncate the cache
    return out


# the reference commits its evidence figures (the AUROC-comparison notebook's
# persisted outputs); these are ours — a small committed subset of the driver's
# ROC/boxplot PNGs, refreshed by every full evidence run
FIGURES = ("similarity_boxplot_encoded(Category)",
           "similarity_boxplot_encoded_validate(Category)",
           "similarity_boxplot_tfidf_validate(Category)")


def _export_figures(plot_dir, stage, platform):
    """Copy the stage's headline ROC/boxplot figures into evidence/figures/
    (tracked), with a provenance sidecar naming the run that produced them.
    Stale figures from earlier runs of the same stage are pruned so the tracked
    set never mixes runs; a missing source PNG is logged, not silently skipped."""
    import shutil

    fig_dir = os.path.join(HERE, "figures")
    os.makedirs(fig_dir, exist_ok=True)
    copied = []
    for name in FIGURES:
        src = os.path.join(plot_dir, name + ".png")
        if not os.path.exists(src):
            print(f"figures: WARNING — {stage} produced no {name}.png; "
                  "not exported")
            continue
        dst = f"{stage}_{name}.png"
        shutil.copyfile(src, os.path.join(fig_dir, dst))
        copied.append(dst)
    for f in os.listdir(fig_dir):
        if (f.startswith(stage + "_") and f.endswith(".png")
                and f not in copied):
            os.remove(os.path.join(fig_dir, f))
            print(f"figures: pruned stale {f} (not produced by this run)")
    prov = os.path.join(fig_dir, f"{stage}.provenance.txt")
    if copied:
        stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
        with open(prov, "w") as f:
            print(f"stage={stage} platform={platform} seed={SEED} "
                  f"generated={stamp}", file=f)
            for c in copied:
                print(c, file=f)
    elif os.path.exists(prov):
        # this run produced no figures and the pruning above removed the old
        # ones — a surviving sidecar would list files that no longer exist
        os.remove(prov)
        print(f"figures: removed stale {stage}.provenance.txt "
              "(no figures produced by this run)")
    return copied


def _check_figures(stage, names):
    """A stage resumed from cache exports nothing — verify its previously
    exported figures are still on disk, so RESULTS.md can't claim figures that
    a clean wiped."""
    fig_dir = os.path.join(HERE, "figures")
    missing = [n for n in names if not os.path.exists(os.path.join(fig_dir, n))]
    if missing:
        print(f"figures: WARNING — {stage} resumed from cache but its "
              f"exported figures are missing from evidence/figures/: {missing}."
              " Delete evidence/.stage_cache.json and rerun to regenerate.")


def main():
    t0 = time.time()
    import jax

    platform = jax.devices()[0].platform
    print(f"evidence run on platform={platform}")

    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import (
        main as main_autoencoder)
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet import (
        main as main_triplet)
    from dae_rnn_news_recommendation_tpu.cli.main_starspace import (
        main as main_starspace)
    from dae_rnn_news_recommendation_tpu.cli.main_user_model import (
        main as main_user_model)

    scratch = tempfile.mkdtemp(prefix="evidence_")
    cwd = os.getcwd()
    os.chdir(scratch)
    try:
        def _main_stage():
            model, out = main_autoencoder(MAIN_ARGS)
            return {"aurocs": out,
                    "figures": _export_figures(model.plot_dir, "online",
                                               platform)}

        main_out = _staged("online-mining driver", _main_stage)
        aurocs = main_out["aurocs"]
        _check_figures("online-mining driver", main_out.get("figures", []))
        tri_aurocs = _staged("precomputed-triplet driver",
                             lambda: main_triplet(TRIPLET_ARGS)[1])

        def _ss():
            result, ss_aurocs = main_starspace(STARSPACE_ARGS)
            return {"best_val_error": float(result["best_val_error"]),
                    "epoch_errors": [float(v) for v in result["epoch_errors"]],
                    "aurocs": ss_aurocs}

        ss = _staged("native StarSpace baseline", _ss)
        ss_result, ss_aurocs = ss, ss["aurocs"]
        moe_aurocs = _staged("mixture-of-denoisers (4 experts, net-new family)",
                             lambda: main_autoencoder(MOE_ARGS)[1])

        def _ref():
            t_ref = time.time()
            model, out = main_autoencoder(REFSCALE_ARGS)
            return {"aurocs": out, "wall": time.time() - t_ref,
                    "figures": _export_figures(model.plot_dir, "refscale",
                                               platform)}

        ref = _staged("reference-scale run (8000 x 10000 -> 500, bf16, "
                      "streaming eval)", _ref)
        ref_aurocs, t_ref = ref["aurocs"], ref["wall"]
        _check_figures("reference-scale run", ref.get("figures", []))

        user = _staged("user model (stacked DAE -> GRU, config 5)",
                       lambda: main_user_model(USER_ARGS)[1])
    finally:
        os.chdir(cwd)

    # ------------------------------------------------------------ assertions
    checks = {}

    def check(name, ok, detail):
        checks[name] = {"pass": bool(ok), "detail": detail}
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}: {detail}")

    enc_tr = aurocs["similarity_boxplot_encoded(Category)"]
    enc_vl = aurocs["similarity_boxplot_encoded_validate(Category)"]
    tfidf_tr = aurocs["similarity_boxplot_tfidf(Category)"]
    tfidf_vl = aurocs["similarity_boxplot_tfidf_validate(Category)"]
    check("encoded_beats_chance_train", enc_tr > 0.65,
          f"encoded(Category) train AUROC {enc_tr:.4f} > 0.65")
    check("encoded_beats_chance_validate", enc_vl > 0.65,
          f"encoded(Category) validate AUROC {enc_vl:.4f} > 0.65")
    check("encoded_beats_tfidf_train", enc_tr > tfidf_tr,
          f"encoded {enc_tr:.4f} > tfidf {tfidf_tr:.4f} (Category, train)")
    check("encoded_beats_tfidf_validate", enc_vl > tfidf_vl,
          f"encoded {enc_vl:.4f} > tfidf {tfidf_vl:.4f} (Category, validate)")
    check("triplet_encoded_above_chance", tri_aurocs["encoded"] > 0.5,
          f"triplet encoded AUROC {tri_aurocs['encoded']:.4f} > 0.5")
    moe_vl = moe_aurocs["similarity_boxplot_encoded_validate(Category)"]
    check("moe_encoded_beats_tfidf_validate",
          moe_vl > 0.65 and moe_vl > tfidf_vl,
          f"4-expert mixture encoded {moe_vl:.4f} > tfidf {tfidf_vl:.4f} "
          "(Category, validate; same corpus, 60-epoch schedule — each expert "
          "sees ~1/4 of the rows per epoch)")
    ref_enc = ref_aurocs["similarity_boxplot_encoded_validate(Category)"]
    ref_tfidf = ref_aurocs["similarity_boxplot_tfidf_validate(Category)"]
    check("refscale_encoded_beats_tfidf",
          ref_enc > 0.6 and ref_enc > ref_tfidf,
          f"reference-scale encoded {ref_enc:.4f} > tfidf {ref_tfidf:.4f} "
          f"(Category, validate; {t_ref:.0f}s end to end)")
    import numpy as np

    ss_loss = float(ss_result["best_val_error"])
    ss_epoch = int(np.argmin(ss_result["epoch_errors"]))
    check("starspace_converged", np.isfinite(ss_loss),
          f"early stopping loss {ss_loss:.6f} @ epoch {ss_epoch}")
    check("user_rank_above_chance", user["rank_accuracy"] > 0.6,
          f"held-out pairwise rank accuracy {user['rank_accuracy']:.4f} > 0.6 "
          "(chance 0.5)")
    check("user_category_top1", user["category_top1_accuracy"] > 0.3,
          f"interest-category top-1 {user['category_top1_accuracy']:.4f} > 0.3 "
          "(chance ~1/7)")

    payload = {
        "generated": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "platform": platform,
        "seed": SEED,
        "wall_seconds": round(time.time() - t0, 1),
        "commands": {
            "main_autoencoder": MAIN_ARGS,
            "main_autoencoder_triplet": TRIPLET_ARGS,
            "main_starspace": STARSPACE_ARGS,
            "main_autoencoder_moe": MOE_ARGS,
            "main_autoencoder_refscale": REFSCALE_ARGS,
            "main_user_model": USER_ARGS,
        },
        "aurocs_online_mining": {k: float(v) for k, v in sorted(aurocs.items())},
        "aurocs_refscale": {k: float(v) for k, v in sorted(ref_aurocs.items())},
        "refscale_wall_seconds": round(t_ref, 1),
        "aurocs_triplet": {k: float(v) for k, v in sorted(tri_aurocs.items())},
        "aurocs_moe": {k: float(v) for k, v in sorted(moe_aurocs.items())},
        "aurocs_starspace": {k: float(v) for k, v in sorted(ss_aurocs.items())},
        "starspace": {"best_loss": ss_loss, "best_epoch": ss_epoch},
        "user_model": dict(user),
        "checks": checks,
    }
    with open(os.path.join(HERE, "results.json"), "w") as f:
        json.dump(payload, f, indent=2)

    _write_md(payload)
    if os.path.exists(CACHE):  # a complete run owes nothing to partial state
        os.remove(CACHE)
    n_fail = sum(not c["pass"] for c in checks.values())
    print(f"evidence: {len(checks) - n_fail}/{len(checks)} checks passed; "
          f"artifacts in evidence/ ({payload['wall_seconds']}s)")
    return 1 if n_fail else 0


def _write_md(p):
    lines = [
        "# Quality evidence (seeded synthetic corpus)",
        "",
        f"Generated {p['generated']} on platform `{p['platform']}`, "
        f"seed {p['seed']}, {p['wall_seconds']}s wall.",
        "",
        "Reproduce: `JAX_PLATFORMS= python evidence/run.py` "
        "(exact driver flags recorded in results.json).",
        "",
        "The real UCI parquet is stripped from this environment "
        "(`/root/reference/.MISSING_LARGE_BLOBS`), so this is the seeded "
        "synthetic-corpus record — the same shape of evidence the reference "
        "commits in `starspace/train.log` and its AUROC-comparison notebook. "
        "Headline ROC/boxplot figures from the runs are committed under "
        "`evidence/figures/` (provenance sidecars name the producing run).",
        "",
        "## Online-mining driver: 12 AUROCs",
        "",
        "| representation | split | Category | Story |",
        "|---|---|---|---|",
    ]
    a = p["aurocs_online_mining"]
    for rep in ("tfidf", "binary_count", "encoded"):
        for split, sfx in (("train", ""), ("validate", "_validate")):
            cat = a[f"similarity_boxplot_{rep}{sfx}(Category)"]
            sto = a[f"similarity_boxplot_{rep}{sfx}(Story)"]
            lines.append(f"| {rep} | {split} | {cat:.4f} | {sto:.4f} |")
    lines += [
        "",
        "The DAE is trained with `batch_all` online mining on the Category "
        "label; the claim under test (reference notebook cells 9-13) is that "
        "the learned 100-dim embedding beats the 2000-dim tf-idf "
        "representation on that label's related-vs-unrelated AUROC.",
        "",
        "## Reference-scale run (8000 x 10000 -> 500, bf16, streaming eval)",
        "",
        f"The reference's headline workload shape end to end in "
        f"{p['refscale_wall_seconds']}s (50 epochs of batch_all mining + "
        "histogram-streaming AUROC eval, figures included):",
        "",
        "| representation | split | Category | Story |",
        "|---|---|---|---|",
    ]
    r = p["aurocs_refscale"]
    for rep in ("tfidf", "binary_count", "encoded"):
        for split, sfx in (("train", ""), ("validate", "_validate")):
            cat = r[f"similarity_boxplot_{rep}{sfx}(Category)"]
            sto = r[f"similarity_boxplot_{rep}{sfx}(Story)"]
            lines.append(f"| {rep} | {split} | {cat:.4f} | {sto:.4f} |")
    m = p["aurocs_moe"]
    lines += [
        "",
        "## Mixture-of-denoisers (--n_experts 4, net-new family)",
        "",
        "Same corpus as the online-mining run above, routed across 4 expert "
        "DAEs (Switch-style top-1 gating) on a 60-epoch schedule (each expert "
        "sees ~1/4 of the rows per epoch, so the mixture converges slower "
        "than the single DAE's 25 epochs):",
        "",
        "| representation | split | Category | Story |",
        "|---|---|---|---|",
    ]
    for split, sfx in (("train", ""), ("validate", "_validate")):
        cat = m[f"similarity_boxplot_encoded{sfx}(Category)"]
        sto = m[f"similarity_boxplot_encoded{sfx}(Story)"]
        lines.append(f"| encoded (4-expert MoE) | {split} | {cat:.4f} | {sto:.4f} |")
    lines += [
        "",
        "## Precomputed-triplet driver",
        "",
        "| representation | AUROC |",
        "|---|---|",
    ]
    for k, v in p["aurocs_triplet"].items():
        lines.append(f"| {k} | {v:.4f} |")
    lines += [
        "",
        "## Native StarSpace baseline",
        "",
        f"Early-stopping loss **{p['starspace']['best_loss']:.6f}** at epoch "
        f"{p['starspace']['best_epoch']} "
        "(reference format: starspace/train.log:115-121).",
        "",
        "| comparison | AUROC |",
        "|---|---|",
    ]
    for k, v in p["aurocs_starspace"].items():
        lines.append(f"| {k} | {v:.4f} |")
    u = p["user_model"]
    lines += [
        "",
        "## User model (BASELINE config 5: stacked DAE -> GRU)",
        "",
        "The paper pipeline the reference never implemented: stacked 2-layer "
        "DAE pretraining (128,64) + joint fine-tune, GRU user states over "
        "simulated browse sessions, held-out users:",
        "",
        f"- pairwise rank accuracy **{u['rank_accuracy']:.4f}** (chance 0.5)",
        f"- interest-category top-1 **{u['category_top1_accuracy']:.4f}** "
        "(chance ~1/7)",
        f"- {u['n_users_eval']} held-out users, seq_len {u['seq_len']}, "
        f"{u['d_embed']}-dim embeddings",
    ]
    lines += ["", "## Checks", ""]
    for name, c in p["checks"].items():
        lines.append(f"- **{'PASS' if c['pass'] else 'FAIL'}** {name}: {c['detail']}")
    lines.append("")
    with open(os.path.join(HERE, "RESULTS.md"), "w") as f:
        f.write("\n".join(lines))


if __name__ == "__main__":
    sys.exit(main())
