"""3-seed spread on the headline quality tables (VERDICT r4 item 5).

The committed evidence record (results.json) is one seeded run; the AUROCs at
1500-article scale carry real run-to-run variance, so the frontier checks
calibrated to one draw (triplet > 0.70, story > 0.64) need a measured spread
behind them. This reruns the three small headline stages — online-mining,
story-mined, precomputed-triplet — at seeds 0/1/2 (same flags as
evidence/run.py otherwise) and commits per-seed AUROCs + mean/min/max for the
check-relevant cells. evidence/run.py's checks reference these bounds.

The reference-scale stage is excluded: at 8000x10000 the AUROCs are tight
(histogram-streaming over 2000 validate rows) and one run costs ~90 CPU-min.

Run: python evidence/seed_spread.py    (CPU-forced; resumable per seed/stage)
"""

import json
import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

OUT = os.path.join(HERE, "seed_spread.json")
SEEDS = (0, 1, 2)

# the check-relevant cells summarized at the end
KEY_CELLS = {
    "main": ["similarity_boxplot_encoded(Category)",
             "similarity_boxplot_encoded_validate(Category)",
             "similarity_boxplot_tfidf(Category)",
             "similarity_boxplot_tfidf_validate(Category)",
             "similarity_boxplot_encoded_validate(Story)"],
    "story": ["similarity_boxplot_encoded_validate(Story)",
              "similarity_boxplot_binary_count_validate(Story)",
              "similarity_boxplot_tfidf_validate(Story)"],
    "triplet": ["similarity_boxplot_encoded_validate(Category)",
                "similarity_boxplot_binary_count_validate(Category)",
                "similarity_boxplot_encoded_validate(Story)"],
}


def _stage_args(seed):
    """Mirror evidence/run.py's MAIN/STORY/TRIPLET args at the given seed."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "evrun", os.path.join(HERE, "run.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)

    def reseed(args):
        args = list(args)
        args[args.index("--seed") + 1] = str(seed)
        return args

    return {"main": reseed(m.MAIN_ARGS), "story": reseed(m.STORY_ARGS),
            "triplet": reseed(m.TRIPLET_ARGS)}


def git_rev():
    try:
        return subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                              capture_output=True, text=True).stdout.strip()
    except OSError:
        return "nogit"


def main():
    import tempfile

    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import (
        main as main_autoencoder)
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet import (
        main as main_triplet)

    try:
        with open(OUT) as f:
            payload = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        payload = {
            "purpose": ("VERDICT r4 item 5: per-seed AUROCs for the three "
                        "small headline stages; frontier checks reference "
                        "the worst-case seed instead of one draw"),
            "platform": "cpu", "git_rev": git_rev(), "seeds": list(SEEDS),
            "runs": {},
        }

    cwd = os.getcwd()
    scratch = tempfile.mkdtemp(prefix="seed_spread_")
    os.chdir(scratch)
    try:
        for seed in SEEDS:
            args = _stage_args(seed)
            for stage, driver in (("main", main_autoencoder),
                                  ("story", main_autoencoder),
                                  ("triplet", main_triplet)):
                key = f"{stage}_seed{seed}"
                if key in payload["runs"]:
                    print(f"[skip] {key}")
                    continue
                a = list(args[stage])
                a[a.index("--model_name") + 1] += f"_s{seed}"
                print(f"[run ] {key}", flush=True)
                _, aurocs = driver(a)
                payload["runs"][key] = {
                    k: round(float(v), 4) for k, v in sorted(aurocs.items())}
                with open(OUT, "w") as f:
                    json.dump(payload, f, indent=1)
                print(f"[done] {key}", flush=True)
    finally:
        os.chdir(cwd)

    summary = {}
    for stage, cells in KEY_CELLS.items():
        for cell in cells:
            vals = [payload["runs"][f"{stage}_seed{s}"][cell] for s in SEEDS]
            summary[f"{stage}:{cell}"] = {
                "per_seed": vals,
                "mean": round(sum(vals) / len(vals), 4),
                "min": min(vals), "max": max(vals),
            }
    payload["summary"] = summary
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
    for k, v in summary.items():
        print(f"{k}: mean {v['mean']} range [{v['min']}, {v['max']}]")


if __name__ == "__main__":
    main()
