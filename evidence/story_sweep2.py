"""Round-5 widened story sweep (VERDICT r4 item 7): beyond the round-4 sweep's
4 knobs (alpha/corr_frac/epochs/compress_factor, evidence/story_sweep.json),
this adds the orthogonal dimensions the verdict asked for:

  * joint two-label mining (--label story --label2 category_publish_name):
    the round-4 frontier overfits the tiny story set (train 0.97 vs validate
    0.68); a category margin term regularizes the same embedding
  * tfidf-input story mining (--input_format tfidf --loss_func mean_squared,
    the reference's cross-field rule, main_autoencoder.py:108-109)
  * compress_factor (code width), learning_rate, batch_size (mining-pool
    size), and corruption type

Goal: story-mined encoded validate(Story) >= tfidf 0.6932, else commit the
plateau (>= 25 configs total across both sweeps). Writes
evidence/story_sweep2.json incrementally; rerunnable (finished runs reload).

Run: python evidence/story_sweep2.py   (CPU-forced; ~3 min/config)
"""

import json
import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

OUT = os.path.join(HERE, "story_sweep2.json")

# the round-4 sweep's base config (story_sweep.json "base_config"), verbatim
BASE = ["--synthetic", "--validation", "--num_epochs", "25",
        "--train_row", "1000", "--validate_row", "300",
        "--max_features", "2000", "--batch_size", "0.1",
        "--opt", "ada_grad", "--learning_rate", "0.5",
        "--triplet_strategy", "batch_all", "--corr_type", "masking",
        "--seed", "0", "--label", "story", "--synthetic_oversample", "4.0"]

# every config pins alpha explicitly; later duplicate flags win in argparse,
# so extras may override BASE entries
GRID = [
    # joint two-label mining (net-new knob; needs the r5 label2 feature)
    ("joint_a30_l2a03", ["--alpha", "30.0", "--corr_frac", "0.3",
                         "--label2", "category_publish_name",
                         "--label2_alpha", "0.3"]),
    ("joint_a30_l2a10", ["--alpha", "30.0", "--corr_frac", "0.3",
                         "--label2", "category_publish_name",
                         "--label2_alpha", "1.0"]),
    ("joint_a10_l2a10", ["--alpha", "10.0", "--corr_frac", "0.3",
                         "--label2", "category_publish_name",
                         "--label2_alpha", "1.0"]),
    # tfidf-input story mining (reference cross-field rule)
    ("tfidf_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                   "--input_format", "tfidf", "--loss_func", "mean_squared",
                   "--dec_act_func", "none", "--enc_act_func", "tanh"]),
    ("tfidf_a10", ["--alpha", "10.0", "--corr_frac", "0.3",
                   "--input_format", "tfidf", "--loss_func", "mean_squared",
                   "--dec_act_func", "none", "--enc_act_func", "tanh"]),
    # code width around the default compress_factor 10
    ("cf5_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                 "--compress_factor", "5"]),
    ("cf40_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                  "--compress_factor", "40"]),
    # learning rate
    ("lr01_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                  "--learning_rate", "0.1"]),
    ("lr10_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                  "--learning_rate", "1.0"]),
    # batch size = mining-pool size for batch_all
    ("bs025_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                   "--batch_size", "0.25"]),
    ("bs005_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                   "--batch_size", "0.05"]),
    # activation/loss family at the frontier alpha
    ("tanh_ms_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                     "--enc_act_func", "tanh", "--dec_act_func", "none",
                     "--loss_func", "mean_squared"]),
    # corruption type
    ("snp_a30", ["--alpha", "30.0", "--corr_frac", "0.3",
                 "--corr_type", "salt_and_pepper"]),
    # joint mining with the bigger mining pool
    ("joint_a30_l2a03_bs025", ["--alpha", "30.0", "--corr_frac", "0.3",
                               "--batch_size", "0.25",
                               "--label2", "category_publish_name",
                               "--label2_alpha", "0.3"]),
]


def git_rev():
    try:
        return subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                              capture_output=True, text=True).stdout.strip()
    except OSError:
        return "nogit"


def main():
    import tempfile

    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import (
        main as main_autoencoder)

    try:
        with open(OUT) as f:
            payload = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        payload = {
            "purpose": ("VERDICT r4 item 7: widen the story sweep beyond "
                        "alpha/corr_frac/epochs/compress_factor — joint "
                        "two-label mining, tfidf input, lr, batch size, "
                        "corruption; goal encoded validate(Story) >= tfidf "
                        "0.6932 or a >= 25-config plateau (13 r4 + these)"),
            "base_config": " ".join(BASE),
            "platform": "cpu",
            "git_rev": git_rev(),
            "runs": [],
        }
    done = {r["name"] for r in payload["runs"]}

    cwd = os.getcwd()
    scratch = tempfile.mkdtemp(prefix="story_sweep2_")
    os.chdir(scratch)
    try:
        for name, extra in GRID:
            if name in done:
                print(f"[skip] {name} (already recorded)")
                continue
            args = BASE + ["--model_name", f"sw2_{name}"] + extra
            print(f"[run ] {name}: {' '.join(extra)}", flush=True)
            _, aurocs = main_autoencoder(args)
            payload["runs"].append({
                "name": name, "args": " ".join(extra),
                "aurocs": {k: round(float(v), 4)
                           for k, v in sorted(aurocs.items())},
            })
            with open(OUT, "w") as f:
                json.dump(payload, f, indent=1)
            print(f"[done] {name}: validate(Story) encoded="
                  f"{aurocs['similarity_boxplot_encoded_validate(Story)']:.4f}",
                  flush=True)
    finally:
        os.chdir(cwd)

    best = max(payload["runs"],
               key=lambda r: r["aurocs"]["similarity_boxplot_encoded_validate(Story)"])
    payload["frontier"] = {
        "config": best["name"], "args": best["args"],
        "encoded_validate_story":
            best["aurocs"]["similarity_boxplot_encoded_validate(Story)"],
    }
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
    print("frontier:", payload["frontier"])


if __name__ == "__main__":
    main()
