"""Scale evidence: BASELINE config 3 — batch_hard mining, max_features=50000,
100k articles — run end to end and recorded in-repo (SCALE.md + scale.json).

This is the configuration the reference cannot run at all: its eval
materializes six [N, N] float32 matrices (240 GB at N=100k) and its batch_all
masks OOM beyond ~1k rows (SURVEY §2.3, §5.7). Here the whole pipeline —
100k-doc vectorization, batch_hard training (10k-row batches via the
sparse-ingest feed), encode, and the exact streaming AUROC over all 10^10
pairs — completes on a single chip.

The wide sparse representations (tfidf/binary at 50k features) are excluded
from the AUROC sweep via --eval_reps: their pair sweeps cost ~F/D times the
encoded one (~5e14 FLOPs each), which is not an eval any framework runs at
this size; the learned embedding is the representation under test.

Reproduce:  python evidence/scale.py          (~30 min on one TPU chip;
            python evidence/scale.py --cpu    forces CPU — hours, not
            recommended; the flag sets the platform before jax import AND via
            jax.config, since the env var alone is ignored by the axon hook)
"""

import datetime
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

SEED = 0
ARGS = [
    "--model_name", "evidence_scale", "--synthetic",
    "--synthetic_vocab", "60000", "--validation",
    "--num_epochs", "60", "--train_row", "100000", "--validate_row", "5000",
    "--max_features", "50000", "--batch_size", "0.1",
    "--opt", "ada_grad", "--learning_rate", "0.5",
    "--triplet_strategy", "batch_hard", "--alpha", "1.0",
    "--corr_type", "masking", "--corr_frac", "0.3",
    "--compute_dtype", "bfloat16", "--eval_reps", "encoded",
    "--verbose", "--verbose_step", "20", "--seed", str(SEED),
]


def main(argv=None):
    t0 = time.time()
    argv = sys.argv[1:] if argv is None else argv
    if "--cpu" in argv:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if "--cpu" in argv:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    print(f"scale evidence on platform={platform}")

    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import (
        main as main_autoencoder)

    scratch = tempfile.mkdtemp(prefix="evidence_scale_")
    cwd = os.getcwd()
    os.chdir(scratch)
    try:
        _, aurocs = main_autoencoder(ARGS)
    finally:
        os.chdir(cwd)
    # jaxcheck: disable=R2 (whole-run wall clock: `aurocs` are host floats already, nothing is still in flight)
    wall = time.time() - t0

    checks = {}

    def check(name, ok, detail):
        checks[name] = {"pass": bool(ok), "detail": detail}
        print(f"  [{'PASS' if ok else 'FAIL'}] {name}: {detail}")

    enc_vl = aurocs["similarity_boxplot_encoded_validate(Category)"]
    check("scale_run_completes", True,
          f"100k x 50k batch_hard pipeline end to end in {wall:.0f}s "
          "(train + encode + 10^10-pair streaming AUROC)")
    check("scale_encoded_above_chance", enc_vl > 0.55,
          f"encoded(Category) validate AUROC {enc_vl:.4f} > 0.55 at 100k rows")
    story_vl = aurocs["similarity_boxplot_encoded_validate(Story)"]
    check("scale_story_chance_by_construction", 0.40 <= story_vl <= 0.62,
          f"encoded(Story) validate AUROC {story_vl:.4f} within the chance "
          "band [0.40, 0.62]: this run's batch_hard mining is keyed on "
          "Category alone, so the embedding carries no Story signal by "
          "construction (same treatment as RESULTS.md's triplet Story cells)")

    try:
        import subprocess

        rev = subprocess.run(["git", "-C", REPO, "rev-parse", "HEAD"],
                             capture_output=True, text=True,
                             timeout=10).stdout.strip()
    except Exception:
        rev = ""
    payload = {
        "generated": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "git_rev": rev,
        "platform": platform,
        "seed": SEED,
        "wall_seconds": round(wall, 1),
        "command": ARGS,
        "aurocs": {k: float(v) for k, v in sorted(aurocs.items())},
        "checks": checks,
    }
    with open(os.path.join(HERE, "scale.json"), "w") as f:
        json.dump(payload, f, indent=2)

    lines = [
        "# Scale evidence — BASELINE config 3 (100k articles, 50k features)",
        "",
        f"Generated {payload['generated']} on platform `{platform}`, seed "
        f"{SEED}, **{wall:.0f}s end to end** on one chip.",
        "",
        "Reproduce: `python evidence/scale.py"
        + (" --cpu" if "--cpu" in argv else "") + "`.",
        "",
        "Pipeline: 105k synthetic docs -> CountVectorizer (50k features) -> "
        "DAE with batch_hard mining (10k-row batches, sparse-ingest feed, "
        "bf16) -> 2500-dim codes -> exact streaming AUROC over all 10^10 "
        "train pairs + validate pairs (histogram figures included). The "
        "reference cannot run this configuration: its eval needs six "
        "[100k, 100k] float32 matrices (240 GB) and its full-set validation "
        "feed OOMs at ~1k rows under mining.",
        "",
        "| metric | value |",
        "|---|---|",
    ]
    for k, v in payload["aurocs"].items():
        lines.append(f"| {k} | {v:.4f} |")
    lines += [
        "",
        "The at-chance Story cells are expected, not a failure: this run's "
        "batch_hard mining is keyed on Category alone, so the embedding "
        "carries no Story signal by construction — the bounded check below "
        "asserts those cells stay inside the chance band instead of leaving "
        "them unexplained.",
    ]
    lines += ["", "## Checks", ""]
    for name, c in checks.items():
        lines.append(f"- **{'PASS' if c['pass'] else 'FAIL'}** {name}: "
                     f"{c['detail']}")
    lines.append("")
    with open(os.path.join(HERE, "SCALE.md"), "w") as f:
        f.write("\n".join(lines))

    n_fail = sum(not c["pass"] for c in checks.values())
    print(f"scale evidence: {len(checks) - n_fail}/{len(checks)} checks passed")
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
