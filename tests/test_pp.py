"""Pipeline-parallel stacked-DAE tower (parallel/pp.py) vs the single-device
layer composition, on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dae_rnn_news_recommendation_tpu.models.dae_core import encode as dae_encode
from dae_rnn_news_recommendation_tpu.models.stacked import StackedDenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.parallel import (
    pipeline_stack_encode, stack_tower_params)


@pytest.fixture
def fitted(rng):
    X = (rng.uniform(size=(48, 30)) < 0.2).astype(np.float32)
    sdae = StackedDenoisingAutoencoder([10, 10, 10, 10, 10], num_epochs=1,
                                       batch_size=24, seed=0)
    sdae.fit(X)
    inp, tower, act = stack_tower_params(sdae)
    x0 = jnp.asarray(dae_encode(inp, jnp.asarray(X), sdae.configs[0]))
    return sdae, X, x0, tower, act


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("stage",))


@pytest.mark.parametrize("microbatches", [1, 2, 8])
def test_pp_matches_layer_composition(fitted, microbatches):
    sdae, X, x0, tower, act = fitted
    ref = sdae.encode(X)
    got = pipeline_stack_encode(tower, x0, _mesh(4), act=act,
                                microbatches=microbatches)
    np.testing.assert_allclose(ref, np.asarray(got), atol=1e-5)


def test_pp_is_differentiable(fitted):
    """The tower trains through the pipeline: grads match the serial composition."""
    sdae, X, x0, tower, act = fitted
    mesh = _mesh(4)

    def loss_pp(tw):
        return jnp.mean(pipeline_stack_encode(tw, x0, mesh,
                                              act=act,
                                              microbatches=2) ** 2)

    def loss_serial(tw):
        h = x0
        for l in range(tw["W"].shape[0]):
            h = jnp.tanh(h @ tw["W"][l] + tw["bh"][l]) - jnp.tanh(tw["bh"][l])
        return jnp.mean(h ** 2)

    np.testing.assert_allclose(float(loss_pp(tower)), float(loss_serial(tower)),
                               rtol=1e-6)
    g_pp = jax.grad(loss_pp)(tower)
    g_s = jax.grad(loss_serial)(tower)
    for k in g_s:
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_s[k]),
                                   atol=1e-5, err_msg=k)


def test_pp_shape_validation(fitted, rng):
    sdae, X, x0, tower, act = fitted
    with pytest.raises(AssertionError):  # 4 layers on an 8-device axis
        pipeline_stack_encode(tower, x0, _mesh(8), act=act)
    uneven = StackedDenoisingAutoencoder([12, 8], num_epochs=0, batch_size=24)
    uneven.fit((rng.uniform(size=(24, 30)) < 0.2).astype(np.float32))
    with pytest.raises(AssertionError, match="equal-width"):
        stack_tower_params(uneven)


def test_single_layer_stack_rejected(rng):
    single = StackedDenoisingAutoencoder([10], num_epochs=0, batch_size=24)
    single.fit((rng.uniform(size=(24, 30)) < 0.2).astype(np.float32))
    with pytest.raises(AssertionError, match="at least 2 layers"):
        stack_tower_params(single)
