"""Two-process jax.distributed smoke test: initialize_multihost must assemble a
global runtime (jax.devices() spanning both processes) and XLA collectives must
work over the combined mesh — the CPU stand-in for the multi-host TPU story
(SURVEY §5.8; the reference has no distributed backend at all).

Each worker is a real OS process with its own JAX runtime (2 virtual CPU
devices), a gloo collectives backend, and a gRPC coordinator on localhost.
Skipped when the sandbox forbids sockets or the gloo backend is absent.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    pid, port, repo = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from dae_rnn_news_recommendation_tpu.parallel import (
        get_mesh, initialize_multihost)

    i, n = initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                                num_processes=2, process_id=pid)
    assert (i, n) == (pid, 2), (i, n)
    assert len(jax.devices()) == 4, jax.devices()
    assert len(jax.local_devices()) == 2

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # global mesh over all 4 devices; each process contributes its local rows,
    # then a jitted global sum forces a cross-process psum
    mesh = get_mesh(4)
    sharding = NamedSharding(mesh, P("data"))
    local = np.full((2, 3), float(pid + 1), np.float32)  # 2 rows per process
    garr = jax.make_array_from_process_local_data(sharding, local, (4, 3))
    total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
    assert float(total) == 2 * 3 * 1.0 + 2 * 3 * 2.0, float(total)

    # full distributed train step over the combined mesh: each process feeds its
    # local rows (parallel/feed.py), global mining must equal the single-device
    # oracle on the concatenated batch
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
    from dae_rnn_news_recommendation_tpu.parallel import (
        make_parallel_train_step, put_replicated, put_sharded_batch)
    from dae_rnn_news_recommendation_tpu.train import make_optimizer
    from dae_rnn_news_recommendation_tpu.train.step import make_train_step

    b, f, d = 16, 32, 8  # 4 rows per process slice of the global batch
    config = DAEConfig(n_features=f, n_components=d, enc_act_func="tanh",
                       dec_act_func="none", loss_func="mean_squared",
                       corr_type="none", corr_frac=0.0,
                       triplet_strategy="batch_all", alpha=1.0,
                       matmul_precision="highest")
    rng = np.random.default_rng(0)  # same stream on both processes
    full = {
        "x": (rng.uniform(size=(b, f)) < 0.3).astype(np.float32),
        "labels": rng.integers(0, 4, b).astype(np.int32),
        "row_valid": np.ones(b, np.float32),
    }
    params = init_params(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer("ada_grad", 0.1)
    opt_state = optimizer.init(params)

    lo, hi = pid * (b // 2), (pid + 1) * (b // 2)  # this process's rows
    gbatch = put_sharded_batch({k: v[lo:hi] for k, v in full.items()}, mesh)
    gparams = put_replicated(params, mesh)
    gopt = put_replicated(jax.tree_util.tree_map(np.asarray, opt_state), mesh)

    step = make_parallel_train_step(config, optimizer, mesh,
                                    mining_scope="global", donate=False)
    _, _, metrics = step(gparams, gopt, jax.random.PRNGKey(7), gbatch)
    dist_cost = float(metrics["cost"])

    single = make_train_step(config, optimizer, donate=False)
    _, _, m1 = single(params, opt_state, jax.random.PRNGKey(7), full)
    np.testing.assert_allclose(dist_cost, float(m1["cost"]), rtol=1e-5)

    # expert-parallel MoE step across processes: one expert per device, the
    # all_to_all dispatch/return and mining all_gathers cross the process
    # boundary over gloo; ample capacity -> must equal the dense oracle
    from dae_rnn_news_recommendation_tpu.parallel.ep import (
        make_moe_train_step, moe_init_params, moe_loss_and_metrics)

    ep_mesh = get_mesh(4, axis_name="expert")
    moe_params = moe_init_params(jax.random.PRNGKey(1), config, 4)
    moe_opt = optimizer.init(moe_params)
    gmoe_params = put_replicated(moe_params, ep_mesh)
    gmoe_opt = put_replicated(jax.tree_util.tree_map(np.asarray, moe_opt),
                              ep_mesh)
    ep_batch = put_sharded_batch({k: v[lo:hi] for k, v in full.items()},
                                 ep_mesh, data_axis="expert")
    ep_step = make_moe_train_step(config, optimizer, ep_mesh,
                                  capacity_factor=4.0, donate=False)
    _, _, ep_metrics = ep_step(gmoe_params, gmoe_opt, jax.random.PRNGKey(9),
                               ep_batch)
    assert float(ep_metrics["routed_fraction"]) == 1.0
    cost0, _ = moe_loss_and_metrics(moe_params, full, jax.random.PRNGKey(9),
                                    config)
    np.testing.assert_allclose(float(ep_metrics["cost"]), float(cost0),
                               rtol=1e-5)
    print("MULTIHOST_OK", pid, flush=True)
""")


# end-to-end pod path (VERDICT r2 item 7): a 2-process DenoisingAutoencoder
# .fit() — each process batches its LOCAL rows, the estimator stitches them
# into global arrays via parallel/feed.py, trains collectively, checkpoints
# with orbax per process, restores ACROSS processes, and resumes training
_FIT_WORKER = textwrap.dedent("""
    import os, sys
    pid, port, repo, workdir, nproc = (int(sys.argv[1]), sys.argv[2],
                                       sys.argv[3], sys.argv[4],
                                       int(sys.argv[5]))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from dae_rnn_news_recommendation_tpu.parallel import (
        get_mesh, initialize_multihost)

    initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)
    n_dev = 2 * nproc
    assert len(jax.devices()) == n_dev
    os.chdir(workdir)

    import numpy as np
    from jax.experimental import multihost_utils

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
        latest_checkpoint, load_checkpoint)

    b, f = 32 * (nproc // 2), 20  # global rows, split evenly by process
    rng = np.random.default_rng(0)  # same stream on every process
    X = (rng.uniform(size=(b, f)) < 0.3).astype(np.float32)
    y = rng.integers(0, 4, b).astype(np.int32)
    lo, hi = pid * (b // nproc), (pid + 1) * (b // nproc)

    def make_model(num_epochs):
        # ONE shared artifact tree: orbax checkpoints are saved collectively
        # (every process calls save on the same dir; the primary finalizes),
        # process 0 owns the shared logs, others log under proc{i}/
        return DenoisingAutoencoder(
            model_name="mh", main_dir="mh/", results_root="results_shared",
            num_epochs=num_epochs, batch_size=8, opt="ada_grad",
            learning_rate=0.1, corr_type="masking", corr_frac=0.3,
            triplet_strategy="batch_all", alpha=1.0, seed=0,
            verbose=False, verbose_step=10, checkpoint_every=1,
            mesh=get_mesh(n_dev), mining_scope="global")

    model = make_model(num_epochs=2)
    model.fit(X[lo:hi], train_set_label=y[lo:hi])
    own = jax.tree_util.tree_map(np.asarray, model.params)

    # both processes' replicated params must agree bit-for-bit: training was
    # one collective computation
    gathered = multihost_utils.process_allgather(own["W"])
    for g in gathered[1:]:
        np.testing.assert_array_equal(gathered[0], g)

    # every process restores the collectively written checkpoint and must
    # find the identical replicated state
    ckpt_dir = os.path.join("results_shared", "dae", "mh", "models", "mh")
    path, step = latest_checkpoint(ckpt_dir)
    assert path is not None and step == 2, (ckpt_dir, path, step)
    like = {"params": own,
            "opt_state": jax.tree_util.tree_map(np.asarray, model.opt_state),
            "epoch": np.asarray(0)}
    restored = load_checkpoint(path, like)
    np.testing.assert_allclose(restored["params"]["W"], own["W"], atol=0)
    assert int(restored["epoch"]) == 2

    # resume through the same multi-process feed: epoch counter continues
    model2 = make_model(num_epochs=1)
    model2.fit(X[lo:hi], train_set_label=y[lo:hi],
               restore_previous_model=True)
    assert model2._epoch0 == 2, model2._epoch0
    _, step2 = latest_checkpoint(ckpt_dir)
    assert step2 == 3, step2
    print("MULTIHOST_FIT_OK", pid, flush=True)
""")


# unseeded (seed=-1) pod runs: resolve_seed draws per-process OS entropy, so
# without the broadcast in _root_key every process would init different params
# and put_replicated would assemble a silently inconsistent "replicated" array
_UNSEEDED_WORKER = textwrap.dedent("""
    import os, sys
    pid, port, repo, workdir = (int(sys.argv[1]), sys.argv[2], sys.argv[3],
                                sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from dae_rnn_news_recommendation_tpu.parallel import (
        get_mesh, initialize_multihost)

    initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)
    os.chdir(workdir)

    import numpy as np
    from jax.experimental import multihost_utils

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder

    rng = np.random.default_rng(100 + pid)  # deliberately DIFFERENT data rng
    X = (rng.uniform(size=(8, 12)) < 0.3).astype(np.float32)
    model = DenoisingAutoencoder(
        model_name="mh_unseeded", main_dir="mh_unseeded/",
        results_root="results_shared", num_epochs=1, batch_size=8,
        opt="ada_grad", learning_rate=0.1, corr_type="masking", corr_frac=0.3,
        triplet_strategy="none", seed=-1, verbose=False, checkpoint_every=0,
        mesh=get_mesh(4), mining_scope="global")
    model.fit(X)

    # every process must have adopted process 0's resolved seed...
    seeds = multihost_utils.process_allgather(
        np.asarray(model._resolved_seed, np.uint32))
    assert (seeds == seeds[0]).all(), seeds
    # ...and the trained replicated params must agree bit-for-bit
    gathered = multihost_utils.process_allgather(
        np.asarray(model.params["W"]))
    for g in gathered[1:]:
        np.testing.assert_array_equal(gathered[0], g)
    print("MULTIHOST_UNSEEDED_OK", pid, flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path, worker_src, ok_marker, nproc, extra_argv=(),
                 timeout=240):
    """Launch nproc copies of `worker_src` (argv: pid, port, repo, *extra_argv),
    join them, skip on missing sockets/gloo, and assert every worker printed
    `ok_marker <pid>`. Returns the joined output."""
    try:
        port = _free_port()
    except OSError:
        pytest.skip("sandbox forbids sockets")
    worker = tmp_path / "worker.py"
    worker.write_text(worker_src)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen([sys.executable, str(worker), str(pid), str(port),
                          repo, *map(str, extra_argv)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out; partial output: "
                    + " | ".join(outs))

    joined = "\n".join(outs)
    if any(p.returncode != 0 for p in procs) and (
            "gloo" in joined.lower() and "unavailable" in joined.lower()):
        pytest.skip("gloo collectives backend unavailable")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    for pid in range(nproc):
        assert f"{ok_marker} {pid}" in joined
    return joined


def test_two_process_distributed_psum(tmp_path):
    _run_workers(tmp_path, _WORKER, "MULTIHOST_OK", nproc=2, timeout=180)


def _run_fit_workers(tmp_path, nproc, timeout=420):
    workdir = tmp_path / "run"
    workdir.mkdir()
    _run_workers(tmp_path, _FIT_WORKER, "MULTIHOST_FIT_OK", nproc=nproc,
                 extra_argv=(workdir, nproc), timeout=timeout)


def test_two_process_end_to_end_fit(tmp_path):
    """The exact pod path: fit() with process-local feeding, collective
    training, shared collective orbax checkpoints, cross-process restore,
    resume."""
    _run_fit_workers(tmp_path, nproc=2)


def test_two_process_unseeded_fit_agrees(tmp_path):
    """seed=-1 on the pod path: _root_key must broadcast process 0's resolved
    seed so replicated init/corruption PRNG streams are identical (ADVICE r3
    medium)."""
    workdir = tmp_path / "run"
    workdir.mkdir()
    _run_workers(tmp_path, _UNSEEDED_WORKER, "MULTIHOST_UNSEEDED_OK", nproc=2,
                 extra_argv=(workdir,))


def test_four_process_end_to_end_fit(tmp_path):
    """Same pod path at 4 processes x 2 devices: multiple NON-primary hosts
    participate in the collective checkpoint (the orbax primary-commit
    semantics that made per-process dirs silently uncommitted)."""
    _run_fit_workers(tmp_path, nproc=4)
