"""jaxcheck wiring into tier-1.

Three contracts:
  * seeded  — every planted violation in tests/fixtures/jaxcheck/ is found
              (and nothing else: the fixtures' clean twins must stay clean);
  * self-clean — the repo's own contract set (package + bench.py + evidence/)
              has zero unsuppressed findings, and every suppression that
              silences something carries a reason;
  * runtime — compile_guard counts real XLA backend compiles, and the
              pipelined-feed bucketing path compiles at most len(buckets)
              step variants per epoch (PR 1's shape-bucket invariant).
"""

import json
import os
import re

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from dae_rnn_news_recommendation_tpu.analysis import (
    RULES, analyze_file, analyze_paths, default_targets,
    CompileBudgetExceeded, compile_guard)
from dae_rnn_news_recommendation_tpu.analysis.__main__ import main as cli_main

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "jaxcheck")
_PLANTED_RE = re.compile(r"#\s*planted:\s*([A-Z0-9,\s]+)")


def planted_markers(path):
    """(line, rule) pairs declared by `# planted: R1[,R5]` comments."""
    pairs = set()
    with open(path, encoding="utf-8") as f:
        for lineno, text in enumerate(f, start=1):
            m = _PLANTED_RE.search(text)
            if m:
                for rule_id in m.group(1).split(","):
                    pairs.add((lineno, rule_id.strip()))
    return pairs


def fixture_files():
    return sorted(p for p in os.listdir(FIXTURE_DIR) if p.endswith(".py"))


# ------------------------------------------------------------------ seeded

def test_every_rule_has_a_fixture():
    planted = set()
    for name in fixture_files():
        planted |= {r for _, r in
                    planted_markers(os.path.join(FIXTURE_DIR, name))}
    assert {"R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9",
            "R10", "R11", "R12", "R13", "R14",
            "C1", "C2", "C3", "C4", "C5",
            "S1", "S2", "S3", "S4", "S5"} <= planted


@pytest.mark.parametrize("name", fixture_files())
def test_planted_violations_detected(name):
    path = os.path.join(FIXTURE_DIR, name)
    planted = planted_markers(path)
    findings, _ = analyze_file(path, root=FIXTURE_DIR)
    found = {(f.line, f.rule) for f in findings}
    missed = planted - found
    assert not missed, f"planted violations not detected: {sorted(missed)}"


@pytest.mark.parametrize("name", fixture_files())
def test_no_unplanted_findings(name):
    """The fixtures' clean twins (fenced timers, rebound donations, split
    keys, static_argnums) must NOT be flagged — false-positive regression."""
    path = os.path.join(FIXTURE_DIR, name)
    planted = planted_markers(path)
    findings, _ = analyze_file(path, root=FIXTURE_DIR)
    extra = {(f.line, f.rule) for f in findings
             if f.rule in RULES} - planted
    assert not extra, f"unplanted findings (false positives): {sorted(extra)}"


# ------------------------------------------------------------- suppressions

def test_reasoned_suppression_silences():
    path = os.path.join(FIXTURE_DIR, "suppressed_ok.py")
    findings, suppressed = analyze_file(path, root=FIXTURE_DIR)
    assert findings == []
    assert [s.rule for s in suppressed] == ["R5"]
    assert suppressed[0].suppress_reason  # the reason travels with it


def test_reasonless_suppression_is_a_finding():
    path = os.path.join(FIXTURE_DIR, "suppressed_noreason.py")
    findings, _ = analyze_file(path, root=FIXTURE_DIR)
    rules = [f.rule for f in findings]
    assert "SUP" in rules          # the bad disable itself
    assert "R5" in rules           # and it did NOT silence the violation


def test_sup_cannot_be_suppressed(tmp_path):
    p = tmp_path / "laundering.py"
    p.write_text("import jax\n"
                 "def f(key):\n"
                 "    a = jax.random.normal(key, (2,))\n"
                 "    # jaxcheck: disable=R5,SUP\n"
                 "    b = jax.random.normal(key, (2,))\n"
                 "    return a + b\n")
    findings, _ = analyze_file(str(p), root=str(tmp_path))
    assert any(f.rule == "SUP" for f in findings)


# -------------------------------------------------------------- self-clean

def test_repo_is_self_clean():
    """Zero unsuppressed findings on the package + bench.py + evidence/,
    every suppression reasoned — the acceptance criterion, as a test."""
    root, targets = default_targets()
    findings, suppressed, n_files = analyze_paths(targets, root=root)
    assert n_files > 30  # the walk actually covered the tree
    assert findings == [], "\n".join(f.render() for f in findings)
    assert all(s.suppress_reason for s in suppressed)


# --------------------------------------------------------------------- CLI

def test_cli_json_mode(capsys):
    rc = cli_main(["--json", os.path.join(FIXTURE_DIR, "r5_key_reuse.py")])
    out = capsys.readouterr().out
    report = json.loads(out)
    assert rc == 1
    assert report["files_analyzed"] == 1
    assert {f["rule"] for f in report["findings"]} == {"R5"}
    assert all(set(f) >= {"rule", "path", "line", "message"}
               for f in report["findings"])


def test_cli_clean_exit_zero(capsys):
    rc = cli_main([os.path.join(FIXTURE_DIR, "suppressed_ok.py")])
    captured = capsys.readouterr()
    assert rc == 0
    assert "clean" in captured.err


# ------------------------------------------------------------ compile_guard

def test_compile_guard_counts_and_raises():
    @jax.jit
    def f(x):
        return x * 2.0 + 1.0

    with pytest.raises(CompileBudgetExceeded) as e:
        with compile_guard(max_compiles=1):
            f(np.ones(4, np.float32))   # shape (4,): compile 1
            f(np.ones(8, np.float32))   # shape (8,): compile 2 — over budget
    assert "2 XLA backend compiles" in str(e.value)

    # both shapes now cached: a fresh guard over the same calls sees zero
    with compile_guard(max_compiles=0) as guard:
        f(np.ones(4, np.float32))
        f(np.ones(8, np.float32))
    assert guard.count == 0


def test_pipelined_feed_compiles_at_most_bucket_variants():
    """Satellite regression for PR 1's invariant: with bucket padding on, a
    full epoch (ragged tail included) compiles at most len(buckets) step
    variants — the ragged tail pads up instead of tracing its own program.
    A second epoch compiles nothing."""
    from dae_rnn_news_recommendation_tpu.data.batcher import (
        SparseIngestBatcher)
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
    from dae_rnn_news_recommendation_tpu.train import make_optimizer
    from dae_rnn_news_recommendation_tpu.train.pipeline import (
        PipelinedFeed, bucket_sizes)
    from dae_rnn_news_recommendation_tpu.train.step import make_train_step

    config = DAEConfig(n_features=24, n_components=4, enc_act_func="tanh",
                       dec_act_func="none", loss_func="mean_squared",
                       corr_type="masking", corr_frac=0.3,
                       triplet_strategy="none")
    optimizer = make_optimizer("ada_grad", 0.1)
    params = init_params(jax.random.PRNGKey(0), config)
    opt_state = optimizer.init(params)
    step = make_train_step(config, optimizer, donate_batch=True)
    buckets = bucket_sizes(8, n_buckets=2, floor=4)  # (4, 8)

    rng = np.random.default_rng(0)
    x = sp.csr_matrix((rng.uniform(size=(33, 24)) < 0.3).astype(np.float32))
    key = jax.random.PRNGKey(1)
    key, _ = jax.random.split(key)  # pre-warm split's own compile

    def one_epoch(params, opt_state, key):
        batcher = SparseIngestBatcher(8, shuffle=False)
        feed = PipelinedFeed(batcher.epoch(x), depth=2, buckets=buckets)
        for batch in feed:  # 33 rows @ 8: shapes 8,8,8,8 then 1 -> padded 4
            key, sub = jax.random.split(key)
            params, opt_state, metrics = step(params, opt_state, sub, batch)
        jax.block_until_ready(metrics["cost"])
        return params, opt_state, key

    with compile_guard(max_compiles=len(buckets)) as first:
        params, opt_state, key = one_epoch(params, opt_state, key)
    assert 1 <= first.count <= len(buckets)

    with compile_guard(max_compiles=0) as second:
        params, opt_state, key = one_epoch(params, opt_state, key)
    assert second.count == 0
