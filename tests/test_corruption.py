"""Tests for corruption ops (reference test_utils.py:108-131 style: statistical checks
for masking, exact checks where deterministic)."""

import numpy as np
import pytest
import scipy.sparse as sp
import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import corruption as C


@pytest.mark.parametrize("v", [0.0, 0.3, 1.0])
def test_masking_noise_ratio(v, rng):
    x = jnp.asarray(rng.uniform(0.5, 1.0, size=(200, 300)).astype(np.float32))
    out = np.asarray(C.masking_noise(jax.random.PRNGKey(0), x, v))
    # surviving-nonzero ratio ~ 1 - v (reference test_utils.py:108-125, tol 1e-2)
    ratio = (out != 0).sum() / x.size
    assert abs(ratio - (1 - v)) < 2e-2
    # no new nonzeros, survivors unchanged
    mask = out != 0
    np.testing.assert_array_equal(out[mask], np.asarray(x)[mask])


def test_masking_noise_keeps_zeros(rng):
    x = np.zeros((10, 20), np.float32)
    x[0, 0] = 5.0
    out = np.asarray(C.masking_noise(jax.random.PRNGKey(1), jnp.asarray(x), 0.0))
    np.testing.assert_array_equal(out, x)


def test_salt_and_pepper_noise(rng):
    x = rng.uniform(0.2, 0.8, size=(50, 40)).astype(np.float32)
    mn, mx = x.min(), x.max()
    out = np.asarray(
        C.salt_and_pepper_noise(jax.random.PRNGKey(2), jnp.asarray(x), n_corrupt=8)
    )
    changed = out != x
    # every changed element is at the min or max
    assert changed.sum() > 0
    vals = out[changed]
    assert np.all((vals == mn) | (vals == mx))
    # at most n_corrupt changes per row (with replacement can repeat)
    assert (changed.sum(axis=1) <= 8).all()


def test_salt_and_pepper_zero_corrupt(rng):
    x = jnp.asarray(rng.uniform(size=(5, 6)).astype(np.float32))
    out = C.salt_and_pepper_noise(jax.random.PRNGKey(3), x, n_corrupt=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_decay_noise(rng):
    x = rng.uniform(size=(5, 6)).astype(np.float32)
    out = np.asarray(C.decay_noise(jnp.asarray(x), 0.3))
    np.testing.assert_allclose(out, x * 0.7, rtol=1e-6)


@pytest.mark.parametrize("corr_type", ["masking", "salt_and_pepper", "decay", "none"])
def test_corrupt_dispatch(corr_type, rng):
    x = jnp.asarray(rng.uniform(size=(8, 10)).astype(np.float32))
    out = C.corrupt(jax.random.PRNGKey(4), x, corr_type, 0.3)
    assert out.shape == x.shape


def test_corrupt_dispatch_unknown():
    with pytest.raises(ValueError):
        C.corrupt(jax.random.PRNGKey(0), jnp.zeros((2, 2)), "bogus", 0.1)


def test_corrupt_is_jittable(rng):
    x = jnp.asarray(rng.uniform(size=(8, 10)).astype(np.float32))
    f = jax.jit(lambda k, x: C.corrupt(k, x, "masking", 0.3))
    out = f(jax.random.PRNGKey(5), x)
    assert out.shape == x.shape


@pytest.mark.parametrize("v", [0.0, 0.3, 1.0])
def test_masking_noise_sparse_host(v, rng):
    x = sp.random(100, 200, density=0.1, format="csr", random_state=0)
    out = C.masking_noise_sparse_host(rng, x, v)
    assert sp.issparse(out)
    ratio = out.nnz / max(x.nnz, 1)
    assert abs(ratio - (1 - v)) < 5e-2
    # survivors are a subset with unchanged values
    d_in = x.todense()
    d_out = out.todense()
    mask = np.asarray(d_out != 0)
    np.testing.assert_array_equal(np.asarray(d_out)[mask], np.asarray(d_in)[mask])
