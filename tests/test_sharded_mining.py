"""Anchor-partitioned global mining (parallel/mining.py) vs the square oracle
(ops/triplet.py) on the virtual 8-device mesh: same loss, same per-row
data_weight, same fraction/count/extras — while each device only ever holds a
[B_local, B, B] (batch_all) or [B_local, B] (batch_hard) anchor slice."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dae_rnn_news_recommendation_tpu.ops.triplet import (
    batch_all_triplet_loss, batch_hard_triplet_loss)
from dae_rnn_news_recommendation_tpu.parallel import get_mesh
from dae_rnn_news_recommendation_tpu.parallel.mesh import _shard_map
from dae_rnn_news_recommendation_tpu.parallel.mining import (
    sharded_batch_all_triplet_loss, sharded_batch_hard_triplet_loss)

B, D, P_DEV = 64, 12, 8


def _data(n_classes, pad_tail=0):
    rng = np.random.default_rng(3)
    enc = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, n_classes, B), jnp.int32)
    valid = np.ones(B, np.float32)
    if pad_tail:
        valid[-pad_tail:] = 0.0
    return enc, labels, jnp.asarray(valid)


def _run_sharded(fn, labels, enc, valid, **kw):
    """Drive the mining fn inside shard_map: codes row-sharded, then gathered
    inside (the caller layout ep.py uses)."""
    mesh = get_mesh(P_DEV, axis_name="x")

    def local(enc_local, labels_g, valid_g):
        enc_g = jax.lax.all_gather(enc_local, "x", tiled=True)
        loss, dw, frac, num, extras = fn(labels_g, enc_local, enc_g, "x",
                                         row_valid=valid_g, **kw)
        return loss, dw, frac, num, extras

    return _shard_map(
        local, mesh=mesh, in_specs=(P("x"), P(), P()),
        out_specs=(P(), P("x"), P(), P(), P()),
    )(enc, labels, valid)


@pytest.mark.parametrize("pos_only", [False, True])
@pytest.mark.parametrize("n_classes,pad", [(4, 0), (6, 5), (1, 0)])
def test_sharded_batch_all_matches_oracle(pos_only, n_classes, pad):
    enc, labels, valid = _data(n_classes, pad)
    o_loss, o_dw, o_frac, o_num, _ = batch_all_triplet_loss(
        labels, enc, pos_triplets_only=pos_only, row_valid=valid)
    s_loss, s_dw, s_frac, s_num, _ = _run_sharded(
        sharded_batch_all_triplet_loss, labels, enc, valid,
        pos_triplets_only=pos_only)
    np.testing.assert_allclose(float(s_loss), float(o_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s_dw), np.asarray(o_dw),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(s_frac), float(o_frac), rtol=1e-5)
    np.testing.assert_allclose(float(s_num), float(o_num), rtol=1e-6)


@pytest.mark.parametrize("n_classes,pad", [(4, 0), (6, 5), (1, 0)])
def test_sharded_batch_hard_matches_oracle(n_classes, pad):
    enc, labels, valid = _data(n_classes, pad)
    o_loss, o_dw, o_frac, o_num, o_ex = batch_hard_triplet_loss(
        labels, enc, row_valid=valid)
    s_loss, s_dw, s_frac, s_num, s_ex = _run_sharded(
        sharded_batch_hard_triplet_loss, labels, enc, valid)
    np.testing.assert_allclose(float(s_loss), float(o_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s_dw), np.asarray(o_dw),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(s_frac), float(o_frac), rtol=1e-5)
    np.testing.assert_allclose(float(s_num), float(o_num), rtol=1e-6)
    for k in o_ex:
        np.testing.assert_allclose(float(s_ex[k]), float(o_ex[k]), rtol=1e-5)


def test_sharded_mining_differentiable():
    """Gradient of the sharded loss w.r.t. the codes equals the oracle's."""
    enc, labels, valid = _data(4)

    def oracle_loss(e):
        return batch_all_triplet_loss(labels, e, row_valid=valid)[0]

    def sharded_loss(e):
        mesh = get_mesh(P_DEV, axis_name="x")

        def local(enc_local):
            enc_g = jax.lax.all_gather(enc_local, "x", tiled=True)
            return sharded_batch_all_triplet_loss(
                labels, enc_local, enc_g, "x", row_valid=valid)[0]

        return _shard_map(local, mesh=mesh, in_specs=P("x"),
                             out_specs=P())(e)

    g_o = jax.grad(oracle_loss)(enc)
    g_s = jax.grad(sharded_loss)(enc)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_o),
                               rtol=1e-4, atol=1e-6)
