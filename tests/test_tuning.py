"""Measured Pallas autotuner contracts (ISSUE 20): legality pruning before
any compile, bitwise-parity-gated admission (re-verified independently here,
not just trusted from the tuner's own bookkeeping), schema-additive
ProfileDB persistence, resolve() provenance and fallbacks, the CLI, and the
zero-post-warm-recompile regression with tuning enabled.

Everything runs on the CPU Pallas interpreter (interpret=True), which is a
parity instrument, not a timing instrument — the admission logic under test
is identical on hardware; only the recorded milliseconds are synthetic.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu import tuning
from dae_rnn_news_recommendation_tpu.analysis.runtime import compile_guard
from dae_rnn_news_recommendation_tpu.ops import tile_defaults as td
from dae_rnn_news_recommendation_tpu.telemetry.profile_db import (ProfileDB,
                                                                  row_key)
from dae_rnn_news_recommendation_tpu.tuning import space
from dae_rnn_news_recommendation_tpu.tuning import search as tsearch
from dae_rnn_news_recommendation_tpu.tuning.search import tune_op

TOPK_SHAPE = (8, 256, 8, 3)          # (B, N, D, k) — tiny but panel-real
BATCH_HARD_SHAPE = (32, 8)
IVF_SHAPE = (4, 8, 64, 8, 3, 2)      # (B, C, cap, D, k, probes)


@pytest.fixture(autouse=True)
def _isolated_tuning(tmp_path):
    """Every test starts from a fresh resolution state pointed at an empty
    DB path — never the committed repo ProfileDB — and leaves no state for
    the next test file."""
    tuning.reset()
    tuning.configure(enabled=True, db_path=str(tmp_path / "tuning_db.json"))
    yield
    tuning.reset()


# -------------------------------------------------------------- candidates

def test_candidate_space_prunes_before_any_compile():
    """The static pruner rejects misaligned and VMEM-overflowing configs up
    front (stats say how many), always emits the hand-picked default FIRST,
    and never emits a duplicate or an illegal survivor."""
    stats = {}
    cands = space.candidates("topk_fused", (64, 8192, 512, 10), "float32",
                             stats=stats)
    assert cands[0] == td.default_config("topk_fused", (64, 8192, 512, 10))
    assert len({tuple(sorted(c.items())) for c in cands}) == len(cands)
    for c in cands:
        assert space.validate("topk_fused", c, (64, 8192, 512, 10),
                              "float32")
        assert space.vmem_footprint("topk_fused", c, (64, 8192, 512, 10),
                                    "float32") <= space.VMEM_BUDGET_BYTES
    assert stats["n_raw"] == (len(cands) + stats["n_illegal"]
                              + stats["n_vmem"])


def test_vmem_budget_actually_prunes():
    """A huge key must lose candidates to the VMEM model — if nothing is
    ever pruned the footprint model is dead code."""
    stats = {}
    space.candidates("topk_fused", (256, 65536, 2048, 10), "float32",
                     stats=stats)
    assert stats["n_vmem"] > 0


# ---------------------------------------------- parity-gated admission

def _reverify(op, shape, dtype, row, *, seed=0):
    """Re-run every candidate the tuner ADMITTED against the rebuilt
    problem's oracle and the default config's outputs — independent
    re-verification of the acceptance bar (admitted == output-identical)."""
    prob = tsearch._PROBLEMS[op](tuple(shape), dtype, seed, True)
    default_out = None
    for rep in row["tuner"]["candidates"]:
        if not rep["admitted"]:
            assert rep["reject"], rep
            continue
        out = jax.device_get(prob["make_fn"](rep["config"])())
        if default_out is None:           # candidate 0 is always the default
            default_out = out
        assert prob["compare"](out, default_out), rep["config"]
        if prob["oracle"] is not None:
            assert prob["compare"](out, prob["oracle"]), rep["config"]


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_topk_admitted_candidates_are_bitwise_exact(dtype):
    row = tune_op("topk_fused", TOPK_SHAPE, dtype, n=2, warmup=1,
                  interpret=True)
    t = row["tuner"]
    assert t["admitted"] and t["parity"] == "exact"
    assert t["candidates"][0]["admitted"]          # the default always races
    assert t["candidates"][0]["config"] == t["default_config"]
    assert t["speedup_vs_default"] >= 1.0          # winner = measured min
    assert space.validate("topk_fused", row["config"], TOPK_SHAPE, dtype)
    _reverify("topk_fused", TOPK_SHAPE, dtype, row)


@pytest.mark.parametrize("op,shape,dtype", [
    ("batch_hard", BATCH_HARD_SHAPE, "float32"),
    ("batch_hard", BATCH_HARD_SHAPE, "bfloat16"),
    ("ivf_topk", IVF_SHAPE, "float32"),
    ("ivf_topk", IVF_SHAPE, "int8"),
    ("wire_unpack", (16, 25), "int32"),
])
def test_admitted_candidates_are_output_identical(op, shape, dtype):
    row = tune_op(op, shape, dtype, n=2, warmup=1, interpret=True)
    t = row["tuner"]
    assert t["admitted"]
    assert t["candidates"][0]["admitted"]
    assert t["speedup_vs_default"] >= 1.0
    key_shape = tuple(int(s) for s in row["shape"].split("x"))
    _reverify(op, key_shape, dtype, row)


def test_batch_hard_foreign_blocks_reject_not_admit_wrong():
    """block_rows changes f32 summation order, so a differing block either
    produces the same bytes or is REJECTED on parity — it can never be
    admitted with different outputs (checked via _reverify above; here we
    pin that the race actually tried a non-default block)."""
    row = tune_op("batch_hard", BATCH_HARD_SHAPE, "float32", n=2, warmup=1,
                  interpret=True)
    tried = {rep["config"]["block_rows"]
             for rep in row["tuner"]["candidates"]}
    assert len(tried) > 1, "grid degenerated to the default only"
    for rep in row["tuner"]["candidates"]:
        assert rep["admitted"] or rep["reject"]


def test_masking_interpret_capture_is_refused():
    """The masking kernel's PRNG is stubbed in the interpreter, so an
    off-TPU 'capture' would admit configs on fake bytes — tune_op refuses
    and returns None instead of recording."""
    notes = []
    row = tune_op("masking", (8, 16), "float32", interpret=True,
                  log=notes.append)
    assert row is None
    assert any("masking" in n for n in notes)


def test_wire_unpack_key_shape_is_the_real_wire_layout():
    """The recorded key uses the spec's actual words_per_row (the shape a
    serving unpack resolves under), not the requested synthetic guess."""
    row = tune_op("wire_unpack", (16, 8), "int32", n=2, warmup=1,
                  interpret=True)
    words = int(row["shape"].split("x")[1])
    assert row["shape"].startswith("16x")
    assert words >= 8 and words % 8 == 0


# ------------------------------------------------------------- persistence

def test_db_round_trips_old_rows_unchanged(tmp_path):
    """Schema-additive: a pre-r20 plain measurement row (no config/tuner)
    survives record/save/load byte-identically next to a tuned row, and
    resolve() treats it as a miss, not an error."""
    path = str(tmp_path / "db.json")
    old = {"op": "topk_fused", "shape": "8x256x8x3", "dtype": "float32",
           "device_kind": "cpu", "best_ms": 0.5, "median_ms": 0.6,
           "n": 5, "n_clean": 5}
    db = ProfileDB(path)
    db.record(dict(old))
    db.save()
    row = tune_op("topk_fused", TOPK_SHAPE, "bfloat16", db=ProfileDB(path),
                  n=2, warmup=1, interpret=True)
    reloaded = ProfileDB(path)
    back = reloaded._rows[row_key("topk_fused", "8x256x8x3", "float32",
                                  "cpu")]
    assert back == old
    assert len(reloaded) == 2

    tuning.configure(db_path=path)
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert prov == "default"
    assert cfg == td.default_config("topk_fused", TOPK_SHAPE)
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "bfloat16",
                               device_kind=row["device_kind"])
    assert prov == "tuned" and cfg == row["config"]


def test_corrupt_db_degrades_to_defaults_with_a_warning(tmp_path):
    path = tmp_path / "broken.json"
    path.write_text("{not json")
    tuning.configure(db_path=str(path))
    with pytest.warns(RuntimeWarning, match="fall back to defaults"):
        cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32")
    assert prov == "default"


# ----------------------------------------------------------------- resolve

def _plant_row(path, *, op="topk_fused", shape="8x256x8x3",
               dtype="float32", device_kind="cpu",
               config=None, tuner=None):
    db = ProfileDB(str(path))
    row = {"op": op, "shape": shape, "dtype": dtype,
           "device_kind": device_kind, "best_ms": 0.1,
           "config": config if config is not None
           else {"block": 256, "bq": 8},
           "tuner": tuner if tuner is not None else {"admitted": True}}
    db.record(row)
    db.save()
    return row


def test_resolve_hit_miss_and_resolution_log(tmp_path):
    path = tmp_path / "db.json"
    planted = _plant_row(path)
    tuning.configure(db_path=str(path))
    assert tuning.prime() == 1

    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert (cfg, prov) == (planted["config"], "tuned")
    # memoized: same key resolves from the cache to the identical answer
    assert tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                          device_kind="cpu") == (cfg, prov)
    # miss: a foreign shape falls back to the hand-picked default
    miss_shape = (8, 512, 8, 3)
    cfg2, prov2 = tuning.resolve("topk_fused", miss_shape, "float32",
                                 device_kind="cpu")
    assert prov2 == "default"
    assert cfg2 == td.default_config("topk_fused", miss_shape)

    recs = tuning.resolutions()
    assert [r["provenance"] for r in recs] == ["tuned", "default"]
    man = tuning.resolution_manifest()
    assert man["enabled"] is True
    assert (man["n_tuned"], man["n_default"]) == (1, 1)
    assert man["db_path"] == str(path)


def test_resolve_rejects_stale_and_interpret_rows(tmp_path):
    # an illegal tuned config (fails today's legality laws) degrades to
    # the default instead of dispatching a misaligned tile
    stale = tmp_path / "stale.json"
    _plant_row(stale, config={"block": 100, "bq": 8})
    tuning.configure(db_path=str(stale))
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert prov == "default"

    # an interpreter capture must never drive a real TPU dispatch...
    interp = tmp_path / "interp.json"
    _plant_row(interp, device_kind="TPU v4",
               tuner={"admitted": True, "interpret": True})
    tuning.configure(db_path=str(interp))
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="TPU v4")
    assert prov == "default"
    # ...but the same row is an honest hit on the host kind it ran on
    host = tmp_path / "host.json"
    _plant_row(host, device_kind="cpu",
               tuner={"admitted": True, "interpret": True})
    tuning.configure(db_path=str(host))
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert prov == "tuned"


def test_tuning_off_switch_forces_defaults(tmp_path):
    path = tmp_path / "db.json"
    planted = _plant_row(path)
    tuning.configure(enabled=False, db_path=str(path))
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert prov == "default"
    assert tuning.resolution_manifest()["enabled"] is False
    tuning.configure(enabled=True)
    cfg, prov = tuning.resolve("topk_fused", TOPK_SHAPE, "float32",
                               device_kind="cpu")
    assert (cfg, prov) == (planted["config"], "tuned")


def test_cap_multiple_hint_votes_admitted_rows_only(tmp_path):
    path = tmp_path / "db.json"
    db = ProfileDB(str(path))
    base = {"op": "ivf_topk", "dtype": "float32", "device_kind": "cpu",
            "best_ms": 0.1}
    db.record({**base, "shape": "4x8x64x8x3x2",
               "config": {"bq": 16, "cap_multiple": 64},
               "tuner": {"admitted": True}})
    # the alias row echoes the winner at the new layout cap — not a vote
    db.record({**base, "shape": "4x8x128x8x3x2",
               "config": {"bq": 16, "cap_multiple": 64},
               "tuner": {"admitted": True, "alias_of": "4x8x64x8x3x2"}})
    # a plain r18 measurement row is not a vote either
    db.record({**base, "shape": "4x8x32x8x3x2"})
    db.save()
    tuning.configure(db_path=str(path))
    assert tuning.cap_multiple_hint(device_kind="cpu") == 64
    assert tuning.cap_multiple_hint(device_kind="TPU v4") \
        == td.IVF_CAP_MULTIPLE
    ops = {r["op"] for r in tuning.resolutions()}
    assert "ivf_layout" in ops


# --------------------------------------------------- zero post-warm compiles

def test_kernel_dispatch_resolves_without_retrace(tmp_path):
    """Two jit calls at the same key: resolve() feeds the second call the
    SAME memoized config, so the warm cache hits and compile_guard sees
    zero new compiles — the r09/r19 contract with tuning enabled."""
    from dae_rnn_news_recommendation_tpu.ops.topk_fused import topk_fused

    b, n, d, k = TOPK_SHAPE
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, d)).astype(np.float32))
    emb = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
    valid = jnp.ones((n,), jnp.float32)
    fn = jax.jit(lambda a, e, v: topk_fused(a, e, v, k, impl="pallas",
                                            interpret=True))
    jax.block_until_ready(fn(q, emb, valid))      # warm: pays the compile
    with compile_guard() as guard:
        jax.block_until_ready(fn(q, emb, valid))
    assert guard.count == 0, guard.entries


@pytest.mark.slow
def test_service_zero_post_warm_compiles_with_tuning_enabled(tmp_path):
    """Service-level regression: with tuning ON (resolving through an
    actually-tuned DB row for the serving corpus shape), warmup() still
    pre-compiles everything a burst needs — zero post-warm compiles."""
    from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                                 init_params)
    from dae_rnn_news_recommendation_tpu.serve import (RecommendationService,
                                                       ServingCorpus)

    n_art, n_feat, n_dim = 64, 24, 8
    config = DAEConfig(n_features=n_feat, n_components=n_dim,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((n_art, n_feat),
                                               dtype=np.float32)
    corpus = ServingCorpus(config, block=16)
    corpus.swap(params, articles, note="initial")
    svc = RecommendationService(params, config, corpus, top_k=5,
                                max_batch=8, max_inflight=64)
    svc.warmup()
    try:
        with compile_guard() as guard:
            futs = [svc.submit(articles[i % n_art], deadline_s=10.0)
                    for i in range(10)]
            assert all(f.result(timeout=10.0).ok for f in futs)
        assert guard.count == 0, guard.entries
        assert svc.summary()["tuning"]["enabled"] is True
    finally:
        svc.stop()


# --------------------------------------------------------------------- CLI

def test_cli_tune_show_clear_round_trip(tmp_path, capsys):
    from dae_rnn_news_recommendation_tpu.tuning.__main__ import main

    db = str(tmp_path / "db.json")
    rc = main(["tune", "--select", "wire_unpack", "--shape", "16x8",
               "--dtype", "int32", "--db", db, "--n", "2", "--warmup", "1",
               "--interpret"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "recorded 1 tuned row(s)" in out
    assert "wire_unpack" in out

    rc = main(["show", "--db", db])
    out = capsys.readouterr().out
    assert rc == 0
    assert "kernel autotuner: 1 tuned rows" in out
    assert "interpreter captures" in out

    rc = main(["clear", "--select", "wire_unpack", "--db", db])
    out = capsys.readouterr().out
    assert rc == 0
    assert "dropped 1 tuned row(s)" in out
    assert len(ProfileDB(db)) == 0


def test_cli_shape_requires_single_op():
    from dae_rnn_news_recommendation_tpu.tuning.__main__ import main

    with pytest.raises(SystemExit):
        main(["tune", "--shape", "16x8"])


# ------------------------------------------------------------ report flag

def test_report_tuning_sentinel_contract(tmp_path, capsys):
    """--tuning matches the --fleet/--profile/--quality sentinel contract:
    omitted flag auto-detects silently, bare flag without a DB degrades to
    a note (exit 0), explicit/auto-detected DB renders the section and the
    JSON report carries the key."""
    from dae_rnn_news_recommendation_tpu.telemetry.__main__ import \
        main as cli_main

    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "fit/epoch", "ph": "X", "ts": 0, "dur": 1000,
         "pid": 1, "tid": 1}]}))
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "kernel autotuner" not in out
    assert "tuning DB unavailable" not in out       # silent when not asked

    rc = cli_main(["report", str(trace), "--tuning"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "tuning DB unavailable" in out
    assert "kernel autotuner" not in out

    # a DB next to the trace is picked up with NO flag at all — and plain
    # r18 measurement rows alone do NOT fabricate a tuning section
    db = ProfileDB(str(tmp_path / "profile_db.json"))
    db.record({"op": "train/step", "shape": "800x10000", "dtype": "bfloat16",
               "device_kind": "cpu", "best_ms": 3.0})
    db.save()
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0 and "kernel autotuner" not in out

    db.record({"op": "topk_fused", "shape": "8x4096x512x10",
               "dtype": "float32", "device_kind": "TPU v4", "best_ms": 0.21,
               "config": {"block": 1024, "bq": 16},
               "tuner": {"admitted": True, "parity": "exact",
                         "default_config": {"block": 512, "bq": 8},
                         "default_best_ms": 0.25,
                         "speedup_vs_default": 1.19, "n_candidates": 12,
                         "n_rejected": 1, "n_pruned_illegal": 3,
                         "n_pruned_vmem": 2}})
    db.save()
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "kernel autotuner: 1 tuned rows" in out
    assert "block=1024,bq=16" in out
    assert "x1.190" in out

    rc = cli_main(["report", str(trace), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["tuning"]["n_rows"] == 1
    assert payload["tuning"]["rows"][0]["op"] == "topk_fused"
