"""Model core + estimator tests: the paper-encoder property, train-step learning,
checkpoint resume, reference API surface (fit/transform/load_model/get_model_parameters),
triplet estimator, stacked DAE, GRU user model."""

import os

import numpy as np
import pytest
import scipy.sparse as sp
import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.models import (
    DAEConfig, DenoisingAutoencoder, DenoisingAutoencoderTriplet,
    GRUUserModel, StackedDenoisingAutoencoder, init_params, encode, forward,
)
from dae_rnn_news_recommendation_tpu.train import make_optimizer, make_train_step


def _cfg(**kw):
    base = dict(n_features=32, n_components=8, enc_act_func="tanh",
                dec_act_func="none", loss_func="mean_squared",
                corr_type="none", corr_frac=0.0, triplet_strategy="none")
    base.update(kw)
    return DAEConfig(**base)


def test_encode_zero_is_zero():
    """H = f(Wx+b) - f(b) guarantees encode(0) == 0 (reference autoencoder.py:389) —
    the property padding correctness relies on."""
    for act in ("sigmoid", "tanh", "none"):
        cfg = _cfg(enc_act_func=act)
        params = init_params(jax.random.PRNGKey(0), cfg)
        params["bh"] = jnp.asarray(np.random.default_rng(0).normal(size=8), jnp.float32)
        h = encode(params, jnp.zeros((3, 32)), cfg)
        np.testing.assert_allclose(np.asarray(h), 0.0, atol=1e-7)


def test_forward_shapes_and_tied_weights():
    cfg = _cfg(matmul_precision="highest")
    params = init_params(jax.random.PRNGKey(1), cfg)
    x = jnp.asarray(np.random.default_rng(1).uniform(size=(5, 32)), jnp.float32)
    h, y = forward(params, x, cfg)
    assert h.shape == (5, 8) and y.shape == (5, 32)
    # decode uses W^T of the same W (tied): y = h @ W.T + bv for dec_act none
    expect = np.asarray(h) @ np.asarray(params["W"]).T + np.asarray(params["bv"])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("opt", ["gradient_descent", "ada_grad", "momentum", "adam"])
def test_train_step_learns(opt):
    cfg = _cfg(corr_type="masking", corr_frac=0.2)
    params = init_params(jax.random.PRNGKey(2), cfg)
    optimizer = make_optimizer(opt, 0.05)
    opt_state = optimizer.init(params)
    step = make_train_step(cfg, optimizer)
    x = (np.random.default_rng(2).uniform(size=(16, 32)) < 0.3).astype(np.float32)
    batch = {"x": jnp.asarray(x), "row_valid": jnp.ones(16)}
    key = jax.random.PRNGKey(3)
    costs = []
    for i in range(30):
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        costs.append(float(metrics["cost"]))
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_train_step_triplet_strategies():
    labels = np.random.default_rng(3).integers(0, 3, 16).astype(np.int32)
    x = (np.random.default_rng(4).uniform(size=(16, 32)) < 0.3).astype(np.float32)
    for strategy in ("batch_all", "batch_hard"):
        cfg = _cfg(triplet_strategy=strategy, alpha=1.0)
        params = init_params(jax.random.PRNGKey(4), cfg)
        optimizer = make_optimizer("ada_grad", 0.1)
        opt_state = optimizer.init(params)
        step = make_train_step(cfg, optimizer)
        batch = {"x": jnp.asarray(x), "labels": jnp.asarray(labels),
                 "row_valid": jnp.ones(16)}
        params, opt_state, metrics = step(params, opt_state, jax.random.PRNGKey(5), batch)
        for k in ("cost", "autoencoder_loss", "triplet_loss", "fraction_triplet", "num_triplet"):
            assert np.isfinite(float(metrics[k])), (strategy, k)


def test_train_step_joint_two_label_mining():
    """label2_alpha adds a second batch_all term over labels2; rows with
    labels2 < 0 (missing secondary label) sit out that term. Oracle: compose
    the two single-label calls by hand."""
    from dae_rnn_news_recommendation_tpu.ops import losses, triplet
    from dae_rnn_news_recommendation_tpu.train.step import loss_and_metrics

    rng = np.random.default_rng(7)
    b = 16
    x = (rng.uniform(size=(b, 32)) < 0.3).astype(np.float32)
    lab1 = rng.integers(0, 3, b).astype(np.int32)
    lab2 = rng.integers(0, 4, b).astype(np.int32)
    lab2[:5] = -1  # missing secondary labels
    rv = np.ones(b, np.float32)
    cfg = _cfg(triplet_strategy="batch_all", alpha=2.0, label2_alpha=0.5,
               corr_type="none")
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = {"x": jnp.asarray(x), "labels": jnp.asarray(lab1),
             "labels2": jnp.asarray(lab2), "row_valid": jnp.asarray(rv)}
    cost, metrics = loss_and_metrics(params, batch, jax.random.PRNGKey(1), cfg)

    from dae_rnn_news_recommendation_tpu.models.dae_core import decode, encode
    h = encode(params, jnp.asarray(x), cfg)
    y = decode(params, h, cfg)
    t1, w1, _, _, _ = triplet.batch_all_triplet_loss(
        jnp.asarray(lab1), h, row_valid=jnp.asarray(rv))
    rv2 = rv * (lab2 >= 0)
    t2, w2, _, _, _ = triplet.batch_all_triplet_loss(
        jnp.asarray(lab2), h, row_valid=jnp.asarray(rv2))
    ae = losses.weighted_loss(jnp.asarray(x), y, cfg.loss_func,
                              weight=jnp.maximum(w1, w2),
                              row_valid=jnp.asarray(rv))
    expect = float(ae + 2.0 * (t1 + 0.5 * t2))
    np.testing.assert_allclose(float(cost), expect, rtol=1e-6)
    np.testing.assert_allclose(float(metrics["triplet_loss"]),
                               float(t1 + 0.5 * t2), rtol=1e-6)

    # label2_alpha=0 ignores labels2 entirely (reference single-label behavior)
    cfg0 = _cfg(triplet_strategy="batch_all", alpha=2.0, corr_type="none")
    cost0, _ = loss_and_metrics(init_params(jax.random.PRNGKey(0), cfg0),
                                batch, jax.random.PRNGKey(1), cfg0)
    expect0 = float(losses.weighted_loss(
        jnp.asarray(x), y, cfg0.loss_func, weight=w1,
        row_valid=jnp.asarray(rv)) + 2.0 * t1)
    np.testing.assert_allclose(float(cost0), expect0, rtol=1e-6)


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _fit_small(workdir, **kw):
    rng = np.random.default_rng(0)
    X = sp.random(60, 24, density=0.3, format="csr", random_state=0, dtype=np.float32)
    labels = rng.integers(0, 4, 60)
    defaults = dict(model_name="t", compress_factor=6, num_epochs=3, batch_size=16,
                    opt="ada_grad", learning_rate=0.1, corr_type="masking",
                    corr_frac=0.3, verbose=False, verbose_step=2, seed=11,
                    triplet_strategy="batch_all", use_tensorboard=False)
    defaults.update(kw)
    m = DenoisingAutoencoder(**defaults)
    m.fit(X, validation_set=X[:20], train_set_label=labels,
          validation_set_label=labels[:20])
    return m, X, labels


def test_estimator_end_to_end(workdir):
    m, X, labels = _fit_small(workdir)
    enc = m.transform(X, name="enc", save=True)
    assert enc.shape == (60, 4)
    assert np.isfinite(enc).all()
    # artifact tree (reference autoencoder.py:544-564)
    for d in (m.models_dir, m.data_dir, m.tf_summary_dir, m.tsv_dir, m.plot_dir):
        assert os.path.isdir(d)
    assert os.path.isfile(m.parameter_file)
    assert os.path.isfile(os.path.join(m.data_dir, "enc.npy"))
    assert os.path.isfile(os.path.join(m.tf_summary_dir, "train/metrics.jsonl"))
    p = m.get_model_parameters()
    assert p["enc_w"].shape == (24, 4)
    assert p["enc_b"].shape == (4,)
    assert p["dec_b"].shape == (24,)


def test_estimator_restore_continues(workdir):
    m, X, labels = _fit_small(workdir)
    w0 = m.get_model_parameters()["enc_w"]
    m2 = DenoisingAutoencoder(model_name="t", compress_factor=6, num_epochs=2,
                              batch_size=16, opt="ada_grad", learning_rate=0.1,
                              verbose=False, seed=11, triplet_strategy="batch_all",
                              use_tensorboard=False)
    m2.fit(X, train_set_label=labels, restore_previous_model=True)
    assert m2._epoch0 == 3  # resumed from epoch 3
    w1 = m2.get_model_parameters()["enc_w"]
    assert not np.allclose(w0, w1)  # training continued


def test_estimator_dense_input_and_none_strategy(workdir):
    X = (np.random.default_rng(1).uniform(size=(40, 24)) < 0.3).astype(np.float32)
    m = DenoisingAutoencoder(model_name="d", compress_factor=6, num_epochs=2,
                             batch_size=10, enc_act_func="sigmoid",
                             dec_act_func="sigmoid", loss_func="cross_entropy",
                             verbose=False, seed=1, triplet_strategy="none",
                             use_tensorboard=False)
    m.fit(X)
    enc = m.transform(X)
    assert enc.shape == (40, 4)


def test_load_model_roundtrip(workdir):
    m, X, _ = _fit_small(workdir)
    enc1 = m.transform(X)
    m2 = DenoisingAutoencoder(model_name="t", use_tensorboard=False, verbose=False)
    m2.load_model((24, 4), m.model_path)
    enc2 = m2.transform(X, from_checkpoint=False)
    np.testing.assert_allclose(enc1, enc2, rtol=1e-5, atol=1e-6)


def test_triplet_estimator(workdir):
    rng = np.random.default_rng(2)
    org = sp.random(40, 24, density=0.3, format="csr", random_state=1, dtype=np.float32)
    pos = sp.random(40, 24, density=0.3, format="csr", random_state=2, dtype=np.float32)
    neg = sp.random(40, 24, density=0.3, format="csr", random_state=3, dtype=np.float32)
    train = {"org": org, "pos": pos, "neg": neg}
    m = DenoisingAutoencoderTriplet(model_name="trip", compress_factor=6, num_epochs=3,
                                    batch_size=10, opt="ada_grad", learning_rate=0.1,
                                    corr_type="masking", corr_frac=0.2, verbose=False,
                                    seed=5, alpha=1, use_tensorboard=False)
    m.fit(train, validation_set={k: v[:10] for k, v in train.items()})
    enc = m.transform(org)
    assert enc.shape == (40, 4)
    assert np.isfinite(enc).all()


def test_stacked_dae():
    X = (np.random.default_rng(3).uniform(size=(50, 32)) < 0.3).astype(np.float32)
    m = StackedDenoisingAutoencoder([12, 6], num_epochs=2, batch_size=16,
                                    corr_frac=0.2, seed=0)
    m.fit(X)
    code = m.encode(X)
    assert code.shape == (50, 6)
    # zero input -> zero code at every depth
    z = m.encode(np.zeros((2, 32), np.float32))
    np.testing.assert_allclose(z, 0.0, atol=1e-6)


def test_gru_user_model_learns():
    rng = np.random.default_rng(4)
    N, T, D = 64, 5, 8
    # synthetic: positive articles align with the mean of the browse history
    seq = rng.normal(size=(N, T, D)).astype(np.float32)
    pos = seq + 0.1 * rng.normal(size=(N, T, D)).astype(np.float32)
    neg = -seq + 0.1 * rng.normal(size=(N, T, D)).astype(np.float32)
    mask = np.ones((N, T), np.float32)
    mask[:, -1] = 0.0  # ragged tails

    m = GRUUserModel(d_embed=D, d_hidden=8, num_epochs=1, batch_size=32, seed=0)
    from dae_rnn_news_recommendation_tpu.models.gru_user import pairwise_rank_loss
    import jax.numpy as jnp
    m.fit(seq, pos, neg, mask)
    l1 = float(pairwise_rank_loss(m.params, jnp.asarray(seq), jnp.asarray(pos),
                                  jnp.asarray(neg), jnp.asarray(mask)))
    m2 = GRUUserModel(d_embed=D, d_hidden=8, num_epochs=8, batch_size=32, seed=0)
    m2.fit(seq, pos, neg, mask)
    l2 = float(pairwise_rank_loss(m2.params, jnp.asarray(seq), jnp.asarray(pos),
                                  jnp.asarray(neg), jnp.asarray(mask)))
    assert l2 < l1, (l1, l2)
    states = m2.user_state(seq, mask)
    assert states.shape == (N, 8)
    scores = m2.score(seq, rng.normal(size=(7, 8)).astype(np.float32), mask)
    assert scores.shape == (N, 7)


def test_profile_and_histograms(tmp_path, monkeypatch, rng):
    """profile=True captures an XProf trace under logs/profile/; parameter
    histograms land in the train metrics stream at the summary cadence
    (reference tf.summary.histogram parity, autoencoder.py:391-393)."""
    import json
    import os

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder

    monkeypatch.chdir(tmp_path)
    X = (rng.uniform(size=(60, 40)) < 0.2).astype(np.float32)
    model = DenoisingAutoencoder(
        model_name="prof", main_dir="prof", compress_factor=10, num_epochs=2,
        batch_size=20, verbose=False, verbose_step=1, triplet_strategy="none",
        loss_func="mean_squared", dec_act_func="none", enc_act_func="tanh",
        profile=True, use_tensorboard=False, seed=0)
    model.fit(X)

    prof_dir = os.path.join(model.tf_summary_dir, "profile")
    assert os.path.isdir(prof_dir)
    assert any(files for _, _, files in os.walk(prof_dir)), "empty profile trace"

    with open(os.path.join(model.tf_summary_dir, "train/metrics.jsonl")) as f:
        records = [json.loads(line) for line in f]
    hist_tags = {r["tag"] for r in records if "hist" in r}
    assert {"enc_w", "hidden_bias", "visible_bias"} <= hist_tags
    w_hists = [r for r in records if r["tag"] == "enc_w"]
    assert len(w_hists) == 2  # verbose_step=1, two epochs
    assert w_hists[0]["hist"]["n"] == 40 * 4
    # histogram steps share the scalars' global-batch-step domain (3 batches/epoch)
    assert [r["step"] for r in w_hists] == [3, 6]
    scalar_steps = {r["step"] for r in records if "hist" not in r}
    assert set([3, 6]) <= scalar_steps

    # short run below the cadence: the catch-up validation still emits histograms
    model2 = DenoisingAutoencoder(
        model_name="prof2", main_dir="prof2", compress_factor=10, num_epochs=2,
        batch_size=20, verbose=False, verbose_step=5, triplet_strategy="none",
        loss_func="mean_squared", dec_act_func="none", enc_act_func="tanh",
        use_tensorboard=False, seed=0)
    model2.fit(X)
    with open(os.path.join(model2.tf_summary_dir, "train/metrics.jsonl")) as f:
        records2 = [json.loads(line) for line in f]
    assert sum(1 for r in records2 if r["tag"] == "enc_w") == 1


def test_checkpoint_retention(tmp_path, monkeypatch, rng):
    """keep_checkpoint_max trims old step_* dirs; the newest survive and restore."""
    import os

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import prune_checkpoints

    monkeypatch.chdir(tmp_path)
    X = (rng.uniform(size=(40, 30)) < 0.2).astype(np.float32)
    model = DenoisingAutoencoder(
        model_name="keep", main_dir="keep", compress_factor=10, num_epochs=6,
        batch_size=20, verbose=False, triplet_strategy="none",
        loss_func="mean_squared", dec_act_func="none", enc_act_func="tanh",
        checkpoint_every=1, keep_checkpoint_max=2, seed=0)
    model.fit(X)
    steps = sorted(os.listdir(model.model_path))
    assert steps == ["step_5", "step_6"]
    # restore still works from the retained tail
    model2 = DenoisingAutoencoder(
        model_name="keep", main_dir="keep", compress_factor=10, num_epochs=1,
        batch_size=20, verbose=False, triplet_strategy="none",
        loss_func="mean_squared", dec_act_func="none", enc_act_func="tanh", seed=0)
    model2.fit(X, restore_previous_model=True)
    assert model2._epoch0 == 6

    assert prune_checkpoints(str(tmp_path / "nonexistent"), 3) == []
    assert prune_checkpoints(model2.model_path, 0) == []


def test_transform_sparse_matches_dense_path(workdir):
    """Sparse inputs take the sparse-ingest device stream; it must produce the
    same codes as densifying on host and running the dense encode."""
    m, X, _ = _fit_small(workdir)
    enc_sparse = m.transform(X)                       # csr -> sparse-ingest path
    enc_dense = m.transform(np.asarray(X.todense()))  # ndarray -> dense path
    np.testing.assert_allclose(enc_sparse, enc_dense, rtol=1e-5, atol=1e-6)

    # ragged tail + multi-batch: batch_size smaller than N, N % batch_size != 0
    enc_batched = m.transform(X, batch_size=17)
    np.testing.assert_allclose(enc_batched, enc_sparse, rtol=1e-5, atol=1e-6)

    # empty rows encode to exactly zero on both paths (dae_core H(0) == 0)
    X_holes = X.tolil()
    X_holes[0] = 0
    enc_holes = m.transform(X_holes.tocsr())
    np.testing.assert_array_equal(enc_holes[0], np.zeros(enc_holes.shape[1]))


def test_async_mid_run_checkpoints(workdir):
    """checkpoint_every saves run on a background writer; all checkpoints must
    be durable by the end of fit and the newest must restore exactly."""
    m, X, labels = _fit_small(workdir, checkpoint_every=1, num_epochs=4)
    steps = sorted(int(n.split("_")[1]) for n in os.listdir(m.model_path)
                   if n.startswith("step_"))
    assert steps == [1, 2, 3, 4]  # 3 async mid-run + 1 blocking final
    # transform restores from the latest checkpoint (waits for in-flight writes)
    enc = m.transform(X)
    assert np.isfinite(enc).all()
    # and the saved state resumes exactly (epoch recorded in aux)
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
        latest_checkpoint, load_checkpoint)
    path, step = latest_checkpoint(m.model_path)
    state = load_checkpoint(path, {"params": m.params, "opt_state": m.opt_state})
    assert state["epoch"] == 4
    np.testing.assert_array_equal(np.asarray(state["params"]["W"]),
                                  np.asarray(m.params["W"]))
