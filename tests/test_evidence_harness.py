"""Unit tests for the evidence harness's provenance machinery (evidence/run.py):
the round-2 record spliced CPU stages into a TPU-labeled header, and these pin
the guards that prevent a recurrence — per-stage provenance through the stage
cache, fingerprint invalidation, and the mixed-record warning in RESULTS.md.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def evrun(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "evrun_under_test", os.path.join(REPO, "evidence", "run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "CACHE", str(tmp_path / "stage_cache.json"))
    mod.STAGE_PROVENANCE.clear()
    return mod


def test_staged_records_and_replays_provenance(evrun):
    calls = []
    out1 = evrun._staged("s1", lambda: (calls.append(1), {"x": 1})[1],
                         platform="tpu", run_id="run_a")
    assert out1 == {"x": 1} and calls == [1]
    assert evrun.STAGE_PROVENANCE["s1"] == {"platform": "tpu",
                                            "run_id": "run_a"}

    # a later run (different platform/run id) reuses the cache but must
    # surface the ORIGINAL provenance, not claim its own
    evrun.STAGE_PROVENANCE.clear()
    out2 = evrun._staged("s1", lambda: pytest.fail("must not re-run"),
                         platform="cpu", run_id="run_b")
    assert out2 == {"x": 1}
    assert evrun.STAGE_PROVENANCE["s1"] == {"platform": "tpu",
                                            "run_id": "run_a"}
    # a new stage in the second run carries the second run's provenance ->
    # the aggregate is visibly mixed
    evrun._staged("s2", lambda: {"y": 2}, platform="cpu", run_id="run_b")
    platforms = {p["platform"] for p in evrun.STAGE_PROVENANCE.values()}
    assert platforms == {"tpu", "cpu"}


def test_stage_cache_invalidates_on_fingerprint_change(evrun, monkeypatch):
    evrun._staged("s1", lambda: {"x": 1}, platform="cpu", run_id="r")
    monkeypatch.setattr(evrun, "_fingerprint", lambda: "different-config")
    calls = []
    out = evrun._staged("s1", lambda: (calls.append(1), {"x": 99})[1],
                        platform="cpu", run_id="r2")
    assert out == {"x": 99} and calls == [1]  # stale cache was NOT reused


def test_results_md_flags_mixed_provenance(evrun, monkeypatch, tmp_path):
    """The committed record is the template; flipping uniform_provenance must
    produce the explicit mixed-record warning instead of the uniform claim."""
    with open(os.path.join(REPO, "evidence", "results.json")) as f:
        payload = json.load(f)
    monkeypatch.setattr(evrun, "HERE", str(tmp_path))

    evrun._write_md(dict(payload, uniform_provenance=True))
    uniform_md = (tmp_path / "RESULTS.md").read_text()
    assert "single run on this single platform" in uniform_md
    assert "WARNING" not in uniform_md

    evrun._write_md(dict(payload, uniform_provenance=False))
    mixed_md = (tmp_path / "RESULTS.md").read_text()
    assert "WARNING" in mixed_md and "different runs or platforms" in mixed_md


def test_evidence_arg_lists_parse(evrun):
    """Flag renames must not silently rot the committed evidence scripts: every
    stage's arg list parses against the live config schema."""
    from dae_rnn_news_recommendation_tpu.utils.config import parse_flags

    for name in ("MAIN_ARGS", "STORY_ARGS", "MOE_ARGS", "REFSCALE_ARGS",
                 "REFSTORY_ARGS"):
        parse_flags(getattr(evrun, name))
    for name in ("TRIPLET_ARGS", "TRIPLET_STORY_ARGS"):
        parse_flags(getattr(evrun, name), triplet_mode=True)

    spec = importlib.util.spec_from_file_location(
        "scale_under_test", os.path.join(REPO, "evidence", "scale.py"))
    scale = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(scale)
    flags = parse_flags(scale.ARGS)
    assert flags.max_features == 50000 and flags.train_row == 100000


def test_sweep_script_arg_lists_parse(evrun):
    """The committed sweep/spread harnesses must keep parsing too: every GRID
    entry in story_sweep2 and every reseeded stage in seed_spread goes through
    the live flag schema."""
    from dae_rnn_news_recommendation_tpu.utils.config import parse_flags

    spec = importlib.util.spec_from_file_location(
        "sweep2_under_test", os.path.join(REPO, "evidence", "story_sweep2.py"))
    sweep2 = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep2)
    for name, extra in sweep2.GRID:
        parse_flags(sweep2.BASE + ["--model_name", name] + extra)

    spec = importlib.util.spec_from_file_location(
        "spread_under_test", os.path.join(REPO, "evidence", "seed_spread.py"))
    spread = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(spread)
    args = spread._stage_args(seed=5)
    for stage in ("main", "story"):
        flags = parse_flags(args[stage])
        assert flags.seed == 5
    assert parse_flags(args["triplet"], triplet_mode=True).seed == 5


def test_bench_trajectory_gate_fails_on_same_platform_drop(evrun, monkeypatch):
    """ISSUE 11 satellite: a >15% drop on a named metric vs the latest PRIOR
    record of the SAME platform fails the gate; cross-platform ratios are
    never formed (CPU and TPU rounds interleave in the committed history)."""
    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu", "train_articles_per_sec": 100.0}),
        ("r2", {"platform": "tpu", "train_articles_per_sec": 9000.0}),
        ("r3", {"platform": "cpu", "train_articles_per_sec": 80.0,
                "serve_ivf_speedup": 2.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert not ok and "train_articles_per_sec" in detail
    # the drop is vs r1 (same platform), not the TPU r2
    assert "100.0" in detail and "9000" not in detail


def test_bench_trajectory_gate_tolerates_absent_history(evrun, monkeypatch):
    """Missing metrics, a never-before-seen platform, or a thin history pass
    with a note — the gate fails only on a MEASURED drop."""
    monkeypatch.setattr(evrun, "_bench_history", lambda: [("only", {})])
    ok, detail = evrun._bench_trajectory_gate()
    assert ok and "nothing to gate" in detail

    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu"}),
        ("r2", {"platform": "tpu", "serve_queries_per_sec": 5.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert ok and "pass by absence" in detail


def test_bench_trajectory_gate_passes_within_tolerance(evrun, monkeypatch):
    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu", "serve_queries_per_sec": 100.0,
                "serve_ivf_queries_per_sec": 50.0}),
        ("r2", {"platform": "cpu", "serve_queries_per_sec": 90.0,
                "serve_ivf_queries_per_sec": 55.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert ok and "serve_ivf_queries_per_sec" in detail


def test_bench_trajectory_gate_reads_committed_history(evrun):
    """The real committed BENCH_r*.json trajectory must parse and pass —
    if this fails, either a record is corrupt or a real regression landed."""
    hist = evrun._bench_history()
    assert len(hist) >= 2           # r02..r05 carry parsed extras
    ok, detail = evrun._bench_trajectory_gate()
    assert ok, detail


def test_bench_trajectory_gate_inverts_lower_is_better_metrics(evrun,
                                                               monkeypatch):
    """ISSUE 12 satellite: fleet tail-latency and shed-rate metrics gate in
    the LOWER-is-better direction — a p99 that grows >15% fails even though
    the raw ratio now/base would look like an 'improvement'."""
    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu", "fleet_p99_ms": 100.0}),
        ("r2", {"platform": "cpu", "fleet_p99_ms": 140.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert not ok and "fleet_p99_ms" in detail and "lower is better" in detail

    # an improving (shrinking) p99 passes
    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu", "fleet_p99_ms": 140.0}),
        ("r2", {"platform": "cpu", "fleet_p99_ms": 100.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert ok

    # a zero-valued base (e.g. a 0.0 shed rate) never forms a ratio: the
    # metric passes by absence instead of dividing by zero
    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"platform": "cpu", "fleet_shed_rate": 0.0}),
        ("r2", {"platform": "cpu", "fleet_shed_rate": 0.0}),
    ])
    ok, detail = evrun._bench_trajectory_gate()
    assert ok and "pass by absence" in detail


def test_profile_overhead_gate_reads_latest_race(evrun, monkeypatch):
    """ISSUE 18: the devprof disabled-instrumentation race gates <1% on the
    LATEST record carrying both legs; a history without the race passes with
    a note, a measured slowdown fails."""
    monkeypatch.setattr(evrun, "_bench_history",
                        lambda: [("r1", {"platform": "cpu"})])
    ok, detail = evrun._profile_overhead_gate()
    assert ok and "pass by absence" in detail

    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("r1", {"profile_overhead_bare_aps": 1000.0,
                "profile_overhead_instrumented_aps": 996.0}),
    ])
    ok, detail = evrun._profile_overhead_gate()
    assert ok and "0.40%" in detail

    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("old", {"profile_overhead_bare_aps": 1000.0,
                 "profile_overhead_instrumented_aps": 999.0}),
        ("new", {"profile_overhead_bare_aps": 1000.0,
                 "profile_overhead_instrumented_aps": 950.0}),
    ])
    ok, detail = evrun._profile_overhead_gate()
    assert not ok and detail.startswith("new:") and "5.00%" in detail


def test_autotuned_speedup_gate_latest_race(evrun, monkeypatch):
    """ISSUE 20: the autotuner race gates >= 1.0 on the LATEST record
    carrying a speedup figure; CPU-only histories (no figure) pass by
    absence, and a figure below 1.0 fails — the default always races, so
    sub-1.0 means the measurement itself broke."""
    monkeypatch.setattr(evrun, "_bench_history",
                        lambda: [("r1", {"platform": "cpu"})])
    ok, detail = evrun._autotuned_speedup_gate()
    assert ok and "pass by absence" in detail

    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("old", {"serve_autotuned_speedup": 0.5}),   # superseded: ignored
        ("new", {"serve_autotuned_speedup": 1.07,
                 "train_autotuned_speedup": 1.0}),
    ])
    ok, detail = evrun._autotuned_speedup_gate()
    assert ok and detail.startswith("new:")

    monkeypatch.setattr(evrun, "_bench_history", lambda: [
        ("bad", {"serve_autotuned_speedup": 0.93,
                 "train_autotuned_speedup": 1.2}),
    ])
    ok, detail = evrun._autotuned_speedup_gate()
    assert not ok and "0.93" in detail
