"""Chaos-soak acceptance (ISSUE 6): seeded fault plans replayed end-to-end
must recover to BITWISE-identical params on CPU, with every fault and retry
visible in the run manifest (zero silent recoveries) and every plan bounded
by a deadline (zero hangs). Plus the SIGTERM flavor: a fit killed by a real
signal and resumed in a fresh process state must match an uninterrupted run
exactly.

Component-level contracts live in tests/test_reliability.py; this file is
the end-to-end bar.
"""

import os
import subprocess
import sys
import textwrap

import pytest

import jax

from dae_rnn_news_recommendation_tpu.reliability.chaos import chaos_soak
from dae_rnn_news_recommendation_tpu.telemetry.report import (
    faults_summary, render_text)

N_PLANS = 8  # >= 6 consecutive seeds cover every fault family (faults.py)


def test_chaos_soak_is_crash_exact_and_nothing_is_silent(tmp_path):
    out = chaos_soak(str(tmp_path), n_plans=N_PLANS)
    results = out["results"]
    assert len(results) == N_PLANS

    for res in results:
        seed = res.plan["seed"]
        assert res.ok, f"plan {seed}: {res.detail}"
        if jax.default_backend() == "cpu":
            assert res.bitwise, (
                f"plan {seed}: recovered but not bitwise ({res.detail})")
        assert res.injected, f"plan {seed} landed no faults (nothing tested)"
        # zero silent recoveries: the FINAL run manifest carries every fault
        # that fired and every retry taken, across all crashed attempts
        mf = res.manifest_faults
        assert len(mf.get("injected") or []) == len(res.injected), (
            f"plan {seed}: manifest lost injected faults: "
            f"{mf.get('injected')} vs {res.injected}")
        assert len(mf.get("retries") or []) == len(res.retries), (
            f"plan {seed}: manifest lost retries: "
            f"{mf.get('retries')} vs {res.retries}")
        assert mf.get("plan_seed") == seed

    assert out["all_ok"] and out["n_ok"] == N_PLANS

    # the soak as a whole exercised both recovery modes...
    assert any(r.restarts > 0 for r in results)   # restart-from-checkpoint
    assert any(r.retries for r in results)        # absorbed transients
    # ...and every fault family the generator round-robins over
    sites = {(e["site"], e["kind"]) for r in results for e in r.injected}
    assert {("train.step", "preempt"), ("feed.worker", "fatal"),
            ("feed.h2d", "transient"), ("ckpt.save", "transient"),
            ("ckpt.commit", "fatal"), ("ckpt.corrupt", "truncate")} <= sites

    # `telemetry report` renders the ledger (satellite: faults section)
    res = next(r for r in results if r.retries)
    faults = faults_summary({"faults": res.manifest_faults})
    assert faults is not None
    assert faults["n_injected"] == len(res.injected)
    assert faults["n_retries"] == len(res.retries)
    text = render_text([], faults=faults)
    assert "faults/retries:" in text
    assert "injected:" in text and "retry:" in text


# The kill-and-resume parity script: run an uninterrupted reference fit, then
# the same fit interrupted by a REAL SIGTERM (delivered by a watcher thread
# the moment the first epoch checkpoint commits — deterministic, no parent
# timing races), then resume it; both digests are printed for the parent.
_SCRIPT = textwrap.dedent("""
    import os, sys, signal, threading, time
    repo = sys.argv[1]
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.reliability.chaos import (
        params_digest, soak_data)
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
        latest_checkpoint)

    TOTAL = 6
    X = soak_data(n_rows=240, n_features=24, seed=1234)  # 20 batches/epoch

    def make(tag, num_epochs):
        # masking corruption + momentum so the per-batch PRNG chain and the
        # optimizer state both MATTER: a wrong resume shows up in the digest
        return DenoisingAutoencoder(
            model_name=f"parity-{tag}", main_dir=f"parity-{tag}/",
            results_root=os.path.join(os.getcwd(), tag),
            num_epochs=num_epochs, batch_size=12, verbose=False,
            use_tensorboard=False, seed=11, opt="momentum", momentum=0.7,
            learning_rate=0.05, corr_type="masking", corr_frac=0.3,
            triplet_strategy="none", checkpoint_every=1,
            checkpoint_every_steps=4, n_components=4)

    ref = make("ref", TOTAL)
    ref.fit(X)
    print("REF_DIGEST", params_digest(ref.params), flush=True)

    m = make("chaos", TOTAL)
    done = threading.Event()

    def watcher():
        # fire the moment epoch 1's checkpoint commits -> the signal lands
        # mid-epoch-2 and the graceful handler stops at that boundary
        first = os.path.join(m.model_path, "step_1")
        while not done.is_set():
            if os.path.isdir(first):
                os.kill(os.getpid(), signal.SIGTERM)
                return
            time.sleep(0.001)

    threading.Thread(target=watcher, daemon=True).start()
    m.fit(X)
    done.set()
    path, _ = latest_checkpoint(m.model_path)
    completed = int(np.load(os.path.join(path, "aux.npz"))["epoch"])
    print("STOPPED_AT", completed, flush=True)
    if completed >= TOTAL:
        print("TOO_LATE", flush=True)  # signal lost the race; nothing to test
        sys.exit(0)

    m2 = make("chaos", TOTAL - completed)
    m2.fit(X, restore_previous_model=True)
    print("RESUMED_DIGEST", params_digest(m2.params), flush=True)
""")


def test_sigterm_kill_and_resume_matches_uninterrupted_run(tmp_path):
    script = tmp_path / "parity.py"
    script.write_text(_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run([sys.executable, str(script), repo],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, cwd=tmp_path, env=env, timeout=570)
    out = proc.stdout
    assert proc.returncode == 0, out[-3000:]
    if "TOO_LATE" in out:  # pragma: no cover - timing fallback, not expected
        pytest.skip("SIGTERM landed after the fit finished; nothing to test")

    def grab(prefix):
        lines = [ln for ln in out.splitlines() if ln.startswith(prefix)]
        assert lines, f"{prefix} missing from:\n{out[-3000:]}"
        return lines[0].split()[1]

    stopped = int(grab("STOPPED_AT"))
    assert 1 <= stopped < 6, out[-2000:]       # it really was interrupted
    assert "stopping early" in out             # via the graceful SIGTERM path
    ref, resumed = grab("REF_DIGEST"), grab("RESUMED_DIGEST")
    assert ref == resumed, (
        f"kill-and-resume diverged: ref {ref[:16]} vs resumed "
        f"{resumed[:16]} (stopped at epoch {stopped})\n{out[-2000:]}")
