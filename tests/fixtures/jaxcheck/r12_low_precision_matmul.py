"""Planted R12: low-precision matmuls that silently accumulate in the input
dtype. The serving-recall contract (docs/serving.md) is fp32 accumulation
over bf16/int8 operands via `preferred_element_type` — without it the MXU
rounds every partial sum to the narrow dtype. Clean twins: the same matmuls
carrying `preferred_element_type=jnp.float32`, an fp32-cast matmul (no low
evidence), and a reasoned compute-dtype-contract disable."""

import jax
import jax.numpy as jnp


def bf16_cast_operand(x, w):
    return jnp.matmul(x.astype(jnp.bfloat16), w)  # planted: R12


def int8_bound_then_matmul_op(h, w):
    w8 = w.astype("int8")
    return h @ w8.T  # planted: R12


def config_compute_dtype_idiom(params, x, config):
    # the repo's dae_core shape: dt is only *maybe* low — R12 treats maybe
    # as yes, because the config default IS bfloat16
    dt = jnp.dtype(config.compute_dtype)
    w = params["W"].astype(dt)
    return jnp.matmul(x.astype(dt), w)  # planted: R12


def einsum_low_operand(x, w):
    xq = x.astype(jnp.bfloat16)
    return jnp.einsum("bf,fd->bd", xq, w)  # planted: R12


def dot_general_low_operand(q, e):
    eq = e.astype(jnp.int8)
    return jax.lax.dot_general(q, eq, (((1,), (1,)), ((), ())))  # planted: R12


# ---------------------------------------------------------------- clean twins

def bf16_with_preferred(x, w):
    return jnp.matmul(x.astype(jnp.bfloat16), w,
                      preferred_element_type=jnp.float32)


def dtype_var_with_preferred(params, x, config):
    dt = jnp.dtype(config.compute_dtype)
    w = params["W"].astype(dt)
    return jnp.matmul(x.astype(dt), w,
                      preferred_element_type=jnp.float32)


def fp32_cast_is_not_low(h, emb):
    # widening cast: accumulation dtype == operand dtype == fp32, no hazard
    return h @ emb.astype(jnp.float32).T


def fp32_dtype_binding_is_not_low(x, w):
    dt = jnp.dtype("float32")
    return jnp.matmul(x.astype(dt), w)


def narrow_accumulation_is_the_contract(params, x, config):
    dt = jnp.dtype(config.compute_dtype)
    w = params["W"].astype(dt)
    # jaxcheck: disable=R12 (compute-dtype parity with the reference model: the narrow rounding is the numerical contract under test)
    return jnp.matmul(x.astype(dt), w)
