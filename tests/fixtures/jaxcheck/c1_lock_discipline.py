"""C1 fixture: a thread-shared class (it allocates its own lock) writes one
attribute both under `with self._lock:` and bare — the bare write races the
locked read-modify-write. Clean twin guards every write of the attribute.
"""

import threading


class HitCounter:
    """Shared between the caller and a flush worker thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._epoch = 0

    def add(self, k):
        with self._lock:
            self._n += k

    def flush(self):
        total = self._n
        self._n = 0       # planted: C1
        return total


class CleanCounter:
    """Same shape, every write of the guarded attribute under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def add(self, k):
        with self._lock:
            self._n += k

    def flush(self):
        with self._lock:
            total = self._n
            self._n = 0
        return total
