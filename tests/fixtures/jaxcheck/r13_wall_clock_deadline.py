"""Planted R13: wall-clock time.time() in deadline/timeout arithmetic — the
clock-jump failure shapes that make a serve deadline fire early/late/never.
Clean twins: time.monotonic() for every interval, with time.time() kept only
for log/manifest timestamps, and a reasoned disable on a genuine wall-clock
contract (an absolute due time from an external scheduler)."""

import time


def compute_deadline(budget_s):
    deadline = time.time() + budget_s  # planted: R13
    return deadline


def shed_expired(requests, deadline):
    alive = []
    for req in requests:
        if time.time() > deadline:  # planted: R13
            break
        alive.append(req)
    return alive


def watchdog_loop(t0, timeout_s, poll):
    while time.time() - t0 < timeout_s:  # planted: R13
        poll()


def park_until(fut, t_start, budget_s):
    return fut.result(timeout=time.time() - t_start)  # planted: R13


# ---------------------------------------------------------------- clean twins

def compute_deadline_monotonic(budget_s):
    deadline = time.monotonic() + budget_s  # interval math on the right clock
    return deadline


def shed_expired_monotonic(requests, deadline):
    alive = []
    for req in requests:
        if time.monotonic() > deadline:
            break
        alive.append(req)
    return alive


def stamp_manifest(manifest):
    manifest["ts"] = time.time()  # a wall-clock TIMESTAMP, not deadline state
    started = time.time()
    manifest["wall_s"] = time.time() - started  # duration stamp, no compare
    return manifest


def external_due_time(job):
    # jaxcheck: disable=R13 (the scheduler hands us an absolute wall-clock due time; comparing against wall clock IS the contract here)
    return time.time() >= job["due_at_unix"]
