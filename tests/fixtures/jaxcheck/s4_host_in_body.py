"""S4 fixture: host-side work captured in a shard_map body — device
transfers, `np.` materialization of traced operands, `.tolist()` — breaks
tracing or pins a host round-trip into every collective dispatch. Clean
twin: device-only body; static host `np` arithmetic outside the traced
operands stays allowed.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MESH_AXIS_NAMES = ("data",)


def make_densify(mesh):
    def local(x):
        rows = np.asarray(x)                     # planted: S4
        moved = jax.device_put(rows)             # planted: S4
        cells = rows.tolist()                    # planted: S4
        return jnp.asarray(moved) + len(cells)

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))


def make_densify_clean(mesh):
    scale = np.float32(1.0 / 8.0)   # static host constant: fine

    def local(x):
        return x * jnp.asarray(scale)

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))
