"""Planted R1 violations: host syncs reachable inside traced code.

Each line the analyzer must flag carries a trailing planted-rule marker.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def bad_item(params, x):
    h = jnp.dot(params["w"], x)
    v = h.item()  # planted: R1
    return v


@jax.jit
def bad_branch(params, x):
    h = jnp.tanh(jnp.dot(params["w"], x))
    if h.sum() > 0:  # planted: R1
        h = -h
    return h


@jax.jit
def bad_float(params, x):
    h = jnp.dot(params["w"], x)
    scale = float(h)  # planted: R1
    return h * scale


def scan_body(carry, x):
    y = np.asarray(x)  # planted: R1
    return carry + 1, y


def run_scan(xs):
    return lax.scan(scan_body, 0, xs)


@jax.jit
def ok_none_guard(params, x):
    # `is None` never calls __bool__ on a tracer — must NOT be flagged
    h = jnp.dot(params["w"], x)
    if params.get("bias") is None:
        return h
    return h + params["bias"]
