"""Planted R11: unbounded queues and blocking get()/join() without timeouts
in serve/feed loops — the exact failure shapes that deadlock a microbatcher
or turn overload into silent unbounded buffering. Clean twins: bounded
construction, timeout-polled gets with a liveness check, join(timeout=...),
and a reasoned disable on a deliberately unbounded drained mailbox."""

import queue
import threading


def unbounded_admission_queue():
    q = queue.Queue()  # planted: R11
    return q


def blocking_consumer_loop(worker_alive):
    q = queue.Queue(maxsize=8)
    while True:
        item = q.get()  # planted: R11
        if item is None:
            return


def join_without_timeout(run):
    q = queue.Queue(maxsize=4)
    t = threading.Thread(target=run, args=(q,))
    t.start()
    t.join()  # planted: R11
    return q


# ---------------------------------------------------------------- clean twins

def bounded_polling_consumer(stop):
    q = queue.Queue(maxsize=8)
    t = threading.Thread(target=stop.wait)
    t.start()
    while True:
        try:
            item = q.get(timeout=0.2)  # bounded poll + liveness check
        except queue.Empty:
            if not t.is_alive():
                raise RuntimeError("producer died without its sentinel")
            continue
        if item is None:
            break
    t.join(timeout=5)  # bounded join: a wedged worker surfaces, not hangs


def nonblocking_get(q):
    while True:
        try:
            return q.get(block=False)
        except queue.Empty:
            return None


def drained_result_mailbox(n_workers):
    # jaxcheck: disable=R11 (result mailbox, not an admission queue: exactly n_workers puts happen and the caller drains all of them before returning)
    box = queue.Queue()
    return box
