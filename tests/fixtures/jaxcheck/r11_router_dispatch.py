"""Planted R11, router-shaped: the dispatch-loop failure modes a replica
fleet invites — an unbounded cross-replica dispatch queue, a blocking wait
for attempt completions inside the dispatch loop, and joining a hedge worker
without a timeout (one wedged replica then hangs the whole router's
shutdown). Clean twins: the real router's shapes — bounded mailbox,
timeout-polled waits with a stop check, bounded join."""

import queue
import threading


def unbounded_dispatch_queue():
    dispatch_q = queue.Queue()  # planted: R11
    return dispatch_q


def router_dispatch_loop(replicas):
    dispatch_q = queue.Queue(maxsize=64)
    while True:
        req = dispatch_q.get()  # planted: R11
        if req is None:
            return
        replicas[0].submit(req)


def hedge_worker_shutdown(hedge_loop):
    t = threading.Thread(target=hedge_loop)
    t.start()
    t.join()  # planted: R11
    return t


# ---------------------------------------------------------------- clean twins

def bounded_dispatch_loop(replicas, stop):
    dispatch_q = queue.Queue(maxsize=64)
    while True:
        try:
            req = dispatch_q.get(timeout=0.05)  # bounded poll + stop check
        except queue.Empty:
            if stop.is_set():
                return
            continue
        replicas[0].submit(req)


def hedge_worker_bounded_shutdown(hedge_loop):
    t = threading.Thread(target=hedge_loop, daemon=True)
    t.start()
    t.join(timeout=5)  # a wedged hedge worker surfaces, never hangs stop()
    return t
