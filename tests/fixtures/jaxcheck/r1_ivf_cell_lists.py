"""Planted R1: per-shard IVF cell-list re-materialization inside a jit.

The sharded cell layout (index/layout.build_sharded_cells) gathers each
shard's owned cell rows into fixed-capacity slabs — a host-side surgery over
the kmeans assignment (np.flatnonzero per cell, python loop over shards).
Dragging that under a jitted scorer "to fuse the layout with the scan" pulls
jax.device_get / np.asarray into trace, where the data-dependent flatnonzero
either breaks tracing or pins a host sync into every dispatch. The clean
twin does what the real builder does: host layout OUTSIDE any trace, then a
jitted scorer over finished device slabs.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_score_with_inline_layout(q, emb, assign, cell):
    owned = np.flatnonzero(np.asarray(assign) == cell)  # planted: R1
    rows = jax.device_get(emb)[owned]  # planted: R1
    return jnp.asarray(rows) @ q


def _gather_cell_rows(emb, assign, cell):
    # reachable from the jitted caller below: the host materialization is a
    # bug anywhere trace can reach, not only under the decorator itself
    owned = np.flatnonzero(np.asarray(assign) == cell)  # planted: R1
    return owned


@jax.jit
def bad_score_via_helper(q, emb, assign, cell):
    owned = _gather_cell_rows(emb, assign, cell)
    return emb[jnp.asarray(owned)] @ q


# -------------------------------------------------------------- clean twin

def build_cell_slab(emb, assign, cell, cap):
    """Host-side layout OUTSIDE any trace — the shape build_sharded_cells
    actually uses: materialize the owned rows on the host, pad to the fixed
    cell capacity, and hand the jitted scorer a finished device slab."""
    owned = np.flatnonzero(np.asarray(assign) == cell)[:cap]
    slab = np.zeros((cap, emb.shape[1]), np.float32)
    slab[: owned.size] = np.asarray(emb)[owned]
    return _score_slab(jnp.asarray(slab), owned.size)


def _score_slab(slab, n_owned):
    return _scorer(slab, jnp.asarray(n_owned))


@jax.jit
def _scorer(slab, n_owned):
    mask = jnp.arange(slab.shape[0]) < n_owned
    return jnp.where(mask[:, None], slab, 0.0)
