"""S1 fixture (ISSUE 19): the shadow scorer's exact re-score is a device
dispatch from a BACKGROUND thread — on a sharded service it is a collective
program, so dispatching it without the process-wide mesh dispatch lock can
interleave with the batcher's own collective and deadlock the mesh (the
r16 bug class serve/shadow.py exists to never reintroduce). Clean twins
wrap the re-score in `with dispatch_lock():` — the sanctioned idiom the
real ShadowScorer._score uses.
"""

import threading

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from dae_rnn_news_recommendation_tpu.parallel.mesh import dispatch_lock

MESH_AXIS_NAMES = ("data",)


def make_exact_rescore(mesh):
    """Factory: the exact full-scan top-k as a collective (never
    dispatches it here)."""

    def local(emb, q):
        scores = emb @ q.T
        return jax.lax.psum(scores, "data")

    return shard_map(local, mesh=mesh,
                     in_specs=(P("data", None), P(None, None)),
                     out_specs=P(None, None))


class ShadowRescorer:
    """One scorer shared with the batcher thread; offer() feeds a queue the
    scorer thread drains (it owns a lock -> thread-shared)."""

    def __init__(self, mesh):
        self._lock = threading.Lock()
        self._fn = make_exact_rescore(mesh)

    def rescore(self, emb, q):
        return self._fn(emb, q)               # planted: S1

    def rescore_guarded(self, emb, q):
        # the real shadow path: a background-thread collective serializes
        # with every other dispatcher in the process
        with dispatch_lock():
            return self._fn(emb, q)


def shadow_worker(mesh, emb, q):
    """Runs on the scorer thread (see start_shadow) — bare dispatch."""
    fn = make_exact_rescore(mesh)
    return fn(emb, q)                         # planted: S1


def shadow_worker_guarded(mesh, emb, q):
    fn = make_exact_rescore(mesh)
    with dispatch_lock():
        return fn(emb, q)


def start_shadow(mesh, emb, q):
    t = threading.Thread(target=shadow_worker, args=(mesh, emb, q),
                         daemon=True)
    t.start()
    u = threading.Thread(target=shadow_worker_guarded,
                         args=(mesh, emb, q), daemon=True)
    u.start()
    return t, u
