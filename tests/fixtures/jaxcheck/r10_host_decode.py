"""Planted R10: host-side per-batch decompression in feed/training loops —
the decode sits on the critical path between batches and serializes the feed
on host CPU. The wire-format design (ops/wire.py) packs once at ingest and
expands on DEVICE inside the jitted step. Clean twins: decode hoisted out of
the loop, device-side unpack, and a reasoned codec-accounting disable."""

import pickle
import zlib

import numpy as np


def decompressing_feed_loop(compressed_batches, step):
    for blob in compressed_batches:
        batch = pickle.loads(zlib.decompress(blob))  # planted: R10
        step(batch)


def unpackbits_in_train_loop(packed_batches, step):
    for words in packed_batches:
        bits = np.unpackbits(words, axis=-1)  # planted: R10
        step(bits)


def host_unpack_generator(wires):
    from dae_rnn_news_recommendation_tpu.ops import wire

    # a generator body re-runs per yielded batch: per-batch host decode
    for w in wires:
        yield wire.unpack_wire_host(w)  # planted: R10


# ---------------------------------------------------------------- clean twins

def hoisted_decode(blob, step):
    batches = pickle.loads(zlib.decompress(blob))  # once, outside the loop
    for batch in batches:
        step(batch)


def device_side_unpack_loop(packed_batches, step):
    # the sanctioned shape: ship packed words, expand inside the jitted step
    for packed in packed_batches:
        step(packed)  # step calls ops/wire.unpack_wire under jit


def codec_accounting_sweep(pool, modes, pack_csr_wire, wire_nbytes):
    sizes = {}
    for mode in modes:
        # jaxcheck: disable=R10 (codec accounting, not a feed: each pack is measured for bytes/article, never shipped)
        sizes[mode] = wire_nbytes(pack_csr_wire(pool, mode=mode))
    return sizes
