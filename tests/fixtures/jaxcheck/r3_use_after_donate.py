"""Planted R3 violations: reading a name after its buffer was donated."""

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.train.step import make_train_step


def train_step(params, opt_state, key, batch):
    return params, opt_state


step = jax.jit(train_step, donate_argnums=(0, 1))


def read_after_donate(params, opt_state, key, batch):
    new_params, new_opt = step(params, opt_state, key, batch)
    norm = jnp.linalg.norm(params["w"])  # planted: R3
    return new_params, new_opt, norm


def donate_in_loop(params, opt_state, key, batches):
    local_step = jax.jit(train_step, donate_argnums=(0, 1))
    for batch in batches:
        out = local_step(params, opt_state, key, batch)  # planted: R3,R5
    return out


def factory_donated_batch(config, optimizer, init, batches):
    fit_step = make_train_step(config, optimizer, donate_batch=True)
    params, opt_state = init()
    key = jax.random.PRNGKey(0)
    stash = batches[0]
    params, opt_state, metrics = fit_step(params, opt_state, key, stash)
    x = stash["x"]  # planted: R3
    return params, x


def rebound_ok(params, opt_state, key, batches):
    # donated names rebound from the call's results every iteration: clean
    for batch in batches:
        key, sub = jax.random.split(key)
        params, opt_state = step(params, opt_state, sub, batch)
    return params, opt_state
