"""Planted R14: metric/counter mutation inside jit-traced code — the Python
side effect runs once at trace time, so the counter freezes while the
compiled function keeps executing. Clean twins: the same metrics recorded on
the HOST side of the dispatch boundary (around the jitted call, never
inside), and host-side threading.Event.set() showing the token filter leaves
non-metric `.set()` alone."""

import threading

import jax
import jax.numpy as jnp


class _FakeRegistry:
    def counter(self, name):
        raise NotImplementedError

    def gauge(self, name):
        raise NotImplementedError

    def histogram(self, name):
        raise NotImplementedError


metrics = _FakeRegistry()


@jax.jit
def encode_and_count(x):
    metrics.counter("batches").inc()  # planted: R14
    return jnp.tanh(x)


def scored(x, registry):
    c = registry.counter("scored")
    y = jnp.dot(x, x)
    c.inc()  # planted: R14
    return y


scored_jit = jax.jit(scored)


@jax.jit
def observe_latency(x, batch_histogram):
    y = jnp.sum(x)
    batch_histogram.observe(0.0)  # planted: R14
    return y


@jax.jit
def stamp_gauge(x):
    metrics.gauge("queue_depth").set(0)  # planted: R14
    return x * 2


# ---------------------------------------------------------------- clean twins

def encode_batch_host(x):
    """Metrics on the host side of the dispatch boundary: increment AROUND
    the jitted call, never inside it."""
    y = _encode_compiled(x)
    metrics.counter("batches").inc()  # host side: runs per call, honestly
    return y


@jax.jit
def _encode_compiled(x):
    return jnp.tanh(x)


def drain_queue(stop_event):
    # threading.Event.set() is not a metric mutation: no metric token on the
    # receiver, nothing bound from a registry factory
    stop_event.set()


class _Worker:
    def __init__(self):
        self._stop = threading.Event()

    def shutdown(self):
        self._stop.set()  # host-side lifecycle, stays clean

    def run_step(self, x):
        y = _encode_compiled(x)
        metrics.histogram("batch_ms").observe(1.0)  # host side, after fetch
        return y
