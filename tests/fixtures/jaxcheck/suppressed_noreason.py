"""A reasonless disable: the suppression itself is a finding (rule SUP) and
the underlying violation is still reported."""

import jax


def double_draw(key):
    a = jax.random.normal(key, (4,))
    # jaxcheck: disable=R5
    b = jax.random.uniform(key, (4,))  # planted: R5
    return a, b
