"""Planted R2 violation: an autotuner candidate race timing each config
with a bare perf_counter pair — no fence, no warmup, so the "winner" is
whichever candidate's dispatch returned fastest (plus whoever paid the
compile), not the fastest kernel.

Named r2_tuning_* so it falls inside R2's tuning scope (the real search
loop, dae_rnn_news_recommendation_tpu/tuning/search.py, lives by the same
law). The clean twin routes each candidate through `devprof.measure`, which
R2 knows is a fence: every timed iteration ends with a `device_fence` on
the call's result, and warmup absorbs the per-config compile.
"""

import time

from dae_rnn_news_recommendation_tpu.telemetry import devprof


def race_wrong(make_fn, candidates):
    # each candidate's first call compiles inside the timed region and the
    # clock reads before the device finishes: dispatch time, not kernel time
    best, best_dt = None, None
    for cfg in candidates:
        fn = make_fn(cfg)
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0  # planted: R2
        if best_dt is None or dt < best_dt:
            best, best_dt = cfg, dt
    return best, best_dt


def race_right(make_fn, candidates):
    # the fenced best-of-N timer per candidate IS the fence for this region
    t0 = time.perf_counter()
    results = [(cfg, devprof.measure(make_fn(cfg), n=3, warmup=1))
               for cfg in candidates]
    host_total = time.perf_counter() - t0
    best, result = min(results, key=lambda cr: cr[1].best_ms)
    return best, result, host_total
