"""C3 fixture: untimed queue get / event wait / device sync inside a lock
body pins the lock for the full wait. Clean twins: timed waits outside the
lock, and the sanctioned `cv.wait()` shape (waiting on the held condition
variable releases it).
"""

import queue
import threading

import jax


class ResultMailbox:
    """A worker fills the queue; readers drain it under the state lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._q = queue.Queue(maxsize=8)
        self._ready = threading.Event()

    def take(self):
        with self._lock:
            return self._q.get()          # planted: C3

    def await_ready(self):
        with self._lock:
            self._ready.wait()            # planted: C3

    def score_sync(self, fn, batch):
        with self._lock:
            out = fn(batch)
            jax.block_until_ready(out)    # planted: C3
            return out

    # ---- clean twins ----

    def take_clean(self):
        if not self._ready.is_set():
            self._ready.wait(timeout=0.5)
        return self._q.get(timeout=0.5)

    def wait_for(self, pred):
        # untimed wait on the HELD condition variable is the sanctioned
        # shape: cv.wait releases the lock for the duration
        with self._cv:
            while not pred():
                self._cv.wait()
