"""Planted R1: shard-recovery host re-materialization inside a jitted region.

The shard-recovery path (serve/corpus.recover_shards) re-materializes a lost
shard from the HOST mirror — a D2H/H2D round trip that must live on the host
side of the dispatch boundary. Jitting the recovery "for speed" drags the
materialization under trace, where np.asarray / jax.device_get either break
tracing outright or pin a silent sync into every dispatch. The clean twin
keeps the host surgery outside the jit and hands the jitted installer a
finished device value.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_recover_shard(emb, mirror, lo, hi):
    host = jax.device_get(emb)  # planted: R1
    patch = np.asarray(mirror)  # planted: R1
    return jnp.asarray(host).at[lo:hi].set(jnp.asarray(patch[lo:hi]))


def _rematerialize(mirror, lo, hi):
    # reachable from the jitted caller below: host-sync is a bug anywhere
    # trace can reach, not just under the decorator itself
    rows = np.asarray(mirror[lo:hi])  # planted: R1
    return rows


@jax.jit
def bad_recover_via_helper(emb, mirror, lo, hi):
    patch = _rematerialize(mirror, lo, hi)
    return emb.at[lo:hi].set(patch)


# -------------------------------------------------------------- clean twin

def recover_shard(emb, mirror, lo, hi):
    """Host-side surgery OUTSIDE any trace: materialize the mirror rows on
    the host, then hand the jitted installer a finished device value — the
    shape serve/corpus.recover_shards actually uses (mesh.rebuild_shards is
    pure transfers; only the install is compiled)."""
    patch = jnp.asarray(np.asarray(mirror[lo:hi]))
    return _install(emb, patch, lo)


@jax.jit
def _install(emb, patch, lo):
    return jax.lax.dynamic_update_slice(emb, patch, (lo, 0))
