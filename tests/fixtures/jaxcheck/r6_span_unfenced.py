"""Planted R6 violation: a fence=False span wrapping device work with no
fence in its body — the span's duration measures enqueue, not compute.

The clean twins below must NOT be flagged: default-fenced spans, fence=False
spans that end with their own device fetch, host-only regions, and spans in
bench-style code whose timed region fences via the span itself.
"""

import time

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu import telemetry


def bad_unfenced_span(x):
    with telemetry.span("step", fence=False):  # planted: R6
        y = jnp.dot(x, x)
    return y


def ok_default_fence(x):
    # fence defaults to True: span exit runs device_fence on the nominated out
    with telemetry.span("step") as sp:
        y = sp.fence_on(jnp.dot(x, x))
    return y


def ok_explicit_fetch(x):
    # fence=False, but the body ends with its own host round trip
    with telemetry.span("step", fence=False):
        y = jnp.dot(x, x)
        host = jax.device_get(y)
    return host


def ok_host_only(rows):
    # fence=False on genuinely host-only work is exactly what the flag is for
    with telemetry.span("feed/pad", fence=False):
        padded = [r + [0] * (8 - len(r)) for r in rows]
    return padded


def ok_span_fences_timer(step, params, batch):
    # R2 companion: the default-fenced span inside the timed region counts as
    # the region's fence (no raw device_get needed)
    t0 = time.perf_counter()
    with telemetry.span("bench/steps") as sp:
        for _ in range(10):
            params = step(params, batch)
        sp.fence_on(params)
    dt = time.perf_counter() - t0
    return params, dt
