"""S5 fixture: an out_spec of `P()` promises the output is identical on
every shard — the runtime reads ONE shard's buffer as the answer. Only a
reducing collective makes that true; returning a per-shard value through
`P()` silently serves shard 0's partial result. This is the static twin of
shard_map's check_rep, which the Pallas paths must disable. Clean twin:
psum before returning through `P()`.
"""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MESH_AXIS_NAMES = ("data",)


def make_mean(mesh):
    def local(x):
        local_sum = x.sum()         # per-shard partial, never reduced
        return local_sum                         # planted: S5

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P())


def make_mean_clean(mesh):
    def local(x):
        total = jax.lax.psum(x.sum(), "data")
        return total

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P())


def make_stats(mesh):
    def local(x):
        total = jax.lax.psum(x.sum(), "data")
        peak = x.max()              # position 1 claims P() but never reduced
        return total, peak                       # planted: S5

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=(P(), P()))
