"""Planted R2 violation: a timed region with no fetch fence before the read.

Named bench_* so it falls inside R2's bench/evidence scope. The fenced twin
below must NOT be flagged.
"""

import time

import jax


def timed_unfenced(step, params, batch):
    t0 = time.perf_counter()
    for _ in range(10):
        params = step(params, batch)
    dt = time.perf_counter() - t0  # planted: R2
    return params, dt


def timed_fenced(step, params, batch):
    t0 = time.perf_counter()
    for _ in range(10):
        params = step(params, batch)
    jax.device_get(params)
    dt = time.perf_counter() - t0
    return params, dt


def timed_span_fenced(step, params, batch):
    # a default-fenced telemetry span counts as the region's fence: its exit
    # runs a real device fetch (telemetry/tracer.py), so no raw device_get
    from dae_rnn_news_recommendation_tpu import telemetry

    t0 = time.perf_counter()
    with telemetry.span("bench/steps") as sp:
        for _ in range(10):
            params = step(params, batch)
        sp.fence_on(params)
    dt = time.perf_counter() - t0
    return params, dt


def watchdog_ok(deadline):
    # time.monotonic is this repo's watchdog convention, outside R2's scope
    start = time.monotonic()
    while time.monotonic() - start < deadline:
        pass
