"""Planted R9: broad except handlers in training/feed loops that swallow the
error — the silent-truncation class (a dead feed or failed step vanishes and
the fit 'completes' on partial data). Clean twins: re-raise, narrow clause,
recording handlers, and a reasoned surface-on-consumer disable."""

import warnings


def swallowing_feed_loop(batches, step):
    n = 0
    for batch in batches:
        try:
            step(batch)
            n += 1
        except Exception:  # planted: R9
            pass  # batch silently dropped — the fit lies about coverage
    return n


def swallowing_try_around_loop(batches, step):
    try:
        for batch in batches:
            step(batch)
    except BaseException:  # planted: R9
        return None  # the whole tail of the epoch vanishes


def bare_except_in_loop(batches, step):
    for batch in batches:
        try:
            step(batch)
        except:  # noqa: E722  # planted: R9
            continue


# ---------------------------------------------------------------- clean twins

def reraising_loop(batches, step):
    for batch in batches:
        try:
            step(batch)
        except Exception:
            raise  # surfaces immediately: clean


def recording_loop(batches, step):
    for batch in batches:
        try:
            step(batch)
        except Exception as e:
            warnings.warn(f"step failed: {e}", RuntimeWarning)  # recorded


def narrow_clause_loop(batches, step):
    for batch in batches:
        try:
            step(batch)
        except KeyError:
            continue  # a narrow, deliberate clause is not R9's business


def no_loop_guard(fn):
    try:
        return fn()
    except Exception:
        return None  # not in/around a loop: import-guard class, exempt


def worker_surface_on_consumer(batches, step, err):
    for batch in batches:
        try:
            step(batch)
        # jaxcheck: disable=R9 (worker thread cannot re-raise; err[] is re-raised by the consumer)
        except BaseException as e:
            err.append(e)
