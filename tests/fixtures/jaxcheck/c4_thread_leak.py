"""C4 fixture: a started non-daemon Thread with no join anywhere in the
module leaks — interpreter shutdown blocks on it forever. Clean twins:
daemon=True at construction, daemon-ness assigned post-construction, and a
non-daemon worker joined with a timeout.
"""

import threading


def start_collector(sink):
    worker = threading.Thread(target=sink.drain)   # planted: C4
    worker.start()
    return worker


# ---- clean twins ----

def start_collector_daemon(sink):
    t = threading.Thread(target=sink.drain, daemon=True)
    t.start()
    return t


def start_collector_flagged(sink):
    helper = threading.Thread(target=sink.drain)
    helper.daemon = True
    helper.start()
    return helper


def run_bounded(sink):
    t = threading.Thread(target=sink.drain)
    t.start()
    sink.close()
    t.join(timeout=5.0)
