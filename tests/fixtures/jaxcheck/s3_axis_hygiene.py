"""S3 fixture: axis-name hygiene. A collective naming an axis the enclosing
shard_map never binds, or a PartitionSpec naming an axis outside the mesh
vocabulary (MESH_AXIS_NAMES), is a typo XLA only reports at trace time.
Clean twins: literal axis matching the specs, and the variable-axis idiom
(axis flows through one parameter into specs and collectives alike).
"""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MESH_AXIS_NAMES = ("data", "model")


def make_row_sum(mesh):
    def local(x):
        return jax.lax.psum(x, "rows")           # planted: S3

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))


def make_row_sum_clean(mesh):
    def local(x):
        return jax.lax.psum(x, "data")

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))


def make_gather_clean(mesh, axis_name="data"):
    # variable-axis idiom: the same name threads specs and collectives
    def local(x):
        return jax.lax.all_gather(x, axis_name)

    return shard_map(local, mesh=mesh, in_specs=(P(axis_name, None),),
                     out_specs=P(None, axis_name))


def stale_layout():
    return P("batch", None)                      # planted: S3
