"""Planted R8 violations: the full [B, B, B] triplet cube, materialized by
combining rank-3 expands with different None-position signatures
(`dp[:, :, None] op dp[:, None, :]`). O(B^3) memory — the exact footprint
the blockwise/Pallas mining dispatch (ISSUE 5) exists to avoid.

The clean twins must NOT be flagged: rank-2 pairwise expands ([B,1] vs
[1,B], the O(B^2) idiom the repo keeps everywhere), and same-signature
rank-3 expands (no new axis is materialized by the combine).
"""

import jax.numpy as jnp


def bad_cube_distance(dp):
    # the canonical offender (ops/triplet.py:94 pre-dispatch)
    dist = -dp[:, :, None] + dp[:, None, :]  # planted: R8
    return jnp.sum(dist)


def bad_cube_mask_through_names(labels, valid):
    # signatures thread through simple name bindings
    eq = labels[None, :] == labels[:, None]
    i_eq_j = eq[:, :, None]
    i_eq_k = eq[:, None, :]
    valid_labels = i_eq_j & (~i_eq_k)  # planted: R8
    return valid_labels


def bad_cube_valid_chain(valid):
    # chained & over three one-hot expands: the first combine births the cube
    av = valid[:, None, None] & valid[None, :, None] & valid[None, None, :]  # planted: R8
    return av


def bad_cube_compare(dp):
    # a broadcasting comparison materializes the same cube as arithmetic
    harder = dp[:, :, None] > dp[:, None, :]  # planted: R8
    return jnp.sum(harder)


def ok_pairwise_rank2(labels, valid):
    # [B,1] vs [1,B] expands: O(B^2), the repo's standard pairwise idiom
    eq = labels[:, None] == labels[None, :]
    vv = valid[:, None] & valid[None, :]
    return eq & vv


def ok_same_signature(x, y):
    # both operands expand the SAME axis: result is [B, B, 1], not the cube
    return x[:, :, None] - y[:, :, None]


def ok_expand_times_scalar(dp):
    # rank-3 expand combined with a scalar: no second signature, no cube
    return dp[:, :, None] * 2.0
