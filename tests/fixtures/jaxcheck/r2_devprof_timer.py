"""Planted R2 violation: a perf_counter pair around device work with no
fence and no warmup — measures dispatch, not compute.

Named r2_devprof_* so it falls inside R2's devprof scope (the device timer's
own module lives by the fencing law it enforces). The clean twin routes the
same workload through `devprof.measure`, which R2 knows is a fence: every
timed iteration ends with a `device_fence` on the call's result.
"""

import time

from dae_rnn_news_recommendation_tpu.telemetry import devprof


def timed_wrong(fn, x):
    # no fence between dispatch and the clock read, no warmup to absorb the
    # compile: the delta is dispatch latency plus XLA compile time
    t0 = time.perf_counter()
    out = fn(x)
    dt = time.perf_counter() - t0  # planted: R2
    return out, dt


def timed_right(fn, x):
    # the fenced best-of-N timer IS the fence for this region
    t0 = time.perf_counter()
    result = devprof.measure(fn, (x,), n=3, warmup=1)
    host_total = time.perf_counter() - t0
    return result, host_total
