"""Planted R5 violations: PRNG keys consumed twice without a split."""

import jax


def double_draw(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # planted: R5
    return a + b


def loop_reuse(key, xs):
    total = 0.0
    for x in xs:
        total += float(jax.random.normal(key, ()))  # planted: R5
    return total


def split_ok(key, xs):
    out = []
    for x in xs:
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, ()))
    return out


def indexed_ok(key, xs):
    # keys[i] varies per iteration: a fresh key each pass, not a reuse
    keys = jax.random.split(key, len(xs))
    out = []
    for i in range(len(xs)):
        out.append(jax.random.normal(keys[i], ()))
    return out
