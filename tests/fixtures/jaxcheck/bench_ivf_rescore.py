"""Planted R12 + R2 in the shapes the IVF retrieval path tempts you into.

The clustered rescore (ops/ivf_topk.py) dots fp32 queries against int8 cell
panels — drop `preferred_element_type` and the MXU accumulates the partial
sums in the narrow dtype, which silently breaks the probes=n_cells bitwise
parity the index is gated on (R12). And the bench corner's qps race is a
timed region over enqueued dispatches — read the clock without fencing on
the replies and the "speedup" measures dispatch exit, not compute (R2).
Named bench_* so it falls inside R2's bench/evidence scope. The fenced /
widened twins below must NOT be flagged.
"""

import time

import jax
import jax.numpy as jnp


def int8_cell_rescore_narrow(q, cell_panel):
    panel8 = cell_panel.astype(jnp.int8)
    dims = (((1,), (1,)), ((), ()))
    return jax.lax.dot_general(q, panel8, dims)  # planted: R12


def centroid_scan_bf16_narrow(h, centroids):
    c16 = centroids.astype(jnp.bfloat16)
    return h @ c16.T  # planted: R12


def ivf_bench_phase_unfenced(ivf_fn, params, slot, queries):
    t0 = time.perf_counter()
    scores, idx = ivf_fn(params, slot.emb, slot.valid, slot.scales,
                         slot.ivf, queries)
    dt = time.perf_counter() - t0  # planted: R2
    return scores, idx, dt


# ---------------------------------------------------------------- clean twins

def int8_cell_rescore_widened(q, cell_panel):
    # the ops/ivf_topk.py idiom: fp32 accumulation over the int8 panel
    panel8 = cell_panel.astype(jnp.int8)
    return jax.lax.dot_general(q, panel8, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def cell_panel_fp32_cast_is_not_low(q, cell_panel):
    # widening cast: accumulation dtype == operand dtype == fp32, no hazard
    return q @ cell_panel.astype(jnp.float32).T


def ivf_bench_phase_fenced(ivf_fn, params, slot, queries):
    t0 = time.perf_counter()
    scores, idx = ivf_fn(params, slot.emb, slot.valid, slot.scales,
                         slot.ivf, queries)
    jax.device_get(idx)
    dt = time.perf_counter() - t0
    return scores, idx, dt
