"""C5 fixture: resolving waiter futures / invoking subscriber callbacks
while holding the component's lock hands the lock to foreign code — a woken
waiter or callback that calls back in deadlocks instantly. Clean twin:
snapshot under the lock, resolve/invoke after releasing it (the
ReplyFuture._set shape).
"""

import threading


class Broadcast:
    """Fans one published value out to futures and callbacks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futures = []
        self._callbacks = []
        self._value = None

    def add_future(self, fut):
        with self._lock:
            self._futures.append(fut)

    def add_callback(self, cb):
        with self._lock:
            self._callbacks.append(cb)

    def publish(self, value):
        with self._lock:
            self._value = value
            for fut in self._futures:
                fut.set_result(value)      # planted: C5
            for cb in self._callbacks:
                cb(value)                  # planted: C5


class BroadcastClean:
    """Same fan-out, foreign code only ever runs with the lock released."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futures = []
        self._callbacks = []
        self._value = None

    def add_future(self, fut):
        with self._lock:
            self._futures.append(fut)

    def add_callback(self, cb):
        with self._lock:
            self._callbacks.append(cb)

    def publish(self, value):
        with self._lock:
            self._value = value
            futures = list(self._futures)
            callbacks = list(self._callbacks)
            self._futures.clear()
        for fut in futures:
            fut.set_result(value)
        for cb in callbacks:
            cb(value)
