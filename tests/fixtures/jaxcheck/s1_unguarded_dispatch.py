"""S1 fixture: a shard_map program is a collective — every mesh device must
rendezvous on the same program — so dispatching one from a thread-reachable
site without the process-wide mesh dispatch lock can interleave two
programs' per-device arrivals and deadlock (the r16 bug class). Clean twins
wrap the dispatch in `with dispatch_lock():`.
"""

import threading

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from dae_rnn_news_recommendation_tpu.parallel.mesh import dispatch_lock

MESH_AXIS_NAMES = ("data",)


def make_gather(mesh):
    """Factory: returns a shard_map-built callable (never dispatches it)."""

    def local(x):
        return jax.lax.psum(x, "data")

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))


class ShardedScorer:
    """Serving replicas share one scorer; a refresh thread swaps state, so
    its methods run concurrently (it owns a lock -> thread-shared)."""

    def __init__(self, mesh):
        self._lock = threading.Lock()
        self._fn = make_gather(mesh)

    def lookup(self, x):
        return self._fn(x)                    # planted: S1

    def lookup_guarded(self, x):
        # the sanctioned idiom: serialize collective dispatch process-wide
        with dispatch_lock():
            return self._fn(x)


def refresh_worker(mesh, x):
    """Runs on a spawned thread (see start_refresh) — bare dispatch."""
    fn = shard_map(lambda v: v * 2, mesh=mesh, in_specs=(P("data", None),),
                   out_specs=P("data", None))
    return fn(x)                              # planted: S1


def refresh_worker_guarded(mesh, x):
    fn = shard_map(lambda v: v * 2, mesh=mesh, in_specs=(P("data", None),),
                   out_specs=P("data", None))
    with dispatch_lock():
        return fn(x)


def start_refresh(mesh, x):
    t = threading.Thread(target=refresh_worker, args=(mesh, x), daemon=True)
    t.start()
    u = threading.Thread(target=refresh_worker_guarded, args=(mesh, x),
                         daemon=True)
    u.start()
    return t, u
