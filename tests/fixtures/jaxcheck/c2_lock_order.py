"""C2 fixture: two module-level locks taken in opposite nesting order by two
code paths — one thread in each order deadlocks. Clean twin uses a single
global order for its own pair of locks.
"""

import threading

swap_lock = threading.Lock()
stats_lock = threading.Lock()


def publish(version, stats):
    with swap_lock:
        with stats_lock:       # planted: C2
            stats["version"] = version


def snapshot(stats):
    with stats_lock:
        with swap_lock:        # planted: C2
            return dict(stats)


# ---- clean twin: same nesting depth, one consistent order ----

order_lock = threading.Lock()
inner_lock = threading.Lock()


def update(d, k, v):
    with order_lock:
        with inner_lock:
            d[k] = v


def read(d, k):
    with order_lock:
        with inner_lock:
            return d.get(k)
