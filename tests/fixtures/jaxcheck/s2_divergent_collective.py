"""S2 fixture: a collective under a branch predicated on PER-SHARD data —
shards disagreeing on the predicate skip the rendezvous and the rest hang.
Clean twin: the predicate is a shard-invariant closure value and the
collective runs unconditionally.
"""

import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

MESH_AXIS_NAMES = ("data",)


def make_accumulate(mesh):
    def local(x):
        shard_max = x.max()        # concrete per-shard value at trace time
        if shard_max > 0:
            total = jax.lax.psum(x, "data")      # planted: S2
        else:
            total = x
        return total

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))


def make_accumulate_clean(mesh, reduce_it):
    def local(x):
        # shard-invariant config predicate, collective unconditional
        total = jax.lax.psum(x, "data")
        if reduce_it:
            return total
        return x

    return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                     out_specs=P("data", None))
