"""A real violation silenced by a suppression WITH a reason: clean file."""

import jax


def antithetic_pair(key):
    a = jax.random.normal(key, (4,))
    # jaxcheck: disable=R5 (deliberate identical draw: the pair must share the key)
    b = jax.random.uniform(key, (4,))
    return a, b
