"""Planted R7 violations: per-step host conversion of jitted-step outputs
inside a training loop.

The carried-state signature (`params, opt_state, metrics = step(params,
opt_state, ...)`) marks an async-dispatch pipeline; `float()`/`np.asarray`
on the returned metrics inside the loop forces a device sync every step.

The clean twins must NOT be flagged: accumulating device metrics and
fetching once per epoch with jax.device_get (converting only after that
fetch), and an eval-style loop with no carried state.
"""

import jax
import numpy as np

from dae_rnn_news_recommendation_tpu.train.step import (
    make_eval_step, make_train_step)


def bad_float_per_step(config, optimizer, params, opt_state, key, batches):
    step = make_train_step(config, optimizer)
    history = []
    for batch in batches:
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        history.append(float(metrics["cost"]))  # planted: R7
    return params, history


def bad_asarray_per_step(config, optimizer, params, opt_state, key, batches):
    step = make_train_step(config, optimizer)
    costs = []
    for batch in batches:
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        costs.append(np.asarray(metrics["cost"]))  # planted: R7
    return params, costs


def bad_float_in_comprehension(config, optimizer, params, opt_state, key,
                               batches):
    # converting via a dict comprehension over the step's metrics is the
    # same per-step sync, one call deep
    step = make_train_step(config, optimizer)
    rows = []
    for batch in batches:
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        rows.append({k: float(v) for k, v in metrics.items()})  # planted: R7
    return params, rows


def ok_batched_fetch(config, optimizer, params, opt_state, key, batches):
    # the sanctioned pattern: device metrics accumulate in the loop, ONE
    # jax.device_get per epoch, host conversion only after that fetch
    step = make_train_step(config, optimizer)
    device_metrics = []
    for batch in batches:
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        device_metrics.append(metrics)
    host_metrics = jax.device_get(device_metrics)
    return params, [float(m["cost"]) for m in host_metrics]


def ok_eval_no_carried_state(config, params, batches):
    # no carried state: each call is independent, nothing pipelines behind
    # the conversion (the repo's validation loop) — out of R7's scope
    eval_step = make_eval_step(config)
    total = 0.0
    for batch in batches:
        metrics = eval_step(params, batch)
        total += float(metrics["cost"])
    return total
