"""Planted R4 violations: per-iteration retrace/recompile hazards."""

import jax
import numpy as np


def encode(params, n):
    return params


enc = jax.jit(encode)
enc_static = jax.jit(encode, static_argnums=(1,))


def sweep(params):
    for i in range(10):
        out = enc(params, i)  # planted: R4
    return out


def stack_ragged(feeds, group):
    return [np.stack(feeds[g:g + group]) for g in range(0, len(feeds), group)]  # planted: R4


def sweep_static_ok(params):
    # static_argnums(1) hashes the scalar into the cache key: only flagged
    # if the cache churns, which a static analyzer can't see — not reported
    for i in range(10):
        out = enc_static(params, i)
    return out


def stack_guarded_ok(feeds, group):
    assert len(feeds) % group == 0
    return [np.stack(feeds[g:g + group]) for g in range(0, len(feeds), group)]
