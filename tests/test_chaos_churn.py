"""Churn-soak acceptance (ISSUE 10 tentpole): seeded fault plans replayed
through bootstrap -> ingest cycles -> fine-tune-then-rebuild must keep the
served corpus on the health-gated, version-monotonic path, and recover to
BITWISE-identical params on CPU — including a preemption landing INSIDE the
warm-start fine-tune (r05 crash-exact resume, one level up the stack).

Tier-1 runs the two hardest families as a smoke (swap-crash rollback and
mid-fine-tune preemption); the full 6-family soak is `-m slow` and runs in
the evidence pipeline.
"""

import pytest

import jax

from dae_rnn_news_recommendation_tpu.reliability.chaos_churn import (
    chaos_churn_soak, churn_fault_plan, run_churn_plan)


def _assert_plan_ok(res):
    seed = res.plan["seed"]
    assert res.ok, f"plan {seed}: {res.detail}"
    if jax.default_backend() == "cpu":
        assert res.bitwise, (
            f"plan {seed}: recovered but not bitwise ({res.detail})")
    assert res.injected, f"plan {seed} landed no faults (nothing tested)"
    # version monotonicity: promoted versions count 1..n with no gaps, and
    # the chaos session promoted exactly what the fault-free reference did
    assert res.versions == list(range(1, len(res.versions) + 1))
    assert res.versions == res.ref_versions, (
        f"plan {seed}: chaos promoted {res.versions} "
        f"vs reference {res.ref_versions}")
    assert res.n_finetunes >= 1  # the closing rebuild actually ran


def test_swap_crash_rolls_back_then_reconverges(tmp_path):
    # seed 3 -> refresh.swap fatal: the append dies inside the corpus, the
    # ledger records ok=False with version unchanged, and the replayed cycle
    # promotes the version the reference session promoted
    res = run_churn_plan(churn_fault_plan(3), str(tmp_path))
    _assert_plan_ok(res)
    assert res.rollbacks >= 1, "swap crash never surfaced as a rollback"
    assert res.restarts >= 1


def test_preemption_inside_finetune_resumes_crash_exact(tmp_path):
    # seed 5 -> train.step preempt mid-fine-tune: the restarted fine-tune
    # closure must compute remaining epochs from the newest verified
    # checkpoint and land on the reference digest bitwise
    res = run_churn_plan(churn_fault_plan(5), str(tmp_path))
    _assert_plan_ok(res)
    assert any(e["site"] == "train.step" for e in res.injected)
    assert res.restarts >= 1


@pytest.mark.slow
def test_full_churn_soak_covers_every_fault_family(tmp_path):
    out = chaos_churn_soak(str(tmp_path), seeds=range(6))
    results = out["results"]
    assert out["all_ok"] and out["n_ok"] == 6
    for res in results:
        _assert_plan_ok(res)
    sites = {(e["site"], e["kind"]) for r in results for e in r.injected}
    assert {("refresh.ingest", "fatal"), ("refresh.encode", "fatal"),
            ("refresh.encode", "transient"), ("refresh.swap", "fatal"),
            ("refresh.finetune", "fatal"),
            ("train.step", "preempt")} <= sites
    # both recovery modes were exercised across the soak
    assert any(r.restarts > 0 for r in results)
    assert any(r.retries for r in results)
