"""Fleet chaos soak (ISSUE 12): seeded faults x Zipf replay x a mid-trace
staged rollout, audited fleet-wide.

Tier-1 smoke runs the two families the acceptance criteria name explicitly:
seed 0 (a replica killed mid-rollout — skipped, re-homed, still exactly one
outcome per request) and seed 2 (a fleet-stage swap failure after the canary
promoted — the whole fleet reverts to the pre-canary version). The full
six-family soak is the slow tier.
"""

import pytest

from dae_rnn_news_recommendation_tpu.fleet import (chaos_fleet_soak,
                                                   fleet_fault_plan,
                                                   run_fleet_plan)


def test_fault_plans_are_seed_deterministic_and_cover_families():
    plans = [fleet_fault_plan(seed, 24) for seed in range(6)]
    again = [fleet_fault_plan(seed, 24) for seed in range(6)]
    assert [p.specs for p in plans] == [p.specs for p in again]
    sites = [spec.site for p in plans for spec in p.specs]
    assert plans[0].specs == ()   # family 0 is the harness kill directive
    assert sites.count("refresh.swap") == 2
    assert "fleet.route" in sites and "fleet.hedge" in sites
    assert "fleet.replica" in sites


@pytest.mark.parametrize("seed", [0, 2])
def test_fleet_plan_smoke(seed):
    """The acceptance-criteria pair: replica kill mid-rollout (0) and
    fleet-stage gate failure -> whole-fleet rollback (2). Each plan's own
    audits carry the invariants (exactly-one outcome fleet-wide, <=2 live
    corpus versions, rollout honesty); the test asserts they all came back
    clean plus the family-defining facts."""
    result = run_fleet_plan(seed, n_requests=24)
    assert result.ok, result.detail
    assert result.n_unresolved == 0
    assert len(result.versions_seen) <= 2
    assert result.injected, "the planned fault never fired"
    if seed == 0:
        assert result.skipped, "the killed replica was not skipped"
        assert result.rollout_ok
    else:
        assert not result.rollout_ok
        assert result.reverted, "gate failure must revert the fleet"


@pytest.mark.slow
def test_chaos_fleet_soak_all_families():
    out = chaos_fleet_soak(seeds=(0, 1, 2, 3, 4, 5), n_requests=48)
    assert out["all_ok"], [
        (r.seed, r.detail) for r in out["results"] if not r.ok]
