"""Fleet chaos soak (ISSUE 12): seeded faults x Zipf replay x a mid-trace
staged rollout, audited fleet-wide.

Tier-1 smoke runs the two families the acceptance criteria name explicitly:
seed 0 (a replica killed mid-rollout — skipped, re-homed, still exactly one
outcome per request) and seed 2 (a fleet-stage swap failure after the canary
promoted — the whole fleet reverts to the pre-canary version). The full
six-family soak is the slow tier.
"""

import pytest

from dae_rnn_news_recommendation_tpu.fleet import (chaos_fleet_soak,
                                                   fleet_fault_plan,
                                                   run_fleet_plan)


def test_fault_plans_are_seed_deterministic_and_cover_families():
    plans = [fleet_fault_plan(seed, 24) for seed in range(6)]
    again = [fleet_fault_plan(seed, 24) for seed in range(6)]
    assert [p.specs for p in plans] == [p.specs for p in again]
    sites = [spec.site for p in plans for spec in p.specs]
    assert plans[0].specs == ()   # family 0 is the harness kill directive
    assert sites.count("refresh.swap") == 2
    assert "fleet.route" in sites and "fleet.hedge" in sites
    assert "fleet.replica" in sites


@pytest.mark.parametrize("seed", [0, 2])
def test_fleet_plan_smoke(seed):
    """The acceptance-criteria pair: replica kill mid-rollout (0) and
    fleet-stage gate failure -> whole-fleet rollback (2). Each plan's own
    audits carry the invariants (exactly-one outcome fleet-wide, <=2 live
    corpus versions, rollout honesty); the test asserts they all came back
    clean plus the family-defining facts."""
    result = run_fleet_plan(seed, n_requests=24)
    assert result.ok, result.detail
    assert result.n_unresolved == 0
    assert len(result.versions_seen) <= 2
    assert result.injected, "the planned fault never fired"
    if seed == 0:
        assert result.skipped, "the killed replica was not skipped"
        assert result.rollout_ok
    else:
        assert not result.rollout_ok
        assert result.reverted, "gate failure must revert the fleet"


@pytest.mark.slow
def test_chaos_fleet_soak_all_families():
    out = chaos_fleet_soak(seeds=(0, 1, 2, 3, 4, 5), n_requests=48)
    assert out["all_ok"], [
        (r.seed, r.detail) for r in out["results"] if not r.ok]


# --------------------------------------------- observability (ISSUE 14)

def test_plan_fires_the_matching_slo_alert():
    """Alert attribution: the zero-tolerance spec wired to seed 2's fault
    family (a fleet-stage swap failure) must fire — and because that family
    reverts the whole fleet, the revert spec fires with it. The plan's own
    audits already require the EXPECTED alert; this pins the mapping at the
    test layer too."""
    from dae_rnn_news_recommendation_tpu.fleet import FAMILY_ALERTS

    result = run_fleet_plan(2, n_requests=24)
    assert result.ok, result.detail
    assert FAMILY_ALERTS[2 % 6] in result.slo_alerts
    assert "rollout-aborts" in result.slo_alerts  # the abort precedes it


def test_fault_free_reference_replay_is_silent():
    """The other half of the attribution contract: the same fleet, trace,
    and mid-trace rollout with NO injector must complete clean with zero
    SLO alerts — otherwise the chaos assertions above prove nothing."""
    from dae_rnn_news_recommendation_tpu.fleet import run_fleet_reference

    out = run_fleet_reference(1, n_requests=24)
    assert out["ok"], out["detail"]
    assert out["alerts"] == []


def test_observability_dump_joins_in_report_fleet(tmp_path):
    """End-to-end join: a chaos plan dumps fleet_observability.json, the
    report CLI auto-detects it next to a trace and renders the request
    table + SLO alerts + ledger cross-check keyed by request id."""
    import json

    from dae_rnn_news_recommendation_tpu.telemetry.report import report

    dump = tmp_path / "fleet_observability.json"
    result = run_fleet_plan(2, n_requests=24, dump_path=str(dump))
    assert result.ok, result.detail
    assert dump.exists()
    (tmp_path / "trace.json").write_text('{"traceEvents": []}')
    text, code = report(str(tmp_path / "trace.json"))
    assert code == 0
    assert "serving fleet:" in text
    assert "flt-" in text                  # request ids in the join table
    assert "rollout-aborts" in text        # the seed-2 alert rendered
    as_json, code = report(str(tmp_path / "trace.json"), as_json=True)
    fleet = json.loads(as_json)["fleet"]
    assert fleet["ledger"]["join_ok"]      # table rows == ledger submissions
    assert fleet["counters"]["fleet_reverts"] == 1
