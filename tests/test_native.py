"""Native (C++) components: csr packer and StarSpace-style baseline trainer.

Test strategy follows the reference's oracle pattern (SURVEY.md §4): every
native path is checked against a pure-Python/NumPy re-implementation.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from dae_rnn_news_recommendation_tpu import native
from dae_rnn_news_recommendation_tpu.baselines import (
    StarSpaceConfig, embed_docs, export_fasttext_format, train_starspace)
from dae_rnn_news_recommendation_tpu.baselines.starspace import tokens_from_csr
from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import pad_csr_batch


def _rand_csr(rng, n, f, density=0.05):
    return sp.random(n, f, density=density, format="csr", dtype=np.float32,
                     random_state=np.random.RandomState(rng.integers(1 << 30)))


def test_native_library_builds():
    """The build rules require real native components — the library must load
    on this image (g++ is baked in), not silently fall back."""
    assert native.load() is not None


def _pad_py(rows, k=None, k_multiple=64, index_dtype=np.uint16, binary=False):
    """The original pure-Python packer, kept verbatim as the oracle."""
    rows = rows.tocsr()
    b, f = rows.shape
    pad_index = f if binary else 0
    if f + (1 if binary else 0) > np.iinfo(index_dtype).max + 1:
        index_dtype = np.uint32
    nnz = np.diff(rows.indptr)
    kk = int(nnz.max(initial=1)) if k is None else int(k)
    kk = max(k_multiple, int(np.ceil(kk / k_multiple) * k_multiple))
    indices = np.full((b, kk), pad_index, index_dtype)
    values = None if binary else np.zeros((b, kk), np.float32)
    for i in range(b):
        lo, hi = rows.indptr[i], rows.indptr[i + 1]
        n = min(hi - lo, kk)
        indices[i, :n] = rows.indices[lo : lo + n].astype(index_dtype)
        if not binary:
            values[i, :n] = rows.data[lo : lo + n]
    return {"indices": indices, "values": values, "k": kk}


@pytest.mark.parametrize("binary", [False, True])
@pytest.mark.parametrize("f", [500, 100_000])  # uint16 and uint32 index paths
def test_native_packer_matches_python_oracle(binary, f):
    rng = np.random.default_rng(0)
    m = _rand_csr(rng, 257, f, density=0.03)
    if binary:
        m.data[:] = 1.0
    got = pad_csr_batch(m, binary=binary)
    want = _pad_py(m, binary=binary)
    assert got["k"] == want["k"]
    assert got["indices"].dtype == want["indices"].dtype
    np.testing.assert_array_equal(got["indices"], want["indices"])
    if binary:
        assert got["values"] is None and want["values"] is None
    else:
        np.testing.assert_array_equal(got["values"], want["values"])


def test_native_packer_truncates_and_pads():
    # k smaller than a row's nnz -> truncation to first k; empty row -> all pad
    m = sp.csr_matrix(np.array([[1, 2, 3, 4], [0, 0, 0, 0]], np.float32))
    out = pad_csr_batch(m, k=2, k_multiple=2)
    np.testing.assert_array_equal(out["indices"],
                                  [[0, 1], [0, 0]])
    np.testing.assert_array_equal(out["values"],
                                  [[1, 2], [0, 0]])


def _toy_corpus(rng, n=120, vocab=60, n_labels=3, words_per_doc=8):
    """Separable corpus: each label owns a vocab slice."""
    per = vocab // n_labels
    labels = rng.integers(0, n_labels, n).astype(np.int32)
    rows, cols = [], []
    for i, y in enumerate(labels):
        ws = y * per + rng.integers(0, per, words_per_doc)
        rows.extend([i] * words_per_doc)
        cols.extend(ws.tolist())
    docs = sp.csr_matrix(
        (np.ones(len(rows), np.float32), (rows, cols)), shape=(n, vocab))
    return docs, labels


@pytest.mark.parametrize("force_numpy", [False, True])
def test_starspace_learns_separable_corpus(force_numpy):
    """Training error must drop and learned embeddings must rank same-label
    docs above other-label docs (hinge loss semantics, train.log:32-118 shows
    the real binary's error dropping 0.078 -> 0.0008)."""
    rng = np.random.default_rng(1)
    docs, labels = _toy_corpus(rng)
    config = StarSpaceConfig(dim=16, epochs=12, neg=5, threads=2, seed=3)
    out = train_starspace(docs, labels, config=config,
                          force_numpy=force_numpy)
    errs = out["epoch_errors"]
    assert len(errs) == config.epochs
    assert errs[-1] < errs[0] * 0.5, errs

    emb = embed_docs(docs, out["word_emb"])
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8)
    sim = emb @ emb.T
    np.fill_diagonal(sim, 0.0)
    same = labels[:, None] == labels[None, :]
    np.fill_diagonal(same, False)
    assert sim[same].mean() > sim[~same].mean() + 0.2


def test_starspace_early_stopping_restores_best():
    rng = np.random.default_rng(2)
    docs, labels = _toy_corpus(rng, n=80)
    vdocs, vlabels = _toy_corpus(rng, n=40)
    config = StarSpaceConfig(dim=8, epochs=40, neg=3, threads=1, patience=3,
                             seed=5)
    out = train_starspace(docs, labels, vdocs, vlabels, config=config)
    errs = out["epoch_errors"]
    # early stop may trigger before all epochs ran
    assert len(errs) <= config.epochs
    assert out["best_val_error"] == pytest.approx(min(errs), abs=1e-9)


def test_embed_docs_native_matches_numpy():
    rng = np.random.default_rng(3)
    docs = _rand_csr(rng, 50, 40, density=0.2)
    word_emb = rng.normal(size=(40, 6)).astype(np.float32)
    got = embed_docs(docs, word_emb)
    docs_csr = docs.tocsr()
    for i in range(50):
        cols = docs_csr.indices[docs_csr.indptr[i]:docs_csr.indptr[i + 1]]
        want = (word_emb[cols].mean(axis=0) if len(cols)
                else np.zeros(6, np.float32))
        np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-6)


def test_fasttext_format_roundtrip(tmp_path):
    docs = sp.csr_matrix(np.array([[1, 0, 1], [0, 1, 0]], np.float32))
    vocab = {0: "alpha", 1: "beta", 2: "gamma"}
    tokens = tokens_from_csr(docs, vocab)
    assert tokens == [["alpha", "gamma"], ["beta"]]
    path = tmp_path / "train.txt"
    export_fasttext_format(tokens, ["b", "e"], path)
    lines = path.read_text().splitlines()
    assert lines == ["alpha gamma __label__b", "beta __label__e"]
