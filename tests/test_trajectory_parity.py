"""End-to-end training-trajectory parity against an independent-framework oracle.

BASELINE.json's north star demands "triplet loss parity 1e-4 vs TF1 CPU @ epoch
50". TF 1.12 doesn't exist in this environment, so the stand-in oracle is a
from-scratch torch (CPU, autograd) reimplementation of the reference's training
semantics — same modified encoder H = f(xW+b) − f(b) (reference
autoencoder.py:389), tied decode (:411), batch_all/batch_hard mining over dot
products with the reference's exact mask/softplus/data_weight formulas
(triplet_loss_utils.py:79-259, quirks included), weighted cross-entropy
(:262-277), and TF1 optimizer semantics (adagrad accumulator 0.1,
autoencoder.py:444-477) — fed IDENTICAL initial parameters and full-batch data.

Fifty epochs of the jitted JAX step vs fifty epochs of torch autograd must agree
on every epoch's cost. Measured divergence is ~1e-7 relative in float32 (two
independent autodiff systems, different reduction orders); the assertion uses
1e-5 — an order of magnitude inside the 1e-4 north star.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

# torch is the independent oracle, not a framework dependency — skip cleanly in
# environments without it (repo convention, cf. tests/test_tb_writer.py)
torch = pytest.importorskip("torch")

from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
from dae_rnn_news_recommendation_tpu.train import make_optimizer
from dae_rnn_news_recommendation_tpu.train.step import make_train_step

N, F, D = 128, 64, 8
ALPHA, LR, EPOCHS = 1.0, 0.5, 50
EPS = 1e-16


def _data():
    rng = np.random.default_rng(0)
    x = (rng.uniform(size=(N, F)) < 0.25).astype(np.float32)
    labels = rng.integers(0, 4, N).astype(np.int32)
    return x, labels


def _jax_trajectory(strategy, opt_name, x_np, labels_np, p0):
    cfg = DAEConfig(n_features=F, n_components=D, enc_act_func="sigmoid",
                    dec_act_func="sigmoid", loss_func="cross_entropy",
                    corr_type="none", corr_frac=0.0, triplet_strategy=strategy,
                    alpha=ALPHA, matmul_precision="highest")
    opt = make_optimizer(opt_name, LR)
    step = make_train_step(cfg, opt, donate=False)
    params = {k: jnp.asarray(v) for k, v in p0.items()}
    state = opt.init(params)
    batch = {"x": jnp.asarray(x_np), "labels": jnp.asarray(labels_np),
             "row_valid": jnp.ones(N, jnp.float32)}
    costs = []
    for _ in range(EPOCHS):
        params, state, m = step(params, state, jax.random.PRNGKey(0), batch)
        costs.append(float(m["cost"]))
    return np.array(costs)


def _torch_batch_all(dp, lab):
    dist = -dp[:, :, None] + dp[:, None, :]
    ne = ~torch.eye(N, dtype=torch.bool)
    distinct = ne[:, :, None] & ne[:, None, :] & ne[None, :, :]
    leq = lab[None, :] == lab[:, None]
    vmask = (distinct & leq[:, :, None] & ~leq[:, None, :]).float()
    t_loss = ((torch.nn.functional.softplus(dist) * vmask).sum()
              / torch.clamp(vmask.sum(), min=EPS))
    dw = vmask.sum((1, 2)) + vmask.sum((0, 1)) + vmask.sum((0, 2))
    return t_loss, dw


def _torch_batch_hard(dp, lab):
    # reference quirks preserved: hardest-pos via row-max shift
    # (triplet_loss_utils.py:227-231), zero-masked hardest-neg max (:240),
    # float-equality tie double-count in data_weight (:251-253)
    ne = ~torch.eye(N, dtype=torch.bool)
    leq = lab[None, :] == lab[:, None]
    mask_ap = (ne & leq).float()
    mask_an = (~leq).float()
    max_row = dp.max(dim=1, keepdim=True).values
    hardest_pos = (dp + max_row * (1.0 - mask_ap)).min(dim=1, keepdim=True).values
    hardest_neg = (mask_an * dp).max(dim=1, keepdim=True).values
    dist = torch.clamp(hardest_neg - hardest_pos, min=0.0)
    count = (dist > 0.0).float()
    eq_pos = (dp == hardest_pos).float()
    eq_neg = (dp == hardest_neg).float()
    dw = (count.squeeze(1) + (count * eq_pos).sum(0) + (count * eq_neg).sum(0))
    t_loss = ((torch.nn.functional.softplus(dist) * count).sum()
              / torch.clamp(count.sum(), min=EPS))
    return t_loss, dw



def _torch_tower(t, x):
    """One DAE tower pass + clamped cross-entropy per-row loss (the reference
    semantics both parity tests share)."""
    W, bh, bv = t["W"], t["bh"], t["bv"]
    h = torch.sigmoid(x @ W + bh) - torch.sigmoid(bh)
    y = torch.sigmoid(h @ W.T + bv)
    per_row = -(x * torch.log(torch.clamp(y, min=EPS))
                + (1 - x) * torch.log(torch.clamp(1 - y, min=EPS))).sum(1)
    return h, per_row


def _torch_sgd(t, lr):
    with torch.no_grad():
        for k in t:
            t[k] -= lr * t[k].grad
            t[k].grad = None


def _torch_trajectory(strategy, opt_name, x_np, labels_np, p0):
    t = {k: torch.tensor(v, dtype=torch.float32, requires_grad=True)
         for k, v in p0.items()}
    acc = {k: torch.full_like(t[k], 0.1) for k in t}  # TF1 adagrad accumulator
    x = torch.tensor(x_np)
    lab = torch.tensor(labels_np.astype(np.int64))
    mine = _torch_batch_all if strategy == "batch_all" else _torch_batch_hard
    costs = []
    for _ in range(EPOCHS):
        h, per_row = _torch_tower(t, x)
        t_loss, dw = mine(h @ h.T, lab)
        ae = (per_row * dw).sum() / torch.clamp(dw.sum(), min=EPS)
        cost = ae + ALPHA * t_loss
        cost.backward()
        if opt_name == "ada_grad":
            with torch.no_grad():
                for k in t:
                    g = t[k].grad
                    acc[k] += g * g
                    t[k] -= LR * g / (torch.sqrt(acc[k]) + 1e-7)
                    t[k].grad = None
        else:
            _torch_sgd(t, LR)
        costs.append(float(cost.detach()))
    return np.array(costs)


@pytest.mark.parametrize("opt_name", ["gradient_descent", "ada_grad"])
@pytest.mark.parametrize("strategy", ["batch_all", "batch_hard"])
def test_fifty_epoch_trajectory_parity(strategy, opt_name):
    x_np, labels_np = _data()
    cfg = DAEConfig(n_features=F, n_components=D, triplet_strategy=strategy)
    p0 = {k: np.asarray(v)
          for k, v in init_params(jax.random.PRNGKey(0), cfg).items()}
    ours = _jax_trajectory(strategy, opt_name, x_np, labels_np, p0)
    oracle = _torch_trajectory(strategy, opt_name, x_np, labels_np, p0)
    assert np.isfinite(ours).all() and np.isfinite(oracle).all()
    # the training must actually move (a frozen model would trivially "agree")
    assert ours[-1] < ours[0]
    np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)


def test_fifty_epoch_triplet_tower_parity():
    """Same oracle treatment for the precomputed-triplet objective (reference
    autoencoder_triplet.py:296-315): three weight-sharing towers, summed plain
    reconstruction losses + alpha * mean softplus(-(dot(a,p) - dot(a,n)))."""
    from dae_rnn_news_recommendation_tpu.train.step import (
        triplet_loss_and_metrics)

    rng = np.random.default_rng(1)
    trip = {n: (rng.uniform(size=(N, F)) < 0.25).astype(np.float32)
            for n in ("org", "pos", "neg")}
    cfg = DAEConfig(n_features=F, n_components=D, enc_act_func="sigmoid",
                    dec_act_func="sigmoid", loss_func="cross_entropy",
                    corr_type="none", corr_frac=0.0, triplet_strategy="none",
                    alpha=ALPHA, matmul_precision="highest")
    p0 = {k: np.asarray(v)
          for k, v in init_params(jax.random.PRNGKey(3), cfg).items()}

    opt = make_optimizer("gradient_descent", LR)
    step = make_train_step(cfg, opt, loss_fn=triplet_loss_and_metrics,
                           donate=False)
    params = {k: jnp.asarray(v) for k, v in p0.items()}
    state = opt.init(params)
    batch = {**{k: jnp.asarray(v) for k, v in trip.items()},
             "row_valid": jnp.ones(N, jnp.float32)}
    ours = []
    for _ in range(EPOCHS):
        params, state, m = step(params, state, jax.random.PRNGKey(0), batch)
        ours.append(float(m["cost"]))

    t = {k: torch.tensor(v, dtype=torch.float32, requires_grad=True)
         for k, v in p0.items()}
    tx = {k: torch.tensor(v) for k, v in trip.items()}
    oracle = []
    for _ in range(EPOCHS):
        hs, ae = {}, 0.0
        for n in ("org", "pos", "neg"):
            hs[n], per_row = _torch_tower(t, tx[n])
            ae = ae + per_row.mean()
        margin = (hs["org"] * hs["pos"] - hs["org"] * hs["neg"]).sum(1)
        cost = ae + ALPHA * torch.nn.functional.softplus(-margin).mean()
        cost.backward()
        _torch_sgd(t, LR)
        oracle.append(float(cost.detach()))

    ours, oracle = np.array(ours), np.array(oracle)
    assert ours[-1] < ours[0]
    np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)
