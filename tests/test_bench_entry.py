"""The driver-facing evidence surfaces must never bitrot: bench.py's measurement
functions and __graft_entry__.entry() are exercised here on CPU with tiny
workloads (round 1 lost its headline record to exactly this kind of rot)."""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest
import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load("bench_under_test", os.path.join(REPO, "bench.py"))


TINY = dict(batch=64, n_batches=2, warmup=1, prefetch=1,
            train_batch=32, train_steps=2, train_warmup=1,
            stream_rows=128, stream_batch=64, stream_epochs=1,
            serve_corpus=64, serve_requests=8,
            churn_corpus=64, churn_batch=16, churn_cycles=2,
            fleet_corpus=64, fleet_requests=24, fleet_replicas=3)


def test_bench_functions_produce_finite_rates(bench):
    """Every measurement the child can run — including the TPU-only branches
    (via_dense race on shared feeds, large-batch train override) — must
    execute: a bug in a TPU-only path would otherwise surface only on
    hardware, burning a scarce tunnel window."""
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=bench.F, n_components=bench.D, enc_act_func="sigmoid",
        dec_act_func="sigmoid", loss_func="cross_entropy", corr_type="none",
        corr_frac=0.0, triplet_strategy="none", compute_dtype="bfloat16")
    params = jax.device_put(init_params(jax.random.PRNGKey(0), config))

    feeds = bench._pack_encode_feeds(TINY)
    r_enc = bench._bench_encode(jax, params, config, TINY, feeds=feeds)
    r_dense = bench._bench_encode(jax, params, config, TINY, via_dense=True,
                                  feeds=feeds)
    r_scan = bench._bench_encode(jax, params, config, TINY, feeds=feeds,
                                 scan_group=2)
    r_train = bench._bench_train(jax, TINY)
    r_big = bench._bench_train(jax, TINY, batch_override=48, steps_override=2)
    wl = bench._fit_workload(jax, TINY)
    r_stream = bench._bench_train_stream(jax, TINY, workload=wl)
    r_pipe, pipe_stats = bench._bench_fit_pipelined(jax, TINY, workload=wl)
    for r in (r_enc, r_dense, r_scan, r_train, r_big, r_stream, r_pipe):
        assert np.isfinite(r) and r > 0.0
    # the diagnostic the pipelined figure ships with must be populated
    assert 0.0 <= pipe_stats.feed_stall_fraction <= 1.0
    assert pipe_stats.batches > 0 and pipe_stats.epoch_s > 0


def test_stack_groups_drops_ragged_tail(bench):
    """The scanned-dispatch grouping must emit uniformly-shaped stacks only —
    a ragged tail group would recompile inside the timed section (ADVICE r05)."""
    feeds = [np.full((4, 8), i, np.uint16) for i in range(7)]
    grouped = bench._stack_groups(feeds, 3)
    assert len(grouped) == 2  # 7 // 3 — the 1-batch tail is dropped
    assert all(g.shape == (3, 4, 8) for g in grouped)
    np.testing.assert_array_equal(grouped[1][0], feeds[3])
    # exact divisibility keeps everything
    assert len(bench._stack_groups(feeds[:6], 3)) == 2


def test_bench_encode_scan_rejects_ragged_n_batches(bench):
    """A scan_group that does not divide n_batches must fail fast at the
    assert, not silently recompile mid-measurement."""
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=bench.F, n_components=bench.D, enc_act_func="sigmoid",
        dec_act_func="sigmoid", loss_func="cross_entropy", corr_type="none",
        corr_frac=0.0, triplet_strategy="none", compute_dtype="bfloat16")
    params = jax.device_put(init_params(jax.random.PRNGKey(0), config))
    sz = dict(TINY, n_batches=3)
    with pytest.raises(AssertionError, match="must divide n_batches"):
        bench._bench_encode(jax, params, config, sz, feeds=([], []),
                            scan_group=2)


def test_bench_churn_produces_finite_figures(bench):
    """The churn phase must land its metrics at tiny sizes — a bug here would
    otherwise surface only inside a live bench round."""
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=bench.F, n_components=bench.D, enc_act_func="sigmoid",
        dec_act_func="sigmoid", loss_func="cross_entropy", corr_type="none",
        corr_frac=0.0, triplet_strategy="none", compute_dtype="bfloat16")
    params = jax.device_put(init_params(jax.random.PRNGKey(0), config))
    out = bench._bench_churn(jax, params, config, TINY)
    assert out["churn_encode_articles_per_sec"] > 0
    assert out["refresh_swap_p95_ms"] >= out["refresh_swap_p50_ms"] > 0
    assert out["churn_final_version"] == 2 + TINY["churn_cycles"]
    assert out["churn_final_rows"] == (
        TINY["churn_corpus"] + (1 + TINY["churn_cycles"]) * TINY["churn_batch"])


def test_bench_fleet_produces_finite_figures(bench):
    """The fleet phase must land every gated metric at tiny sizes, and the
    hedged run must beat the unhedged one at the tail: the straggler replica's
    lag is deterministic and the hedge delay cap sits well under it, so
    'hedging reduces p99' is a designed property here, not a coin flip."""
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=bench.F, n_components=bench.D, enc_act_func="sigmoid",
        dec_act_func="sigmoid", loss_func="cross_entropy", corr_type="none",
        corr_frac=0.0, triplet_strategy="none", compute_dtype="bfloat16")
    params = jax.device_put(init_params(jax.random.PRNGKey(0), config))
    out = bench._bench_fleet(jax, params, config, TINY)
    assert out["fleet_qps"] > 0
    assert out["fleet_p99_ms"] >= out["fleet_p95_ms"] >= out["fleet_p50_ms"] > 0
    assert 0.0 <= out["fleet_shed_rate"] <= 1.0
    assert out["rollout_inflight_p95_ms"] > 0
    # directional hedging claim: the hedged p99 must undercut the unhedged
    # p99 on the same trace (the straggler adds a fixed 750ms tail; hedges
    # re-issue after <=400ms to a fast replica)
    assert out["fleet_p99_ms"] < out["fleet_p99_ms_no_hedge"], out
    assert out["fleet_hedge_p99_improvement_ms"] > 0
    assert out["fleet_hedges"] > 0
    # the mid-replay rollout must have promoted every replica exactly once
    assert all(v == 2 for v in out["fleet_versions"].values()), out


def test_bench_size_tables_consistent(bench):
    """Every platform's workload dict must carry the same knobs (a missing key
    in one table would only explode on that platform, i.e. at round time)."""
    keys = {k: set(v) for k, v in bench.SIZES.items()}
    assert keys["tpu"] == keys["cpu"] == set(TINY)


def test_run_child_kills_silent_child_fast(bench):
    """A child that hangs without heartbeating (the dead-tunnel failure mode:
    mute at backend init) must be killed by the no-progress watchdog in
    ~noprogress_timeout, not the overall timeout."""
    import time

    t0 = time.monotonic()
    rc, out, err, killed = bench._run_child(
        [sys.executable, "-c", "import time; time.sleep(600)"],
        dict(os.environ), overall_timeout=500, noprogress_timeout=3)
    assert killed and "no heartbeat" in killed
    assert rc is None
    assert time.monotonic() - t0 < 60


def test_run_child_passes_through_healthy_child(bench):
    """Heartbeating children run to completion; stdout is captured in full."""
    prog = ("import sys, time\n"
            "for i in range(3):\n"
            "    print('hb', file=sys.stderr, flush=True); time.sleep(0.2)\n"
            "print('{\"metric\": \"x\", \"value\": 1}')\n")
    rc, out, err, killed = bench._run_child(
        [sys.executable, "-c", prog], dict(os.environ),
        overall_timeout=60, noprogress_timeout=30)
    assert killed is None and rc == 0
    assert '{"metric"' in out
    assert "hb" in err


def test_run_child_overall_timeout(bench):
    """A child that heartbeats forever still dies at the overall cap."""
    import time

    prog = ("import sys, time\n"
            "while True:\n"
            "    print('hb', file=sys.stderr, flush=True); time.sleep(0.5)\n")
    t0 = time.monotonic()
    rc, out, err, killed = bench._run_child(
        [sys.executable, "-c", prog], dict(os.environ),
        overall_timeout=4, noprogress_timeout=30)
    assert killed and "overall timeout" in killed
    assert time.monotonic() - t0 < 60


def _fake_time(sleep_fn):
    """A time-module stand-in swapped in for bench's module-global `time`
    binding. NEVER patch time.sleep on the real module: bench.time IS the
    global time module, and background threads from other tests (orbax
    writers, prefetchers) call time.sleep concurrently — patching the global
    pollutes sleep recordings and makes those threads spin."""
    import time as _real
    from types import SimpleNamespace

    return SimpleNamespace(sleep=sleep_fn, monotonic=_real.monotonic,
                           perf_counter=_real.perf_counter, time=_real.time)


def _scripted_main(bench, monkeypatch, tmp_path, probe_script, child_script,
                   sidecar=None):
    """Run bench.main() with _tpu_alive/_run_child replaced by scripted fakes
    and the TPU sidecar redirected to an isolated tmp path (optionally
    pre-populated with `sidecar`). Returns (rc, printed_metric_lines,
    child_call_envs). Script lengths are exact: an extra probe or child call
    raises StopIteration and fails the test, so the attempt sequencing is
    enforced, not just observed."""
    probes = iter(probe_script)
    children = iter(child_script)
    envs = []

    side_path = str(tmp_path / "bench_tpu.json")
    if sidecar is not None:
        with open(side_path, "w") as f:
            json.dump(sidecar, f)
    monkeypatch.setattr(bench, "SIDECAR_PATH", side_path)
    monkeypatch.setattr(bench, "_tpu_alive", lambda attempt: next(probes))
    monkeypatch.setattr(bench, "time", _fake_time(lambda s: None))

    def fake_run_child(argv, env, overall_timeout, noprogress_timeout=None):
        envs.append(dict(env))
        return next(children)

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    printed = []
    monkeypatch.setattr("builtins.print",
                        lambda *a, **k: printed.append(" ".join(map(str, a))))
    rc = bench.main()
    metric_lines = [ln for ln in printed if ln.startswith('{"metric"')]
    return rc, metric_lines, envs


METRIC = '{"metric": "encode_articles_per_sec", "value": 1.0}'
TPU_METRIC = json.dumps({
    "metric": "encode_articles_per_sec", "value": 2_000_000.0,
    "unit": "articles/sec (tpu)", "vs_baseline": 10.0,
    "extra": {"platform": "tpu", "jax_version": "x", "device_kind": "TPU v5e"}})
SIDE = {"captured_utc": "2026-07-31T00:00:00+00:00", "git_rev": "cafe" * 10,
        "jax_version": "x", "device_kind": "TPU v5e",
        "record": json.loads(TPU_METRIC)}


def test_main_dead_tunnel_falls_back_to_cpu(bench, monkeypatch, tmp_path):
    """All probes fail -> no TPU child ever runs; the forced final attempt runs
    the CPU child and its metric line is the result (no sidecar captured yet)."""
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[False, False, False],       # attempt0: 1 probe; attempt1: 2
        child_script=[(0, METRIC + "\n", "", None)])
    assert rc == 0 and len(lines) == 1
    assert json.loads(lines[0]) == {**json.loads(METRIC), "live": True}
    assert len(envs) == 1 and envs[0].get("JAX_PLATFORMS") == "cpu"


def test_main_healthy_tunnel_first_try(bench, monkeypatch, tmp_path):
    """Probe passes -> one TPU child, its metric is printed, no fallback, and
    the record is persisted as the last-good TPU sidecar."""
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[True],
        child_script=[(0, "noise\n" + TPU_METRIC + "\n", "", None)])
    assert rc == 0 and len(lines) == 1
    assert json.loads(lines[0]) == {**json.loads(TPU_METRIC), "live": True}
    # exactly one child ran, and it was not the forced CPU fallback (which
    # SETS JAX_PLATFORMS=cpu; the ambient test env may already carry it)
    assert len(envs) == 1
    assert envs[0].get("JAX_PLATFORMS") == os.environ.get("JAX_PLATFORMS")
    with open(tmp_path / "bench_tpu.json") as f:
        side = json.load(f)
    assert side["record"] == json.loads(TPU_METRIC)
    assert side["device_kind"] == "TPU v5e" and side["captured_utc"]


def test_main_killed_child_retries_then_falls_back(bench, monkeypatch, tmp_path):
    """Attempt 0's child is killed by the watchdog; attempt 1's probes fail;
    the final CPU attempt still lands a number."""
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[True, False, False],
        child_script=[(None, "", "phase: train", "no heartbeat for 300s"),
                      (0, METRIC + "\n", "", None)])
    assert rc == 0 and len(lines) == 1
    assert json.loads(lines[0]) == {**json.loads(METRIC), "live": True}
    assert len(envs) == 2 and envs[1].get("JAX_PLATFORMS") == "cpu"


def test_main_cpu_fallback_upgraded_by_sidecar(bench, monkeypatch, tmp_path):
    """A CPU-only live run with a committed last-good TPU sidecar emits the
    TPU headline (value + vs_baseline), labeled with capture provenance, and
    carries the live CPU measurement in extra.live_fallback."""
    cpu_rec = ('{"metric": "encode_articles_per_sec", "value": 5000.0, '
               '"unit": "articles/sec (cpu)", "vs_baseline": 0.025, '
               '"extra": {"platform": "cpu"}}')
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[False, False, False],
        child_script=[(0, cpu_rec + "\n", "", None)],
        sidecar=SIDE)
    assert rc == 0 and len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] == 2_000_000.0 and rec["vs_baseline"] == 10.0
    assert rec["live"] is False  # mechanically marked as substituted
    assert "last-good TPU sidecar" in rec["unit"]
    assert "2026-07-31" in rec["unit"] and "cafecafec" in rec["unit"]
    assert rec["extra"]["live_fallback"] == json.loads(cpu_rec)
    assert rec["extra"]["tpu_sidecar"]["device_kind"] == "TPU v5e"


def test_main_total_failure_emits_zero_record(bench, monkeypatch, tmp_path):
    """Even when every attempt fails, ONE parseable zero-value record is
    emitted and rc is nonzero — the round record is never empty."""
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[True, True, True],
        child_script=[(1, "", "boom", None), (1, "", "boom", None),
                      (1, "", "boom", None)])
    assert rc == 1 and len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] == 0.0 and "metric" in rec


def test_main_total_failure_with_sidecar_still_lands_tpu(bench, monkeypatch,
                                                         tmp_path):
    """Total live failure + existing sidecar -> the TPU headline is still the
    round record, but rc is 2 and the record carries live=false so automation
    can detect that the live bench is broken (ADVICE r3)."""
    rc, lines, envs = _scripted_main(
        bench, monkeypatch, tmp_path,
        probe_script=[True, True, True],
        child_script=[(1, "", "boom", None), (1, "", "boom", None),
                      (1, "", "boom", None)],
        sidecar=SIDE)
    assert rc == 2 and len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["value"] == 2_000_000.0
    assert rec["live"] is False
    assert rec["extra"]["live_fallback"]["value"] == 0.0


def _scripted_capture(bench, monkeypatch, tmp_path, probe_script, child_script):
    """Like _scripted_main but driving capture_tpu_main (TPU-only, no CPU
    fallback). Returns (rc, printed_metric_lines, sleeps)."""
    probes = iter(probe_script)
    children = iter(child_script)
    sleeps = []

    monkeypatch.setattr(bench, "SIDECAR_PATH", str(tmp_path / "bench_tpu.json"))
    monkeypatch.setattr(bench, "_tpu_alive", lambda attempt: next(probes))
    monkeypatch.setattr(bench, "time", _fake_time(sleeps.append))
    monkeypatch.setattr(bench, "_run_child",
                        lambda *a, **k: next(children))
    printed = []
    monkeypatch.setattr("builtins.print",
                        lambda *a, **k: printed.append(" ".join(map(str, a))))
    rc = bench.capture_tpu_main()
    return rc, [ln for ln in printed if ln.startswith('{"metric"')], sleeps


def test_capture_tpu_success_writes_sidecar(bench, monkeypatch, tmp_path):
    rc, lines, sleeps = _scripted_capture(
        bench, monkeypatch, tmp_path,
        probe_script=[True],
        child_script=[(0, TPU_METRIC + "\n", "", None)])
    assert rc == 0 and len(lines) == 1
    assert json.loads(lines[0])["value"] == 2_000_000.0
    with open(tmp_path / "bench_tpu.json") as f:
        assert json.load(f)["record"]["extra"]["platform"] == "tpu"
    assert sleeps == []  # success: no backoff burned


def test_capture_tpu_failed_child_backs_off_then_retries(bench, monkeypatch,
                                                         tmp_path):
    """A probed-alive tunnel whose child dies mid-run (watchdog kill) must
    back off before the final attempt — not burn it seconds later."""
    rc, lines, sleeps = _scripted_capture(
        bench, monkeypatch, tmp_path,
        probe_script=[True, True],
        child_script=[(None, "", "mute", "no heartbeat for 300s"),
                      (0, TPU_METRIC + "\n", "", None)])
    assert rc == 0 and len(lines) == 1
    assert len(sleeps) == 1  # exactly one backoff between the two attempts
    assert os.path.exists(tmp_path / "bench_tpu.json")


def test_capture_tpu_dead_tunnel_gives_up_quietly(bench, monkeypatch, tmp_path):
    rc, lines, sleeps = _scripted_capture(
        bench, monkeypatch, tmp_path,
        probe_script=[False, False],
        child_script=[])
    assert rc == 1 and lines == []
    assert len(sleeps) == 1  # backoff before the second probe, none after
    assert not os.path.exists(tmp_path / "bench_tpu.json")


def test_attempt_child_tolerates_malformed_metric_line(bench, monkeypatch):
    monkeypatch.setattr(bench, "_run_child",
                        lambda *a, **k: (0, '{"metric" garbage\n', "", None))
    diags = []
    monkeypatch.setattr(bench, "_diag", lambda a, n: diags.append(n))
    assert bench._attempt_child(0, {}, 10) is None
    assert any("unparseable" in d for d in diags)


def test_roofline_accounting(bench):
    """Analytic FLOPs/bytes and TPU utilization figures: encode intensity ~1
    FLOP/byte (HBM-bound), train MFU computed against the chip peak."""
    roof = bench._roofline("tpu", "TPU v5 lite", encode_aps=2.0e6,
                           train_aps=1.0e5, train_batch=800)
    assert roof["encode_eff_flops_per_article"] == 2 * bench.NNZ_PER_ROW * bench.D
    assert roof["encode_hbm_bytes_per_article"] == (
        bench.NNZ_PER_ROW * bench.D * 2 + bench.D * 4)
    intensity = (roof["encode_eff_flops_per_article"]
                 / roof["encode_hbm_bytes_per_article"])
    assert 0.5 < intensity < 2.0
    assert roof["peak_bf16_tflops"] == 197.0
    # 2e6 aps * 200200 B = ~400 GB/s of 819 -> ~0.49
    assert 0.4 < roof["encode_hbm_utilization"] < 0.6
    assert roof["train_mfu"] == pytest.approx(
        1.0e5 * (12 * bench.F * bench.D + 6 * 800 * bench.D) / 197e12,
        rel=1e-3)
    # unknown chip or cpu -> analytic terms only, no utilization claims
    cpu_roof = bench._roofline("cpu", "cpu", 1.0, 1.0, 64)
    assert "train_mfu" not in cpu_roof and "peak_bf16_tflops" not in cpu_roof

    # the via_dense strategy sits on the MXU axis: 2*F*D real FLOPs against
    # ~4*F HBM bytes per article
    droof = bench._roofline("tpu", "TPU v5 lite", encode_aps=1.0e7,
                            train_aps=None, train_batch=800,
                            encode_strategy="via_dense (MXU)")
    assert droof["encode_eff_flops_per_article"] == 2 * bench.F * bench.D
    assert droof["encode_hbm_bytes_per_article"] == 4 * bench.F + 4 * bench.D
    assert "MXU" in droof["bound"]["encode"]
    # 1e7 aps * 10M FLOPs = 100 TFLOP/s of 197 -> ~0.51 MFU
    assert 0.4 < droof["encode_mfu"] < 0.6


def test_graft_entry_compiles():
    """entry() must return (jittable fn, example args) that actually compile
    and produce the flagship forward pass shapes."""
    mod = _load("graft_entry_under_test", os.path.join(REPO, "__graft_entry__.py"))
    fn, args = mod.entry()
    h, y = jax.jit(fn)(*args)
    params, x = args
    assert h.shape == (x.shape[0], 500)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(h)).all()
