"""The driver-facing evidence surfaces must never bitrot: bench.py's measurement
functions and __graft_entry__.entry() are exercised here on CPU with tiny
workloads (round 1 lost its headline record to exactly this kind of rot)."""

import importlib.util
import os
import sys

import numpy as np
import pytest
import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load("bench_under_test", os.path.join(REPO, "bench.py"))


TINY = dict(batch=64, n_batches=2, warmup=1, prefetch=1,
            train_batch=32, train_steps=2, train_warmup=1,
            stream_rows=128, stream_batch=64, stream_epochs=1)


def test_bench_functions_produce_finite_rates(bench):
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=bench.F, n_components=bench.D, enc_act_func="sigmoid",
        dec_act_func="sigmoid", loss_func="cross_entropy", corr_type="none",
        corr_frac=0.0, triplet_strategy="none", compute_dtype="bfloat16")
    params = jax.device_put(init_params(jax.random.PRNGKey(0), config))

    r_enc = bench._bench_encode(jax, params, config, TINY)
    r_train = bench._bench_train(jax, TINY)
    r_stream = bench._bench_train_stream(jax, TINY)
    for r in (r_enc, r_train, r_stream):
        assert np.isfinite(r) and r > 0.0


def test_bench_size_tables_consistent(bench):
    """Every platform's workload dict must carry the same knobs (a missing key
    in one table would only explode on that platform, i.e. at round time)."""
    keys = {k: set(v) for k, v in bench.SIZES.items()}
    assert keys["tpu"] == keys["cpu"] == set(TINY)


def test_graft_entry_compiles():
    """entry() must return (jittable fn, example args) that actually compile
    and produce the flagship forward pass shapes."""
    mod = _load("graft_entry_under_test", os.path.join(REPO, "__graft_entry__.py"))
    fn, args = mod.entry()
    h, y = jax.jit(fn)(*args)
    params, x = args
    assert h.shape == (x.shape[0], 500)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(h)).all()
