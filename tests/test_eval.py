"""Eval tests: pairwise similarity vs the reference's hardcoded self-check
(helpers.py:267-276), AUROC sanity, plot file output."""

import numpy as np
import pytest
import scipy.sparse as sp

from dae_rnn_news_recommendation_tpu.eval import (
    nearest_neighbor_report, pairwise_similarity, related_unrelated_auroc,
    visualize_pairwise_similarity, visualize_scatter)

# the reference's own oracle values (helpers.py:269-276)
LIST_CNT = [[1, 1, 0, 1], [0, 1, 0, 1], [0, 1, 1, 1]]
EXPECTED = np.array([
    [0.0, 0.816496580927726, 0.6666666666666669],
    [0.816496580927726, 0.0, 0.816496580927726],
    [0.6666666666666669, 0.816496580927726, 0.0],
])


@pytest.mark.parametrize("kind", ["list", "ndarray", "sparse"])
def test_pairwise_similarity_reference_oracle(kind):
    data = {"list": LIST_CNT, "ndarray": np.array(LIST_CNT),
            "sparse": sp.csr_matrix(LIST_CNT)}[kind]
    got = pairwise_similarity(data)
    np.testing.assert_allclose(got, EXPECTED, rtol=1e-5, atol=1e-6)


def test_linear_kernel_with_l2_norm_equals_cosine():
    x = np.random.default_rng(0).uniform(size=(10, 6)).astype(np.float32)
    cos = pairwise_similarity(x, metric="cosine")
    lin = pairwise_similarity(x, norm="l2", metric="linear kernel")
    np.testing.assert_allclose(lin, cos, rtol=1e-4, atol=1e-5)


def test_pairwise_similarity_blocked_equals_unblocked():
    x = np.random.default_rng(1).normal(size=(50, 8)).astype(np.float32)
    a = pairwise_similarity(x, block_size=7)
    b = pairwise_similarity(x, block_size=1000)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_auroc_separable_labels():
    # two clusters: same-label rows identical, cross-label orthogonal
    x = np.zeros((20, 4), np.float32)
    x[:10, 0] = 1.0
    x[10:, 1] = 1.0
    labels = np.array([0] * 10 + [1] * 10)
    sim = pairwise_similarity(x)
    assert related_unrelated_auroc(labels, sim) == 1.0


def test_auroc_missing_labels_masked():
    x = np.random.default_rng(2).normal(size=(12, 4)).astype(np.float32)
    labels = np.array([0, 0, 1, 1, -1, -1, 0, 1, -1, 0, 1, -1])
    sim = pairwise_similarity(x)
    a = related_unrelated_auroc(labels, sim)
    assert 0.0 <= a <= 1.0


def test_visualize_writes_png(tmp_path):
    x = np.random.default_rng(3).normal(size=(20, 4)).astype(np.float32)
    labels = np.random.default_rng(3).integers(0, 3, 20)
    sim = pairwise_similarity(x)
    out = tmp_path / "plot.png"
    auroc = visualize_pairwise_similarity(labels, sim, save_path=str(out))
    assert out.exists() and out.stat().st_size > 0
    assert 0.0 <= auroc <= 1.0
    out2 = tmp_path / "scatter.png"
    visualize_scatter(x[:, :2], labels.astype(str), "t", figsize=(4, 4),
                      save_path=str(out2))
    assert out2.exists()


def test_nearest_neighbor_report():
    import pandas as pd
    df = pd.DataFrame({"category_publish_name": list("aabb"),
                       "title": [f"t{i}" for i in range(4)]})
    sim = np.array([[0, .9, .1, .2], [.9, 0, .1, .2],
                    [.1, .1, 0, .8], [.2, .2, .8, 0]], np.float32)
    rows = nearest_neighbor_report(df, sim, sim, top=2)
    assert len(rows) == 2
    assert rows[0]["most_similar_by_embedding"]["title"] == "t1"
    assert rows[0]["score"] == pytest.approx(0.9)
