"""Eval tests: pairwise similarity vs the reference's hardcoded self-check
(helpers.py:267-276), AUROC sanity, plot file output."""

import numpy as np
import pytest
import scipy.sparse as sp

from dae_rnn_news_recommendation_tpu.eval import (
    nearest_neighbor_report, pairwise_similarity, related_unrelated_auroc,
    visualize_pairwise_similarity, visualize_scatter)

# the reference's own oracle values (helpers.py:269-276)
LIST_CNT = [[1, 1, 0, 1], [0, 1, 0, 1], [0, 1, 1, 1]]
EXPECTED = np.array([
    [0.0, 0.816496580927726, 0.6666666666666669],
    [0.816496580927726, 0.0, 0.816496580927726],
    [0.6666666666666669, 0.816496580927726, 0.0],
])


@pytest.mark.parametrize("kind", ["list", "ndarray", "sparse"])
def test_pairwise_similarity_reference_oracle(kind):
    data = {"list": LIST_CNT, "ndarray": np.array(LIST_CNT),
            "sparse": sp.csr_matrix(LIST_CNT)}[kind]
    got = pairwise_similarity(data)
    np.testing.assert_allclose(got, EXPECTED, rtol=1e-5, atol=1e-6)


def test_linear_kernel_with_l2_norm_equals_cosine():
    x = np.random.default_rng(0).uniform(size=(10, 6)).astype(np.float32)
    cos = pairwise_similarity(x, metric="cosine")
    lin = pairwise_similarity(x, norm="l2", metric="linear kernel")
    np.testing.assert_allclose(lin, cos, rtol=1e-4, atol=1e-5)


def test_pairwise_similarity_blocked_equals_unblocked():
    x = np.random.default_rng(1).normal(size=(50, 8)).astype(np.float32)
    a = pairwise_similarity(x, block_size=7)
    b = pairwise_similarity(x, block_size=1000)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_auroc_separable_labels():
    # two clusters: same-label rows identical, cross-label orthogonal
    x = np.zeros((20, 4), np.float32)
    x[:10, 0] = 1.0
    x[10:, 1] = 1.0
    labels = np.array([0] * 10 + [1] * 10)
    sim = pairwise_similarity(x)
    assert related_unrelated_auroc(labels, sim) == 1.0


def test_auroc_missing_labels_masked():
    x = np.random.default_rng(2).normal(size=(12, 4)).astype(np.float32)
    labels = np.array([0, 0, 1, 1, -1, -1, 0, 1, -1, 0, 1, -1])
    sim = pairwise_similarity(x)
    a = related_unrelated_auroc(labels, sim)
    assert 0.0 <= a <= 1.0


def test_visualize_writes_png(tmp_path):
    x = np.random.default_rng(3).normal(size=(20, 4)).astype(np.float32)
    labels = np.random.default_rng(3).integers(0, 3, 20)
    sim = pairwise_similarity(x)
    out = tmp_path / "plot.png"
    auroc = visualize_pairwise_similarity(labels, sim, save_path=str(out))
    assert out.exists() and out.stat().st_size > 0
    assert 0.0 <= auroc <= 1.0
    out2 = tmp_path / "scatter.png"
    visualize_scatter(x[:, :2], labels.astype(str), "t", figsize=(4, 4),
                      save_path=str(out2))
    assert out2.exists()


def test_nearest_neighbor_report():
    import pandas as pd
    df = pd.DataFrame({"category_publish_name": list("aabb"),
                       "title": [f"t{i}" for i in range(4)]})
    sim = np.array([[0, .9, .1, .2], [.9, 0, .1, .2],
                    [.1, .1, 0, .8], [.2, .2, .8, 0]], np.float32)
    rows = nearest_neighbor_report(df, sim, sim, top=2)
    assert len(rows) == 2
    assert rows[0]["most_similar_by_embedding"]["title"] == "t1"
    assert rows[0]["score"] == pytest.approx(0.9)


def test_histogram_figure_matches_exact_auroc(tmp_path):
    """The streaming path's figure must report (nearly) the same AUROC as the
    exact pair-population path, and the ROC points must be a valid curve."""
    from dae_rnn_news_recommendation_tpu.eval import (
        roc_points_from_histograms, streaming_auroc,
        visualize_similarity_from_histograms)

    rng = np.random.default_rng(4)
    x = rng.normal(size=(60, 8)).astype(np.float32)
    x[:30] += 0.8  # related pairs inside the shifted cluster score higher
    labels = np.array([0] * 30 + [1] * 30)

    sim = pairwise_similarity(x, metric="cosine")
    exact = related_unrelated_auroc(labels, sim)

    _, h_rel, h_unrel, edges = streaming_auroc(x, labels, return_histograms=True)
    out = tmp_path / "hist_fig.png"
    got = visualize_similarity_from_histograms(h_rel, h_unrel, edges,
                                               title="t", save_path=str(out))
    assert out.exists() and out.stat().st_size > 0
    assert got == pytest.approx(exact, abs=2e-3)  # bin-quantization tolerance

    fpr, tpr = roc_points_from_histograms(h_rel, h_unrel)
    assert fpr[0] == 0.0 and tpr[0] == 0.0
    assert fpr[-1] == 1.0 and tpr[-1] == 1.0
    assert (np.diff(fpr) >= 0).all() and (np.diff(tpr) >= 0).all()


def test_histogram_figure_degenerate_returns_nan(tmp_path):
    from dae_rnn_news_recommendation_tpu.eval import (
        visualize_similarity_from_histograms)

    h = np.zeros(16)
    edges = np.linspace(-1, 1, 17)
    assert np.isnan(visualize_similarity_from_histograms(h, h, edges))


@pytest.mark.parametrize("kind", ["dense", "sparse"])
def test_streaming_top1_matches_full_matrix(kind):
    from dae_rnn_news_recommendation_tpu.eval import streaming_top1

    rng = np.random.default_rng(5)
    dense = rng.uniform(size=(40, 12)).astype(np.float32)
    dense[dense < 0.7] = 0.0
    data = sp.csr_matrix(dense) if kind == "sparse" else dense

    sim = pairwise_similarity(dense, metric="cosine")  # diagonal zeroed
    want_idx = np.argmax(sim, axis=1)[:5]

    idx, score = streaming_top1(data, metric="cosine", n_rows=5, block_size=16)
    np.testing.assert_array_equal(idx, want_idx)
    np.testing.assert_allclose(score, sim[np.arange(5), want_idx],
                               rtol=1e-5, atol=1e-5)


def test_streaming_top1_all_negative_neighbors_matches_zero_diagonal():
    """A row whose every off-diagonal cosine is negative picks itself at 0.0 on
    the full-matrix path (zeroed diagonal); the streaming path must agree."""
    from dae_rnn_news_recommendation_tpu.eval import streaming_top1

    x = np.array([[1.0, 0.0], [-1.0, 0.1], [-1.0, -0.1]], np.float32)
    sim = pairwise_similarity(x, metric="cosine")
    want_idx = np.argmax(sim, axis=1)
    idx, score = streaming_top1(x, metric="cosine", n_rows=3, block_size=2)
    np.testing.assert_array_equal(idx, want_idx)
    assert idx[0] == 0 and score[0] == 0.0  # row 0: self at zero


def test_streaming_report_matches_matrix_report():
    import pandas as pd

    from dae_rnn_news_recommendation_tpu.eval import (
        nearest_neighbor_report_from_top1, streaming_top1)

    rng = np.random.default_rng(6)
    x = rng.normal(size=(20, 6)).astype(np.float32)
    df = pd.DataFrame({"category_publish_name": ["c"] * 20,
                       "title": [f"t{i}" for i in range(20)]})
    sim = pairwise_similarity(x, metric="cosine")
    want = nearest_neighbor_report(df, sim, sim, top=5)
    got = nearest_neighbor_report_from_top1(
        df, streaming_top1(x, n_rows=5), streaming_top1(x, n_rows=5), top=5)
    for w, g in zip(want, got):
        assert w["most_similar_by_embedding"] == g["most_similar_by_embedding"]
        assert w["most_similar_by_count"] == g["most_similar_by_count"]
        assert w["score"] == pytest.approx(g["score"], abs=1e-5)
