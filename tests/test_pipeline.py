"""Overlapped sparse-feed pipeline (train/pipeline.py): the pipelined fit must
be a pure FEED change — same batches, same PRNG chain, same math as streaming
(parity rtol <= 1e-5 on CPU) — while the runtime properties the design claims
(bounded compilations under ragged shapes, donated input buffers freed, worker
errors surfaced, no deadlock on early exit) are each pinned by a test."""

import threading

import numpy as np
import pytest
import scipy.sparse as sp
import jax

from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.train.pipeline import (
    FeedStats, PipelinedFeed, bucket_pad, bucket_sizes)


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _data(rng, n=37, f=24, sparse=False):
    x = (rng.uniform(size=(n, f)) < 0.25).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return (sp.csr_matrix(x) if sparse else x), labels


def _fit(workdir, feed, sparse=False, **kw):
    rng = np.random.default_rng(0)
    x, labels = _data(rng, sparse=sparse)
    kw.setdefault("batch_size", 10)
    tag = f"p_{feed}_{sparse}_{kw.get('n_devices', 1)}"
    model = DenoisingAutoencoder(
        model_name=tag, main_dir=tag,
        n_components=6, num_epochs=3, seed=7,
        corr_type="masking", corr_frac=0.3, loss_func="mean_squared",
        opt="ada_grad", learning_rate=0.1, verbose=False, verbose_step=10,
        use_tensorboard=False, feed=feed,
        results_root=str(workdir / "results"), **kw)
    model.fit(x, train_set_label=labels)
    return model


# ------------------------------------------------------------------ parity

@pytest.mark.parametrize("sparse", [False, True])
def test_pipelined_matches_streaming(workdir, sparse):
    """Same seed, same data: the pipelined and streaming fits agree on the
    full per-step loss trajectory AND the final parameters (rtol <= 1e-5) —
    the pipeline is a feed change, not a math change."""
    m_stream = _fit(workdir, feed="stream", sparse=sparse)
    m_pipe = _fit(workdir, feed="pipelined", sparse=sparse)
    assert m_stream._last_fit_feed == "stream"
    assert m_pipe._last_fit_feed == "pipelined"
    np.testing.assert_allclose(m_stream.train_cost_batch[0],
                               m_pipe.train_cost_batch[0], rtol=1e-5)
    for k in ("W", "bh", "bv"):
        np.testing.assert_allclose(
            np.asarray(m_stream.params[k]), np.asarray(m_pipe.params[k]),
            rtol=1e-5, atol=1e-7, err_msg=k)


def test_pipelined_fit_records_feed_stats(workdir):
    m = _fit(workdir, feed="pipelined")
    assert len(m.feed_stats_epochs) == 3  # one summary per epoch
    for s in m.feed_stats_epochs:
        assert 0.0 <= s["feed_stall_fraction"] <= 1.0
        assert s["feed_batches"] == 4  # ceil(37 / 10)
        assert s["feed_bytes"] > 0
        assert s["feed_wait_s"] >= 0.0 and s["step_time_s"] >= 0.0


def test_pipelined_mesh_matches_streaming(workdir):
    """The mesh-sharded pipelined path (staged via parallel/feed.py
    put_sharded_batch) reproduces the mesh streaming fit on the same 8 virtual
    devices."""
    m_stream = _fit(workdir, feed="stream", n_devices=8, batch_size=8)
    m_pipe = _fit(workdir, feed="pipelined", n_devices=8, batch_size=8)
    assert m_pipe._last_fit_feed == "pipelined"
    np.testing.assert_allclose(m_stream.train_cost_batch[0],
                               m_pipe.train_cost_batch[0], rtol=1e-5)
    for k in ("W", "bh", "bv"):
        np.testing.assert_allclose(
            np.asarray(m_stream.params[k]), np.asarray(m_pipe.params[k]),
            rtol=1e-5, atol=1e-7, err_msg=k)


# ------------------------------------------------------------------ donation

def test_donation_frees_device_buffers_host_untouched():
    """The donation contract the pipeline relies on: a donated device buffer
    whose storage XLA reuses is DELETED after the call (the consumer can never
    accidentally reuse it), while the host array it was staged from is
    untouched. The toy fn returns same-shape/dtype outputs so the reuse is
    guaranteed on every backend, CPU included."""
    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def bump(batch):
        return {k: v + 1.0 for k, v in batch.items()}

    host = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
            "row_valid": np.ones(3, np.float32)}
    host_copy = {k: v.copy() for k, v in host.items()}
    dev = jax.device_put(host)
    out = bump(dev)
    jax.block_until_ready(out)
    for k, arr in dev.items():
        assert arr.is_deleted(), f"{k} should have been donated"
    with pytest.raises(RuntimeError):  # a reuse attempt fails loudly
        np.asarray(dev["x"])
    for k in host:  # donation must never reach back to the host copies
        np.testing.assert_array_equal(host[k], host_copy[k])
    np.testing.assert_array_equal(np.asarray(out["x"]), host["x"] + 1.0)


def test_donate_batch_step_trains_through_pipelined_feed():
    """make_train_step(donate_batch=True) driven by a PipelinedFeed: every
    batch is consumed exactly once, the fit's host data is untouched, and the
    step keeps producing finite metrics across the donated epoch (the
    single-device pipelined configuration, end to end)."""
    from dae_rnn_news_recommendation_tpu.data.batcher import SparseIngestBatcher
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
    from dae_rnn_news_recommendation_tpu.train import make_optimizer
    from dae_rnn_news_recommendation_tpu.train.step import make_train_step

    config = DAEConfig(n_features=24, n_components=4, enc_act_func="tanh",
                       dec_act_func="none", loss_func="mean_squared",
                       corr_type="masking", corr_frac=0.3,
                       triplet_strategy="none")
    optimizer = make_optimizer("ada_grad", 0.1)
    params = init_params(jax.random.PRNGKey(0), config)
    opt_state = optimizer.init(params)
    step = make_train_step(config, optimizer, donate_batch=True)

    rng = np.random.default_rng(0)
    x = sp.csr_matrix((rng.uniform(size=(33, 24)) < 0.3).astype(np.float32))
    data_before = x.toarray().copy()
    batcher = SparseIngestBatcher(8, shuffle=True, seed=3)
    key = jax.random.PRNGKey(1)
    costs = []
    for batch in PipelinedFeed(batcher.epoch(x), depth=2):
        key, sub = jax.random.split(key)
        params, opt_state, metrics = step(params, opt_state, sub, batch)
        costs.append(float(metrics["cost"]))
    assert len(costs) == 5  # ceil(33 / 8)
    assert all(np.isfinite(c) for c in costs)
    np.testing.assert_array_equal(x.toarray(), data_before)


# ------------------------------------------------------------------ bucketing

def test_bucket_sizes_halving():
    assert bucket_sizes(128, n_buckets=3, floor=16) == (32, 64, 128)
    assert bucket_sizes(10, n_buckets=2, floor=4) == (5, 10)
    assert bucket_sizes(8, n_buckets=3, floor=8) == (8,)  # floor caps the set


def test_bucket_pad_contract():
    batch = {"x": np.ones((3, 4), np.float32),
             "labels": np.zeros(3, np.int32),
             "row_valid": np.ones(3, np.float32),
             "corr_min": np.float32(0.0)}  # scalar rides through untouched
    out = bucket_pad(batch, (5, 10))
    assert out["x"].shape == (5, 4)
    np.testing.assert_array_equal(out["x"][3:], 0.0)
    np.testing.assert_array_equal(out["labels"], [0, 0, 0, -1, -1])
    np.testing.assert_array_equal(out["row_valid"], [1, 1, 1, 0, 0])
    assert out["corr_min"] == np.float32(0.0)
    # already at a bucket size: passthrough (same object, no copy)
    b5 = {"x": np.ones((5, 4), np.float32), "row_valid": np.ones(5, np.float32)}
    assert bucket_pad(b5, (5, 10)) is b5
    # larger than every bucket: passthrough
    b99 = {"x": np.ones((99, 4), np.float32)}
    assert bucket_pad(b99, (5, 10)) is b99


def test_bucket_pad_synthesizes_row_valid():
    out = bucket_pad({"x": np.ones((2, 3), np.float32)}, (4,))
    np.testing.assert_array_equal(out["row_valid"], [1, 1, 0, 0])


def test_bucketing_bounds_compilations():
    """A ragged epoch through a bucketed PipelinedFeed compiles at most
    len(buckets) programs (the tentpole's recompile guarantee)."""
    traces = []

    @jax.jit
    def f(batch):
        traces.append(batch["x"].shape)  # side effect fires once per trace
        return (batch["x"].sum(axis=1) * batch["row_valid"]).sum()

    buckets = bucket_sizes(10, n_buckets=2, floor=4)  # (5, 10)
    sizes = [10, 7, 3, 9, 10, 5, 2, 8]
    batches = [{"x": np.ones((s, 4), np.float32),
                "row_valid": np.ones(s, np.float32)} for s in sizes]
    feed = PipelinedFeed(iter(batches), buckets=buckets)
    outs = [float(f(b)) for b in feed]
    assert len(traces) <= len(buckets)
    # padded rows are inert: each sum equals the REAL row count * 4
    np.testing.assert_allclose(outs, [s * 4.0 for s in sizes])


# ------------------------------------------------------------------ feed mechanics

def test_pipelined_feed_yields_device_batches_in_order():
    stats = FeedStats()
    batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(5)]
    feed = PipelinedFeed(iter(batches), depth=2,
                         extremes={"corr_min": np.float32(-1.0)}, stats=stats)
    seen = list(feed)
    assert len(seen) == 5
    for i, b in enumerate(seen):
        assert isinstance(b["x"], jax.Array)  # staged on device by the worker
        assert float(b["x"][0, 0]) == i       # order preserved
        assert float(b["corr_min"]) == -1.0   # extremes merged before placement
    assert stats.batches == 5 and stats.bytes_in > 0


def test_pipelined_feed_propagates_worker_error():
    def gen():
        yield {"x": np.ones((2, 2), np.float32)}
        raise RuntimeError("boom in the feed")

    it = iter(PipelinedFeed(gen(), depth=1))
    next(it)
    with pytest.raises(RuntimeError, match="boom in the feed"):
        next(it)


def test_pipelined_feed_early_exit_releases_worker():
    """Breaking out of a pipelined epoch (graceful stop, exception) must not
    leave the worker blocked forever on the full queue."""
    batches = ({"x": np.ones((2, 2), np.float32)} for _ in range(1000))
    it = iter(PipelinedFeed(batches, depth=1))
    next(it)
    it.close()  # consumer abandons the epoch -> stop event fires
    workers = [t for t in threading.enumerate() if t.name == "pipelined-feed"]
    for t in workers:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in workers)


def test_feed_stats_split():
    s = FeedStats()
    s.note_wait(0.25)
    s.note_wait(0.25)
    s.note_bytes(100)
    s.finish(2.0)
    assert s.feed_wait_s == pytest.approx(0.5)
    assert s.step_time_s == pytest.approx(1.5)
    assert s.feed_stall_fraction == pytest.approx(0.25)
    assert s.summary()["feed_batches"] == 2
    s.reset()
    assert s.feed_stall_fraction == 0.0 and s.batches == 0


# ------------------------------------------------------------------ selection

def test_feed_selection_rules(workdir, monkeypatch):
    rng = np.random.default_rng(0)
    x, _ = _data(rng, sparse=True)
    model = DenoisingAutoencoder(
        model_name="sel", main_dir="sel", n_components=6, num_epochs=1,
        batch_size=10, seed=1, verbose=False, use_tensorboard=False,
        results_root=str(workdir / "results"))  # resident_feed="auto" default

    # CPU auto: streaming (keeps existing CPU evidence byte-stable)
    assert model._select_feed(x) == "stream"

    # TPU auto, corpus fits the budget: resident wins
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert model._select_feed(x) == "resident"

    # TPU auto, corpus exceeds the budget: falls back to PIPELINED (the
    # tentpole's auto rule), not streaming
    model.resident_budget_bytes = 1
    assert model._select_feed(x) == "pipelined"

    # explicit modes
    model.feed = "stream"
    assert model._select_feed(x) == "stream"
    model.feed = "resident"
    assert model._select_feed(x) == "resident"
    model.feed = "pipelined"
    assert model._select_feed(x) == "pipelined"

    # explicit resident on a multi-device fit: ineligible -> stream
    model.feed = "resident"
    model.n_devices = 2
    assert model._select_feed(x) == "stream"

    # pipelined is allowed on a data-axis mesh, not on an expert-only mesh
    from types import SimpleNamespace
    model.feed = "pipelined"
    model.n_devices = 1
    model.mesh = SimpleNamespace(shape={"expert": 4})
    assert model._select_feed(x) == "stream"
    model.mesh = SimpleNamespace(shape={"data": 8})
    assert model._select_feed(x) == "pipelined"


def test_feed_param_validated():
    with pytest.raises(AssertionError):
        DenoisingAutoencoder(feed="warp-drive")


# ------------------------------------------------------------------ wire feed

def test_feed_stats_row_and_wire_byte_accounting():
    from dae_rnn_news_recommendation_tpu.train.pipeline import FeedStats

    s = FeedStats()
    s.note_rows(8, 2)
    s.note_rows(6, 0)
    s.note_bytes(700)
    s.finish(1.0)
    assert s.padded_row_fraction == pytest.approx(2 / 16)
    assert s.wire_bytes_per_article == pytest.approx(700 / 14)
    summ = s.summary()
    assert summ["padded_row_fraction"] == pytest.approx(0.125)
    assert summ["wire_bytes_per_article"] == pytest.approx(50.0)
    s.reset()
    assert s.padded_row_fraction == 0.0 and s.wire_bytes_per_article == 0.0


def test_pipelined_fit_logs_wire_byte_stats(workdir):
    m = _fit(workdir, feed="pipelined", sparse=True)
    for s in m.feed_stats_epochs:
        assert s["wire_bytes_per_article"] > 0  # bytes per REAL article
        assert 0.0 <= s["padded_row_fraction"] < 1.0  # 37 rows pad to 40


def test_pipelined_feed_slot_accounting():
    stats = FeedStats()
    batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(5)]
    feed = PipelinedFeed(iter(batches), slots=2, stats=stats)
    assert feed.depth == 2  # `slots` is the staging-slot alias for depth
    assert len(list(feed)) == 5
    ss = feed.slot_summary()
    assert ss["slots"] == 2
    assert ss["batches"] == [3, 2]  # round-robin: seq % depth
    assert len(ss["h2d_s"]) == 2 and all(t >= 0.0 for t in ss["h2d_s"])
    # slots wins over depth when both are given
    assert PipelinedFeed(iter([]), depth=3, slots=4).depth == 4


def test_epoch_cache_offer_seal_replay():
    from dae_rnn_news_recommendation_tpu.train.pipeline import EpochCache

    cache = EpochCache(1000)
    batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(3)]
    for b in batches:
        cache.offer(b, 100)
    assert not cache.ready  # replay only after a COMPLETE warm epoch
    cache.seal()
    assert cache.ready and cache.n_batches == 3 and cache.nbytes == 300
    first = list(cache.replay())
    assert [b["x"][0, 0] for b in first] == [0.0, 1.0, 2.0]  # warm order
    assert first[0] is batches[0]  # the PINNED refs, not copies
    assert cache.hits == 3
    assert len(list(cache.replay())) == 3 and cache.hits == 6
    cache.offer({"x": np.ones(1)}, 50)  # post-seal offers are no-ops
    assert cache.n_batches == 3 and cache.nbytes == 300


def test_epoch_cache_over_budget_disables_and_frees():
    from dae_rnn_news_recommendation_tpu.train.pipeline import EpochCache

    cache = EpochCache(250)
    cache.offer({"x": 1}, 100)
    cache.offer({"x": 2}, 100)
    cache.offer({"x": 3}, 100)  # 300 > 250: flips to disabled
    assert cache.disabled and "budget" in cache.disabled_reason
    assert cache.n_batches == 0 and cache.nbytes == 0  # refs dropped at once
    cache.seal()
    assert not cache.ready  # a disabled cache never replays
    with pytest.raises(AssertionError):
        next(cache.replay())


def test_epoch_cache_empty_seal_stays_not_ready():
    from dae_rnn_news_recommendation_tpu.train.pipeline import EpochCache

    cache = EpochCache(10)
    cache.seal()
    assert not cache.ready


def test_epoch_cache_budget_trip_mid_epoch_evicts_deterministically():
    """The budget can trip MID-epoch, after real batches are already pinned.
    The trip must evict EVERY pinned slot at once (weakref-observable — the
    device buffers free with the refs), always at the same offer for the
    same sequence, and later offers of the same epoch must stay no-ops: a
    half-warm cache never survives to replay half an epoch."""
    import gc
    import weakref

    from dae_rnn_news_recommendation_tpu.train.pipeline import EpochCache

    class Staged:  # dicts can't be weakref'd; pinned batches can
        def __init__(self, i):
            self.i = i

    def run_epoch(budget):
        cache = EpochCache(budget)
        refs, trip_at = [], None
        for i, nbytes in enumerate([100, 100, 100, 100, 100]):
            b = Staged(i)
            refs.append(weakref.ref(b))
            cache.offer(b, nbytes)
            del b
            if cache.disabled and trip_at is None:
                trip_at = i
        return cache, refs, trip_at

    cache, refs, trip_at = run_epoch(250)
    assert trip_at == 2  # first offer that crosses 250, never earlier/later
    assert cache.disabled and "budget" in cache.disabled_reason
    assert cache.n_batches == 0 and cache.nbytes == 0
    gc.collect()
    assert all(r() is None for r in refs)  # nothing keeps a slot alive
    # the epoch keeps running: offers 3 and 4 already happened post-trip and
    # stayed no-ops; sealing the "complete" epoch must not resurrect it
    cache.seal()
    assert not cache.ready
    with pytest.raises(AssertionError):
        next(cache.replay())
    # determinism: the same sequence trips at the same slot every time
    for _ in range(3):
        _, _, again = run_epoch(250)
        assert again == trip_at
