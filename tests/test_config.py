"""Config/flag system tests: defaults, env overrides (presence-triggered booleans,
fixed miswiring), cross-field validation, .env parsing."""

import pytest

from dae_rnn_news_recommendation_tpu.utils import config as C


def test_defaults_match_reference():
    args = C.build_parser().parse_args([])
    assert args.verbose is False
    assert args.verbose_step == 5
    assert args.input_format == "binary"
    assert args.train_row == 8000
    assert args.validate_row == 2000
    assert args.max_features == 10000
    assert args.compress_factor == 20
    assert args.corr_type == "masking"
    assert args.corr_frac == 0.3
    assert args.loss_func == "cross_entropy"
    assert args.opt == "gradient_descent"
    assert args.learning_rate == 0.1
    assert args.num_epochs == 50
    assert args.batch_size == 0.1
    assert args.triplet_strategy == "batch_all"


def test_env_override_correct_keys():
    """The reference miswired corr_type/corr_frac to os.environ['compress_factor']
    (main_autoencoder.py:79-80) — fixed here."""
    args = C.build_parser().parse_args([])
    env = {"corr_type": "decay", "corr_frac": "0.5", "compress_factor": "99"}
    C.apply_env_overrides(args, env)
    assert args.corr_type == "decay"
    assert args.corr_frac == 0.5
    assert args.compress_factor == 99


def test_env_bool_presence_triggered():
    args = C.build_parser().parse_args([])
    C.apply_env_overrides(args, {"verbose": "0", "validation": "false"})
    # presence wins regardless of value (reference :36-42 semantics)
    assert args.verbose is True
    assert args.validation is True


def test_tfidf_forbids_cross_entropy():
    args = C.build_parser().parse_args(["--input_format", "tfidf"])
    with pytest.raises(AssertionError):
        C.validate(args)
    args2 = C.build_parser().parse_args(
        ["--input_format", "tfidf", "--loss_func", "mean_squared"])
    C.validate(args2)  # ok


def test_main_dir_defaults_to_model_name():
    args = C.build_parser().parse_args(["--model_name", "foo"])
    C.validate(args)
    assert args.main_dir == "foo"


def test_load_dotenv(tmp_path, monkeypatch):
    envfile = tmp_path / ".env"
    envfile.write_text("# comment\nalpha=10\nopt=ada_grad\nverbose=1\n")
    monkeypatch.delenv("alpha", raising=False)
    monkeypatch.delenv("opt", raising=False)
    out = C.load_dotenv(envfile)
    assert out == {"alpha": "10", "opt": "ada_grad", "verbose": "1"}
    args = C.build_parser().parse_args([])
    C.apply_env_overrides(args, out)
    assert args.alpha == 10.0
    assert args.opt == "ada_grad"
    assert args.verbose is True


def test_parse_flags_end_to_end(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = C.parse_flags(["--model_name", "m", "--num_epochs", "3"])
    assert args.num_epochs == 3
    assert args.main_dir == "m"


def test_n_experts_rejects_model_parallel(tmp_path, monkeypatch):
    """Expert and model parallelism are mutually exclusive mesh layouts."""
    monkeypatch.chdir(tmp_path)
    with pytest.raises(AssertionError, match="mutually exclusive"):
        C.parse_flags(["--model_name", "m", "--n_experts", "8",
                       "--n_devices", "8", "--model_parallel", "2"])


def test_n_experts_requires_matching_devices(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    with pytest.raises(AssertionError, match="one expert per device"):
        C.parse_flags(["--model_name", "m", "--n_experts", "4",
                       "--n_devices", "8"])


def test_package_version_matches_pyproject():
    """__version__ and pyproject.toml must stay in sync (the docstring says so)."""
    import os

    tomllib = pytest.importorskip("tomllib")  # stdlib only from Python 3.11
    import dae_rnn_news_recommendation_tpu as pkg

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    assert pkg.__version__ == meta["project"]["version"]
