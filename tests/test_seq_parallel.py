"""Sequence-parallel (pipelined) GRU vs the single-device scan oracle, on the
virtual 8-device CPU mesh (SURVEY §4 multi-node strategy)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dae_rnn_news_recommendation_tpu.models.gru_user import (
    gru_apply, gru_init_params, pairwise_rank_loss)
from dae_rnn_news_recommendation_tpu.parallel.seq import pipeline_gru_apply


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("seq",))


def _data(rng, b=8, t=32, d=5, ragged=True):
    seq = jnp.asarray(rng.normal(size=(b, t, d)).astype(np.float32))
    if ragged:
        lengths = rng.integers(1, t + 1, size=b)
        mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
    else:
        mask = np.ones((b, t), np.float32)
    return seq, jnp.asarray(mask)


@pytest.mark.parametrize("microbatches", [1, 2, 8])
def test_pipeline_matches_scan_oracle(rng, mesh, microbatches):
    params = gru_init_params(jax.random.PRNGKey(0), 5, 6)
    seq, mask = _data(rng)
    ref_states, ref_final = gru_apply(params, seq, mask)
    got_states, got_final = pipeline_gru_apply(params, seq, mask, mesh,
                                               microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(ref_states), np.asarray(got_states),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref_final), np.asarray(got_final),
                               atol=1e-5)


def test_pipeline_dense_mask_and_default_microbatches(rng, mesh):
    params = gru_init_params(jax.random.PRNGKey(1), 4, 4)
    seq, mask = _data(rng, b=16, t=16, d=4, ragged=False)
    _, ref_final = gru_apply(params, seq, mask)
    states, final = pipeline_gru_apply(params, seq, mask, mesh)  # M = mesh size
    np.testing.assert_allclose(np.asarray(ref_final), np.asarray(final), atol=1e-5)
    assert states.shape == (16, 16, 4)


def test_pipeline_shape_validation(rng, mesh):
    params = gru_init_params(jax.random.PRNGKey(2), 4, 4)
    seq, mask = _data(rng, b=8, t=30, d=4)  # T=30 not divisible by 8
    with pytest.raises(AssertionError, match="not divisible"):
        pipeline_gru_apply(params, seq, mask, mesh)
    seq, mask = _data(rng, b=6, t=32, d=4)  # B=6 not divisible by M=4
    with pytest.raises(AssertionError, match="microbatches"):
        pipeline_gru_apply(params, seq, mask, mesh, microbatches=4)


def test_pipeline_is_differentiable(rng, mesh):
    """The rank loss must train through the pipeline (long-history training path):
    gradients match the single-device oracle."""
    params = gru_init_params(jax.random.PRNGKey(3), 4, 4)
    seq, mask = _data(rng, b=8, t=16, d=4)
    pos = jnp.asarray(rng.normal(size=(8, 16, 4)).astype(np.float32))
    neg = jnp.asarray(rng.normal(size=(8, 16, 4)).astype(np.float32))

    def loss_ref(p):
        return pairwise_rank_loss(p, seq, pos, neg, mask)

    def loss_pipe(p):
        states, _ = pipeline_gru_apply(p, seq, mask, mesh, microbatches=2)
        s_pos = jnp.sum(states * pos, axis=-1)
        s_neg = jnp.sum(states * neg, axis=-1)
        per = jax.nn.softplus(-(s_pos - s_neg)) * mask
        return jnp.sum(per) / jnp.sum(mask)

    np.testing.assert_allclose(float(loss_ref(params)), float(loss_pipe(params)),
                               rtol=1e-5)
    g_ref = jax.grad(loss_ref)(params)
    g_pipe = jax.grad(loss_pipe)(params)
    for k in g_ref:
        np.testing.assert_allclose(np.asarray(g_ref[k]), np.asarray(g_pipe[k]),
                                   atol=1e-4, err_msg=k)


def test_gru_user_model_trains_through_mesh(rng, mesh):
    """GRUUserModel(mesh=...) trains the recurrence through the pipeline; since
    semantics are exact, the trained params match meshless training bit-for-bit
    (same RNG order, same updates)."""
    from dae_rnn_news_recommendation_tpu.models.gru_user import GRUUserModel

    n, t, d = 16, 16, 4
    seq = rng.normal(size=(n, t, d)).astype(np.float32)
    pos = rng.normal(size=(n, t, d)).astype(np.float32)
    neg = rng.normal(size=(n, t, d)).astype(np.float32)

    local = GRUUserModel(d_embed=d, num_epochs=2, batch_size=8, seed=0)
    local.fit(seq, pos, neg)
    piped = GRUUserModel(d_embed=d, num_epochs=2, batch_size=8, seed=0,
                         mesh=mesh, seq_microbatches=2)
    piped.fit(seq, pos, neg)
    for k in local.params:
        np.testing.assert_allclose(np.asarray(local.params[k]),
                                   np.asarray(piped.params[k]), atol=1e-5,
                                   err_msg=k)
    np.testing.assert_allclose(local.user_state(seq), piped.user_state(seq),
                               atol=1e-5)


def test_gru_user_model_mesh_validation_and_fallback(rng, mesh):
    from dae_rnn_news_recommendation_tpu.models.gru_user import GRUUserModel

    seq = rng.normal(size=(16, 16, 4)).astype(np.float32)
    pos = rng.normal(size=(16, 16, 4)).astype(np.float32)
    neg = rng.normal(size=(16, 16, 4)).astype(np.float32)
    # T=16 on an 8-device axis is fine, but bs=10 % microbatches(8) != 0
    bad = GRUUserModel(d_embed=4, num_epochs=1, batch_size=10, seed=0, mesh=mesh)
    with pytest.raises(ValueError, match="seq_microbatches"):
        bad.fit(seq, pos, neg)

    m = GRUUserModel(d_embed=4, num_epochs=1, batch_size=8, seed=0, mesh=mesh,
                     seq_microbatches=2)
    m.fit(seq, pos, neg)
    # inference on shapes the pipeline can't take falls back to the local scan
    odd = rng.normal(size=(7, 13, 4)).astype(np.float32)
    states = m.user_state(odd)
    ref, final = gru_apply(m.params, jnp.asarray(odd))
    np.testing.assert_allclose(states, np.asarray(final), atol=1e-6)
