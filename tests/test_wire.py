"""Compressed CSR wire format (ops/wire.py) + the packed-wire fit path.

The codec's whole claim is *bitwise* fidelity: `unpack_wire_host(pack(m))`
must reproduce `pad_csr_batch(m)` exactly (f32 / binary modes), the jnp and
Pallas-interpret unpacks must match the host unpack exactly, and therefore a
packed-wire pipelined fit must land on the SAME parameter digest as the
padded-CSR pipelined fit — compression is a wire change, never a math change.
The device-resident epoch cache rides the same contract: replayed epochs ship
zero bytes and still hit the identical digest.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.ops import wire
from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import pad_csr_batch
from dae_rnn_news_recommendation_tpu.reliability.chaos import params_digest


@pytest.fixture
def csr():
    """33 x 400, ~5% dense, float32 — includes an all-zero row (row 7)."""
    m = sp.random(33, 400, density=0.05, format="csr", random_state=0,
                  dtype=np.float32)
    lil = m.tolil()
    lil[7, :] = 0
    return lil.tocsr()


@pytest.fixture
def bin_csr(csr):
    b = csr.copy()
    b.data[:] = 1.0
    return b


# ------------------------------------------------------------- round trip

def test_roundtrip_bitwise_f32(csr):
    w = wire.pack_csr_wire(csr, mode="f32")
    out = wire.unpack_wire_host(w)
    ref = pad_csr_batch(csr)
    assert out["k"] == ref["k"]
    assert out["indices"].dtype == ref["indices"].dtype == np.uint16
    np.testing.assert_array_equal(out["indices"], ref["indices"])
    np.testing.assert_array_equal(  # bitwise, not allclose
        out["values"].view(np.uint32), ref["values"].view(np.uint32))


def test_roundtrip_bitwise_binary(bin_csr):
    w = wire.pack_csr_wire(bin_csr, mode="binary")
    assert "values" not in w  # binary elides the values plane entirely
    out = wire.unpack_wire_host(w)
    ref = pad_csr_batch(bin_csr, binary=True)
    assert out["values"] is None and ref["values"] is None
    assert w["spec"].pad_index == bin_csr.shape[1]
    np.testing.assert_array_equal(out["indices"], ref["indices"])


def test_roundtrip_f16_exact_on_01_data(bin_csr):
    # 0/1 values are exactly representable in f16: lossless despite the cast
    w = wire.pack_csr_wire(bin_csr, mode="f16")
    assert w["values"].dtype == np.float16
    out = wire.unpack_wire_host(w)
    ref = pad_csr_batch(bin_csr)
    np.testing.assert_array_equal(out["values"], ref["values"])
    np.testing.assert_array_equal(out["indices"], ref["indices"])


def test_roundtrip_i8_quantization_bound(csr):
    w = wire.pack_csr_wire(csr, mode="i8")
    assert w["values"].dtype == np.int8 and w["scale"].dtype == np.float32
    out = wire.unpack_wire_host(w)
    ref = pad_csr_batch(csr)
    np.testing.assert_array_equal(out["indices"], ref["indices"])
    # per-row absmax/127 linear quantization: error <= scale/2 per entry
    err = np.abs(out["values"] - ref["values"])
    bound = w["scale"][:, None] / 2 + 1e-7
    assert (err <= bound).all()


def test_roundtrip_empty_matrix():
    m = sp.csr_matrix((5, 300), dtype=np.float32)
    for mode, binary in (("f32", False), ("binary", True)):
        out = wire.unpack_wire_host(wire.pack_csr_wire(m, mode=mode))
        ref = pad_csr_batch(m, binary=binary)
        np.testing.assert_array_equal(out["indices"], ref["indices"])
        assert out["k"] == ref["k"] == 64  # k_multiple floor


# ----------------------------------------------------------- spec contract

def test_plan_wire_mirrors_pad_csr_promotion():
    row = sp.csr_matrix((np.ones(2, np.float32), ([0, 0], [0, 65534])),
                        shape=(1, 65535))
    assert wire.plan_wire(row).index_dtype == "uint16"
    assert wire.plan_wire(row, mode="binary").index_dtype == "uint16"
    wide = sp.csr_matrix((np.ones(2, np.float32), ([0, 0], [0, 65535])),
                         shape=(1, 65536))
    # non-binary: max column 65535 still fits uint16; binary pad_index = F
    # (65536) does not — exactly pad_csr_batch's promotion boundary
    assert wire.plan_wire(wide).index_dtype == "uint16"
    assert wire.plan_wire(wide, mode="binary").index_dtype == "uint32"
    wider = sp.csr_matrix((np.ones(1, np.float32), ([0], [65536])),
                          shape=(1, 65537))
    assert wire.plan_wire(wider).index_dtype == "uint32"


def test_wide_corpus_roundtrip_uint32(bin_csr):
    m = sp.csr_matrix((bin_csr.data, bin_csr.indices, bin_csr.indptr),
                      shape=(bin_csr.shape[0], 70000))
    w = wire.pack_csr_wire(m, mode="binary")
    out = wire.unpack_wire_host(w)
    ref = pad_csr_batch(m, binary=True)
    assert out["indices"].dtype == ref["indices"].dtype == np.uint32
    np.testing.assert_array_equal(out["indices"], ref["indices"])


def test_pack_rejects_corpus_outside_spec(csr):
    tight = sp.csr_matrix(np.tril(np.ones((4, 8), np.float32)))  # gaps of 1
    spec = wire.plan_wire(tight)
    assert spec.bits == 4
    with pytest.raises(ValueError, match="does not match"):
        wire.pack_csr_wire(csr, spec=spec)  # 400-column gaps need 16 bits


def test_shared_spec_packs_every_batch_of_a_corpus(csr):
    spec = wire.plan_wire(csr)
    ref = pad_csr_batch(csr, k=spec.k)
    for lo, hi in ((0, 10), (10, 25), (25, 33)):
        out = wire.unpack_wire_host(wire.pack_csr_wire(csr[lo:hi], spec=spec))
        np.testing.assert_array_equal(out["indices"], ref["indices"][lo:hi])
        np.testing.assert_array_equal(out["values"], ref["values"][lo:hi])


def test_wirespec_is_jit_static_pytree(csr):
    w = wire.pack_csr_wire(csr)
    leaves, treedef = jax.tree_util.tree_flatten(w)
    assert not any(isinstance(leaf, wire.WireSpec) for leaf in leaves)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt["spec"] == w["spec"]
    # same spec -> same treedef: one compiled program per corpus
    w2 = wire.pack_csr_wire(csr[:8], spec=w["spec"])
    assert jax.tree_util.tree_structure(w) == jax.tree_util.tree_structure(w2)


def test_wire_compresses_clustered_and_binary_corpora():
    """The byte claim the bench records: binary wire beats binary padded-CSR
    (kk*2), and an index-clustered corpus (gap bits 4) beats full padded-CSR
    (kk*6) even shipping lossless f32 values."""
    rng = np.random.default_rng(3)
    rows, cols = [], []
    for i in range(64):
        start = rng.integers(0, 3000)
        cols.extend(start + np.cumsum(rng.integers(1, 15, size=40)))
        rows.extend([i] * 40)
    m = sp.csr_matrix((np.ones(len(cols), np.float32), (rows, cols)),
                      shape=(64, 4000))
    kk = pad_csr_batch(m)["k"]
    wb = wire.pack_csr_wire(m, mode="binary")
    assert wire.plan_wire(m).bits <= 8
    assert wire.wire_bytes_per_article(wb) < kk * 2
    wf = wire.pack_csr_wire(m, mode="f32")
    assert wire.wire_bytes_per_article(wf) < kk * 6


# --------------------------------------------------------- device unpacks

@pytest.mark.parametrize("mode", ["f32", "f16", "i8", "binary"])
def test_jnp_unpack_matches_host_bitwise(csr, bin_csr, mode):
    m = bin_csr if mode in ("f16", "binary") else csr
    w = wire.pack_csr_wire(m, mode=mode)
    ref = wire.unpack_wire_host(w)
    idx, vals = wire.unpack_wire_jnp(
        w["words"], w["first"], w["nnz"], w["spec"],
        values=w.get("values"), scale=w.get("scale"))
    np.testing.assert_array_equal(np.asarray(idx), ref["indices"])
    if mode == "binary":
        assert vals is None
    else:
        np.testing.assert_array_equal(
            np.asarray(vals).view(np.uint32), ref["values"].view(np.uint32))


@pytest.mark.parametrize("mode", ["f32", "binary"])
def test_pallas_interpret_unpack_matches_host(csr, bin_csr, mode):
    m = bin_csr if mode == "binary" else csr
    w = wire.pack_csr_wire(m, mode=mode)
    ref = wire.unpack_wire_host(w)
    idx, _ = wire.unpack_wire_pallas(
        w["words"], w["first"], w["nnz"], w["spec"],
        values=w.get("values"), interpret=True)
    assert np.asarray(idx).dtype == ref["indices"].dtype
    np.testing.assert_array_equal(np.asarray(idx), ref["indices"])


def test_unpack_dispatch_routes_off_tpu_to_jnp(csr):
    w = wire.pack_csr_wire(csr)
    idx, vals = wire.unpack_wire(w["words"], w["first"], w["nnz"], w["spec"],
                                 values=w["values"], impl="auto")
    ref = wire.unpack_wire_host(w)
    np.testing.assert_array_equal(np.asarray(idx), ref["indices"])
    np.testing.assert_array_equal(np.asarray(vals), ref["values"])


# ------------------------------------------------------- packed-wire fits

def _sparse_corpus(n=37, f=24):
    rng = np.random.default_rng(0)
    x = sp.csr_matrix((rng.uniform(size=(n, f)) < 0.25).astype(np.float32))
    labels = rng.integers(0, 4, n).astype(np.int32)
    return x, labels


def _fit(workdir, tag, **kw):
    x, labels = _sparse_corpus()
    model = DenoisingAutoencoder(
        model_name=tag, main_dir=tag,
        n_components=6, num_epochs=3, seed=7, batch_size=10,
        corr_type="masking", corr_frac=0.3, loss_func="mean_squared",
        opt="ada_grad", learning_rate=0.1, verbose=False, verbose_step=10,
        use_tensorboard=False, feed="pipelined",
        results_root=str(workdir / "results"), **{"shuffle": False, **kw})
    model.fit(x, train_set_label=labels)
    return model


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_wire_fit_matches_padded_csr_fit_bitwise(workdir):
    """The acceptance criterion: the packed-wire pipelined fit reproduces the
    plain pipelined fit digest-for-digest on CPU — same batches, same PRNG
    chain, indices/values recovered bitwise inside the jitted step."""
    m_csr = _fit(workdir, "w_csr", wire_feed=None)
    m_wire = _fit(workdir, "w_wire", wire_feed="f32")
    assert params_digest(m_csr.params) == params_digest(m_wire.params)
    np.testing.assert_array_equal(m_csr.train_cost_batch[0],
                                  m_wire.train_cost_batch[0])
    # the feed accounting knows it shipped a compressed wire
    s = m_wire.feed_stats_epochs[0]
    assert s["wire_bytes_per_article"] > 0
    assert 0.0 <= s["padded_row_fraction"] < 1.0


def test_wire_cache_replays_bitwise_with_zero_h2d(workdir):
    """Epoch cache: warm epoch pays the wire once, epochs 2..N replay pinned
    device batches — feed_bytes 0 — and the digest still matches the
    uncached packed-wire fit."""
    m_plain = _fit(workdir, "c_plain", wire_feed="f32")
    m_cached = _fit(workdir, "c_cached", wire_feed="f32",
                    wire_cache_budget_bytes=1 << 30)
    assert params_digest(m_plain.params) == params_digest(m_cached.params)
    cache = m_cached._wire_cache
    assert cache is not None and cache.ready and not cache.disabled
    assert cache.n_batches == 4  # ceil(37 / 10)
    assert cache.hits == 8       # replayed twice (epochs 2 and 3)
    warm, *replayed = m_cached.feed_stats_epochs
    assert warm["feed_bytes"] > 0
    for s in replayed:
        assert s["feed_bytes"] == 0            # nothing crossed the link
        assert s["feed_batches"] == 4          # but every batch was consumed


def test_wire_cache_over_budget_falls_back(workdir):
    """A corpus that outgrows the budget disables the cache mid-warm and the
    fit keeps paying H2D — fallback, not failure; math unchanged."""
    m_plain = _fit(workdir, "b_plain", wire_feed="f32")
    m_tiny = _fit(workdir, "b_tiny", wire_feed="f32",
                  wire_cache_budget_bytes=1)
    assert params_digest(m_plain.params) == params_digest(m_tiny.params)
    cache = m_tiny._wire_cache
    assert cache.disabled and not cache.ready
    assert "budget" in cache.disabled_reason
    for s in m_tiny.feed_stats_epochs:
        assert s["feed_bytes"] > 0  # every epoch shipped the wire


def test_wire_cache_requires_repeating_batch_order(workdir):
    m = _fit(workdir, "shuf", wire_feed="f32",
             wire_cache_budget_bytes=1 << 30, shuffle=True)
    assert m._wire_cache is None  # shuffle on: epoch 2 needs a new order


# --------------------------------------------------------- batcher edges

def test_wire_batcher_all_empty_rows_batch_is_inert():
    """A batch whose rows are ALL empty (every article filtered out, or a
    zero stripe of the corpus) must ship a fully zeroed payload — words,
    first, nnz, values — and unpack to pure padding, exactly like the
    all-zero row the codec round-trips inside a mixed batch."""
    from dae_rnn_news_recommendation_tpu.data.batcher import (
        WireSparseIngestBatcher)

    dense = np.zeros((6, 300), np.float32)
    dense[:3] = sp.random(3, 300, density=0.1, format="csr", random_state=4,
                          dtype=np.float32).toarray()  # rows 3-5 stay empty
    csr = sp.csr_matrix(dense)
    batcher = WireSparseIngestBatcher(batch_size=3, shuffle=False)
    batches = list(batcher.epoch(csr, labels=np.arange(6)))
    assert len(batches) == 2
    empty = batches[1]  # rows 3..5: no padding, just genuinely empty rows
    spec = empty["x_wire_spec"]
    assert not empty["x_wire_words"].any()
    assert not empty["x_wire_first"].any()
    assert not empty["x_wire_nnz"].any()
    assert not empty["x_wire_values"].any()
    assert empty["row_valid"].all()  # empty != padded: rows are real
    np.testing.assert_array_equal(empty["labels"], [3, 4, 5])
    # unpack: every slot is the inert pad column with a zero value
    packed = {k[len("x_wire_"):]: v for k, v in empty.items()
              if k.startswith("x_wire_")}
    out = wire.unpack_wire_host(packed)
    assert (out["indices"] == spec.pad_index).all()
    assert not out["values"].any()
    ref = pad_csr_batch(csr[3:6], k=out["k"])
    np.testing.assert_array_equal(out["indices"], ref["indices"])
    np.testing.assert_array_equal(out["values"], ref["values"])


def test_wire_batcher_all_empty_rows_batch_is_inert_quantized():
    # same contract under i8: the per-row scale must stay a safe nonzero
    from dae_rnn_news_recommendation_tpu.data.batcher import (
        WireSparseIngestBatcher)

    dense = np.zeros((4, 128), np.float32)
    dense[0, 5] = 0.7
    csr = sp.csr_matrix(dense)
    batcher = WireSparseIngestBatcher(batch_size=2, shuffle=False,
                                      wire_mode="i8")
    empty = list(batcher.epoch(csr))[1]
    assert not empty["x_wire_nnz"].any()
    assert not empty["x_wire_values"].any()
    assert np.isfinite(empty["x_wire_scale"]).all()
    packed = {k[len("x_wire_"):]: v for k, v in empty.items()
              if k.startswith("x_wire_")}
    out = wire.unpack_wire_host(packed)
    assert not out["values"].any()
