"""Shadow-scorer contracts (ISSUE 19 tentpole): deterministic sampling,
strictly-off-the-reply-path re-scoring, exact-path agreement, quality
metrics, and the zero-post-warm-compiles regression.

The shadow scorer rides the serving path's own invariants: `offer()` runs
AFTER every primary reply resolved and never blocks (a full queue drops the
sample, counted); the re-score is a background-thread dispatch under the
mesh dispatch lock; and every exact variant it executes was compiled inside
`warmup()` — a sampled request must never retrace.
"""

import time

import numpy as np
import pytest

import jax

from dae_rnn_news_recommendation_tpu.analysis.runtime import compile_guard
from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.serve import (RecommendationService,
                                                   ServingCorpus)
from dae_rnn_news_recommendation_tpu.telemetry import MetricsRegistry

N, F, D = 64, 24, 8
SLA = 10.0


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((N, F), dtype=np.float32)
    return config, params, articles


def _service(config, params, articles, *, registry=None, corpus_kw=None,
             **kw):
    corpus = ServingCorpus(config, block=16, registry=registry,
                           **(corpus_kw or {}))
    corpus.swap(params, articles, note="initial")
    kw.setdefault("top_k", 5)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_inflight", 64)
    kw.setdefault("shadow_rate", 1.0)
    kw.setdefault("shadow_queue", 128)
    svc = RecommendationService(params, config, corpus, registry=registry,
                                **kw)
    svc.warmup()
    return svc


def _burst(svc, articles, n, seed=0):
    rng = np.random.default_rng(seed)
    futs = [svc.submit(articles[int(rng.integers(0, N))], deadline_s=SLA)
            for _ in range(n)]
    return [f.result(timeout=SLA) for f in futs]


# ---------------------------------------------------------------- sampling

def test_sampling_is_deterministic_every_nth():
    """rate=0.25 keeps exactly every 4th reply, reproducibly: two scorers
    fed the same reply sequence pick the same positions — a sampled quality
    dip can be replayed, never a coin flip."""
    from dae_rnn_news_recommendation_tpu.serve.shadow import ShadowScorer

    class _Svc:  # offer() touches only .metrics on the sampling path
        metrics = None
        name = "stub"

    picks = []
    for _ in range(2):
        sc = ShadowScorer(_Svc(), rate=0.25, max_queue=64)
        kept = [sc.offer(f"r{i}", np.zeros(F, np.float32),
                         np.zeros(5, np.int64), np.zeros(5, np.float32),
                         None, 5) for i in range(16)]
        sc._stop.set()  # nothing scoreable was enqueued for a real dispatch
        picks.append(kept)
    assert picks[0] == picks[1]
    assert sum(picks[0]) == 4
    assert [i for i, keep in enumerate(picks[0]) if keep] == [0, 4, 8, 12]


def test_full_queue_drops_and_counts_never_blocks():
    from dae_rnn_news_recommendation_tpu.serve.shadow import ShadowScorer

    class _Svc:
        metrics = None
        name = "stub"

    sc = ShadowScorer(_Svc(), rate=1.0, max_queue=2)
    sc._stop.set()          # freeze the drain loop: the queue can only fill
    sc._thread.join(timeout=5.0)
    sc._stop.clear()
    t0 = time.monotonic()
    for i in range(6):
        sc.offer(f"r{i}", np.zeros(F, np.float32), np.zeros(5, np.int64),
                 np.zeros(5, np.float32), None, 5)
    assert time.monotonic() - t0 < 1.0      # put_nowait, never a block
    assert sc.counts["dropped"] == 4
    assert sc.counts["sampled"] == 2


# ------------------------------------------------------------ live scoring

def test_exact_corpus_shadow_scores_recall_one_and_metrics(setup):
    """On an exact (non-IVF) corpus the shadow path IS the primary path, so
    every sampled request must score recall 1.0 with zero displacement —
    and the registry must carry the full counter/gauge/histogram set."""
    config, params, articles = setup
    reg = MetricsRegistry(name="shadow-test")
    svc = _service(config, params, articles, registry=reg)
    try:
        replies = _burst(svc, articles, 12)
        assert all(r.ok for r in replies)
        assert svc.shadow.flush(timeout=SLA)
        s = svc.shadow.summary()
        assert s["counts"]["scored"] == 12
        assert s["counts"]["errors"] == 0
        assert s["recall_mean"] == 1.0 and s["recall_min"] == 1.0
        assert all(rec["rank_displacement"] == 0.0 for rec in s["samples"])
        snap = reg.snapshot()
        assert snap["counters"]["shadow_scored"] == 12
        assert snap["counters"]["shadow_misses"] == 0
        assert snap["gauges"]["shadow_recall"] == 1.0
        assert snap["gauges"]["shadow_recall_mean"] == 1.0
        assert snap["histograms"]["shadow_recall"]["count"] == 12
        assert snap["histograms"]["shadow_rank_displacement"]["count"] == 12
    finally:
        svc.stop()


def test_shadow_never_blocks_or_reorders_primary_replies(setup):
    """The primary reply stream must be byte-identical with the shadow on:
    same indices, same scores, same per-request ordering — the shadow only
    ever reads a host-side copy after the future resolved."""
    config, params, articles = setup
    queries = [articles[i % N] for i in range(16)]
    svc_off = _service(config, params, articles, shadow_rate=0.0)
    try:
        base = [svc_off.submit(q, deadline_s=SLA).result(timeout=SLA)
                for q in queries]
    finally:
        svc_off.stop()
    svc_on = _service(config, params, articles, shadow_rate=1.0)
    try:
        shadowed = [svc_on.submit(q, deadline_s=SLA).result(timeout=SLA)
                    for q in queries]
        assert svc_on.shadow.flush(timeout=SLA)
        assert svc_on.shadow.counts["scored"] == 16
    finally:
        svc_on.stop()
    for b, s in zip(base, shadowed):
        assert b.ok and s.ok
        np.testing.assert_array_equal(b.indices, s.indices)
        np.testing.assert_allclose(b.scores, s.scores, rtol=0, atol=0)


def test_ivf_shadow_measures_true_recall_against_exact(setup):
    """On an IVF corpus with few probes the shadow compares the clustered
    answer against the exact full scan: recall lands in (0, 1], and the
    probe-hit/miss cell histograms appear once any exact row was checked."""
    config, params, articles = setup
    reg = MetricsRegistry(name="shadow-ivf")
    svc = _service(config, params, articles, registry=reg,
                   corpus_kw={"retrieval": "ivf", "n_cells": 4,
                              "cell_cap": N}, probes=2)
    try:
        replies = _burst(svc, articles, 12, seed=7)
        assert all(r.ok for r in replies)
        assert svc.shadow.flush(timeout=SLA)
        s = svc.shadow.summary()
        assert s["counts"]["scored"] == 12 and s["counts"]["errors"] == 0
        assert 0.0 < s["recall_mean"] <= 1.0
        snap = reg.snapshot()
        hit = snap["histograms"].get("ivf_probe_hit_cell_rows")
        miss = snap["histograms"].get("ivf_probe_miss_cell_rows")
        checked = ((hit["count"] if hit else 0)
                   + (miss["count"] if miss else 0))
        assert checked > 0    # every finite exact row was attributed a cell
    finally:
        svc.stop()


# ---------------------------------------------------------- compile guard

def test_shadow_path_zero_post_warm_compiles(setup):
    """Regression: warmup() pre-compiles the shadow's exact variants (the
    IVF service's fallback fns at the shadow bucket), so a full sampled
    burst triggers ZERO retraces — on both corpus retrieval modes."""
    config, params, articles = setup
    for corpus_kw, probes in (
            (None, None),
            ({"retrieval": "ivf", "n_cells": 4, "cell_cap": N}, 2)):
        kw = {} if probes is None else {"probes": probes}
        svc = _service(config, params, articles, corpus_kw=corpus_kw, **kw)
        try:
            with compile_guard() as guard:
                replies = _burst(svc, articles, 10, seed=11)
                assert all(r.ok for r in replies)
                assert svc.shadow.flush(timeout=SLA)
                assert svc.shadow.counts["scored"] == 10
                assert svc.shadow.counts["errors"] == 0
            assert guard.count == 0, guard.entries
        finally:
            svc.stop()
