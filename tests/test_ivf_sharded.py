"""Sharded IVF parity suite (ISSUE 16 tentpole acceptance).

The sharded clustered scorer must be INDEX-EXACT against the unsharded one
at matched probes — same ids, bitwise-identical finite scores — and against
the exact scorer at probes = n_cells, on the 8-device CPU mesh the test
conftest forces, for fp32 and int8 corpora and both impls. Plus the layout
unit contract: every cell's rows land on exactly one shard, every slot row
in exactly one slab, shard slabs equal-sized.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.index import (ShardedIVFCells,
                                                   build_cells,
                                                   build_sharded_cells,
                                                   cell_shard_owner,
                                                   kmeans_fit)
from dae_rnn_news_recommendation_tpu.ops.ivf_topk import (ivf_topk,
                                                          sharded_ivf_topk)
from dae_rnn_news_recommendation_tpu.ops.topk_fused import (_IDX_SENTINEL,
                                                            topk_fused)
from dae_rnn_news_recommendation_tpu.parallel.mesh import get_mesh, shard_rows
from dae_rnn_news_recommendation_tpu.serve.corpus import quantize_corpus

N, D, C, B, K = 200, 16, 10, 7, 9  # N divides the 8-device mesh


def _corpora(dtype, seed=0):
    """(queries, unsharded ops args, sharded ops args, mesh) for one dtype:
    the SAME logical corpus, flat + clustered, single-device + mesh-placed."""
    rng = np.random.default_rng(seed)
    emb = rng.normal(size=(N, D)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    q = rng.normal(size=(B, D)).astype(np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    valid = np.ones(N, np.float32)
    valid[-3:] = 0.0  # a few dead rows: the mask must survive the layout
    q_emb, scales = quantize_corpus(jnp.asarray(emb), dtype)
    km = kmeans_fit(jnp.asarray(emb), jnp.asarray(valid), C, seed=3)
    mesh = get_mesh()
    put = lambda x: shard_rows(x, mesh)
    flat = dict(emb=jnp.asarray(q_emb), valid=jnp.asarray(valid),
                scales=None if scales is None else jnp.asarray(scales))
    cells_u = build_cells(flat["emb"], flat["valid"], flat["scales"],
                          km.centroids, km.assign)
    cells_s = build_sharded_cells(flat["emb"], flat["valid"], flat["scales"],
                                  km.centroids, km.assign,
                                  n_shards=8, device_put=put)
    sharded = dict(emb=put(flat["emb"]), valid=put(flat["valid"]),
                   scales=None if scales is None else put(flat["scales"]))
    return jnp.asarray(q), flat, cells_u, sharded, cells_s, mesh


def test_cell_placement_every_cell_on_exactly_one_shard():
    _, _, _, _, cells, _ = _corpora("float32")
    assert isinstance(cells, ShardedIVFCells) and cells.n_shards == 8
    owner = cell_shard_owner(cells)
    row_ids = np.asarray(cells.row_ids)
    assign = np.asarray(cells.assign)
    stride = int(cells.shard_rows)
    assert row_ids.shape[0] == 8 * stride  # equal-sized shard slabs
    real = row_ids[row_ids != _IDX_SENTINEL]
    # every slot row (valid or padding — the scorer sees the exact same row
    # population as the flat scan) lives in exactly one slab
    assert sorted(real.tolist()) == list(range(N))
    for slab_row, rid in enumerate(row_ids):
        if rid == _IDX_SENTINEL:
            continue
        assert owner[assign[rid]] == slab_row // stride, (
            f"row {rid} (cell {assign[rid]}) placed on shard "
            f"{slab_row // stride}, owner is {owner[assign[rid]]}")


@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("impl", ["jnp", "pallas"])
def test_sharded_matches_unsharded_ivf_at_matched_probes(dtype, impl):
    q, flat, cells_u, sh, cells_s, mesh = _corpora(dtype)
    kw = dict(impl=impl, interpret=True if impl == "pallas" else None)
    for probes in (3, C):
        s_u, i_u = ivf_topk(q, flat["emb"], flat["valid"], K, cells=cells_u,
                            probes=probes, scales=flat["scales"], **kw)
        s_s, i_s = sharded_ivf_topk(q, sh["emb"], sh["valid"], K,
                                    cells=cells_s, probes=probes, mesh=mesh,
                                    scales=sh["scales"], **kw)
        s_u, i_u = np.asarray(s_u), np.asarray(i_u)
        s_s, i_s = np.asarray(s_s), np.asarray(i_s)
        finite = np.isfinite(s_u)
        np.testing.assert_array_equal(finite, np.isfinite(s_s))
        np.testing.assert_array_equal(i_u[finite], i_s[finite])
        # bitwise, not approx: same row bytes, same reduction order
        np.testing.assert_array_equal(s_u[finite].view(np.int32),
                                      s_s[finite].view(np.int32))


@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_sharded_at_full_probes_matches_exact_scorer(dtype):
    q, flat, _, sh, cells_s, mesh = _corpora(dtype)
    s_e, i_e = topk_fused(q, flat["emb"], flat["valid"], K,
                          scales=flat["scales"], impl="jnp")
    s_s, i_s = sharded_ivf_topk(q, sh["emb"], sh["valid"], K, cells=cells_s,
                                probes=C, mesh=mesh, scales=sh["scales"],
                                impl="jnp")
    np.testing.assert_array_equal(np.asarray(i_e), np.asarray(i_s))
    np.testing.assert_array_equal(np.asarray(s_e).view(np.int32),
                                  np.asarray(s_s).view(np.int32))


def test_oversized_k_degrades_to_sharded_exact():
    """k past the accumulator budget (_ACC_LANES) must fall back to the flat
    sharded scorer (honest degrade), never a truncated candidate list."""
    n, k = 1152, 129  # k > 128 lanes; shard rows 1152/8 = 144 >= k
    rng = np.random.default_rng(1)
    emb = rng.normal(size=(n, D)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    q = rng.normal(size=(B, D)).astype(np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    valid = jnp.ones(n, jnp.float32)
    km = kmeans_fit(jnp.asarray(emb), valid, C, seed=3)
    mesh = get_mesh()
    put = lambda x: shard_rows(x, mesh)
    cells = build_sharded_cells(jnp.asarray(emb), valid, None, km.centroids,
                                km.assign, n_shards=8, device_put=put)
    s_s, i_s = sharded_ivf_topk(jnp.asarray(q), put(jnp.asarray(emb)),
                                put(valid), k, cells=cells, probes=1,
                                mesh=mesh, impl="jnp")
    s_e, i_e = topk_fused(jnp.asarray(q), jnp.asarray(emb), valid, k,
                          impl="jnp")
    np.testing.assert_array_equal(np.asarray(i_e), np.asarray(i_s))
    np.testing.assert_array_equal(np.asarray(s_e).view(np.int32),
                                  np.asarray(s_s).view(np.int32))


def test_default_service_config_is_sharded_ivf():
    """`default_corpus` + a kwarg-less service on a multi-device host =
    sharded IVF serving, zero post-warmup compiles."""
    from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                                 init_params)
    from dae_rnn_news_recommendation_tpu.serve import (RecommendationService,
                                                       default_corpus)

    config = DAEConfig(n_features=24, n_components=8,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(0), config)
    articles = np.random.default_rng(0).random((64, 24), dtype=np.float32)
    corpus = default_corpus(config, block=16, n_cells=4)
    assert corpus.retrieval == "ivf" and corpus.mesh is not None
    corpus.swap(params, articles, note="seed")
    assert hasattr(corpus.active.ivf, "n_shards")
    svc = RecommendationService(params, config, corpus, top_k=5, max_batch=8,
                                probes=4)
    try:
        assert svc.sharded and svc.retrieval == "ivf"
        svc.warmup()
        reply = svc.submit(articles[0], deadline_s=30.0).result(timeout=30)
        assert reply.ok and reply.degraded == ()
        assert svc.summary()["compiles"]["post_warmup"] == 0
    finally:
        svc.stop()
