"""threadcheck (C1-C5) wiring into tier-1.

Mirrors test_jaxcheck.py for the concurrency rule family:
  * seeded   — the c*_ fixtures' planted violations fire and their clean
               twins stay silent (the parametrized fixture tests in
               test_jaxcheck.py already sweep them; here we pin the
               CROSS-FILE and call-graph behaviors those can't show);
  * self-clean — the repo's contract set has zero unsuppressed C findings;
  * CLI      — --select / --list-rules ergonomics;
  * suppressions — multi-rule one-line disables, standalone disable above a
               decorated def, unused-suppression reporting, and the
               SUP-cannot-be-suppressed laundering guard.
"""

import os
import textwrap
import threading

import pytest

from dae_rnn_news_recommendation_tpu.analysis import (
    RULES, analyze_file, analyze_paths, default_targets)
from dae_rnn_news_recommendation_tpu.analysis.__main__ import main as cli_main
from dae_rnn_news_recommendation_tpu.analysis.core import parse_suppressions

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "jaxcheck")
C_RULES = {"C1", "C2", "C3", "C4", "C5"}


def _write(path, src):
    path.write_text(textwrap.dedent(src))
    return str(path)


# ---------------------------------------------------------------- registry

def test_c_rules_registered():
    assert C_RULES <= set(RULES)


# -------------------------------------------------- cross-file / call graph

def test_c2_inversion_across_modules(tmp_path):
    """The tentpole case per-file analysis cannot see: module A orders
    a_lock -> b_lock, module B (importing both) orders b_lock -> a_lock.
    The whole-package index keys module-level locks globally, so each file
    gets its own finding at its inner acquisition."""
    pkg = tmp_path / "lockpkg"
    pkg.mkdir()
    _write(pkg / "__init__.py", "")
    mod_a = _write(pkg / "mod_a.py", """\
        import threading

        a_lock = threading.Lock()
        b_lock = threading.Lock()


        def forward(d):
            with a_lock:
                with b_lock:
                    d["fwd"] = True
        """)
    mod_b = _write(pkg / "mod_b.py", """\
        from .mod_a import a_lock, b_lock


        def backward(d):
            with b_lock:
                with a_lock:
                    d["bwd"] = True
        """)
    fa, _ = analyze_file(mod_a, root=str(tmp_path))
    fb, _ = analyze_file(mod_b, root=str(tmp_path))
    assert [f.rule for f in fa] == ["C2"]
    assert [f.rule for f in fb] == ["C2"]
    # each finding names the opposite order's location in the OTHER module
    assert "mod_b.py" in fa[0].message
    assert "mod_a.py" in fb[0].message


def test_c5_through_call_graph(tmp_path):
    """A helper only ever called under the lock is analyzed with the lock
    held — the resolution inside it is flagged even though no `with` is
    lexically visible there."""
    p = _write(tmp_path / "helper_resolve.py", """\
        import threading


        class Resolver:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def finish(self, fut, value):
                with self._lock:
                    self._n += 1
                    self._mark(fut, value)

            def _mark(self, fut, value):
                fut.set_result(value)
        """)
    findings, _ = analyze_file(p, root=str(tmp_path))
    assert [f.rule for f in findings] == ["C5"]
    assert findings[0].line == 15   # inside _mark, not at the call site


def test_c1_tolerates_helper_called_under_lock(tmp_path):
    """The inverse of the C5 case: a write inside a helper counts as locked
    when every call site holds the lock — no false positive."""
    p = _write(tmp_path / "helper_write.py", """\
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = None

            def put(self, v):
                with self._lock:
                    self._store(v)

            def swap(self, v):
                with self._lock:
                    old = self._v
                    self._store(v)
                    return old

            def _store(self, v):
                self._v = v
        """)
    findings, _ = analyze_file(p, root=str(tmp_path))
    assert findings == []


# -------------------------------------------------------------- self-clean

def test_repo_is_self_clean_for_c_rules():
    """The acceptance criterion, scoped to the new family: zero unsuppressed
    C findings on the package + bench.py + evidence/."""
    root, targets = default_targets()
    findings, _, n_files = analyze_paths(targets, root=root, select=C_RULES)
    assert n_files > 30
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------------------- CLI

def test_cli_list_rules(capsys):
    rc = cli_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    listed = {line.split(":")[0] for line in out.splitlines() if ":" in line}
    assert C_RULES <= listed
    assert {"R1", "R14"} <= listed


def test_cli_select_runs_only_named_rules(capsys):
    path = os.path.join(FIXTURE_DIR, "c4_thread_leak.py")
    rc = cli_main(["--select", "C4", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "C4" in out
    # same file, disjoint selection: nothing fires
    rc = cli_main(["--select", "C1,C2", path])
    assert rc == 0


def test_cli_select_unknown_rule_is_usage_error(capsys):
    rc = cli_main(["--select", "C9", os.path.join(FIXTURE_DIR,
                                                  "c4_thread_leak.py")])
    err = capsys.readouterr().err
    assert rc == 2
    assert "C9" in err


# ------------------------------------------------------------- suppressions

def test_multi_rule_one_line_disable(tmp_path):
    """`disable=C3,C5` silences two rules firing on the same line, and both
    count as used (no stale-disable report)."""
    p = _write(tmp_path / "multi.py", """\
        import queue
        import threading


        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue(maxsize=2)

            def pump(self, fut):
                with self._lock:
                    # jaxcheck: disable=C3,C5 (producer is bound and lock-free; fut carries no callbacks)
                    fut.set_result(self._q.get())
        """)
    findings, suppressed = analyze_file(p, root=str(tmp_path))
    assert findings == []
    assert sorted(s.rule for s in suppressed) == ["C3", "C5"]


def test_standalone_disable_above_decorated_def():
    """A comment between the decorator and the `def` is legal Python; the
    tokenizer must surface it and it covers the def line below — the
    documented placement for suppressing a def-anchored finding. It does
    NOT stretch into the body."""
    src = ("import functools\n"
           "@functools.lru_cache\n"
           "# jaxcheck: disable=C4 (demo placement)\n"
           "def f():\n"
           "    return 1\n")
    sups = parse_suppressions(src)
    assert len(sups) == 1
    assert sups[0].line == 3
    assert sups[0].rules == ("C4",)
    assert sups[0].covers(4, "C4")        # the def line directly below
    assert not sups[0].covers(5, "C4")    # never the body


def test_docstring_disable_is_prose_not_suppression():
    """The token-aware parser ignores disables quoted inside strings — a
    docstring SHOWING the syntax must neither suppress nor be reported as
    an unused disable."""
    src = ('"""Example:\n'
           '    x = y  # jaxcheck: disable=R3 (docs only)\n'
           '"""\n')
    assert parse_suppressions(src) == []


def test_unused_suppression_is_reported(tmp_path):
    p = _write(tmp_path / "stale.py", """\
        import threading


        def tidy():
            # jaxcheck: disable=C4 (was a leak once, fixed since)
            t = threading.Thread(target=print, daemon=True)
            t.start()
        """)
    findings, _ = analyze_file(p, root=str(tmp_path))
    assert [f.rule for f in findings] == ["SUP"]
    assert "unused suppression" in findings[0].message
    # ...but not when the named rule was excluded from the run: a rule that
    # didn't execute proves nothing about the disable
    findings, _ = analyze_file(p, root=str(tmp_path), select={"C1"})
    assert findings == []


def test_sup_not_launderable_via_reasoned_disable(tmp_path):
    """Even a REASONED `disable=SUP` cannot silence SUP: SUP findings are
    generated after suppression matching, and naming SUP is itself an
    unknown-rule finding."""
    p = _write(tmp_path / "launder.py", """\
        import threading


        def tidy():
            # jaxcheck: disable=SUP (attempting to launder)
            # jaxcheck: disable=C4
            t = threading.Thread(target=print)
            t.start()
        """)
    findings, _ = analyze_file(p, root=str(tmp_path))
    rules = [f.rule for f in findings]
    assert rules.count("SUP") >= 2   # unknown-rule SUP + reasonless disable
    assert "C4" in rules             # the reasonless disable silenced nothing


# ------------------------------------------------- thread-exception fixture

@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_thread_excepthook_records_and_is_consumable(_thread_exception_log):
    """The conftest session fixture sees uncaught background-thread
    exceptions; a test that EXPECTS one consumes the record so the autouse
    teardown check doesn't fail it."""
    start = len(_thread_exception_log)

    def boom():
        raise ZeroDivisionError("deliberate")

    t = threading.Thread(target=boom, daemon=True)
    t.start()
    t.join(timeout=5.0)
    assert len(_thread_exception_log) == start + 1
    assert _thread_exception_log[-1].exc_type is ZeroDivisionError
    del _thread_exception_log[start:]   # consumed: this crash was the point
