"""Resident-epoch execution (train/resident.py): the whole-epoch lax.scan path
must reproduce the streaming per-batch path exactly — same batcher permutation,
same PRNG chain, same padded-row handling — so the two fits agree on parameters
and per-step metrics to float tolerance (different XLA programs, so not
bitwise). No reference counterpart (the reference dispatches one Session.run
per batch, autoencoder/autoencoder.py:233)."""

import numpy as np
import pytest
import scipy.sparse as sp
import jax

from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.train.resident import (
    build_resident, resident_bytes, stack_epoch_indices)
from dae_rnn_news_recommendation_tpu.data.batcher import PaddedBatcher


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _data(rng, n=37, f=24, sparse=False):
    x = (rng.uniform(size=(n, f)) < 0.25).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    return (sp.csr_matrix(x) if sparse else x), labels


def _fit(workdir, resident, rng_seed=0, sparse=False, epochs=3, **kw):
    rng = np.random.default_rng(rng_seed)
    x, labels = _data(rng, sparse=sparse)
    model = DenoisingAutoencoder(
        model_name=f"res_{resident}_{sparse}", main_dir=f"res_{resident}_{sparse}",
        n_components=6, num_epochs=epochs, batch_size=10, seed=7,
        corr_type="masking", corr_frac=0.3, loss_func="mean_squared",
        opt="ada_grad", learning_rate=0.1, verbose=False, verbose_step=10,
        use_tensorboard=False, resident_feed=resident,
        results_root=str(workdir / "results"), **kw)
    model.fit(x, train_set_label=labels,
              **({"train_set_label2": (labels + 1) % 4}
                 if kw.get("label2_alpha") else {}))
    return model


@pytest.mark.parametrize("sparse", [False, True])
def test_resident_matches_streaming(workdir, sparse):
    """Same seed, same data: resident and streaming fits converge to the same
    parameters (the strongest possible equivalence for the scan rewrite)."""
    m_stream = _fit(workdir, resident=False, sparse=sparse)
    m_res = _fit(workdir, resident=True, sparse=sparse)
    assert m_res._last_fit_resident and not m_stream._last_fit_resident
    for k in ("W", "bh", "bv"):
        np.testing.assert_allclose(
            np.asarray(m_stream.params[k]), np.asarray(m_res.params[k]),
            rtol=2e-5, atol=2e-6, err_msg=k)


def test_resident_matches_streaming_with_label2(workdir):
    m_stream = _fit(workdir, resident=False, label2_alpha=0.5)
    m_res = _fit(workdir, resident=True, label2_alpha=0.5)
    for k in ("W", "bh", "bv"):
        np.testing.assert_allclose(
            np.asarray(m_stream.params[k]), np.asarray(m_res.params[k]),
            rtol=2e-5, atol=2e-6, err_msg=k)


def test_resident_trajectory_matches_streaming(workdir):
    """Per-step costs line up too — parity holds step by step, not just at the
    end (catches compensating errors)."""
    logs = {}
    for resident in (False, True):
        rng = np.random.default_rng(0)
        x, labels = _data(rng)
        model = DenoisingAutoencoder(
            model_name=f"traj{resident}", main_dir=f"traj{resident}",
            n_components=6, num_epochs=2, batch_size=10, seed=3,
            corr_type="masking", corr_frac=0.3, triplet_strategy="batch_all",
            opt="gradient_descent", learning_rate=0.05, verbose=False,
            verbose_step=10, use_tensorboard=False, resident_feed=resident,
            results_root=str(workdir / "results"))
        model.fit(x, train_set_label=labels)
        logs[resident] = [model.train_cost_batch[0], model.train_cost_batch[2]]
    np.testing.assert_allclose(logs[False], logs[True], rtol=2e-4, atol=1e-6)


def test_stack_epoch_indices_mirrors_streaming_batcher():
    """Two batchers with the same seed: the stacked indices equal the streamed
    epoch's batch composition (same rows, same order, same padding)."""
    n = 23
    b1 = PaddedBatcher(5, shuffle=True, seed=11)
    b2 = PaddedBatcher(5, shuffle=True, seed=11)
    perm, rv = stack_epoch_indices(b1, n)
    streamed = list(b2._index_batches(n))
    assert perm.shape == (len(streamed), 5)
    for i, (idx, _n_real, valid) in enumerate(streamed):
        np.testing.assert_array_equal(perm[i], idx)
        np.testing.assert_array_equal(rv[i], valid)
    # padding row: last batch has 23 % 5 = 3 real rows
    assert rv[-1].sum() == 3.0


def test_build_resident_sparse_layout_matches_streaming_feed():
    """Resident sparse arrays use the same padded layout as the streaming
    SparseIngestBatcher, so the on-device densify sees identical input."""
    rng = np.random.default_rng(5)
    x = sp.csr_matrix((rng.uniform(size=(9, 16)) < 0.3).astype(np.float32))
    res = build_resident(x)
    from dae_rnn_news_recommendation_tpu.data.batcher import SparseIngestBatcher

    batcher = SparseIngestBatcher(9, shuffle=False)
    batch = next(batcher.epoch(x))
    np.testing.assert_array_equal(np.asarray(res["indices"]), batch["indices"])
    np.testing.assert_allclose(np.asarray(res["values"]), batch["values"])


def test_resident_bytes_estimate():
    rng = np.random.default_rng(6)
    dense = rng.uniform(size=(10, 20)).astype(np.float32)
    assert resident_bytes(dense) == 10 * 20 * 4
    sparse = sp.csr_matrix((dense < 0.1).astype(np.float32))
    assert resident_bytes(sparse) > 0


def test_resident_bytes_mirrors_pad_csr_rows_layout():
    """The auto-budget estimate must match what build_resident ACTUALLY
    allocates: pad_csr_rows rounds k up to a multiple of 64 and flips to
    uint32 indices past the uint16 feature range — the raw-csr estimate
    underestimated ~13x at low density and could admit a feed that OOMs the
    chip (ADVICE r05)."""
    # k=3 max nnz/row -> padded kk=64; f=100 -> uint16 (2B) indices + f32 values
    rows = np.zeros((10, 100), np.float32)
    rows[:, :3] = 1.0
    small = sp.csr_matrix(rows)
    assert resident_bytes(small) == 10 * 64 * (2 + 4)
    # labels ride along as int32, one per row (labels2 doubles it)
    labels = np.zeros(10, np.int32)
    assert resident_bytes(small, labels) == 10 * 64 * (2 + 4) + 10 * 4
    assert resident_bytes(small, labels, labels) == 10 * 64 * (2 + 4) + 2 * 10 * 4
    # feature count past the uint16 range -> 4-byte indices
    big = sp.csr_matrix((np.ones(3, np.float32), np.array([0, 70000, 70001]),
                         np.array([0, 3])), shape=(1, 70002))
    assert resident_bytes(big) == 1 * 64 * (4 + 4)
    # the estimate must match build_resident's real allocation exactly
    res = build_resident(small)
    actual = sum(np.asarray(v).nbytes for v in res.values())
    assert resident_bytes(small) == actual


def test_resident_never_active_on_multi_device(workdir):
    """A mesh (or n_devices>1) fit must keep the mesh-sharded step: the
    resident scan is single-device and would silently train on one chip while
    the rest idle (ADVICE r05)."""
    rng = np.random.default_rng(0)
    x, _labels = _data(rng)
    model = DenoisingAutoencoder(
        model_name="md", main_dir="md", n_components=6, num_epochs=1,
        batch_size=10, seed=1, verbose=False, use_tensorboard=False,
        resident_feed=True, results_root=str(workdir / "results"))
    assert model._resident_active(x) is True  # single-device: forced on
    model.n_devices = 2
    assert model._resident_active(x) is False
    model.n_devices = 1
    model.mesh = object()  # any mesh sentinel disqualifies
    assert model._resident_active(x) is False


def test_resident_fit_multi_device_keeps_mesh_step(workdir):
    """End to end: an 8-virtual-device fit with resident_feed=True must run
    the mesh-sharded path, not the single-device scan."""
    rng = np.random.default_rng(0)
    x, labels = _data(rng, n=40)
    model = DenoisingAutoencoder(
        model_name="md8", main_dir="md8", n_components=6, num_epochs=1,
        batch_size=8, seed=1, verbose=False, use_tensorboard=False,
        resident_feed=True, n_devices=8,
        results_root=str(workdir / "results"))
    model.fit(x, train_set_label=labels)
    assert model._last_fit_resident is False
    assert model._last_fit_feed == "stream"


def test_moe_never_enters_resident_path(workdir):
    """The MoE estimator overrides _loss_fn with the mixture objective and
    [E,F,D] params; a resident scan would train the WRONG objective on an
    incompatible gather layout — forced resident must fall back to streaming
    (ADVICE r05)."""
    from dae_rnn_news_recommendation_tpu.models import MoEDenoisingAutoencoder

    rng = np.random.default_rng(0)
    x = (rng.uniform(size=(48, 32)) < 0.2).astype(np.float32)
    labels = rng.integers(0, 4, 48).astype(np.int32)
    model = MoEDenoisingAutoencoder(
        n_experts=4, model_name="moe_res", main_dir="moe_res", n_components=6,
        num_epochs=1, batch_size=16, seed=1, triplet_strategy="none",
        corr_type="masking", corr_frac=0.3, verbose=False,
        use_tensorboard=False, resident_feed=True,
        results_root=str(workdir / "results"))
    assert model._resident_active(x) is False
    model.fit(x, train_set_label=labels)
    assert model._last_fit_resident is False
    # the mixture params survived the fit (a resident scan would have crashed
    # or silently trained the base objective)
    assert np.asarray(model.params["W"]).ndim == 3


def test_resident_auto_is_off_on_cpu(workdir):
    """`auto` must not flip CPU fits onto the scan path (keeps existing CPU
    evidence byte-stable); explicit True forces it anywhere."""
    rng = np.random.default_rng(0)
    x, labels = _data(rng)
    model = DenoisingAutoencoder(
        model_name="auto", main_dir="auto", n_components=6, num_epochs=1,
        batch_size=10, seed=1, verbose=False, use_tensorboard=False,
        results_root=str(workdir / "results"))
    assert jax.default_backend() == "cpu"
    assert model._resident_active(x) is False
    model.resident_feed = True
    assert model._resident_active(x) is True
    model.resident_feed = False
    assert model._resident_active(x) is False


def test_resident_checkpoint_resume(workdir):
    """Graceful-resume parity: a resident fit checkpointed mid-run and resumed
    matches an uninterrupted resident fit (epoch-exact resume, SURVEY §2.3.12
    fix, exercised through the scan path)."""
    rng = np.random.default_rng(0)
    x, labels = _data(rng)

    def make(name, epochs):
        return DenoisingAutoencoder(
            model_name=name, main_dir=name, n_components=6, num_epochs=epochs,
            batch_size=10, seed=5, corr_type="masking", corr_frac=0.3,
            opt="ada_grad", learning_rate=0.1, verbose=False,
            use_tensorboard=False, resident_feed=True,
            results_root=str(workdir / "results"))

    full = make("full", 4)
    full.fit(x, train_set_label=labels)

    part = make("part", 2)
    part.fit(x, train_set_label=labels)
    resumed = make("part", 2)
    resumed.fit(x, train_set_label=labels, restore_previous_model=True)

    # resume restarts the batcher's shuffle stream, so exact equality with the
    # uninterrupted run is not expected — but the loss must keep improving and
    # the epoch counter must be exact
    assert resumed._epoch0 == 2
    assert resumed._last_epoch == 4


def test_sparse_encode_scan_matches_per_batch():
    """sparse_encode_scan (one dispatch over stacked batches, used by the
    bench's dispatch-decomposition figures) equals per-batch sparse_encode."""
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
    from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import (
        pad_csr_batch, sparse_encode, sparse_encode_scan)

    rng = np.random.default_rng(9)
    config = DAEConfig(n_features=32, n_components=6, enc_act_func="sigmoid",
                       dec_act_func="none", loss_func="mean_squared",
                       corr_type="none", corr_frac=0.0, triplet_strategy="none")
    params = init_params(jax.random.PRNGKey(0), config)
    mats = [sp.csr_matrix((rng.uniform(size=(8, 32)) < 0.3).astype(np.float32))
            for _ in range(3)]
    packed = [pad_csr_batch(m, k=16) for m in mats]
    idx = np.stack([p["indices"] for p in packed])
    vals = np.stack([p["values"] for p in packed])

    scanned = sparse_encode_scan(params, idx, vals, config, chunk=8)
    for i, p in enumerate(packed):
        one = sparse_encode(params, p["indices"], p["values"], config, chunk=8)
        np.testing.assert_allclose(np.asarray(scanned[i]), np.asarray(one),
                                   rtol=1e-6, atol=1e-7)
    # binary mode (values=None): padding points at index F, W extended inside
    packed_b = [pad_csr_batch(m, k=16, binary=True) for m in mats]
    idx_b = np.stack([p["indices"] for p in packed_b])
    scanned_b = sparse_encode_scan(params, idx_b, None, config, chunk=8)
    for i, p in enumerate(packed_b):
        one = sparse_encode(params, p["indices"], None, config, chunk=8)
        np.testing.assert_allclose(np.asarray(scanned_b[i]), np.asarray(one),
                                   rtol=1e-6, atol=1e-7)
