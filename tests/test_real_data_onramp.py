"""Real-data on-ramp: the stripped UCI parquet can't ship in this mount, so a
tiny checked-in fixture with the REAL schema (article_id / title /
main_content / category_publish_name, no story column, CJK text, ragged
bodies) proves the drop-the-parquet-here path end to end — loader edge cases
(reference datasets/articles.py:47-68), the story-from-title regex, the jieba
tokenizer branch, and the full main_autoencoder driver on --data_path.

Fixture: tests/fixtures/articles_fixture.snappy.parquet (43 rows; 3 are
empty/whitespace/NaN bodies the loader must drop). Regenerate with the
snippet in this repo's git history (commit introducing this file).
"""

import os

import numpy as np
import pytest

from dae_rnn_news_recommendation_tpu.data import articles

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "articles_fixture.snappy.parquet")


def test_read_articles_real_schema():
    df = articles.read_articles(FIXTURE)
    # the 3 degenerate bodies are gone (reference :61-62 drops them)
    assert len(df) == 40
    assert df.index.tolist() == df.article_id.tolist()
    # story extracted from 【...（/】 titles only (reference :65-66)
    assert df.story.notna().sum() == 14  # every 3rd of 40 rows has the marker
    assert set(df.story.dropna()) == {"食物設計", "美劇巡禮", "選舉2024"}
    # untouched schema columns survive
    assert {"title", "main_content", "category_publish_name"} <= set(df.columns)


def test_story_column_respected_when_present():
    df = articles.read_articles(FIXTURE)
    df2 = df.copy()
    df2["story"] = "preset"
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "with_story.parquet")
        df2.reset_index(drop=True).to_parquet(p, index=False)
        back = articles.read_articles(p)
    assert (back.story == "preset").all()  # regex must not overwrite


def test_jieba_tokenizer_branch():
    if articles.tokenizer_chinese is None:
        pytest.skip("jieba not installed")
    toks = articles.tokenizer_chinese("政府公布最新經濟數據123 market")
    assert toks and all(len(t) > 1 for t in toks)
    assert not any(t.isdigit() for t in toks)
    # vectorizing the real-schema fixture through the jieba branch
    df = articles.read_articles(FIXTURE)
    vec, X, _, _ = articles.count_vectorize(
        df.main_content, tokenizer=articles.tokenizer_chinese,
        max_features=200, binary=True)
    assert X.shape == (40, min(200, len(vec.vocabulary_)))
    assert X.nnz > 0


def test_driver_end_to_end_on_real_parquet(tmp_path, monkeypatch):
    """The full online-mining driver against --data_path (NOT --synthetic):
    real-schema read, 即時-prefix category normalization, label engineering,
    vectorization, fit, and the 12-AUROC eval tail all run."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    monkeypatch.chdir(tmp_path)
    model, aurocs = main([
        "--model_name", "fixture_e2e", "--data_path", FIXTURE,
        "--validation", "--num_epochs", "2",
        "--train_row", "30", "--validate_row", "10",
        "--max_features", "150", "--batch_size", "0.5",
        "--triplet_strategy", "batch_all", "--corr_type", "masking",
        "--corr_frac", "0.3", "--seed", "0",
    ])
    for k, v in aurocs.items():
        assert np.isfinite(v), (k, v)
    assert "similarity_boxplot_encoded_validate(Category)" in aurocs
    # the 即時體育 category must have been normalized (reference :186 strips
    # the 即時 live-news prefix before factorizing): 即時體育 and 體育 rows
    # share one label id while the raw column keeps the prefix
    import pandas as pd

    saved = pd.concat([
        pd.read_parquet(os.path.join(model.data_dir, p))
        for p in ("article.snappy.parquet", "article_validate.snappy.parquet")
    ])
    assert (saved.category_publish_name.str.startswith("即時")).any()
    live = saved[saved.category_publish_name == "即時體育"]
    plain = saved[saved.category_publish_name == "體育"]
    assert len(live) and len(plain)
    assert (set(live.label_category_publish_name)
            == set(plain.label_category_publish_name))
