"""Clustered two-stage retrieval contracts (ISSUE 11 tentpole).

The IVF path's promise mirrors the fused scorer's: at `probes = n_cells`
the clustered scorer IS the exact scorer — scores bitwise, indices
tie-exact — for BOTH implementations (`impl="jnp"` off-TPU fallback and
`impl="pallas", interpret=True` exercising the gather/mask/selection
kernel on CPU). Below full probing the two implementations must still
agree with each other wherever scores are finite. The adversarial corners:
duplicate rows (3x score ties), hand-built empty cells, k exceeding the
shortlist (pinned to the honest exact-degrade), an all-invalid corpus, and
int8 quantized cells.

On top: k-means fit/reseed/determinism, the cell-major layout permutation
invariants, corpus/service wiring (`retrieval="ivf"`), churn composition
(appends route into existing cells WITHOUT refitting; sustained imbalance
trips a background reindex), and the sharded-composition contracts: a
mesh-sharded slot built from a bare `device_put` closure now APPENDS
through the two-phase protocol (ISSUE 13 replaced the r11 refusal), and
ivf + sharded COMPOSES (r16): a mesh corpus builds the shard-major index
and the service derives sharded+ivf as its default configuration. The full
sharded-IVF parity suite lives in tests/test_ivf_sharded.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.index import (CAP_ROUND, assign_cells,
                                                   build_cells, cell_stats,
                                                   kmeans_fit)
from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.ops.ivf_topk import ivf_topk
from dae_rnn_news_recommendation_tpu.ops.topk_fused import _IDX_SENTINEL
from dae_rnn_news_recommendation_tpu.parallel import get_mesh, shard_rows
from dae_rnn_news_recommendation_tpu.refresh import (ChurnConfig,
                                                     ChurnSupervisor)
from dae_rnn_news_recommendation_tpu.serve import (RecommendationService,
                                                   ServingCorpus,
                                                   SwapRejected,
                                                   dequantize_rows,
                                                   make_serve_fn,
                                                   quantize_corpus)

# pallas-interpret runs the real kernel logic (scalar-prefetch gather,
# membership mask, selection network) on CPU; jnp is the off-TPU path
PALLAS = dict(impl="pallas", interpret=True)
JNP = dict(impl="jnp")


def _oracle(queries, emb, valid, k, scales=None):
    """Exact masked-matmul + lax.top_k — no code shared with ops/."""
    scores = jnp.asarray(queries, jnp.float32) @ jnp.asarray(
        emb).astype(jnp.float32).T
    if scales is not None:
        scores = scores * jnp.asarray(scales, jnp.float32)[None, :]
    scores = jnp.where(jnp.asarray(valid)[None, :] > 0, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _case(b=6, n=200, d=16, n_valid=None, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((b, d), dtype=np.float32)
    e = rng.standard_normal((n, d), dtype=np.float32)
    e /= np.linalg.norm(e, axis=1, keepdims=True)
    valid = np.zeros(n, np.float32)
    valid[:n if n_valid is None else n_valid] = 1.0
    return q, e, valid


def _fit_cells(e, valid, n_cells, scales=None, seed=0):
    fit = kmeans_fit(jnp.asarray(e, jnp.float32) if scales is None
                     else dequantize_rows(jnp.asarray(e),
                                          jnp.asarray(scales), e.shape[0]),
                     jnp.asarray(valid), n_cells, seed=seed)
    return build_cells(jnp.asarray(e), jnp.asarray(valid), scales,
                       fit.centroids, fit.assign)


def _ivf(q, e, valid, k, cells, probes, scales=None, **kw):
    return jax.device_get(ivf_topk(
        jnp.asarray(q), jnp.asarray(e), jnp.asarray(valid), k, cells=cells,
        probes=probes, scales=None if scales is None else jnp.asarray(scales),
        **kw))


# --------------------------------------------------------------- kmeans

def test_kmeans_partitions_all_valid_rows():
    _, e, valid = _case(n=120, d=12, n_valid=100, seed=1)
    fit = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 7, seed=1)
    assert fit.centroids.shape == (7, 12)
    assert int(fit.counts.sum()) == 100          # every valid row owned once
    np.testing.assert_allclose(np.linalg.norm(fit.centroids, axis=1), 1.0,
                               rtol=1e-5)
    assert np.isfinite(fit.inertia)


def test_kmeans_is_deterministic_per_seed():
    _, e, valid = _case(n=90, d=10, seed=2)
    a = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 5, seed=4)
    b = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 5, seed=4)
    np.testing.assert_array_equal(a.centroids, b.centroids)
    np.testing.assert_array_equal(a.assign, b.assign)
    c = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 5, seed=5)
    assert not np.array_equal(a.assign, c.assign)  # seed actually matters


def test_kmeans_reseeds_rather_than_nan_on_degenerate_data():
    # 3 distinct rows, 8 requested cells: most Lloyd cells go empty every
    # iteration — the reseed step must keep every centroid finite/unit
    base = np.random.default_rng(3).standard_normal((3, 8)).astype(np.float32)
    e = np.tile(base, (10, 1))
    e /= np.linalg.norm(e, axis=1, keepdims=True)
    fit = kmeans_fit(jnp.asarray(e), jnp.ones(30, np.float32), 8, seed=0)
    assert np.all(np.isfinite(fit.centroids))
    np.testing.assert_allclose(np.linalg.norm(fit.centroids, axis=1), 1.0,
                               rtol=1e-5)
    assert int(fit.counts.sum()) == 30


def test_kmeans_accepts_drift_gate_centroid_seed():
    _, e, valid = _case(n=80, d=12, seed=6)
    seed_vec = np.asarray(e[:40].mean(axis=0), np.float32)
    a = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 4, seed=2,
                   init_centroid=seed_vec)
    b = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 4, seed=2,
                   init_centroid=seed_vec)
    np.testing.assert_array_equal(a.centroids, b.centroids)


def test_assign_cells_is_nearest_centroid_by_cosine():
    _, e, valid = _case(n=60, d=12, seed=7)
    fit = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 5, seed=7)
    got = assign_cells(jnp.asarray(e), fit.centroids)
    want = np.argmax(np.asarray(e) @ np.asarray(fit.centroids).T, axis=1)
    np.testing.assert_array_equal(got, want.astype(np.int32))


# --------------------------------------------------------------- layout

def test_build_cells_is_a_permutation_of_the_slot():
    _, e, valid = _case(n=150, d=12, n_valid=140, seed=8)
    fit = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 6, seed=8)
    cells = build_cells(jnp.asarray(e), jnp.asarray(valid), None,
                        fit.centroids, fit.assign)
    assert cells.cell_cap % CAP_ROUND == 0
    ids = np.asarray(cells.row_ids)
    real = ids[ids != _IDX_SENTINEL]
    # every original row (valid AND padding) placed exactly once
    np.testing.assert_array_equal(np.sort(real), np.arange(150))
    # a placed row's payload is the slot row, moved not recomputed
    emb = np.asarray(cells.cell_emb)
    np.testing.assert_array_equal(emb[ids != _IDX_SENTINEL],
                                  np.asarray(e)[real])
    # dummy cell (last slab) is all padding, and padding slots are invalid
    cap = cells.cell_cap
    assert np.all(ids[-cap:] == _IDX_SENTINEL)
    np.testing.assert_array_equal(
        np.asarray(cells.cell_valid)[ids == _IDX_SENTINEL], 0.0)


def test_cell_stats_reports_occupancy():
    _, e, valid = _case(n=100, d=10, seed=9)
    fit = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 4, seed=9)
    cells = build_cells(jnp.asarray(e), jnp.asarray(valid), None,
                        fit.centroids, fit.assign)
    st = cell_stats(cells)
    assert st["n_cells"] == 4 and st["n_rows"] == 100
    assert st["imbalance"] >= 1.0 and 0.0 <= st["frac_empty"] <= 1.0
    assert int(st["counts"].sum()) == 100


# ------------------------------------------------- kernel parity (tentpole)

@pytest.mark.parametrize("impl_kw", [PALLAS, JNP],
                         ids=["pallas-interpret", "jnp"])
class TestFullProbeParity:
    """probes = n_cells: the clustered scorer must BE the exact scorer."""

    def test_bitwise_vs_oracle(self, impl_kw):
        q, e, valid = _case(b=9, n=300, d=24, n_valid=290, seed=10)
        cells = _fit_cells(e, valid, 7, seed=10)
        s, i = _ivf(q, e, valid, 10, cells, probes=7, **impl_kw)
        es, ei = jax.device_get(_oracle(q, e, valid, 10))
        np.testing.assert_array_equal(s, np.asarray(es))  # bitwise
        np.testing.assert_array_equal(i, np.asarray(ei))

    def test_duplicate_rows_tie_break_by_ascending_index(self, impl_kw):
        rng = np.random.default_rng(11)
        q = rng.standard_normal((5, 12)).astype(np.float32)
        base = rng.standard_normal((30, 12)).astype(np.float32)
        e = np.concatenate([base, base, base])      # every score appears 3x
        e /= np.linalg.norm(e, axis=1, keepdims=True)
        valid = np.ones(90, np.float32)
        cells = _fit_cells(e, valid, 5, seed=11)
        s, i = _ivf(q, e, valid, 9, cells, probes=5, **impl_kw)
        es, ei = jax.device_get(_oracle(q, e, valid, 9))
        np.testing.assert_array_equal(s, np.asarray(es))
        np.testing.assert_array_equal(i, np.asarray(ei))

    def test_hand_built_empty_cells(self, impl_kw):
        # an assign that never touches cells 2 and 5: probing them must be
        # an inert panel scan, not garbage candidates
        q, e, valid = _case(b=4, n=80, d=12, seed=12)
        fit = kmeans_fit(jnp.asarray(e), jnp.asarray(valid), 6, seed=12)
        assign = np.asarray(fit.assign).copy()
        assign[assign == 2] = 1
        assign[assign == 5] = 0
        cells = build_cells(jnp.asarray(e), jnp.asarray(valid), None,
                            fit.centroids, assign)
        assert cell_stats(cells)["frac_empty"] >= 2 / 6
        s, i = _ivf(q, e, valid, 8, cells, probes=6, **impl_kw)
        es, ei = jax.device_get(_oracle(q, e, valid, 8))
        np.testing.assert_array_equal(s, np.asarray(es))
        np.testing.assert_array_equal(i, np.asarray(ei))

    def test_all_rows_invalid(self, impl_kw):
        q, e, valid = _case(b=4, n=96, d=12, seed=13)
        valid[:] = 0.0
        # fit on the geometry, but the LAYOUT carries the slot's real (all
        # zero) valid mask — the kernel reads validity from cell_valid
        fit = kmeans_fit(jnp.asarray(e), jnp.ones(96, np.float32), 4,
                         seed=13)
        cells = build_cells(jnp.asarray(e), jnp.asarray(valid), None,
                            fit.centroids, fit.assign)
        s, i = _ivf(q, e, valid, 6, cells, probes=4, **impl_kw)
        assert np.all(np.isneginf(s))
        # -inf ties break by ascending ORIGINAL row id, like lax.top_k
        np.testing.assert_array_equal(i, np.tile(np.arange(6), (4, 1)))

    def test_int8_cells(self, impl_kw):
        q, e, valid = _case(b=6, n=200, d=16, seed=14)
        eq, scales = quantize_corpus(jnp.asarray(e), "int8")
        cells = _fit_cells(np.asarray(eq), valid, 5,
                           scales=np.asarray(scales), seed=14)
        assert np.asarray(cells.cell_emb).dtype == np.int8  # moved, not cast
        s, i = _ivf(q, np.asarray(eq), valid, 7, cells, probes=5,
                    scales=np.asarray(scales), **impl_kw)
        es, ei = jax.device_get(_oracle(q, np.asarray(eq), valid, 7,
                                        scales=np.asarray(scales)))
        np.testing.assert_array_equal(s, np.asarray(es))
        np.testing.assert_array_equal(i, np.asarray(ei))


def test_partial_probe_impls_agree_and_recall_is_sane():
    q, e, valid = _case(b=16, n=400, d=24, seed=15)
    cells = _fit_cells(e, valid, 8, seed=15)
    sp, ip = _ivf(q, e, valid, 10, cells, probes=3, **PALLAS)
    sj, ij = _ivf(q, e, valid, 10, cells, probes=3, **JNP)
    # identical candidate sets -> identical finite results; the -inf tail's
    # indices are the one documented divergence (sentinel vs top_k filler)
    finite = np.isfinite(sj)
    np.testing.assert_array_equal(sp, sj)
    np.testing.assert_array_equal(ip[finite], ij[finite])
    _, ei = jax.device_get(_oracle(q, e, valid, 10))
    recall = np.mean([len(set(a) & set(b)) / 10.0
                      for a, b in zip(ij, np.asarray(ei))])
    assert recall >= 0.5, f"recall@10 {recall:.2f} at 3/8 probes"


def test_k_beyond_shortlist_degrades_to_exact():
    # probes=1 -> shortlist of cell_cap rows < k: the call must return the
    # EXACT answer over the flat slot, not a truncated shortlist
    q, e, valid = _case(b=3, n=120, d=12, seed=16)
    cells = _fit_cells(e, valid, 4, seed=16)
    k = cells.cell_cap + 8
    assert k <= 120                        # still a valid k for the corpus
    s, i = _ivf(q, e, valid, k, cells, probes=1, **JNP)
    es, ei = jax.device_get(_oracle(q, e, valid, k))
    np.testing.assert_array_equal(s, np.asarray(es))
    np.testing.assert_array_equal(i, np.asarray(ei))


def test_k_bounds_are_validated():
    q, e, valid = _case(b=2, n=64, d=8, seed=17)
    cells = _fit_cells(e, valid, 2, seed=17)
    for bad in (0, 65):
        with pytest.raises(ValueError, match="outside"):
            ivf_topk(jnp.asarray(q), jnp.asarray(e), jnp.asarray(valid),
                     bad, cells=cells, probes=2)


# ------------------------------------------------ corpus + service wiring

N, F, D = 64, 24, 8


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((N, F), dtype=np.float32)
    return config, params, articles


def _ivf_corpus(config, params, articles, **kw):
    kw.setdefault("retrieval", "ivf")
    kw.setdefault("n_cells", 6)
    corpus = ServingCorpus(config, block=16, **kw)
    corpus.swap(params, articles, note="initial")
    return corpus


def test_full_swap_attaches_a_refit_index(setup):
    config, params, articles = setup
    corpus = _ivf_corpus(config, params, articles, corpus_dtype="int8")
    slot = corpus.active
    assert slot.ivf is not None and slot.ivf.n_cells == 6
    ev = [e for e in corpus.events if e["event"] == "ivf_index"]
    assert ev and ev[-1]["refit"] is True
    assert corpus.ivf_stale_cycles == 0 and not corpus.reindex_due


def test_retrieval_knob_is_validated(setup):
    config, _, _ = setup
    with pytest.raises(ValueError, match="retrieval"):
        ServingCorpus(config, retrieval="annoy")


def test_service_full_probes_matches_exact_scorer(setup):
    config, params, articles = setup
    corpus = _ivf_corpus(config, params, articles, corpus_dtype="int8")
    slot = corpus.active
    svc = RecommendationService(params, config, corpus, top_k=5, max_batch=8,
                                retrieval="ivf", probes=6)
    svc.warmup()
    try:
        assert svc.summary()["retrieval"] == "ivf"
        assert svc.summary()["probes"] == 6
        exact = make_serve_fn(config, 5)
        for row in (0, 11, 40):
            reply = svc.submit(articles[row],
                               deadline_s=10.0).result(timeout=10.0)
            assert reply.ok
            _, ei = jax.device_get(exact(params, slot.emb, slot.valid,
                                         slot.scales, articles[row][None]))
            np.testing.assert_array_equal(reply.indices, np.asarray(ei)[0])
    finally:
        svc.stop()


def test_service_without_index_serves_degraded_fallback(setup):
    """r16 satellite: a slot promoted without an index SERVES through the
    recorded exact-scoring fallback (degraded="ivf_unavailable") instead of
    erroring — and the answer matches the exact scorer exactly."""
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)       # exact corpus: no slot.ivf
    corpus.swap(params, articles, note="initial")
    svc = RecommendationService(params, config, corpus, top_k=5, max_batch=8,
                                retrieval="ivf", probes=4)
    svc.warmup()                       # warms the fallback variants instead
    try:
        reply = svc.submit(articles[0], deadline_s=10.0).result(timeout=10.0)
        assert reply.ok
        assert "ivf_unavailable" in reply.degraded
        slot = corpus.active
        exact = make_serve_fn(config, 5)
        _, ei = jax.device_get(exact(params, slot.emb, slot.valid,
                                     slot.scales, articles[0][None]))
        np.testing.assert_array_equal(reply.indices, np.asarray(ei)[0])
        ev = [e for e in svc.events if e["event"] == "ivf_unavailable"]
        assert len(ev) == 1 and ev[0]["corpus_version"] == slot.version
    finally:
        svc.stop()


def test_ivf_composes_with_sharded(setup):
    """r16 tentpole smoke: retrieval='ivf' + a mesh-sharded corpus builds a
    shard-major index, the service DERIVES sharded=True + retrieval='ivf'
    from the corpus (the multi-device default configuration), and a served
    reply matches the unsharded exact scorer at probes=n_cells."""
    config, params, articles = setup
    mesh = get_mesh()
    corpus = ServingCorpus(config, block=16, mesh=mesh, retrieval="ivf",
                           n_cells=4)
    corpus.swap(params, articles, note="initial")
    slot = corpus.active
    assert hasattr(slot.ivf, "n_shards")           # shard-major layout
    svc = RecommendationService(params, config, corpus, top_k=5, max_batch=8,
                                probes=4)          # sharded/retrieval derived
    svc.warmup()
    try:
        s = svc.summary()
        assert s["sharded"] is True and s["retrieval"] == "ivf"
        reply = svc.submit(articles[0], deadline_s=10.0).result(timeout=10.0)
        assert reply.ok
        exact = make_serve_fn(config, 5)
        flat = ServingCorpus(config, block=16)
        flat.swap(params, articles, note="flat")
        fs = flat.active
        _, ei = jax.device_get(exact(params, fs.emb, fs.valid, fs.scales,
                                     articles[0][None]))
        np.testing.assert_array_equal(reply.indices, np.asarray(ei)[0])
    finally:
        svc.stop()


def test_reindex_requires_ivf_retrieval(setup):
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)
    corpus.swap(params, articles, note="initial")
    with pytest.raises(SwapRejected, match="ivf"):
        corpus.reindex()


# ----------------------------------------------------- churn composition

def test_incremental_append_routes_without_refitting(setup):
    config, params, articles = setup
    corpus = _ivf_corpus(config, params, articles)
    c0 = np.asarray(corpus.active.ivf.centroids).copy()
    extra = np.random.default_rng(21).random((12, F), dtype=np.float32)
    corpus.swap_incremental(params, extra, note="n1")
    slot = corpus.active
    assert slot.n == N + 12
    # centroids untouched: routing-only update
    np.testing.assert_array_equal(c0, np.asarray(slot.ivf.centroids))
    # and every row (old AND appended) sits at its nearest centroid
    x = dequantize_rows(slot.emb, slot.scales, slot.emb.shape[0])
    np.testing.assert_array_equal(np.asarray(slot.ivf.assign),
                                  assign_cells(x, slot.ivf.centroids))


def test_sustained_imbalance_trips_a_supervised_reindex(setup):
    config, params, articles = setup
    # imbalance = max/mean >= 1 whenever rows exist, so imbalance_max=0.5
    # makes every incremental promote "imbalanced" — a deterministic trip
    corpus = ServingCorpus(config, block=16, retrieval="ivf", n_cells=4,
                           imbalance_max=0.5, reindex_after=2)
    sup = ChurnSupervisor(params, config, corpus,
                          churn=ChurnConfig(microbatch=16))
    sup.bootstrap(articles)
    rng = np.random.default_rng(22)
    r1 = sup.ingest(rng.random((8, F), dtype=np.float32), note="n1")
    assert r1["action"] == "incremental" and corpus.ivf_stale_cycles == 1
    c_before = np.asarray(corpus.active.ivf.centroids).copy()
    r2 = sup.ingest(rng.random((8, F), dtype=np.float32), note="n2")
    assert r2["action"] == "incremental+reindex" and r2["reindex"]["ok"]
    led = corpus.ledger[-1]
    assert led["kind"] == "reindex" and led["ok"]
    # the rebuild REFIT the centroids and reset the staleness counter
    assert corpus.ivf_stale_cycles == 0 and not corpus.reindex_due
    assert not np.array_equal(c_before,
                              np.asarray(corpus.active.ivf.centroids))
    # reindex is a routing rebuild, not an ingest: corpus contents unchanged
    assert corpus.active.n == N + 16


def test_reindex_bumps_version_and_keeps_serving_exactly(setup):
    config, params, articles = setup
    corpus = _ivf_corpus(config, params, articles)
    v0 = corpus.version
    corpus.reindex(note="manual")
    assert corpus.version == v0 + 1
    slot = corpus.active
    assert slot.ivf is not None
    q = jnp.asarray(articles[:4])
    fn = make_serve_fn(config, 5)
    h_s, h_i = jax.device_get(fn(params, slot.emb, slot.valid, slot.scales,
                                 q))
    from dae_rnn_news_recommendation_tpu.serve import make_ivf_serve_fn
    ivf_fn = make_ivf_serve_fn(config, 5, probes=slot.ivf.n_cells)
    s, i = jax.device_get(ivf_fn(params, slot.emb, slot.valid, slot.scales,
                                 slot.ivf, q))
    np.testing.assert_array_equal(s, h_s)
    np.testing.assert_array_equal(i, h_i)


# --------------------------------- satellite: sharded slots refuse appends

def test_sharded_slot_appends_through_two_phase_swap(setup):
    """ISSUE 13 replaced the r11 refusal: a slot sharded through a bare
    `device_put` closure (no mesh= kwarg) appends via the same two-phase
    prepare -> commit as the mesh= flavor — the row multiple is inferred
    from the base slot, and the commit stamps every shard uniformly."""
    config, params, articles = setup
    mesh = get_mesh(4)
    corpus = ServingCorpus(config, block=16,
                           device_put=lambda x: shard_rows(x, mesh))
    corpus.swap(params, articles, note="sharded")     # full swap is fine
    assert corpus.version == 1
    corpus.swap_incremental(
        params, np.random.default_rng(23).random((4, F), dtype=np.float32),
        note="sharded-append")
    assert corpus.version == 2 and corpus.active.n == N + 4
    # the appended slot keeps the 4-way row sharding and uniform stamps
    assert len(corpus.active.emb.sharding.device_set) == 4
    assert list(corpus.active.shard_versions) == [2] * 4
    assert corpus.active.emb.shape[0] % 4 == 0
