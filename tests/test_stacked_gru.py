"""Tests for the net-new deep half of the pipeline: stacked DAE pretraining and the
GRU user-state model (SURVEY.md §7 step 10 — the reference never implemented the RNN,
reference README.md:5). Oracle style follows the reference's NumPy-loop pattern
(reference autoencoder/tests/test_triplet_loss_utils.py:73-203)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.models.stacked import StackedDenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.models.gru_user import (
    GRUUserModel, gru_apply, gru_cell, gru_init_params, pairwise_rank_loss)


# ---------------------------------------------------------------- stacked DAE

def _toy_data(rng, n=96, f=30):
    return (rng.uniform(size=(n, f)) < 0.15).astype(np.float32)


def test_stacked_fit_encode_shapes(rng):
    X = _toy_data(rng)
    sdae = StackedDenoisingAutoencoder([16, 8], num_epochs=2, batch_size=32, seed=0)
    sdae.fit(X)
    assert len(sdae.params) == 2 and len(sdae.configs) == 2
    assert sdae.configs[0].n_features == 30 and sdae.configs[0].n_components == 16
    assert sdae.configs[1].n_features == 16 and sdae.configs[1].n_components == 8
    codes = sdae.encode(X)
    assert codes.shape == (96, 8)
    assert np.isfinite(codes).all()


def test_stacked_zero_row_embeds_to_zero(rng):
    """The paper's modified encoder H=f(Wx+b)-f(b) maps x=0 to H=0; composition
    through the stack preserves this (reference autoencoder.py:389 semantics at
    every depth)."""
    X = _toy_data(rng)
    X[0] = 0.0
    sdae = StackedDenoisingAutoencoder([12, 6], num_epochs=1, batch_size=32, seed=1)
    sdae.fit(X)
    codes = sdae.encode(X)
    np.testing.assert_allclose(codes[0], 0.0, atol=1e-6)
    assert np.abs(codes[1:]).sum() > 0


def test_stacked_accepts_sparse_input(rng):
    X = sp.csr_matrix(_toy_data(rng))
    sdae = StackedDenoisingAutoencoder([10], num_epochs=1, batch_size=32, seed=2)
    sdae.fit(X)
    codes = sdae.encode(X)
    assert codes.shape == (96, 10) and np.isfinite(codes).all()


def test_stacked_corruption_only_at_data_layer(rng):
    sdae = StackedDenoisingAutoencoder([8, 4], corr_type="masking", corr_frac=0.4,
                                       num_epochs=1, batch_size=32)
    sdae.fit(_toy_data(rng))
    assert sdae.configs[0].corr_type == "masking"
    assert sdae.configs[0].corr_frac == pytest.approx(0.4)
    assert sdae.configs[1].corr_type == "none"
    assert sdae.configs[1].corr_frac == 0.0


def test_stacked_pretraining_reduces_reconstruction_error(rng):
    """Layer-0 reconstruction after training beats the untrained init."""
    from dae_rnn_news_recommendation_tpu.models.dae_core import (
        DAEConfig, forward, init_params)

    X = _toy_data(rng, n=128)
    sdae = StackedDenoisingAutoencoder([16], num_epochs=8, batch_size=32,
                                       learning_rate=0.5, seed=3)
    sdae.fit(X)
    cfg = sdae.configs[0]
    x = jnp.asarray(X)

    def mse(params):
        _, recon = forward(params, x, cfg)
        return float(jnp.mean((recon - x) ** 2))

    untrained = init_params(jax.random.PRNGKey(99), cfg)
    assert mse(sdae.params[0]) < mse(untrained)


# ---------------------------------------------------------------- GRU cell/apply

def _np_gru_cell(p, h, x):
    """NumPy oracle of the standard GRU update."""
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    z = sig(x @ p["Wz"] + h @ p["Uz"] + p["bz"])
    r = sig(x @ p["Wr"] + h @ p["Ur"] + p["br"])
    n = np.tanh(x @ p["Wn"] + (r * h) @ p["Un"] + p["bn"])
    return (1.0 - z) * n + z * h


def test_gru_cell_matches_numpy_oracle(rng):
    d, hdim, b = 5, 7, 4
    params = gru_init_params(jax.random.PRNGKey(0), d, hdim)
    p_np = {k: np.asarray(v) for k, v in params.items()}
    h = rng.normal(size=(b, hdim)).astype(np.float32)
    x = rng.normal(size=(b, d)).astype(np.float32)
    got = np.asarray(gru_cell(params, jnp.asarray(h), jnp.asarray(x)))
    np.testing.assert_allclose(got, _np_gru_cell(p_np, h, x), atol=1e-5)


def test_gru_apply_matches_stepwise_oracle(rng):
    d, hdim, b, t = 4, 6, 3, 5
    params = gru_init_params(jax.random.PRNGKey(1), d, hdim)
    p_np = {k: np.asarray(v) for k, v in params.items()}
    seq = rng.normal(size=(b, t, d)).astype(np.float32)
    states, final = gru_apply(params, jnp.asarray(seq))
    h = np.zeros((b, hdim), np.float32)
    for step in range(t):
        h = _np_gru_cell(p_np, h, seq[:, step])
        np.testing.assert_allclose(np.asarray(states[:, step]), h, atol=1e-5)
    np.testing.assert_allclose(np.asarray(final), h, atol=1e-5)


def test_gru_mask_carries_state_through(rng):
    """A masked (padding) step must leave the state unchanged: running [x1, x2, pad]
    yields the same final state as running [x1, x2]."""
    d, hdim = 4, 5
    params = gru_init_params(jax.random.PRNGKey(2), d, hdim)
    seq = rng.normal(size=(2, 3, d)).astype(np.float32)
    mask = np.array([[1.0, 1.0, 0.0], [1.0, 1.0, 0.0]], np.float32)
    _, final_masked = gru_apply(params, jnp.asarray(seq), jnp.asarray(mask))
    _, final_short = gru_apply(params, jnp.asarray(seq[:, :2]))
    np.testing.assert_allclose(np.asarray(final_masked), np.asarray(final_short),
                               atol=1e-6)


def test_rank_loss_prefers_separating_params(rng):
    """Loss is softplus(-(s_pos - s_neg)): params scoring pos above neg must have a
    lower loss than params scoring them equally (softplus(0)=log 2)."""
    d, hdim, b, t = 3, 3, 4, 2
    params = gru_init_params(jax.random.PRNGKey(3), d, hdim)
    seq = rng.normal(size=(b, t, d)).astype(np.float32)
    states, _ = gru_apply(params, jnp.asarray(seq))
    st = np.asarray(states)
    pos = st * 100.0 / (np.linalg.norm(st, axis=-1, keepdims=True) + 1e-8)
    neg = -pos                               # aligned with the state -> s_pos >> s_neg
    loss_sep = float(pairwise_rank_loss(params, jnp.asarray(seq), jnp.asarray(pos),
                                        jnp.asarray(neg)))
    loss_tied = float(pairwise_rank_loss(params, jnp.asarray(seq), jnp.asarray(pos),
                                         jnp.asarray(pos)))
    assert loss_sep < 0.05 < loss_tied
    assert loss_tied == pytest.approx(np.log(2.0), abs=1e-5)


def test_gru_user_model_learns_and_scores(rng):
    """End-to-end: training reduces the rank loss on a learnable synthetic task
    (clicked articles point along a fixed direction, negatives opposite)."""
    n, t, d = 32, 4, 8
    direction = rng.normal(size=(d,)).astype(np.float32)
    direction /= np.linalg.norm(direction)
    seq = rng.normal(size=(n, t, d)).astype(np.float32) * 0.1 + direction
    pos = np.broadcast_to(direction, (n, t, d)).astype(np.float32)
    neg = -pos + rng.normal(size=(n, t, d)).astype(np.float32) * 0.01

    model = GRUUserModel(d_embed=d, d_hidden=d, num_epochs=1, batch_size=16, seed=0)
    model.fit(seq[:2], pos[:2], neg[:2])  # barely-trained baseline
    loss_before = float(pairwise_rank_loss(
        model.params, jnp.asarray(seq), jnp.asarray(pos), jnp.asarray(neg)))

    model = GRUUserModel(d_embed=d, d_hidden=d, num_epochs=30, batch_size=16, seed=0)
    model.fit(seq, pos, neg)
    loss_after = float(pairwise_rank_loss(
        model.params, jnp.asarray(seq), jnp.asarray(pos), jnp.asarray(neg)))
    assert loss_after < loss_before

    states = model.user_state(seq)
    assert states.shape == (n, d)
    cands = np.stack([direction, -direction])
    scores = model.score(seq, cands)
    assert scores.shape == (n, 2)
    # the trained user state should prefer the clicked direction
    assert (scores[:, 0] > scores[:, 1]).mean() > 0.9


def test_gru_fit_with_ragged_mask(rng):
    n, t, d = 8, 5, 4
    seq = rng.normal(size=(n, t, d)).astype(np.float32)
    pos = rng.normal(size=(n, t, d)).astype(np.float32)
    neg = rng.normal(size=(n, t, d)).astype(np.float32)
    lengths = rng.integers(1, t + 1, size=n)
    mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)
    model = GRUUserModel(d_embed=d, num_epochs=2, batch_size=4, seed=1)
    model.fit(seq, pos, neg, mask)
    assert model.params is not None
    states = model.user_state(seq, mask)
    assert np.isfinite(states).all()


def test_stacked_save_load_roundtrip(tmp_path, rng):
    from dae_rnn_news_recommendation_tpu.models import StackedDenoisingAutoencoder

    X = (rng.uniform(size=(48, 20)) < 0.3).astype(np.float32)
    m = StackedDenoisingAutoencoder([8, 4], num_epochs=2, batch_size=16, seed=3,
                                    corr_type="none")
    m.fit(X)
    path = str(tmp_path / "stack.npz")
    m.save(path)
    m2 = StackedDenoisingAutoencoder.load(path)
    np.testing.assert_allclose(m2.encode(X), m.encode(X), rtol=1e-6, atol=1e-7)
    assert [c.n_components for c in m2.configs] == [8, 4]
    # the loaded stack keeps training (fine-tune path intact)
    m2.fit_finetune(X, num_epochs=1)


def test_gru_save_load_roundtrip(tmp_path, rng):
    from dae_rnn_news_recommendation_tpu.models import GRUUserModel

    d, t, n = 6, 5, 12
    seq = rng.normal(size=(n, t, d)).astype(np.float32)
    pos = rng.normal(size=(n, t, d)).astype(np.float32)
    neg = rng.normal(size=(n, t, d)).astype(np.float32)
    # d_hidden must equal d_embed for the rank loss (<state, embed> scores)
    m = GRUUserModel(d, num_epochs=2, batch_size=6, seed=5)
    m.fit(seq, pos, neg)
    path = str(tmp_path / "gru.npz")
    m.save(path)
    m2 = GRUUserModel.load(path)
    assert (m2.d_embed, m2.d_hidden) == (6, 6)
    np.testing.assert_allclose(m2.user_state(seq), m.user_state(seq),
                               rtol=1e-6, atol=1e-7)
