"""Parallel-layer tests on a virtual 8-device CPU mesh (conftest sets
--xla_force_host_platform_device_count=8): global-mining DP must be numerically
equivalent to single-device training; feature-sharded (2-D mesh) likewise; ring
similarity must match the NumPy oracle."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
from dae_rnn_news_recommendation_tpu.parallel import (
    get_mesh, get_mesh_2d, make_parallel_eval_step, make_parallel_train_step,
    ring_pairwise_similarity,
)
from dae_rnn_news_recommendation_tpu.train import make_optimizer, make_train_step

B, F, D = 32, 64, 8


def _setup(strategy="batch_all", corr_type="none"):
    cfg = DAEConfig(n_features=F, n_components=D, enc_act_func="tanh",
                    dec_act_func="none", loss_func="mean_squared",
                    corr_type=corr_type, corr_frac=0.3,
                    triplet_strategy=strategy, alpha=1.0,
                    matmul_precision="highest")
    params = init_params(jax.random.PRNGKey(0), cfg)
    optimizer = make_optimizer("ada_grad", 0.1)
    opt_state = optimizer.init(params)
    rng = np.random.default_rng(0)
    batch = {
        "x": jnp.asarray((rng.uniform(size=(B, F)) < 0.3).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 4, B), jnp.int32),
        "row_valid": jnp.ones(B, jnp.float32),
    }
    return cfg, params, optimizer, opt_state, batch


def test_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("strategy", ["batch_all", "batch_hard", "none"])
def test_global_dp_matches_single_device(strategy):
    """'global' mining scope: N-device result == 1-device result (same triplets,
    same loss, same update)."""
    cfg, params, optimizer, opt_state, batch = _setup(strategy)
    single = make_train_step(cfg, optimizer, donate=False)
    p1, _, m1 = single(params, opt_state, jax.random.PRNGKey(7), batch)

    mesh = get_mesh(8)
    par = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="global",
                                   donate=False)
    p8, _, m8 = par(params, opt_state, jax.random.PRNGKey(7), batch)

    np.testing.assert_allclose(float(m8["cost"]), float(m1["cost"]), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)
    if strategy != "none":
        np.testing.assert_allclose(float(m8["num_triplet"]), float(m1["num_triplet"]))


def test_global_dp_with_corruption_matches():
    """On-device corruption is part of the traced program, so it partitions
    identically too."""
    cfg, params, optimizer, opt_state, batch = _setup("none", corr_type="masking")
    single = make_train_step(cfg, optimizer, donate=False)
    p1, _, m1 = single(params, opt_state, jax.random.PRNGKey(3), batch)
    mesh = get_mesh(8)
    par = make_parallel_train_step(cfg, optimizer, mesh, donate=False)
    p8, _, m8 = par(params, opt_state, jax.random.PRNGKey(3), batch)
    np.testing.assert_allclose(float(m8["cost"]), float(m1["cost"]), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)


def test_feature_sharded_2d_mesh_matches():
    """W sharded over the model axis (wide-F layout): same numbers as replicated."""
    cfg, params, optimizer, opt_state, batch = _setup("batch_all")
    single = make_train_step(cfg, optimizer, donate=False)
    p1, _, m1 = single(params, opt_state, jax.random.PRNGKey(5), batch)

    mesh = get_mesh_2d(2, 4)
    par = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="global",
                                   model_axis="model", donate=False)
    p8, _, m8 = par(params, opt_state, jax.random.PRNGKey(5), batch)
    np.testing.assert_allclose(float(m8["cost"]), float(m1["cost"]), rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)


def test_weight_update_sharding_matches_replicated():
    """Cross-replica weight-update sharding (arXiv:2004.13336, ZeRO-1 style):
    optimizer accumulators shard over the data axis — identical trajectory to
    the replicated-state path over several steps, and the returned opt state
    is ACTUALLY sharded (1/N leading-axis shards on each device)."""
    cfg, params, optimizer, opt_state, batch = _setup("batch_all")
    mesh = get_mesh(8)
    rep = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="global",
                                   donate=False)
    wus = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="global",
                                   donate=False, weight_update_sharding=True)
    p_r, o_r, p_s, o_s = params, opt_state, params, opt_state
    for i in range(3):
        key = jax.random.PRNGKey(10 + i)
        p_r, o_r, m_r = rep(p_r, o_r, key, batch)
        p_s, o_s, m_s = wus(p_s, o_s, key, batch)
    np.testing.assert_allclose(float(m_s["cost"]), float(m_r["cost"]), rtol=1e-5)
    for k in p_r:
        np.testing.assert_allclose(np.asarray(p_s[k]), np.asarray(p_r[k]),
                                   rtol=1e-4, atol=1e-6)

    # the W-shaped accumulator really shards its leading (F) axis over the mesh
    from jax.sharding import PartitionSpec as P

    sharded_leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(o_s)
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[:1] == (F,)
    ]
    assert sharded_leaves, "expected W/bv-shaped accumulator leaves"
    for leaf in sharded_leaves:
        spec = leaf.sharding.spec
        assert spec and spec[0] == "data", spec
        assert leaf.addressable_shards[0].data.shape[0] == F // 8


def test_weight_update_sharding_checkpoint_resume(tmp_path, monkeypatch):
    """Mid-run checkpoints gather the 1/N-sharded accumulators to host and a
    resumed fit reshards them on entry — the full estimator save/restore loop
    must work under weight_update_sharding, continuing the epoch schedule."""
    import os

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder

    monkeypatch.chdir(tmp_path)
    rng = np.random.default_rng(0)
    X = (rng.uniform(size=(64, 40)) < 0.2).astype(np.float32)
    kwargs = dict(model_name="wus", main_dir="wus", compress_factor=10,
                  batch_size=16, verbose=False, triplet_strategy="none",
                  loss_func="mean_squared", dec_act_func="none",
                  enc_act_func="tanh", opt="ada_grad", learning_rate=0.1,
                  n_devices=8, weight_update_sharding=True, seed=0)
    m1 = DenoisingAutoencoder(num_epochs=3, checkpoint_every=1, **kwargs)
    m1.fit(X)
    assert os.path.isdir(m1.model_path)

    m2 = DenoisingAutoencoder(num_epochs=5, checkpoint_every=0, **kwargs)
    m2.fit(X, restore_previous_model=True)
    assert m2._epoch0 == 3
    # the resumed opt state is sharded again after the first resumed step
    leaves = [l for l in jax.tree_util.tree_leaves(m2.opt_state)
              if getattr(l, "ndim", 0) >= 1 and l.shape[0] % 8 == 0]
    assert leaves and all(l.sharding.spec[0] == "data" for l in leaves)


def test_weight_update_sharding_rejects_bad_combos():
    cfg, params, optimizer, opt_state, batch = _setup("none")
    mesh2d = get_mesh_2d(2, 4)
    with pytest.raises(ValueError):
        make_parallel_train_step(cfg, optimizer, mesh2d, mining_scope="global",
                                 model_axis="model",
                                 weight_update_sharding=True)
    with pytest.raises(ValueError):
        make_parallel_train_step(cfg, optimizer, get_mesh(8),
                                 mining_scope="shard",
                                 weight_update_sharding=True)


def test_shard_scope_runs_and_learns():
    """'shard' mining scope: different mining semantics (local triplets), but must
    train and stay finite."""
    cfg, params, optimizer, opt_state, batch = _setup("batch_all")
    mesh = get_mesh(8)
    step = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="shard",
                                    donate=False)
    key = jax.random.PRNGKey(0)
    costs = []
    for i in range(5):
        key, sub = jax.random.split(key)
        params, opt_state, m = step(params, opt_state, sub, batch)
        costs.append(float(m["cost"]))
    assert all(np.isfinite(costs))
    assert costs[-1] < costs[0]


def test_parallel_eval_step():
    cfg, params, optimizer, opt_state, batch = _setup("batch_all")
    mesh = get_mesh(8)
    ev = make_parallel_eval_step(cfg, mesh)
    m = ev(params, batch)
    assert np.isfinite(float(m["cost"]))
    # eval must equal the single-device eval step
    from dae_rnn_news_recommendation_tpu.train import make_eval_step
    m1 = make_eval_step(cfg)(params, batch)
    np.testing.assert_allclose(float(m["cost"]), float(m1["cost"]), rtol=1e-5)


def test_shard_eval_matches_shard_train_objective():
    """Under mining_scope='shard', validation must measure the objective being
    trained: per-shard mining, not global. Eval cost == the train step's
    pre-update cost on an identical clean batch, and != the global-scope eval."""
    cfg, params, optimizer, opt_state, batch = _setup("batch_all")
    mesh = get_mesh(8)
    tr = make_parallel_train_step(cfg, optimizer, mesh, mining_scope="shard",
                                  donate=False)
    _, _, m_train = tr(params, opt_state, jax.random.PRNGKey(0), batch)

    ev = make_parallel_eval_step(cfg, mesh, mining_scope="shard")
    m_eval = ev(params, batch)
    np.testing.assert_allclose(float(m_eval["cost"]), float(m_train["cost"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_eval["num_triplet"]),
                               float(m_train["num_triplet"]))

    m_global = make_parallel_eval_step(cfg, mesh, mining_scope="global")(
        params, batch)
    # global mining sees B-row triplet populations; 8 local shards of B/8 rows
    # cannot form the same count on this label distribution
    assert float(m_eval["num_triplet"]) != float(m_global["num_triplet"])


def test_ring_pairwise_similarity_matches_numpy():
    rng = np.random.default_rng(1)
    emb = rng.normal(size=(64, 16)).astype(np.float32)
    mesh = get_mesh(8)
    got = np.asarray(ring_pairwise_similarity(jnp.asarray(emb), mesh))
    normed = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    expect = normed @ normed.T
    np.fill_diagonal(expect, 0.0)
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_ring_similarity_dot_product_mode():
    rng = np.random.default_rng(2)
    emb = rng.normal(size=(32, 8)).astype(np.float32)
    mesh = get_mesh(8)
    got = np.asarray(ring_pairwise_similarity(jnp.asarray(emb), mesh,
                                              normalize=False,
                                              set_diagonal_zero=False))
    np.testing.assert_allclose(got, emb @ emb.T, rtol=1e-4, atol=1e-5)


def test_estimator_with_mesh(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    import scipy.sparse as sp
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    X = sp.random(64, 32, density=0.3, format="csr", random_state=0, dtype=np.float32)
    labels = np.random.default_rng(0).integers(0, 4, 64)
    m = DenoisingAutoencoder(model_name="mesh", compress_factor=8, num_epochs=2,
                             batch_size=16, verbose=False, seed=3,
                             triplet_strategy="batch_all", n_devices=8,
                             use_tensorboard=False)
    m.fit(X, train_set_label=labels)
    enc = m.transform(X)
    assert enc.shape == (64, 4)
    assert np.isfinite(enc).all()


def test_parallel_first_import_order():
    """`import ...parallel` before anything else must not hit the
    models<->train import cycle (regression: estimator imports are lazy)."""
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from dae_rnn_news_recommendation_tpu.parallel import initialize_multihost\n"
        "idx, n = initialize_multihost()\n"
        "assert (idx, n) == (0, 1), (idx, n)\n"
        "idx2, n2 = initialize_multihost()\n"  # idempotent
        "assert (idx2, n2) == (0, 1)\n"
        "from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder\n"
        "print('ok')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, env={**__import__('os').environ,
                                          "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ok" in out.stdout


def test_estimator_with_mesh_shard_scope_sparse_feed(tmp_path, monkeypatch):
    """mining_scope='shard' + the sparse-ingest feed + chunked validation all
    compose: (indices, values) batches densify per shard inside shard_map."""
    monkeypatch.chdir(tmp_path)
    import scipy.sparse as sp
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    X = sp.random(64, 32, density=0.3, format="csr", random_state=1, dtype=np.float32)
    labels = np.random.default_rng(1).integers(0, 4, 64)
    m = DenoisingAutoencoder(model_name="meshs", compress_factor=8, num_epochs=2,
                             batch_size=16, verbose=False, seed=3,
                             triplet_strategy="batch_all", n_devices=8,
                             mining_scope="shard", verbose_step=1,
                             use_tensorboard=False)
    m.fit(X, validation_set=X[:32], train_set_label=labels,
          validation_set_label=labels[:32])
    enc = m.transform(X)
    assert enc.shape == (64, 4) and np.isfinite(enc).all()


def test_estimator_2d_mesh_matches_single_device(tmp_path, monkeypatch):
    """A 2-D (data x model) mesh through the estimator: W feature-sharded,
    global mining — fit must match the single-device run to float tolerance,
    through the sparse-ingest feed."""
    monkeypatch.chdir(tmp_path)
    import scipy.sparse as sp
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.parallel import get_mesh_2d

    X = sp.random(64, 32, density=0.3, format="csr", random_state=2,
                  dtype=np.float32)
    labels = np.random.default_rng(2).integers(0, 4, 64)
    kw = dict(compress_factor=8, num_epochs=2, batch_size=16, opt="ada_grad",
              learning_rate=0.1, verbose=False, seed=4,
              triplet_strategy="batch_all", use_tensorboard=False)
    m1 = DenoisingAutoencoder(model_name="one", **kw)
    m1.fit(X, train_set_label=labels)
    m2 = DenoisingAutoencoder(model_name="two", mesh=get_mesh_2d(4, 2), **kw)
    m2.fit(X, train_set_label=labels)
    for k in m1.params:
        np.testing.assert_allclose(np.asarray(m2.params[k]),
                                   np.asarray(m1.params[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_estimator_2d_mesh_shard_scope_rejected(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    import scipy.sparse as sp
    import pytest as _pytest
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.parallel import get_mesh_2d

    X = sp.random(32, 16, density=0.3, format="csr", random_state=3,
                  dtype=np.float32)
    m = DenoisingAutoencoder(model_name="bad", compress_factor=4, num_epochs=1,
                             batch_size=8, verbose=False, seed=1,
                             triplet_strategy="none", mining_scope="shard",
                             mesh=get_mesh_2d(4, 2), use_tensorboard=False)
    with _pytest.raises(ValueError, match="1-D data mesh"):
        m.fit(X)
