"""Model-health observability: in-graph sentinel, flight recorder, crash
bundles, checkpoint health sidecar, and the report CLI's health section.

The acceptance test seeds a NaN into one mid-fit batch and asserts the
bundle pins the exact first bad step, the default fit still completes, and
`health_abort=True` stops at the next epoch boundary.
"""

import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu import telemetry
from dae_rnn_news_recommendation_tpu.analysis import compile_guard
from dae_rnn_news_recommendation_tpu.data.batcher import PaddedBatcher
from dae_rnn_news_recommendation_tpu.models import (
    DAEConfig, DenoisingAutoencoder, init_params)
from dae_rnn_news_recommendation_tpu.telemetry import (
    FlightRecorder, summarize_batch)
from dae_rnn_news_recommendation_tpu.telemetry.__main__ import main as cli_main
from dae_rnn_news_recommendation_tpu.train import make_optimizer
from dae_rnn_news_recommendation_tpu.train.step import make_train_step
from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
    load_checkpoint, save_checkpoint)


def _cfg(**kw):
    base = dict(n_features=24, n_components=4, enc_act_func="tanh",
                dec_act_func="none", loss_func="mean_squared",
                corr_type="none", corr_frac=0.0, triplet_strategy="none")
    base.update(kw)
    return DAEConfig(**base)


# ------------------------------------------------------------ sentinel

def _one_step(batch_x, health=True):
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    optimizer = make_optimizer("gradient_descent", 0.05)
    opt_state = optimizer.init(params)
    step = make_train_step(cfg, optimizer, donate=False, health=health)
    batch = {"x": jnp.asarray(batch_x),
             "row_valid": jnp.ones(batch_x.shape[0], jnp.float32)}
    return step(params, opt_state, jax.random.PRNGKey(1), batch)


def test_sentinel_clean_step_flags_zero():
    x = (np.random.default_rng(0).uniform(size=(16, 24)) < 0.3).astype(
        np.float32)
    _, _, metrics = _one_step(x)
    m = jax.device_get(metrics)
    assert float(m["health/nonfinite"]) == 0.0
    assert float(m["health/grad_norm"]) > 0.0
    assert float(m["health/param_norm"]) > 0.0
    assert float(m["health/update_ratio"]) > 0.0
    # embedding health rides along on every loss path
    assert float(m["health/embedding_norm_mean"]) >= 0.0
    assert -1.0 - 1e-5 <= float(m["health/embedding_collapse"]) <= 1.0 + 1e-5


def test_sentinel_flags_nan_batch():
    x = (np.random.default_rng(0).uniform(size=(16, 24)) < 0.3).astype(
        np.float32)
    x[0, 0] = np.nan
    _, _, metrics = _one_step(x)
    m = jax.device_get(metrics)
    assert float(m["health/nonfinite"]) == 1.0
    assert not np.isfinite(float(m["cost"]))


def test_health_false_step_omits_sentinel_keys():
    x = (np.random.default_rng(0).uniform(size=(16, 24)) < 0.3).astype(
        np.float32)
    _, _, metrics = _one_step(x, health=False)
    assert not any(k.startswith("health/grad") for k in metrics)
    assert "health/nonfinite" not in metrics


def test_sentinel_single_compile_and_no_per_step_fetches(monkeypatch):
    """CI guard (satellite 6): the health-flagged step compiles once across
    same-shape steps and the loop needs ZERO host fetches per step — the
    sentinel rides the one end-of-loop device_get."""
    cfg = _cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    optimizer = make_optimizer("gradient_descent", 0.05)
    opt_state = optimizer.init(params)
    step = make_train_step(cfg, optimizer, donate=False, health=True)
    x = (np.random.default_rng(1).uniform(size=(16, 24)) < 0.3).astype(
        np.float32)
    batch = {"x": jnp.asarray(x), "row_valid": jnp.ones(16, jnp.float32)}
    key = jax.random.PRNGKey(2)
    key, _ = jax.random.split(key)  # pre-warm split's own compile

    fetches = {"n": 0}
    real_device_get = jax.device_get

    def counting_device_get(tree):
        fetches["n"] += 1
        return real_device_get(tree)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    device_metrics = []
    with compile_guard(max_compiles=1):
        for _ in range(4):
            key, sub = jax.random.split(key)
            params, opt_state, metrics = step(params, opt_state, sub, batch)
            device_metrics.append(metrics)
    assert fetches["n"] == 0  # no host sync inside the hot loop
    host = jax.device_get(device_metrics)
    assert fetches["n"] == 1  # the single per-epoch fetch carries health too
    assert all(float(m["health/nonfinite"]) == 0.0 for m in host)


# ------------------------------------------------------- flight recorder

def test_recorder_flags_first_nonfinite_step_once():
    rec = FlightRecorder()
    for s in range(1, 4):
        assert rec.record(s, {"cost": 1.0 - 0.1 * s}) is None
    reason = rec.record(4, {"cost": float("nan")})
    assert reason is not None and "nonfinite" in reason
    assert rec.status == "degraded"
    assert rec.first_bad_step == 4 and rec.last_good_step == 3
    # later anomalies only update the ring: the bundle names the FIRST
    assert rec.record(5, {"cost": float("inf")}) is None
    assert rec.first_bad_step == 4


def test_recorder_trips_on_sentinel_flag():
    rec = FlightRecorder()
    assert rec.record(1, {"cost": 0.5, "health/nonfinite": 0.0}) is None
    reason = rec.record(2, {"cost": 0.5, "health/nonfinite": 1.0})
    assert reason is not None and "sentinel" in reason


def test_recorder_divergence_after_warmup():
    rec = FlightRecorder(divergence_factor=10.0, warmup_steps=5)
    for s in range(1, 8):
        assert rec.record(s, {"cost": 1.0}) is None
    reason = rec.record(8, {"cost": 50.0})
    assert reason is not None and "divergence" in reason
    # before warmup the same jump must NOT trip (noisy first steps)
    rec2 = FlightRecorder(divergence_factor=10.0, warmup_steps=5)
    rec2.record(1, {"cost": 1.0})
    assert rec2.record(2, {"cost": 50.0}) is None


def test_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=4)
    for s in range(1, 11):
        rec.record(s, {"cost": 1.0})
    assert [r["step"] for r in rec.ring] == [7, 8, 9, 10]


def test_recorder_dump_bundle_roundtrip(tmp_path):
    manifest = tmp_path / "manifest.json"
    manifest.write_text('{"schema": 1, "feed_mode": "stream"}')
    rec = FlightRecorder()
    rec.record(1, {"cost": 1.0})
    rec.record(2, {"cost": float("nan")})
    path = rec.dump(str(tmp_path / "run" / "health_bundle.json"),
                    manifest_path=str(manifest),
                    trace_tail=[{"name": "train/step"}],
                    extra={"note": "seeded"})
    assert path and os.path.isfile(path) and rec.bundle_path == path
    with open(path, encoding="utf-8") as f:
        bundle = json.load(f)  # NaN tokens round-trip through json.loads
    assert bundle["schema"] == FlightRecorder.BUNDLE_SCHEMA
    assert bundle["first_bad_step"] == 2 and bundle["last_good_step"] == 1
    assert "nonfinite" in bundle["reason"]
    assert [r["step"] for r in bundle["ring"]] == [1, 2]
    assert bundle["manifest"]["feed_mode"] == "stream"
    assert bundle["trace_tail"] == [{"name": "train/step"}]
    assert bundle["note"] == "seeded"


def test_recorder_repeated_dumps_get_suffixes(tmp_path):
    """Regression: repeated anomalies in ONE run must not clobber the first
    bundle — later dumps take health_bundle_<n>.json suffixes. A FRESH
    recorder still writes the bare path (a rerun may overwrite a stale
    bundle from a previous run)."""
    target = str(tmp_path / "health_bundle.json")
    rec = FlightRecorder()
    rec.record(1, {"cost": float("nan")})
    first = rec.dump(target, reason="first anomaly")
    assert first == target
    second = rec.dump(target, reason="second anomaly")
    third = rec.dump(target, reason="third anomaly")
    assert second == str(tmp_path / "health_bundle_2.json")
    assert third == str(tmp_path / "health_bundle_3.json")
    for path, reason in [(first, "first anomaly"), (second, "second anomaly"),
                         (third, "third anomaly")]:
        with open(path, encoding="utf-8") as f:
            assert json.load(f)["reason"] == reason
    # a fresh recorder (fresh run) overwrites the stale first-path bundle
    rec2 = FlightRecorder()
    rec2.record(1, {"cost": float("inf")})
    assert rec2.dump(target, reason="fresh run") == target
    with open(target, encoding="utf-8") as f:
        assert json.load(f)["reason"] == "fresh run"


def test_recorder_exception_marks_failed():
    rec = FlightRecorder()
    rec.record(1, {"cost": 1.0})
    rec.note_exception(ValueError("boom"))
    assert rec.status == "failed"
    snap = rec.snapshot()
    assert snap["status"] == "failed" and "boom" in snap["reason"]
    assert snap["step"] == 1


def test_summarize_batch_stats_and_device_safety():
    batch = {"x": np.array([[1.0, np.nan], [3.0, 4.0]], np.float32),
             "labels": np.array([1, 2], np.int32),
             "weird": "hello"}
    sig = summarize_batch(batch)
    assert sig["x"]["shape"] == [2, 2] and sig["x"]["n_nonfinite"] == 1
    assert sig["x"]["max"] == 4.0
    assert "n_nonfinite" not in sig["labels"]  # ints carry shape/dtype only
    assert summarize_batch("not a dict") == {"type": "str"}


# ------------------------------------------------- seeded NaN acceptance

@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _inject_nan_at(monkeypatch, target_batch):
    """Corrupt x[0, 0] of the `target_batch`-th batch (1-based, counted
    across epochs — the estimator's global step key) yielded by
    PaddedBatcher."""
    calls = {"n": 0}
    orig = PaddedBatcher._payload

    def corrupting(self, ctx, idx, n_real):
        out = orig(self, ctx, idx, n_real)
        calls["n"] += 1
        if calls["n"] == target_batch:
            out["x"][0, 0] = np.nan
        return out

    monkeypatch.setattr(PaddedBatcher, "_payload", corrupting)
    return calls


def _fit_with_nan(workdir, monkeypatch, target_step=5, **kw):
    # 48 rows @ batch 16 -> 3 batches/epoch; 3 epochs -> steps 1..9;
    # target_step=5 lands mid-fit (epoch 2, batch 2)
    X = (np.random.default_rng(0).uniform(size=(48, 24)) < 0.3).astype(
        np.float32)
    _inject_nan_at(monkeypatch, target_step)
    defaults = dict(model_name="h", main_dir="h", n_components=4,
                    num_epochs=3, batch_size=16, seed=3, corr_type="none",
                    corr_frac=0.0, loss_func="mean_squared",
                    opt="gradient_descent", learning_rate=0.05,
                    triplet_strategy="none", verbose=False,
                    use_tensorboard=False, trace=True,
                    results_root=str(workdir / "results"))
    defaults.update(kw)
    m = DenoisingAutoencoder(**defaults)
    m.fit(X)
    return m


def test_nan_injection_produces_bundle_with_first_bad_step(
        workdir, monkeypatch, capsys):
    m = _fit_with_nan(workdir, monkeypatch, target_step=5)
    # the default path records the anomaly and COMPLETES (prior behavior)
    assert m._last_epoch == 3
    assert m.health_status == "degraded"
    assert m.health_bundle_path and os.path.isfile(m.health_bundle_path)
    with open(m.health_bundle_path, encoding="utf-8") as f:
        bundle = json.load(f)
    assert bundle["first_bad_step"] == 5
    assert bundle["last_good_step"] == 4
    assert bundle["status"] == "degraded"
    assert "nonfinite" in bundle["reason"]
    steps = {r["step"]: r for r in bundle["ring"]}
    assert not np.isfinite(steps[5]["cost"])  # the offending step is pinned
    assert np.isfinite(steps[4]["cost"])
    assert bundle["batch_signature"]["x"]["shape"] == [16, 24]
    assert bundle["manifest"]["feed_mode"] == "stream"
    assert bundle.get("trace_tail")  # tracing was live at dump time

    # the report CLI auto-detects the bundle next to the trace
    assert m.trace_path and os.path.isfile(m.trace_path)
    rc = cli_main(["report", m.trace_path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "model health: degraded" in out
    assert "first bad step: 5" in out


def test_health_abort_stops_at_next_epoch_boundary(workdir, monkeypatch):
    m = _fit_with_nan(workdir, monkeypatch, target_step=5, health_abort=True)
    # injection at step 5 (epoch 2): the per-epoch fetch notices it at the
    # end of epoch 2 and the loop breaks there — epoch 3 never runs
    assert m._last_epoch == 2
    assert m.health_status == "degraded"
    with open(m.health_bundle_path, encoding="utf-8") as f:
        assert json.load(f)["first_bad_step"] == 5


def test_clean_fit_has_no_bundle(workdir):
    X = (np.random.default_rng(0).uniform(size=(48, 24)) < 0.3).astype(
        np.float32)
    m = DenoisingAutoencoder(
        model_name="c", main_dir="c", n_components=4, num_epochs=2,
        batch_size=16, seed=3, corr_type="none", corr_frac=0.0,
        loss_func="mean_squared", opt="gradient_descent", learning_rate=0.05,
        triplet_strategy="none", verbose=False, use_tensorboard=False,
        results_root=str(workdir / "results"))
    m.fit(X)
    assert m.health_bundle_path is None
    assert not os.path.isfile(os.path.join(m.tf_summary_dir,
                                           "health_bundle.json"))


# --------------------------------------------------- checkpoint sidecar

def test_checkpoint_embeds_health_and_restore_warns(tmp_path):
    state = {"params": {"w": np.ones(3, np.float32)}, "opt_state": None,
             "epoch": 2}
    health = {"status": "degraded", "step": 7, "loss_ema": 1.5,
              "grad_norm": 2.0, "first_bad_step": 5,
              "reason": "nonfinite metrics at step 5: ['cost']"}
    path = save_checkpoint(str(tmp_path / "ck"), state, 7, use_orbax=False,
                           health=health)
    assert os.path.isfile(os.path.join(path, "health.json"))
    like = {"params": {"w": np.zeros(3, np.float32)}, "opt_state": None}
    with pytest.warns(RuntimeWarning, match="degraded"):
        out = load_checkpoint(path, like)
    assert out["health"]["first_bad_step"] == 5

    # an ok-status sidecar restores silently
    ok_path = save_checkpoint(str(tmp_path / "ck2"), state, 7,
                              use_orbax=False,
                              health={"status": "ok", "step": 7})
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = load_checkpoint(ok_path, like)
    assert out["health"]["status"] == "ok"

    # no sidecar at all: nothing under 'health', no warning
    bare = save_checkpoint(str(tmp_path / "ck3"), state, 7, use_orbax=False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = load_checkpoint(bare, like)
    assert "health" not in out


# ------------------------------------------- report graceful degradation

def _write_trace(path, events):
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)


def test_report_missing_optional_inputs_degrade_to_notes(tmp_path, capsys):
    trace = tmp_path / "trace.json"
    _write_trace(str(trace), [])
    rec = FlightRecorder()
    rec.record(1, {"cost": 1.0})
    rec.record(2, {"cost": float("nan")})
    rec.dump(str(tmp_path / "health_bundle.json"))

    # empty trace + a loadable health bundle: partial report, rc 0
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no span events in trace" in out
    assert "model health: degraded" in out

    # missing/unreadable OPTIONAL inputs become notes, never a crash
    rc = cli_main(["report", str(trace),
                   "--bench", str(tmp_path / "missing_bench.json"),
                   "--metrics", str(tmp_path / "missing_metrics.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "note:" in out

    # corrupt bundle: note + the rest of the report still renders
    (tmp_path / "bad").mkdir()
    bad_trace = tmp_path / "bad" / "trace.json"
    _write_trace(str(bad_trace), [])
    (tmp_path / "bad" / "health_bundle.json").write_text("{not json")
    rc = cli_main(["report", str(bad_trace),
                   "--health", str(tmp_path / "bad" / "health_bundle.json")])
    out = capsys.readouterr().out
    assert rc == 1  # nothing loaded: same contract as empty-trace-alone
    assert cli_main(["report", str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()
