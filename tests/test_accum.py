"""Microbatch gradient accumulation (ISSUE 5): `accum_steps` must change peak
activation memory, not the math — an accumulated step is the same optimizer
update as the full-batch step (to float tolerance, since only the reduction
order moves), traced ONCE regardless of accum_steps, and its provenance
(effective accum, any fallback reason) must land in the run manifest, never
silently.

Everything here runs on CPU; the no-mining, no-corruption objective makes the
accum=K vs full-batch comparison key-independent (every loss term is a batch
mean, and with equal microbatch sizes the mean of microbatch means IS the
full-batch mean).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.analysis import compile_guard
from dae_rnn_news_recommendation_tpu.models import (
    DAEConfig, DenoisingAutoencoder, init_params)
from dae_rnn_news_recommendation_tpu.train import make_optimizer
from dae_rnn_news_recommendation_tpu.train.step import (
    grads_and_metrics, loss_and_metrics, make_train_step, split_microbatches)


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _no_mining_config(f=12, d=5):
    # corr_type="none" + triplet_strategy="none": the objective ignores the
    # PRNG key, so per-microbatch key splitting cannot move the comparison
    return DAEConfig(n_features=f, n_components=d, enc_act_func="tanh",
                     dec_act_func="none", loss_func="mean_squared",
                     corr_type="none", triplet_strategy="none")


def _batch(rng, b, f):
    return {"x": jnp.asarray(rng.uniform(size=(b, f)).astype(np.float32))}


# ------------------------------------------------------------------ split

def test_split_microbatches_shapes_and_shared(rng):
    batch = {"x": jnp.asarray(rng.uniform(size=(12, 6)).astype(np.float32)),
             "labels": jnp.asarray(rng.integers(0, 3, 12), jnp.int32),
             "corr_min": np.float32(-0.5)}
    xs, shared = split_microbatches(batch, 3)
    assert xs["x"].shape == (3, 4, 6)
    assert xs["labels"].shape == (3, 4)
    assert set(shared) == {"corr_min"}
    # row-major reshape: microbatch i is rows [4i, 4i+4) — contiguous slices
    np.testing.assert_array_equal(np.asarray(xs["x"][1]),
                                  np.asarray(batch["x"][4:8]))


def test_split_microbatches_nondivisible_raises(rng):
    batch = _batch(rng, 10, 4)
    with pytest.raises(ValueError, match="accum_steps=3 must divide"):
        split_microbatches(batch, 3)


# ----------------------------------------------------- one-step parity

def test_accum_grads_match_full_batch(rng):
    """grads_and_metrics(accum_steps=4) returns the same cost and gradients
    as the plain full-batch value_and_grad, to float tolerance."""
    config = _no_mining_config()
    params = init_params(jax.random.PRNGKey(0), config)
    batch = _batch(rng, 32, config.n_features)
    key = jax.random.PRNGKey(1)

    c_full, m_full, g_full = grads_and_metrics(loss_and_metrics, config,
                                               params, batch, key)
    c_acc, m_acc, g_acc = grads_and_metrics(loss_and_metrics, config,
                                            params, batch, key,
                                            accum_steps=4)
    np.testing.assert_allclose(float(c_acc), float(c_full), rtol=1e-6)
    # same metric surface either way (accumulated metrics are meaned, never
    # dropped)
    assert set(m_acc) == set(m_full)
    for (ka, ga), (kb, gb) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(g_full),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(g_acc),
                   key=lambda kv: str(kv[0]))):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(ga),
                                   atol=1e-6, err_msg=str(ka))


def test_accum_trajectory_matches_full_batch(rng):
    """Acceptance: a short training trajectory under make_train_step
    (accum_steps=4) tracks the full-batch trajectory — the optimizer sees
    the same gradients, so the parameters stay together step after step."""
    config = _no_mining_config(f=10, d=4)
    optimizer = make_optimizer("ada_grad", 0.1)
    params = init_params(jax.random.PRNGKey(0), config)
    params_acc = jax.tree_util.tree_map(jnp.array, params)
    opt_state = optimizer.init(params)
    opt_state_acc = optimizer.init(params_acc)
    step_full = make_train_step(config, optimizer, donate=False)
    step_acc = make_train_step(config, optimizer, donate=False,
                               accum_steps=4)

    key = jax.random.PRNGKey(2)
    for _ in range(5):
        key, sub = jax.random.split(key)
        batch = _batch(rng, 16, config.n_features)
        params, opt_state, m_full = step_full(params, opt_state, sub, batch)
        params_acc, opt_state_acc, m_acc = step_acc(params_acc,
                                                    opt_state_acc, sub, batch)
        np.testing.assert_allclose(float(m_acc["cost"]),
                                   float(m_full["cost"]), rtol=1e-5)
    for (ka, pa), (kb, pb) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(params),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(params_acc),
                   key=lambda kv: str(kv[0]))):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(np.asarray(pb), np.asarray(pa),
                                   atol=1e-5, err_msg=str(ka))


# ---------------------------------------------------------- compile count

def test_accum_step_compiles_once(rng):
    """Satellite regression: the microbatch loop is a lax.scan INSIDE the one
    jitted step — accum_steps=4 compiles exactly one program, and repeat
    calls (and a second "epoch") compile nothing."""
    # n_features unique to this test so the step can't be cache-warm from
    # another module when the whole suite shares the process
    config = _no_mining_config(f=23, d=4)
    optimizer = make_optimizer("ada_grad", 0.1)
    params = init_params(jax.random.PRNGKey(0), config)
    opt_state = optimizer.init(params)
    step = make_train_step(config, optimizer, accum_steps=4)
    key = jax.random.PRNGKey(1)
    key, _ = jax.random.split(key)  # pre-warm split's own compile

    def run(params, opt_state, key, n):
        for _ in range(n):
            key, sub = jax.random.split(key)
            batch = _batch(rng, 16, config.n_features)
            params, opt_state, metrics = step(params, opt_state, sub, batch)
        jax.block_until_ready(metrics["cost"])
        return params, opt_state, key

    with compile_guard(max_compiles=1) as first:
        params, opt_state, key = run(params, opt_state, key, 3)
    assert first.count == 1

    with compile_guard(max_compiles=0) as second:
        params, opt_state, key = run(params, opt_state, key, 2)
    assert second.count == 0


# ------------------------------------------------- estimator provenance

def test_estimator_manifest_records_accum_and_mining(workdir):
    """The run manifest self-describes the large-batch knobs: requested
    mining_impl and the accum_steps actually in effect."""
    from dae_rnn_news_recommendation_tpu import telemetry

    rng = np.random.default_rng(0)
    x = (rng.uniform(size=(30, 24)) < 0.25).astype(np.float32)
    labels = rng.integers(0, 4, 30).astype(np.int32)
    m = DenoisingAutoencoder(
        model_name="accum", main_dir="accum", n_components=6, num_epochs=1,
        batch_size=10, seed=7, corr_type="masking", corr_frac=0.3,
        loss_func="mean_squared", opt="ada_grad", learning_rate=0.1,
        verbose=False, use_tensorboard=False, accum_steps=2,
        results_root=str(workdir / "results"))
    m.fit(x, train_set_label=labels)
    manifest = telemetry.read_manifest(m.run_manifest_path)
    assert manifest["mining_impl"] == "auto"
    assert manifest["accum_steps"] == 2
    assert "accum_fallback" not in manifest  # nothing fell back, no noise
    assert m._accum_effective == 2
    # the feed rounds batches to a multiple of accum_steps so the jitted
    # step's [accum, B/accum, ...] reshape is always exact
    assert m._batch_multiple == 2


def test_estimator_shard_scope_fallback_is_recorded(workdir):
    """mining_scope='shard' has no accumulation path (the objective runs
    inside shard_map) — the build must fall back to accum_steps=1 AND record
    why, never silently. Build-level only: exercising the sharded step needs
    jax.shard_map (tests/test_sharded_mining.py covers it when present)."""
    m = DenoisingAutoencoder(
        model_name="accum_shard", main_dir="accum_shard", n_components=4,
        num_epochs=1, batch_size=8, seed=7, loss_func="mean_squared",
        opt="ada_grad", learning_rate=0.1, verbose=False,
        use_tensorboard=False, n_devices=2, mining_scope="shard",
        accum_steps=4, results_root=str(workdir / "results"))
    m._build(16, False)
    assert m._accum_effective == 1
    assert m._accum_fallback is not None
    assert "mining_scope='shard'" in m._accum_fallback
    assert "accum_steps=4 ignored" in m._accum_fallback
    # the data-shard batch multiple no longer carries the accum factor
    assert m._batch_multiple == 2


def test_parallel_step_refuses_shard_accum():
    """Defense in depth below the estimator: dp.py itself rejects the
    combination rather than splitting a shard_map objective wrong."""
    from dae_rnn_news_recommendation_tpu.parallel.dp import (
        get_mesh, make_parallel_train_step)

    config = _no_mining_config(f=8, d=3)
    optimizer = make_optimizer("ada_grad", 0.1)
    mesh = get_mesh(2)
    with pytest.raises(ValueError, match="accum_steps"):
        make_parallel_train_step(config, optimizer, mesh,
                                 mining_scope="shard", accum_steps=2)
