"""Graceful-interruption fit: SIGTERM mid-run must finish the epoch, save a
checkpoint with the true epoch, and return normally — so a preempted job
resumes exactly (SURVEY §5 failure-recovery; the reference loses the whole run,
autoencoder.py:156 saves only after all epochs)."""

import os
import signal
import subprocess
import sys
import textwrap

import numpy as np

_SCRIPT = textwrap.dedent("""
    import os, sys
    repo = sys.argv[1]
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np, scipy.sparse as sp
    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder

    X = sp.random(200, 64, density=0.3, format="csr", random_state=0,
                  dtype=np.float32)
    labels = np.random.default_rng(0).integers(0, 5, 200)
    m = DenoisingAutoencoder(model_name="g", compress_factor=8, num_epochs=500,
                             batch_size=32, opt="ada_grad", learning_rate=0.1,
                             verbose=True, verbose_step=1, seed=0,
                             triplet_strategy="batch_all", use_tensorboard=False)
    # verbose_step=1 prints a line per epoch -> the parent signals on epoch 2
    m.fit(X, train_set_label=labels)
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
        latest_checkpoint)
    path, step = latest_checkpoint(m.model_path)
    print("STOPPED_AT", step, flush=True)
""")


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    script = tmp_path / "run.py"
    script.write_text(_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.Popen([sys.executable, str(script), repo],
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True, cwd=tmp_path, env=env)
    # wait for a couple of per-epoch lines, then interrupt
    lines = []
    for line in proc.stdout:
        lines.append(line)
        if line.startswith("At step 2"):
            proc.send_signal(signal.SIGTERM)
        if line.startswith("STOPPED_AT"):
            break
    out, _ = proc.communicate(timeout=300)
    lines.append(out or "")
    joined = "".join(lines)
    assert proc.returncode == 0, joined[-2000:]
    stopped = [ln for ln in joined.splitlines() if ln.startswith("STOPPED_AT")]
    assert stopped, joined[-2000:]
    step = int(stopped[0].split()[1])
    assert 2 <= step < 500, joined[-1000:]  # stopped early, checkpoint present
    assert "stopping early" in joined


def test_keyboard_interrupt_mid_epoch_saves_cursor_and_joins_feed(
        tmp_path, monkeypatch, capsys):
    """A KeyboardInterrupt that lands MID-epoch (past the graceful signal
    handler: a second Ctrl-C, or one on the consumer thread) must stop the
    pipelined feed (worker joined, not leaked), persist the epoch's progress
    as a mid-epoch cursor checkpoint, and let fit() return normally."""
    import glob
    import threading

    import scipy.sparse as sp

    from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
    from dae_rnn_news_recommendation_tpu.train import pipeline as pipeline_mod

    monkeypatch.chdir(tmp_path)

    class InterruptingFeed(pipeline_mod.PipelinedFeed):
        """The real feed, but the consumer gets Ctrl-C'd after 2 batches."""

        def __iter__(self):
            for i, batch in enumerate(super().__iter__()):
                if i == 2:
                    raise KeyboardInterrupt
                yield batch

    # the estimator imports PipelinedFeed from train.pipeline at fit() time
    monkeypatch.setattr(pipeline_mod, "PipelinedFeed", InterruptingFeed)
    x = sp.random(100, 32, density=0.3, format="csr", random_state=0,
                  dtype=np.float32)
    m = DenoisingAutoencoder(
        model_name="ki", main_dir="ki", n_components=4, num_epochs=5,
        batch_size=10, opt="ada_grad", learning_rate=0.1, verbose=False,
        seed=0, use_tensorboard=False, feed="pipelined",
        triplet_strategy="none",
        results_root=str(tmp_path / "results"))
    m.fit(x)  # must RETURN, not propagate the interrupt
    out = capsys.readouterr().out
    assert "interrupted mid-epoch 1 at step 2" in out
    assert "cursor checkpoint saved" in out
    assert m._stop_requested  # epochs 2..5 never ran
    # the cursor checkpoint is on disk (step_<E>_<2>) and resumable
    cursors = glob.glob(os.path.join(m.model_path, "step_*_2"))
    assert cursors, os.listdir(m.model_path)
    from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
        load_checkpoint)
    state = load_checkpoint(cursors[0], {"params": m.params,
                                         "opt_state": m.opt_state,
                                         "epoch": np.asarray(0)})
    assert set(state) >= {"params", "opt_state"}
    # the feed worker joined: nothing named pipelined-feed is left running
    leaked = [t for t in threading.enumerate()
              if t.name.startswith("pipelined-feed") and t.is_alive()]
    assert leaked == []
