"""Serving-path contracts: graph correctness, deadline admission, degraded
modes, and the health-gated hot corpus swap (ISSUE 8 tentpole).

The invariant every test leans on: a submitted request ends in EXACTLY ONE of
{reply, explicit shed, explicit error} — never a hang, never a silent drop.
"""

import threading
import time

import numpy as np
import pytest

import jax

from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.reliability import faults
from dae_rnn_news_recommendation_tpu.reliability.retry import RetryPolicy
from dae_rnn_news_recommendation_tpu.serve import (RecommendationService,
                                                   ServingCorpus,
                                                   make_serve_fn)

N, F, D = 64, 24, 8
SLA = 10.0  # generous: CPU test boxes stall; admission logic is what's tested


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((N, F), dtype=np.float32)
    return config, params, articles


def make_corpus(config, params, articles, **kw):
    corpus = ServingCorpus(config, block=16, **kw)
    corpus.swap(params, articles, note="initial")
    return corpus


def make_service(config, params, corpus, **kw):
    kw.setdefault("top_k", 5)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_inflight", 16)
    svc = RecommendationService(params, config, corpus, **kw)
    svc.warmup()
    return svc


# ------------------------------------------------------------------- graph

def _unit(h):
    # host twin of ops.normalize.l2_normalize (tf.nn.l2_normalize form)
    sq = np.sum(np.square(h), axis=-1, keepdims=True)
    return h * (1.0 / np.sqrt(np.maximum(sq, 1e-12)))


@pytest.mark.parametrize("fused", [True, False])
def test_topk_graph_matches_numpy_ranking(setup, fused):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    slot = corpus.active
    fn = make_serve_fn(config, 7, fused=fused)
    queries = articles[:5]
    scores, idx = jax.device_get(
        fn(params, slot.emb, slot.valid, slot.scales, queries))
    # oracle: encode everything densely on host via the same jitted encode
    from dae_rnn_news_recommendation_tpu.train.step import make_encode_fn

    enc = make_encode_fn(config)
    emb = _unit(np.asarray(jax.device_get(enc(params, articles))))
    qh = _unit(np.asarray(jax.device_get(enc(params, queries))))
    oracle = (qh @ emb.T).argsort(axis=1)[:, ::-1][:, :7]
    np.testing.assert_array_equal(idx, oracle)
    assert np.all(np.diff(scores, axis=1) <= 1e-6)  # descending


def test_fused_and_unfused_serve_graphs_agree_bitwise(setup):
    """The fused scorer must be a drop-in for the r07 materializing path:
    identical scores (bitwise) and identical tie-broken indices."""
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    slot = corpus.active
    queries = articles[:9]
    a = jax.device_get(make_serve_fn(config, 7, fused=True)(
        params, slot.emb, slot.valid, slot.scales, queries))
    b = jax.device_get(make_serve_fn(config, 7, fused=False)(
        params, slot.emb, slot.valid, slot.scales, queries))
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_query_of_a_corpus_row_ranks_itself_first(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        fut = svc.submit(articles[11], deadline_s=SLA)
        reply = fut.result(timeout=SLA)
        assert reply.ok and reply.indices[0] == 11
        assert reply.deadline_met and reply.degraded == ()
        assert reply.corpus_version == corpus.version
    finally:
        svc.stop()


# --------------------------------------------------------------- admission

def test_every_submission_gets_exactly_one_outcome(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        futs = [svc.submit(articles[i % N], deadline_s=SLA)
                for i in range(40)]
        replies = [f.result(timeout=SLA) for f in futs]
    finally:
        svc.stop()
    c = svc.counts
    assert c["submitted"] == 40
    assert c["replied"] + c["shed"] + c["errors"] == 40
    assert all(r.status in ("ok", "shed", "error") for r in replies)
    # a shed is never anonymous
    assert all(r.reason for r in replies if r.status == "shed")


def test_provably_unmeetable_deadline_is_shed_at_admission(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        assert svc._floor_s > 0  # warmup seeded the proof floor
        reply = svc.submit(articles[0], deadline_s=1e-9).result(timeout=SLA)
        assert reply.status == "shed"
        assert reply.reason == "deadline_unmeetable"
    finally:
        svc.stop()


def test_queue_overflow_sheds_instead_of_buffering(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    # a 2-deep admission queue and a batcher parked behind a slow flush
    svc = make_service(config, params, corpus, max_inflight=2,
                       linger_s=0.5, flush_slack_s=0.01)
    try:
        futs = [svc.submit(articles[i % N], deadline_s=SLA)
                for i in range(12)]
        replies = [f.result(timeout=SLA) for f in futs]
    finally:
        svc.stop()
    sheds = [r for r in replies if r.status == "shed"]
    assert any(r.reason == "queue_full" for r in sheds)
    assert all(r.status in ("ok", "shed") for r in replies)


def test_stop_resolves_everything_still_queued(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    futs = [svc.submit(articles[i % N], deadline_s=SLA) for i in range(6)]
    svc.stop()
    replies = [f.result(timeout=5) for f in futs]  # nothing may hang
    assert all(r.status in ("ok", "shed") for r in replies)
    post = svc.submit(articles[0], deadline_s=SLA).result(timeout=5)
    assert post.status == "shed" and post.reason == "shutdown"
    assert not svc._thread.is_alive()


# ------------------------------------------------------------ fault injection

def test_transient_batch_fault_is_retried_and_recorded(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("serve.batch", 1, "transient"),))
    inj = faults.FaultInjector(plan)
    svc = make_service(config, params, corpus, retry=RetryPolicy(
        max_attempts=3, backoff_s=0.001, rng=lambda: 1.0))
    try:
        with faults.install(inj):
            reply = svc.submit(articles[4], deadline_s=SLA).result(
                timeout=SLA)
        assert reply.ok and reply.indices[0] == 4  # absorbed, answer intact
        assert [e["site"] for e in inj.retries] == ["serve.batch"]
        assert inj.fired and inj.fired[0]["kind"] == "transient"
    finally:
        svc.stop()


def test_fatal_batch_fault_is_an_explicit_error_not_a_hang(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("serve.batch", 1, "fatal"),))
    svc = make_service(config, params, corpus)
    try:
        with faults.install(faults.FaultInjector(plan)):
            reply = svc.submit(articles[0], deadline_s=SLA).result(
                timeout=SLA)
        assert reply.status == "error"
        assert "InjectedFault" in reply.reason
        # the service keeps serving after the fault
        again = svc.submit(articles[1], deadline_s=SLA).result(timeout=SLA)
        assert again.ok
    finally:
        svc.stop()


def test_fatal_enqueue_fault_is_an_explicit_error(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("serve.enqueue", 1, "fatal"),))
    svc = make_service(config, params, corpus)
    try:
        with faults.install(faults.FaultInjector(plan)):
            reply = svc.submit(articles[0], deadline_s=SLA).result(
                timeout=SLA)
        assert reply.status == "error" and "serve.enqueue" in reply.reason
    finally:
        svc.stop()


# ------------------------------------------------------------ degraded modes

def test_overload_enters_recorded_degraded_mode_with_truncated_topk(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    # max_batch=1 so the batcher dispatches one request at a time, and an
    # injected transient on the FIRST dispatch makes its retry sleep 0.3 s —
    # a deterministic stall during which the remaining submissions pile up
    # past the watermark, so the next dispatch provably runs degraded.
    svc = make_service(config, params, corpus, top_k=6, degraded_top_k=2,
                       max_batch=1, max_inflight=16,
                       overload_watermark=0.5, linger_s=0.001,
                       flush_slack_s=0.001,
                       retry=RetryPolicy(max_attempts=3, backoff_s=0.3,
                                         rng=lambda: 1.0))
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("serve.batch", 1, "transient"),))
    try:
        with faults.install(faults.FaultInjector(plan)):
            futs = [svc.submit(articles[i % N], deadline_s=SLA)
                    for i in range(12)]
            replies = [f.result(timeout=SLA) for f in futs]
    finally:
        svc.stop()
    degraded = [r for r in replies
                if r.ok and "topk_truncated" in r.degraded]
    assert degraded, "overload never engaged the degraded mode"
    assert all(len(r.indices) == 2 for r in degraded)
    assert all("coarse_batching" in r.degraded for r in degraded)
    events = [e["event"] for e in svc.events]
    assert "degraded_enter" in events  # recorded, never silent
    assert any(e["event"] == "degraded_enter" and "occupancy" in e
               for e in svc.events)


def test_swap_during_serving_tags_stale_and_promotes(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        v0 = corpus.version
        fresh = np.random.default_rng(9).random((N, F), dtype=np.float32)
        stale_seen = []

        def swapper():
            corpus.swap(params, fresh, note="refresh")

        t = threading.Thread(target=swapper)
        t.start()
        while t.is_alive():
            r = svc.submit(articles[0], deadline_s=SLA).result(timeout=SLA)
            if r.ok and "stale_corpus" in r.degraded:
                stale_seen.append(r)
        t.join(timeout=10)
        assert corpus.version == v0 + 1
        assert any(e["event"] == "swap" for e in corpus.events)
        # post-swap replies come from the new version
        r = svc.submit(fresh[7], deadline_s=SLA).result(timeout=SLA)
        assert r.ok and r.corpus_version == v0 + 1 and r.indices[0] == 7
    finally:
        svc.stop()


# ----------------------------------------------------------------- hot swap

def test_injected_swap_fault_rolls_back_to_the_serving_corpus(setup):
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        v0 = corpus.version
        plan = faults.FaultPlan(seed=0, specs=(
            faults.FaultSpec("serve.swap", 1, "fatal"),))
        fresh = np.random.default_rng(10).random((N, F), dtype=np.float32)
        with faults.install(faults.FaultInjector(plan)):
            slot = corpus.swap(params, fresh, note="doomed")
        assert corpus.version == v0  # rollback: version unchanged
        assert slot is corpus.active
        rb = [e for e in corpus.events if e["event"] == "swap_rollback"]
        assert rb and "InjectedFault" in rb[0]["error"]
        # the OLD corpus still serves
        r = svc.submit(articles[5], deadline_s=SLA).result(timeout=SLA)
        assert r.ok and r.indices[0] == 5 and r.corpus_version == v0
    finally:
        svc.stop()


def test_health_gate_refuses_a_collapsed_corpus(setup):
    config, params, articles = setup
    # every article identical -> every embedding identical -> mean pairwise
    # cosine 1 > ceiling: the textbook collapse the gate exists to refuse
    collapsed = np.tile(articles[:1], (N, 1))
    corpus = make_corpus(config, params, articles)
    v0 = corpus.version
    slot = corpus.swap(params, collapsed, note="collapsed")
    assert corpus.version == v0 and slot is corpus.active
    rb = [e for e in corpus.events if e["event"] == "swap_rollback"]
    assert rb and "health gate" in rb[0]["error"]


def test_failed_first_swap_raises_with_nothing_to_serve(setup):
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("serve.swap", 1, "fatal"),))
    with faults.install(faults.FaultInjector(plan)):
        with pytest.raises(faults.InjectedFault):
            corpus.swap(params, articles, note="first")
    assert corpus.active is None


class _HoldSwapOpen:
    """Injector double whose fire() parks inside the swap's standby build —
    a deterministic in-flight window for the re-entrancy tests (no sleeps)."""

    def __init__(self, site):
        self.site = site
        self.entered = threading.Event()
        self.release = threading.Event()

    def fire(self, site, **info):
        if site == self.site:
            self.entered.set()
            assert self.release.wait(timeout=SLA)

    def note_retry(self, event):
        pass


def test_swap_reentrancy_raises_swap_in_progress_deterministically(setup):
    """Satellite: concurrent refresh attempts while a swap is in flight must
    fail fast with SwapInProgress — never interleave slot state. The window
    is held open deterministically by parking the first swap inside its
    build hook."""
    from dae_rnn_news_recommendation_tpu.serve import SwapInProgress

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    v0 = corpus.version
    hold = _HoldSwapOpen("serve.swap")
    fresh = np.random.default_rng(11).random((N, F), dtype=np.float32)
    with faults.install(hold):
        t = threading.Thread(
            target=corpus.swap, args=(params, fresh),
            kwargs={"note": "in-flight"})
        t.start()
        assert hold.entered.wait(timeout=SLA)  # swap A is inside its build
        with pytest.raises(SwapInProgress):
            corpus.swap(params, articles, note="concurrent full")
        with pytest.raises(SwapInProgress):
            corpus.swap_incremental(params, articles[:8],
                                    note="concurrent incremental")
        hold.release.set()
        t.join(timeout=SLA)
        assert not t.is_alive()
    # swap A landed exactly once; the rejected attempts left no slot state
    assert corpus.version == v0 + 1
    rejected = [e for e in corpus.events
                if e["event"] == "swap_rejected_busy"]
    assert len(rejected) == 2
    # the guard is released: a follow-up swap succeeds normally
    corpus.swap(params, articles, note="after")
    assert corpus.version == v0 + 2


def test_sharded_service_matches_single_device_ranking(setup):
    """Satellite: RecommendationService(sharded=True) serves a row-sharded
    corpus through make_sharded_serve_fn with the same replies as the
    single-device path (conftest pins 8 virtual CPU devices)."""
    from dae_rnn_news_recommendation_tpu.parallel.mesh import (get_mesh,
                                                               shard_rows)

    config, params, articles = setup
    mesh = get_mesh()
    corpus = make_corpus(config, params, articles,
                         device_put=lambda x: shard_rows(x, mesh))
    svc = make_service(config, params, corpus, sharded=True, mesh=mesh)
    try:
        assert svc.sharded and svc.summary()["sharded"]
        replies = [svc.submit(articles[i], deadline_s=SLA).result(timeout=SLA)
                   for i in (0, 11, 37)]
        assert all(r.ok for r in replies)
        assert [r.indices[0] for r in replies] == [0, 11, 37]
    finally:
        svc.stop()
    ref_corpus = make_corpus(config, params, articles)
    ref = make_service(config, params, ref_corpus)
    try:
        for i, r in zip((0, 11, 37), replies):
            rr = ref.submit(articles[i], deadline_s=SLA).result(timeout=SLA)
            np.testing.assert_array_equal(np.asarray(r.indices),
                                          np.asarray(rr.indices))
    finally:
        ref.stop()


# ---------------------------------------------------------------- telemetry

def test_serving_emits_fenced_batch_spans_and_request_spans(setup):
    import dae_rnn_news_recommendation_tpu.telemetry as telemetry

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    telemetry.enable()
    try:
        svc.submit(articles[0], deadline_s=SLA).result(timeout=SLA)
        time.sleep(0.05)  # let the batcher's span land
    finally:
        svc.stop()
        tracer = telemetry.disable()
    names = [e["name"] for e in tracer.events()]
    assert "serve/batch" in names
    assert "serve/request" in names
    batch = next(e for e in tracer.events() if e["name"] == "serve/batch")
    assert batch["args"]["n"] == 1 and batch["args"]["k"] == 5


# ------------------------------------------- absolute deadlines (ISSUE 12)

def test_absolute_deadline_budget_shrinks_instead_of_resetting(setup):
    """The deadline-propagation fix: a hedge/retry re-enqueue passes the
    original request's ABSOLUTE deadline, so a nearly-expired request is
    shed as provably unmeetable at admission — never re-queued with a fresh
    full budget."""
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    try:
        assert svc._floor_s > 0  # warmup seeded the proof floor
        nearly_spent = time.monotonic() + svc._floor_s / 10
        reply = svc.submit(articles[0],
                           deadline_at=nearly_spent).result(timeout=SLA)
        assert reply.status == "shed"
        assert reply.reason == "deadline_unmeetable"
        # deadline_at WINS over deadline_s: the generous relative budget a
        # buggy re-enqueue might pass alongside cannot resurrect the request
        reply = svc.submit(articles[0], deadline_s=SLA,
                           deadline_at=time.monotonic() - 1.0).result(
                               timeout=SLA)
        assert reply.status == "shed"
        assert reply.reason == "deadline_unmeetable"
        # a healthy absolute deadline serves like a relative one
        reply = svc.submit(articles[3],
                           deadline_at=time.monotonic() + SLA).result(
                               timeout=SLA)
        assert reply.ok and reply.indices[0] == 3
    finally:
        svc.stop()


# --------------------------------------- readers vs swap/revert (ISSUE 12)

def test_swap_rollback_and_revert_with_concurrent_readers(setup):
    """Readers hammering `corpus.active` across promotes, reverts, and
    fault-injected rollbacks must never observe a torn slot: every slot
    reference is immutable once promoted, array shapes stay mutually
    consistent, and only fully-promoted versions are ever visible. A reader
    that pinned the pre-churn slot can still score against it afterwards."""
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    held = corpus.active  # a long-lived reader pins the pre-churn slot
    stop = threading.Event()
    torn, seen_versions = [], set()

    def reader():
        while not stop.is_set():
            slot = corpus.active
            seen_versions.add(slot.version)
            emb = np.asarray(slot.emb)
            if (emb.shape[0] != slot.valid.shape[0]
                    or slot.n > slot.valid.shape[0] or slot.version < 1):
                torn.append(slot.version)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(4):
            fresh = np.random.default_rng(100 + i).random(
                (N, F), dtype=np.float32)
            corpus.swap(params, fresh, note=f"promote-{i}")
            corpus.revert(note=f"fleet-rollback-{i}")
            # the OTHER failure path: a mid-build fault discards the standby
            # and the serving slot never changes hands at all
            plan = faults.FaultPlan(seed=i, specs=(
                faults.FaultSpec("serve.swap", 1, "fatal"),))
            with faults.install(faults.FaultInjector(plan)):
                corpus.swap(params, fresh, note=f"doomed-{i}")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert torn == []
    assert seen_versions <= {1, 2}  # never more than the two live versions
    assert corpus.version == 1 and corpus.active is held
    # the pinned pre-churn slot is still fully usable after all the churn
    fn = make_serve_fn(config, 5, fused=True)
    _, idx = jax.device_get(
        fn(params, held.emb, held.valid, held.scales, articles[:3]))
    np.testing.assert_array_equal(np.asarray(idx)[:, 0], [0, 1, 2])
    from dae_rnn_news_recommendation_tpu.reliability.ledger import (
        audit_version_ledger)
    _, _, problems = audit_version_ledger(corpus.ledger, allow_revert=True)
    assert problems == []


# ------------------------------------------------ revert edges (ISSUE 13)

def test_revert_refuses_without_a_displaced_slot(setup):
    """Revert is a single-level undo of a promote that DISPLACED a serving
    slot: after only the initial swap there is nothing to re-install, and
    a second revert without an intervening promote is equally illegal."""
    from dae_rnn_news_recommendation_tpu.serve import SwapRejected

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)  # v1, nothing displaced
    with pytest.raises(SwapRejected, match="no previous slot"):
        corpus.revert(note="nothing to undo")
    assert corpus.version == 1  # the refusal left the serving line alone
    corpus.swap(params, articles, note="promote")  # v2 displaces v1
    corpus.revert(note="legal undo")
    assert corpus.version == 1
    with pytest.raises(SwapRejected, match="no previous slot"):
        corpus.revert(note="double undo")
    assert corpus.version == 1
    # the guard is released both times: a follow-up promote works
    corpus.swap(params, articles, note="after")
    assert corpus.version == 2


def test_revert_racing_concurrent_readers_never_tears(setup):
    """Readers pinning and re-reading `corpus.active` across a promote ->
    revert churn loop only ever observe fully-promoted slots, and a slot
    pinned BEFORE a revert stays scoreable after it."""
    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            slot = corpus.active
            if slot.version not in (1, 2) or slot.n > slot.valid.shape[0]:
                bad.append(slot.version)

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    pinned = corpus.active
    try:
        for i in range(6):
            corpus.swap(params, articles, note=f"promote-{i}")
            corpus.revert(note=f"revert-{i}")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert bad == []
    assert corpus.version == 1
    fn = make_serve_fn(config, 5, fused=True)
    _, idx = jax.device_get(
        fn(params, pinned.emb, pinned.valid, pinned.scales, articles[:2]))
    np.testing.assert_array_equal(np.asarray(idx)[:, 0], [0, 1])


# ------------------------------------- sharded swap/append (ISSUE 13)

def test_ivf_sharded_composition_accepted_and_taxonomy_kept():
    """r16 flipped the r11 refusal: retrieval='ivf' + a mesh composes (the
    corpus constructor accepts it without touching a device), and the typed
    `ShardedUnsupported` stays importable in the exception taxonomy for
    callers that guard on it."""
    from dae_rnn_news_recommendation_tpu.parallel.mesh import get_mesh
    from dae_rnn_news_recommendation_tpu.serve import ShardedUnsupported

    assert issubclass(ShardedUnsupported, ValueError)
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    corpus = ServingCorpus(config, retrieval="ivf", mesh=get_mesh())
    assert corpus.retrieval == "ivf" and corpus.mesh is not None


def test_sharded_swap_incremental_promotes_with_uniform_shard_stamps(setup):
    """The ISSUE 13 acceptance path: `swap_incremental` on a mesh-sharded
    slot SUCCEEDS (the r10 refusal is gone), the append rides the two-phase
    prepare -> commit (a swap_prepare event stages the shards, the promote
    stamps every shard to the new version), the ledger stays
    version-monotonic with uniform per-shard stamps, and the sharded
    ranking matches a single-device corpus running the same ops."""
    from dae_rnn_news_recommendation_tpu.parallel.mesh import get_mesh
    from dae_rnn_news_recommendation_tpu.reliability.ledger import (
        audit_version_ledger)

    config, params, articles = setup
    mesh = get_mesh()
    n_dev = len(jax.devices())
    batch = np.random.default_rng(77).random((16, F), dtype=np.float32)

    corpus = ServingCorpus(config, block=16, mesh=mesh)
    corpus.swap(params, articles, note="initial")
    slot = corpus.swap_incremental(params, batch, max_rows=N,
                                   note="sharded append")
    assert corpus.version == 2 and slot.n == N
    assert slot.shard_versions is not None
    assert list(slot.shard_versions) == [2] * n_dev
    prepares = [e for e in corpus.events if e["event"] == "swap_prepare"]
    assert len(prepares) == 2  # one per two-phase swap (full + incremental)
    assert prepares[-1]["n_shards"] == n_dev
    versions, n_rollbacks, problems = audit_version_ledger(corpus.ledger)
    assert (versions, n_rollbacks, problems) == ([1, 2], 0, [])
    for rec in corpus.ledger:
        assert rec["shards"]["versions"] == [rec["version"]] * n_dev

    # ledger + ranking parity with the single-device line of the same ops
    ref = ServingCorpus(config, block=16)
    ref.swap(params, articles, note="initial")
    ref_slot = ref.swap_incremental(params, batch, max_rows=N,
                                    note="append")
    ref_versions, _, ref_problems = audit_version_ledger(ref.ledger)
    assert ref_versions == versions and ref_problems == []
    assert ref_slot.n == slot.n
    np.testing.assert_array_equal(slot.ages, ref_slot.ages)
    from dae_rnn_news_recommendation_tpu.serve import make_sharded_serve_fn

    sharded_fn = make_sharded_serve_fn(config, 5, mesh)
    flat_fn = make_serve_fn(config, 5, fused=True)
    queries = articles[:6]
    _, idx_sharded = jax.device_get(sharded_fn(
        params, slot.emb, slot.valid, slot.scales, queries))
    _, idx_flat = jax.device_get(flat_fn(
        params, ref_slot.emb, ref_slot.valid, ref_slot.scales, queries))
    np.testing.assert_array_equal(np.asarray(idx_sharded),
                                  np.asarray(idx_flat))


# --------------------------------------------- observability (ISSUE 14)

def test_request_ids_and_timing_decomposition(setup):
    """Every reply carries a request id and a per-hop timing record whose
    components (admit -> queue -> batch formation -> fenced compute ->
    resolve) sum to the reply's own latency — the timing-honesty contract
    the fleet soak audits at scale."""
    from dae_rnn_news_recommendation_tpu.telemetry import MetricsRegistry

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    reg = MetricsRegistry("svc")
    svc = make_service(config, params, corpus, name="svc", registry=reg)
    try:
        replies = [svc.submit(articles[i], deadline_s=SLA).result(timeout=SLA)
                   for i in range(6)]
        custom = svc.submit(articles[0], deadline_s=SLA,
                            request_id="caller-7").result(timeout=SLA)
    finally:
        svc.stop()
    ids = [r.request_id for r in replies]
    assert all(ids) and len(set(ids)) == len(ids)
    assert all(rid.startswith("svc-") for rid in ids)
    assert custom.request_id == "caller-7"  # caller-supplied id wins
    for r in replies + [custom]:
        assert r.ok
        t = r.timings
        assert set(t) <= {"admit_s", "queue_s", "batch_form_s",
                          "compute_s", "resolve_s"}
        assert "compute_s" in t
        assert all(v >= 0.0 for v in t.values())
        assert abs(sum(t.values()) - r.latency_s) < 1e-3, (t, r.latency_s)
    snap = reg.snapshot()
    assert snap["counters"]["submitted"] == 7
    assert snap["counters"]["replied"] == 7
    assert snap["histograms"]["request_latency_ms"]["count"] == 7


def test_shed_replies_carry_ids_and_timings_too(setup):
    """An admission shed is still a traced outcome: id + (short) timing
    record, and the per-reason shed counter increments."""
    from dae_rnn_news_recommendation_tpu.telemetry import MetricsRegistry

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    reg = MetricsRegistry("svc")
    svc = make_service(config, params, corpus, registry=reg)
    try:
        reply = svc.submit(articles[0], deadline_s=1e-9).result(timeout=SLA)
    finally:
        svc.stop()
    assert reply.status == "shed"
    assert reply.request_id
    assert sum(reply.timings.values()) >= 0.0
    snap = reg.snapshot()
    assert snap["counters"]["shed"] == 1
    assert any(k.startswith("shed.") and v == 1
               for k, v in snap["counters"].items()), snap["counters"]


def test_trace_sampling_thins_request_spans_not_counters(setup):
    """trace_sample_rate=0.25 keeps every 4th `serve/request` span (the
    zero-length per-request event) while counters and histograms still see
    every request — sampling thins the TRACE, never the metrics."""
    import dae_rnn_news_recommendation_tpu.telemetry as telemetry
    from dae_rnn_news_recommendation_tpu.telemetry import MetricsRegistry

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    reg = MetricsRegistry("svc")
    svc = make_service(config, params, corpus, registry=reg,
                       trace_sample_rate=0.25)
    telemetry.enable(xla_events=False)
    try:
        for i in range(8):
            assert svc.submit(articles[i],
                              deadline_s=SLA).result(timeout=SLA).ok
    finally:
        svc.stop()
        tracer = telemetry.disable()
    req_spans = [e for e in tracer.events() if e["name"] == "serve/request"]
    assert len(req_spans) == 2  # period 4 -> requests 1 and 5 of 8
    assert reg.counter("replied").value == 8
    assert reg.histogram("request_latency_ms").state()["count"] == 8


def test_default_sampling_keeps_every_request_span(setup):
    import dae_rnn_news_recommendation_tpu.telemetry as telemetry

    config, params, articles = setup
    corpus = make_corpus(config, params, articles)
    svc = make_service(config, params, corpus)
    telemetry.enable(xla_events=False)
    try:
        for i in range(4):
            assert svc.submit(articles[i],
                              deadline_s=SLA).result(timeout=SLA).ok
    finally:
        svc.stop()
        tracer = telemetry.disable()
    req_spans = [e for e in tracer.events() if e["name"] == "serve/request"]
    assert len(req_spans) == 4
    assert all(e["args"]["id"] for e in req_spans)
    assert all("timings" in e["args"] for e in req_spans)
