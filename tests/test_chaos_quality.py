"""Chaos-quality: the both-ways retrieval-quality alert contract (ISSUE 19).

Every plan (serve/chaos_quality.py) runs a real sharded/IVF service with
100% shadow sampling and the quality SLO monitor attached. Tier-1 smokes
one fault plan plus its fault-free reference per family; the multi-seed
soak is `slow`.

The contract, both ways:
  * cell-owning-shard-loss fires `quality-coverage` (and was provably
    clean BEFORE the fault);
  * churn-drift (serving params drifted from the corpus build params,
    materiality-verified at plan construction) fires `quality-recall`
    while coverage stays pinned at 1.0 — `quality-coverage` must NOT fire;
  * the fault-free reference replay fires NOTHING, with shadow recall
    exactly 1.0 (structural: each query is a corpus row's own features,
    and kmeans assigns every row to its nearest final centroid, so the
    probed cell always contains the exact top-1);
  * `quality-quant-error` stays silent everywhere (fp32 corpora);
  * zero post-warm compiles in every plan — the shadow path never
    retraces live.
"""

import pytest

from dae_rnn_news_recommendation_tpu.serve import (QUALITY_FAMILIES,
                                                   chaos_quality_soak,
                                                   run_quality_plan,
                                                   run_quality_reference)


def test_quality_families_map_onto_the_fleet_alert_contract():
    from dae_rnn_news_recommendation_tpu.fleet import QUALITY_FAMILY_ALERTS
    assert set(QUALITY_FAMILIES) == set(QUALITY_FAMILY_ALERTS) == {
        "cell-owning-shard-loss", "churn-drift"}
    assert set(QUALITY_FAMILY_ALERTS.values()) == {
        "quality-coverage", "quality-recall"}


@pytest.mark.parametrize("family", QUALITY_FAMILIES)
def test_quality_fault_plan_fires_the_mapped_alert(family):
    result = run_quality_plan(0, family, n_requests=24)
    assert result.ok, result.detail
    assert result.injected
    assert result.n_scored > 0
    assert result.n_post_warm_compiles == 0
    fired = set(result.alerts)
    if family == "cell-owning-shard-loss":
        assert "quality-coverage" in fired
        assert result.min_coverage < 1.0
    else:
        assert "quality-recall" in fired
        assert "quality-coverage" not in fired
        assert result.min_coverage == 1.0
        assert result.recall_mean < 1.0
    assert "quality-quant-error" not in fired


@pytest.mark.parametrize("family", QUALITY_FAMILIES)
def test_quality_reference_replay_is_silent(family):
    result = run_quality_reference(0, family, n_requests=24)
    assert result.ok, result.detail
    assert not result.injected
    assert result.alerts == []
    assert result.recall_mean == 1.0
    assert result.min_coverage == 1.0
    assert result.n_post_warm_compiles == 0


@pytest.mark.slow
def test_chaos_quality_full_soak():
    out = chaos_quality_soak(n_seeds=3, n_requests=24)
    failing = [r.detail for r in out["results"] if not r.ok]
    assert out["all_ok"], failing
    # n_seeds x |families| x {fault, reference}
    assert out["n_ok"] == out["n_plans"] == 3 * len(QUALITY_FAMILIES) * 2
