"""Expert-parallel (mixture-of-denoisers) tests on the virtual 8-device CPU mesh.

The load-bearing assertion, in the repo's oracle style: the all_to_all-routed EP
path (one expert per device, static capacity, two collectives) must match the dense
single-device oracle (all experts on all rows, top-1 select) — losses, metrics,
gradients-after-one-step, and encode outputs — whenever capacity doesn't drop rows.
Capacity-overflow semantics (Switch-style drops) are tested separately.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.models import DAEConfig
from dae_rnn_news_recommendation_tpu.parallel import get_mesh
from dae_rnn_news_recommendation_tpu.parallel.ep import (
    capacity,
    make_moe_encode_fn,
    make_moe_train_step,
    moe_forward_dense,
    moe_init_params,
    moe_loss_and_metrics,
)
from dae_rnn_news_recommendation_tpu.train import make_optimizer

B, F, D, E = 64, 48, 8, 8


def _setup(strategy="none", corr_type="none"):
    cfg = DAEConfig(n_features=F, n_components=D, enc_act_func="tanh",
                    dec_act_func="none", loss_func="mean_squared",
                    corr_type=corr_type, corr_frac=0.3,
                    triplet_strategy=strategy, alpha=1.0,
                    matmul_precision="highest")
    params = moe_init_params(jax.random.PRNGKey(0), cfg, E)
    rng = np.random.default_rng(0)
    batch = {
        "x": jnp.asarray((rng.uniform(size=(B, F)) < 0.3).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 4, B), jnp.int32),
        "row_valid": jnp.ones(B, jnp.float32),
    }
    return cfg, params, batch


def test_dense_oracle_shapes_and_aux():
    """Dense path shapes; aux loss equals the NumPy Switch formula."""
    cfg, params, batch = _setup()
    h, y, routed, aux = moe_forward_dense(params, batch["x"], cfg)
    assert h.shape == (B, D) and y.shape == (B, F)
    assert np.all(np.asarray(routed) == 1.0)

    x = np.asarray(batch["x"])
    logits = x @ np.asarray(params["gate"])
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    e = p.argmax(-1)
    f = np.bincount(e, minlength=E) / B
    np.testing.assert_allclose(float(aux), E * float((f * p.mean(0)).sum()),
                               rtol=1e-5)


@pytest.mark.parametrize("strategy", ["none", "batch_all", "batch_hard"])
def test_routed_matches_dense_oracle(strategy):
    """EP train step over 8 devices == dense single-device oracle step when
    capacity is ample (capacity_factor = E guarantees zero drops)."""
    cfg, params, batch = _setup(strategy)
    optimizer = make_optimizer("ada_grad", 0.1)
    opt_state = optimizer.init(params)

    # dense oracle: plain jit step on the unsharded mixture
    def oracle_step(p, o, key, b):
        (cost, metrics), grads = jax.value_and_grad(
            moe_loss_and_metrics, has_aux=True)(p, b, key, cfg)
        updates, o = optimizer.update(grads, o, p)
        return jax.tree_util.tree_map(lambda a, u: a + u, p, updates), o, metrics

    p1, _, m1 = jax.jit(oracle_step)(params, opt_state, jax.random.PRNGKey(7),
                                     batch)

    mesh = get_mesh(E, axis_name="expert")
    step = make_moe_train_step(cfg, optimizer, mesh, capacity_factor=float(E),
                               donate=False)
    p8, _, m8 = step(params, opt_state, jax.random.PRNGKey(7), batch)

    assert float(m8["routed_fraction"]) == 1.0
    np.testing.assert_allclose(float(m8["cost"]), float(m1["cost"]), rtol=1e-5)
    np.testing.assert_allclose(float(m8["router_aux"]), float(m1["router_aux"]),
                               rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)


def test_routed_encode_matches_dense():
    cfg, params, batch = _setup()
    mesh = get_mesh(E, axis_name="expert")
    h_dense, r_dense = make_moe_encode_fn(cfg)(params, batch["x"])
    h_ep, r_ep = make_moe_encode_fn(cfg, mesh, capacity_factor=float(E))(
        params, batch["x"])
    assert np.all(np.asarray(r_dense) == 1.0) and np.all(np.asarray(r_ep) == 1.0)
    np.testing.assert_allclose(np.asarray(h_ep), np.asarray(h_dense),
                               rtol=1e-5, atol=1e-7)


def test_routed_encode_reports_drops():
    """Capacity-dropped rows must surface in the returned mask, and their codes
    must be exact zeros (never mistaken for real embeddings)."""
    cfg, params, batch = _setup()
    mesh = get_mesh(E, axis_name="expert")
    h, routed = make_moe_encode_fn(cfg, mesh, capacity_factor=0.25)(
        params, batch["x"])
    routed = np.asarray(routed)
    assert 0.0 < routed.mean() < 1.0
    np.testing.assert_array_equal(np.asarray(h)[routed == 0.0], 0.0)


def test_capacity_overflow_drops_rows():
    """With capacity_factor < 1 some rows must drop: routed_fraction < 1, the
    loss stays finite, and training still updates parameters."""
    cfg, params, batch = _setup()
    optimizer = make_optimizer("gradient_descent", 0.1)
    opt_state = optimizer.init(params)
    mesh = get_mesh(E, axis_name="expert")
    step = make_moe_train_step(cfg, optimizer, mesh, capacity_factor=0.25,
                               donate=False)
    p8, _, m8 = step(params, opt_state, jax.random.PRNGKey(3), batch)
    assert 0.0 < float(m8["routed_fraction"]) < 1.0
    assert np.isfinite(float(m8["cost"]))
    assert not np.allclose(np.asarray(p8["gate"]), np.asarray(params["gate"]))


@pytest.mark.parametrize("strategy", ["none", "batch_all"])
def test_padded_rows_never_route(strategy):
    """Padded rows (row_valid=0) must not consume dispatch capacity, must not
    enter the aux-loss routing stats, and the routed path must still equal the
    dense oracle on the real rows."""
    cfg, params, batch = _setup(strategy)
    batch = dict(batch, row_valid=jnp.asarray(
        (np.arange(B) < B - 24).astype(np.float32)))
    optimizer = make_optimizer("ada_grad", 0.1)
    opt_state = optimizer.init(params)

    def oracle_step(p, o, key, b):
        (cost, metrics), grads = jax.value_and_grad(
            moe_loss_and_metrics, has_aux=True)(p, b, key, cfg)
        updates, o = optimizer.update(grads, o, p)
        return jax.tree_util.tree_map(lambda a, u: a + u, p, updates), o, metrics

    p1, _, m1 = jax.jit(oracle_step)(params, opt_state, jax.random.PRNGKey(7),
                                     batch)
    mesh = get_mesh(E, axis_name="expert")
    step = make_moe_train_step(cfg, optimizer, mesh, capacity_factor=float(E),
                               donate=False)
    p8, _, m8 = step(params, opt_state, jax.random.PRNGKey(7), batch)

    # every REAL row routes; fraction is relative to real rows, not batch slots
    assert float(m1["routed_fraction"]) == 1.0
    assert float(m8["routed_fraction"]) == 1.0
    np.testing.assert_allclose(float(m8["cost"]), float(m1["cost"]), rtol=1e-5)
    np.testing.assert_allclose(float(m8["router_aux"]), float(m1["router_aux"]),
                               rtol=1e-5)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p8[k]), np.asarray(p1[k]),
                                   rtol=1e-4, atol=1e-6)


def test_padded_rows_cannot_evict_real_rows():
    """With capacity exactly fitting the real rows, adding padding must not
    displace any real row's dispatch slot (the -1-wraparound hazard)."""
    cfg, params, batch = _setup()
    valid = np.ones(B, np.float32)
    valid[::2] = 0.0  # padding interleaved BEFORE real rows in shard order
    batch = dict(batch, row_valid=jnp.asarray(valid))
    mesh = get_mesh(E, axis_name="expert")
    optimizer = make_optimizer("gradient_descent", 0.1)
    step = make_moe_train_step(cfg, optimizer, mesh, capacity_factor=float(E),
                               donate=False)
    _, _, m = step(params, optimizer.init(params), jax.random.PRNGKey(5), batch)
    assert float(m["routed_fraction"]) == 1.0  # all real rows kept


def test_gate_receives_gradient():
    """The router must train: scaling expert outputs by the top-1 probability
    routes gradient through the (otherwise non-differentiable) argmax."""
    cfg, params, batch = _setup()
    grads = jax.grad(lambda p: moe_loss_and_metrics(
        p, batch, jax.random.PRNGKey(0), cfg)[0])(params)
    assert float(jnp.abs(grads["gate"]).max()) > 0.0


def test_corruption_inside_moe_step():
    """Masking corruption composes with routing (per-shard keys, finite loss)."""
    cfg, params, batch = _setup(corr_type="masking")
    optimizer = make_optimizer("ada_grad", 0.1)
    opt_state = optimizer.init(params)
    mesh = get_mesh(E, axis_name="expert")
    step = make_moe_train_step(cfg, optimizer, mesh, capacity_factor=float(E),
                               donate=False)
    _, _, m = step(params, opt_state, jax.random.PRNGKey(11), batch)
    assert np.isfinite(float(m["cost"]))


def test_capacity_formula():
    assert capacity(8, 8, 2.0) == 2
    assert capacity(64, 8, 1.0) == 8
    assert capacity(3, 8, 1.0) == 1
