"""TB event writer: crc vectors, record framing, and round-trip through the real
TensorBoard event loader (gold reader) when the tensorboard package is present."""

import struct

import numpy as np
import pytest

from dae_rnn_news_recommendation_tpu.utils.tb_writer import (
    EventFileWriter, crc32c, masked_crc32c)


def test_crc32c_known_vectors():
    # RFC 3720 / Castagnoli reference vectors
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0x0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_record_framing_is_valid(tmp_path):
    w = EventFileWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, 1)
    w.add_histogram("weights", np.arange(100.0), 1)
    w.close()
    [path] = tmp_path.iterdir()
    blob = path.read_bytes()
    n_records = 0
    off = 0
    while off < len(blob):
        header = blob[off : off + 8]
        (length,) = struct.unpack("<Q", header)
        (len_crc,) = struct.unpack("<I", blob[off + 8 : off + 12])
        assert len_crc == masked_crc32c(header)
        payload = blob[off + 12 : off + 12 + length]
        (data_crc,) = struct.unpack("<I", blob[off + 12 + length : off + 16 + length])
        assert data_crc == masked_crc32c(payload)
        off += 16 + length
        n_records += 1
    assert off == len(blob)
    assert n_records == 3  # file_version + scalar + histogram


def test_roundtrip_through_tensorboard_reader(tmp_path):
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader")

    w = EventFileWriter(str(tmp_path))
    w.add_scalar("train/cost", 1.25, 7)
    w.add_scalar("train/cost", 0.75, 8)
    vals = np.concatenate([np.zeros(10), np.ones(30)])
    w.add_histogram("params/W", vals, 8)
    w.close()

    [path] = tmp_path.iterdir()
    events = list(loader_mod.LegacyEventFileLoader(str(path)).Load())
    assert events[0].file_version == "brain.Event:2"

    scalars = [(e.step, v.tag, v.simple_value)
               for e in events for v in e.summary.value
               if v.HasField("simple_value")]
    assert scalars == [(7, "train/cost", 1.25), (8, "train/cost", 0.75)]

    histos = [(e.step, v.tag, v.histo) for e in events for v in e.summary.value
              if v.HasField("histo")]
    assert len(histos) == 1
    step, tag, h = histos[0]
    assert (step, tag) == (8, "params/W")
    assert h.min == 0.0 and h.max == 1.0 and h.num == 40
    assert h.sum == 30.0 and h.sum_squares == 30.0
    assert sum(h.bucket) == 40


def test_metrics_writer_emits_tb_events(tmp_path):
    from dae_rnn_news_recommendation_tpu.utils import MetricsWriter

    with MetricsWriter(str(tmp_path)) as mw:
        mw.scalar("cost", 2.0, 0)
        mw.histogram("W", np.ones(5), 0)
    files = [p.name for p in tmp_path.iterdir()]
    assert "metrics.jsonl" in files
    assert any(f.startswith("events.out.tfevents.") for f in files)


# --------------------------------------------------- hardening regressions

def test_histogram_empty_and_nonfinite_do_not_raise(tmp_path):
    """A logging call must never kill training: empty and NaN/Inf inputs
    write well-framed records instead of raising (np.histogram raises on
    both without the guard)."""
    w = EventFileWriter(str(tmp_path))
    w.add_histogram("empty", np.array([]), 1)
    w.add_histogram("all_nan", np.full(4, np.nan), 2)
    w.add_histogram("mixed", np.array([1.0, np.inf, 2.0, np.nan]), 3)
    w.close()
    [path] = tmp_path.iterdir()
    blob = path.read_bytes()
    off = n_records = 0
    while off < len(blob):  # every record still frames + checksums cleanly
        (length,) = struct.unpack("<Q", blob[off : off + 8])
        payload = blob[off + 12 : off + 12 + length]
        (data_crc,) = struct.unpack(
            "<I", blob[off + 12 + length : off + 16 + length])
        assert data_crc == masked_crc32c(payload)
        off += 16 + length
        n_records += 1
    assert n_records == 4  # file_version + the three histograms


def test_mixed_nonfinite_histogram_keeps_finite_stats(tmp_path):
    loader_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_file_loader")

    w = EventFileWriter(str(tmp_path))
    w.add_histogram("mixed", np.array([1.0, np.inf, 3.0, np.nan]), 1)
    w.close()
    [path] = tmp_path.iterdir()
    events = list(loader_mod.LegacyEventFileLoader(str(path)).Load())
    [h] = [v.histo for e in events for v in e.summary.value
           if v.HasField("histo")]
    assert h.min == 1.0 and h.max == 3.0 and h.num == 2  # non-finite dropped


def test_add_scalar_unconvertible_value_is_dropped(tmp_path):
    w = EventFileWriter(str(tmp_path))
    w.add_scalar("bad", None, 1)
    w.add_scalar("bad", "not-a-number", 2)
    w.add_scalar("good", 1.5, 3)
    w.close()
    [path] = tmp_path.iterdir()
    blob = path.read_bytes()
    off = n_records = 0
    while off < len(blob):
        (length,) = struct.unpack("<Q", blob[off : off + 8])
        off += 16 + length
        n_records += 1
    assert n_records == 2  # file_version + the one good scalar


def test_metrics_writer_histogram_hardening_and_idempotent_close(tmp_path):
    import json

    from dae_rnn_news_recommendation_tpu.utils import MetricsWriter

    mw = MetricsWriter(str(tmp_path))
    mw.histogram("empty", np.array([]), 1)
    mw.histogram("mixed", np.array([1.0, np.nan, 3.0]), 2)
    mw.flush()
    records = [json.loads(line) for line in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    empty, mixed = records[0]["hist"], records[1]["hist"]
    assert empty["n"] == 0 and empty["min"] is None
    assert mixed["n"] == 2 and mixed["n_nonfinite"] == 1
    assert mixed["min"] == 1.0 and mixed["max"] == 3.0
    mw.close()
    mw.close()  # idempotent: the fit paths close in finally + explicitly


def test_scalar_nonfinite_recorded_deterministically(tmp_path):
    """A NaN'd loss must be diagnosable from the logs: the raw value lands in
    metrics.jsonl (json emits NaN/Infinity tokens json.loads round-trips),
    the TB sink is skipped (its renderers choke on NaN points), and
    nonfinite_scalar_count says how many were seen."""
    import json
    import math

    from dae_rnn_news_recommendation_tpu.utils import MetricsWriter

    mw = MetricsWriter(str(tmp_path))
    tb_calls = []

    class StubTB:
        def add_scalar(self, tag, value, step):
            tb_calls.append((tag, value, step))

        def close(self):
            pass

    mw._tb = StubTB()
    mw.scalar("cost", 1.5, 1)
    mw.scalars({"cost": float("nan"), "health/grad_norm": float("inf")}, 2)
    mw.scalar("cost", 2.5, 3)
    mw.close()
    assert mw.nonfinite_scalar_count == 2

    records = [json.loads(line) for line in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert ("cost", 1.5, 1) in [(r["tag"], r["value"], r["step"])
                                for r in records]
    [nan_rec] = [r for r in records if r["tag"] == "cost" and r["step"] == 2]
    assert math.isnan(nan_rec["value"])
    [inf_rec] = [r for r in records if r["tag"] == "health/grad_norm"]
    assert math.isinf(inf_rec["value"])
    # the TB sink saw only the finite points
    assert tb_calls == [("cost", 1.5, 1), ("cost", 2.5, 3)]
