"""Continuous-churn contracts (ISSUE 10 tentpole): frozen-vocab incremental
vectorization, in-graph drift metrics, versioned incremental swaps with
age-based eviction, and the ChurnSupervisor's drift-gated refresh loop —
a drift trip must BLOCK the incremental swap and trigger
fine-tune-then-rebuild, never serve stale embeddings.

End-to-end crash/recovery lives in tests/test_chaos_churn.py; this file is
the component bar.
"""

import numpy as np
import pytest
import scipy.sparse as sp
from sklearn.feature_extraction.text import CountVectorizer

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.data import IncrementalVectorizer
from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.refresh import (ChurnConfig,
                                                     ChurnSupervisor,
                                                     DriftTripped)
from dae_rnn_news_recommendation_tpu.reliability import faults
from dae_rnn_news_recommendation_tpu.serve import ServingCorpus, SwapRejected
from dae_rnn_news_recommendation_tpu.telemetry import drift_health

N, F, D = 48, 24, 8


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((N, F), dtype=np.float32)
    return config, params, articles


def make_supervisor(config, params, articles, *, block=16, **churn_kw):
    corpus = ServingCorpus(config, block=block)
    churn_kw.setdefault("microbatch", 16)
    sup = ChurnSupervisor(params, config, corpus,
                          churn=ChurnConfig(**churn_kw))
    sup.bootstrap(articles)
    return sup


def batch(seed, rows=12):
    return np.random.default_rng(seed).random((rows, F), dtype=np.float32)


# ------------------------------------------------------- incremental vectorizer

DOCS = ["the cat sat on the mat", "dog bites man near the market",
        "market rally lifts tech stocks", "cat and dog adoption rates rise"]


def test_frozen_vocab_matches_fitted_transform_exactly():
    cv = CountVectorizer()
    X_ref = cv.fit_transform(DOCS)
    iv = IncrementalVectorizer.from_fitted(cv)
    X = iv.transform(DOCS)
    assert X.dtype == np.float32 and X.shape == X_ref.shape
    np.testing.assert_array_equal(X.toarray(), X_ref.toarray())
    assert iv.oov_fraction == 0.0


def test_oov_terms_hash_stably_never_refit():
    cv = CountVectorizer()
    cv.fit(DOCS)
    vocab_before = dict(cv.vocabulary_)
    iv = IncrementalVectorizer.from_fitted(cv)
    oov_doc = ["blockchain zeitgeist cat"]
    a = iv.transform(oov_doc)
    b = IncrementalVectorizer.from_fitted(cv).transform(oov_doc)
    # replay determinism: a fresh instance (fresh process in the chaos story)
    # produces the byte-identical matrix — crc32, not PYTHONHASHSEED
    np.testing.assert_array_equal(a.toarray(), b.toarray())
    assert iv.vocabulary == vocab_before  # frozen: OOV never grew the vocab
    assert 0.0 < iv.oov_fraction < 1.0    # 2 of 3 tokens hashed
    assert iv.stats()["n_oov"] == 2


def test_oov_buckets_confine_hash_collisions_to_tail():
    vocab = {f"t{i:02d}": i for i in range(20)}
    iv = IncrementalVectorizer(vocab, n_features=F, oov_buckets=4)
    X = iv.transform(["t01 t05 zebra quux flarp"])
    oov_cols = X.nonzero()[1][X.nonzero()[1] >= 20]
    assert len(oov_cols) > 0 and all(20 <= c < F for c in oov_cols)
    in_vocab = set(X.nonzero()[1]) - set(oov_cols)
    assert in_vocab == {1, 5}


# ------------------------------------------------------------- drift metrics

def test_drift_health_zero_for_identical_distribution():
    rng = np.random.default_rng(0)
    h = rng.normal(size=(32, D)).astype(np.float32)
    u = h / np.linalg.norm(h, axis=1, keepdims=True)
    ref_centroid = u.mean(axis=0)
    rep = jax.device_get(drift_health(jnp.asarray(h),
                                      jnp.asarray(ref_centroid),
                                      jnp.float32(0.0)))
    assert float(rep["health/drift_centroid_shift"]) < 1e-5
    assert float(rep["health/drift_collapse_delta"]) == pytest.approx(
        abs(float(rep["health/drift_collapse"])), abs=1e-6)


def test_drift_health_flags_flipped_embeddings_and_padding_is_exact():
    rng = np.random.default_rng(1)
    h = rng.normal(size=(24, D)).astype(np.float32)
    u = h / np.linalg.norm(h, axis=1, keepdims=True)
    ref = u.mean(axis=0)
    flipped = jax.device_get(drift_health(jnp.asarray(-h), jnp.asarray(ref),
                                          jnp.float32(0.0)))
    assert float(flipped["health/drift_centroid_shift"]) > 1.9  # cos = -1
    # masked padding must not perturb the metrics
    padded = np.zeros((32, D), np.float32)
    padded[:24] = h
    valid = np.zeros(32, np.float32)
    valid[:24] = 1.0
    a = jax.device_get(drift_health(jnp.asarray(h), jnp.asarray(ref),
                                    jnp.float32(0.0)))
    b = jax.device_get(drift_health(jnp.asarray(padded), jnp.asarray(ref),
                                    jnp.float32(0.0),
                                    row_valid=jnp.asarray(valid)))
    assert float(a["health/drift_centroid_shift"]) == pytest.approx(
        float(b["health/drift_centroid_shift"]), abs=1e-6)
    assert float(a["health/drift_collapse"]) == pytest.approx(
        float(b["health/drift_collapse"]), abs=1e-6)


# --------------------------------------------------------- incremental swap

def test_incremental_swap_appends_and_versions_monotonically(setup):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles)
    v0 = sup.corpus.version
    for i in range(3):
        rep = sup.ingest(batch(100 + i))
        assert rep["action"] == "incremental"
        assert rep["version"] == v0 + 1 + i
        assert rep["gate"]["ok"] and rep["gate"]["tail"]
    assert sup.corpus.active.n == N + 3 * 12
    assert sup.resident_rows() == N + 3 * 12
    led = sup.corpus.ledger
    assert [r["version"] for r in led if r["ok"]] == [1, 2, 3, 4]
    assert [r["kind"] for r in led] == ["full"] + ["incremental"] * 3


def test_max_rows_evicts_oldest_first(setup):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles, max_rows=45)
    rep = sup.ingest(batch(200))
    # 48 resident + 12 new > 45: keep budget 33 -> evict the 15 oldest
    assert rep["n_evicted"] == 15
    assert sup.corpus.active.n == 45
    assert sup.resident_rows() == 45  # host mirror trimmed in lockstep


def test_max_age_versions_expires_old_news(setup):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles, max_age_versions=1)
    r1 = sup.ingest(batch(300))   # v2: v1 rows age exactly 1, still kept
    assert r1["n_evicted"] == 0
    assert sup.corpus.active.n == N + 12
    r2 = sup.ingest(batch(301))   # v3: v1 rows age 2 > 1, expired
    assert r2["n_evicted"] == N
    assert sup.corpus.active.n == 24
    assert sup.resident_rows() == 24


def test_incremental_swap_requires_a_bootstrapped_corpus(setup):
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)
    with pytest.raises(SwapRejected):
        corpus.swap_incremental(params, articles[:8], note="no base")


def test_injected_swap_fault_rolls_back_and_replay_converges(setup):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles)
    v0 = sup.corpus.version
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("refresh.swap", 1, "fatal"),))
    with faults.install(faults.FaultInjector(plan)) as injector:
        rep = sup.ingest(batch(400))
        assert rep["action"] == "rollback"
        assert sup.corpus.version == v0
        assert sup.resident_rows() == N       # mirror untouched on rollback
        assert injector.fired
        # the replayed cycle reconverges (the spec is consumed)
        rep2 = sup.ingest(batch(400))
    assert rep2["action"] == "incremental" and rep2["version"] == v0 + 1
    assert sup.corpus.active.n == N + 12
    led = sup.corpus.ledger
    assert [r["ok"] for r in led] == [True, False, True]


def test_transient_encode_fault_is_absorbed_by_retry(setup):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles)
    plan = faults.FaultPlan(seed=0, specs=(
        faults.FaultSpec("refresh.encode", 1, "transient"),))
    with faults.install(faults.FaultInjector(plan)) as injector:
        rep = sup.ingest(batch(500))
    assert rep["action"] == "incremental"     # the blip never surfaced
    assert injector.fired and injector.retries  # ...but was never silent


# ----------------------------------------------------------------- drift gate

def test_drift_trip_blocks_swap_and_triggers_finetune(setup):
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)
    calls = []

    def finetune_fn(train):
        calls.append(int(train.shape[0]))
        return params

    sup = ChurnSupervisor(
        params, config, corpus,
        churn=ChurnConfig(microbatch=16, drift_centroid_max=-1.0),
        finetune_fn=finetune_fn)  # ceiling below zero: every cycle trips
    sup.bootstrap(articles)
    v0 = corpus.version
    rep = sup.ingest(batch(600))
    assert rep["action"] == "finetune_rebuild"
    assert sup.drift_trips and sup.drift_trips[0]["tripped"]
    # the fine-tune saw resident rows + the triggering batch, and the corpus
    # was FULL-rebuilt (never an incremental append of drifted embeddings)
    assert calls == [N + 12]
    assert corpus.version == v0 + 1
    assert corpus.ledger[-1]["kind"] == "full"
    assert all(r["kind"] != "incremental" for r in corpus.ledger)
    assert len(sup.finetunes) == 1


def test_drift_trip_without_finetune_path_raises(setup):
    config, params, articles = setup
    corpus = ServingCorpus(config, block=16)
    sup = ChurnSupervisor(params, config, corpus,
                          churn=ChurnConfig(microbatch=16,
                                            drift_collapse_max=-1.0))
    sup.bootstrap(articles)
    v0 = corpus.version
    with pytest.raises(DriftTripped):
        sup.ingest(batch(700))
    assert corpus.version == v0  # nothing swapped


# -------------------------------------------------------- telemetry surface

def test_dump_history_roundtrips_into_the_report(setup, tmp_path):
    config, params, articles = setup
    sup = make_supervisor(config, params, articles)
    for i in range(3):
        sup.ingest(batch(800 + i))
    path = sup.dump_history(str(tmp_path / "churn_history.json"))
    assert not (tmp_path / "churn_history.json.tmp").exists()  # atomic

    from dae_rnn_news_recommendation_tpu.telemetry.report import (
        churn_summary, load_churn, render_text)
    dump = load_churn(path)
    summary = churn_summary(dump)
    assert summary["n_cycles"] == 3
    assert summary["actions"] == {"incremental": 3}
    assert summary["drift_trips"] == 0
    assert summary["version_span"] == [2, 4]  # bootstrap is v1
    assert summary["swap_p95_ms"] >= summary["swap_p50_ms"] > 0
    assert summary["encode_articles_per_sec"] > 0
    assert summary["resident_rows"] == N + 3 * 12
    assert summary["corpus_version"] == 4
    assert summary["finetunes"] == 0 and summary["retries"] == 0

    text = render_text([], churn=summary)
    assert "corpus churn: 3 cycles, 0 drift trips, versions v2..v4" in text
    assert "incremental x3" in text and "swap latency:" in text


def test_load_churn_accepts_bare_history_and_rejects_garbage(tmp_path):
    import json as _json
    from dae_rnn_news_recommendation_tpu.telemetry.report import (
        churn_summary, load_churn)
    bare = tmp_path / "bare.json"
    bare.write_text(_json.dumps([{"cycle": 1, "action": "incremental",
                                  "version": 2}]))
    dump = load_churn(str(bare))
    assert churn_summary(dump)["n_cycles"] == 1
    bad = tmp_path / "bad.json"
    bad.write_text(_json.dumps({"history": "nope"}))
    with pytest.raises(ValueError):
        load_churn(str(bad))


# ------------------------------------------------------------- text end-to-end

def test_supervisor_ingests_raw_text_through_frozen_vocab(setup):
    config, params, articles = setup
    vocab = {f"t{i:02d}": i for i in range(F)}
    iv = IncrementalVectorizer(vocab, n_features=F)
    corpus = ServingCorpus(config, block=16)
    # text counts live on a different scale than the random bootstrap, so
    # open the drift ceilings wide — this test is about the vectorizer path
    sup = ChurnSupervisor(params, config, corpus,
                          churn=ChurnConfig(microbatch=16,
                                            drift_centroid_max=2.5,
                                            drift_collapse_max=2.0),
                          vectorizer=iv)
    sup.bootstrap(sp.csr_matrix(articles))
    texts = [f"t{i % F:02d} t{(i + 3) % F:02d} neologism{i}"
             for i in range(12)]
    rep = sup.ingest(texts)
    assert rep["action"] == "incremental" and rep["n_new"] == 12
    assert rep["oov_fraction"] == pytest.approx(1 / 3, abs=1e-6)
    assert sup.corpus.active.n == N + 12
