"""MoEDenoisingAutoencoder estimator: the mixture-of-denoisers through the
sklearn-style surface — fit/transform/checkpoint-resume on a single device, the
expert-parallel 8-device mesh path, sparse-ingest feeds, and the CLI dispatch."""

import numpy as np
import pytest
import scipy.sparse as sp
import jax

from dae_rnn_news_recommendation_tpu.models import MoEDenoisingAutoencoder

B, F, E = 96, 64, 8


def _corpus(seed=0):
    rng = np.random.default_rng(seed)
    x = (rng.uniform(size=(B, F)) < 0.2).astype(np.float32)
    labels = rng.integers(0, 4, B).astype(np.int32)
    return x, labels


def _model(tmp_path, **kw):
    kw.setdefault("n_experts", 4)
    kw.setdefault("model_name", "moe_t")
    kw.setdefault("num_epochs", 3)
    kw.setdefault("batch_size", 32)
    kw.setdefault("n_components", 8)
    kw.setdefault("enc_act_func", "tanh")
    kw.setdefault("dec_act_func", "none")
    kw.setdefault("loss_func", "mean_squared")
    kw.setdefault("opt", "ada_grad")
    kw.setdefault("learning_rate", 0.1)
    kw.setdefault("triplet_strategy", "none")
    kw.setdefault("corr_type", "masking")
    kw.setdefault("corr_frac", 0.3)
    kw.setdefault("seed", 0)
    kw.setdefault("verbose", False)
    kw.setdefault("use_tensorboard", False)
    kw.setdefault("results_root", str(tmp_path))
    return MoEDenoisingAutoencoder(**kw)


def test_fit_transform_single_device(tmp_path):
    x, labels = _corpus()
    m = _model(tmp_path, triplet_strategy="batch_all")
    m.fit(x, train_set_label=labels)
    h = m.transform(x, from_checkpoint=True)
    assert h.shape == (B, 8)
    assert np.isfinite(h).all()
    # routing must not have collapsed the codes to a constant
    assert float(np.std(h)) > 0.0


def test_fit_reduces_cost(tmp_path):
    """The full-batch mixture objective must drop from init to trained params
    (train_cost_batch only retains the LAST epoch, so compare the loss itself)."""
    from dae_rnn_news_recommendation_tpu.parallel.ep import (
        moe_init_params, moe_loss_and_metrics)
    import jax.numpy as jnp

    x, labels = _corpus()
    m = _model(tmp_path, num_epochs=8, verbose_step=100, corr_type="none")
    m.fit(x, train_set_label=labels)
    assert np.isfinite(m.train_cost_batch[0]).all()

    batch = {"x": jnp.asarray(x), "labels": jnp.asarray(labels),
             "row_valid": jnp.ones(B, jnp.float32)}
    key = jax.random.PRNGKey(0)
    init = moe_init_params(key, m.config, m.n_experts)
    cost0 = float(moe_loss_and_metrics(init, batch, key, m.config)[0])
    cost1 = float(moe_loss_and_metrics(m.params, batch, key, m.config)[0])
    assert cost1 < cost0


def test_checkpoint_resume(tmp_path):
    x, labels = _corpus()
    m = _model(tmp_path)
    m.fit(x, train_set_label=labels)
    m2 = _model(tmp_path, num_epochs=2)
    m2.fit(x, train_set_label=labels, restore_previous_model=True)
    assert m2._epoch0 == 3  # resumed from the first run's final epoch
    h = m2.transform(x)
    assert h.shape == (B, 8)


def test_sparse_feed(tmp_path):
    x, labels = _corpus()
    m = _model(tmp_path)
    m.fit(sp.csr_matrix(x), train_set_label=labels)
    h_sparse = m.transform(sp.csr_matrix(x))
    h_dense = m.transform(x)
    np.testing.assert_allclose(h_sparse, h_dense, rtol=1e-5, atol=1e-6)


def test_expert_parallel_mesh(tmp_path):
    """n_devices == n_experts == 8: the estimator routes training through the
    all_to_all EP step; validation and transform stay on the exact dense path."""
    x, labels = _corpus()
    vx, vlabels = _corpus(seed=1)
    m = _model(tmp_path, n_experts=E, n_devices=E, capacity_factor=float(E),
               triplet_strategy="batch_all", verbose_step=1)
    m.fit(x, train_set_label=labels, validation_set=vx,
          validation_set_label=vlabels)
    h = m.transform(x)
    assert h.shape == (B, 8) and np.isfinite(h).all()


def test_triplet_driver_rejects_n_experts(tmp_path, monkeypatch):
    """The precomputed-triplet driver has no MoE variant: the flag must fail
    loudly there, never silently train a plain triplet DAE."""
    monkeypatch.chdir(tmp_path)  # keep any .env out of the parse
    from dae_rnn_news_recommendation_tpu.utils.config import parse_flags

    with pytest.raises(AssertionError, match="MoE"):
        parse_flags(["--model_name", "t", "--n_experts", "2"],
                    triplet_mode=True)


def test_mesh_expert_count_mismatch(tmp_path):
    with pytest.raises(AssertionError, match="one expert per device"):
        m = _model(tmp_path, n_experts=4, n_devices=8)
        m.fit(*_corpus()[:1])


def test_get_model_parameters_shapes(tmp_path):
    x, labels = _corpus()
    m = _model(tmp_path)
    m.fit(x, train_set_label=labels)
    p = m.get_model_parameters()
    assert p["gate"].shape == (F, 4)
    assert p["enc_w"].shape == (4, F, 8)
    assert p["enc_b"].shape == (4, 8)
    assert p["dec_b"].shape == (4, F)


def test_load_model_roundtrip(tmp_path):
    x, labels = _corpus()
    m = _model(tmp_path)
    m.fit(x, train_set_label=labels)
    h1 = m.transform(x)
    m2 = _model(tmp_path)
    m2.load_model((F, 8), m.model_path)
    h2 = m2.transform(x, from_checkpoint=False)
    np.testing.assert_allclose(h2, h1, rtol=1e-6)


def test_cli_dispatch(tmp_path, monkeypatch):
    """--n_experts 2 selects the MoE estimator end to end through the driver."""
    monkeypatch.chdir(tmp_path)
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    main(["--model_name", "moe_cli", "--synthetic", "--train_row", "80",
          "--validate_row", "20", "--max_features", "50", "--num_epochs", "2",
          "--n_experts", "2", "--compress_factor", "10", "--batch_size", "0.5",
          "--synthetic_vocab", "60", "--eval_reps", "encoded"])
    out = tmp_path / "results" / "moe_dae" / "moe_cli"
    assert (out / "models").exists()
    assert any((out / "models").iterdir())  # a checkpoint landed
