"""IO round-trip tests over the (type x format) matrix, modeled on reference
tests/test_helpers.py:8-61."""

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from dae_rnn_news_recommendation_tpu.data import read_file, save_file


@pytest.fixture
def arr():
    return np.random.default_rng(0).uniform(size=(6, 4))


@pytest.mark.parametrize("fmt", ["csv", "tsv", "npy"])
def test_numpy_roundtrip(arr, fmt, tmp_path):
    path = tmp_path / f"a.{fmt}"
    save_file(arr, path)
    back = read_file(path, data_type="numpy")
    np.testing.assert_allclose(back, arr, rtol=1e-6)


@pytest.mark.parametrize("fmt", ["csv", "tsv", "npz"])
def test_scipy_roundtrip(arr, fmt, tmp_path):
    m = sp.csr_matrix(np.where(arr > 0.5, arr, 0))
    path = tmp_path / f"s.{fmt}"
    save_file(m, path)
    back = read_file(path, data_type="scipy")
    assert sp.issparse(back)
    np.testing.assert_allclose(back.toarray(), m.toarray(), rtol=1e-6)


@pytest.mark.parametrize("fmt", ["csv", "tsv", "parquet", "pkl"])
def test_dataframe_roundtrip(arr, fmt, tmp_path):
    df = pd.DataFrame(arr, columns=[f"c{i}" for i in range(arr.shape[1])])
    path = tmp_path / f"d.{fmt}"
    save_file(df, path)
    back = read_file(path, data_type="pandas_df")
    np.testing.assert_allclose(back.values, df.values, rtol=1e-6)


@pytest.mark.parametrize("fmt", ["csv", "tsv", "pkl"])
def test_series_roundtrip(arr, fmt, tmp_path):
    s = pd.Series(arr[:, 0])
    path = tmp_path / f"x.{fmt}"
    save_file(s, path)
    back = read_file(path, data_type="pandas_series")
    np.testing.assert_allclose(np.asarray(back), s.values, rtol=1e-6)


def test_format_autodetect(tmp_path, arr):
    save_file(arr, tmp_path / "a.npy")
    assert isinstance(read_file(tmp_path / "a.npy"), np.ndarray)
    m = sp.csr_matrix(arr)
    save_file(m, tmp_path / "m.npz")
    assert sp.issparse(read_file(tmp_path / "m.npz"))


def test_unsupported_combo_raises(tmp_path, arr):
    with pytest.raises(AssertionError):
        save_file(arr, tmp_path / "a.parquet")
    with pytest.raises(AssertionError):
        read_file(tmp_path / "nope.csv")
