"""End-to-end user-embedding pipeline driver (cli/main_user_model.py — the paper's
second half, net-new vs the reference) + stacked-DAE fine-tuning."""

import json
import os

import numpy as np
import pytest

from dae_rnn_news_recommendation_tpu.cli.main_user_model import simulate_sessions


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_simulate_sessions_structure(rng):
    categories = rng.integers(0, 4, 200)
    s = simulate_sessions(categories, n_users=30, seq_len=6, rng=rng,
                          p_interest=1.0)
    assert s["browse"].shape == (30, 6) and s["pos"].shape == (30, 6)
    # p_interest=1: every browsed and clicked article is in the interest category;
    # every negative is outside it
    for u in range(30):
        c = s["interest"][u]
        assert (categories[s["browse"][u]] == c).all()
        assert (categories[s["pos"][u]] == c).all()
        assert (categories[s["neg"][u]] != c).all()


def test_user_model_pipeline_end_to_end(workdir):
    from dae_rnn_news_recommendation_tpu.cli.main_user_model import main

    gru, metrics = main([
        # max_features must cover the category vocabulary: the synthetic
        # corpus spreads its 8 category slices across a 3000-word Zipf vocab,
        # so a top-400 document-frequency cut keeps mostly base words and the
        # ranking task degenerates to chance
        "--model_name", "t", "--n_articles", "500", "--max_features", "2000",
        "--n_components", "32", "--dae_epochs", "2", "--n_users", "200",
        "--seq_len", "8", "--gru_epochs", "25", "--seq_devices", "4",
        "--seed", "0",
    ])
    # ranking the clicked article above the non-clicked one must beat chance
    assert metrics["rank_accuracy"] > 0.55
    # 8 categories -> chance 0.125; tiny config, so assert above-chance with margin
    assert metrics["category_top1_accuracy"] >= 0.15
    # artifacts
    d = "results/gru_user/t/"
    assert os.path.isfile(d + "models/gru_user_params.npz")
    assert os.path.isfile(d + "data/article_embeddings.npy")
    with open(d + "logs/user_model_metrics.json") as f:
        assert json.load(f)["rank_accuracy"] == metrics["rank_accuracy"]


def test_user_model_pipeline_stacked_embeddings(workdir):
    """--stacked_layers swaps the single-layer DAE for the greedy-pretrained
    (+fine-tuned) stack; the last layer size becomes the embedding dim."""
    from dae_rnn_news_recommendation_tpu.cli.main_user_model import main

    gru, metrics = main([
        "--model_name", "st", "--n_articles", "300", "--max_features", "400",
        "--dae_epochs", "2", "--n_users", "60", "--seq_len", "6",
        "--gru_epochs", "8", "--stacked_layers", "64,16",
        "--finetune_epochs", "1", "--seed", "0",
    ])
    assert metrics["d_embed"] == 16
    assert 0.0 <= metrics["rank_accuracy"] <= 1.0
    assert os.path.isfile("results/gru_user/st/data/article_embeddings.npy")
    emb = np.load("results/gru_user/st/data/article_embeddings.npy")
    assert emb.shape == (300, 16)


def test_stacked_finetune_improves_reconstruction(rng):
    import jax.numpy as jnp

    from dae_rnn_news_recommendation_tpu.models.stacked import (
        StackedDenoisingAutoencoder)

    X = (rng.uniform(size=(128, 30)) < 0.15).astype(np.float32)
    sdae = StackedDenoisingAutoencoder([16, 8], num_epochs=3, batch_size=32,
                                       learning_rate=0.3, seed=0)
    sdae.fit(X)

    def recon_mse(model):
        _, y = model._stack_forward(model.params, jnp.asarray(X))
        return float(np.mean((np.asarray(y) - X) ** 2))

    before = recon_mse(sdae)
    sdae.fit_finetune(X, num_epochs=15, learning_rate=0.05)
    after = recon_mse(sdae)
    assert after < before
    # the stack still encodes (params stayed structurally intact)
    codes = sdae.encode(X)
    assert codes.shape == (128, 8) and np.isfinite(codes).all()


def test_stacked_finetune_requires_fit(rng):
    from dae_rnn_news_recommendation_tpu.models.stacked import (
        StackedDenoisingAutoencoder)

    with pytest.raises(AssertionError, match="fit"):
        StackedDenoisingAutoencoder([8]).fit_finetune(np.ones((4, 6), np.float32))
