"""Device-time profiling (ISSUE 18): the fenced `devprof.measure` timer, the
ProfileDB persistence contract, `report --profile`, and the always-on
instrumentation's disabled-cost contract.

Contracts pinned here:
  * measure() — fenced best-of-N with per-iteration compile accounting: the
    fresh executable's compile lands in warmup, timed iterations stay clean
    (n_clean == n), best <= median, and the key coordinates default to the
    largest array leaf's signature;
  * ProfileDB — rows keyed by (op, shape, dtype, device_kind) round-trip
    through the JSON file; the tmp+os.replace rewrite means a concurrent
    reader always parses a COMPLETE document; a malformed file raises
    instead of being silently treated as empty and clobbered;
  * report --profile — an explicit path renders the top-N device-time
    table; a bare --profile with no DB next to the trace is a note + exit 0
    (pass-by-absence, the --fleet contract); with no flag at all a
    `profile_db.json` next to the trace is auto-detected;
  * instrument() disabled — ZERO host syncs (devprof.device_fence is never
    reached) and zero extra compiles across N calls (compile_guard): the
    regression-test half of the profile_overhead_lt_1pct evidence gate.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.analysis.runtime import compile_guard
from dae_rnn_news_recommendation_tpu.telemetry import ProfileDB, devprof
from dae_rnn_news_recommendation_tpu.telemetry.__main__ import main as cli_main
from dae_rnn_news_recommendation_tpu.telemetry.profile_db import row_key

# ------------------------------------------------------------------ measure


def test_measure_is_fenced_best_of_n_with_compile_provenance():
    f = jax.jit(lambda x: (x * 2.0).sum())
    x = jnp.arange(512, dtype=jnp.float32).reshape(8, 64)
    res = devprof.measure(f, (x,), n=4, warmup=1, op="t/sum")
    assert res.op == "t/sum"
    assert res.shape == "8x64" and res.dtype == "float32"
    assert res.n == 4 and len(res.times_ms) == 4
    assert res.compiles_warmup >= 1  # the fresh executable compiled in warmup
    assert res.compiles_timed == 0 and res.n_clean == 4
    assert 0.0 < res.best_ms <= res.median_ms


def test_measure_records_and_round_trips_through_profile_db(tmp_path):
    path = str(tmp_path / "profile_db.json")
    db = ProfileDB(path)
    f = jax.jit(lambda x: x @ x.T)
    x = jnp.ones((16, 32), jnp.float32)
    res = devprof.measure(f, (x,), n=3, warmup=1, op="t/matmul", db=db)
    fresh = ProfileDB(path)  # a separate reader, straight from disk
    row = fresh.get("t/matmul", "16x32", "float32", res.device_kind)
    assert row is not None
    assert row["best_ms"] == pytest.approx(res.best_ms, abs=1e-6)
    assert row["n"] == 3 and row["warmup"] == 1
    # rows carry their key fields inline — consumers never parse key strings
    assert [row[k] for k in ("op", "shape", "dtype")] == [
        "t/matmul", "16x32", "float32"]


# ---------------------------------------------------------------- ProfileDB


def test_row_key_and_record_validation(tmp_path):
    assert row_key("op/a", (4, 8), "float32", "cpu") == "op/a|4x8|float32|cpu"
    db = ProfileDB(str(tmp_path / "db.json"))
    with pytest.raises(ValueError, match="missing key fields"):
        db.record({"op": "x", "shape": "4", "dtype": "f32"})  # no device_kind
    db.record({"op": "x", "shape": (4,), "dtype": "f32",
               "device_kind": "cpu", "best_ms": 1.0})
    assert "x|4|f32|cpu" in db and len(db) == 1


def test_malformed_db_raises_not_clobbers(tmp_path):
    p = tmp_path / "profile_db.json"
    p.write_text('{"rows": []}')  # wrong shape: rows must be a dict
    with pytest.raises(ValueError, match="not a profile DB"):
        ProfileDB(str(p))
    assert p.read_text() == '{"rows": []}'  # failed load must not rewrite


def test_atomic_rewrite_under_concurrent_reader(tmp_path):
    """tmp + os.replace: a reader racing 200 rewrites must always parse a
    complete document — either generation, never a torn write."""
    path = str(tmp_path / "profile_db.json")
    db = ProfileDB(path)
    db.record({"op": "k0", "shape": "1", "dtype": "f32",
               "device_kind": "cpu", "best_ms": 0.5})
    db.save()
    n_seen, failures = [], []

    def reader():
        for _ in range(400):
            try:
                n_seen.append(len(ProfileDB(path)))
            except ValueError as e:  # a torn write would parse-error here
                failures.append(repr(e))
                return

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i in range(200):
            db.record({"op": f"k{i % 7}", "shape": "1", "dtype": "f32",
                       "device_kind": "cpu", "best_ms": 0.5 + i})
            db.save()
    finally:
        t.join(timeout=60)
    assert failures == []
    assert n_seen and all(n >= 1 for n in n_seen)
    assert len(ProfileDB(path)) == 7  # k0..k6, last write per key wins


# ----------------------------------------------------------- report --profile


def _trace_with_one_span(tmp_path):
    trace = tmp_path / "trace.json"
    trace.write_text(
        '{"traceEvents": [{"name": "fit/epoch", "ph": "X", "ts": 0,'
        ' "dur": 1000, "pid": 1, "tid": 1}]}')
    return trace


def _sample_row(**over):
    row = {"op": "ops/topk_fused_k10", "shape": "8x512", "dtype": "float32",
           "device_kind": "TPU v5 lite", "best_ms": 0.25, "median_ms": 0.3,
           "n": 5, "n_clean": 5, "warmup": 2, "compiles_warmup": 1,
           "compiles_timed": 0, "times_ms": [0.25, 0.3, 0.31],
           "flops": 1.2e9, "bytes_accessed": 3.4e6, "mfu": 0.02,
           "bw_fraction": 0.41, "roofline_fraction": 0.41, "bound": "memory"}
    row.update(over)
    return row


def test_report_cli_profile_flag_renders_table(tmp_path, capsys):
    trace = _trace_with_one_span(tmp_path)
    db = ProfileDB(str(tmp_path / "pdb.json"))
    db.record(_sample_row())
    db.record(_sample_row(op="train/step", shape="256x10000",
                          dtype="bfloat16", best_ms=12.5, median_ms=13.0))
    db.save()
    rc = cli_main(["report", str(trace), "--profile",
                   str(tmp_path / "pdb.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device-time profile: 2 rows" in out
    assert "TPU v5 lite" in out
    assert "ops/topk_fused_k10" in out and "train/step" in out
    assert "0.410 (memory)" in out  # the roofline column


def test_report_bare_profile_with_no_db_is_note_not_failure(tmp_path, capsys):
    trace = _trace_with_one_span(tmp_path)
    rc = cli_main(["report", str(trace), "--profile"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "profile DB unavailable" in out
    assert "device-time profile" not in out


def test_report_autodetects_profile_db_next_to_trace(tmp_path, capsys):
    trace = _trace_with_one_span(tmp_path)
    db = ProfileDB(str(tmp_path / "profile_db.json"))  # the default name
    db.record(_sample_row())
    db.save()
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device-time profile: 1 rows" in out


# --------------------------------------------------------------- instrument


def test_instrument_disabled_adds_no_syncs_and_no_compiles(monkeypatch):
    """The profile_overhead_lt_1pct contract's regression half: with
    profiling disabled the wrapper is ONE predicate per call — it must never
    reach device_fence (zero host syncs) and must not add a jit signature
    (a single compile across 10 calls)."""
    assert not devprof.enabled()

    def boom(x=None):
        raise AssertionError("device_fence reached with profiling disabled")

    monkeypatch.setattr(devprof, "device_fence", boom)
    f = jax.jit(lambda x: x * 3.0 + 1.0)
    w = devprof.instrument(f, op="t/step")
    x = jnp.arange(16.0)
    with compile_guard(max_compiles=1) as guard:
        outs = [w(x) for _ in range(10)]
    assert guard.count <= 1
    np.testing.assert_allclose(jax.device_get(outs[-1]),
                               np.arange(16.0) * 3.0 + 1.0)


def test_instrument_enabled_accumulates_and_collects_rows(tmp_path):
    f = jax.jit(lambda x: x + 1.0)
    w = devprof.instrument(f, op="t/inc")
    x = jnp.ones((4, 4), jnp.float32)
    w(x)  # compile before arming: enabled-mode rows measure steady state
    devprof.enable()
    try:
        for _ in range(3):
            w(x)
        db = ProfileDB(str(tmp_path / "pdb.json"))
        rows = devprof.collect(device_kind="cpu", db=db)
    finally:
        acc = devprof.disable()
    (row,) = rows
    assert row["op"] == "t/inc" and row["n"] == 3
    assert row["shape"] == "4x4" and row["n_clean"] == 3
    assert ProfileDB(str(tmp_path / "pdb.json")).get(
        "t/inc", "4x4", "float32", "cpu")
    assert "t/inc" in acc  # disable() hands back the accumulator
