"""Sparse device-ingestion ops vs dense oracles."""

import numpy as np
import pytest
import scipy.sparse as sp
import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import sparse_ingest as SI
from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params
from dae_rnn_news_recommendation_tpu.models.dae_core import encode as dense_encode


@pytest.fixture
def csr():
    return sp.random(33, 400, density=0.05, format="csr", random_state=0,
                     dtype=np.float32)


def test_pad_csr_batch_roundtrip(csr):
    padded = SI.pad_csr_batch(csr, k_multiple=16)
    assert padded["indices"].dtype == np.uint16
    assert padded["k"] % 16 == 0
    dense = np.zeros(csr.shape, np.float32)
    for i in range(csr.shape[0]):
        for j in range(padded["k"]):
            dense[i, padded["indices"][i, j]] += padded["values"][i, j]
    np.testing.assert_allclose(dense, csr.toarray(), rtol=1e-6)


def test_pad_csr_wide_features_promotes_dtype():
    m = sp.random(4, 70000, density=0.001, format="csr", random_state=1,
                  dtype=np.float32)
    padded = SI.pad_csr_batch(m)
    assert padded["indices"].dtype == np.uint32


@pytest.mark.parametrize("chunk", [256, 11])  # 33 % 11 == 0; 33 % 256 != 0 (tail path)
def test_sparse_encode_matmul_matches_dense(csr, chunk):
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(400, 32)).astype(np.float32))
    padded = SI.pad_csr_batch(csr)
    got = SI.sparse_encode_matmul(w, jnp.asarray(padded["indices"]),
                                  jnp.asarray(padded["values"]), chunk=chunk,
                                  precision=jax.lax.Precision.HIGHEST)
    expect = csr.toarray() @ np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-5)


def test_densify_on_device_matches(csr):
    padded = SI.pad_csr_batch(csr)
    got = SI.densify_on_device(jnp.asarray(padded["indices"]),
                               jnp.asarray(padded["values"]), csr.shape[1])
    np.testing.assert_allclose(np.asarray(got), csr.toarray(), rtol=1e-6)


def test_sparse_encode_matches_dense_encode(csr):
    cfg = DAEConfig(n_features=400, n_components=32, enc_act_func="sigmoid",
                    dec_act_func="none", loss_func="mean_squared", corr_type="none",
                    triplet_strategy="none", matmul_precision="highest")
    params = init_params(jax.random.PRNGKey(0), cfg)
    padded = SI.pad_csr_batch(csr)
    got = SI.sparse_encode(params, jnp.asarray(padded["indices"]),
                           jnp.asarray(padded["values"]), cfg)
    expect = dense_encode(params, jnp.asarray(csr.toarray()), cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-4, atol=1e-5)


def test_binary_mode_matches_dense(csr):
    """binary pad mode (no values shipped) == dense matmul on a 0/1 matrix."""
    bin_csr = csr.copy()
    bin_csr.data[:] = 1.0
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(400, 16)).astype(np.float32))
    padded = SI.pad_csr_batch(bin_csr, binary=True)
    assert padded["values"] is None
    w_ext = SI.extend_w_for_binary(w)
    got = SI.sparse_encode_matmul(w_ext, jnp.asarray(padded["indices"]), None,
                                  precision=jax.lax.Precision.HIGHEST)
    expect = bin_csr.toarray() @ np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-4, atol=1e-5)


def test_binary_mode_sparse_encode(csr):
    bin_csr = csr.copy()
    bin_csr.data[:] = 1.0
    cfg = DAEConfig(n_features=400, n_components=32, enc_act_func="sigmoid",
                    dec_act_func="none", loss_func="mean_squared", corr_type="none",
                    triplet_strategy="none", matmul_precision="highest")
    params = init_params(jax.random.PRNGKey(2), cfg)
    padded = SI.pad_csr_batch(bin_csr, binary=True)
    got = SI.sparse_encode(params, jnp.asarray(padded["indices"]), None, cfg)
    expect = dense_encode(params, jnp.asarray(bin_csr.toarray()), cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=1e-4, atol=1e-5)


def test_sparse_encode_is_jittable(csr):
    cfg = DAEConfig(n_features=400, n_components=32, enc_act_func="tanh",
                    dec_act_func="none", loss_func="mean_squared", corr_type="none",
                    triplet_strategy="none")
    params = init_params(jax.random.PRNGKey(1), cfg)
    padded = SI.pad_csr_batch(csr)
    fn = jax.jit(lambda p, i, v: SI.sparse_encode(p, i, v, cfg))
    out = fn(params, jnp.asarray(padded["indices"]), jnp.asarray(padded["values"]))
    assert out.shape == (33, 32)


def test_pad_csr_rows_matches_slice_then_pack(rng):
    """Native gather+pack must equal pad_csr_batch on the scipy row slice,
    including shuffled/duplicate row ids, value and binary modes."""
    import scipy.sparse as sp

    from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import (
        pad_csr_batch, pad_csr_rows)

    m = sp.random(200, 500, density=0.05, format="csr",
                  random_state=np.random.RandomState(4), dtype=np.float32)
    ids = rng.integers(0, 200, 64)
    ids[5] = ids[6]  # duplicates allowed (shuffled epochs can't produce them,
                     # but the contract is plain gather)
    k = int(np.diff(m.indptr).max(initial=1))

    got = pad_csr_rows(m, ids, k=k)
    want = pad_csr_batch(m[ids], k=k)
    np.testing.assert_array_equal(got["indices"], want["indices"])
    np.testing.assert_array_equal(got["values"], want["values"])
    assert got["k"] == want["k"]

    mb = (m > 0).astype(np.float32)
    got_b = pad_csr_rows(mb, ids, k=k, binary=True)
    want_b = pad_csr_batch(mb[ids], k=k, binary=True)
    np.testing.assert_array_equal(got_b["indices"], want_b["indices"])
    assert got_b["values"] is None


def test_pad_csr_rows_float64_input(rng):
    """tfidf matrices are float64; values must come back float32 and exact."""
    import scipy.sparse as sp

    from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import pad_csr_rows

    m = sp.random(50, 100, density=0.1, format="csr",
                  random_state=np.random.RandomState(5), dtype=np.float64)
    ids = np.arange(50)
    k = int(np.diff(m.indptr).max(initial=1))
    got = pad_csr_rows(m, ids, k=k)
    assert got["values"].dtype == np.float32
    dense = np.asarray(m.todense(), np.float32)
    for i in range(50):
        row = dense[i]
        nz = np.flatnonzero(row)
        np.testing.assert_array_equal(got["indices"][i][: len(nz)], nz)
        np.testing.assert_allclose(got["values"][i][: len(nz)], row[nz])


@pytest.mark.parametrize("binary", [False, True])
def test_sparse_encode_via_dense_matches_gather(csr, binary):
    """The via_dense (densify + MXU matmul) strategy must equal the
    gather-accumulate strategy and the dense oracle, both feed modes."""
    data = csr.copy()
    if binary:
        data.data[:] = 1.0
    cfg = DAEConfig(n_features=400, n_components=32, enc_act_func="sigmoid",
                    dec_act_func="none", loss_func="mean_squared",
                    corr_type="none", triplet_strategy="none",
                    matmul_precision="highest")
    params = init_params(jax.random.PRNGKey(1), cfg)
    padded = SI.pad_csr_batch(data, binary=binary)
    idx = jnp.asarray(padded["indices"])
    vals = None if binary else jnp.asarray(padded["values"])
    gather = SI.sparse_encode(params, idx, vals, cfg, via_dense=False)
    dense = SI.sparse_encode(params, idx, vals, cfg, via_dense=True)
    oracle = dense_encode(params, jnp.asarray(data.toarray()), cfg)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(oracle),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(gather),
                               rtol=1e-4, atol=1e-5)


def test_ragged_tail_adapts_or_warns():
    """A batch not divisible by chunk must not silently lose the chunked
    [c, K, D] memory bound (VERDICT r2 item 10): the chunk adapts to the
    largest divisor of B when a usable one exists, and the unchunked fallback
    announces itself at trace time otherwise. A batch smaller than one chunk
    stays quiet (chunk clamps to b, so the batch is divisible)."""
    import warnings

    w = jnp.ones((50, 8), jnp.float32)
    rng = np.random.default_rng(0)

    # 792 = 8*9*11: divisor 396 <= 512 exists -> adapted, no warning, oracle-
    # exact (the evidence run's encode tail hit exactly this shape)
    idx = jnp.asarray(rng.integers(0, 50, (792, 3)), jnp.int32)
    vals = jnp.asarray(rng.uniform(size=(792, 3)).astype(np.float32))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        got = SI.sparse_encode_matmul(w, idx, vals, chunk=512)
    assert not any("unchunked" in str(r.message) for r in rec)
    dense = np.zeros((792, 50), np.float32)
    np.add.at(dense, (np.arange(792)[:, None], np.asarray(idx)),
              np.asarray(vals))
    np.testing.assert_allclose(np.asarray(got), dense @ np.ones((50, 8)),
                               rtol=1e-5)

    ragged = jnp.zeros((7, 3), jnp.int32)  # prime b: no usable divisor
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        SI.sparse_encode_matmul(w, ragged, jnp.ones((7, 3)), chunk=2)
    assert any("no usable divisor" in str(r.message) for r in rec)

    small = jnp.zeros((3, 3), jnp.int32)  # b < chunk: chunk clamps to b
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        SI.sparse_encode_matmul(w, small, jnp.ones((3, 3)), chunk=8)
    assert not any("divisor" in str(r.message) for r in rec)


def test_ragged_divisor_adaptation_fuzz():
    """Any (b, chunk) pair must produce oracle-exact results — adapted chunk,
    clamped chunk, or warned unchunked fallback alike."""
    import warnings

    rng = np.random.default_rng(7)
    w = jnp.asarray(rng.normal(size=(60, 6)).astype(np.float32))
    wd = np.asarray(w)
    for b in (1, 2, 7, 30, 96, 97, 120):
        for chunk in (1, 3, 8, 32, 256):
            idx = rng.integers(0, 60, (b, 4))
            vals = rng.uniform(size=(b, 4)).astype(np.float32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                got = SI.sparse_encode_matmul(w, jnp.asarray(idx, jnp.int32),
                                              jnp.asarray(vals), chunk=chunk)
            dense = np.zeros((b, 60), np.float32)
            np.add.at(dense, (np.arange(b)[:, None], idx), vals)
            np.testing.assert_allclose(np.asarray(got), dense @ wd,
                                       rtol=2e-5, atol=1e-5)


# ------------------------------------------------- index-dtype edges (pack)

def _one_row(f, cols):
    cols = np.asarray(cols, np.int64)
    return sp.csr_matrix((np.ones(cols.size, np.float32),
                          (np.zeros(cols.size, np.int64), cols)), shape=(1, f))


def test_pad_csr_uint16_boundaries():
    """The promotion rule, pinned at its exact boundary: non-binary needs the
    max COLUMN (F-1) to fit uint16, binary additionally needs pad_index = F
    itself to fit — so F=65536 promotes only in binary mode."""
    for f, binary, want in [
        (65535, False, np.uint16),
        (65535, True, np.uint16),   # pad_index 65535 == uint16 max: fits
        (65536, False, np.uint16),  # max column 65535: still fits
        (65536, True, np.uint32),   # pad_index 65536: first over the edge
        (65537, False, np.uint32),
    ]:
        m = _one_row(f, [3, f - 1])
        p = SI.pad_csr_batch(m, binary=binary)
        assert p["indices"].dtype == want, (f, binary)
        # the extreme column survives the pack at full precision
        assert int(p["indices"][0, 1]) == f - 1
        if binary:
            assert int(p["indices"][0, 2]) == f  # pad slots point at F
        else:
            assert int(p["indices"][0, 2]) == 0


def test_pad_csr_empty_rows_and_empty_matrix():
    m = sp.csr_matrix(np.array([[0, 0, 5, 0], [0, 0, 0, 0], [1, 0, 0, 2]],
                               np.float32))
    p = SI.pad_csr_batch(m, k_multiple=4)
    np.testing.assert_array_equal(p["indices"][1], 0)  # all-pad row
    np.testing.assert_array_equal(p["values"][1], 0.0)
    pb = SI.pad_csr_batch((m > 0).astype(np.float32), k_multiple=4,
                          binary=True)
    np.testing.assert_array_equal(pb["indices"][1], 4)  # pad_index = F
    empty = sp.csr_matrix((6, 100), dtype=np.float32)
    pe = SI.pad_csr_batch(empty)
    assert pe["k"] == 64  # nnz.max(initial=1) rounded to k_multiple
    np.testing.assert_array_equal(pe["indices"], 0)
    np.testing.assert_array_equal(pe["values"], 0.0)


@pytest.mark.parametrize("binary", [False, True])
@pytest.mark.parametrize("f", [400, 70000])
def test_pad_csr_native_and_numpy_paths_agree(csr, monkeypatch, binary, f):
    """The C fast path and the numpy fallback are the same layout bit for
    bit — uint16 and promoted-uint32, values and binary alike."""
    from dae_rnn_news_recommendation_tpu import native

    m = sp.csr_matrix((csr.data, csr.indices, csr.indptr),
                      shape=(csr.shape[0], f))
    if binary:
        m = m.copy()
        m.data[:] = 1.0
    fast = SI.pad_csr_batch(m, binary=binary)
    monkeypatch.setattr(native, "load", lambda: None)  # force the fallback
    slow = SI.pad_csr_batch(m, binary=binary)
    assert fast["k"] == slow["k"]
    assert fast["indices"].dtype == slow["indices"].dtype
    np.testing.assert_array_equal(fast["indices"], slow["indices"])
    if binary:
        assert fast["values"] is None and slow["values"] is None
    else:
        np.testing.assert_array_equal(fast["values"].view(np.uint32),
                                      slow["values"].view(np.uint32))
