"""Reliability subsystem contracts (PR 6): deterministic fault injection,
bounded recorded retries, feed failure propagation, async-save error
surfacing, checksum-verified restore with quarantine + fallback, atomic
checkpoint commit, and the crash-exact resume payload roundtrip.

The end-to-end story (kill a fit, resume it, get bitwise-identical params)
lives in tests/test_chaos.py; this file pins each component contract in
isolation so a chaos failure localizes to one layer.
"""

import json
import os
import threading
import time
import warnings

import numpy as np
import pytest

import jax

from dae_rnn_news_recommendation_tpu.reliability import faults as faults_mod
from dae_rnn_news_recommendation_tpu.reliability.faults import (
    FaultInjector, FaultPlan, FaultSpec, InjectedFault, SimulatedPreemption,
    TransientFault)
from dae_rnn_news_recommendation_tpu.reliability.retry import (
    RetryPolicy, is_transient)
from dae_rnn_news_recommendation_tpu.train.pipeline import PipelinedFeed
from dae_rnn_news_recommendation_tpu.utils.checkpoint import (
    AsyncCheckpointer, latest_checkpoint, load_checkpoint, save_checkpoint,
    verify_checkpoint)
from dae_rnn_news_recommendation_tpu.utils.seeding import (
    deserialize_key, restore_rng_state, rng_state, serialize_key)


# ------------------------------------------------------------- fault plans

def test_fault_plan_roundtrips_through_dict():
    plan = FaultPlan.generate(seed=3, n_steps=12)
    again = FaultPlan.from_dict(plan.to_dict())
    assert again == plan
    assert json.loads(json.dumps(plan.to_dict())) == plan.to_dict()


def test_fault_plan_generation_is_deterministic():
    a = FaultPlan.generate(seed=5, n_steps=12)
    b = FaultPlan.generate(seed=5, n_steps=12)
    assert a == b
    assert FaultPlan.generate(seed=6, n_steps=12) != a


def test_eight_consecutive_seeds_cover_every_family():
    sites = set()
    for seed in range(8):
        plan = FaultPlan.generate(seed, n_steps=12)
        sites |= {(s.site, s.kind) for s in plan.specs}
    assert {("train.step", "preempt"), ("feed.worker", "fatal"),
            ("feed.h2d", "transient"), ("ckpt.save", "transient"),
            ("ckpt.commit", "fatal"), ("ckpt.corrupt", "truncate")} <= sites


def test_preemption_never_planned_at_step_one():
    # a pre-first-checkpoint preemption tests restart-from-scratch, which is
    # not the recovery path the soak is meant to exercise
    for seed in range(32):
        for spec in FaultPlan.generate(seed, n_steps=12).specs:
            if spec.site == "train.step":
                assert spec.at >= 2


def test_fault_spec_validates_site_and_kind():
    with pytest.raises(AssertionError):
        FaultSpec("nonsite", 1, "fatal")
    with pytest.raises(AssertionError):
        FaultSpec("feed.worker", 1, "nonkind")


# ---------------------------------------------------------------- injector

def test_injector_fires_at_planned_call_and_logs():
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("feed.worker", at=2, kind="fatal", note="boom"),))
    inj = FaultInjector(plan)
    inj.fire("feed.worker", batch=0)         # call 1: below `at`
    with pytest.raises(InjectedFault):
        inj.fire("feed.worker", batch=1)     # call 2: fires
    inj.fire("feed.worker", batch=2)         # call 3: past the window
    assert [e["call"] for e in inj.fired] == [2]
    assert inj.fired[0]["kind"] == "fatal"
    assert inj.fired[0]["batch"] == 1


def test_injector_kind_maps_to_exception_class():
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("train.step", at=1, kind="preempt"),
        FaultSpec("feed.h2d", at=1, kind="transient")))
    inj = FaultInjector(plan)
    with pytest.raises(SimulatedPreemption):
        inj.fire("train.step")
    with pytest.raises(TransientFault):
        inj.fire("feed.h2d")


def test_fire_is_a_noop_without_an_installed_injector():
    assert faults_mod.active_injector() is None
    faults_mod.fire("train.step", step=1)  # must not raise


def test_install_rejects_nesting():
    plan = FaultPlan(seed=0, specs=())
    with faults_mod.install(FaultInjector(plan)) as inj:
        assert faults_mod.active_injector() is inj
        with pytest.raises(AssertionError):
            with faults_mod.install(FaultInjector(plan)):
                pass  # pragma: no cover
    assert faults_mod.active_injector() is None


# ------------------------------------------------------------------- retry

def _no_sleep(_):
    pass


def test_retry_absorbs_transient_and_records_every_attempt():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=3, backoff_s=0.01, sleep=_no_sleep)
    assert policy.run(flaky, site="feed.h2d") == "ok"
    assert len(calls) == 3
    assert [e["attempt"] for e in policy.events] == [1, 2]
    assert all(e["site"] == "feed.h2d" for e in policy.events)
    # backoff doubles between recorded attempts
    assert policy.events[1]["backoff_s"] == pytest.approx(
        policy.events[0]["backoff_s"] * 2)


def test_retry_is_bounded_and_propagates_the_original():
    def always():
        raise TransientFault("persistent")

    policy = RetryPolicy(max_attempts=3, sleep=_no_sleep)
    with pytest.raises(TransientFault, match="persistent"):
        policy.run(always, site="ckpt.save")
    assert len(policy.events) == 2  # attempts 1 and 2 retried; 3 propagated


def test_retry_never_retries_deterministic_failures():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not a blip")

    policy = RetryPolicy(max_attempts=5, sleep=_no_sleep)
    with pytest.raises(ValueError):
        policy.run(broken)
    assert len(calls) == 1 and policy.events == []


def test_is_transient_classification():
    assert is_transient(TransientFault("x"))
    assert is_transient(TimeoutError())
    assert is_transient(OSError(11, "EAGAIN"))       # errno.EAGAIN
    assert not is_transient(OSError(2, "ENOENT"))    # structural
    assert not is_transient(ValueError("x"))
    assert not is_transient(InjectedFault("fatal"))


def test_retry_events_mirror_into_active_injector():
    """The final fit attempt's manifest must show recoveries from EARLIER
    crashed attempts: RetryPolicy mirrors each event into the installed
    injector's cumulative log, which outlives any one policy instance."""
    plan = FaultPlan(seed=0, specs=())
    inj = FaultInjector(plan)

    def make_flaky():
        box = []

        def flaky():
            box.append(1)
            if len(box) == 1:
                raise TransientFault("blip")

        return flaky

    with faults_mod.install(inj):
        RetryPolicy(max_attempts=2, sleep=_no_sleep).run(
            make_flaky(), site="feed.h2d")   # "attempt 1" of the fit
        RetryPolicy(max_attempts=2, sleep=_no_sleep).run(
            make_flaky(), site="ckpt.save")  # a fresh policy after restart
    assert [e["site"] for e in inj.retries] == ["feed.h2d", "ckpt.save"]


def test_retry_full_jitter_draws_within_the_base_delay():
    """Each sleep is uniform in [0, base]: the event records both the
    deterministic base (`backoff_s`) and the drawn value (`sleep_s`), and an
    injected rng makes the schedule exactly reproducible."""
    draws = iter([0.5, 0.25])
    slept = []

    def always():
        raise TransientFault("blip")

    policy = RetryPolicy(max_attempts=3, backoff_s=0.08, factor=2.0,
                         sleep=slept.append, rng=lambda: next(draws))
    with pytest.raises(TransientFault):
        policy.run(always, site="serve.batch")
    assert [e["backoff_s"] for e in policy.events] == [0.08, 0.16]
    assert [e["sleep_s"] for e in policy.events] == [0.04, 0.04]
    assert slept == [pytest.approx(0.04), pytest.approx(0.04)]
    for e in policy.events:
        assert 0.0 <= e["sleep_s"] <= e["backoff_s"]


def test_retry_jitter_off_restores_the_deterministic_schedule():
    slept = []

    def always():
        raise TransientFault("blip")

    policy = RetryPolicy(max_attempts=3, backoff_s=0.05, jitter=False,
                         sleep=slept.append,
                         rng=lambda: 1 / 0)  # must never be consulted
    with pytest.raises(TransientFault):
        policy.run(always)
    assert slept == [pytest.approx(0.05), pytest.approx(0.1)]


def test_retry_cumulative_cap_trips_recorded_and_propagates():
    """`max_elapsed_s` bounds TOTAL backoff sleep: once the next sleep would
    cross it, the original failure propagates immediately — but the trip is
    recorded in policy.events and the active injector first (never silent)."""
    slept = []

    def always():
        raise TransientFault("persistent blip")

    plan = FaultPlan(seed=0, specs=())
    inj = FaultInjector(plan)
    policy = RetryPolicy(max_attempts=10, backoff_s=0.1, factor=2.0,
                         jitter=False, max_elapsed_s=0.25,
                         sleep=slept.append)
    with faults_mod.install(inj):
        with pytest.raises(TransientFault, match="persistent blip"):
            policy.run(always, site="serve.batch")
    # sleeps 0.1 then 0.2 would total 0.3 > 0.25: only the first happens
    assert slept == [pytest.approx(0.1)]
    trip = policy.events[-1]
    assert trip["cap_tripped"] is True
    assert trip["max_elapsed_s"] == pytest.approx(0.25)
    assert trip["elapsed_s"] == pytest.approx(0.1)
    assert inj.retries[-1].get("cap_tripped") is True


def test_retry_cap_never_trips_under_budget():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=3, backoff_s=0.01, jitter=False,
                         max_elapsed_s=10.0, sleep=_no_sleep)
    assert policy.run(flaky) == "ok"
    assert not any(e.get("cap_tripped") for e in policy.events)


# -------------------------------------------------------- feed propagation

def _batches(n, rows=4, cols=6):
    for i in range(n):
        yield np.full((rows, cols), float(i), dtype=np.float32)


def test_feed_worker_death_reraises_original_exception():
    class FeedBug(RuntimeError):
        pass

    def bad_batches():
        yield np.ones((2, 3), np.float32)
        raise FeedBug("died in the generator")

    feed = PipelinedFeed(bad_batches(), depth=2)
    it = iter(feed)
    next(it)  # first batch staged fine
    with pytest.raises(FeedBug, match="died in the generator") as e:
        for _ in it:
            pass
    # the original traceback travels with it: the raising frame is the
    # generator body, not the consumer's re-raise site
    tb_names = set()
    tb = e.value.__traceback__
    while tb is not None:
        tb_names.add(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "bad_batches" in tb_names


def test_feed_worker_death_wakes_a_blocked_consumer():
    """A worker that dies without queueing anything must not leave the
    consumer blocked on q.get() forever — the poll notices the dead thread
    and raises promptly."""
    def dead_on_arrival():
        raise RuntimeError("immediate death")
        yield  # pragma: no cover

    feed = PipelinedFeed(dead_on_arrival(), depth=2)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="immediate death"):
        for _ in feed:
            pass  # pragma: no cover
    assert time.monotonic() - t0 < 10.0  # bounded, not a hang


def test_feed_stop_joins_worker_and_drains_queue():
    feed = PipelinedFeed(_batches(64), depth=2)
    it = iter(feed)
    next(it)           # start the worker, take one batch
    feed.stop()        # abandon mid-epoch
    worker = feed._thread
    assert worker is not None and not worker.is_alive()
    assert feed._queue.empty()
    feed.stop()        # idempotent


def test_feed_completes_normally_and_stops_its_worker():
    got = [np.asarray(b) for b in PipelinedFeed(_batches(5), depth=2)]
    assert len(got) == 5
    assert all(float(np.asarray(b)[0, 0]) == i for i, b in enumerate(got))


def test_feed_transient_h2d_fault_is_retried_and_recorded():
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("feed.h2d", at=2, kind="transient", note="flaky link"),))
    policy = RetryPolicy(max_attempts=3, backoff_s=0.001)
    with faults_mod.install(FaultInjector(plan)) as inj:
        feed = PipelinedFeed(_batches(4), depth=2, retry=policy)
        got = list(feed)
    assert len(got) == 4                      # the blip was absorbed
    assert [e["site"] for e in policy.events] == ["feed.h2d"]
    assert [e["site"] for e in inj.retries] == ["feed.h2d"]
    assert [e["site"] for e in inj.fired] == ["feed.h2d"]


def test_feed_fatal_worker_fault_propagates():
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("feed.worker", at=2, kind="fatal", note="worker death"),))
    with faults_mod.install(FaultInjector(plan)):
        feed = PipelinedFeed(_batches(6), depth=2,
                             retry=RetryPolicy(max_attempts=3,
                                               backoff_s=0.001))
        with pytest.raises(InjectedFault, match="feed.worker"):
            list(feed)   # fatal is NOT retryable: it must surface


# ------------------------------------------------------------- checkpoints

def _tiny_state(epoch=1, scale=1.0):
    return {"params": {"w": np.full((3, 2), scale, np.float32),
                       "b": np.zeros((2,), np.float32)},
            "opt_state": [np.full((3, 2), 0.5, np.float32)],
            "epoch": epoch}


def test_save_checkpoint_is_atomic_and_checksummed(tmp_path):
    d = str(tmp_path)
    path = save_checkpoint(d, _tiny_state(epoch=1), step=1, use_orbax=False)
    assert os.path.basename(path) == "step_1"
    assert os.path.isfile(os.path.join(path, "CHECKSUMS.json"))
    assert not os.path.isdir(path + ".tmp")
    ok, reason = verify_checkpoint(path)
    assert ok, reason


def test_commit_fault_leaves_no_partial_checkpoint(tmp_path):
    d = str(tmp_path)
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("ckpt.commit", at=1, kind="fatal", note="torn commit"),))
    with faults_mod.install(FaultInjector(plan)):
        with pytest.raises(InjectedFault):
            save_checkpoint(d, _tiny_state(), step=1, use_orbax=False)
    # neither a committed dir nor a .tmp turd that restore could pick up
    assert latest_checkpoint(d) == (None, -1)
    assert not os.path.isdir(os.path.join(d, "step_1"))


def test_tmp_turd_is_invisible_to_latest_checkpoint(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _tiny_state(epoch=1), step=1, use_orbax=False)
    os.makedirs(os.path.join(d, "step_2.tmp"))  # a crashed half-write
    path, step = latest_checkpoint(d)
    assert step == 1 and path.endswith("step_1")


def test_corrupt_checkpoint_quarantined_with_fallback(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _tiny_state(epoch=1, scale=1.0), step=1,
                    use_orbax=False)
    newest = save_checkpoint(d, _tiny_state(epoch=2, scale=2.0), step=2,
                             use_orbax=False)
    # bit-rot the newest checkpoint's aux payload
    with open(os.path.join(newest, "aux.npz"), "r+b") as f:
        f.truncate(10)
    with pytest.warns(RuntimeWarning, match="quarantined corrupt checkpoint"):
        path, step = latest_checkpoint(d)
    assert step == 1 and path.endswith("step_1")       # fell back
    assert os.path.isdir(os.path.join(d, "quarantined-step_2"))  # evidence
    assert not os.path.isdir(newest)
    # the fallback actually restores
    out = load_checkpoint(path, _tiny_state())
    assert out["epoch"] == 1
    assert float(np.asarray(out["params"]["w"])[0, 0]) == 1.0


def test_verify_checkpoint_detects_missing_and_mutated_files(tmp_path):
    d = str(tmp_path)
    path = save_checkpoint(d, _tiny_state(), step=1, use_orbax=False)
    ok, _ = verify_checkpoint(path)
    assert ok
    aux = os.path.join(path, "aux.npz")
    payload = open(aux, "rb").read()
    os.remove(aux)
    ok, reason = verify_checkpoint(path)
    assert not ok and "missing" in reason
    # same size, different bytes -> only the sha256 catches it
    open(aux, "wb").write(payload[:-1] + bytes([payload[-1] ^ 0xFF]))
    ok, reason = verify_checkpoint(path)
    assert not ok and "checksum mismatch" in reason


def test_resave_of_same_step_supersedes(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _tiny_state(scale=1.0), step=1, use_orbax=False)
    save_checkpoint(d, _tiny_state(scale=9.0), step=1, use_orbax=False)
    path, _ = latest_checkpoint(d)
    out = load_checkpoint(path, _tiny_state())
    assert float(np.asarray(out["params"]["w"])[0, 0]) == 9.0


def test_cursor_checkpoints_sort_between_epoch_boundaries(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, _tiny_state(epoch=1), step=1, use_orbax=False)
    save_checkpoint(d, _tiny_state(epoch=1), step=1, cursor=2,
                    use_orbax=False)
    path, _ = latest_checkpoint(d)
    assert path.endswith("step_1_2")  # the mid-epoch save is newer
    save_checkpoint(d, _tiny_state(epoch=2), step=2, use_orbax=False)
    path, _ = latest_checkpoint(d)
    assert path.endswith("step_2")    # the next boundary supersedes it


# -------------------------------------------------------------- async saves

def test_async_checkpointer_surfaces_background_failure(tmp_path):
    """Regression: a background save that raises must re-surface on the next
    save()/wait(), never be swallowed by the worker thread."""
    d = str(tmp_path)
    ac = AsyncCheckpointer()
    state = _tiny_state()
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("ckpt.commit", at=1, kind="fatal", note="bg failure"),))
    with faults_mod.install(FaultInjector(plan)):
        ac.save(d, state, step=1, use_orbax=False)
        with pytest.raises(InjectedFault) as e:
            ac.wait()
    notes = "".join(getattr(e.value, "__notes__", []))
    assert "step=1" in notes and d in notes  # failure carries its identity
    ac.wait()  # a surfaced failure is consumed, not raised twice


def test_async_checkpointer_surfaces_failure_on_next_save(tmp_path):
    d = str(tmp_path)
    ac = AsyncCheckpointer()
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("ckpt.commit", at=1, kind="fatal"),))
    with faults_mod.install(FaultInjector(plan)):
        ac.save(d, _tiny_state(), step=1, use_orbax=False)
        with pytest.raises(InjectedFault):
            ac.save(d, _tiny_state(), step=2, use_orbax=False)
        ac.wait()  # the second submission never happened; nothing in flight
    assert latest_checkpoint(d) == (None, -1)


def test_async_checkpointer_retry_absorbs_transient_save_fault(tmp_path):
    d = str(tmp_path)
    policy = RetryPolicy(max_attempts=3, backoff_s=0.001)
    ac = AsyncCheckpointer(retry=policy)
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("ckpt.save", at=1, kind="transient", note="NFS hiccup"),))
    with faults_mod.install(FaultInjector(plan)) as inj:
        ac.save(d, _tiny_state(), step=1, use_orbax=False)
        ac.wait()  # the transient was absorbed; no exception
    path, step = latest_checkpoint(d)
    assert step == 1 and verify_checkpoint(path)[0]
    assert [e["site"] for e in policy.events] == ["ckpt.save"]
    assert [e["site"] for e in inj.retries] == ["ckpt.save"]


def test_async_checkpointer_saves_a_host_snapshot(tmp_path):
    """save() snapshots the state BEFORE returning: mutating the live params
    afterwards must not race the background writer."""
    d = str(tmp_path)
    ac = AsyncCheckpointer()
    state = _tiny_state(scale=1.0)
    ac.save(d, state, step=1, use_orbax=False)
    state["params"]["w"][:] = 999.0  # trainer keeps going
    ac.wait()
    out = load_checkpoint(os.path.join(d, "step_1"), _tiny_state())
    assert float(np.asarray(out["params"]["w"])[0, 0]) == 1.0


# ------------------------------------------------------- resume payload RNG

def test_prng_key_roundtrips_through_json():
    key = jax.random.PRNGKey(42)
    key, sub = jax.random.split(key)
    words = serialize_key(key)
    assert json.loads(json.dumps(words)) == words
    restored = deserialize_key(words)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(key))
    # the restored key continues the exact draw chain
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(restored, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_numpy_generator_state_roundtrips_through_json():
    rng = np.random.default_rng(7)
    rng.random(13)  # advance off the seed point
    snap = json.loads(json.dumps(rng_state(rng)))
    expected = rng.permutation(50)  # the draw a resumed run must reproduce
    fresh = np.random.default_rng(0)
    restore_rng_state(fresh, snap)
    np.testing.assert_array_equal(fresh.permutation(50), expected)


# ------------------------------------------------------- threaded injector

def test_injector_is_thread_safe_under_concurrent_fire():
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("feed.worker", at=50, kind="fatal"),))
    inj = FaultInjector(plan)
    hits, errs = [], []

    def hammer():
        for _ in range(25):
            try:
                inj.fire("feed.worker")
            except InjectedFault:
                hits.append(1)
            except Exception as e:  # pragma: no cover - diagnostic only
                errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(hits) == 1       # exactly one call was the 50th
    assert len(inj.fired) == 1
