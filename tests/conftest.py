"""Test harness: force JAX onto CPU with 8 virtual devices so pjit/shard_map mesh
tests run without TPU hardware (SURVEY.md §4 multi-node story).

Note: this environment pre-imports jax at interpreter startup (PYTHONPATH site hook)
with JAX_PLATFORMS=axon pointing at a real TPU. Backends initialize lazily, so
flipping the platform via jax.config BEFORE any device use still works — env vars
alone do not, because the env was already read.
"""

import os

# DAE_TPU_TESTS=1 leaves the platform alone so the TPU-gated tests
# (test_pallas_kernels.py hardware-PRNG / compiled-VJP) run on the real chip.
_ON_HW = os.environ.get("DAE_TPU_TESTS") == "1"

_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_HW and "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if not _ON_HW:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if not _ON_HW:
    jax.config.update("jax_platforms", "cpu")

import threading

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# --------------------------------------------------------- thread sanitizer
# The serve/fleet/chaos tests run real worker threads (batcher flush, hedge
# scheduler, churn supervisor). An UNCAUGHT exception on one of those threads
# only prints to stderr — the owning test still passes, and the bug ships.
# threading.excepthook is process-global, so the recorder is session-scoped;
# an autouse per-test fixture diffs the log and fails the test that owned
# the crash. Tests that deliberately crash a thread consume their records
# (`del log[start:]`) before teardown.

@pytest.fixture(scope="session")
def _thread_exception_log():
    log = []
    prev = threading.excepthook

    def hook(args):
        log.append(args)
        prev(args)   # keep the stderr traceback for debugging

    threading.excepthook = hook
    yield log
    threading.excepthook = prev


@pytest.fixture(autouse=True)
def _fail_on_background_thread_exception(_thread_exception_log):
    start = len(_thread_exception_log)
    yield
    fresh = _thread_exception_log[start:]
    if fresh:
        del _thread_exception_log[start:]   # don't poison the next test
        detail = "; ".join(
            f"{a.exc_type.__name__}: {a.exc_value} (thread "
            f"{getattr(a.thread, 'name', '?')})" for a in fresh)
        pytest.fail(
            f"uncaught exception on a background thread during this test: "
            f"{detail}")
