"""Test harness: force JAX onto CPU with 8 virtual devices BEFORE jax is imported,
so pjit/shard_map mesh tests run without TPU hardware (SURVEY.md §4 multi-node story).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
