"""Test harness: force JAX onto CPU with 8 virtual devices so pjit/shard_map mesh
tests run without TPU hardware (SURVEY.md §4 multi-node story).

Note: this environment pre-imports jax at interpreter startup (PYTHONPATH site hook)
with JAX_PLATFORMS=axon pointing at a real TPU. Backends initialize lazily, so
flipping the platform via jax.config BEFORE any device use still works — env vars
alone do not, because the env was already read.
"""

import os

# DAE_TPU_TESTS=1 leaves the platform alone so the TPU-gated tests
# (test_pallas_kernels.py hardware-PRNG / compiled-VJP) run on the real chip.
_ON_HW = os.environ.get("DAE_TPU_TESTS") == "1"

_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_HW and "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
if not _ON_HW:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if not _ON_HW:
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
