"""Driver integration tests: the reference has none (SURVEY §4 'no driver tests') —
these run both CLIs end to end on tiny synthetic corpora and check the artifact tree."""

import os

import numpy as np
import pytest


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_main_autoencoder_end_to_end(workdir):
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    model, aurocs = main([
        "--model_name", "t", "--synthetic", "--validation", "--num_epochs", "2",
        "--train_row", "120", "--validate_row", "40", "--max_features", "300",
        "--batch_size", "0.25", "--opt", "ada_grad", "--verbose_step", "2",
    ])
    assert len(aurocs) == 12  # 3 representations x 2 splits x 2 label kinds
    # story labels can lack related pairs on tiny splits -> nan is legitimate there
    finite = {k: v for k, v in aurocs.items() if np.isfinite(v)}
    assert all(0.0 <= v <= 1.0 for v in finite.values())
    assert any("(Category)" in k for k in finite)
    d = model.data_dir
    for f in ("article.snappy.parquet", "article_binary_count_vectorized.npz",
              "article_tfidf_vectorized.npz", "count_vectorizer.joblib"):
        assert os.path.isfile(d + f), f
    assert os.path.isfile(model.parameter_file)
    assert any(name.startswith("step_") for name in os.listdir(model.model_path))
    # one PNG per non-degenerate AUROC (nan cases skip plotting)
    assert len(os.listdir(model.plot_dir)) == len(finite)


def test_main_autoencoder_joint_two_label_mining(workdir):
    # --label2 mines a second batch_all term (story) jointly with the primary
    # (category) label; rows without a story sit out the second term
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    model, aurocs = main([
        "--model_name", "j2", "--synthetic", "--validation", "--num_epochs", "2",
        "--train_row", "120", "--validate_row", "40", "--max_features", "300",
        "--batch_size", "0.25", "--opt", "ada_grad",
        "--label2", "story", "--label2_alpha", "0.5",
    ])
    assert model.label2_alpha == 0.5
    assert len(aurocs) == 12
    finite = {k: v for k, v in aurocs.items() if np.isfinite(v)}
    assert all(0.0 <= v <= 1.0 for v in finite.values())


def test_main_autoencoder_restore_data(workdir):
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    args = ["--model_name", "r", "--synthetic", "--num_epochs", "1",
            "--train_row", "100", "--validate_row", "30", "--max_features", "200",
            "--batch_size", "0.5", "--opt", "ada_grad"]
    main(args)
    # second run restores the saved data artifacts and the model
    model, aurocs = main(args + ["--restore_previous_data", "--restore_previous_model"])
    assert any(np.isfinite(v) for v in aurocs.values())


def test_main_autoencoder_triplet_story_keyed(workdir):
    # --label story keys similar_articles on the story column (net-new; the
    # reference recipe is category-only and carries no Story signal)
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet import main

    model, aurocs = main([
        "--model_name", "ts", "--synthetic", "--num_epochs", "1",
        "--train_row", "80", "--validate_row", "20", "--max_features", "300",
        "--batch_size", "0.25", "--opt", "ada_grad", "--label", "story",
        "--synthetic_oversample", "10.0",
        "--loss_func", "mean_squared", "--dec_act_func", "none", "--validation",
    ])
    assert len(aurocs) == 12
    finite = {k: v for k, v in aurocs.items() if np.isfinite(v)}
    assert all(0.0 <= v <= 1.0 for v in finite.values())


def test_main_autoencoder_triplet_end_to_end(workdir):
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet import main

    model, aurocs = main([
        "--model_name", "tt", "--synthetic", "--num_epochs", "2",
        "--train_row", "120", "--validate_row", "30", "--max_features", "300",
        "--batch_size", "0.25", "--opt", "ada_grad",
        "--loss_func", "mean_squared", "--dec_act_func", "none", "--validation",
    ])
    # reference-parity eval tail: 3 representations x 2 splits x 2 label kinds
    # (reference main_autoencoder_triplet.py:249-321)
    assert len(aurocs) == 12
    finite = {k: v for k, v in aurocs.items() if np.isfinite(v)}
    assert all(0.0 <= v <= 1.0 for v in finite.values())
    assert any("(Category)" in k for k in finite)
    assert any("_validate" in k for k in aurocs)


def test_main_starspace_end_to_end(workdir):
    from dae_rnn_news_recommendation_tpu.cli.main_starspace import main

    result, aurocs = main([
        "--model_name", "ss", "--synthetic", "--train_row", "150",
        "--validate_row", "60", "--epochs", "4", "--threads", "2",
        "--dim", "16", "--max_features", "300",
    ])
    assert len(result["epoch_errors"]) <= 4
    assert np.isfinite(result["best_val_error"])
    assert set(aurocs) == {"starspace_train", "starspace_validate",
                           "tfidf_train", "tfidf_validate"}
    d = "results/starspace/ss/"
    for f in ("uci_train_starspace.txt", "uci_validate_starspace.txt",
              "uci_train_starspace_embed.txt",
              "uci_validate_starspace_embed.txt"):
        assert os.path.isfile(d + f), f
    emb = np.loadtxt(d + "uci_train_starspace_embed.txt")
    assert emb.shape == (150, 16)


def test_main_autoencoder_streaming_eval(workdir):
    """--streaming_eval computes the 12 AUROCs blockwise, with the ROC/boxplot
    figures derived from the score histograms; values agree with the
    full-matrix path on the same run."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    args = ["--model_name", "se", "--synthetic", "--validation", "--num_epochs", "2",
            "--train_row", "120", "--validate_row", "40", "--max_features", "300",
            "--batch_size", "0.25", "--opt", "ada_grad", "--seed", "0"]
    model_s, stream = main(args + ["--streaming_eval"])
    assert len(stream) == 12
    # one histogram-derived figure per finite AUROC (degenerate label splits
    # skip the figure, exactly like the full-matrix path)
    n_finite = sum(np.isfinite(v) for v in stream.values())
    plots = os.listdir(model_s.plot_dir)
    assert len(plots) == n_finite > 0
    assert all(p.endswith(".png") for p in plots)
    model_f, full = main(["--model_name", "sf"] + args[2:])
    assert set(stream) == set(full)
    for k in full:
        if np.isfinite(full[k]):
            assert abs(full[k] - stream[k]) < 5e-3, k
        else:
            assert not np.isfinite(stream[k]), k


def test_main_autoencoder_auto_streaming(workdir):
    """Above --streaming_eval_threshold rows the eval tail auto-selects the
    streaming path (figures still produced, full [N, N] matrices never built)."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    model, aurocs = main(
        ["--model_name", "au", "--synthetic", "--validation", "--num_epochs", "1",
         "--train_row", "120", "--validate_row", "40", "--max_features", "300",
         "--batch_size", "0.25", "--seed", "0", "--streaming_eval_threshold", "60"])
    assert len(aurocs) == 12
    n_finite = sum(np.isfinite(v) for v in aurocs.values())
    assert len(os.listdir(model.plot_dir)) == n_finite > 0


def test_main_autoencoder_from_parquet(workdir):
    """The real-data path: --data_path pointing at a parquet with the reference
    schema (the UCI artifact's shape) must run the full driver end to end —
    proven here on a synthetic corpus written to disk, since the environment
    ships no real parquet."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main
    from dae_rnn_news_recommendation_tpu.data import articles

    corpus = articles.synthetic_articles(n_articles=160, seed=3)
    path = str(workdir / "uci_like.snappy.parquet")
    articles.save_articles(corpus, path)

    model, aurocs = main([
        "--model_name", "pq", "--validation", "--num_epochs", "2",
        "--data_path", path, "--train_row", "120", "--validate_row", "40",
        "--max_features", "300", "--batch_size", "0.25", "--opt", "ada_grad",
        "--seed", "0",
    ])
    assert len(aurocs) == 12
    finite = {k: v for k, v in aurocs.items() if np.isfinite(v)}
    assert all(0.0 <= v <= 1.0 for v in finite.values()) and finite
    # story extraction survived the parquet round trip (title regex path)
    import pandas as pd
    back = pd.read_parquet(model.data_dir + "article.snappy.parquet")
    assert back.story.notna().any()


def test_main_autoencoder_model_parallel(workdir):
    """--model_parallel 2 with --n_devices 8 runs the driver on a 2-D
    (data x model) mesh with W feature-sharded."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    model, aurocs = main([
        "--model_name", "mp", "--synthetic", "--num_epochs", "1",
        "--train_row", "96", "--validate_row", "32", "--max_features", "256",
        "--batch_size", "0.5", "--n_devices", "8", "--model_parallel", "2",
        "--seed", "0",
    ])
    assert dict(model.mesh.shape) == {"data": 4, "model": 2}
    assert any(np.isfinite(v) for v in aurocs.values())


def test_main_autoencoder_eval_reps_filter(workdir):
    """--eval_reps restricts the AUROC sweep (scale runs skip the wide sparse
    representations); works on both eval branches."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main

    args = ["--synthetic", "--validation", "--num_epochs", "1",
            "--train_row", "100", "--validate_row", "30", "--max_features", "200",
            "--batch_size", "0.5", "--seed", "0", "--eval_reps", "encoded"]
    _, aurocs = main(["--model_name", "er1"] + args)
    assert set(aurocs) == {
        "similarity_boxplot_encoded(Category)",
        "similarity_boxplot_encoded(Story)",
        "similarity_boxplot_encoded_validate(Category)",
        "similarity_boxplot_encoded_validate(Story)"}
    _, aurocs_s = main(["--model_name", "er2"] + args + ["--streaming_eval"])
    assert set(aurocs_s) == set(aurocs)


def test_main_starspace_from_artifacts(workdir):
    """--from_artifacts trains StarSpace on the EXACT split a main_autoencoder
    run saved (the reference notebook's export-the-DAE-split flow, cells 3-5):
    row counts must match the saved parquets and the label flag pair
    (--train_row/--validate_row) must be ignored entirely."""
    from dae_rnn_news_recommendation_tpu.cli.main_autoencoder import main as m_ae
    from dae_rnn_news_recommendation_tpu.cli.main_starspace import main as m_ss

    model, _ = m_ae([
        "--model_name", "src", "--synthetic", "--validation",
        "--num_epochs", "1", "--train_row", "120", "--validate_row", "40",
        "--max_features", "300", "--batch_size", "0.5",
    ])
    result, aurocs = m_ss([
        "--model_name", "ss_art", "--epochs", "3", "--threads", "2",
        "--dim", "16", "--max_features", "300",
        "--train_row", "9999", "--validate_row", "9999",  # must be ignored
        "--from_artifacts", os.path.abspath(model.data_dir),
    ])
    assert np.isfinite(result["best_val_error"])
    emb = np.loadtxt("results/starspace/ss_art/uci_train_starspace_embed.txt")
    assert emb.shape == (120, 16)  # the DAE run's split, not the flags
    emb_vl = np.loadtxt(
        "results/starspace/ss_art/uci_validate_starspace_embed.txt")
    assert emb_vl.shape == (40, 16)
    assert set(aurocs) == {"starspace_train", "starspace_validate",
                           "tfidf_train", "tfidf_validate"}
