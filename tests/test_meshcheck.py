"""meshcheck (S1-S5) wiring into tier-1.

Mirrors test_threadcheck.py for the mesh/SPMD rule family:
  * seeded    — the s*_ fixtures' planted violations fire and their clean
                twins stay silent (test_jaxcheck.py's parametrized sweep
                covers them; here we pin the CROSS-FILE behavior those
                can't show: a sharded callable built by a factory in one
                module and dispatched from a thread-spawned method in
                another);
  * self-clean — the repo's contract set has zero unsuppressed S findings;
  * CLI       — family-letter --select ('S', 'R,C,S') ergonomics;
  * runtime   — the satellite-1 regression: the swap/health-gate device
                work of a mesh-sharded ServingCorpus and the eval ring
                dispatch actually serialize through the process-wide
                parallel/mesh.MESH_DISPATCH_LOCK (the r16 deadlock fix),
                and a single-device corpus never touches it.
"""

import json
import os
import textwrap

import numpy as np
import pytest

import jax

from dae_rnn_news_recommendation_tpu.analysis import (
    RULES, analyze_file, analyze_paths, default_targets)
from dae_rnn_news_recommendation_tpu.analysis.__main__ import main as cli_main

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "jaxcheck")
S_RULES = {"S1", "S2", "S3", "S4", "S5"}


def _write(path, src):
    path.write_text(textwrap.dedent(src))
    return str(path)


# ---------------------------------------------------------------- registry

def test_s_rules_registered():
    assert S_RULES <= set(RULES)


# -------------------------------------------------- cross-file / call graph

def test_s1_cross_module_factory_dispatch(tmp_path):
    """The tentpole case per-file analysis cannot see: the sharded callable
    is BUILT by a factory in builder.py and dispatched from a
    thread-spawned method in worker.py. The whole-package mesh index closes
    the factory -> attribute -> dispatch chain, so the bare dispatch fires
    S1 while the dispatch_lock-guarded twin stays silent."""
    pkg = tmp_path / "meshpkg"
    pkg.mkdir()
    _write(pkg / "__init__.py", "")
    builder = _write(pkg / "builder.py", """\
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        MESH_AXIS_NAMES = ("data",)


        def make_gather(mesh):
            def local(x):
                return jax.lax.psum(x, "data")

            return shard_map(local, mesh=mesh, in_specs=(P("data", None),),
                             out_specs=P("data", None))
        """)
    worker = _write(pkg / "worker.py", """\
        import threading

        from .builder import make_gather


        class Refresher:
            def __init__(self, mesh):
                self._fn = make_gather(mesh)
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                return self._fn(0)

            def run_guarded(self, dispatch_lock):
                with dispatch_lock():
                    return self._fn(0)
        """)
    fb, _ = analyze_file(builder, root=str(tmp_path))
    fw, _ = analyze_file(worker, root=str(tmp_path))
    assert fb == []
    assert [f.rule for f in fw] == ["S1"]
    assert "self._fn" in fw[0].message and "_run" in fw[0].message


# -------------------------------------------------------------- self-clean

def test_repo_is_s_clean():
    """The acceptance criterion: zero unsuppressed S findings on the
    package + bench.py + evidence/ (the serving, eval, and bench dispatch
    sites all route through parallel/mesh.dispatch_lock)."""
    root, targets = default_targets()
    findings, suppressed, n_files = analyze_paths(
        targets, root=root, select=S_RULES)
    assert n_files > 30
    assert findings == [], "\n".join(f.render() for f in findings)
    assert all(s.suppress_reason for s in suppressed)


# --------------------------------------------------------------------- CLI

def test_cli_family_letter_select(capsys):
    rc = cli_main(["--json", "--select", "S",
                   os.path.join(FIXTURE_DIR, "s3_axis_hygiene.py")])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in report["findings"]} == {"S3"}


def test_cli_mixed_families_and_ids(capsys):
    rc = cli_main(["--json", "--select", "R,C,S1,S3",
                   os.path.join(FIXTURE_DIR, "s3_axis_hygiene.py")])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in report["findings"]} == {"S3"}


def test_cli_unknown_family_is_usage_error(capsys):
    assert cli_main(["--select", "Q",
                     os.path.join(FIXTURE_DIR, "s3_axis_hygiene.py")]) == 2
    capsys.readouterr()
    assert cli_main(["--select", "S9",
                     os.path.join(FIXTURE_DIR, "s3_axis_hygiene.py")]) == 2


# ------------------------------------------------- runtime lock regression

class _RecordingLock:
    """Context-manager proxy standing in for MESH_DISPATCH_LOCK."""

    def __init__(self):
        self.acquired = 0
        self.depth = 0

    def __enter__(self):
        assert self.depth == 0, "mesh dispatch lock acquired re-entrantly"
        self.depth += 1
        self.acquired += 1
        return self

    def __exit__(self, *exc):
        self.depth -= 1
        return False


@pytest.fixture()
def recording_lock(monkeypatch):
    """Swap the process-wide mesh dispatch lock for a counting proxy.
    dispatch_lock() reads the module global at call time, so every caller
    that routes through it is observed."""
    from dae_rnn_news_recommendation_tpu.parallel import mesh as mesh_mod

    proxy = _RecordingLock()
    monkeypatch.setattr(mesh_mod, "MESH_DISPATCH_LOCK", proxy)
    return proxy


def _small_setup():
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(n_features=24, n_components=8,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(0), config)
    articles = np.random.default_rng(0).random((48, 24), dtype=np.float32)
    return config, params, articles


def test_sharded_corpus_swap_takes_dispatch_lock(recording_lock):
    """A mesh-sharded corpus's swap path (encode + health gate) runs on the
    churn/rollout thread concurrently with serving threads — its device
    dispatches must serialize through the process-wide lock (the r16 bug
    class, satellite 1)."""
    from dae_rnn_news_recommendation_tpu.parallel.mesh import get_mesh
    from dae_rnn_news_recommendation_tpu.serve import ServingCorpus

    config, params, articles = _small_setup()
    corpus = ServingCorpus(config, block=16, mesh=get_mesh())
    corpus.swap(params, articles, note="initial")
    assert recording_lock.acquired >= 2  # encode/build + health gate
    assert recording_lock.depth == 0


def test_single_device_corpus_skips_dispatch_lock(recording_lock):
    """dispatch_lock(sharded=False) is a free nullcontext: a single-device
    corpus must never contend on the collective-dispatch lock."""
    from dae_rnn_news_recommendation_tpu.serve import ServingCorpus

    config, params, articles = _small_setup()
    corpus = ServingCorpus(config, block=16)
    corpus.swap(params, articles, note="initial")
    assert recording_lock.acquired == 0


def test_ring_auroc_dispatch_takes_dispatch_lock(recording_lock):
    """The eval ring (ppermute collective) was the named real finding: it
    used to dispatch shard_map with no guard while serving threads dispatch
    concurrently. It must now hold the lock exactly once per sweep."""
    from dae_rnn_news_recommendation_tpu.eval.streaming_auroc import (
        ring_streaming_auroc, streaming_auroc)
    from dae_rnn_news_recommendation_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(7)
    x = rng.standard_normal((24, 6)).astype(np.float32)
    labels = rng.integers(0, 3, size=24)
    got = ring_streaming_auroc(x, labels, get_mesh(), bins=128)
    assert recording_lock.acquired == 1
    assert recording_lock.depth == 0
    ref = streaming_auroc(x, labels, bins=128)
    np.testing.assert_allclose(got, ref, rtol=0, atol=0)
