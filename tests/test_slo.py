"""SLO burn-rate monitor contracts (ISSUE 14): counter-delta windowing,
multi-window AND-gating, zero-tolerance specs, edge-firing (one alert per
breach episode), and the gauge/latency kinds.

Every test drives the monitor with a FAKE clock and hand-built registry
snapshots — the monitor's contract is pure arithmetic over (t, snapshot)
pairs, so nothing here touches a real service.
"""

import pytest

from dae_rnn_news_recommendation_tpu.telemetry import (SLOMonitor, SLOSpec,
                                                       serving_slo_specs)


def _snap(counters=None, gauges=None, histograms=None):
    return {"registry": "t", "counters": counters or {},
            "gauges": gauges or {}, "histograms": histograms or {}}


def _clock(holder):
    return lambda: holder["t"]


# ----------------------------------------------------------------- rate_max

def test_rate_uses_window_deltas_not_raw_totals():
    """A fleet with ancient errors but a CLEAN recent window must not fire:
    rates come from counter deltas between the window baseline and the
    latest snapshot, never from lifetime totals."""
    clk = {"t": 0.0}
    spec = SLOSpec("errors", "rate_max", 0.05, numerator="errors",
                   denominator="replied", short_window_s=10.0,
                   long_window_s=10.0, fast_burn=1.0, slow_burn=1.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    # ancient history: 50% error rate, far outside the window
    mon.observe(_snap(counters={"errors": 0, "replied": 0}))
    clk["t"] = 1.0
    mon.observe(_snap(counters={"errors": 50, "replied": 100}))
    # window baseline: errors stop, traffic continues
    clk["t"] = 100.0
    mon.observe(_snap(counters={"errors": 50, "replied": 200}))
    clk["t"] = 109.0
    mon.observe(_snap(counters={"errors": 50, "replied": 300}))
    assert mon.evaluate() == []

    # and the mirror: a breach INSIDE the window fires
    clk["t"] = 110.0
    mon.observe(_snap(counters={"errors": 80, "replied": 400}))
    fired = mon.evaluate()
    assert [a["slo"] for a in fired] == ["errors"]


def test_zero_objective_spec_fires_on_any_occurrence_and_only_then():
    clk = {"t": 0.0}
    spec = SLOSpec("kills", "rate_max", 0.0, numerator="replica_kills",
                   short_window_s=100.0, long_window_s=100.0,
                   fast_burn=1.0, slow_burn=1.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    mon.observe(_snap(counters={"replica_kills": 0}))
    clk["t"] = 1.0
    mon.observe(_snap(counters={"replica_kills": 0}))
    assert mon.evaluate() == []
    clk["t"] = 2.0
    mon.observe(_snap(counters={"replica_kills": 1}))
    fired = mon.evaluate()
    assert len(fired) == 1 and fired[0]["slo"] == "kills"
    assert fired[0]["short_burn"] == "inf"


def test_alert_fires_once_per_breach_episode():
    """Edge-firing: a sustained breach records ONE alert; recovery then a
    fresh breach records a second."""
    clk = {"t": 0.0}
    spec = SLOSpec("sheds", "rate_max", 0.0, numerator="shed",
                   short_window_s=5.0, long_window_s=5.0,
                   fast_burn=1.0, slow_burn=1.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    mon.observe(_snap(counters={"shed": 0}))
    clk["t"] = 1.0
    mon.observe(_snap(counters={"shed": 3}))
    assert len(mon.evaluate()) == 1
    clk["t"] = 2.0
    mon.observe(_snap(counters={"shed": 3}))
    assert mon.evaluate() == []          # still the same episode
    # recovery: the window rolls past the sheds, the spec goes quiet
    clk["t"] = 20.0
    mon.observe(_snap(counters={"shed": 3}))
    clk["t"] = 24.0
    mon.observe(_snap(counters={"shed": 3}))
    assert mon.evaluate() == []
    # a NEW sheds burst is a new episode -> second alert
    clk["t"] = 25.0
    mon.observe(_snap(counters={"shed": 5}))
    assert len(mon.evaluate()) == 1
    assert len(mon.alerts) == 2


# --------------------------------------------------------- gauge / latency

def test_gauge_min_fires_below_floor_and_reads_aggregate_min():
    clk = {"t": 0.0}
    spec = SLOSpec("coverage", "gauge_min", 0.99, gauge="corpus_coverage",
                   short_window_s=10.0, long_window_s=10.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    # aggregate {min,max,mean} form: the WORST replica is what matters
    mon.observe(_snap(gauges={"corpus_coverage":
                              {"min": 1.0, "max": 1.0, "mean": 1.0}}))
    assert mon.evaluate() == []
    clk["t"] = 1.0
    mon.observe(_snap(gauges={"corpus_coverage":
                              {"min": 0.5, "max": 1.0, "mean": 0.9}}))
    fired = mon.evaluate()
    assert [a["slo"] for a in fired] == ["coverage"]
    assert fired[0]["value"] == 0.5


def test_gauge_max_fires_above_ceiling_and_reads_aggregate_max():
    """gauge_max is gauge_min's mirror (ISSUE 19: the int8 score-error
    ceiling): the WORST replica is the aggregate max, an absent gauge never
    breaches, and recovery closes the episode."""
    clk = {"t": 0.0}
    spec = SLOSpec("quant", "gauge_max", 0.05, gauge="int8_score_error",
                   short_window_s=10.0, long_window_s=10.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    # absent gauge: pass by absence, never a breach
    mon.observe(_snap(gauges={}))
    assert mon.evaluate() == []
    clk["t"] = 1.0
    mon.observe(_snap(gauges={"int8_score_error":
                              {"min": 0.001, "max": 0.01, "mean": 0.004}}))
    assert mon.evaluate() == []
    # one replica's error spikes past the ceiling -> aggregate max breaches
    clk["t"] = 2.0
    mon.observe(_snap(gauges={"int8_score_error":
                              {"min": 0.001, "max": 0.2, "mean": 0.05}}))
    fired = mon.evaluate()
    assert [a["slo"] for a in fired] == ["quant"]
    assert fired[0]["value"] == 0.2
    # sustained breach: same episode, no second alert
    clk["t"] = 3.0
    mon.observe(_snap(gauges={"int8_score_error": {"max": 0.2}}))
    assert mon.evaluate() == []
    # recovery (raw-value gauge form): the episode closes
    clk["t"] = 4.0
    mon.observe(_snap(gauges={"int8_score_error": 0.01}))
    assert mon.evaluate() == []
    assert mon.summary()["active"] == []


def test_quality_specs_cover_recall_coverage_and_quant_error():
    """quality_slo_specs wires the ISSUE 19 trio: shadow-miss burn rate,
    coverage floor, quantization-error ceiling — and a quiet fleet fires
    none of them."""
    from dae_rnn_news_recommendation_tpu.telemetry import quality_slo_specs
    clk = {"t": 0.0}
    mon = SLOMonitor(quality_slo_specs(), clock=_clock(clk))
    assert {s.name for s in mon.specs} == {
        "quality-recall", "quality-coverage", "quality-quant-error"}
    mon.observe(_snap(counters={"shadow_misses": 0, "shadow_expected": 0},
                      gauges={"corpus_coverage": 1.0,
                              "int8_score_error": 0.001}))
    clk["t"] = 1.0
    mon.observe(_snap(counters={"shadow_misses": 0, "shadow_expected": 40},
                      gauges={"corpus_coverage": 1.0,
                              "int8_score_error": 0.001}))
    assert mon.evaluate() == []
    # a burst of shadow misses past the 5% objective fires quality-recall
    clk["t"] = 2.0
    mon.observe(_snap(counters={"shadow_misses": 10, "shadow_expected": 80},
                      gauges={"corpus_coverage": 1.0,
                              "int8_score_error": 0.001}))
    assert [a["slo"] for a in mon.evaluate()] == ["quality-recall"]


def test_latency_percentile_evaluated_on_window_delta():
    clk = {"t": 0.0}
    spec = SLOSpec("p95", "latency_max", 100.0,
                   histogram="request_latency_ms", percentile=95.0,
                   short_window_s=10.0, long_window_s=10.0,
                   fast_burn=1.0, slow_burn=1.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    fast = {"bounds": [50.0, 200.0], "counts": [100, 0, 0], "count": 100,
            "sum": 1000.0, "min": 5.0, "max": 40.0}
    mon.observe(_snap(histograms={"request_latency_ms": fast}))
    assert mon.evaluate() == []
    # the new window's traffic lands entirely in the 50-200ms bucket
    slow = {"bounds": [50.0, 200.0], "counts": [100, 50, 0], "count": 150,
            "sum": 9000.0, "min": 5.0, "max": 180.0}
    clk["t"] = 1.0
    mon.observe(_snap(histograms={"request_latency_ms": slow}))
    fired = mon.evaluate()
    assert [a["slo"] for a in fired] == ["p95"]


def test_gauge_growth_fires_on_sustained_climb_only():
    """The memory-leak shape: long-window growth past the objective AND a
    still-climbing short window breach; a spike that plateaus resolves, and
    an absent gauge (CPU: no memory_stats) never breaches."""
    clk = {"t": 0.0}
    spec = SLOSpec("mem", "gauge_growth_max", 100.0, gauge="hbm_bytes_in_use",
                   short_window_s=5.0, long_window_s=10.0)
    mon = SLOMonitor([spec], clock=_clock(clk))
    # absent gauge: silent by absence
    mon.observe(_snap())
    assert mon.evaluate() == []
    # steady climb: 50 bytes/s -> long-window growth 500 > 100, short > 0
    for t, v in ((1.0, 1000), (5.0, 1200), (9.0, 1400), (11.0, 1500)):
        clk["t"] = t
        mon.observe(_snap(gauges={"hbm_bytes_in_use":
                                  {"min": v, "max": v, "mean": v}}))
    fired = mon.evaluate()
    assert [a["slo"] for a in fired] == ["mem"]
    # plateau: long growth still big vs an old baseline, but the short
    # window stops climbing -> the episode resolves
    for t in (12.0, 14.0, 18.0, 21.0):
        clk["t"] = t
        mon.observe(_snap(gauges={"hbm_bytes_in_use":
                                  {"min": 1500, "max": 1500, "mean": 1500}}))
    assert mon.evaluate() == []
    assert mon.summary()["active"] == []


# ------------------------------------------------------------ housekeeping

def test_summary_carries_specs_alerts_and_active_state():
    clk = {"t": 0.0}
    mon = SLOMonitor(serving_slo_specs(), clock=_clock(clk))
    mon.observe(_snap(counters={"shed": 0, "submitted": 0}))
    clk["t"] = 1.0
    mon.observe(_snap(counters={"shed": 50, "submitted": 100}))
    mon.evaluate()
    s = mon.summary()
    assert {sp["name"] for sp in s["specs"]} == {
        "deadline-miss-rate", "shed-rate", "corpus-coverage", "reply-p95",
        "device-memory-growth"}
    assert [a["slo"] for a in s["alerts"]] == ["shed-rate"]
    assert s["active"] == ["shed-rate"]
    assert s["n_observations"] == 2


def test_duplicate_spec_names_are_rejected():
    with pytest.raises(AssertionError):
        SLOMonitor([SLOSpec("x", "rate_max", 0.0, numerator="a"),
                    SLOSpec("x", "rate_max", 0.0, numerator="b")])
