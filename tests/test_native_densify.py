"""Native densify_csr_rows: parity with scipy .todense() and a timed advantage.

The dense-batch feed (data/batcher.py densify_rows) is the host-side analog of
the reference's dense batch slicing (reference autoencoder/utils.py:55-63); the
native path must produce byte-identical tiles.
"""

import time

import numpy as np
import pytest
import scipy.sparse as sp

try:
    from dae_rnn_news_recommendation_tpu.native.fastbatch import densify_csr_rows
except ImportError:
    densify_csr_rows = None

pytestmark = pytest.mark.skipif(
    densify_csr_rows is None, reason="native library unavailable")


def _random_csr(rng, n, f, density=0.02):
    m = sp.random(n, f, density=density, format="csr", random_state=np.random.RandomState(0),
                  dtype=np.float32)
    # add an empty row and a full-ish row for edge coverage
    m = m.tolil()
    m[0] = 0
    m[1, : min(50, f)] = rng.uniform(size=min(50, f))
    return m.tocsr()


def test_parity_with_scipy(rng):
    m = _random_csr(rng, 257, 301)
    want = np.asarray(m.todense(), np.float32)
    got = densify_csr_rows(m)
    np.testing.assert_array_equal(got, want)


def test_parity_binary_and_slice(rng):
    m = (sp.random(100, 64, density=0.05, format="csr",
                   random_state=np.random.RandomState(1)) > 0).astype(np.float32)
    idx = rng.integers(0, 100, 33)
    rows = m[idx]
    np.testing.assert_array_equal(
        densify_csr_rows(rows), np.asarray(rows.todense(), np.float32))


def test_out_reuse(rng):
    m = _random_csr(rng, 64, 128)
    out = np.empty((64, 128), np.float32)
    got = densify_csr_rows(m, out=out)
    assert got is out
    np.testing.assert_array_equal(out, np.asarray(m.todense(), np.float32))
    # stale contents must be overwritten, including rows that became empty
    out.fill(7.0)
    got2 = densify_csr_rows(m, out=out)
    assert got2 is out
    np.testing.assert_array_equal(out, np.asarray(m.todense(), np.float32))


def test_batcher_uses_native_path(rng):
    from dae_rnn_news_recommendation_tpu.data import batcher

    assert batcher._native_densify is densify_csr_rows
    m = _random_csr(rng, 90, 50)
    b = batcher.PaddedBatcher(32, shuffle=False)
    batches = list(b.epoch(m))
    assert batches[0]["x"].shape == (32, 50)
    np.testing.assert_array_equal(
        batches[0]["x"], np.asarray(m[:32].todense(), np.float32))
    # ragged tail: padded rows zero
    assert batches[-1]["row_valid"].sum() == 90 - 2 * 32
    assert (batches[-1]["x"][int(batches[-1]["row_valid"].sum()):] == 0).all()


def test_timed_advantage_over_scipy():
    """Best-of-3 on a feed-scale tile: the native scatter should beat
    csr.todense(); assert with margin so CI noise can't flake it."""
    m = sp.random(8192, 10000, density=0.02, format="csr",
                  random_state=np.random.RandomState(2), dtype=np.float32)
    out = np.empty(m.shape, np.float32)

    def best(f, reps=3):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    t_native = best(lambda: densify_csr_rows(m, out=out))
    t_scipy = best(lambda: np.asarray(m.todense(), np.float32))
    assert t_native < t_scipy * 1.5, (t_native, t_scipy)
    print(f"densify 8192x10000: native {t_native*1e3:.1f}ms "
          f"scipy {t_scipy*1e3:.1f}ms ({t_scipy/t_native:.1f}x)")
