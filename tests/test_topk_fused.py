"""Fused serving scorer contracts (ISSUE 9 tentpole).

The kernel's promise is strict: `topk_fused` == masked-matmul + `lax.top_k`
with scores BITWISE equal and indices tie-exact — including the ugly corners
(all rows invalid, k > n_valid, duplicate scores, tail-padded corpora).
`impl="pallas", interpret=True` exercises the kernel's own selection network
on CPU; `impl="jnp"` is the off-TPU serving path. Both must match the oracle,
so both are parametrized through the edge cases. On top: quantized-corpus
build/gate/bytes contracts, the sharded scorer vs single-device parity on the
conftest-provided 8-device CPU mesh, and the single-eps normalize regression.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.ops.normalize import (NORMALIZE_EPS,
                                                           l2_normalize)
from dae_rnn_news_recommendation_tpu.ops.topk_fused import topk_fused
from dae_rnn_news_recommendation_tpu.parallel import get_mesh, shard_rows
from dae_rnn_news_recommendation_tpu.serve import (ServingCorpus,
                                                   make_serve_fn,
                                                   make_sharded_serve_fn,
                                                   quantize_corpus)

# interpret-mode kernel with a small panel so several grid steps run
KERNEL = dict(impl="pallas", interpret=True, block=128)


def _oracle(queries, emb, valid, k, scales=None):
    """Raw masked-matmul + lax.top_k — the acceptance oracle, built from jax
    primitives only (no code shared with ops/topk_fused)."""
    scores = jnp.asarray(queries, jnp.float32) @ jnp.asarray(
        emb).astype(jnp.float32).T
    if scales is not None:
        scores = scores * jnp.asarray(scales, jnp.float32)[None, :]
    scores = jnp.where(jnp.asarray(valid)[None, :] > 0, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _case(b=9, n=300, d=40, n_valid=None, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((b, d), dtype=np.float32)
    e = rng.standard_normal((n, d), dtype=np.float32)
    valid = np.zeros(n, np.float32)
    valid[:n if n_valid is None else n_valid] = 1.0
    return q, e, valid


def _assert_matches_oracle(q, e, valid, k, scales=None, **kw):
    s, i = jax.device_get(topk_fused(jnp.asarray(q), jnp.asarray(e),
                                     jnp.asarray(valid), k, scales=None
                                     if scales is None else
                                     jnp.asarray(scales), **kw))
    es, ei = jax.device_get(_oracle(q, e, valid, k, scales))
    np.testing.assert_array_equal(s, np.asarray(es))   # bitwise, not allclose
    np.testing.assert_array_equal(i, np.asarray(ei))


# ------------------------------------------------------------ kernel parity

def test_interpret_kernel_matches_lax_topk_bitwise():
    q, e, valid = _case(b=9, n=300, d=40)   # N=300: tail-padded to 384
    _assert_matches_oracle(q, e, valid, 7, **KERNEL)


def test_jnp_fallback_matches_lax_topk_at_record_shapes():
    # the off-TPU serving path at bench-record shapes (CPU corpus size)
    q, e, valid = _case(b=64, n=1024, d=50, seed=4)
    _assert_matches_oracle(q, e, valid, 10, impl="jnp")


def test_interpret_kernel_multi_query_block():
    # bq=8 forces the query-block grid axis to step too
    q, e, valid = _case(b=20, n=256, d=16, seed=5)
    _assert_matches_oracle(q, e, valid, 5, bq=8, **KERNEL)


@pytest.mark.parametrize("impl_kw", [KERNEL, dict(impl="jnp")],
                         ids=["pallas-interpret", "jnp"])
class TestEdgeCases:
    """Both implementations through the same corners, same oracle."""

    def test_all_rows_invalid(self, impl_kw):
        q, e, valid = _case(b=4, n=160, d=12)
        valid[:] = 0.0
        # lax.top_k on an all--inf row returns indices 0..k-1: -inf ties
        # break by ascending index, and the kernel must reproduce that
        s, i = jax.device_get(topk_fused(jnp.asarray(q), jnp.asarray(e),
                                         jnp.asarray(valid), 6, **impl_kw))
        assert np.all(np.isneginf(s))
        np.testing.assert_array_equal(i, np.tile(np.arange(6), (4, 1)))

    def test_k_exceeds_n_valid(self, impl_kw):
        q, e, valid = _case(b=5, n=200, d=12, n_valid=3, seed=1)
        _assert_matches_oracle(q, e, valid, 8, **impl_kw)
        s, i = jax.device_get(topk_fused(jnp.asarray(q), jnp.asarray(e),
                                         jnp.asarray(valid), 8, **impl_kw))
        assert np.all(i[:, :3] < 3)          # the real rows come first
        assert np.all(np.isneginf(s[:, 3:]))  # then -inf tie-filler

    def test_duplicate_scores_tie_break_by_ascending_index(self, impl_kw):
        rng = np.random.default_rng(2)
        q = rng.standard_normal((6, 16)).astype(np.float32)
        base = rng.standard_normal((40, 16)).astype(np.float32)
        e = np.concatenate([base, base, base])  # every score appears 3x
        valid = np.ones(len(e), np.float32)
        _assert_matches_oracle(q, e, valid, 9, **impl_kw)

    def test_int8_scales_parity(self, impl_kw):
        q, e, valid = _case(b=6, n=256, d=24, seed=3)
        eq, scales = quantize_corpus(jnp.asarray(e), "int8")
        _assert_matches_oracle(q, np.asarray(eq), valid, 7,
                               scales=np.asarray(scales), **impl_kw)

    def test_tail_pad_rows_stay_masked(self, impl_kw):
        # N not a multiple of the panel: the pad rows the kernel (or the
        # serve graph's block_indices) appends must never be returned while
        # any real row remains
        q, e, valid = _case(b=7, n=130, d=12, seed=6)
        _assert_matches_oracle(q, e, valid, 10, **impl_kw)
        _, i = jax.device_get(topk_fused(jnp.asarray(q), jnp.asarray(e),
                                         jnp.asarray(valid), 10, **impl_kw))
        assert np.all(i < 130)


def test_k_bounds_are_validated():
    q, e, valid = _case(b=2, n=32, d=8)
    with pytest.raises(ValueError, match="outside"):
        topk_fused(jnp.asarray(q), jnp.asarray(e), jnp.asarray(valid), 0)
    with pytest.raises(ValueError, match="outside"):
        topk_fused(jnp.asarray(q), jnp.asarray(e), jnp.asarray(valid), 33)


# ------------------------------------------------------- quantized corpus

N, F, D = 64, 24, 8


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(7), config)
    articles = np.random.default_rng(7).random((N, F), dtype=np.float32)
    return config, params, articles


def _corpus(config, params, articles, **kw):
    corpus = ServingCorpus(config, block=16, **kw)
    corpus.swap(params, articles, note="build")
    return corpus


def test_quantized_corpus_builds_and_passes_the_gate(setup):
    config, params, articles = setup
    slots = {}
    for dtype in ("float32", "bfloat16", "int8"):
        corpus = _corpus(config, params, articles, corpus_dtype=dtype)
        assert corpus.version == 1, f"{dtype} build failed its health gate"
        slot = corpus.active
        assert slot.dtype == dtype
        assert (slot.scales is not None) == (dtype == "int8")
        slots[dtype] = slot
    # the whole point of quantizing: strictly smaller resident footprint
    assert (slots["int8"].resident_bytes()
            < slots["bfloat16"].resident_bytes()
            < slots["float32"].resident_bytes())
    # (the bench-corpus D=500 ratio claim — int8 <= 0.35x fp32 — is asserted
    # on TPU by evidence/run.py; at this fixture's D=8 the per-row scale
    # overhead dominates, so only the ordering is pinned here)


@pytest.mark.parametrize("dtype,min_recall", [("bfloat16", 0.95),
                                              ("int8", 0.8)])
def test_quantized_ranking_recall_vs_fp32(setup, dtype, min_recall):
    # D=8 is brutally low-dimensional for quantization (bench's D=500 corpus
    # measures 0.997/0.987); these floors catch broken dequant, not drift
    config, params, articles = setup
    fp32 = _corpus(config, params, articles).active
    slot = _corpus(config, params, articles, corpus_dtype=dtype).active
    fn = make_serve_fn(config, 5)
    queries = articles[:16]
    _, base = jax.device_get(fn(params, fp32.emb, fp32.valid, fp32.scales,
                                queries))
    _, got = jax.device_get(fn(params, slot.emb, slot.valid, slot.scales,
                               queries))
    recall = np.mean([len(set(a) & set(b)) / 5.0
                      for a, b in zip(np.asarray(base), np.asarray(got))])
    assert recall >= min_recall, f"{dtype} recall@5 {recall:.3f}"


def test_service_serves_from_an_int8_corpus(setup):
    from dae_rnn_news_recommendation_tpu.serve import RecommendationService

    config, params, articles = setup
    corpus = _corpus(config, params, articles, corpus_dtype="int8")
    svc = RecommendationService(params, config, corpus, top_k=5, max_batch=8)
    svc.warmup()
    try:
        reply = svc.submit(articles[11], deadline_s=10.0).result(timeout=10.0)
        assert reply.ok and reply.indices[0] == 11
    finally:
        svc.stop()


# --------------------------------------------------------- sharded scoring

def test_sharded_serve_matches_single_device(setup):
    config, params, articles = setup
    corpus = _corpus(config, params, articles)   # n_pad=64: 16 rows/device
    slot = corpus.active
    mesh = get_mesh(4)
    queries = jnp.asarray(articles[:6])
    s1, i1 = jax.device_get(make_serve_fn(config, 5)(
        params, slot.emb, slot.valid, slot.scales, queries))
    emb, valid = shard_rows((slot.emb, slot.valid), mesh)
    s2, i2 = jax.device_get(make_sharded_serve_fn(config, 5, mesh)(
        params, emb, valid, None, queries))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)


def test_sharded_serve_int8_matches_single_device(setup):
    config, params, articles = setup
    corpus = _corpus(config, params, articles, corpus_dtype="int8")
    slot = corpus.active
    mesh = get_mesh(4)
    queries = jnp.asarray(articles[:6])
    s1, i1 = jax.device_get(make_serve_fn(config, 5)(
        params, slot.emb, slot.valid, slot.scales, queries))
    emb, valid, scales = shard_rows((slot.emb, slot.valid, slot.scales), mesh)
    s2, i2 = jax.device_get(make_sharded_serve_fn(config, 5, mesh)(
        params, emb, valid, scales, queries))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-6)


def test_sharded_serve_rejects_sub_k_shards(setup):
    config, params, articles = setup
    corpus = _corpus(config, params, articles)
    slot = corpus.active
    mesh = get_mesh(8)   # 8 rows/device < k=10
    with pytest.raises(AssertionError, match="shard rows"):
        make_sharded_serve_fn(config, 10, mesh)(
            params, slot.emb, slot.valid, None, jnp.asarray(articles[:2]))


# --------------------------------------------------- normalize eps pinning

def test_l2_normalize_eps_is_pinned():
    """Pre-r09 the repo carried THREE L2-normalize implementations with two
    eps values (serve 1e-9 divide-form vs losses/ring 1e-12 tf-form) — cosine
    scores differed between train and serve in the last mantissa bits. One
    helper, one eps, pinned here so a drive-by 'fix' can't fork them again."""
    assert NORMALIZE_EPS == 1e-12
    # tf.nn.l2_normalize form: zero rows map to zero, not NaN
    z = jax.device_get(l2_normalize(jnp.zeros((3, 5))))
    np.testing.assert_array_equal(np.asarray(z), np.zeros((3, 5)))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 6)),
                    jnp.float32)
    u = jax.device_get(l2_normalize(x))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(u), axis=1),
                               1.0, rtol=1e-6)


def test_losses_and_ring_share_the_one_normalize():
    from dae_rnn_news_recommendation_tpu.ops import losses
    from dae_rnn_news_recommendation_tpu.parallel import ring

    assert losses._l2_normalize is l2_normalize
    x = jnp.asarray(np.random.default_rng(1).standard_normal((4, 6)),
                    jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ring._l2_normalize_rows(x))),
        np.asarray(jax.device_get(l2_normalize(x, axis=1))))
