"""Streaming blockwise AUROC vs the full-matrix sklearn oracle
(eval/plots.py:related_unrelated_auroc, itself the reference helpers.py:99-101
twin). The streaming path must agree to bin-quantization tolerance while never
materializing the N x N similarity matrix."""

import numpy as np
import pytest

from dae_rnn_news_recommendation_tpu.eval import (
    pairwise_similarity, related_unrelated_auroc, streaming_auroc)
from dae_rnn_news_recommendation_tpu.eval.streaming_auroc import (
    auroc_from_histograms)


def _clustered_embeddings(rng, n=300, d=16, n_classes=5, missing_frac=0.1):
    labels = rng.integers(0, n_classes, n)
    centers = rng.normal(size=(n_classes, d)) * 2.0
    x = centers[labels] + rng.normal(size=(n, d))
    labels = labels.astype(np.int64)
    labels[rng.uniform(size=n) < missing_frac] = -1
    return x.astype(np.float32), labels


def _oracle(x, labels):
    sim = pairwise_similarity(x, metric="cosine", set_diagonal_zero=False)
    return related_unrelated_auroc(labels, sim)


def test_matches_full_matrix_oracle(rng):
    x, labels = _clustered_embeddings(rng)
    ref = _oracle(x, labels)
    got = streaming_auroc(x, labels, block=64)
    assert abs(ref - got) < 2e-3, (ref, got)
    assert got > 0.7  # clustered data: the metric is meaningfully above chance


def test_block_size_invariance(rng):
    x, labels = _clustered_embeddings(rng, n=200)
    results = [streaming_auroc(x, labels, block=b) for b in (32, 100, 256, 512)]
    for r in results[1:]:
        assert abs(results[0] - r) < 1e-9  # same bins -> identical histograms


def test_missing_labels_excluded(rng):
    """Rows with label < 0 contribute no pairs: AUROC equals the filtered subset's."""
    x, labels = _clustered_embeddings(rng, n=150, missing_frac=0.0)
    labels2 = labels.copy()
    drop = rng.uniform(size=len(labels)) < 0.3
    labels2[drop] = -1
    got = streaming_auroc(x, labels2, block=64)
    ref = streaming_auroc(x[~drop], labels2[~drop], block=64)
    assert abs(got - ref) < 1e-9


def test_degenerate_label_structure(rng):
    x, _ = _clustered_embeddings(rng, n=50)
    assert np.isnan(streaming_auroc(x, np.zeros(50)))        # no unrelated pairs
    assert np.isnan(streaming_auroc(x, np.arange(50)))       # no related pairs
    assert np.isnan(streaming_auroc(x, np.full(50, -1)))     # all missing


def test_linear_kernel_requires_range(rng):
    x, labels = _clustered_embeddings(rng, n=60)
    with pytest.raises(ValueError, match="value_range"):
        streaming_auroc(x, labels, metric="linear kernel")
    got = streaming_auroc(x, labels, metric="linear kernel",
                          value_range=(-300.0, 300.0), bins=262144, block=64)
    sim = pairwise_similarity(x, metric="linear kernel", set_diagonal_zero=False)
    ref = related_unrelated_auroc(labels, sim)
    assert abs(ref - got) < 5e-3


def test_auroc_from_histograms_exact():
    """Hand-computable case: related all in the top bin, unrelated all below."""
    rel = np.array([0.0, 0.0, 4.0])
    unrel = np.array([3.0, 0.0, 0.0])
    assert auroc_from_histograms(rel, unrel) == 1.0
    # complete overlap in one bin -> ties count half
    assert auroc_from_histograms(np.array([5.0]), np.array([7.0])) == 0.5


def test_out_of_range_scores_raise(rng):
    """Silent edge-bin clipping would bias the statistic — must raise instead."""
    x, labels = _clustered_embeddings(rng, n=60)
    with pytest.raises(ValueError, match="outside value_range"):
        streaming_auroc(x, labels, metric="linear kernel",
                        value_range=(-0.01, 0.01), block=64)


def test_64bit_hash_labels(rng):
    """Labels are remapped to contiguous int32: 64-bit hashes that collide in the
    low 32 bits must still compare as distinct."""
    x, small = _clustered_embeddings(rng, n=120, missing_frac=0.0)
    big = small.astype(np.int64) + (small.astype(np.int64) << 33)  # same low bits
    ref = streaming_auroc(x, small, block=64)
    got = streaming_auroc(x, big, block=64)
    assert abs(ref - got) < 1e-12
    # two labels identical mod 2^32 but different values -> must stay unrelated
    lab = np.array([7, 7, 7 + 2**33, 7 + 2**33], np.int64)
    xs = np.concatenate([np.eye(2, dtype=np.float32)[[0, 0]],
                         np.eye(2, dtype=np.float32)[[1, 1]]])
    assert streaming_auroc(xs + 0.01, lab, block=4) > 0.99


def test_perfect_separation():
    """Two orthogonal direction clusters: related cosine ~1, unrelated ~0."""
    rng = np.random.default_rng(0)
    d = 8
    e0, e1 = np.zeros(d, np.float32), np.zeros(d, np.float32)
    e0[0] = e1[1] = 1.0
    x = np.concatenate([e0 + rng.normal(size=(4, d)).astype(np.float32) * 0.01,
                        e1 + rng.normal(size=(4, d)).astype(np.float32) * 0.01])
    labels = np.array([0] * 4 + [1] * 4)
    assert streaming_auroc(x, labels, block=4) > 0.99


def test_sparse_input_matches_dense(rng):
    """scipy sparse rows densify blockwise; result identical to the dense path."""
    import scipy.sparse as sp

    x, labels = _clustered_embeddings(rng, n=150)
    x[x < 0.5] = 0.0  # sparsify
    xs = sp.csr_matrix(x)
    ref = streaming_auroc(x, labels, block=64)
    got = streaming_auroc(xs, labels, block=64)
    assert abs(ref - got) < 1e-6  # reciprocal-multiply vs divide rounding
    # ragged final block exercises the per-block padding path
    got2 = streaming_auroc(xs, labels, block=47)
    assert abs(ref - got2) < 1e-6


def test_multi_label_single_sweep_matches_separate_calls(rng):
    """[L, N] labels score L label kinds in one sweep, matching L single calls."""
    x, labels_a = _clustered_embeddings(rng, n=150)
    labels_b = rng.integers(0, 3, 150).astype(np.int64)
    both = streaming_auroc(x, np.stack([labels_a, labels_b]), block=64)
    assert isinstance(both, list) and len(both) == 2
    assert abs(both[0] - streaming_auroc(x, labels_a, block=64)) < 1e-12
    assert abs(both[1] - streaming_auroc(x, labels_b, block=64)) < 1e-12
    # histograms come back stacked
    _, hr, hu, edges = streaming_auroc(x, np.stack([labels_a, labels_b]),
                                       block=64, return_histograms=True)
    assert hr.shape[0] == 2 and hu.shape[0] == 2


class TestRingStreamingAuroc:
    """Mesh-distributed sweep must match the single-device path bit-for-bit
    (same binning, same pair semantics, exact counting via split accumulators)."""

    def _mesh(self):
        from dae_rnn_news_recommendation_tpu.parallel import get_mesh
        return get_mesh(8)

    def test_matches_single_device(self, rng):
        from dae_rnn_news_recommendation_tpu.eval import (
            ring_streaming_auroc, streaming_auroc)

        x = rng.normal(size=(96, 12)).astype(np.float32)
        labels = rng.integers(0, 5, 96)
        want = streaming_auroc(x, labels, bins=512)
        got = ring_streaming_auroc(x, labels, self._mesh(), bins=512)
        assert got == pytest.approx(want, abs=0)  # identical histograms

    def test_multi_label_and_histograms(self, rng):
        from dae_rnn_news_recommendation_tpu.eval import (
            ring_streaming_auroc, streaming_auroc)

        x = rng.normal(size=(64, 8)).astype(np.float32)
        lab = np.stack([rng.integers(0, 4, 64),
                        np.where(rng.uniform(size=64) < 0.3, -1,
                                 rng.integers(0, 3, 64))])
        want, w_rel, w_unrel, w_edges = streaming_auroc(
            x, lab, bins=256, return_histograms=True)
        got, g_rel, g_unrel, g_edges = ring_streaming_auroc(
            x, lab, self._mesh(), bins=256, return_histograms=True)
        np.testing.assert_array_equal(g_rel, w_rel)
        np.testing.assert_array_equal(g_unrel, w_unrel)
        np.testing.assert_allclose(g_edges, w_edges)
        assert got == pytest.approx(want, abs=0)

    def test_ragged_rows_padded(self, rng):
        """N not divisible by the mesh: padded rows must contribute nothing."""
        from dae_rnn_news_recommendation_tpu.eval import (
            ring_streaming_auroc, streaming_auroc)

        x = rng.normal(size=(37, 6)).astype(np.float32)
        labels = rng.integers(0, 3, 37)
        want = streaming_auroc(x, labels, bins=128)
        got = ring_streaming_auroc(x, labels, self._mesh(), bins=128)
        assert got == pytest.approx(want, abs=0)

    def test_out_of_range_raises(self, rng):
        from dae_rnn_news_recommendation_tpu.eval import ring_streaming_auroc

        x = rng.normal(size=(32, 4)).astype(np.float32) * 10
        labels = rng.integers(0, 3, 32)
        with pytest.raises(ValueError, match="value_range"):
            ring_streaming_auroc(x, labels, self._mesh(),
                                 metric="linear kernel", value_range=(-1, 1))

    def test_odd_mesh_matches(self, rng):
        """Odd device count exercises the no-antipodal-split branch of the
        triangular ring schedule."""
        from dae_rnn_news_recommendation_tpu.eval import (
            ring_streaming_auroc, streaming_auroc)
        from dae_rnn_news_recommendation_tpu.parallel import get_mesh

        x = rng.normal(size=(55, 7)).astype(np.float32)
        labels = rng.integers(0, 4, 55)
        want = streaming_auroc(x, labels, bins=128)
        got = ring_streaming_auroc(x, labels, get_mesh(5), bins=128)
        assert got == pytest.approx(want, abs=0)
