"""Legacy image datasets + driver (reference autoencoder/datasets.py and
run_autoencoder.py — the latter broken upstream, SURVEY §2.3.7; ours must run)."""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from dae_rnn_news_recommendation_tpu.data.image_datasets import (
    CIFAR_FEATURES, MNIST_FEATURES, load_cifar10_dataset, load_mnist_dataset,
    read_idx, synthetic_digit_images)


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


# ---------------------------------------------------------------- synthetic path

def test_mnist_synthetic_supervised_shapes():
    trX, trY, vlX, vlY, teX, teY = load_mnist_dataset(
        data_dir="does_not_exist/", synthetic_sizes=(50, 10, 20))
    assert trX.shape == (50, MNIST_FEATURES) and trY.shape == (50, 10)
    assert vlX.shape == (10, MNIST_FEATURES) and vlY.shape == (10, 10)
    assert teX.shape == (20, MNIST_FEATURES) and teY.shape == (20, 10)
    assert trX.dtype == np.float32
    assert trX.min() >= 0.0 and trX.max() <= 1.0
    np.testing.assert_allclose(trY.sum(axis=1), 1.0)  # valid one-hot


def test_mnist_synthetic_int_labels_and_unsupervised():
    tr6 = load_mnist_dataset(one_hot=False, data_dir="does_not_exist/",
                             synthetic_sizes=(30, 5, 5))
    assert tr6[1].shape == (30,) and tr6[1].dtype == np.int64
    trX, vlX, teX = load_mnist_dataset(mode="unsupervised",
                                       data_dir="does_not_exist/",
                                       synthetic_sizes=(30, 5, 5))
    assert trX.shape == (30, MNIST_FEATURES)
    np.testing.assert_array_equal(trX, tr6[0])  # same seed -> same data


def test_synthetic_images_are_class_structured():
    """Same-class images must be more similar than cross-class ones (the loaders'
    stand-in has to be learnable for the driver's DAE to produce signal)."""
    X, y = synthetic_digit_images(200, seed=1)
    X = X - X.mean(axis=0)
    same, diff = [], []
    for c in range(10):
        mc = X[y == c]
        if len(mc) > 1:
            same.append(np.corrcoef(mc[0], mc[1])[0, 1])
        other = X[y != c]
        diff.append(np.corrcoef(mc[0], other[0])[0, 1])
    assert np.mean(same) > np.mean(diff) + 0.2


# ---------------------------------------------------------------- real-format parsing

def _write_idx_images(path, arr_uint8, gz=True):
    n, rows, cols = arr_uint8.shape
    payload = struct.pack(">IIII", 2051, n, rows, cols) + arr_uint8.tobytes()
    opener = gzip.open if gz else open
    with opener(path + (".gz" if gz else ""), "wb") as f:
        f.write(payload)


def _write_idx_labels(path, labels_uint8, gz=True):
    payload = struct.pack(">II", 2049, len(labels_uint8)) + labels_uint8.tobytes()
    opener = gzip.open if gz else open
    with opener(path + (".gz" if gz else ""), "wb") as f:
        f.write(payload)


def test_mnist_idx_round_trip(workdir):
    d = str(workdir / "MNIST_data")
    os.makedirs(d)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(40, 28, 28), dtype=np.uint8)
    labs = rng.integers(0, 10, size=40, dtype=np.uint8)
    te_imgs = rng.integers(0, 256, size=(10, 28, 28), dtype=np.uint8)
    te_labs = rng.integers(0, 10, size=10, dtype=np.uint8)
    _write_idx_images(os.path.join(d, "train-images-idx3-ubyte"), imgs)
    _write_idx_labels(os.path.join(d, "train-labels-idx1-ubyte"), labs)
    _write_idx_images(os.path.join(d, "t10k-images-idx3-ubyte"), te_imgs, gz=False)
    _write_idx_labels(os.path.join(d, "t10k-labels-idx1-ubyte"), te_labs, gz=False)

    trX, trY, vlX, vlY, teX, teY = load_mnist_dataset(one_hot=False, data_dir=d)
    # n_val = min(5000, 40//10) = 4 -> 36 train / 4 validation
    assert trX.shape == (36, 784) and vlX.shape == (4, 784)
    assert teX.shape == (10, 784)
    np.testing.assert_allclose(trX[0], imgs[0].reshape(-1) / 255.0)
    np.testing.assert_array_equal(trY, labs[:36])
    np.testing.assert_array_equal(vlY, labs[36:])
    np.testing.assert_array_equal(teY, te_labs)


def test_read_idx_rejects_bad_magic(workdir):
    path = str(workdir / "bad")
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 1234, 0))
    with pytest.raises(ValueError, match="magic"):
        read_idx(path)


def test_cifar_pickle_round_trip(workdir):
    d = str(workdir / "cifar-10-batches-py")
    os.makedirs(d)
    rng = np.random.default_rng(0)
    tr1 = {b"data": rng.integers(0, 256, (20, CIFAR_FEATURES), dtype=np.uint8),
           b"labels": list(rng.integers(0, 10, 20))}
    tr2 = {b"data": rng.integers(0, 256, (15, CIFAR_FEATURES), dtype=np.uint8),
           b"labels": list(rng.integers(0, 10, 15))}
    te = {b"data": rng.integers(0, 256, (10, CIFAR_FEATURES), dtype=np.uint8),
          b"labels": list(rng.integers(0, 10, 10))}
    for name, batch in (("data_batch_1", tr1), ("data_batch_2", tr2),
                        ("test_batch", te), ("readme.html", None),
                        ("batches.meta", None)):
        with open(os.path.join(d, name), "wb") as f:
            if batch is not None:
                pickle.dump(batch, f)

    trX, trY, teX, teY = load_cifar10_dataset(d)
    assert trX.shape == (35, CIFAR_FEATURES) and teX.shape == (10, CIFAR_FEATURES)
    assert trX.max() <= 1.0
    np.testing.assert_allclose(trX[0], tr1[b"data"][0] / 255.0, atol=1e-6)
    np.testing.assert_array_equal(trY[:20], tr1[b"labels"])
    np.testing.assert_array_equal(teY, te[b"labels"])

    trX_u, teX_u = load_cifar10_dataset(d, mode="unsupervised")
    np.testing.assert_array_equal(trX_u, trX)


def test_cifar_synthetic_fallback():
    trX, trY, teX, teY = load_cifar10_dataset("", synthetic_sizes=(25, 10))
    assert trX.shape == (25, CIFAR_FEATURES) and teX.shape == (10, CIFAR_FEATURES)
    assert 0.0 <= trX.min() and trX.max() <= 1.0


# ---------------------------------------------------------------- legacy driver e2e

def test_run_autoencoder_driver_mnist(workdir):
    """The reference's legacy driver crashes on ctor kwargs (SURVEY §2.3.7);
    ours must train, encode, and emit weight images end to end."""
    from dae_rnn_news_recommendation_tpu.cli.run_autoencoder import main

    dae = main(["--dataset", "mnist", "--mnist_dir", "none/", "--n_components", "16",
                "--num_epochs", "2", "--batch_size", "25", "--opt", "ada_grad",
                "--learning_rate", "0.1", "--corr_type", "masking",
                "--corr_frac", "0.3", "--encode_train", "--weight_images", "3",
                "--seed", "0"])
    assert dae.n_components == 16
    enc = np.load(os.path.join(dae.data_dir, "train.npy"))
    assert enc.shape[1] == 16 and np.isfinite(enc).all()
    img_dir = os.path.join(dae.data_dir, "img/")
    assert len([f for f in os.listdir(img_dir) if f.endswith(".png")]) == 3


def test_run_autoencoder_driver_cifar(workdir):
    from dae_rnn_news_recommendation_tpu.cli.run_autoencoder import main

    dae = main(["--dataset", "cifar10", "--n_components", "8",
                "--num_epochs", "1", "--batch_size", "50", "--seed", "1"])
    assert dae.n_components == 8
    assert dae.config.n_features == CIFAR_FEATURES
