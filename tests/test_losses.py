"""Oracle tests for reconstruction losses (reference test_triplet_loss_utils.py:205-234
style: all three losses x {unweighted, weighted} against NumPy formulas)."""

import numpy as np
import pytest
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import losses as L

B, F = 7, 12
_EPS = 1e-16


def _np_l2_normalize(x, eps=1e-12):
    sq = (x**2).sum(1, keepdims=True)
    return x / np.sqrt(np.maximum(sq, eps))


def _oracle_per_row(x, d, loss_func):
    if loss_func == "cross_entropy":
        return -(x * np.log(d + _EPS) + (1 - x) * np.log(1 - d + _EPS)).sum(1)
    if loss_func == "mean_squared":
        return ((x - d) ** 2).sum(1)
    return -(_np_l2_normalize(x) * _np_l2_normalize(d)).sum(1)


@pytest.mark.parametrize("loss_func", L.LOSS_FUNCS)
@pytest.mark.parametrize("weighted", [False, True])
def test_weighted_loss(loss_func, weighted, rng):
    x = rng.uniform(0.01, 0.99, size=(B, F)).astype(np.float32)
    d = rng.uniform(0.01, 0.99, size=(B, F)).astype(np.float32)
    w = rng.uniform(0, 3, size=B).astype(np.float32) if weighted else None

    per_row = _oracle_per_row(x, d, loss_func)
    wts = w if w is not None else np.ones(B)
    expected = (per_row * wts).sum() / (wts.sum() + _EPS)

    got = L.weighted_loss(
        jnp.asarray(x), jnp.asarray(d), loss_func,
        weight=None if w is None else jnp.asarray(w),
    )
    np.testing.assert_allclose(float(got), expected, rtol=1e-5)


@pytest.mark.parametrize("loss_func", L.LOSS_FUNCS)
def test_weighted_loss_padding(loss_func, rng):
    """Padded rows (weight forced to 0 via row_valid) must not move the loss."""
    x = rng.uniform(0.01, 0.99, size=(B, F)).astype(np.float32)
    d = rng.uniform(0.01, 0.99, size=(B, F)).astype(np.float32)
    pad = 4
    xp = np.concatenate([x, np.zeros((pad, F), np.float32)])
    dp = np.concatenate([d, rng.uniform(0.01, 0.99, size=(pad, F)).astype(np.float32)])
    valid = np.concatenate([np.ones(B), np.zeros(pad)]).astype(np.float32)

    base = L.weighted_loss(jnp.asarray(x), jnp.asarray(d), loss_func)
    padded = L.weighted_loss(
        jnp.asarray(xp), jnp.asarray(dp), loss_func, row_valid=jnp.asarray(valid)
    )
    np.testing.assert_allclose(float(padded), float(base), rtol=1e-5)


def test_zero_weight_is_safe():
    x = jnp.ones((3, 4)) * 0.5
    got = L.weighted_loss(x, x, "mean_squared", weight=jnp.zeros(3))
    assert np.isfinite(float(got))
    assert float(got) == 0.0
