"""reliability/ledger.py: the shared exactly-one-outcome and version-ledger
audits every chaos harness now leans on. The failure modes these must catch
are exactly the ones a hedged fleet can smuggle past aggregate counters — a
request that resolves twice (double-count) and one that never resolves
(silent drop) — plus the rollout-only legality of corpus version reverts."""

import pytest

from dae_rnn_news_recommendation_tpu.reliability.ledger import (
    OutcomeLedger, audit_outcome_counts, audit_shard_reads,
    audit_version_ledger)


# ------------------------------------------------------------ OutcomeLedger

def test_clean_ledger_audits_empty():
    led = OutcomeLedger()
    for i in range(4):
        led.submit(i, t_submit=float(i))
    for i in range(4):
        led.resolve(i, "ok" if i % 2 else "shed", replica="r0")
    assert led.audit() == []
    assert led.n_submitted == 4
    assert led.counts() == {"ok": 2, "shed": 2}


def test_double_outcome_is_caught():
    """The hedge failure mode: both the primary and the hedge surface a
    terminal decision for the same request."""
    led = OutcomeLedger()
    led.submit(7)
    led.resolve(7, "ok", replica="r0")
    led.resolve(7, "ok", replica="r1")   # the losing hedge, wrongly surfaced
    problems = led.audit()
    assert len(problems) == 1
    assert "double outcome" in problems[0] and "7" in problems[0]
    # first outcome wins the counts; the duplicate is evidence, not traffic
    assert led.counts() == {"ok": 1}


def test_lost_request_is_caught():
    """The deadlock/silent-drop failure mode: submitted, never resolved."""
    led = OutcomeLedger()
    led.submit("a")
    led.submit("b")
    led.resolve("a", "error")
    problems = led.audit()
    assert len(problems) == 1
    assert "lost request" in problems[0] and "b" in problems[0]


def test_ghost_outcome_is_caught():
    led = OutcomeLedger()
    led.resolve("never-submitted", "ok")
    assert any("never submitted" in p for p in led.audit())


def test_resolve_never_raises_at_record_time():
    """A chaos run must capture misbehavior, not die on it."""
    led = OutcomeLedger()
    led.resolve("ghost", "ok")
    led.resolve("ghost", "shed")
    assert len(led.records) == 2


# ------------------------------------------------------ aggregate counting

def test_outcome_counts_balanced():
    assert audit_outcome_counts(10, 7, 2, 1) == []


def test_outcome_counts_leak_and_unresolved():
    problems = audit_outcome_counts(10, 7, 1, 1, n_unresolved=0)
    assert len(problems) == 1 and "outcome leak" in problems[0]
    problems = audit_outcome_counts(10, 7, 2, 0, n_unresolved=1)
    assert any("never resolved" in p for p in problems)
    assert not any("outcome leak" in p for p in problems)  # 7+2+0+1 == 10


# ---------------------------------------------------- version-ledger audit

def _promote(v, **kw):
    return {"version": v, "kind": "incremental", "ok": True,
            "gate": {"ok": True}, **kw}


def _rollback(active, error="gate refused"):
    return {"version": active, "kind": "incremental", "ok": False,
            "error": error, "active_version": active, "gate": None}


def test_version_ledger_clean_monotonic():
    versions, n_rb, problems = audit_version_ledger(
        [_promote(1), _promote(2), _promote(3)])
    assert versions == [1, 2, 3] and n_rb == 0 and problems == []


def test_version_ledger_skip_is_a_problem():
    _, _, problems = audit_version_ledger([_promote(1), _promote(3)])
    assert any("not +1" in p for p in problems)


def test_version_ledger_gateless_promote_is_a_problem():
    bad = _promote(1)
    bad["gate"] = {"ok": False}
    _, _, problems = audit_version_ledger([bad])
    assert any("without gate ok" in p for p in problems)


def test_version_ledger_rollback_keeps_verified_version():
    versions, n_rb, problems = audit_version_ledger(
        [_promote(1), _rollback(1), _promote(2)])
    assert versions == [1, 2] and n_rb == 1 and problems == []


def test_version_ledger_injected_crash_must_recover():
    _, _, problems = audit_version_ledger(
        [_promote(1), _rollback(1, error="injected: swap crash")])
    assert any("injected swap crash not followed" in p for p in problems)
    # ...but an abandoned rollout is a legal terminal on the fleet path
    _, _, problems = audit_version_ledger(
        [_promote(1), _rollback(1, error="injected: swap crash")],
        allow_revert=True)
    assert problems == []


@pytest.mark.parametrize("allow", (False, True))
def test_version_ledger_revert_legality(allow):
    """The fleet-rollout move: promote v2, revert to v1, re-promote v2. Legal
    ONLY with allow_revert — the churn path must flag any revert record."""
    ledger = [
        _promote(1),
        _promote(2),
        {"version": 1, "kind": "revert", "ok": True, "revert": True,
         "from_version": 2},
        _promote(2),
    ]
    _, _, problems = audit_version_ledger(ledger, allow_revert=allow)
    if allow:
        assert problems == []
    else:
        assert any("unexpected revert" in p for p in problems)


def test_version_ledger_revert_to_unverified_version():
    ledger = [
        _promote(1),
        {"version": 5, "kind": "revert", "ok": True, "revert": True,
         "from_version": 1},
    ]
    _, _, problems = audit_version_ledger(ledger, allow_revert=True)
    assert any("never promoted" in p for p in problems)


def test_version_ledger_repeat_without_revert_is_a_problem():
    """A version number repeating WITHOUT an intervening revert is a torn
    serving line, not a rollback."""
    _, _, problems = audit_version_ledger(
        [_promote(1), _promote(2), _promote(2)], allow_revert=True)
    assert any("not +1" in p for p in problems)


# ------------------------------------- sharded ledger records (ISSUE 13)

def _shards(v, n=4):
    return {"n": n, "versions": [v] * n}


def test_version_ledger_sharded_promotes_clean():
    ledger = [_promote(1, shards=_shards(1)), _promote(2, shards=_shards(2))]
    versions, n_rb, problems = audit_version_ledger(ledger)
    assert versions == [1, 2] and n_rb == 0 and problems == []


def test_version_ledger_torn_shard_commit_is_caught():
    """The failure the two-phase commit exists to prevent: a promote whose
    per-shard stamps disagree means some shards flipped and some did not."""
    bad = _promote(2, shards={"n": 4, "versions": [2, 2, 1, 2]})
    _, _, problems = audit_version_ledger([_promote(1, shards=_shards(1)),
                                           bad])
    assert any("torn shard commit" in p for p in problems)
    # the same stamps also violate the promoted-version equality check
    assert any("commit must stamp every shard" in p for p in problems)


def test_version_ledger_cross_shard_skew_bound():
    """Stamps more than one version apart are drifted shards, flagged even
    on a record the other checks would pass over."""
    bad = _promote(3, shards={"n": 3, "versions": [3, 1, 3]})
    _, _, problems = audit_version_ledger(
        [_promote(1, shards=_shards(1)), _promote(2, shards=_shards(2)),
         bad])
    assert any("skew" in p for p in problems)


def test_version_ledger_recover_record_is_not_a_promote():
    """A recover record (lost shard re-materialized from the host mirror)
    is ok=True at an UNCHANGED version: it must neither bump the serving
    line nor count as a promote, and its shard stamps must match the
    recovered version."""
    ledger = [
        _promote(1, shards=_shards(1)),
        _promote(2, shards=_shards(2)),
        {"version": 2, "kind": "shard_degraded", "ok": False,
         "error": "shard loss: [1] quarantined (coverage 0.750)",
         "active_version": 2, "coverage": 0.75},
        {"version": 2, "kind": "recover", "ok": True, "recover": True,
         "recovered": [1], "shards": _shards(2)},
        _promote(3, shards=_shards(3)),
    ]
    versions, n_rb, problems = audit_version_ledger(ledger)
    assert versions == [1, 2, 3]  # recover did not enter the promote line
    assert n_rb == 1              # the degrade record is the only not-ok
    assert problems == []


def test_version_ledger_recover_at_wrong_version_is_caught():
    ledger = [
        _promote(1, shards=_shards(1)),
        {"version": 2, "kind": "recover", "ok": True, "recover": True,
         "recovered": [0], "shards": _shards(2)},
    ]
    _, _, problems = audit_version_ledger(ledger)
    assert any("recovery must not move the version" in p for p in problems)
    assert any("never promoted" in p for p in problems)


# ------------------------------------------- torn-read audit (ISSUE 13)

def test_shard_reads_uniform_samples_pass():
    samples = [{"version": v, "shards": [v] * 8} for v in (1, 1, 2, 2, 3)]
    assert audit_shard_reads(samples) == []


def test_shard_reads_catch_torn_and_stale_and_staged():
    problems = audit_shard_reads([
        {"version": 2, "shards": [2, 2, 1, 2]},    # torn mix
        {"version": 3, "shards": [2, 2, 2, 2]},    # stale vs slot version
        {"version": 2, "shards": [-2, -2, -2, -2]},  # staged sentinel leaked
    ])
    assert any("torn cross-shard read" in p for p in problems)
    assert sum("!= slot version" in p for p in problems) >= 2


def test_shard_reads_empty_reader_cannot_vacuously_pass():
    assert any("never ran" in p for p in audit_shard_reads([]))
    assert any("no shard stamps" in p
               for p in audit_shard_reads([{"version": 1, "shards": []}]))


def test_partial_corpus_outcomes_counted_exactly_once_with_coverage():
    """Satellite: degraded partial_corpus replies flow through the same
    exactly-one-outcome ledger as healthy ones — each carries its coverage
    fraction, resolves exactly once, and a hedged double-resolve of a
    degraded reply is still caught."""
    led = OutcomeLedger()
    for i in range(6):
        led.submit(i)
    for i in range(4):
        led.resolve(i, "ok", coverage=1.0, partial=False)
    led.resolve(4, "ok", coverage=0.875, partial=True)
    led.resolve(5, "ok", coverage=0.875, partial=True)
    assert led.audit() == []
    assert led.counts() == {"ok": 6}
    partial = [r for r in led.records if r.get("partial")]
    assert len(partial) == 2
    assert all(0.0 < r["coverage"] < 1.0 for r in partial)
    # a duplicate resolve of a degraded reply is evidence, not traffic
    led.resolve(4, "ok", coverage=0.875, partial=True)
    assert any("double outcome" in p for p in led.audit())
    assert led.counts() == {"ok": 6}
