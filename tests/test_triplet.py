"""Oracle tests for triplet mining ops.

Modeled on the reference's autoencoder/tests/test_triplet_loss_utils.py: every op is
verified against a brute-force NumPy re-implementation (triple nested loops), across
num_classes in {1, 3, 5} (1 exercises the no-valid-triplet edge) — plus net-new
padded-batch cases for the XLA static-shape path.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import triplet as T

B, D = 10, 6
_EPS = 1e-16


def _rand(num_classes, rng, b=B):
    labels = rng.integers(0, num_classes, size=b).astype(np.int32)
    embed = rng.normal(size=(b, D)).astype(np.float32)
    return labels, embed


def _oracle_triplet_mask(labels):
    b = len(labels)
    m = np.zeros((b, b, b), dtype=bool)
    for i in range(b):
        for j in range(b):
            for k in range(b):
                distinct = i != j and i != k and j != k
                valid = labels[i] == labels[j] and labels[i] != labels[k]
                m[i, j, k] = distinct and valid
    return m


@pytest.mark.parametrize("num_classes", [1, 3, 5])
def test_anchor_positive_mask(num_classes, rng):
    labels, _ = _rand(num_classes, rng)
    expected = np.array(
        [[i != j and labels[i] == labels[j] for j in range(B)] for i in range(B)]
    )
    got = np.asarray(T.anchor_positive_mask(jnp.asarray(labels)))
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("num_classes", [1, 3, 5])
def test_anchor_negative_mask(num_classes, rng):
    labels, _ = _rand(num_classes, rng)
    expected = np.array([[labels[i] != labels[j] for j in range(B)] for i in range(B)])
    got = np.asarray(T.anchor_negative_mask(jnp.asarray(labels)))
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("num_classes", [1, 3, 5])
def test_triplet_mask(num_classes, rng):
    labels, _ = _rand(num_classes, rng)
    got = np.asarray(T.triplet_mask(jnp.asarray(labels)))
    np.testing.assert_array_equal(got, _oracle_triplet_mask(labels))


def _oracle_batch_all(labels, embed, pos_triplets_only):
    b = len(labels)
    dp = embed @ embed.T
    mask3 = _oracle_triplet_mask(labels)
    num_valid = mask3.sum()
    loss_sum = 0.0
    num_pos = 0
    weight = np.zeros(b)
    pos3 = np.zeros_like(mask3)
    for i in range(b):
        for j in range(b):
            for k in range(b):
                if not mask3[i, j, k]:
                    continue
                d = -dp[i, j] + dp[i, k]
                if d > _EPS:
                    num_pos += 1
                    pos3[i, j, k] = True
    use3 = pos3 if pos_triplets_only else mask3
    for i in range(b):
        for j in range(b):
            for k in range(b):
                if use3[i, j, k]:
                    d = -dp[i, j] + dp[i, k]
                    loss_sum += np.logaddexp(0.0, d)  # softplus
                    weight[i] += 1  # anchor
                    weight[j] += 1  # positive
                    weight[k] += 1  # negative
    num = num_pos if pos_triplets_only else num_valid
    loss = loss_sum / (num + _EPS)
    frac = num_pos / (num_valid + _EPS)
    return loss, weight, frac, num_pos


@pytest.mark.parametrize("num_classes", [1, 3, 5])
@pytest.mark.parametrize("pos_only", [False, True])
def test_batch_all_triplet_loss(num_classes, pos_only, rng):
    labels, embed = _rand(num_classes, rng)
    e_loss, e_w, e_frac, e_num = _oracle_batch_all(labels, embed, pos_only)
    loss, w, frac, num, _ = T.batch_all_triplet_loss(
        jnp.asarray(labels), jnp.asarray(embed), pos_triplets_only=pos_only
    )
    np.testing.assert_allclose(float(loss), e_loss, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w), e_w, rtol=1e-5)
    np.testing.assert_allclose(float(frac), e_frac, rtol=1e-5, atol=1e-7)
    assert int(num) == e_num


def _oracle_batch_hard(labels, embed):
    b = len(labels)
    dp = embed @ embed.T
    hardest_pos = np.zeros(b)
    hardest_neg = np.zeros(b)
    for i in range(b):
        mask_ap = np.array([i != j and labels[i] == labels[j] for j in range(b)])
        mask_an = np.array([labels[i] != labels[j] for j in range(b)])
        row_max = dp[i].max()
        shifted = dp[i] + row_max * (1.0 - mask_ap.astype(float))
        hardest_pos[i] = shifted.min()
        hardest_neg[i] = (mask_an.astype(float) * dp[i]).max()
    dist = np.maximum(hardest_neg - hardest_pos, 0.0)
    count = (dist > 0.0).astype(float)
    weight = count.copy()
    for r in range(b):
        for i in range(b):
            if dp[i, r] == hardest_pos[i]:
                weight[r] += count[i]
            if dp[i, r] == hardest_neg[i]:
                weight[r] += count[i]
    total = count.sum()
    loss = (np.logaddexp(0.0, dist) * count).sum() / (total + _EPS)
    return loss, weight, total / b, total


@pytest.mark.parametrize("num_classes", [1, 3, 5])
def test_batch_hard_triplet_loss(num_classes, rng):
    labels, embed = _rand(num_classes, rng)
    e_loss, e_w, e_frac, e_num = _oracle_batch_hard(labels, embed)
    loss, w, frac, num, extras = T.batch_hard_triplet_loss(
        jnp.asarray(labels), jnp.asarray(embed)
    )
    np.testing.assert_allclose(float(loss), e_loss, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w), e_w, rtol=1e-5)
    np.testing.assert_allclose(float(frac), e_frac, rtol=1e-5)
    np.testing.assert_allclose(float(num), e_num, rtol=1e-5)
    assert "hardest_positive_dotproduct" in extras


@pytest.mark.parametrize("num_classes", [3, 5])
def test_batch_all_padding_equivalence(num_classes, rng):
    """Padded rows must mine zero triplets: padded result == unpadded result."""
    labels, embed = _rand(num_classes, rng)
    pad = 6
    labels_p = np.concatenate([labels, np.full(pad, -1, np.int32)])
    embed_p = np.concatenate([embed, rng.normal(size=(pad, D)).astype(np.float32)])
    valid = np.concatenate([np.ones(B), np.zeros(pad)]).astype(np.float32)

    base = T.batch_all_triplet_loss(jnp.asarray(labels), jnp.asarray(embed))
    padded = T.batch_all_triplet_loss(
        jnp.asarray(labels_p), jnp.asarray(embed_p), row_valid=jnp.asarray(valid)
    )
    np.testing.assert_allclose(float(padded[0]), float(base[0]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(padded[1])[:B], np.asarray(base[1]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(padded[1])[B:], 0.0)
    np.testing.assert_allclose(float(padded[3]), float(base[3]))


@pytest.mark.parametrize("num_classes", [3, 5])
def test_batch_hard_padding_masks_rows(num_classes, rng):
    """Padded rows contribute no anchors and no data_weight."""
    labels, embed = _rand(num_classes, rng)
    pad = 6
    labels_p = np.concatenate([labels, np.full(pad, -1, np.int32)])
    # padded embeddings are exactly zero in the real model (encode(0) == 0)
    embed_p = np.concatenate([embed, np.zeros((pad, D), np.float32)])
    valid = np.concatenate([np.ones(B), np.zeros(pad)]).astype(np.float32)

    loss, w, frac, num, _ = T.batch_hard_triplet_loss(
        jnp.asarray(labels_p), jnp.asarray(embed_p), row_valid=jnp.asarray(valid)
    )
    np.testing.assert_allclose(np.asarray(w)[B:], 0.0)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("case", range(12))
def test_fuzz_mining_vs_oracle(case):
    """Seeded fuzz across random (B, D, n_classes, padding) extremes — minimal
    batches, D=1, all-unique labels, heavy padding — each checked against the
    brute-force oracles (padding by comparing to the oracle on the real rows,
    with padded embeddings zero as in the real model, encode(0) == 0)."""
    r = np.random.default_rng(1000 + case)
    b = int(r.integers(3, 25))
    d = int(r.integers(1, 17))
    n_classes = int(r.integers(1, b + 1))
    pad = int(r.integers(0, b // 2 + 1))
    labels = r.integers(0, n_classes, size=b).astype(np.int32)
    embed = r.normal(size=(b, d)).astype(np.float32)

    labels_p = np.concatenate([labels, np.full(pad, -1, np.int32)])
    embed_p = np.concatenate([embed, np.zeros((pad, d), np.float32)])
    valid = np.concatenate([np.ones(b), np.zeros(pad)]).astype(np.float32)

    pos_only = bool(case % 2)
    e_loss, e_w, e_frac, e_num = _oracle_batch_all(labels, embed, pos_only)
    loss, w, frac, num, _ = T.batch_all_triplet_loss(
        jnp.asarray(labels_p), jnp.asarray(embed_p),
        pos_triplets_only=pos_only, row_valid=jnp.asarray(valid))
    np.testing.assert_allclose(float(loss), e_loss, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w)[:b], e_w, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w)[b:], 0.0)
    np.testing.assert_allclose(float(frac), e_frac, rtol=1e-4, atol=1e-7)
    assert int(num) == e_num

    e_loss, e_w, e_frac, e_num = _oracle_batch_hard(labels, embed)
    loss, w, frac, num, _ = T.batch_hard_triplet_loss(
        jnp.asarray(labels_p), jnp.asarray(embed_p), row_valid=jnp.asarray(valid))
    np.testing.assert_allclose(float(loss), e_loss, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w)[:b], e_w, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(w)[b:], 0.0)
    np.testing.assert_allclose(float(frac), e_frac, rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(float(num), e_num, rtol=1e-5)


def test_precomputed_triplet_loss(rng):
    a = rng.normal(size=(B, D)).astype(np.float32)
    p = rng.normal(size=(B, D)).astype(np.float32)
    n = rng.normal(size=(B, D)).astype(np.float32)
    margin = (a * p - a * n).sum(1)
    expected = np.logaddexp(0.0, -margin).mean()
    got = T.precomputed_triplet_loss(jnp.asarray(a), jnp.asarray(p), jnp.asarray(n))
    np.testing.assert_allclose(float(got), expected, rtol=1e-5)


def test_precomputed_triplet_loss_padding(rng):
    a = rng.normal(size=(B, D)).astype(np.float32)
    p = rng.normal(size=(B, D)).astype(np.float32)
    n = rng.normal(size=(B, D)).astype(np.float32)
    valid = np.concatenate([np.ones(B - 3), np.zeros(3)]).astype(np.float32)
    margin = (a * p - a * n).sum(1)[: B - 3]
    expected = np.logaddexp(0.0, -margin).mean()
    got = T.precomputed_triplet_loss(
        jnp.asarray(a), jnp.asarray(p), jnp.asarray(n), row_valid=jnp.asarray(valid)
    )
    np.testing.assert_allclose(float(got), expected, rtol=1e-4)
