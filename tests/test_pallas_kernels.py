"""Pallas kernel tests. batch_all runs in interpreter mode against the XLA oracle
(ops/triplet.py, itself NumPy-oracle-tested in test_triplet.py). The masking
kernel's hardware PRNG is stubbed to zeros by the interpreter, so only its
structural properties are testable here; the statistical tests are TPU-gated and
were validated on a real v5e (see ops/pallas_kernels.py module docstring)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import triplet
from dae_rnn_news_recommendation_tpu.ops.pallas_kernels import (
    batch_all_triplet_loss_pallas, batch_hard_triplet_loss_pallas,
    masking_noise_pallas)

ON_TPU = jax.default_backend() == "tpu"
# compiled Mosaic requires tk % 128 == 0; the interpreter takes any tile
DEFAULT_TILES = (8, 128, 128) if ON_TPU else (8, 16, 16)


def _compare(labels, enc, pos_only, row_valid, tiles=DEFAULT_TILES):
    ref = triplet.batch_all_triplet_loss(labels, enc, pos_triplets_only=pos_only,
                                         row_valid=row_valid)
    got = batch_all_triplet_loss_pallas(labels, enc, pos_triplets_only=pos_only,
                                        row_valid=row_valid, tiles=tiles,
                                        interpret=not ON_TPU)
    np.testing.assert_allclose(float(ref[0]), float(got[0]), rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(ref[1]), np.asarray(got[1]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(ref[2]), float(got[2]), rtol=1e-5)
    np.testing.assert_allclose(float(ref[3]), float(got[3]), rtol=1e-5)


@pytest.mark.parametrize("n_classes", [1, 3, 5])
@pytest.mark.parametrize("pos_only", [False, True])
def test_batch_all_matches_xla_oracle(rng, n_classes, pos_only):
    b = 24
    labels = jnp.asarray(rng.integers(0, n_classes, b))
    enc = jnp.asarray(rng.normal(size=(b, 6)).astype(np.float32))
    _compare(labels, enc, pos_only, None)


def test_batch_all_row_valid_and_padding(rng):
    """Padding rows (row_valid=0) mine nothing; B not a tile multiple exercises
    the wrapper's pad-with-invalid path."""
    b = 21  # deliberately not a multiple of any tile
    labels = jnp.asarray(rng.integers(0, 4, b))
    enc = jnp.asarray(rng.normal(size=(b, 5)).astype(np.float32))
    rv = jnp.asarray((rng.uniform(size=b) < 0.7).astype(np.float32))
    for pos_only in (False, True):
        _compare(labels, enc, pos_only, rv)


def test_batch_all_tile_shapes(rng):
    """Result is tile-independent (grid decomposition is pure bookkeeping)."""
    b = 30
    labels = jnp.asarray(rng.integers(0, 3, b))
    enc = jnp.asarray(rng.normal(size=(b, 4)).astype(np.float32))
    tile_sets = ([(8, 128, 128), (16, 128, 128), (8, 256, 256)] if ON_TPU
                 else [(8, 8, 8), (8, 16, 16), (16, 16, 16)])
    results = [
        batch_all_triplet_loss_pallas(labels, enc, tiles=t, interpret=not ON_TPU)
        for t in tile_sets
    ]
    for r in results[1:]:
        np.testing.assert_allclose(float(results[0][0]), float(r[0]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(results[0][1]), np.asarray(r[1]),
                                   rtol=1e-6)


def test_batch_all_mixed_tiles_lcm_padding(rng):
    """Tiles where no single tile divides the max (lcm > max): the padded
    extent must be the lcm or the bp//tile grids would silently drop trailing
    blocks (ADVICE r3). Interpreter takes arbitrary tiles; on TPU use
    Mosaic-aligned tiles with the same property."""
    b = 26
    labels = jnp.asarray(rng.integers(0, 3, b))
    enc = jnp.asarray(rng.normal(size=(b, 5)).astype(np.float32))
    rv = jnp.asarray((rng.uniform(size=b) < 0.8).astype(np.float32))
    tile_sets = ([(24, 128, 128), (40, 128, 128)] if ON_TPU
                 else [(6, 8, 8), (4, 6, 12), (10, 4, 8)])
    for tiles in tile_sets:
        for pos_only in (False, True):
            _compare(labels, enc, pos_only, rv, tiles=tiles)


def test_batch_all_no_valid_triplets(rng):
    """Single class -> no negatives -> loss 0, weights 0 (reference class=1 edge,
    test_triplet_loss_utils.py:11)."""
    labels = jnp.zeros(16, jnp.int32)
    enc = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    loss, dw, frac, num, _ = batch_all_triplet_loss_pallas(
        labels, enc, interpret=not ON_TPU)
    assert float(loss) == 0.0 and float(num) == 0.0
    np.testing.assert_array_equal(np.asarray(dw), 0.0)


def test_masking_identity_and_shapes(rng):
    """v=0 keeps everything (u >= 0 always) — holds even under the interpreter's
    zero-stubbed PRNG; output shape survives row padding."""
    x = jnp.asarray(rng.uniform(size=(37, 19)).astype(np.float32)) + 0.5
    out = masking_noise_pallas(0, x, 0.0, block_rows=16, interpret=not ON_TPU)
    assert out.shape == x.shape
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_masking_validates_fraction():
    x = jnp.ones((8, 8))
    with pytest.raises(ValueError, match="corruption fraction"):
        masking_noise_pallas(0, x, 1.5, interpret=not ON_TPU)


@pytest.mark.skipif(ON_TPU, reason="interpret-only guard")
def test_masking_interpret_refuses_nonzero_v():
    """Off-TPU the stubbed PRNG would silently zero everything — must raise."""
    with pytest.raises(NotImplementedError, match="TPU hardware"):
        masking_noise_pallas(0, jnp.ones((8, 8)), 0.3, interpret=True)


@pytest.mark.skipif(not ON_TPU, reason="hardware PRNG is stubbed off-TPU")
def test_masking_statistics_tpu(rng):
    """Zeroed fraction ~= v, survivors unchanged, per-seed deterministic,
    blocks decorrelated. (Validated on v5e; auto-runs wherever tests see a TPU.)"""
    x = jnp.asarray(rng.uniform(size=(1000, 500)).astype(np.float32)) + 0.1
    for v in (0.1, 0.3, 0.7, 1.0):
        out = np.asarray(masking_noise_pallas(42, x, v))
        assert abs((out == 0).mean() - v) < 5e-3
        nz = out != 0
        np.testing.assert_array_equal(out[nz], np.asarray(x)[nz])
    o1 = np.asarray(masking_noise_pallas(7, x, 0.3))
    o2 = np.asarray(masking_noise_pallas(7, x, 0.3))
    o3 = np.asarray(masking_noise_pallas(8, x, 0.3))
    assert np.array_equal(o1, o2) and not np.array_equal(o1, o3)
    rows = np.asarray(masking_noise_pallas(3, jnp.ones((512, 100)), 0.5,
                                           block_rows=256))
    assert not np.array_equal(rows[:256], rows[256:])


@pytest.mark.parametrize("pos_only", [False, True])
@pytest.mark.parametrize("use_rv", [False, True])
def test_batch_all_custom_vjp_matches_xla_grad(rng, pos_only, use_rv):
    """The custom VJP (second Pallas kernel over the same grid) must equal XLA
    autodiff of the oracle exactly: masks and counts are comparison-derived,
    so their true gradient is zero and the only flow is sigmoid(dist)*mask
    through dp = E E^T."""
    b, d = 37, 12  # non-divisible b exercises the padded-rows-in-bwd path
    # multi-tile grid (J > 1 and K > 1): the backward accumulators must be
    # correct under block revisits, the pattern that only works on compiled
    # Mosaic when each reduction is the innermost grid axis
    tiles = DEFAULT_TILES if ON_TPU else (4, 8, 8)
    labels = jnp.asarray(rng.integers(0, 4, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
    rv = (jnp.asarray((rng.uniform(size=b) > 0.2).astype(np.float32))
          if use_rv else None)

    def l_pallas(e):
        return batch_all_triplet_loss_pallas(
            labels, e, pos_triplets_only=pos_only, row_valid=rv,
            tiles=tiles, interpret=not ON_TPU)[0]

    def l_oracle(e):
        return triplet.batch_all_triplet_loss(
            labels, e, pos_triplets_only=pos_only, row_valid=rv)[0]

    lp, gp = jax.value_and_grad(l_pallas)(enc)
    lo, go = jax.value_and_grad(l_oracle)(enc)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(go), atol=1e-5)


def test_batch_all_vjp_trains_one_step(rng):
    """The kernel is usable inside a jitted optimization step: one SGD step on
    the pallas loss must reduce it, and nondiff outputs pass through."""
    b, d = 24, 8
    labels = jnp.asarray(rng.integers(0, 3, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))

    @jax.jit
    def step(e):
        def loss_fn(e):
            out = batch_all_triplet_loss_pallas(
                labels, e, tiles=DEFAULT_TILES, interpret=not ON_TPU)
            return out[0], out[1:]
        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(e)
        return loss, aux, e - 0.5 * g

    l0, aux, enc1 = step(enc)
    l1, _, _ = step(enc1)
    assert float(l1) < float(l0)
    assert aux[0].shape == (b,)  # data_weight rides along untouched


@pytest.mark.skipif(not ON_TPU, reason="block-revisit semantics are a "
                    "compiled-Mosaic property the interpreter can't exercise")
def test_batch_all_vjp_multiblock_grid_tpu(rng):
    """COMPILED backward with J = K = 2 (b=256 at the default (8,128,128)
    tiles): the gradient accumulators see genuine block revisits, the case
    where a middle-axis reduction silently drops partial sums on hardware."""
    b, d = 256, 32
    labels = jnp.asarray(rng.integers(0, 6, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
    gp = jax.grad(lambda e: batch_all_triplet_loss_pallas(
        labels, e, tiles=(8, 128, 128), interpret=False)[0])(enc)
    go = jax.grad(lambda e: triplet.batch_all_triplet_loss(labels, e)[0])(enc)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(go),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------- batch_hard

_BH_BLOCK_ROWS = 8 if ON_TPU else 4


def _compare_hard(labels, enc, row_valid, block_rows=_BH_BLOCK_ROWS):
    ref = triplet.batch_hard_triplet_loss(labels, enc, row_valid=row_valid)
    got = batch_hard_triplet_loss_pallas(labels, enc, row_valid=row_valid,
                                         block_rows=block_rows,
                                         interpret=not ON_TPU)
    np.testing.assert_allclose(float(ref[0]), float(got[0]),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(ref[1]), np.asarray(got[1]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(ref[2]), float(got[2]), rtol=1e-5)
    np.testing.assert_allclose(float(ref[3]), float(got[3]), rtol=1e-5)
    for k in ref[4]:
        np.testing.assert_allclose(float(ref[4][k]), float(got[4][k]),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n_classes", [1, 3, 5])
def test_batch_hard_matches_xla_oracle(rng, n_classes):
    """Includes the dense quirks observable through the tuple: zero-valued
    invalid negatives in the hardest-neg max, float-equality tie counting."""
    b = 24
    labels = jnp.asarray(rng.integers(0, n_classes, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, 6)).astype(np.float32))
    _compare_hard(labels, enc, None)


def test_batch_hard_row_valid_and_padding(rng):
    """B not a block multiple: the padded columns must be invisible — they
    carry +inf into the hardest-pos min and -inf into the hardest-neg max
    (a zero pad would corrupt both reductions; see _batch_hard_kernel)."""
    b = 21
    labels = jnp.asarray(rng.integers(0, 4, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, 5)).astype(np.float32))
    rv = jnp.asarray((rng.uniform(size=b) < 0.7).astype(np.float32))
    _compare_hard(labels, enc, rv)


def test_batch_hard_all_rows_invalid(rng):
    """row_valid all zero: nothing mines, no NaN from the n_valid guard."""
    b = 12
    labels = jnp.asarray(rng.integers(0, 3, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, 4)).astype(np.float32))
    rv = jnp.zeros(b, jnp.float32)
    loss, dw, frac, num, extras = batch_hard_triplet_loss_pallas(
        labels, enc, row_valid=rv, block_rows=_BH_BLOCK_ROWS,
        interpret=not ON_TPU)
    assert float(loss) == 0.0 and float(num) == 0.0 and float(frac) == 0.0
    np.testing.assert_array_equal(np.asarray(dw), 0.0)
    for v in extras.values():
        assert np.isfinite(float(v))


def test_batch_hard_block_rows_invariance(rng):
    """Result is block-size independent (the grid split is bookkeeping)."""
    b = 30
    labels = jnp.asarray(rng.integers(0, 3, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, 4)).astype(np.float32))
    blocks = [8, 16, 32] if ON_TPU else [2, 4, 10]
    outs = [batch_hard_triplet_loss_pallas(labels, enc, block_rows=br,
                                           interpret=not ON_TPU)
            for br in blocks]
    for o in outs[1:]:
        np.testing.assert_allclose(float(outs[0][0]), float(o[0]), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(outs[0][1]), np.asarray(o[1]),
                                   rtol=1e-6)


@pytest.mark.parametrize("use_rv", [False, True])
def test_batch_hard_grad_matches_xla_grad(rng, use_rv):
    """The custom VJP recomputes through the blockwise XLA twin — it must
    equal XLA autodiff of the dense oracle (min/max subgradient choices
    agree because the blockwise twin reproduces the dense tie-breaks)."""
    b, d = 27, 9
    labels = jnp.asarray(rng.integers(0, 4, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
    rv = (jnp.asarray((rng.uniform(size=b) > 0.2).astype(np.float32))
          if use_rv else None)
    gp = jax.grad(lambda e: batch_hard_triplet_loss_pallas(
        labels, e, row_valid=rv, block_rows=_BH_BLOCK_ROWS,
        interpret=not ON_TPU)[0])(enc)
    go = jax.grad(lambda e: triplet.batch_hard_triplet_loss(
        labels, e, row_valid=rv)[0])(enc)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(go), atol=1e-5)
