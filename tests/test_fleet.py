"""Fleet contracts (ISSUE 12 tentpole): replica health/kill, the p2c router's
exactly-one-outcome promise across hedges and retries, absolute-deadline
propagation, and the staged canary->probe->fleet rollout with whole-fleet
revert.

Everything here is the unit-level story; the integrated
faults x traffic x mid-rollout runs live in tests/test_chaos_fleet.py.
"""

import time

import numpy as np
import pytest

import jax

from dae_rnn_news_recommendation_tpu.fleet import (FleetSupervisor, Router,
                                                   ServiceReplica)
from dae_rnn_news_recommendation_tpu.models.dae_core import (DAEConfig,
                                                             init_params)
from dae_rnn_news_recommendation_tpu.refresh import ChurnConfig
from dae_rnn_news_recommendation_tpu.reliability import OutcomeLedger, faults
from dae_rnn_news_recommendation_tpu.reliability.faults import (FaultInjector,
                                                                FaultPlan,
                                                                FaultSpec)

N, F, D = 64, 24, 8
SLA = 10.0  # generous: CPU test boxes stall; routing logic is what's tested


@pytest.fixture(scope="module")
def setup():
    config = DAEConfig(n_features=F, n_components=D,
                       triplet_strategy="none", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(3), config)
    articles = np.random.default_rng(3).random((N, F), dtype=np.float32)
    return config, params, articles


def make_replica(setup, name="r0", warm=True, seed_corpus=True, **kw):
    config, params, articles = setup
    kw.setdefault("top_k", 5)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_inflight", 16)
    kw.setdefault("default_deadline_s", SLA)
    rep = ServiceReplica(name, params, config, **kw)
    if seed_corpus:
        rep.corpus.swap(params, articles, note="initial")
    if warm:
        rep.warmup()
    return rep


def make_fleet(setup, n=3, bootstrap=True, router_kw=None, **replica_kw):
    config, params, articles = setup
    replicas = [make_replica(setup, name=f"r{i}", warm=False,
                             seed_corpus=not bootstrap, **replica_kw)
                for i in range(n)]
    router = Router(replicas, default_deadline_s=SLA, seed=5,
                    ledger=OutcomeLedger(), **(router_kw or {}))
    sup = FleetSupervisor(params, config, replicas, router,
                          churn=ChurnConfig(microbatch=16,
                                            drift_centroid_max=1.0,
                                            drift_collapse_max=1.0))
    if bootstrap:
        sup.bootstrap(articles)
    for r in replicas:
        r.warmup()
    return replicas, router, sup


def stop_fleet(replicas, router):
    router.stop()
    for r in replicas:
        r.stop()


# ------------------------------------------------------------------ replica

def test_replica_health_lifecycle(setup):
    rep = make_replica(setup)
    try:
        assert rep.health() == "warm" and rep.routable
        rep.drain()
        assert rep.health() == "draining" and not rep.routable
        reply = rep.submit(np.zeros(F, np.float32)).result(timeout=5)
        assert reply.status == "shed" and reply.reason == "replica_draining"
    finally:
        rep.stop()
    assert rep.health() == "dead"
    reply = rep.submit(np.zeros(F, np.float32)).result(timeout=5)
    assert reply.status == "shed" and reply.reason == "replica_dead"


def test_replica_kill_resolves_inflight_as_shed(setup):
    """kill() is the crash simulation: every queued future must resolve
    (shed), never hang — the router depends on this to re-home requests."""
    config, params, articles = setup
    rep = make_replica(setup, linger_s=0.2, flush_slack_s=0.5)
    futs = [rep.submit(articles[i]) for i in range(8)]
    rep.kill()
    statuses = {f.result(timeout=5).status for f in futs}
    assert statuses <= {"ok", "shed"} and all(f.done() for f in futs)


def test_replica_lag_delays_but_never_loses_outcomes(setup):
    config, params, articles = setup
    rep = make_replica(setup, lag_s=0.15)
    try:
        t0 = time.monotonic()
        reply = rep.submit(articles[0]).result(timeout=5)
        assert reply.ok
        assert time.monotonic() - t0 >= 0.15
    finally:
        rep.stop()


def test_replica_degraded_health_follows_service_events(setup):
    rep = make_replica(setup)
    try:
        with rep.service._lock:
            rep.service.events.append({"event": "degraded_enter"})
        assert rep.health() == "degraded" and rep.routable
        with rep.service._lock:
            rep.service.events.append({"event": "degraded_exit"})
        assert rep.health() == "warm"
    finally:
        rep.stop()


# ------------------------------------------------------------------- router

def test_router_exactly_one_outcome_under_load(setup):
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup)
    try:
        futs = [router.submit(articles[i % N]) for i in range(32)]
        replies = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in replies), router.summary()
        assert router.ledger.audit() == []
        counts = router.counts
        assert counts["submitted"] == 32
        assert counts["replied"] + counts["shed"] + counts["errors"] == 32
    finally:
        stop_fleet(replicas, router)


def test_router_p2c_spreads_load(setup):
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup, router_kw={"hedge": False})
    try:
        futs = [router.submit(articles[i % N]) for i in range(48)]
        [f.result(timeout=30) for f in futs]
        used = {r["replica"] for r in router.records}
        assert len(used) >= 2, f"p2c routed everything to {used}"
    finally:
        stop_fleet(replicas, router)


def test_router_retries_on_killed_replica(setup):
    """A replica death surfaces as retryable sheds; the router re-homes the
    request on a live replica with the ORIGINAL deadline and the caller sees
    one ok reply, never the shed."""
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup, router_kw={"hedge": False})
    try:
        replicas[1].kill()
        futs = [router.submit(articles[i % N]) for i in range(16)]
        replies = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in replies), router.summary()
        assert router.ledger.audit() == []
        assert all(r["replica"] != "r1" for r in router.records)
    finally:
        stop_fleet(replicas, router)


def test_router_no_replica_is_an_explicit_shed(setup):
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup, n=2, router_kw={"hedge": False})
    try:
        for r in replicas:
            r.kill()
        reply = router.submit(articles[0]).result(timeout=5)
        assert reply.status == "shed" and reply.reason == "no_replica"
    finally:
        stop_fleet(replicas, router)


def test_hedge_fires_and_wins_against_a_straggler(setup):
    """One replica lags every reply by 0.4s; the hedge delay floor is 50ms,
    so a request primary-routed to the straggler is re-issued to the fast
    replica and the caller's latency is hedge-delay-bounded, not
    lag-bounded. The loser resolves later and is discarded, not
    double-counted."""
    config, params, articles = setup
    replicas = [make_replica(setup, name="fast"),
                make_replica(setup, name="slow", lag_s=0.4)]
    router = Router(replicas, default_deadline_s=SLA, seed=5,
                    ledger=OutcomeLedger(), hedge=True,
                    hedge_delay_floor_s=0.05, hedge_delay_cap_s=0.05)
    try:
        fut = router.submit(articles[0], pin="slow")  # warm the pin path
        assert fut.result(timeout=10).ok
        # route until a primary lands on the straggler
        futs = [router.submit(articles[i % N]) for i in range(12)]
        replies = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in replies)
        time.sleep(0.6)   # let the losing (lagged) attempts resolve
        assert router.counts["hedges"] >= 1, router.summary()
        assert router.counts["hedge_wins"] >= 1, router.summary()
        assert router.ledger.audit() == []   # discarded losers stay hidden
        hedged_ok = [r for r in router.records
                     if r["status"] == "ok" and r["hedged"]
                     and r["replica"] == "fast"]
        assert all(r["latency_s"] < 0.4 for r in hedged_ok), hedged_ok
    finally:
        stop_fleet(replicas, router)


def test_hedge_budget_bounds_duplication(setup):
    config, params, articles = setup
    replicas = [make_replica(setup, name="a", lag_s=0.2),
                make_replica(setup, name="b", lag_s=0.2)]
    router = Router(replicas, default_deadline_s=SLA, seed=5, hedge=True,
                    hedge_delay_floor_s=0.01, hedge_delay_cap_s=0.01,
                    hedge_burst=2, hedge_budget_frac=0.0)
    try:
        futs = [router.submit(articles[i % N]) for i in range(12)]
        [f.result(timeout=30) for f in futs]
        time.sleep(0.4)
        assert router.counts["hedges"] <= 2
        assert router.counts["hedge_suppressed_budget"] >= 1
    finally:
        stop_fleet(replicas, router)


def test_nearly_expired_request_is_shed_not_hedged(setup):
    """ISSUE 12 deadline-propagation regression: a request whose ABSOLUTE
    deadline leaves less than the observed device floor must be shed as
    provably unmeetable at the replica — and the hedge scheduler must refuse
    to duplicate it rather than burn a second slot on a lost cause."""
    config, params, articles = setup
    replicas, router, _ = make_fleet(
        setup, router_kw={"hedge": True, "hedge_delay_floor_s": 0.0,
                          "hedge_delay_cap_s": 0.001})
    try:
        floor = max(r.service._floor_s for r in replicas)
        assert floor > 0.0, "warmup must have seeded the device floor"
        before = dict(router.counts)
        reply = router.submit(articles[0],
                              deadline_s=floor / 10.0).result(timeout=10)
        assert reply.status == "shed"
        assert reply.reason == "deadline_unmeetable"
        time.sleep(0.1)   # let the hedge schedule drain
        assert router.counts["hedges"] == before["hedges"]
        assert router.ledger.audit() == []
    finally:
        stop_fleet(replicas, router)


def test_router_propagates_absolute_deadline_to_retries(setup):
    """A retried request must carry the ORIGINAL deadline_at: after the first
    attempt burns most of the budget on a dead replica, the retry sees the
    REMAINING budget, and a budget below the floor is shed, not retried into
    a deadline it can't meet."""
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup, router_kw={"hedge": False})
    try:
        deadline_at = time.monotonic() + 30.0
        fut = router.submit(articles[0], deadline_at=deadline_at)
        reply = fut.result(timeout=10)
        assert reply.ok and reply.deadline_met
    finally:
        stop_fleet(replicas, router)


def test_route_fault_is_an_explicit_error(setup):
    config, params, articles = setup
    replicas, router, _ = make_fleet(setup, router_kw={"hedge": False})
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("fleet.route", 1, "fatal", note="route dies"),))
    try:
        with faults.install(FaultInjector(plan)):
            reply = router.submit(articles[0]).result(timeout=10)
        assert reply.status == "error"
        assert router.ledger.audit() == []
    finally:
        stop_fleet(replicas, router)


# ------------------------------------------------------------------ rollout

def test_bootstrap_seeds_every_replica_at_v1(setup):
    replicas, router, sup = make_fleet(setup)
    try:
        assert {r.corpus.version for r in replicas} == {1}
    finally:
        stop_fleet(replicas, router)


def test_clean_rollout_advances_whole_fleet_one_version(setup):
    config, params, articles = setup
    replicas, router, sup = make_fleet(setup)
    try:
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)
        stages = []
        report = sup.rollout(batch, note="t", stage_hook=stages.append,
                             probe_query=articles[0])
        assert report["ok"], report
        assert {r.corpus.version for r in replicas} == {2}
        assert stages[0] == "canary" and stages[1] == "probe"
        assert stages[-1] == "done"
        assert report["probe"]["version"] == 2  # probe answered from the NEW slot
    finally:
        stop_fleet(replicas, router)


def test_canary_gate_failure_leaves_fleet_untouched(setup):
    """The canary's swap dies (injected): its corpus rolls itself back and
    the rollout aborts with every replica still at the pre-canary version —
    the fleet never saw the batch."""
    config, params, articles = setup
    replicas, router, sup = make_fleet(setup)
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("refresh.swap", 1, "fatal", note="canary swap dies"),))
    try:
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)
        with faults.install(FaultInjector(plan)):
            report = sup.rollout(batch, probe_query=articles[0])
        assert not report["ok"]
        assert report["canary"]["action"] == "rollback"
        assert report["reverted"] == []     # nothing promoted, nothing undone
        assert {r.corpus.version for r in replicas} == {1}
    finally:
        stop_fleet(replicas, router)


def test_fleet_stage_failure_reverts_canary_too(setup):
    """A fleet-stage swap failure after the canary promoted must restore the
    WHOLE fleet — canary included — to the pre-canary version."""
    config, params, articles = setup
    replicas, router, sup = make_fleet(setup)
    plan = FaultPlan(seed=0, specs=(
        FaultSpec("refresh.swap", 2, "fatal", note="fleet swap dies"),))
    try:
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)
        with faults.install(FaultInjector(plan)):
            report = sup.rollout(batch, probe_query=articles[0])
        assert not report["ok"]
        assert "r0" in report["reverted"]
        assert {r.corpus.version for r in replicas} == {1}
        # the canary corpus records the legal revert, and still serves
        assert any(rec.get("revert") for rec in replicas[0].corpus.ledger)
        reply = router.submit(articles[0]).result(timeout=10)
        assert reply.ok and reply.corpus_version == 1
    finally:
        stop_fleet(replicas, router)


def test_dead_replica_is_skipped_and_recorded(setup):
    config, params, articles = setup
    replicas, router, sup = make_fleet(setup)
    try:
        replicas[2].kill()
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)
        report = sup.rollout(batch, probe_query=articles[0])
        assert report["ok"], report
        assert report["skipped"] == ["r2"]
        assert replicas[0].corpus.version == replicas[1].corpus.version == 2
        assert replicas[2].corpus.version == 1
    finally:
        stop_fleet(replicas, router)


def test_failed_probe_reverts_canary(setup):
    """A canary that swaps clean but cannot ANSWER from the new version is a
    failed rollout: the probe rides the real serving path, pinned."""
    config, params, articles = setup
    replicas, router, sup = make_fleet(setup)
    try:
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)

        def kill_canary_before_probe(stage):
            if stage == "probe":
                replicas[0].kill()

        report = sup.rollout(batch, stage_hook=kill_canary_before_probe,
                             probe_query=articles[0])
        assert not report["ok"] and "probe" in report["detail"]
        assert report["reverted"] == ["r0"]
        assert {r.corpus.version for r in replicas} == {1}
    finally:
        stop_fleet(replicas, router)


def test_shared_corpus_fleet_promotes_once(setup):
    """ISSUE 16: replicas fronting ONE shared (sharded) corpus ride the
    rollout protocol with the fleet stage collapsing — the canary's churn
    ingest IS the fleet promote. Exactly one ledger promote per rollout,
    shared replicas recorded (never silently skipped), zero version skew."""
    from dae_rnn_news_recommendation_tpu.serve import default_corpus

    config, params, articles = setup
    corpus = default_corpus(config)
    replicas = [make_replica(setup, name=f"r{i}", warm=False,
                             seed_corpus=False, corpus=corpus)
                for i in range(3)]
    router = Router(replicas, default_deadline_s=SLA, seed=5,
                    ledger=OutcomeLedger())
    sup = FleetSupervisor(params, config, replicas, router,
                          churn=ChurnConfig(microbatch=16,
                                            drift_centroid_max=1.0,
                                            drift_collapse_max=1.0))
    try:
        boot = sup.bootstrap(articles)
        assert boot["shared"] == ["r1", "r2"]
        assert corpus.version == 1  # seeded once, not once per replica
        for r in replicas:
            r.warmup()
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)
        report = sup.rollout(batch, note="t", probe_query=articles[0])
        assert report["ok"], report
        assert report["shared"] == ["r1", "r2"]
        assert {r.corpus.version for r in replicas} == {2}
        promotes = [rec for rec in corpus.ledger
                    if rec.get("ok") and rec["version"] == 2]
        assert len(promotes) == 1, corpus.ledger  # promoted exactly once
        assert sup.summary()["shared_corpus"] == ["r1", "r2"]
        # every replica answers from the one shared slot
        reply = router.submit(articles[0]).result(timeout=30)
        assert reply.ok and reply.corpus_version == 2
    finally:
        stop_fleet(replicas, router)


def test_shared_corpus_fleet_failure_reverts_once(setup):
    """A failed probe after a shared-corpus canary promote reverts the ONE
    corpus exactly once — shared replicas are not in the promoted list, so
    the rollback path cannot double-revert the object they all front."""
    from dae_rnn_news_recommendation_tpu.serve import default_corpus

    config, params, articles = setup
    corpus = default_corpus(config)
    replicas = [make_replica(setup, name=f"r{i}", warm=False,
                             seed_corpus=False, corpus=corpus)
                for i in range(3)]
    router = Router(replicas, default_deadline_s=SLA, seed=5,
                    ledger=OutcomeLedger())
    sup = FleetSupervisor(params, config, replicas, router,
                          churn=ChurnConfig(microbatch=16,
                                            drift_centroid_max=1.0,
                                            drift_collapse_max=1.0))
    try:
        sup.bootstrap(articles)
        for r in replicas:
            r.warmup()
        batch = np.random.default_rng(9).random((16, F), dtype=np.float32)

        def kill_canary_before_probe(stage):
            if stage == "probe":
                replicas[0].kill()

        report = sup.rollout(batch, stage_hook=kill_canary_before_probe,
                             probe_query=articles[0])
        assert not report["ok"] and "probe" in report["detail"]
        assert report["reverted"] == ["r0"]  # one revert on the one corpus
        assert corpus.version == 1
        reverts = [rec for rec in corpus.ledger if rec.get("revert")]
        assert len(reverts) == 1
    finally:
        stop_fleet(replicas, router)


# --------------------------------------------- observability (ISSUE 14)

def test_fleet_ids_propagate_and_hedge_twin_shares_parent_id(setup):
    """Router requests get `flt-N` ids; the replica-level attempt carries
    the hop suffix, so a hedge twin that WINS resolves the caller's future
    with `flt-N/h` — the winner is attributable from the reply alone."""
    config, params, articles = setup
    replicas = [make_replica(setup, name="fast"),
                make_replica(setup, name="slow", lag_s=0.4)]
    router = Router(replicas, default_deadline_s=SLA, seed=5,
                    ledger=OutcomeLedger(), hedge=True,
                    hedge_delay_floor_s=0.05, hedge_delay_cap_s=0.05)
    try:
        fut = router.submit(articles[0], pin="slow")
        assert fut.result(timeout=10).ok
        futs = [router.submit(articles[i % N]) for i in range(12)]
        replies = [f.result(timeout=30) for f in futs]
        assert all(r.ok for r in replies)
        time.sleep(0.6)
        ids = [r.request_id for r in replies]
        assert all(rid.startswith("flt-") for rid in ids)
        roots = [rid.split("/")[0] for rid in ids]
        assert len(set(roots)) == len(roots)  # one root id per request
        assert router.counts["hedge_wins"] >= 1, router.summary()
        winners = [r for r in router.records if r.get("hedged")
                   and str(r.get("request_id", "")).endswith("/h")]
        assert winners, [r["request_id"] for r in router.records]
    finally:
        stop_fleet(replicas, router)


def test_fleet_timing_decomposition_sums_to_latency(setup):
    """Fleet-level timing honesty: each record's per-hop components plus
    the router's own remainder (`router_s`) reconstruct the end-to-end
    latency the caller observed."""
    replicas, router, sup = make_fleet(setup)
    config, params, articles = setup
    try:
        futs = [router.submit(articles[i % N]) for i in range(10)]
        assert all(f.result(timeout=30).ok for f in futs)
        recs = [r for r in router.records if r["status"] == "ok"]
        assert len(recs) == 10
        for rec in recs:
            t = rec["timings"]
            assert "router_s" in t and "compute_s" in t
            assert abs(sum(t.values()) - rec["latency_s"]) < 1e-3, rec
    finally:
        stop_fleet(replicas, router)


def test_fleet_registries_aggregate_without_double_counting(setup):
    """The router's request-outcome counters are `fleet_`-prefixed exactly
    so the name-keyed aggregate cannot fold them into the replica-level
    submitted/replied (each request is ONE fleet outcome but may be 1+
    replica attempts under hedging/retries)."""
    from dae_rnn_news_recommendation_tpu.fleet import fleet_registries
    from dae_rnn_news_recommendation_tpu.telemetry import (MetricsRegistry,
                                                           aggregate)

    replicas, router, sup = make_fleet(setup)
    config, params, articles = setup
    router.attach_registry(MetricsRegistry("router"))
    for r in replicas:
        r.attach_registry(MetricsRegistry(r.name))
    try:
        futs = [router.submit(articles[i % N]) for i in range(8)]
        assert all(f.result(timeout=30).ok for f in futs)
        regs = fleet_registries(router=router, replicas=replicas,
                                supervisor=sup)
        assert len(regs) == 4  # router + 3 distinct replica registries
        agg = aggregate([m.snapshot() for m in regs])
        assert agg["counters"]["fleet_submitted"] == 8
        assert agg["counters"]["fleet_replied"] == 8
        # replica-level attempts can exceed fleet outcomes, never undercut
        assert agg["counters"]["replied"] >= 8
        assert "request_latency_ms" in agg["histograms"]
        assert agg["histograms"]["fleet_latency_ms"]["count"] == 8
    finally:
        stop_fleet(replicas, router)


def test_clean_stop_is_not_a_replica_kill(setup):
    """stop() is planned teardown; kill() is the crash. Only the crash may
    increment `replica_kills` — the zero-tolerance SLO fires on any count,
    so a clean shutdown must leave it at zero."""
    from dae_rnn_news_recommendation_tpu.telemetry import MetricsRegistry

    rep = make_replica(setup, registry=MetricsRegistry("r0"))
    rep.stop()
    assert rep.metrics.counter("replica_kills").value == 0

    rep2 = make_replica(setup, name="r1", registry=MetricsRegistry("r1"))
    rep2.kill()
    assert rep2.metrics.counter("replica_kills").value == 1
