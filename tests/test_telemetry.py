"""Telemetry subsystem (telemetry/): fenced span tracing, XLA event capture,
run manifests, and the report CLI.

Contracts pinned here:
  * spans — nesting, decorator form, exception survival (the span records
    with args.error and the exception propagates);
  * disabled mode — span() hands out one shared null object and the
    per-call overhead is unmeasurably small (a fit with trace off must not
    pay for the instrumentation);
  * export — the trace is valid Chrome-trace JSON: M metadata first, X
    events with ts/dur/pid/tid, sorted by ts; Perfetto loads this shape;
  * acceptance — a traced pipelined fit produces producer AND consumer
    tracks, >= 1 captured XLA backend-compile event, and a manifest; the
    report CLI renders the p50/p95 table from it (exit 0);
  * counters — record_transfer lands under transfer/<dir> with bytes;
  * manifest — build/write/read round trip with the documented schema keys.
"""

import json
import os
import threading
import time

import numpy as np
import pytest
import scipy.sparse as sp

import jax

from dae_rnn_news_recommendation_tpu import telemetry
from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder
from dae_rnn_news_recommendation_tpu.telemetry.__main__ import main as cli_main


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.fixture(autouse=True)
def _telemetry_off_guard():
    """Every test must leave the module state disabled (fit paths disable in
    `finally`; a leak here would silently slow every later test)."""
    yield
    assert not telemetry.enabled()
    telemetry.disable()  # defensive: no-op when the assert above held


# ------------------------------------------------------------------- spans

def test_span_records_nested_regions_with_args():
    tracer = telemetry.enable(xla_events=False)
    try:
        with telemetry.span("outer", fence=False, args={"k": 1}):
            with telemetry.span("inner", fence=False):
                time.sleep(0.001)
    finally:
        telemetry.disable()
    by_name = {e["name"]: e for e in tracer.events()}
    assert set(by_name) == {"outer", "inner"}
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["args"] == {"k": 1}
    assert outer["ph"] == inner["ph"] == "X"
    # containment: inner starts after outer and ends before it
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert inner["dur"] >= 1e3  # the 1ms sleep, in microseconds


def test_span_decorator_and_instrument():
    calls = []

    @telemetry.span("decorated", fence=False)
    def work(v):
        calls.append(v)
        return v * 2

    stepped = telemetry.instrument(lambda x: x + 1, "stepped",
                                   fence_result=False)
    assert work(3) == 6 and stepped(1) == 2  # disabled: plain passthrough
    tracer = telemetry.enable(xla_events=False)
    try:
        assert work(4) == 8
        assert stepped(2) == 3
    finally:
        telemetry.disable()
    names = [e["name"] for e in tracer.events()]
    assert names == ["decorated", "stepped"]
    assert calls == [3, 4]


def test_span_survives_exception_and_propagates():
    tracer = telemetry.enable(xla_events=False)
    try:
        with pytest.raises(ValueError):
            with telemetry.span("doomed", fence=False):
                raise ValueError("boom")
    finally:
        telemetry.disable()
    [event] = tracer.events()
    assert event["name"] == "doomed"
    assert event["args"]["error"] == "ValueError"


def test_fenced_span_measures_device_work():
    """A default-fenced span around a jitted call must include the compute,
    not just the enqueue: duration_s is a real positive fenced wall time and
    fence_on returns its argument unchanged."""
    f = jax.jit(lambda x: (x @ x).sum())
    x = np.ones((64, 64), np.float32)
    f(x)  # compile outside the span
    telemetry.enable(xla_events=False)
    try:
        with telemetry.span("device") as sman:
            out = sman.fence_on(f(x))
    finally:
        telemetry.disable()
    assert float(out) == 64.0 * 64 * 64
    assert sman.duration_s is not None and sman.duration_s > 0


# ----------------------------------------------------------- disabled mode

def test_disabled_span_is_shared_null_and_cheap():
    assert telemetry.span("a") is telemetry.span("a")  # cached, no alloc
    sman = telemetry.span("c")
    assert sman.fence_on("x") == "x"  # passthrough
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with telemetry.span("hot"):
            pass
    dt = time.perf_counter() - t0
    # generous bound: ~5us/iter would still pass; the point is "no clock
    # reads, no fence, no allocation" — a regression to per-call Span
    # construction lands well above this
    assert dt < 1.0, f"{n} disabled spans took {dt:.3f}s"


def test_untraced_fit_writes_no_trace(workdir):
    rng = np.random.default_rng(0)
    x = (rng.uniform(size=(30, 24)) < 0.25).astype(np.float32)
    labels = rng.integers(0, 4, 30).astype(np.int32)
    m = DenoisingAutoencoder(
        model_name="notrace", main_dir="notrace", n_components=6,
        num_epochs=1, batch_size=10, seed=7, corr_type="masking",
        corr_frac=0.3, loss_func="mean_squared", opt="ada_grad",
        learning_rate=0.1, verbose=False, use_tensorboard=False,
        results_root=str(workdir / "results"))
    m.fit(x, train_set_label=labels)
    assert m.trace_path is None
    assert not telemetry.enabled()
    # the manifest is written regardless: every run self-describes
    assert m.run_manifest_path and os.path.exists(m.run_manifest_path)


# ------------------------------------------------------------------ export

def test_export_is_valid_sorted_chrome_trace(tmp_path):
    tracer = telemetry.enable(xla_events=False)
    try:
        def worker():
            with telemetry.span("producer", fence=False):
                time.sleep(0.002)

        t = threading.Thread(target=worker, name="feed-worker")
        with telemetry.span("consumer", fence=False):
            t.start()
            t.join()
    finally:
        telemetry.disable()
    path = tracer.export(str(tmp_path / "trace.json"), metadata={"run": "t"})
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    assert meta and xs and len(meta) + len(xs) == len(events)
    assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
    thread_names = {m["args"]["name"] for m in meta
                    if m["name"] == "thread_name"}
    assert "feed-worker" in thread_names
    for e in xs:  # every X event is a complete, placeable rectangle
        assert {"name", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    # producer and consumer landed on distinct tracks
    tids = {e["name"]: e["tid"] for e in xs}
    assert tids["producer"] != tids["consumer"]
    assert trace["metadata"]["run"] == "t"


# -------------------------------------------------- traced fit + report CLI

@pytest.fixture(scope="module")
def traced_fit(tmp_path_factory):
    """One traced pipelined fit shared by the acceptance tests below.
    n_features=26 is unique to this module so the step compiles fresh here
    and the trace captures >= 1 backend-compile event even when the whole
    tier-1 suite shares the process."""
    workdir = tmp_path_factory.mktemp("traced_fit")
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        rng = np.random.default_rng(0)
        x = sp.csr_matrix(
            (rng.uniform(size=(37, 26)) < 0.25).astype(np.float32))
        labels = rng.integers(0, 4, 37).astype(np.int32)
        m = DenoisingAutoencoder(
            model_name="traced", main_dir="traced", n_components=6,
            num_epochs=2, batch_size=10, seed=7, corr_type="masking",
            corr_frac=0.3, loss_func="mean_squared", opt="ada_grad",
            learning_rate=0.1, verbose=False, use_tensorboard=False,
            feed="pipelined", trace=True,
            results_root=str(workdir / "results"))
        m.fit(x, train_set_label=labels, validation_set=x[:10],
              validation_set_label=labels[:10])
        with open(m.trace_path, encoding="utf-8") as f:
            trace = json.load(f)
        yield m, trace
    finally:
        os.chdir(cwd)


def test_traced_pipelined_fit_has_producer_and_consumer_tracks(traced_fit):
    m, trace = traced_fit
    assert not telemetry.enabled()  # fit disabled tracing in finally
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    by_name = {}
    for e in xs:
        by_name.setdefault(e["name"], []).append(e)
    # the whole path is covered: feed worker, consumer, epoch, validation
    for required in ("fit/epoch", "feed/wait", "feed/pad", "feed/h2d",
                     "train/step", "fit/validation", "train/eval_step"):
        assert required in by_name, f"missing span {required}"
    assert len(by_name["fit/epoch"]) == 2
    # producer spans (worker thread) on a different track than the consumer
    producer_tids = {e["tid"] for e in by_name["feed/h2d"]}
    consumer_tids = {e["tid"] for e in by_name["train/step"]}
    assert producer_tids and consumer_tids
    assert producer_tids.isdisjoint(consumer_tids)
    # >= 1 captured XLA compile event (fresh 26-feature step shape)
    assert len(by_name.get("xla/backend_compile", [])) >= 1
    # the fenced h2d spans accounted real transfers into the counters
    h2d = trace["metadata"]["counters"].get("transfer/h2d")
    assert h2d and h2d["count"] >= 1 and h2d["bytes"] > 0


def test_traced_fit_writes_manifest(traced_fit):
    m, trace = traced_fit
    manifest = telemetry.read_manifest(m.run_manifest_path)
    assert manifest["schema"] == 1
    assert manifest["feed_mode"] == "pipelined"
    assert manifest["buckets"] == [10]
    assert manifest["jax_version"] == jax.__version__
    assert manifest["config"]["n_components"] == 6
    assert manifest["model"] == "DenoisingAutoencoder"
    assert trace["metadata"]["manifest_path"] == m.run_manifest_path


def test_report_cli_renders_table(traced_fit, capsys):
    m, _ = traced_fit
    metrics_dir = os.path.dirname(m.trace_path)
    rc = cli_main(["report", m.trace_path, "--metrics", metrics_dir])
    out = capsys.readouterr().out
    assert rc == 0
    # table header + the load-bearing spans + the manifest provenance line
    assert "p50 ms" in out and "compiles" in out
    assert "train/step" in out and "feed/h2d" in out
    assert "feed=pipelined" in out
    assert "counters:" in out and "transfer/h2d" in out


def test_report_cli_json_mode(traced_fit, capsys):
    m, _ = traced_fit
    rc = cli_main(["report", m.trace_path, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    spans = {r["span"] for r in report["spans"]}
    assert {"fit/epoch", "train/step", "feed/h2d"} <= spans
    assert report["manifest"]["feed_mode"] == "pipelined"


def test_report_cli_error_exits(tmp_path, capsys):
    assert cli_main(["report", str(tmp_path / "missing.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text('{"traceEvents": []}')
    assert cli_main(["report", str(empty)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------- counters

def test_record_transfer_counters():
    telemetry.record_transfer("h2d", 0.5, 100)  # disabled: silent no-op
    telemetry.enable()
    try:
        telemetry.record_transfer("h2d", 0.25, 1000)
        telemetry.record_transfer("h2d", 0.25, 1000)
        telemetry.record_transfer("d2h", 0.1, 10)
        telemetry.record_transfer("h2d", None, 10)  # unfenced span: dropped
        counters = telemetry.counters()
    finally:
        tracer = telemetry.disable()
    assert counters["transfer/h2d"] == {
        "count": 2, "total_s": 0.5, "bytes": 2000}
    assert counters["transfer/d2h"]["count"] == 1
    # disable() snapshots the counters onto the tracer for export
    assert tracer.counters["transfer/h2d"]["bytes"] == 2000
    assert telemetry.counters() == {}


# ---------------------------------------------------------------- manifest

def test_manifest_round_trip(tmp_path):
    manifest = telemetry.build_manifest(
        config={"n_components": 4}, feed_mode="stream",
        extra={"note": "test"})
    for key in ("schema", "created_utc", "git_rev", "jax_version",
                "numpy_version", "python_version", "backend", "devices"):
        assert key in manifest, key
    assert manifest["feed_mode"] == "stream" and manifest["note"] == "test"
    path = telemetry.write_manifest(str(tmp_path / "m.json"), manifest)
    assert telemetry.read_manifest(path) == manifest


# --------------------------------------------- observability (ISSUE 14)

def test_threads_born_after_enable_get_named_tracks():
    """A thread created AFTER tracing starts still gets a named track: its
    first record_span self-registers the thread name, so its spans don't
    render as an anonymous tid in Perfetto."""
    tracer = telemetry.enable(xla_events=False)
    try:
        def worker():
            with telemetry.span("late/span", fence=False):
                time.sleep(0.001)

        t = threading.Thread(target=worker, name="late-worker")
        t.start()
        t.join()
    finally:
        telemetry.disable()
    span = next(e for e in tracer.events() if e["name"] == "late/span")
    meta = [e for e in tracer.chrome_trace()["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "thread_name"]
    named = {e["tid"]: e["args"]["name"] for e in meta}
    assert named.get(span["tid"]) == "late-worker"


def test_report_cli_fleet_flag_renders_bundle(tmp_path, capsys):
    """`report --fleet PATH` renders the serving-fleet section from an
    explicit bundle path (the auto-detect path is covered end-to-end in
    tests/test_chaos_fleet.py)."""
    bundle = {
        "requests": [{"id": 1, "request_id": "flt-1", "status": "ok",
                      "replica": "r0", "hedged": False, "retries": 0,
                      "latency_s": 0.004,
                      "timings": {"admit_s": 0.001, "queue_s": 0.001,
                                  "compute_s": 0.001, "router_s": 0.001}}],
        "registries": [{"registry": "r0", "counters": {"replied": 1},
                        "gauges": {}, "histograms": {}}],
        "aggregate": {"registry": "fleet", "n_sources": 1,
                      "counters": {"replied": 1}, "gauges": {},
                      "histograms": {}},
        "slo": {"specs": [], "alerts": [], "active": [],
                "n_observations": 2},
        "rollout": [{"action": "bootstrap"}],
        "ledger": {"n_submitted": 1, "counts": {"ok": 1}, "problems": []},
    }
    (tmp_path / "bundle.json").write_text(json.dumps(bundle))
    trace = tmp_path / "trace.json"
    trace.write_text('{"traceEvents": []}')
    rc = cli_main(["report", str(trace), "--fleet",
                   str(tmp_path / "bundle.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "serving fleet: 1 requests" in out
    assert "flt-1" in out
    assert "SLO alerts: none" in out
    assert "[join ok]" in out


def test_report_degrades_gracefully_on_r12_era_layout(tmp_path, capsys):
    """Regression for pre-fleet run directories (trace + health bundle +
    churn history, NO fleet_observability.json): the report renders exactly
    the old sections, no fleet noise, exit 0 — and a bare `--fleet` on the
    same directory degrades to a note instead of an error."""
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "fit/epoch", "ph": "X", "ts": 0, "dur": 1000,
         "pid": 1, "tid": 1}]}))
    (tmp_path / "health_bundle.json").write_text(json.dumps(
        {"status": "healthy", "reason": "", "first_bad_step": None,
         "last_good_step": 9, "loss_ema": 0.5, "n_steps_recorded": 10,
         "ring": []}))
    (tmp_path / "churn_history.json").write_text(json.dumps(
        {"history": [{"action": "incremental", "version": 2,
                      "swap_s": 0.01}]}))
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "model health: healthy" in out
    assert "corpus churn: 1 cycles" in out
    assert "serving fleet" not in out
    assert "fleet bundle unavailable" not in out  # silent when not asked

    rc = cli_main(["report", str(trace), "--fleet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet bundle unavailable" in out
    assert "serving fleet" not in out


def test_report_cli_quality_flag_renders_bundle(tmp_path, capsys):
    """`report --quality PATH` renders the retrieval-quality section from a
    dump_quality_observability bundle: the shadow recall story, worst
    samples, coverage, the quality gauges, and the alert history."""
    bundle = {
        "shadow": {
            "rate": 1.0, "period": 1,
            "counts": {"seen": 20, "sampled": 20, "scored": 20,
                       "dropped": 0, "errors": 0},
            "recall_mean": 0.85, "recall_min": 0.4, "n_samples": 20,
            "samples": [
                {"rid": "q-7", "k": 10, "expected": 10, "hits": 4,
                 "recall": 0.4, "rank_displacement": 2.5,
                 "score_delta": 0.012, "corpus_version": 3,
                 "coverage": 1.0},
                {"rid": "q-8", "k": 10, "expected": 10, "hits": 10,
                 "recall": 1.0, "rank_displacement": 0.0,
                 "score_delta": 0.0, "corpus_version": 3,
                 "coverage": 1.0}]},
        "corpus": {"coverage": 0.75,
                   "ledger": [{"note": "initial"}, {"note": "lost"}]},
        "registries": [{"registry": "svc", "counters": {}, "gauges": {},
                        "histograms": {}}],
        "aggregate": {"registry": "fleet", "n_sources": 1,
                      "counters": {"shadow_misses": 12,
                                   "shadow_expected": 200, "replied": 20},
                      "gauges": {"corpus_coverage": 0.75,
                                 "int8_score_error": 0.003},
                      "histograms": {}},
        "slo": {"specs": [{"name": "quality-recall"},
                          {"name": "quality-coverage"},
                          {"name": "quality-quant-error"}],
                "alerts": [{"slo": "quality-coverage", "kind": "gauge_min",
                            "t": 4.0, "value": 0.75, "short_burn": None,
                            "long_burn": None}],
                "active": ["quality-coverage"], "n_observations": 3},
    }
    (tmp_path / "quality_observability.json").write_text(json.dumps(bundle))
    trace = tmp_path / "trace.json"
    trace.write_text('{"traceEvents": []}')
    rc = cli_main(["report", str(trace), "--quality",
                   str(tmp_path / "quality_observability.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "retrieval quality: shadow rate 1.0, 20 scored" in out
    assert "shadow recall: mean 0.85  min 0.4  over 20 samples" in out
    assert "q-7" in out  # the worst sample leads the table
    assert "live coverage: 0.75  (ledger: 2 records)" in out
    assert "corpus_coverage=0.75" in out
    assert "int8_score_error=0.003" in out
    assert "shadow_misses=12" in out
    assert "replied" not in out.split("shadow counters:")[1].splitlines()[0]
    assert "quality alerts (3 specs): quality-coverage (value 0.75)" in out


def test_report_cli_quality_auto_detects_and_degrades(tmp_path, capsys):
    """The --quality sentinel contract matches --fleet/--profile: omitted
    flag auto-detects silently, bare flag on a directory without the bundle
    degrades to a note, exit 0 either way."""
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "fit/epoch", "ph": "X", "ts": 0, "dur": 1000,
         "pid": 1, "tid": 1}]}))
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "retrieval quality" not in out
    assert "quality bundle unavailable" not in out  # silent when not asked

    rc = cli_main(["report", str(trace), "--quality"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "quality bundle unavailable" in out
    assert "retrieval quality" not in out

    # auto-detect: the bundle sitting next to the trace is picked up with
    # NO flag at all
    (tmp_path / "quality_observability.json").write_text(json.dumps({
        "shadow": {"rate": 0.25, "counts": {"scored": 4, "sampled": 4,
                                            "seen": 16},
                   "recall_mean": 1.0, "recall_min": 1.0, "n_samples": 4,
                   "samples": []}}))
    rc = cli_main(["report", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "retrieval quality: shadow rate 0.25, 4 scored" in out


def test_report_fleet_aggregate_notes_are_rendered(tmp_path, capsys):
    """Regression (ISSUE 19 satellite): aggregate() records
    mismatched-histogram-bounds notes, and `report --fleet` must surface
    them instead of silently folding partial histogram merges."""
    bundle = {
        "registries": [{"registry": "r0", "counters": {"replied": 1},
                        "gauges": {}, "histograms": {}}],
        "aggregate": {"registry": "fleet", "n_sources": 2,
                      "counters": {"replied": 2}, "gauges": {},
                      "histograms": {},
                      "notes": ["histogram reply_latency_ms: mismatched "
                                "bounds, kept 1/2 sources"]},
        "requests": [], "rollout": [],
        "slo": {"specs": [], "alerts": [], "active": [],
                "n_observations": 1},
    }
    (tmp_path / "bundle.json").write_text(json.dumps(bundle))
    trace = tmp_path / "trace.json"
    trace.write_text('{"traceEvents": []}')
    rc = cli_main(["report", str(trace), "--fleet",
                   str(tmp_path / "bundle.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert ("aggregate note: histogram reply_latency_ms: mismatched "
            "bounds, kept 1/2 sources") in out
