"""Unit tests for the shared driver eval tail (cli/eval_tail.py): streaming vs
full-matrix agreement on the same inputs, and the sim_cache contract — the
train-split similarity matrices built during similarity_eval are REUSED by
nn_printout, never recomputed (they are the non-streaming eval's memory
high-water mark; test_cli.py covers the tail end-to-end through both CLIs)."""

import numpy as np
import pandas as pd
import pytest

from dae_rnn_news_recommendation_tpu.cli.eval_tail import (
    nn_printout, similarity_eval)


@pytest.fixture
def tiny(rng):
    n_tr, n_vl, f = 24, 10, 8
    reps = {
        "binary_count": ((rng.uniform(size=(n_tr, f)) < 0.4).astype(np.float32),
                         (rng.uniform(size=(n_vl, f)) < 0.4).astype(np.float32)),
        "encoded": (rng.normal(size=(n_tr, 4)).astype(np.float32),
                    rng.normal(size=(n_vl, 4)).astype(np.float32)),
    }
    labels = {
        "label_category_publish_name": {
            "train": rng.integers(0, 3, n_tr),
            "validate": rng.integers(0, 3, n_vl)},
        "label_story": {"train": rng.integers(-1, 2, n_tr),
                        "validate": rng.integers(-1, 2, n_vl)},
    }
    return reps, labels


def test_streaming_matches_full_matrix(tiny, tmp_path):
    reps, labels = tiny
    full = similarity_eval(reps, labels, str(tmp_path) + "/", streaming=False)
    stream = similarity_eval(reps, labels, str(tmp_path) + "/", streaming=True)
    assert set(full) == set(stream)
    for k in full:
        if np.isfinite(full[k]) or np.isfinite(stream[k]):
            np.testing.assert_allclose(full[k], stream[k], atol=2e-2,
                                       err_msg=k)


def test_missing_validate_split_skipped(tiny, tmp_path):
    reps, labels = tiny
    reps = {k: (tr, None) for k, (tr, vl) in reps.items()}
    aurocs = similarity_eval(reps, labels, str(tmp_path) + "/",
                             streaming=False)
    assert aurocs and not any("_validate" in k for k in aurocs)


def test_nn_printout_reuses_cached_sims(tiny, tmp_path, capsys, monkeypatch):
    """similarity_eval stashes the train-split sims; nn_printout must consume
    them instead of rebuilding the [N, N] matrices."""
    reps, labels = tiny
    cache = {}
    similarity_eval(reps, labels, str(tmp_path) + "/", streaming=False,
                    sim_cache=cache)
    assert set(cache) == {"binary_count", "encoded"}

    from dae_rnn_news_recommendation_tpu import eval as eval_pkg

    def boom(*a, **k):
        raise AssertionError("nn_printout recomputed a cached similarity")

    monkeypatch.setattr(eval_pkg, "pairwise_similarity", boom)
    n_tr = reps["encoded"][0].shape[0]
    rows = pd.DataFrame({
        "title": [f"t{i}" for i in range(n_tr)],
        "category_publish_name": ["c"] * n_tr,
    })
    nn_printout(rows, reps["encoded"][0], reps["binary_count"][0],
                streaming=False, sim_cache=cache)
    assert "most similar article" in capsys.readouterr().out
