"""Metrics registry contracts (ISSUE 14): thread-safe counters/gauges/
fixed-bucket histograms with no per-observation allocation, per-replica
registries, and the name-keyed fleet aggregate.

The load-bearing test is the concurrent-writer race: Python `+=` is not
atomic, so a lockless counter under N threads x M increments loses updates
nondeterministically — the registry must land on the exact total every time.
"""

import threading

import pytest

from dae_rnn_news_recommendation_tpu.telemetry import (
    DEFAULT_LATENCY_BOUNDS_MS, MetricsRegistry, aggregate,
    histogram_percentile)


# ---------------------------------------------------------------- primitives

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry("svc")
    c = reg.counter("replied")
    c.inc()
    c.inc(3)
    assert c.value == 4
    g = reg.gauge("queue_depth")
    assert g.value is None  # unset gauge reads as absent, not 0
    g.set(7)
    assert g.value == 7.0
    h = reg.histogram("latency_ms", bounds=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    state = h.state()
    assert state["counts"] == [1, 1, 1, 1]  # last bucket is +inf overflow
    assert state["count"] == 4
    assert state["min"] == 0.5 and state["max"] == 500.0


def test_registry_create_or_get_returns_same_object():
    reg = MetricsRegistry("svc")
    assert reg.counter("x") is reg.counter("x")
    assert reg.gauge("y") is reg.gauge("y")
    assert reg.histogram("z") is reg.histogram("z")


def test_histogram_percentile_interpolates_and_handles_overflow():
    reg = MetricsRegistry("svc")
    h = reg.histogram("lat", bounds=list(DEFAULT_LATENCY_BOUNDS_MS))
    for v in (1.0,) * 50 + (100.0,) * 50:
        h.observe(v)
    p50 = histogram_percentile(h.state(), 50.0)
    assert p50 <= 100.0
    # everything in the overflow bucket -> the observed max, not infinity
    h2 = reg.histogram("over", bounds=(1.0,))
    h2.observe(1e6)
    assert histogram_percentile(h2.state(), 99.0) == 1e6
    assert histogram_percentile({"counts": [], "count": 0}, 50.0) is None


# ------------------------------------------------------------- concurrency

def test_concurrent_counter_increments_are_exact():
    """N threads x M increments must land on exactly N*M — the lost-update
    race a bare `+=` loses."""
    reg = MetricsRegistry("svc")
    c = reg.counter("hits")
    n_threads, n_inc = 8, 2000
    start = threading.Barrier(n_threads)

    def worker():
        start.wait()
        for _ in range(n_inc):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_inc


def test_concurrent_histogram_observations_are_exact():
    reg = MetricsRegistry("svc")
    h = reg.histogram("lat", bounds=(1.0, 10.0))
    n_threads, n_obs = 8, 1000
    start = threading.Barrier(n_threads)

    def worker(i):
        start.wait()
        for k in range(n_obs):
            h.observe(float(k % 20))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    state = h.state()
    assert state["count"] == n_threads * n_obs
    assert sum(state["counts"]) == n_threads * n_obs


def test_concurrent_create_or_get_yields_one_metric_per_name():
    """Two threads racing counter("same") must converge on ONE counter —
    a torn dict insert would silently fork the count."""
    reg = MetricsRegistry("svc")
    got = []
    start = threading.Barrier(8)

    def worker():
        start.wait()
        c = reg.counter("same")
        c.inc()
        got.append(c)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(c is got[0] for c in got)
    assert reg.counter("same").value == 8


# ---------------------------------------------------------------- aggregate

def test_snapshot_and_fleet_aggregate():
    regs = [MetricsRegistry(f"r{i}") for i in range(3)]
    for i, reg in enumerate(regs):
        reg.counter("replied").inc(10 * (i + 1))
        reg.gauge("corpus_version").set(i + 1)
        h = reg.histogram("lat", bounds=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
    agg = aggregate([r.snapshot() for r in regs])
    assert agg["n_sources"] == 3
    assert agg["counters"]["replied"] == 60
    assert agg["gauges"]["corpus_version"] == {
        "min": 1.0, "max": 3.0, "mean": 2.0}
    merged = agg["histograms"]["lat"]
    assert merged["count"] == 6
    assert merged["counts"][0] == 3 and merged["counts"][1] == 3


def test_aggregate_notes_mismatched_histogram_bounds():
    a, b = MetricsRegistry("a"), MetricsRegistry("b")
    a.histogram("lat", bounds=(1.0, 10.0)).observe(2.0)
    b.histogram("lat", bounds=(5.0,)).observe(2.0)
    agg = aggregate([a.snapshot(), b.snapshot()])
    # keeps the first source's histogram, skips the mismatch, and says so
    assert agg["histograms"]["lat"]["count"] == 1
    assert agg["histograms"]["lat"]["bounds"] == [1.0, 10.0]
    assert any("lat" in note for note in agg.get("notes", []))


def test_histogram_rejects_unsorted_bounds():
    reg = MetricsRegistry("svc")
    with pytest.raises((ValueError, AssertionError)):
        reg.histogram("bad", bounds=(10.0, 1.0))
