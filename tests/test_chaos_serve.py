"""Chaos-serve: seeded fault plans × overload traces against the full serving
stack. Tier-1 runs a 2-plan smoke; the 6-plan soak is marked `slow`
(run with `pytest -m slow`). Invariants asserted by every plan (see
serve/chaos_serve.py): zero unresolved requests, exactly-one-outcome per
submission, injected swap faults roll back with the old corpus still serving,
and p95 stays bounded even in degraded mode.
"""

import pytest

from dae_rnn_news_recommendation_tpu.serve import (chaos_serve_soak,
                                                   run_serve_plan,
                                                   serve_fault_plan)


def test_fault_plans_are_seeded_and_cover_all_serve_sites():
    a = serve_fault_plan(3, 48)
    b = serve_fault_plan(3, 48)
    assert [s.__dict__ for s in a.specs] == [s.__dict__ for s in b.specs]
    # across one round-robin of seeds, every serve site gets exercised
    sites = set()
    for seed in range(6):
        plan = serve_fault_plan(seed, 48)
        assert plan.specs
        sites |= {s.site for s in plan.specs}
    assert sites == {"serve.enqueue", "serve.batch", "serve.swap"}


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_serve_smoke_plan(seed):
    result = run_serve_plan(seed, n_requests=32)
    assert result.ok, result.detail
    assert result.n_unresolved == 0
    assert (result.n_replied + result.n_shed + result.n_errors
            == result.n_submitted)
    assert len(result.injected) > 0  # the plan actually fired
    if result.swap_faulted:
        assert result.swap_rolled_back
    assert result.served_after_swap
    # warmup precompiled every (bucket, k) variant: degraded modes, hot swap
    # and overload must dispatch, never retrace (compile_guard-counted)
    assert result.n_post_warm_compiles == 0


@pytest.mark.slow
def test_chaos_serve_full_soak():
    out = chaos_serve_soak(n_plans=6, n_requests=48)
    failing = [r.detail for r in out["results"] if not r.ok]
    assert out["all_ok"], failing
    assert out["n_ok"] == out["n_plans"] == 6
