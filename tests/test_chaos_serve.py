"""Chaos-serve: seeded fault plans × overload traces against the full serving
stack. Tier-1 runs a 2-plan smoke; the 6-plan soak is marked `slow`
(run with `pytest -m slow`). Invariants asserted by every plan (see
serve/chaos_serve.py): zero unresolved requests, exactly-one-outcome per
submission, injected swap faults roll back with the old corpus still serving,
and p95 stays bounded even in degraded mode.

The chaos-SHARD plans (ISSUE 13; IVF family ISSUE 16) run the mesh-sharded
sibling over the 8 virtual CPU devices conftest pins: tier-1 smokes the two
shard-loss families (seeds 0-1, one per corpus dtype) plus the sharded-IVF
loss family (seed 4, the r16 default configuration); the full 5-family soak
is `slow`.
"""

import pytest

from dae_rnn_news_recommendation_tpu.serve import (chaos_serve_soak,
                                                   chaos_shard_soak,
                                                   run_serve_plan,
                                                   run_shard_plan,
                                                   serve_fault_plan,
                                                   shard_fault_plan)


def test_fault_plans_are_seeded_and_cover_all_serve_sites():
    a = serve_fault_plan(3, 48)
    b = serve_fault_plan(3, 48)
    assert [s.__dict__ for s in a.specs] == [s.__dict__ for s in b.specs]
    # across one round-robin of seeds, every serve site gets exercised
    sites = set()
    for seed in range(6):
        plan = serve_fault_plan(seed, 48)
        assert plan.specs
        sites |= {s.site for s in plan.specs}
    assert sites == {"serve.enqueue", "serve.batch", "serve.swap"}


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_serve_smoke_plan(seed):
    result = run_serve_plan(seed, n_requests=32)
    assert result.ok, result.detail
    assert result.n_unresolved == 0
    assert (result.n_replied + result.n_shed + result.n_errors
            == result.n_submitted)
    assert len(result.injected) > 0  # the plan actually fired
    if result.swap_faulted:
        assert result.swap_rolled_back
    assert result.served_after_swap
    # warmup precompiled every (bucket, k) variant: degraded modes, hot swap
    # and overload must dispatch, never retrace (compile_guard-counted)
    assert result.n_post_warm_compiles == 0


@pytest.mark.slow
def test_chaos_serve_full_soak():
    out = chaos_serve_soak(n_plans=6, n_requests=48)
    failing = [r.detail for r in out["results"] if not r.ok]
    assert out["all_ok"], failing
    assert out["n_ok"] == out["n_plans"] == 6


# ------------------------------------------------- chaos-shard (ISSUE 13)

def test_shard_fault_plans_are_seeded_and_cover_all_families():
    a = shard_fault_plan(2)
    b = shard_fault_plan(2)
    assert [s.__dict__ for s in a.specs] == [s.__dict__ for s in b.specs]
    sites = set()
    for seed in range(5):
        plan = shard_fault_plan(seed)
        assert plan.specs
        sites |= {s.site for s in plan.specs}
    # three loss families plan the harness directive, two crash families
    # plan in-line prepare fatals — one per swap flavor
    assert sites == {"serve.shard", "refresh.swap", "serve.swap"}
    # the serve.shard directive is harness-applied, never fired in-line
    for seed in (0, 1, 4):
        plan = shard_fault_plan(seed)
        assert plan.harness_specs and not plan.inline_specs


@pytest.mark.parametrize("seed", [0, 1, 4])
def test_chaos_shard_smoke_plan(seed):
    """Tier-1 shard-loss smoke: seed 0 loses a float32 embedding shard under
    load (quarantine -> partial_corpus -> blocked swaps -> recover); seed 1
    loses an int8 corpus's scales shard inside an append's prepare phase
    (the commit heals it); seed 4 runs the r16 DEFAULT sharded+IVF
    configuration and loses a cell-owning shard under load — quarantine
    masks the lost cells and recovery restores the index slabs. All must
    end bitwise-equal to the fault-free reference with zero torn reads and
    zero post-warmup compiles."""
    result = run_shard_plan(seed, n_requests=24)
    assert result.ok, result.detail
    assert result.n_replied + result.n_shed + result.n_errors \
        == result.n_submitted
    assert result.n_errors == 0 and result.n_shed == 0
    assert result.bitwise_recovered
    assert result.n_read_samples > 0
    assert result.n_post_warm_compiles == 0
    assert any(e.get("site") == "serve.shard" for e in result.injected)
    if result.family.endswith("shard-lost-under-load"):
        assert result.n_partial > 0
        assert 0.0 < result.min_coverage < 1.0
    else:
        assert result.n_partial == 0 and result.min_coverage == 1.0


@pytest.mark.slow
def test_chaos_shard_full_soak():
    out = chaos_shard_soak(n_plans=5, n_requests=24)
    failing = [f"{r.seed}[{r.family}]: {r.detail}"
               for r in out["results"] if not r.ok]
    assert out["all_ok"], failing
    assert out["n_ok"] == out["n_plans"] == 5
    families = {r.family for r in out["results"]}
    assert len(families) == 5
    dtypes = {r.dtype for r in out["results"]}
    assert dtypes == {"float32", "int8"}
