"""Article pipeline tests: label engineering, pos/neg mapping, vectorization,
synthetic corpus shape."""

import numpy as np
import pandas as pd
import pytest

from dae_rnn_news_recommendation_tpu.data import articles


@pytest.fixture
def df():
    return articles.synthetic_articles(n_articles=300, vocab_size=500,
                                       words_per_article=40, seed=1)


def test_synthetic_articles_schema(df):
    for col in ("article_id", "title", "main_content", "category_publish_name", "story"):
        assert col in df.columns
    assert df.main_content.str.len().min() > 0
    assert df.category_publish_name.nunique() > 2
    assert df.story.notna().sum() > 0


def test_read_articles_story_extraction(tmp_path, df):
    path = tmp_path / "a.parquet"
    df.drop(columns=["story"]).to_parquet(path)
    back = articles.read_articles(path)
    # story re-extracted from the 【...（ title pattern
    assert back.story.notna().sum() > 0
    extracted = back[back.story.notna()].story.iloc[0]
    assert extracted.startswith("story_")


def test_similar_articles_mapping(df):
    out = articles.similar_articles(df, id_colname="article_id",
                                    cate_colname="category_publish_name", seed=0)
    valid = out[out.valid_triplet_data == 1]
    assert len(valid) > 0
    by_id = out.set_index("article_id")
    for _, row in valid.head(20).iterrows():
        # positive shares the category, negative does not
        assert by_id.loc[row.article_id_pos].category_publish_name == row.category_publish_name
        assert by_id.loc[row.article_id_neg].category_publish_name != row.category_publish_name


def test_similar_articles_story_keyed(df):
    # net-new story-keyed mapping (cli/main_autoencoder_triplet.py --label
    # story): positive shares the STORY, negative comes from a different (or
    # no) story — the signal the reference's category-keyed recipe cannot
    # carry by construction (reference datasets/articles.py:83-128)
    out = articles.similar_articles(df, id_colname="article_id",
                                    cate_colname="story", seed=0)
    valid = out[out.valid_triplet_data == 1]
    assert len(valid) > 0
    by_id = out.set_index("article_id")
    for _, row in valid.head(20).iterrows():
        assert row.story is not None
        assert by_id.loc[row.article_id_pos].story == row.story
        assert by_id.loc[row.article_id_neg].story != row.story


def test_count_vectorize_shared_vocab(df):
    out = articles.similar_articles(df, cate_colname="category_publish_name", seed=0)
    valid = out[out.valid_triplet_data == 1].head(50)
    content = out.main_content
    cv, X, X_pos, X_neg = articles.count_vectorize(
        valid.main_content, content.loc[valid.article_id_pos],
        content.loc[valid.article_id_neg], tokenizer=None, max_features=200)
    assert X.shape == X_pos.shape == X_neg.shape
    assert X.shape[1] <= 200


def test_tfidf_transform(df):
    cv, X, _, _ = articles.count_vectorize(df.main_content, tokenizer=None,
                                           max_features=100)
    tt, X_tfidf = articles.tfidf_transform(X)
    assert X_tfidf.shape == X.shape
    # sklearn l2-normalizes rows by default
    norms = np.sqrt(np.asarray(X_tfidf.multiply(X_tfidf).sum(axis=1))).ravel()
    np.testing.assert_allclose(norms[norms > 0], 1.0, rtol=1e-6)
