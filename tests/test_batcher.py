"""Batcher tests, modeled on reference autoencoder/tests/test_utils.py:11-106: the
identity-column trick (data column 0 = row index) verifies (data, label) alignment
after shuffling; exact-coverage check verifies every row appears exactly once."""

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from dae_rnn_news_recommendation_tpu.data import batcher as B

N, F = 23, 6


def _identity_data(kind):
    x = np.zeros((N, F), np.float32)
    x[:, 0] = np.arange(N)
    x[:, 1:] = np.random.default_rng(0).uniform(1, 2, (N, F - 1))
    if kind == "csr":
        return sp.csr_matrix(x)
    if kind == "df":
        return pd.DataFrame(x)
    return x


@pytest.mark.parametrize("kind", ["ndarray", "csr", "df"])
@pytest.mark.parametrize("batch_size", [4, 0.3])
@pytest.mark.parametrize("label_kind", [None, "np1d", "np2d", "series", "df"])
def test_padded_batcher_alignment(kind, batch_size, label_kind):
    data = _identity_data(kind)
    labels = None
    if label_kind == "np1d":
        labels = np.arange(N)
    elif label_kind == "np2d":
        labels = np.arange(N).reshape(-1, 1)
    elif label_kind == "series":
        labels = pd.Series(np.arange(N))
    elif label_kind == "df":
        labels = pd.DataFrame(np.arange(N))

    row_show = np.zeros(N)
    for batch in B.PaddedBatcher(batch_size, seed=1).epoch(data, labels):
        x, valid = batch["x"], batch["row_valid"]
        bsz = x.shape[0]
        assert valid.shape == (bsz,)
        real = valid > 0
        ids = x[real, 0].astype(int)
        row_show[ids] += 1
        # padded rows are all-zero
        np.testing.assert_array_equal(x[~real], 0.0)
        if labels is not None:
            lab = batch["labels"]
            # label rides with its row through the shuffle
            np.testing.assert_array_equal(lab[real], ids)
            np.testing.assert_array_equal(lab[~real], -1)
    assert row_show.sum() == N
    assert (row_show == 1).all()


def test_batch_shapes_are_static():
    data = _identity_data("ndarray")
    shapes = {b["x"].shape for b in B.PaddedBatcher(4, seed=0).epoch(data)}
    assert shapes == {(4, F)}  # 23 rows -> 6 batches, last one padded


def test_mesh_batch_multiple_rounds_up():
    data = _identity_data("ndarray")
    shapes = {b["x"].shape for b in B.PaddedBatcher(6, seed=0, mesh_batch_multiple=8).epoch(data)}
    assert shapes == {(8, F)}


def test_resolve_batch_size():
    assert B.resolve_batch_size(4, 100) == 4
    assert B.resolve_batch_size(0.3, 23) == max(round(23 * 0.3), 1)
    assert B.resolve_batch_size(0.0001, 100) == 1
    with pytest.raises(AssertionError):
        B.resolve_batch_size(0, 10)


@pytest.mark.parametrize("batch_size", [4, 0.3])
def test_gen_batches_parity(batch_size):
    """Reference-compatible generator keeps ragged shapes and type fidelity."""
    data = _identity_data("ndarray")
    corr = data * 0.5
    labels = np.arange(N)
    seen = []
    for x, xc, lab in B.gen_batches(data, corr, batch_size, data_label=labels, seed=3):
        np.testing.assert_allclose(xc, x * 0.5)
        np.testing.assert_array_equal(lab, x[:, 0].astype(int))
        seen.extend(x[:, 0].astype(int))
    assert sorted(seen) == list(range(N))


def test_gen_batches_triplet_shared_shuffle():
    org = _identity_data("ndarray")
    d = {"org": org, "pos": org + 100, "neg": org + 200}
    dc = {k: v for k, v in d.items()}
    for (xs, xcs) in B.gen_batches_triplet(d, dc, 5, seed=4):
        base = xs[0][:, 0]
        np.testing.assert_array_equal(xs[1][:, 0], base + 100)
        np.testing.assert_array_equal(xs[2][:, 0], base + 200)


def test_triplet_padded_batcher_alignment():
    org = _identity_data("csr")
    data = {"org": org, "pos": sp.csr_matrix(org.toarray() + 100),
            "neg": sp.csr_matrix(org.toarray() + 200)}
    row_show = np.zeros(N)
    for batch in B.TripletPaddedBatcher(5, seed=5).epoch(data):
        real = batch["row_valid"] > 0
        base = batch["org"][real, 0]
        np.testing.assert_array_equal(batch["pos"][real, 0], base + 100)
        np.testing.assert_array_equal(batch["neg"][real, 0], base + 200)
        row_show[base.astype(int)] += 1
    assert (row_show == 1).all()


def test_densify_rows_types():
    x = np.eye(5, dtype=np.float32)
    for data in (x, sp.csr_matrix(x), pd.DataFrame(x)):
        out = B.densify_rows(data, np.array([2, 0]))
        assert out.dtype == np.float32
        np.testing.assert_array_equal(out, x[[2, 0]])


def test_prefetch_preserves_order_and_content(rng):
    from dae_rnn_news_recommendation_tpu.data.batcher import PaddedBatcher, prefetch

    X = rng.uniform(size=(50, 6)).astype(np.float32)
    b1 = PaddedBatcher(16, shuffle=True, seed=3)
    b2 = PaddedBatcher(16, shuffle=True, seed=3)
    direct = list(b1.epoch(X))
    threaded = list(prefetch(b2.epoch(X), depth=2))
    assert len(direct) == len(threaded)
    for d, t in zip(direct, threaded):
        np.testing.assert_array_equal(d["x"], t["x"])
        np.testing.assert_array_equal(d["row_valid"], t["row_valid"])


def test_prefetch_propagates_errors_and_depth_zero():
    from dae_rnn_news_recommendation_tpu.data.batcher import prefetch

    def boom():
        yield 1
        raise RuntimeError("producer failed")

    it = prefetch(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer failed"):
        list(it)

    plain = iter([1, 2])
    assert prefetch(plain, depth=0) is plain


def test_prefetch_abandoned_consumer_releases_worker():
    """Breaking out of a prefetch loop must retire the worker thread rather than
    leaving it blocked on the full queue."""
    import gc
    import threading
    import time

    from dae_rnn_news_recommendation_tpu.data.batcher import prefetch

    produced = []

    def source():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    it = prefetch(source(), depth=2)
    assert next(it) == 0
    it.close()  # abandon
    gc.collect()
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "prefetch worker thread leaked"
    assert len(produced) < 1000  # producer stopped early, didn't drain the source


class TestSparseIngestBatcher:
    def test_densify_on_device_recovers_dense_batch(self, rng):
        """The sparse-ingest feed + on-device densify must reproduce the dense
        feed's x exactly, batch by batch (same shuffle seed)."""
        import scipy.sparse as sp

        from dae_rnn_news_recommendation_tpu.data.batcher import (
            PaddedBatcher, SparseIngestBatcher)
        from dae_rnn_news_recommendation_tpu.ops.sparse_ingest import (
            densify_on_device)

        dense = rng.uniform(size=(50, 30)).astype(np.float32)
        dense[dense < 0.7] = 0.0
        data = sp.csr_matrix(dense)
        labels = rng.integers(0, 4, 50)

        dense_batches = list(PaddedBatcher(16, seed=7).epoch(data, labels))
        sparse_batches = list(SparseIngestBatcher(16, seed=7).epoch(data, labels))
        assert len(dense_batches) == len(sparse_batches)
        for db, sb in zip(dense_batches, sparse_batches):
            assert set(sb) == {"indices", "values", "row_valid", "labels"}
            x = np.asarray(densify_on_device(sb["indices"], sb["values"], 30))
            np.testing.assert_array_equal(x, db["x"])
            np.testing.assert_array_equal(sb["row_valid"], db["row_valid"])
            np.testing.assert_array_equal(sb["labels"], db["labels"])

    def test_fit_sparse_feed_matches_dense_feed(self, tmp_path, monkeypatch, rng):
        """Training through the sparse-ingest feed must be bit-identical to the
        dense feed (same seed): densify-on-device is exact, not approximate."""
        import scipy.sparse as sp

        from dae_rnn_news_recommendation_tpu.models import DenoisingAutoencoder

        monkeypatch.chdir(tmp_path)
        dense = (rng.uniform(size=(60, 24)) < 0.3).astype(np.float32)
        data = sp.csr_matrix(dense)
        labels = rng.integers(0, 4, 60)
        kw = dict(compress_factor=6, num_epochs=3, batch_size=16, opt="ada_grad",
                  learning_rate=0.1, corr_type="masking", corr_frac=0.3,
                  verbose=False, seed=11, triplet_strategy="batch_all",
                  use_tensorboard=False)
        m_sparse = DenoisingAutoencoder(model_name="sp", **kw)
        m_sparse.fit(data, train_set_label=labels)
        m_dense = DenoisingAutoencoder(model_name="dn", sparse_feed=False, **kw)
        m_dense.fit(data, train_set_label=labels)
        for k in m_sparse.params:
            np.testing.assert_array_equal(np.asarray(m_sparse.params[k]),
                                          np.asarray(m_dense.params[k]), err_msg=k)

    def test_triplet_fit_sparse_feed_matches_dense_feed(self, tmp_path,
                                                        monkeypatch, rng):
        """The precomputed-triplet estimator must train bit-identically through
        the triplet sparse-ingest feed."""
        import scipy.sparse as sp

        from dae_rnn_news_recommendation_tpu.models import (
            DenoisingAutoencoderTriplet)

        monkeypatch.chdir(tmp_path)
        def mat(seed):
            return sp.random(40, 24, density=0.3, format="csr",
                             random_state=seed, dtype=np.float64)

        train = {"org": mat(0), "pos": mat(1), "neg": mat(2)}
        kw = dict(compress_factor=6, num_epochs=3, batch_size=16, opt="ada_grad",
                  learning_rate=0.1, corr_type="masking", corr_frac=0.3,
                  verbose=False, seed=11, use_tensorboard=False)
        m_sparse = DenoisingAutoencoderTriplet(model_name="tsp", **kw)
        m_sparse.fit(train)
        m_dense = DenoisingAutoencoderTriplet(model_name="tdn",
                                              sparse_feed=False, **kw)
        m_dense.fit(train)
        for k in m_sparse.params:
            np.testing.assert_array_equal(np.asarray(m_sparse.params[k]),
                                          np.asarray(m_dense.params[k]),
                                          err_msg=k)
