"""Mining-implementation dispatch (ISSUE 5): the blockwise O(B^2) scan and
the Pallas kernels must be drop-in parity twins of the dense reference
(ops/triplet.py) — values, data weights, extras, AND gradients — and the
`mining_impl` knob must resolve exactly as documented (docs/mining.md).

Everything here runs on CPU: blockwise is plain XLA, and the Pallas paths run
in interpreter mode (the same math, minus Mosaic). Hardware-compiled parity
is covered by tests/test_pallas_kernels.py's TPU-gated cases.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dae_rnn_news_recommendation_tpu.ops import triplet
from dae_rnn_news_recommendation_tpu.ops.triplet_blockwise import (
    batch_all_triplet_loss_blockwise, batch_hard_triplet_loss_blockwise)
from dae_rnn_news_recommendation_tpu.train.step import (
    MINING_IMPLS, _DENSE_AUTO_MAX_ROWS, loss_and_metrics, mine_triplets,
    resolve_mining_impl)

ON_TPU = jax.default_backend() == "tpu"


# ------------------------------------------------------------- resolution

def test_explicit_impls_are_honored():
    for impl in ("dense", "blockwise", "pallas"):
        assert resolve_mining_impl(impl, 8) == impl
        assert resolve_mining_impl(impl, 100_000) == impl


def test_auto_small_batch_is_dense():
    """<= the dense ceiling stays on the reference path — the measured-fastest
    implementation at record shapes, and byte-stable with prior CPU records."""
    assert resolve_mining_impl("auto", 8) == "dense"
    assert resolve_mining_impl("auto", _DENSE_AUTO_MAX_ROWS) == "dense"


def test_auto_large_batch_leaves_dense():
    impl = resolve_mining_impl("auto", _DENSE_AUTO_MAX_ROWS + 1)
    assert impl == ("pallas" if ON_TPU else "blockwise")
    assert resolve_mining_impl("auto", 8192) == impl


def test_unknown_impl_raises():
    with pytest.raises(ValueError, match="mining_impl"):
        resolve_mining_impl("cube", 8)
    assert "cube" not in MINING_IMPLS


# ----------------------------------------------------------------- parity

def _rand_case(rng, b, d=7, n_classes=4, valid_frac=None):
    labels = jnp.asarray(rng.integers(0, n_classes, b), jnp.int32)
    enc = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
    rv = None
    if valid_frac is not None:
        rv = jnp.asarray((rng.uniform(size=b) < valid_frac)
                         .astype(np.float32))
    return labels, enc, rv


def _assert_tuple_close(ref, got, rtol=1e-5, atol=1e-6):
    loss_r, dw_r, frac_r, num_r, ex_r = ref
    loss_g, dw_g, frac_g, num_g, ex_g = got
    np.testing.assert_allclose(float(loss_r), float(loss_g),
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(dw_r), np.asarray(dw_g),
                               rtol=rtol, atol=1e-4)
    np.testing.assert_allclose(float(frac_r), float(frac_g), rtol=rtol,
                               atol=atol)
    np.testing.assert_allclose(float(num_r), float(num_g), rtol=rtol,
                               atol=atol)
    assert set(ex_r) == set(ex_g)
    for k in ex_r:
        np.testing.assert_allclose(float(ex_r[k]), float(ex_g[k]),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("b,valid_frac", [(13, None), (20, 0.7), (8, 0.5),
                                          (5, None)])
@pytest.mark.parametrize("pos_only", [False, True])
def test_blockwise_batch_all_matches_dense(rng, b, valid_frac, pos_only):
    labels, enc, rv = _rand_case(rng, b, valid_frac=valid_frac)
    ref = triplet.batch_all_triplet_loss(labels, enc,
                                         pos_triplets_only=pos_only,
                                         row_valid=rv)
    got = batch_all_triplet_loss_blockwise(labels, enc,
                                           pos_triplets_only=pos_only,
                                           row_valid=rv, anchor_tile=4)
    _assert_tuple_close(ref, got)


@pytest.mark.parametrize("b,valid_frac", [(13, None), (20, 0.7), (8, 0.5)])
def test_blockwise_batch_hard_matches_dense(rng, b, valid_frac):
    """Including the dense path's observable quirks: zero-valued invalid
    negatives in the hardest-negative max and float-equality tie counting."""
    labels, enc, rv = _rand_case(rng, b, valid_frac=valid_frac)
    ref = triplet.batch_hard_triplet_loss(labels, enc, row_valid=rv)
    got = batch_hard_triplet_loss_blockwise(labels, enc, row_valid=rv,
                                            anchor_tile=4)
    _assert_tuple_close(ref, got)


@pytest.mark.parametrize("strategy", ["batch_all", "batch_hard"])
@pytest.mark.parametrize("impl", ["blockwise", "pallas"])
def test_gradients_match_dense(rng, strategy, impl):
    """The custom VJPs (blockwise batch_all rescan; pallas recompute-through-
    blockwise) must equal XLA autodiff of the dense oracle."""
    labels, enc, rv = _rand_case(rng, 19, valid_frac=0.8)

    def loss_via(impl_name):
        def f(e):
            return mine_triplets(strategy, labels, e, row_valid=rv,
                                 mining_impl=impl_name)[0]
        return f

    l_ref, g_ref = jax.value_and_grad(loss_via("dense"))(enc)
    l_got, g_got = jax.value_and_grad(loss_via(impl))(enc)
    np.testing.assert_allclose(float(l_got), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               atol=1e-5)


def test_single_class_edge_all_impls(rng):
    """One class -> no negatives. batch_all mines nothing (loss 0, num 0,
    weights 0) on every implementation. batch_hard is NOT zero here — the
    dense reference's zero-valued invalid negatives make hardest_neg == 0 a
    live competitor — so the contract is cross-impl agreement on the quirk,
    not a zero."""
    labels = jnp.zeros(12, jnp.int32)
    enc = jnp.asarray(rng.normal(size=(12, 5)).astype(np.float32))
    for impl in ("dense", "blockwise", "pallas"):
        loss, dw, _, num, _ = mine_triplets("batch_all", labels, enc,
                                            mining_impl=impl)
        assert float(loss) == 0.0 and float(num) == 0.0, impl
        np.testing.assert_array_equal(np.asarray(dw), 0.0)
    ref = mine_triplets("batch_hard", labels, enc, mining_impl="dense")
    for impl in ("blockwise", "pallas"):
        _assert_tuple_close(ref, mine_triplets("batch_hard", labels, enc,
                                               mining_impl=impl))


# ------------------------------------------------- objective-level parity

def _objective_case(rng, b=16, f=12, d=5, strategy="batch_all",
                    with_labels2=False, mining_impl="auto"):
    from dae_rnn_news_recommendation_tpu.models import DAEConfig, init_params

    config = DAEConfig(
        n_features=f, n_components=d, enc_act_func="tanh",
        dec_act_func="none", loss_func="mean_squared", corr_type="none",
        triplet_strategy=strategy, alpha=1.0,
        label2_alpha=0.5 if with_labels2 else 0.0, mining_impl=mining_impl)
    params = init_params(jax.random.PRNGKey(0), config)
    batch = {
        "x": jnp.asarray(rng.uniform(size=(b, f)).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 3, b), jnp.int32),
        "row_valid": jnp.asarray((rng.uniform(size=b) < 0.85)
                                 .astype(np.float32)),
    }
    if with_labels2:
        # include some -1 "no secondary label" rows (the factorize contract)
        batch["labels2"] = jnp.asarray(rng.integers(-1, 4, b), jnp.int32)
    return config, params, batch


@pytest.mark.parametrize("strategy", ["batch_all", "batch_hard"])
@pytest.mark.parametrize("with_labels2", [False, True])
def test_objective_parity_blockwise_vs_dense(rng, strategy, with_labels2):
    """loss_and_metrics end to end — the full objective including the
    label2_alpha second mining term — agrees across implementations, values
    and parameter gradients both."""
    config, params, batch = _objective_case(
        rng, strategy=strategy, with_labels2=with_labels2)

    def cost_with(impl):
        import dataclasses
        cfg = dataclasses.replace(config, mining_impl=impl)

        def f(p):
            return loss_and_metrics(p, batch, jax.random.PRNGKey(1), cfg)
        return f

    (c_ref, m_ref), g_ref = jax.value_and_grad(
        cost_with("dense"), has_aux=True)(params)
    (c_got, m_got), g_got = jax.value_and_grad(
        cost_with("blockwise"), has_aux=True)(params)
    np.testing.assert_allclose(float(c_got), float(c_ref), rtol=1e-5)
    np.testing.assert_allclose(float(m_got["triplet_loss"]),
                               float(m_ref["triplet_loss"]), rtol=1e-5)
    for (ka, ga), (kb, gb) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(g_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(g_got),
                   key=lambda kv: str(kv[0]))):
        assert str(ka) == str(kb)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(ga),
                                   atol=1e-5, err_msg=str(ka))


def test_auto_default_is_bitwise_dense_at_small_batch(rng):
    """Acceptance: dispatch defaults keep existing CPU records byte-stable —
    "auto" at a record-sized batch must produce the IDENTICAL program, so
    cost and metrics match bit for bit, not just to tolerance."""
    config, params, batch = _objective_case(rng, mining_impl="auto")
    import dataclasses
    dense_cfg = dataclasses.replace(config, mining_impl="dense")
    c_auto, m_auto = jax.jit(loss_and_metrics, static_argnums=(3,))(
        params, batch, jax.random.PRNGKey(1), config)
    c_dense, m_dense = jax.jit(loss_and_metrics, static_argnums=(3,))(
        params, batch, jax.random.PRNGKey(1), dense_cfg)
    assert np.asarray(c_auto).tobytes() == np.asarray(c_dense).tobytes()
    for k in m_auto:
        assert (np.asarray(m_auto[k]).tobytes()
                == np.asarray(m_dense[k]).tobytes()), k
