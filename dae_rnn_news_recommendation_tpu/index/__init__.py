"""Clustered (IVF) retrieval index: on-device k-means + cell-major layout.

`kmeans_fit` partitions the resident corpus into spherical cells (seeded
from the serving slot's drift-gate centroid), `build_cells` permutes the
quantized corpus into contiguous per-cell slabs, and `ops/ivf_topk.py`
scores queries against only the probed slabs. `assign_cells` is the churn
composition hook: appended rows route to existing cells without a refit.
"""

from .kmeans import KMeansResult, assign_cells, kmeans_fit
from .layout import (CAP_ROUND, IVFCells, ShardedIVFCells, build_cells,
                     build_sharded_cells, cell_shard_owner, cell_stats)

__all__ = [
    "CAP_ROUND",
    "IVFCells",
    "KMeansResult",
    "ShardedIVFCells",
    "assign_cells",
    "build_cells",
    "build_sharded_cells",
    "cell_shard_owner",
    "cell_stats",
    "kmeans_fit",
]
