"""Cell-major corpus layout for clustered (IVF) retrieval.

The flat serving slot keeps rows in ingest order; the IVF scorer instead
wants each k-means cell's rows CONTIGUOUS so a probed cell is one aligned
`[cell_cap, D]` panel copy HBM->VMEM (the repo's Mosaic notes in
`ops/pallas_kernels.py` require dynamic-slice offsets aligned to the tile
grid — uniform cell capacity gives that alignment for free). The layout is
a *permutation view* of the slot's already-quantized arrays, never a
re-quantization: a row's int8 payload and scale are bitwise the ones the
exact scorer reads, which is what makes `probes = n_cells` parity exact.

Shape contract (`C = n_cells`, `cap = cell_cap`, uniform):

    cell_emb    [(C+1)*cap, D]  slot dtype; cell c occupies rows
                                [c*cap, (c+1)*cap)
    cell_valid  [(C+1)*cap]     slot valid gathered; padding slots 0
    cell_scales [(C+1)*cap]     per-row dequant scales; padding slots 1
    row_ids     [(C+1)*cap]     ORIGINAL slot row index, or INT32_MAX for
                                padding — the scorer tie-breaks on these,
                                so padding loses every -inf tie to real rows
    assign      [N]             cell id per original row (jnp fallback mask
                                + append routing)

Cell `C` (one extra) is an all-padding dummy: shortlist dedup and query
padding point at it, so every shortlist entry is always a readable panel.
Rows within a cell keep ascending original order (stable sort), though the
scorer does not rely on it.
"""

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..ops.topk_fused import _IDX_SENTINEL

# uniform cell capacity is rounded up to the int8 sublane tile (32), the
# strictest of the f32/bf16/int8 minimums, so one layout serves every dtype
CAP_ROUND = 32


class IVFCells(NamedTuple):
    """Device-resident IVF index: pytree-safe, jit-traceable as an argument."""

    centroids: jnp.ndarray    # [C, D] f32 unit rows
    cell_emb: jnp.ndarray     # [(C+1)*cap, D] slot dtype
    cell_valid: jnp.ndarray   # [(C+1)*cap] f32
    cell_scales: jnp.ndarray  # [(C+1)*cap] f32
    row_ids: jnp.ndarray      # [(C+1)*cap] int32
    assign: jnp.ndarray       # [N] int32

    @property
    def n_cells(self):
        return self.centroids.shape[0]

    @property
    def cell_cap(self):
        return self.row_ids.shape[0] // (self.centroids.shape[0] + 1)

    @property
    def n_rows(self):
        return self.assign.shape[0]

    def resident_bytes(self):
        return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in
                       (self.centroids, self.cell_emb, self.cell_valid,
                        self.cell_scales, self.row_ids, self.assign)))


def build_cells(emb, valid, scales, centroids, assign):
    """Permute a (quantized) corpus into cell-major slabs.

    :param emb: [N, D] slot embeddings, any corpus dtype — gathered as-is
    :param valid: [N] mask
    :param scales: [N] f32 per-row dequant scales, or None for ones
    :param centroids: [C, D] f32 (host or device)
    :param assign: [N] int32 cell id per row (host)
    :returns: IVFCells with all large arrays on device
    """
    emb = jnp.asarray(emb)
    n = emb.shape[0]
    assign_np = np.asarray(assign).astype(np.int64)
    c = int(np.asarray(centroids).shape[0])
    if assign_np.shape[0] != n:
        raise ValueError(f"assign covers {assign_np.shape[0]} rows, corpus {n}")
    counts = np.bincount(assign_np, minlength=c) if n else np.zeros(c, np.int64)
    cap = int(max(CAP_ROUND, -(-int(counts.max(initial=0)) // CAP_ROUND) * CAP_ROUND))

    # stable sort keeps ascending original order within each cell; the
    # vectorized fill places sorted row r at (its cell, its rank in the cell)
    pos = np.full((c + 1, cap), -1, np.int64)
    order = np.argsort(assign_np, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])
    in_cell = np.arange(n, dtype=np.int64) - starts[assign_np[order]]
    pos[assign_np[order], in_cell] = order

    flat = pos.reshape(-1)
    present = flat >= 0
    gather = jnp.asarray(np.where(present, flat, 0).astype(np.int32))
    mask = jnp.asarray(present)
    scales_j = (jnp.ones((n,), jnp.float32) if scales is None
                else jnp.asarray(scales, jnp.float32))
    return IVFCells(
        centroids=jnp.asarray(centroids, jnp.float32),
        cell_emb=jnp.take(emb, gather, axis=0),
        cell_valid=jnp.where(mask, jnp.take(
            jnp.asarray(valid).astype(jnp.float32), gather), 0.0),
        cell_scales=jnp.where(mask, jnp.take(scales_j, gather), 1.0),
        row_ids=jnp.asarray(
            np.where(present, flat, _IDX_SENTINEL).astype(np.int32)),
        assign=jnp.asarray(assign_np.astype(np.int32)),
    )


def cell_stats(cells):
    """Host-side occupancy stats driving the staleness/rebuild decision."""
    c, cap = cells.n_cells, cells.cell_cap
    ids = np.asarray(cells.row_ids).reshape(c + 1, cap)[:c]
    counts = (ids != _IDX_SENTINEL).sum(axis=1).astype(np.int64)
    total = int(counts.sum())
    mean = total / c if c else 0.0
    return {
        "n_cells": c,
        "cell_cap": cap,
        "counts": counts,
        "imbalance": float(counts.max(initial=0) / mean) if mean > 0 else 1.0,
        "frac_empty": float((counts == 0).mean()) if c else 0.0,
        "n_rows": total,
    }
