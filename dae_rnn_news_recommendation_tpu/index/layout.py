"""Cell-major corpus layout for clustered (IVF) retrieval.

The flat serving slot keeps rows in ingest order; the IVF scorer instead
wants each k-means cell's rows CONTIGUOUS so a probed cell is one aligned
`[cell_cap, D]` panel copy HBM->VMEM (the repo's Mosaic notes in
`ops/pallas_kernels.py` require dynamic-slice offsets aligned to the tile
grid — uniform cell capacity gives that alignment for free). The layout is
a *permutation view* of the slot's already-quantized arrays, never a
re-quantization: a row's int8 payload and scale are bitwise the ones the
exact scorer reads, which is what makes `probes = n_cells` parity exact.

Shape contract (`C = n_cells`, `cap = cell_cap`, uniform):

    cell_emb    [(C+1)*cap, D]  slot dtype; cell c occupies rows
                                [c*cap, (c+1)*cap)
    cell_valid  [(C+1)*cap]     slot valid gathered; padding slots 0
    cell_scales [(C+1)*cap]     per-row dequant scales; padding slots 1
    row_ids     [(C+1)*cap]     ORIGINAL slot row index, or INT32_MAX for
                                padding — the scorer tie-breaks on these,
                                so padding loses every -inf tie to real rows
    assign      [N]             cell id per original row (jnp fallback mask
                                + append routing)

Cell `C` (one extra) is an all-padding dummy: shortlist dedup and query
padding point at it, so every shortlist entry is always a readable panel.
Rows within a cell keep ascending original order (stable sort), though the
scorer does not rely on it.

SHARDED layout (`ShardedIVFCells`, built by `build_sharded_cells`): the same
permutation view partitioned across a 1-D device mesh so a corpus can
outgrow one device. Cells are partitioned BY CENTROID — shard `s` owns whole
cells `[s*cps, (s+1)*cps)` with `cps = ceil(C / n_shards)` — and the slab
array is SHARD-MAJOR: shard `s`'s region starts at per-shard row offset
`s * (cps+1) * cap` and holds its `cps` owned cells plus its OWN local dummy
slab (shortlist entries a shard does not own point at its local dummy, so
every shard's gather stays a readable panel). Cells past `C` (when
`n_shards` does not divide `C`) are empty padding cells on the last shards —
never probed, because the replicated centroid scan only knows `C` real
centroids. Every shard's region is the same `(cps+1)*cap` rows, so
`parallel.mesh.shard_rows` places the slab arrays with each shard's cells
exactly on its own device; `row_ids` keep ORIGINAL (global) slot row
numbers, which is what makes the cross-shard merge index-exact.
"""

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.tile_defaults import IVF_CAP_MULTIPLE
from ..ops.topk_fused import _IDX_SENTINEL

# uniform cell capacity rounds up to a multiple of the int8 sublane tile
# (32), the strictest of the f32/bf16/int8 minimums, so one layout serves
# every dtype; the default multiple lives in ops/tile_defaults.py and the
# autotuner may recommend a larger one (fewer, longer panel DMAs) via
# tuning.cap_multiple_hint()
CAP_ROUND = IVF_CAP_MULTIPLE


class IVFCells(NamedTuple):
    """Device-resident IVF index: pytree-safe, jit-traceable as an argument."""

    centroids: jnp.ndarray    # [C, D] f32 unit rows
    cell_emb: jnp.ndarray     # [(C+1)*cap, D] slot dtype
    cell_valid: jnp.ndarray   # [(C+1)*cap] f32
    cell_scales: jnp.ndarray  # [(C+1)*cap] f32
    row_ids: jnp.ndarray      # [(C+1)*cap] int32
    assign: jnp.ndarray       # [N] int32

    @property
    def n_cells(self):
        return self.centroids.shape[0]

    @property
    def cell_cap(self):
        return self.row_ids.shape[0] // (self.centroids.shape[0] + 1)

    @property
    def n_rows(self):
        return self.assign.shape[0]

    def resident_bytes(self):
        return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in
                       (self.centroids, self.cell_emb, self.cell_valid,
                        self.cell_scales, self.row_ids, self.assign)))


@dataclasses.dataclass(frozen=True)
class ShardedIVFCells:
    """Shard-major IVF index over a row-sharded corpus.

    The slab arrays hold `n_shards * (cells_per_shard + 1)` cell slabs in
    shard-major order (each shard's owned cells, then its local dummy) and
    are placed row-sharded so shard `s`'s slabs live on device `s`.
    `centroids` and `assign` are replicated — the centroid scan runs on
    every device. The int fields are pytree AUX DATA (static at trace
    time), so the per-shard gather can derive its shapes and ownership
    arithmetic without tracing them."""

    centroids: object      # [C, D] f32 unit rows, replicated
    cell_emb: object       # [n_shards*(cps+1)*cap, D] slot dtype, row-sharded
    cell_valid: object     # [n_shards*(cps+1)*cap] f32, row-sharded
    cell_scales: object    # [n_shards*(cps+1)*cap] f32, row-sharded
    row_ids: object        # [n_shards*(cps+1)*cap] int32 GLOBAL slot rows
    assign: object         # [N] int32, replicated
    n_shards: int
    cells_per_shard: int   # cps: ceil(C / n_shards), whole cells per shard
    cell_cap: int          # uniform rows per cell slab

    @property
    def n_cells(self):
        return self.centroids.shape[0]

    @property
    def n_rows(self):
        return self.assign.shape[0]

    @property
    def shard_rows(self):
        """Per-shard row stride: shard s's slabs start at s * shard_rows."""
        return (self.cells_per_shard + 1) * self.cell_cap

    def replace(self, **kw):
        return dataclasses.replace(self, **kw)

    def resident_bytes(self):
        return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in
                       (self.centroids, self.cell_emb, self.cell_valid,
                        self.cell_scales, self.row_ids, self.assign)))


jax.tree_util.register_pytree_node(
    ShardedIVFCells,
    lambda c: ((c.centroids, c.cell_emb, c.cell_valid, c.cell_scales,
                c.row_ids, c.assign),
               (c.n_shards, c.cells_per_shard, c.cell_cap)),
    lambda aux, ch: ShardedIVFCells(*ch, *aux))


def cell_shard_owner(cells):
    """[C] int: which shard owns each real cell (cell // cells_per_shard)."""
    return np.arange(cells.n_cells) // int(cells.cells_per_shard)


def _cell_positions(assign_np, counts, cap, n_slabs, slab_of_cell):
    """[n_slabs, cap] original-row positions (-1 = padding): stable sort
    keeps ascending original order within each cell; the vectorized fill
    places sorted row r at (its cell's slab, its rank in the cell)."""
    n = assign_np.shape[0]
    pos = np.full((n_slabs, cap), -1, np.int64)
    order = np.argsort(assign_np, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])
    in_cell = np.arange(n, dtype=np.int64) - starts[assign_np[order]]
    pos[slab_of_cell[assign_np[order]], in_cell] = order
    return pos


def _cell_cap(counts, cap_min, cap_multiple=None):
    mult = int(cap_multiple or CAP_ROUND)
    if mult < 32 or mult % 32 != 0:
        raise ValueError(f"cap_multiple must be a positive multiple of 32 "
                         f"(the int8 sublane tile), got {mult}")
    need = max(int(counts.max(initial=0)), int(cap_min or 0))
    return int(max(mult, -(-need // mult) * mult))


def _gathered_slabs(emb, valid, scales, pos):
    """Gather the slot arrays into the slab order `pos` describes; returns
    (cell_emb, cell_valid, cell_scales, row_ids) with padding slots masked
    (valid 0, scale 1, sentinel row id)."""
    n = emb.shape[0]
    flat = pos.reshape(-1)
    present = flat >= 0
    gather = jnp.asarray(np.where(present, flat, 0).astype(np.int32))
    mask = jnp.asarray(present)
    scales_j = (jnp.ones((n,), jnp.float32) if scales is None
                else jnp.asarray(scales, jnp.float32))
    return (
        jnp.take(emb, gather, axis=0),
        jnp.where(mask, jnp.take(
            jnp.asarray(valid).astype(jnp.float32), gather), 0.0),
        jnp.where(mask, jnp.take(scales_j, gather), 1.0),
        jnp.asarray(np.where(present, flat, _IDX_SENTINEL).astype(np.int32)),
    )


def _check_assign(assign, centroids, n):
    assign_np = np.asarray(assign).astype(np.int64)
    c = int(np.asarray(centroids).shape[0])
    if assign_np.shape[0] != n:
        raise ValueError(f"assign covers {assign_np.shape[0]} rows, corpus {n}")
    counts = (np.bincount(assign_np, minlength=c) if n
              else np.zeros(c, np.int64))
    return assign_np, c, counts


def build_cells(emb, valid, scales, centroids, assign, *, cap_min=None,
                cap_multiple=None):
    """Permute a (quantized) corpus into cell-major slabs.

    :param emb: [N, D] slot embeddings, any corpus dtype — gathered as-is
    :param valid: [N] mask
    :param scales: [N] f32 per-row dequant scales, or None for ones
    :param centroids: [C, D] f32 (host or device)
    :param assign: [N] int32 cell id per row (host)
    :param cap_min: optional floor on the uniform cell capacity — pins the
        layout shapes across swaps whose occupancy skews, so the serving
        variants compiled at warmup keep dispatching (zero-recompile soaks)
    :param cap_multiple: capacity rounding multiple (%32; default
        tile_defaults.IVF_CAP_MULTIPLE, autotuner may recommend larger)
    :returns: IVFCells with all large arrays on device
    """
    emb = jnp.asarray(emb)
    assign_np, c, counts = _check_assign(assign, centroids, emb.shape[0])
    cap = _cell_cap(counts, cap_min, cap_multiple)
    pos = _cell_positions(assign_np, counts, cap, c + 1,
                          np.arange(c, dtype=np.int64))
    cell_emb, cell_valid, cell_scales, row_ids = _gathered_slabs(
        emb, valid, scales, pos)
    return IVFCells(
        centroids=jnp.asarray(centroids, jnp.float32),
        cell_emb=cell_emb, cell_valid=cell_valid, cell_scales=cell_scales,
        row_ids=row_ids, assign=jnp.asarray(assign_np.astype(np.int32)))


def build_sharded_cells(emb, valid, scales, centroids, assign, *, n_shards,
                        cap_min=None, cap_multiple=None, device_put=None):
    """Permute a (quantized) corpus into SHARD-MAJOR cell slabs (see module
    docstring): shard s owns whole cells [s*cps, (s+1)*cps) plus a local
    dummy, every shard's region is (cps+1)*cap rows.

    :param n_shards: mesh size; each shard's region must land on one device
    :param device_put: placement closure for the slab arrays (typically the
        corpus's row-sharder); centroids/assign are placed plain (replicated
        into the compiled programs by the partitioner)
    :returns: ShardedIVFCells
    """
    emb = jnp.asarray(emb)
    n_shards = int(n_shards)
    assert n_shards >= 1
    assign_np, c, counts = _check_assign(assign, centroids, emb.shape[0])
    cap = _cell_cap(counts, cap_min, cap_multiple)
    cps = -(-c // n_shards)                      # whole cells per shard
    cells = np.arange(c, dtype=np.int64)
    slab_of_cell = (cells // cps) * (cps + 1) + cells % cps
    pos = _cell_positions(assign_np, counts, cap, n_shards * (cps + 1),
                          slab_of_cell)
    cell_emb, cell_valid, cell_scales, row_ids = _gathered_slabs(
        emb, valid, scales, pos)
    put = device_put if device_put is not None else (lambda x: x)
    return ShardedIVFCells(
        centroids=jnp.asarray(centroids, jnp.float32),
        cell_emb=put(cell_emb), cell_valid=put(cell_valid),
        cell_scales=put(cell_scales), row_ids=put(row_ids),
        assign=jnp.asarray(assign_np.astype(np.int32)),
        n_shards=n_shards, cells_per_shard=int(cps), cell_cap=cap)


def cell_stats(cells):
    """Host-side occupancy stats driving the staleness/rebuild decision.
    Works on both layouts — the sharded one maps real cells back out of the
    shard-major slab order (dummies and padding cells excluded)."""
    c, cap = cells.n_cells, cells.cell_cap
    ids_all = np.asarray(cells.row_ids).reshape(-1, cap)
    if isinstance(cells, ShardedIVFCells):
        cps = int(cells.cells_per_shard)
        cell = np.arange(c)
        ids = ids_all[(cell // cps) * (cps + 1) + cell % cps]
    else:
        ids = ids_all[:c]
    counts = (ids != _IDX_SENTINEL).sum(axis=1).astype(np.int64)
    total = int(counts.sum())
    mean = total / c if c else 0.0
    return {
        "n_cells": c,
        "cell_cap": cap,
        "counts": counts,
        "imbalance": float(counts.max(initial=0) / mean) if mean > 0 else 1.0,
        "frac_empty": float((counts == 0).mean()) if c else 0.0,
        "n_rows": total,
    }
