"""On-device spherical k-means for the IVF retrieval index.

The clustering runs entirely as one jitted graph (k-means++ seeding loop +
fixed-iteration Lloyd refinement), so index builds ride the same device the
corpus lives on and never round-trip rows through the host. Three properties
matter for the serving integration:

- **Seeded from the drift gate.** `ServingCorpus._health_gate` already
  maintains a mean-direction centroid per slot (`slot.stats["centroid"]`,
  the same statistic `telemetry/health.drift_health` compares against). That
  vector is the first k-means++ seed, so a rebuilt index starts from the
  corpus's actual center of mass instead of a random row — and successive
  rebuilds of a drifting corpus stay comparable.
- **Empty-cell reseeding.** Every Lloyd iteration relocates zero-count
  centroids onto the rows farthest from their current cell (largest cosine
  distance), one distinct row per empty cell, so pathological seeds cannot
  permanently strand capacity.
- **Deterministic.** All randomness flows from one `PRNGKey(seed)` with
  per-step `fold_in`, so a (corpus, seed) pair always yields the same cells
  — the parity suite depends on this.

Rows are treated as directions (the serve graph l2-normalizes both sides),
so "distance" is `1 - cosine` throughout. Invalid rows get a nearest-cell
assignment like everyone else — the IVF scorer must keep them addressable
for `lax.top_k`-exact -inf tie ordering — but carry zero weight in the
centroid update and can never be chosen as seeds.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-12


class KMeansResult(NamedTuple):
    centroids: np.ndarray  # [n_cells, D] f32, unit rows
    assign: np.ndarray     # [N] int32 nearest-cell id (invalid rows included)
    counts: np.ndarray     # [n_cells] f32 valid-row occupancy
    inertia: float         # mean (1 - cosine) of valid rows to their cell


def _unit(x, axis=-1):
    return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), _EPS)


@functools.partial(jax.jit, static_argnames=("n_cells", "n_iters"))
def _kmeans_device(emb, valid, key, init_centroid, n_cells, n_iters):
    n, d = emb.shape
    x = _unit(emb.astype(jnp.float32))
    w = (valid > 0).astype(jnp.float32)

    # ---- k-means++ seeding, first seed = the slot's drift-gate centroid ----
    cents = jnp.zeros((n_cells, d), jnp.float32)
    cents = cents.at[0].set(_unit(init_centroid.astype(jnp.float32)))

    def seed_step(t, cents):
        sims = jnp.dot(x, cents.T)                       # [N, n_cells] f32
        filled = (jnp.arange(n_cells) < t)[None, :]
        best = jnp.max(jnp.where(filled, sims, -jnp.inf), axis=1)
        d2 = jnp.maximum(1.0 - best, 0.0) + 1e-9         # classic D^2 weights
        logits = jnp.where(w > 0, jnp.log(d2), -jnp.inf)
        pick = jax.random.categorical(jax.random.fold_in(key, t), logits)
        return cents.at[t].set(x[pick])

    cents = jax.lax.fori_loop(1, n_cells, seed_step, cents)

    # ---- Lloyd iterations with empty-cell reseeding ----
    def lloyd(_, cents):
        sims = jnp.dot(x, cents.T)
        assign = jnp.argmax(sims, axis=1)
        oh = jax.nn.one_hot(assign, n_cells, dtype=jnp.float32) * w[:, None]
        counts = jnp.sum(oh, axis=0)                     # [n_cells]
        sums = jnp.dot(oh.T, x)                          # [n_cells, D]
        # reseed empties onto the farthest valid rows, one distinct row each
        far = jnp.where(w > 0, 1.0 - jnp.max(sims, axis=1), -jnp.inf)
        order = jnp.argsort(-far)
        empty = counts <= 0
        rank = jnp.clip(jnp.cumsum(empty.astype(jnp.int32)) - 1, 0, n - 1)
        reseed = x[order[rank]]
        mean = sums / jnp.maximum(counts, 1.0)[:, None]
        return _unit(jnp.where(empty[:, None], reseed, mean))

    cents = jax.lax.fori_loop(0, n_iters, lloyd, cents)

    sims = jnp.dot(x, cents.T)
    assign = jnp.argmax(sims, axis=1).astype(jnp.int32)
    oh = jax.nn.one_hot(assign, n_cells, dtype=jnp.float32) * w[:, None]
    counts = jnp.sum(oh, axis=0)
    inertia = (jnp.sum((1.0 - jnp.max(sims, axis=1)) * w)
               / jnp.maximum(jnp.sum(w), 1.0))
    return cents, assign, counts, inertia


@jax.jit
def _assign_device(emb, centroids):
    sims = jnp.dot(_unit(emb.astype(jnp.float32)),
                   centroids.astype(jnp.float32).T)
    return jnp.argmax(sims, axis=1).astype(jnp.int32)


def kmeans_fit(emb, valid, n_cells, *, seed=0, n_iters=8, init_centroid=None):
    """Cluster corpus rows into `n_cells` spherical cells on device.

    :param emb: [N, D] embeddings (any float dtype; dequantize int8 first)
    :param valid: [N] mask; rows <= 0 are assigned but carry no weight
    :param init_centroid: [D] first k-means++ seed — pass the serving slot's
        `stats["centroid"]` so the index inherits the drift gate's view of
        the corpus; None falls back to the valid-row mean direction.
    :returns: KMeansResult on host (centroids stay small: n_cells x D)
    """
    n_cells = int(n_cells)
    emb = jnp.asarray(emb)
    n = emb.shape[0]
    if not 1 <= n_cells <= max(n, 1):
        raise ValueError(f"n_cells={n_cells} outside [1, N={n}]")
    valid = jnp.asarray(valid)
    if init_centroid is None:
        w = (valid > 0).astype(jnp.float32)
        init_centroid = jnp.sum(emb.astype(jnp.float32) * w[:, None], axis=0)
    init_centroid = jnp.asarray(init_centroid, jnp.float32)
    cents, assign, counts, inertia = _kmeans_device(
        emb, valid, jax.random.PRNGKey(seed), init_centroid,
        n_cells=n_cells, n_iters=int(n_iters))
    return KMeansResult(
        centroids=np.asarray(jax.device_get(cents)),
        assign=np.asarray(jax.device_get(assign)),
        counts=np.asarray(jax.device_get(counts)),
        inertia=float(jax.device_get(inertia)),
    )


def assign_cells(emb, centroids):
    """Nearest-centroid cell ids for `emb` rows — the append routing path.

    This is the "no re-index" half of churn composition: appended rows are
    routed to existing cells with one [N, n_cells] argmax, the centroids are
    NOT refit (see `ServingCorpus.reindex` for the full rebuild).
    """
    return np.asarray(jax.device_get(
        _assign_device(jnp.asarray(emb), jnp.asarray(centroids))))
