"""Parameter provenance file, matching reference autoencoder.py:101-124: every
hyperparameter appended (restore) or written (fresh) as key=value lines under a
dashed separator, so runs are auditable from logs/parameter.txt alone."""


def write_parameter_file(path, params, append=False):
    """:param params: ordered dict of name -> value"""
    mode = "a+" if append else "w"
    with open(path, mode) as f:
        print("---------------------------------------", file=f)
        for k, v in params.items():
            print(f"{k}={v}", file=f)
