"""Minimal TensorBoard event-file writer — stdlib + numpy only.

The reference logs TB summaries through TF1's built-in writers
(autoencoder.py:391-393, :431-442); this repo's primary sink is JSONL
(utils/metrics.py), but TB parity should not hinge on an unrelated framework
(torch) being importable. The wire format is small enough to emit directly:

  * event files are TFRecords: each record is
      [uint64 length][uint32 masked_crc32c(length)][payload][uint32 masked_crc32c(payload)]
    with crc32c (Castagnoli, reflected poly 0x82F63B78) and TF's mask
    rot15 + 0xa282ead8.
  * payloads are `tensorflow.Event` protobufs; only three shapes are needed:
    file_version, scalar summary (Summary.Value.simple_value), histogram
    summary (Summary.Value.histo = HistogramProto).

TensorBoard reads these files natively; no tensorflow/torch import anywhere.
"""

import os
import socket
import struct
import threading
import time

import numpy as np

# ------------------------------------------------------------------ crc32c

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data):
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------------ protobuf

def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1  # two's complement for negatives
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field, wire):
    return _varint((field << 3) | wire)


def _double(field, v):
    return _key(field, 1) + struct.pack("<d", float(v))


def _float(field, v):
    return _key(field, 5) + struct.pack("<f", float(v))


def _int64(field, v):
    return _key(field, 0) + _varint(int(v))


def _bytes(field, b):
    if isinstance(b, str):
        b = b.encode("utf-8")
    return _key(field, 2) + _varint(len(b)) + b


def _packed_doubles(field, vals):
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return _key(field, 2) + _varint(len(payload)) + payload


def _scalar_value(tag, value):
    # Summary.Value: tag=1 (string), simple_value=2 (float)
    return _bytes(1, tag) + _float(2, value)


def _histogram_proto(values, bins=30):
    """HistogramProto: min=1 max=2 num=3 sum=4 sum_squares=5 (doubles),
    bucket_limit=6 bucket=7 (packed doubles).

    Non-finite entries are dropped before binning (np.histogram raises on
    them) and an empty/all-nonfinite input encodes as a single empty bucket
    — a logging call must never kill training."""
    v = np.asarray(values, np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        return (_double(1, 0.0) + _double(2, 0.0) + _double(3, 0)
                + _double(4, 0.0) + _double(5, 0.0)
                + _packed_doubles(6, [1.0]) + _packed_doubles(7, [0.0]))
    counts, edges = np.histogram(v, bins=bins)
    return (
        _double(1, v.min()) + _double(2, v.max()) + _double(3, v.size)
        + _double(4, v.sum()) + _double(5, np.square(v).sum())
        + _packed_doubles(6, edges[1:]) + _packed_doubles(7, counts)
    )


def _event(step=None, summary_value=None, file_version=None):
    # Event: wall_time=1 (double), step=2 (int64), file_version=3 (string),
    # summary=5 (Summary); Summary: repeated value=1
    out = _double(1, time.time())
    if step is not None:
        out += _int64(2, step)
    if file_version is not None:
        out += _bytes(3, file_version)
    if summary_value is not None:
        out += _bytes(5, _bytes(1, summary_value))
    return out


class EventFileWriter:
    """Append-only `events.out.tfevents.*` writer (one per directory)."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        host = socket.gethostname() or "localhost"
        self._path = os.path.join(
            logdir, f"events.out.tfevents.{int(time.time())}.{host}")
        self._f = open(self._path, "ab")
        self._lock = threading.Lock()
        self._write(_event(file_version="brain.Event:2"))

    def _write(self, payload):
        header = struct.pack("<Q", len(payload))
        rec = (header + struct.pack("<I", masked_crc32c(header)) + payload
               + struct.pack("<I", masked_crc32c(payload)))
        with self._lock:
            self._f.write(rec)
            self._f.flush()

    def add_scalar(self, tag, value, step):
        try:
            value = float(value)
        except (TypeError, ValueError):
            return  # unconvertible value: drop the point, never kill training
        self._write(_event(step=step, summary_value=_scalar_value(tag, value)))

    def add_histogram(self, tag, values, step, bins=30):
        histo = _bytes(5, _histogram_proto(values, bins))  # Value.histo = 5
        self._write(_event(step=step, summary_value=_bytes(1, tag) + histo))

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()
