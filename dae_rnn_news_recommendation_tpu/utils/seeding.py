"""One seed convention for every model: seed >= 0 is exact, seed < 0 (or None)
draws a fresh random seed — matching the reference's seed>=0 gate
(run_autoencoder.py:52-55: only non-negative seeds pin the RNGs; the default -1
leaves runs randomized)."""

import numpy as np


def resolve_seed(seed):
    """Return a concrete non-negative int seed. Negative/None means 'unseeded':
    draw one from OS entropy (callers may log it for reproducibility)."""
    if seed is not None and seed >= 0:
        return int(seed)
    return int(np.random.SeedSequence().entropy % (2**31))
