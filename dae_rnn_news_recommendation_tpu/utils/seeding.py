"""One seed convention for every model: seed >= 0 is exact, seed < 0 (or None)
draws a fresh random seed — matching the reference's seed>=0 gate
(run_autoencoder.py:52-55: only non-negative seeds pin the RNGs; the default -1
leaves runs randomized)."""

import numpy as np


def resolve_seed(seed):
    """Return a concrete non-negative int seed. Negative/None means 'unseeded':
    draw one from OS entropy (callers may log it for reproducibility)."""
    if seed is not None and seed >= 0:
        return int(seed)
    return int(np.random.SeedSequence().entropy % (2**31))


def serialize_key(key):
    """JAX PRNG key -> JSON-able list of ints, for checkpoint resume sidecars
    (utils/checkpoint.py `resume=`). Works for both raw uint32 keys and typed
    key arrays (whose raw words jax.random.key_data exposes)."""
    import jax

    arr = np.asarray(key)
    if arr.dtype == object or not np.issubdtype(arr.dtype, np.integer):
        # jaxcheck: disable=R5 (serialization reads raw key words; no randomness is drawn by either access)
        arr = np.asarray(jax.random.key_data(key))
    return [int(x) for x in arr.ravel()]


def deserialize_key(words):
    """Inverse of serialize_key: restore the exact PRNG key value, so a
    resumed fit continues the per-batch key chain bit-for-bit (the
    crash-exact resume contract, docs/reliability.md)."""
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(words, dtype=np.uint32))


def rng_state(rng):
    """Snapshot a numpy Generator's bit-generator state as a JSON-able dict
    (JSON carries the 128-bit PCG64 ints natively; npz cannot)."""
    return rng.bit_generator.state


def restore_rng_state(rng, state):
    """Restore a snapshot taken by rng_state onto an existing Generator."""
    rng.bit_generator.state = state
