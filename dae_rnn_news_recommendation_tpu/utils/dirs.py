"""Run directory layout, matching the reference artifact tree
(autoencoder/autoencoder.py:544-564):

    results/<algo_name>/<main_dir>/{models, data, logs, data/tsv, data/plot}
"""

import os


def create_run_directories(algo_name, main_dir, root="results"):
    algo = algo_name if algo_name.endswith("/") else algo_name + "/"
    main = main_dir if main_dir.endswith("/") else main_dir + "/"
    base = os.path.join(root, algo + main)

    models_dir = os.path.join(base, "models/")
    data_dir = os.path.join(base, "data/")
    summary_dir = os.path.join(base, "logs/")
    tsv_dir = os.path.join(data_dir, "tsv/")
    plot_dir = os.path.join(data_dir, "plot/")

    for d in (models_dir, data_dir, summary_dir, tsv_dir, plot_dir):
        os.makedirs(d, exist_ok=True)

    return models_dir, data_dir, summary_dir, tsv_dir, plot_dir
