"""Checkpoint/restore of model + optimizer state.

Twin of the reference's tf.train.Saver usage (autoencoder.py:156, :166, :169-170,
:491) with deliberate upgrades (SURVEY §2.3.12): periodic mid-run saves for fault
tolerance, the epoch stored inside the checkpoint so resume continues the
schedule, and — PR 6 — crash-safe commit semantics:

  * atomic commit: single-process saves write into `<name>.tmp` and
    `os.replace` it into place, so a crash mid-write leaves a `.tmp` turd
    (invisible to restore) instead of a half-checkpoint that restores garbage;
  * checksum manifest: every committed checkpoint carries CHECKSUMS.json
    (sha256 + byte size per file, written last), and `latest_checkpoint`
    VERIFIES it before returning a path — corrupt or torn dirs are quarantined
    (renamed `quarantined-*` + RuntimeWarning) and restore falls back to the
    newest checkpoint that verifies;
  * resume sidecar: `save_checkpoint(resume=...)` persists a JSON payload
    (RNG key, batch-order cursor, batcher RNG state — models/estimator.py)
    alongside the weights, which is what makes kill-and-resume bitwise-exact;
  * fault hooks: `reliability.faults.fire("ckpt.save" | "ckpt.commit")` let a
    chaos plan inject transient I/O errors and torn commits here, and
    AsyncCheckpointer absorbs transient failures via a bounded, recorded
    RetryPolicy (reliability/retry.py).

Layout per checkpoint:  <ckpt_dir>/step_<E>[_<C>]/   (C = mid-epoch cursor)
    params/         model weights — orbax when importable, .npz fallback
    aux.npz         flattened optimizer-state leaves + epoch
    resume.json     crash-exact resume payload (optional)
    health.json     flight-recorder snapshot (optional)
    CHECKSUMS.json  sha256 manifest over all of the above (single-process)
"""

import hashlib
import json
import os
import re
import shutil

import jax
import numpy as np

from ..reliability import faults as _faults

try:
    import orbax.checkpoint as ocp
except Exception:  # pragma: no cover
    ocp = None

# step_<epoch> for epoch-boundary saves; step_<epoch>_<cursor> for mid-epoch
# cursor saves (cursor = optimizer steps completed into epoch `epoch`+1)
_STEP_RE = re.compile(r"^step_(\d+)(?:_(\d+))?$")
_MANIFEST_NAME = "CHECKSUMS.json"


def _step_key(name):
    """(epoch, cursor) for a checkpoint dir name, or None. Epoch-boundary
    dirs sort as cursor 0; a cursor save for the FOLLOWING epoch sorts after
    its base epoch and before the next epoch boundary."""
    m = _STEP_RE.match(name)
    if not m:
        return None
    return int(m.group(1)), int(m.group(2) or 0)


def checkpoint_name(step, cursor=0):
    return f"step_{step}_{cursor}" if cursor else f"step_{step}"


def save_checkpoint(ckpt_dir, state, step, use_orbax=True, multiprocess=False,
                    health=None, resume=None, cursor=0):
    """Save {'params':…, 'opt_state':…, 'epoch':…} at `step`; returns the path.

    `multiprocess=True` is the pod path: EVERY process calls this with the same
    shared `ckpt_dir` and its (replicated or sharded) global jax.Arrays; orbax
    coordinates the collective save (the primary host finalizes — per-process
    private dirs would never commit on non-primary hosts), and the numpy
    sidecars are written by process 0 only. The pod path keeps the legacy
    write-in-place layout (orbax owns its own commit protocol; a host-side
    rename would race the collective) — single-process saves get the atomic
    tmp+rename commit and the checksum manifest.

    `health` is an optional flight-recorder snapshot (telemetry/recorder.py)
    written as a health.json sidecar so a restore can warn when the checkpoint
    came from a degraded run. `resume` is an optional JSON-able payload
    (resume.json) carrying whatever the trainer needs for crash-exact resume.
    `cursor` > 0 names the dir step_<step>_<cursor> for mid-epoch saves."""
    base = os.path.abspath(os.path.join(ckpt_dir, checkpoint_name(step, cursor)))
    primary = not multiprocess or jax.process_index() == 0

    if multiprocess and not (use_orbax and ocp is not None):
        # the npz fallback writes params on process 0 only; unless ckpt_dir is
        # a shared filesystem, non-primary hosts would pass the barrier with an
        # empty step dir and any later restore on them would fail
        import warnings

        warnings.warn(
            "multiprocess checkpoint without orbax: params are written by "
            "process 0 only — restore on other hosts requires ckpt_dir to be "
            "a shared filesystem", RuntimeWarning, stacklevel=2)

    if multiprocess:
        os.makedirs(base, exist_ok=True)
        _write_payload(base, state, use_orbax, primary, health, resume)
        from jax.experimental import multihost_utils

        # no process may return (and possibly restore) before the sidecars
        # and the orbax commit are durable everywhere
        multihost_utils.sync_global_devices(f"ckpt_{ckpt_dir}_{step}_{cursor}")
        return base

    # single process: write everything into a tmp dir, checksum it, then
    # commit with one atomic rename — restore can never observe a torn dir
    _faults.fire("ckpt.save", step=int(step), cursor=int(cursor))
    tmp = base + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)  # turd from a previous crashed/injected commit
    os.makedirs(tmp)
    try:
        _write_payload(tmp, state, use_orbax, True, health, resume)
        _write_checksums(tmp)
        _faults.fire("ckpt.commit", step=int(step), cursor=int(cursor))
        if os.path.isdir(base):
            shutil.rmtree(base)  # re-save of the same step supersedes it
        os.replace(tmp, base)
    except BaseException:
        # leave no committed dir behind; the .tmp turd (if the rmtree below
        # also fails) is invisible to _STEP_RE either way
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return base


def _write_payload(base, state, use_orbax, primary, health, resume):
    params_path = os.path.join(base, "params")
    if use_orbax and ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(params_path, state["params"], force=True)
        ckptr.wait_until_finished()
    elif primary:
        leaves, _ = jax.tree_util.tree_flatten(state["params"])
        np.savez(params_path + ".npz", *[np.asarray(x) for x in leaves])

    if not primary:
        return
    opt_leaves, _ = jax.tree_util.tree_flatten(state.get("opt_state"))
    np.savez(os.path.join(base, "aux.npz"),
             *[np.asarray(x) for x in opt_leaves],
             epoch=np.asarray(int(state.get("epoch", 0))))
    if resume is not None:
        with open(os.path.join(base, "resume.json"), "w",
                  encoding="utf-8") as f:
            json.dump(resume, f)
            f.write("\n")
    if health is not None:
        try:
            with open(os.path.join(base, "health.json"), "w",
                      encoding="utf-8") as f:
                json.dump(health, f, indent=1, default=str)
                f.write("\n")
        except (OSError, TypeError):
            pass  # the health sidecar must never fail a save


def _iter_files(base):
    for root, _, names in os.walk(base):
        for name in sorted(names):
            yield os.path.join(root, name)


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_checksums(base):
    files = {}
    for path in _iter_files(base):
        rel = os.path.relpath(path, base)
        if rel == _MANIFEST_NAME:
            continue
        files[rel] = {"sha256": _sha256(path),
                      "bytes": os.path.getsize(path)}
    with open(os.path.join(base, _MANIFEST_NAME), "w", encoding="utf-8") as f:
        json.dump({"schema": 1, "files": files}, f, indent=1)
        f.write("\n")


def verify_checkpoint(path):
    """(ok, reason) — whether the checkpoint dir at `path` is safe to restore.

    With a CHECKSUMS.json manifest (every single-process save since PR 6):
    every listed file must exist with matching size and sha256. Without one
    (legacy or pod saves): the dir must at least be structurally complete
    (params + aux.npz present) — a dir that fails even that is a torn write."""
    manifest_path = os.path.join(path, _MANIFEST_NAME)
    if os.path.isfile(manifest_path):
        try:
            with open(manifest_path, encoding="utf-8") as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (OSError, ValueError, KeyError, TypeError) as e:
            return False, f"unreadable {_MANIFEST_NAME}: {e}"
        for rel, meta in files.items():
            fp = os.path.join(path, rel)
            if not os.path.isfile(fp):
                return False, f"missing file {rel}"
            if os.path.getsize(fp) != meta.get("bytes"):
                return False, (f"size mismatch for {rel}: "
                               f"{os.path.getsize(fp)} != {meta.get('bytes')}")
            if _sha256(fp) != meta.get("sha256"):
                return False, f"checksum mismatch for {rel}"
        return True, "verified"
    # legacy/pod layout: no manifest to check against, only structure
    has_params = (os.path.isdir(os.path.join(path, "params"))
                  or os.path.isfile(os.path.join(path, "params.npz")))
    has_aux = os.path.isfile(os.path.join(path, "aux.npz"))
    if has_params and has_aux:
        return True, "no manifest (legacy layout); structure complete"
    return False, "partial checkpoint (params or aux.npz missing)"


def quarantine_checkpoint(path, reason=""):
    """Move a bad checkpoint dir aside (never delete — it is evidence) under
    a name restore can't pick up, and warn. Returns the new path."""
    import warnings

    parent, name = os.path.split(os.path.abspath(path))
    dest = os.path.join(parent, f"quarantined-{name}")
    n = 1
    while os.path.exists(dest):
        dest = os.path.join(parent, f"quarantined-{name}.{n}")
        n += 1
    os.replace(path, dest)
    warnings.warn(
        f"quarantined corrupt checkpoint {name} ({reason}) -> {dest}; "
        "falling back to the newest verified checkpoint",
        RuntimeWarning, stacklevel=3)
    return dest


def latest_checkpoint(ckpt_dir, verify=True):
    """(path, step) of the newest VERIFIED checkpoint under ckpt_dir, or
    (None, -1). Candidates that fail verification (torn writes, bit rot,
    chaos-injected truncation) are quarantined with a warning and the next
    newest is tried — restore never silently loads a bad checkpoint."""
    if not os.path.isdir(ckpt_dir):
        return None, -1
    candidates = sorted(
        ((key, name) for name in os.listdir(ckpt_dir)
         if (key := _step_key(name)) is not None),
        reverse=True)
    for (epoch, _cursor), name in candidates:
        path = os.path.join(ckpt_dir, name)
        if not verify:
            return path, epoch
        ok, reason = verify_checkpoint(path)
        if ok:
            return path, epoch
        quarantine_checkpoint(path, reason)
    return None, -1


def load_params(ckpt_path, params_like):
    """Restore just the model weights from a checkpoint directory."""
    params_path = os.path.join(ckpt_path, "params")
    if os.path.isdir(params_path) and ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(np.asarray, params_like)
        return ckptr.restore(os.path.abspath(params_path), abstract)
    npz = params_path + ".npz"
    if os.path.isfile(npz):
        data = np.load(npz)
        leaves, treedef = jax.tree_util.tree_flatten(params_like)
        return jax.tree_util.tree_unflatten(
            treedef, [data[f"arr_{i}"] for i in range(len(leaves))])
    raise FileNotFoundError(f"no params under {ckpt_path}")


def load_checkpoint(ckpt_path, like):
    """Restore the full {'params','opt_state','epoch'} state; `like` provides the
    pytree structure (must use the same optimizer that produced the checkpoint).

    When the checkpoint carries a health.json sidecar (save_checkpoint's
    `health=`), it is returned under out['health'] and a RuntimeWarning is
    raised if the run that wrote it was degraded or failed — resuming a NaN'd
    or diverged run silently is how a bad state propagates. A resume.json
    sidecar (save_checkpoint's `resume=`) comes back under out['resume']."""
    params = load_params(ckpt_path, like["params"])
    aux_path = os.path.join(ckpt_path, "aux.npz")
    out = {"params": params, "opt_state": like.get("opt_state"), "epoch": 0}
    health_path = os.path.join(ckpt_path, "health.json")
    if os.path.isfile(health_path):
        import warnings

        try:
            with open(health_path, encoding="utf-8") as f:
                out["health"] = json.load(f)
        except (OSError, ValueError):
            out["health"] = None
        status = (out["health"] or {}).get("status", "ok")
        if status != "ok":
            warnings.warn(
                f"resuming from a checkpoint whose run was {status} "
                f"(first bad step: {(out['health'] or {}).get('first_bad_step')}, "
                f"reason: {(out['health'] or {}).get('reason')}) — inspect the "
                "run's health_bundle.json before trusting this state",
                RuntimeWarning, stacklevel=2)
    resume_path = os.path.join(ckpt_path, "resume.json")
    if os.path.isfile(resume_path):
        try:
            with open(resume_path, encoding="utf-8") as f:
                out["resume"] = json.load(f)
        except (OSError, ValueError):
            out["resume"] = None
    if os.path.isfile(aux_path):
        data = np.load(aux_path)
        out["epoch"] = int(data["epoch"])
        if like.get("opt_state") is not None:
            leaves, treedef = jax.tree_util.tree_flatten(like["opt_state"])
            n_saved = sum(1 for k in data.files if k.startswith("arr_"))
            if n_saved == len(leaves):
                restored = [data[f"arr_{i}"] for i in range(len(leaves))]
                out["opt_state"] = jax.tree_util.tree_unflatten(treedef, restored)
            else:
                raise ValueError(
                    f"checkpoint at {ckpt_path} was saved with a different optimizer "
                    f"({n_saved} state leaves vs {len(leaves)} expected); restore with "
                    "the same `opt`, or load weights only via load_params")
    return out


class AsyncCheckpointer:
    """Background-thread checkpoint writer for mid-run saves: the train loop
    pays only for the device->host copy; serialization and disk IO overlap the
    following epochs. One save in flight at a time (a new save waits for the
    previous one), so ordering is preserved and host memory stays bounded at
    one extra state copy.

    Failure contract (PR 6): a background save that raises is NEVER swallowed
    — the exception is re-raised (with the failed step attached as a note) on
    the next `save()` or `wait()` call, whichever comes first; fit's
    end-of-run save always calls wait(), so no fit can finish "successfully"
    over a failed mid-run save. Pass `retry=` (reliability.retry.RetryPolicy)
    to absorb transient I/O faults with bounded, recorded retries before they
    count as failures."""

    def __init__(self, retry=None):
        self._future = None
        self._executor = None
        self._inflight = None  # (ckpt_dir, step, cursor) for error context
        self.retry = retry

    def save(self, ckpt_dir, state, step, use_orbax=True, keep=0, health=None,
             resume=None, cursor=0):
        import concurrent.futures

        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt")
        # a real COPY, not np.asarray: for state already on the host,
        # asarray is a view and the trainer's next update would race the
        # background writer (device arrays copy on the D2H transfer anyway)
        host_state = jax.tree_util.tree_map(
            lambda x: x.copy() if isinstance(x, np.ndarray) else np.asarray(x),
            state)
        self.wait()  # surfaces the PREVIOUS save's failure, if any

        def work():
            def once():
                save_checkpoint(ckpt_dir, host_state, step,
                                use_orbax=use_orbax, health=health,
                                resume=resume, cursor=cursor)

            if self.retry is not None:
                self.retry.run(once, site="ckpt.save")
            else:
                once()
            if keep:
                prune_checkpoints(ckpt_dir, keep)

        self._inflight = (ckpt_dir, int(step), int(cursor))
        self._future = self._executor.submit(work)

    def wait(self):
        """Block until the in-flight save (if any) is durable; re-raises its
        exception with the failed checkpoint's identity attached."""
        if self._future is None:
            return
        f, self._future = self._future, None
        ctx, self._inflight = self._inflight, None
        try:
            f.result()
        except Exception as e:
            if ctx is not None:
                note = (f"background checkpoint save failed: "
                        f"dir={ctx[0]} step={ctx[1]} cursor={ctx[2]}")
                if hasattr(e, "add_note"):
                    e.add_note(note)
                else:  # pre-3.11: same attribute, introspectable if not shown
                    e.__notes__ = [*getattr(e, "__notes__", ()), note]
            raise


def prune_checkpoints(ckpt_dir, keep):
    """Delete all but the newest `keep` step_* checkpoints. keep<=0 keeps all.
    Quarantined dirs are never touched — they are crash evidence."""
    if keep <= 0 or not os.path.isdir(ckpt_dir):
        return []
    steps = sorted(
        (key, name) for name in os.listdir(ckpt_dir)
        if (key := _step_key(name)) is not None)
    removed = []
    for _, name in steps[:-keep]:
        shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
        removed.append(name)
    return removed
