"""Checkpoint/restore of model + optimizer state.

Twin of the reference's tf.train.Saver usage (autoencoder.py:156, :166, :169-170,
:491) with two deliberate upgrades (SURVEY §2.3.12): periodic mid-run saves for fault
tolerance, and the epoch stored inside the checkpoint so resume continues the schedule.

Layout per checkpoint:  <ckpt_dir>/step_<N>/
    params/     model weights — orbax when importable (JAX-native, sharding-aware for
                multi-host), .npz fallback otherwise
    aux.npz     flattened optimizer-state leaves + epoch (structure comes from the
                caller's `like` pytree at restore, so weights stay loadable even when
                the restoring process uses a different optimizer — e.g. load_model)
"""

import os
import re

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
except Exception:  # pragma: no cover
    ocp = None

_STEP_RE = re.compile(r"^step_(\d+)$")


def save_checkpoint(ckpt_dir, state, step, use_orbax=True, multiprocess=False,
                    health=None):
    """Save {'params':…, 'opt_state':…, 'epoch':…} at `step`; returns the path.

    `multiprocess=True` is the pod path: EVERY process calls this with the same
    shared `ckpt_dir` and its (replicated or sharded) global jax.Arrays; orbax
    coordinates the collective save (the primary host finalizes — per-process
    private dirs would never commit on non-primary hosts), and the numpy
    sidecars are written by process 0 only.

    `health` is an optional flight-recorder snapshot (telemetry/recorder.py:
    status, step, loss EMA, grad norm, first bad step) written as a
    health.json sidecar so a restore can warn when the checkpoint came from a
    degraded run."""
    base = os.path.abspath(os.path.join(ckpt_dir, f"step_{step}"))
    os.makedirs(base, exist_ok=True)
    primary = not multiprocess or jax.process_index() == 0

    if multiprocess and not (use_orbax and ocp is not None):
        # the npz fallback writes params on process 0 only; unless ckpt_dir is
        # a shared filesystem, non-primary hosts would pass the barrier with an
        # empty step dir and any later restore on them would fail
        import warnings

        warnings.warn(
            "multiprocess checkpoint without orbax: params are written by "
            "process 0 only — restore on other hosts requires ckpt_dir to be "
            "a shared filesystem", RuntimeWarning, stacklevel=2)

    params_path = os.path.join(base, "params")
    if use_orbax and ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(params_path, state["params"], force=True)
        ckptr.wait_until_finished()
    elif primary:
        leaves, _ = jax.tree_util.tree_flatten(state["params"])
        np.savez(params_path + ".npz", *[np.asarray(x) for x in leaves])

    if primary:
        opt_leaves, _ = jax.tree_util.tree_flatten(state.get("opt_state"))
        np.savez(os.path.join(base, "aux.npz"),
                 *[np.asarray(x) for x in opt_leaves],
                 epoch=np.asarray(int(state.get("epoch", 0))))
        if health is not None:
            import json

            try:
                with open(os.path.join(base, "health.json"), "w",
                          encoding="utf-8") as f:
                    json.dump(health, f, indent=1, default=str)
                    f.write("\n")
            except (OSError, TypeError):
                pass  # the health sidecar must never fail a save
    if multiprocess:
        from jax.experimental import multihost_utils

        # no process may return (and possibly restore) before the sidecars
        # and the orbax commit are durable everywhere
        multihost_utils.sync_global_devices(f"ckpt_{ckpt_dir}_{step}")
    return base


class AsyncCheckpointer:
    """Background-thread checkpoint writer for mid-run saves: the train loop
    pays only for the device->host copy; serialization and disk IO overlap the
    following epochs. One save in flight at a time (a new save waits for the
    previous one), so ordering is preserved and host memory stays bounded at
    one extra state copy. Call `wait()` before restoring or at end of fit."""

    def __init__(self):
        self._future = None
        self._executor = None

    def save(self, ckpt_dir, state, step, use_orbax=True, keep=0, health=None):
        import concurrent.futures

        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt")
        host_state = jax.tree_util.tree_map(np.asarray, state)
        self.wait()

        def work():
            save_checkpoint(ckpt_dir, host_state, step, use_orbax=use_orbax,
                            health=health)
            if keep:
                prune_checkpoints(ckpt_dir, keep)

        self._future = self._executor.submit(work)

    def wait(self):
        """Block until the in-flight save (if any) is durable; re-raises its
        exception."""
        if self._future is not None:
            f, self._future = self._future, None
            f.result()


def latest_checkpoint(ckpt_dir):
    """(path, step) of the newest checkpoint under ckpt_dir, or (None, -1)."""
    if not os.path.isdir(ckpt_dir):
        return None, -1
    best, best_step = None, -1
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m:
            step = int(m.group(1))
            if step > best_step:
                best, best_step = os.path.join(ckpt_dir, name), step
    return best, best_step


def load_params(ckpt_path, params_like):
    """Restore just the model weights from a checkpoint directory."""
    params_path = os.path.join(ckpt_path, "params")
    if os.path.isdir(params_path) and ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(np.asarray, params_like)
        return ckptr.restore(os.path.abspath(params_path), abstract)
    npz = params_path + ".npz"
    if os.path.isfile(npz):
        data = np.load(npz)
        leaves, treedef = jax.tree_util.tree_flatten(params_like)
        return jax.tree_util.tree_unflatten(
            treedef, [data[f"arr_{i}"] for i in range(len(leaves))])
    raise FileNotFoundError(f"no params under {ckpt_path}")


def load_checkpoint(ckpt_path, like):
    """Restore the full {'params','opt_state','epoch'} state; `like` provides the
    pytree structure (must use the same optimizer that produced the checkpoint).

    When the checkpoint carries a health.json sidecar (save_checkpoint's
    `health=`), it is returned under out['health'] and a RuntimeWarning is
    raised if the run that wrote it was degraded or failed — resuming a NaN'd
    or diverged run silently is how a bad state propagates."""
    params = load_params(ckpt_path, like["params"])
    aux_path = os.path.join(ckpt_path, "aux.npz")
    out = {"params": params, "opt_state": like.get("opt_state"), "epoch": 0}
    health_path = os.path.join(ckpt_path, "health.json")
    if os.path.isfile(health_path):
        import json
        import warnings

        try:
            with open(health_path, encoding="utf-8") as f:
                out["health"] = json.load(f)
        except (OSError, ValueError):
            out["health"] = None
        status = (out["health"] or {}).get("status", "ok")
        if status != "ok":
            warnings.warn(
                f"resuming from a checkpoint whose run was {status} "
                f"(first bad step: {(out['health'] or {}).get('first_bad_step')}, "
                f"reason: {(out['health'] or {}).get('reason')}) — inspect the "
                "run's health_bundle.json before trusting this state",
                RuntimeWarning, stacklevel=2)
    if os.path.isfile(aux_path):
        data = np.load(aux_path)
        out["epoch"] = int(data["epoch"])
        if like.get("opt_state") is not None:
            leaves, treedef = jax.tree_util.tree_flatten(like["opt_state"])
            n_saved = sum(1 for k in data.files if k.startswith("arr_"))
            if n_saved == len(leaves):
                restored = [data[f"arr_{i}"] for i in range(len(leaves))]
                out["opt_state"] = jax.tree_util.tree_unflatten(treedef, restored)
            else:
                raise ValueError(
                    f"checkpoint at {ckpt_path} was saved with a different optimizer "
                    f"({n_saved} state leaves vs {len(leaves)} expected); restore with "
                    "the same `opt`, or load weights only via load_params")
    return out


def prune_checkpoints(ckpt_dir, keep):
    """Delete all but the newest `keep` step_* checkpoints. keep<=0 keeps all."""
    import shutil

    if keep <= 0 or not os.path.isdir(ckpt_dir):
        return []
    steps = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    )
    removed = []
    for _, name in steps[:-keep]:
        shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
        removed.append(name)
    return removed
