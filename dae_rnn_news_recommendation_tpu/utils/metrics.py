"""Observability: scalar/histogram metrics writer.

Twin of the reference's TensorBoard summaries (autoencoder.py:391-393, :431-442,
:172-173: scalar losses per train step, histograms of W/biases/embeddings, separate
train/validation writers). Primary sink is newline-delimited JSON under
logs/{train,validation}/metrics.jsonl — dependency-free and machine-readable; the
TensorBoard event sink (utils/tb_writer.py, stdlib+numpy only) is always on by
default, so observability parity never hinges on another framework.
"""

import json
import os
import time

import numpy as np

from .tb_writer import EventFileWriter as _TBWriter


class MetricsWriter:
    def __init__(self, logdir, use_tensorboard=True):
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, "metrics.jsonl")
        self._f = open(self._path, "a", buffering=1)
        self._tb = None
        if use_tensorboard:
            try:
                self._tb = _TBWriter(logdir)
            except Exception:  # pragma: no cover - unwritable dir etc.
                self._tb = None

    def scalar(self, tag, value, step):
        rec = {"tag": tag, "value": float(value), "step": int(step), "ts": time.time()}
        self._f.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), int(step))

    def scalars(self, mapping, step):
        for tag, value in mapping.items():
            self.scalar(tag, value, step)

    def feed_stats(self, stats, step):
        """Per-epoch feed/compute split from a pipelined fit
        (train/pipeline.FeedStats): feed_wait_s, step_time_s and
        feed_stall_fraction land in both sinks under feed/ so the
        stream->resident gap is a tracked trajectory, not a one-off print."""
        self.scalars({
            "feed/feed_wait_s": stats.feed_wait_s,
            "feed/step_time_s": stats.step_time_s,
            "feed/feed_stall_fraction": stats.feed_stall_fraction,
        }, step)

    def histogram(self, tag, values, step):
        """Summary-stats histogram (the reference logs full TB histograms; JSONL keeps
        min/max/mean/std/percentiles, TB sink keeps the full histogram)."""
        v = np.asarray(values).ravel()
        rec = {
            "tag": tag, "step": int(step), "ts": time.time(),
            "hist": {
                "min": float(v.min()), "max": float(v.max()),
                "mean": float(v.mean()), "std": float(v.std()),
                "p5": float(np.percentile(v, 5)), "p50": float(np.percentile(v, 50)),
                "p95": float(np.percentile(v, 95)), "n": int(v.size),
            },
        }
        self._f.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            self._tb.add_histogram(tag, v, int(step))

    def close(self):
        self._f.close()
        if self._tb is not None:
            self._tb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
