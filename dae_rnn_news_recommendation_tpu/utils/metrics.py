"""Observability: scalar/histogram metrics writer.

Twin of the reference's TensorBoard summaries (autoencoder.py:391-393, :431-442,
:172-173: scalar losses per train step, histograms of W/biases/embeddings, separate
train/validation writers). Primary sink is newline-delimited JSON under
logs/{train,validation}/metrics.jsonl — dependency-free and machine-readable; the
TensorBoard event sink (utils/tb_writer.py, stdlib+numpy only) is always on by
default, so observability parity never hinges on another framework.
"""

import json
import math
import os
import time

import numpy as np

from .tb_writer import EventFileWriter as _TBWriter


class MetricsWriter:
    def __init__(self, logdir, use_tensorboard=True):
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, "metrics.jsonl")
        self._f = open(self._path, "a", buffering=1)
        self._tb = None
        # NaN/Inf scalars seen so far (a NaN'd loss must be *diagnosable* from
        # the logs, so it can't be dropped silently or crash the writer)
        self.nonfinite_scalar_count = 0
        if use_tensorboard:
            try:
                self._tb = _TBWriter(logdir)
            except Exception:  # pragma: no cover - unwritable dir etc.
                self._tb = None

    def scalar(self, tag, value, step):
        """Log one scalar to both sinks. Non-finite values are recorded
        deterministically: the raw value goes to metrics.jsonl (Python's json
        emits NaN/Infinity tokens that json.loads round-trips), the TB sink is
        skipped (TB renderers choke on NaN points), and
        `nonfinite_scalar_count` is bumped so callers/tests can assert on it."""
        fv = float(value)
        rec = {"tag": tag, "value": fv, "step": int(step), "ts": time.time()}
        self._f.write(json.dumps(rec) + "\n")
        if not math.isfinite(fv):
            self.nonfinite_scalar_count += 1
            return
        if self._tb is not None:
            self._tb.add_scalar(tag, fv, int(step))

    def scalars(self, mapping, step):
        for tag, value in mapping.items():
            self.scalar(tag, value, step)

    def feed_stats(self, stats, step):
        """Per-epoch feed/compute split from a pipelined fit
        (train/pipeline.FeedStats): feed_wait_s, step_time_s and
        feed_stall_fraction land in both sinks under feed/ so the
        stream->resident gap is a tracked trajectory, not a one-off print.
        padded_row_fraction and wire_bytes_per_article track bucket-padding
        waste and the feed's effective wire cost (the compressed-wire codec's
        win, and an epoch-cache replay's ~0) the same way."""
        self.scalars({
            "feed/feed_wait_s": stats.feed_wait_s,
            "feed/step_time_s": stats.step_time_s,
            "feed/feed_stall_fraction": stats.feed_stall_fraction,
            "feed/padded_row_fraction": stats.padded_row_fraction,
            "feed/wire_bytes_per_article": stats.wire_bytes_per_article,
        }, step)

    def histogram(self, tag, values, step):
        """Summary-stats histogram (the reference logs full TB histograms; JSONL keeps
        min/max/mean/std/percentiles, TB sink keeps the full histogram).

        NaN/Inf entries are dropped from the stats (their count is recorded as
        n_nonfinite) and an all-empty/all-nonfinite input logs a null hist —
        a logging call must never kill training."""
        v = np.asarray(values, np.float64).ravel()
        finite = v[np.isfinite(v)]
        if finite.size:
            hist = {
                "min": float(finite.min()), "max": float(finite.max()),
                "mean": float(finite.mean()), "std": float(finite.std()),
                "p5": float(np.percentile(finite, 5)),
                "p50": float(np.percentile(finite, 50)),
                "p95": float(np.percentile(finite, 95)), "n": int(finite.size),
            }
        else:
            hist = {"min": None, "max": None, "mean": None, "std": None,
                    "p5": None, "p50": None, "p95": None, "n": 0}
        if finite.size != v.size:
            hist["n_nonfinite"] = int(v.size - finite.size)
        rec = {"tag": tag, "step": int(step), "ts": time.time(), "hist": hist}
        self._f.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            self._tb.add_histogram(tag, v, int(step))

    def flush(self):
        if not self._f.closed:
            self._f.flush()

    def close(self):
        """Flush and close both sinks; idempotent (fit paths close in
        `finally:` and a later explicit close must not raise)."""
        if not self._f.closed:
            self._f.flush()
            self._f.close()
        if self._tb is not None:
            self._tb.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
