"""Config/flag system: every hyperparameter is a flag; a .env file overrides flags.

Twin of the reference's tf.app.flags blocks + dotenv override
(main_autoencoder.py:13-111), rebuilt on argparse with the same flag names, defaults,
and cross-field validation — and with the reference's miswired env keys fixed
(SURVEY §2.3.1: corr_type/corr_frac were read from os.environ['compress_factor']).

Boolean envs are presence-triggered like the reference (:36-42): defining `verbose`
in .env sets it True regardless of value.
"""

import argparse
import os
from pathlib import Path

_BOOL_FLAGS = ("verbose", "encode_full", "validation", "save_tsv",
               "restore_previous_data", "restore_previous_model", "synthetic",
               "profile", "streaming_eval")


def load_dotenv(path=".env"):
    """Minimal .env parser (KEY=VALUE lines; '#' comments). Returns dict and also
    injects into os.environ like python-dotenv (reference main_autoencoder.py:13-17)."""
    path = Path(path)
    out = {}
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        k, _, v = line.partition("=")
        k, v = k.strip(), v.strip().strip("'\"")
        out[k] = v
        os.environ.setdefault(k, v)
    return out


def build_parser(triplet_mode=False):
    p = argparse.ArgumentParser(
        description="TPU-native DAE article-embedding trainer "
                    "(capabilities of louislung/DAE_RNN_News_Recommendation)")
    # global configuration (reference main_autoencoder.py:27-44)
    p.add_argument("--verbose", action="store_true", default=False)
    p.add_argument("--verbose_step", type=int, default=5)
    p.add_argument("--encode_full", action="store_true", default=False)
    p.add_argument("--validation", action="store_true", default=False)
    p.add_argument("--input_format", default="binary", choices=["binary", "tfidf"])
    p.add_argument("--label", default="category_publish_name",
                   choices=["category_publish_name", "story"])
    p.add_argument("--save_tsv", action="store_true", default=False)
    p.add_argument("--train_row", type=int, default=8000)
    p.add_argument("--validate_row", type=int, default=2000)
    # vectorizer (reference :47-54)
    p.add_argument("--restore_previous_data", action="store_true", default=False)
    p.add_argument("--min_df", type=float, default=0.0)
    p.add_argument("--max_df", type=float, default=0.99)
    p.add_argument("--max_features", type=int, default=10000)
    # model (reference :57-92)
    p.add_argument("--model_name", default="")
    p.add_argument("--restore_previous_model", action="store_true", default=False)
    p.add_argument("--seed", type=int, default=-1)
    p.add_argument("--compress_factor", type=int, default=20)
    p.add_argument("--corr_type", default="masking",
                   choices=["none", "masking", "salt_and_pepper", "decay"])
    p.add_argument("--corr_frac", type=float, default=0.3)
    p.add_argument("--xavier_init", type=int, default=1)
    p.add_argument("--enc_act_func", default="sigmoid", choices=["sigmoid", "tanh"])
    p.add_argument("--dec_act_func", default="sigmoid",
                   choices=["sigmoid", "tanh", "none"])
    p.add_argument("--main_dir", default="")
    p.add_argument("--loss_func", default="cross_entropy",
                   choices=["cross_entropy", "mean_squared", "cosine_proximity"])
    p.add_argument("--opt", default="gradient_descent",
                   choices=["gradient_descent", "ada_grad", "momentum", "adam"])
    p.add_argument("--learning_rate", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.5)
    p.add_argument("--num_epochs", type=int, default=50)
    p.add_argument("--batch_size", type=float, default=0.1)
    p.add_argument("--alpha", type=float, default=1.0)
    if not triplet_mode:
        p.add_argument("--triplet_strategy", default="batch_all",
                       choices=["batch_all", "batch_hard", "none"])
        p.add_argument("--label2", default="none",
                       choices=["none", "category_publish_name", "story"],
                       help="mine a SECOND batch_all margin term on this "
                            "label jointly with --label (net-new; the "
                            "reference mines one label). Rows missing the "
                            "secondary label sit out that term")
        p.add_argument("--label2_alpha", type=float, default=1.0,
                       help="weight of the secondary mining term relative to "
                            "the primary: cost += alpha * label2_alpha * "
                            "triplet_loss(label2)")
    # --- TPU-native extras ---
    p.add_argument("--data_path", default="datasets/uci_news.snappy.parquet",
                   help="article parquet; --synthetic generates data instead")
    p.add_argument("--synthetic", action="store_true", default=False,
                   help="use the built-in synthetic UCI-like corpus")
    p.add_argument("--synthetic_vocab", type=int, default=3000,
                   help="vocabulary size of the synthetic corpus; raise it to "
                        "reach reference-scale feature counts (the UCI workload "
                        "is 10k features, main_autoencoder.py:50)")
    p.add_argument("--synthetic_oversample", type=float, default=1.0,
                   help="generate this multiple of train_row+validate_row "
                        "synthetic articles BEFORE label-validity filtering "
                        "(reference main_autoencoder.py:193-198 shrinks the "
                        "set the same way): ~35%% of synthetic articles carry "
                        "a story, so --label story needs ~3-4x oversampling "
                        "to fill the requested splits")
    p.add_argument("--n_devices", type=int, default=1)
    p.add_argument("--n_experts", type=int, default=1,
                   help="train a Switch-style mixture of N expert DAEs "
                        "(models/estimator_moe.py) instead of a single DAE; "
                        "with --n_devices > 1 each expert lives on its own "
                        "device over an 'expert' mesh axis")
    p.add_argument("--model_parallel", type=int, default=1,
                   help="shard W's feature rows over a 'model' mesh axis of "
                        "this size (the max_features=50k layout); must divide "
                        "--n_devices, and requires mining_scope=global")
    p.add_argument("--mining_scope", default="global", choices=["global", "shard"])
    p.add_argument("--weight_update_sharding", action="store_true", default=False,
                   help="shard optimizer accumulators over the data axis "
                        "(ZeRO-1-style cross-replica weight-update sharding, "
                        "arXiv:2004.13336) — 1/n_devices optimizer memory per "
                        "device, identical math; requires mining_scope=global "
                        "on a 1-D data mesh")
    p.add_argument("--compute_dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--checkpoint_every", type=int, default=0)
    p.add_argument("--profile", action="store_true", default=False,
                   help="capture an XProf/TensorBoard device trace of fit() "
                        "under logs/profile/")
    p.add_argument("--streaming_eval", action="store_true", default=False,
                   help="force the AUROC eval tail onto the streaming blockwise "
                        "path (eval/streaming_auroc) — no N x N similarity "
                        "matrices; ROC/boxplot figures come from the score "
                        "histograms. Auto-selected above --streaming_eval_threshold "
                        "rows regardless of this flag.")
    p.add_argument("--streaming_eval_threshold", type=int, default=20000,
                   help="row count above which the eval tail switches to the "
                        "streaming path automatically (a full [N, N] float32 "
                        "similarity matrix at this default is ~1.6 GB; six of "
                        "them is the host-memory wall)")
    p.add_argument("--eval_reps", default="tfidf,binary_count,encoded",
                   help="comma list of representations to AUROC-evaluate. At "
                        "very large N the wide sparse reps (tfidf/binary at "
                        "50k features) cost ~F/D times the encoded sweep — "
                        "restrict to 'encoded' for scale runs")
    p.add_argument("--sparse_feed", type=int, default=1,
                   help="1 (default): scipy-sparse train/validation sets feed "
                        "the device as (indices, values) pairs and densify "
                        "on-device — bit-identical math, ~50x fewer feed bytes; "
                        "0: dense host batches")
    p.add_argument("--resident_feed", default="auto",
                   choices=["auto", "on", "off"],
                   help="resident-epoch execution (train/resident.py): keep "
                        "the train set in device HBM and run each epoch as ONE "
                        "lax.scan dispatch instead of one dispatch per batch "
                        "(same batches/PRNG chain, tested equivalent). 'auto' "
                        "(default) enables it on TPU backends when the feed "
                        "fits the device budget")
    return p


def apply_env_overrides(args, env=os.environ):
    """Reference behavior: presence of a key in the environment overrides the flag
    (main_autoencoder.py:36-92) — with the corr_type/corr_frac miswiring fixed."""
    for name in vars(args):
        if name not in env:
            continue
        raw = env[name]
        if name in _BOOL_FLAGS:
            setattr(args, name, True)
        else:
            cur = getattr(args, name)
            if isinstance(cur, bool):
                setattr(args, name, True)
            elif isinstance(cur, int):
                setattr(args, name, int(raw))
            elif isinstance(cur, float):
                setattr(args, name, float(raw))
            else:
                setattr(args, name, raw)
    return args


def validate(args, triplet_mode=False):
    """Cross-field asserts (reference main_autoencoder.py:94-111)."""
    assert 0.0 <= args.min_df <= 1.0
    assert 0.0 <= args.max_df <= 1.0
    assert args.max_features >= 1
    assert 0.0 <= args.corr_frac <= 1.0
    assert args.verbose_step > 0
    if args.input_format == "tfidf":
        assert args.loss_func in ("mean_squared", "cosine_proximity"), (
            "tfidf input is not Bernoulli — cross_entropy is invalid "
            "(reference main_autoencoder.py:108-109)")
    if getattr(args, "label2", "none") != "none":
        assert args.label2 != args.label, (
            "--label2 must differ from --label (same label twice is just a "
            "larger --alpha)")
        assert args.triplet_strategy != "none", (
            "--label2 adds a second MINING term; it needs --triplet_strategy")
        assert getattr(args, "n_experts", 1) == 1, (
            "--label2 is not implemented for the MoE estimator "
            "(moe_loss_and_metrics mines the primary label only); drop "
            "--n_experts or --label2")
    if getattr(args, "n_experts", 1) > 1:
        assert not triplet_mode, (
            "--n_experts selects the MoE estimator, which has no precomputed-"
            "triplet variant — it is only valid on main_autoencoder")
        if args.n_devices > 1:
            assert args.n_devices == args.n_experts, (
                "expert parallelism places one expert per device: --n_experts "
                f"{args.n_experts} must equal --n_devices {args.n_devices}")
            assert getattr(args, "model_parallel", 1) == 1, (
                "--n_experts and --model_parallel are mutually exclusive mesh "
                "layouts")
    if args.main_dir == "":
        args.main_dir = args.model_name
    return args


def parse_flags(argv=None, triplet_mode=False, dotenv_path=".env"):
    if Path(dotenv_path).exists():
        print(".env found, will override all flags using values in .env")
        load_dotenv(dotenv_path)
    args = build_parser(triplet_mode).parse_args(argv)
    apply_env_overrides(args)
    return validate(args, triplet_mode)
