from .dirs import create_run_directories  # noqa: F401
from .seeding import resolve_seed  # noqa: F401
from .provenance import write_parameter_file  # noqa: F401
from .metrics import MetricsWriter  # noqa: F401
from .checkpoint import save_checkpoint, load_checkpoint, latest_checkpoint  # noqa: F401
