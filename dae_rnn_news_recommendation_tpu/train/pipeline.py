"""Overlapped sparse-feed pipeline: double-buffered async H2D prefetch.

Why this exists: the streaming fit path hands HOST numpy batches straight to
jit, so every step pays its host->device transfer synchronously inside the
dispatch — over a thin link (the axon TPU tunnel: ~15-60 MB/s effective,
bench.py `h2d_bandwidth_mbytes_per_sec`) the chip idles while bytes trickle
in, which is
exactly the measured stream-vs-resident gap (BENCH_r05: 30.9k vs 65.4k
articles/sec). The resident path (train/resident.py) closes that gap only when
the whole corpus fits the HBM budget; a production news corpus (millions of
articles) does not.

This module is the middle way: batches stay sparse on the wire (padded CSR
(indices, values) pairs, ~nnz*6 bytes/row instead of dense F*4 — the
data/batcher.SparseIngestBatcher layout), a background worker issues
`jax.device_put` up to `depth` batches AHEAD of consumption (double/triple
buffering — transfer of batch i+1..i+depth overlaps compute of batch i), and
the consumer hands device-RESIDENT refs to a jitted step that densifies on
device (ops/sparse_ingest.densify_on_device via train/step.materialize_x) and
donates its input buffers (`make_train_step(donate_batch=True)`) so each
consumed batch's HBM is recycled into the next allocation instead of churning.

The pipeline never touches a batch after yielding it — the consumer is the
sole owner, which is what makes input donation safe (tests/test_pipeline.py
asserts the donated buffers are deleted and the host copies untouched).

Shape bucketing: XLA compiles one program per input shape, so a ragged tail
batch (or any iterator that emits varying leading dims) would recompile the
step mid-epoch. `bucket_pad` pads each batch's leading dim up to a fixed
bucket set (`bucket_sizes`), bounding compilations at len(buckets) per epoch;
padded rows carry row_valid=0 / labels=-1, exactly the PaddedBatcher contract,
so the math is unchanged.

Instrumentation: `FeedStats` splits each epoch's wall time into feed-wait
(consumer blocked on the queue — the chip would be idle) vs step-compute, and
exposes `feed_stall_fraction` = feed_wait / epoch. The estimator logs it per
epoch (utils/metrics.MetricsWriter.feed_stats) and bench.py reports it next to
`fit_pipelined_articles_per_sec`, so the stream->resident gap is a measured,
regression-tracked number instead of folklore.

No reference counterpart: the reference's only feed is the synchronous
in-process feed_dict copy (SURVEY §5.8). Pipelined input prefetch as a
first-class runtime concern follows the TensorFlow system paper (arXiv
1605.08695 §4.2); shipping sparse payloads and densifying device-side follows
"Densifying Assumed-sparse Tensors" (arXiv 1905.04035).
"""

import queue
import threading
import time

import jax
import numpy as np

from .. import telemetry
from ..reliability import faults as _faults

# Keys whose padding rows must be flagged invalid rather than zero-filled
# (PaddedBatcher contract: padded labels never share a class with real rows).
_PAD_MINUS_ONE = ("labels", "labels2")


class FeedStats:
    """Per-epoch feed-wait vs step-compute split for a pipelined feed.

    feed_wait_s counts the time the CONSUMER spent blocked waiting for the
    next device-resident batch — i.e. time the device had nothing new to
    chew on because the feed fell behind. step_time_s is the rest of the
    epoch (dispatch + the epoch-end sync that drains the device queue).
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self.feed_wait_s = 0.0
        self.epoch_s = 0.0
        self.batches = 0
        self.bytes_in = 0
        self.rows_real = 0
        self.rows_padded = 0

    def note_wait(self, dt):
        self.feed_wait_s += dt
        self.batches += 1

    def note_bytes(self, n):
        self.bytes_in += int(n)

    def note_rows(self, real, padded):
        """Row accounting per staged batch: `real` rows carry data, `padded`
        rows exist only to hit a compiled bucket shape (batcher tail padding
        + bucket_pad). Together they make codec overhead and bucket waste
        regression-tracked numbers instead of folklore."""
        self.rows_real += int(real)
        self.rows_padded += int(padded)

    def finish(self, epoch_s):
        """Record the epoch's total wall time (measured by the caller, who
        also owns the epoch-end device sync)."""
        self.epoch_s = float(epoch_s)

    @property
    def step_time_s(self):
        return max(self.epoch_s - self.feed_wait_s, 0.0)

    @property
    def feed_stall_fraction(self):
        """Fraction of the epoch the consumer sat waiting on the feed.
        ~0 means compute-bound (the pipeline kept the device fed); ~1 means
        the feed is the bottleneck and a deeper buffer / fatter link / the
        resident path is the next lever."""
        return self.feed_wait_s / self.epoch_s if self.epoch_s > 0 else 0.0

    @property
    def padded_row_fraction(self):
        """Fraction of staged rows that were padding (tail + bucket_pad):
        wasted wire bytes AND wasted device FLOPs, both shrinkable by batch
        size / bucket choices."""
        total = self.rows_real + self.rows_padded
        return self.rows_padded / total if total > 0 else 0.0

    @property
    def wire_bytes_per_article(self):
        """Staged bytes per REAL article — the feed's effective wire cost.
        Padded-CSR feeds sit near `kk*6`; the compressed-wire feed
        (data/batcher.WireSparseIngestBatcher) well below it; replayed
        epoch-cache epochs at ~0 (nothing crossed the link)."""
        return self.bytes_in / self.rows_real if self.rows_real > 0 else 0.0

    def summary(self):
        return {
            "feed_wait_s": round(self.feed_wait_s, 4),
            "step_time_s": round(self.step_time_s, 4),
            "feed_stall_fraction": round(self.feed_stall_fraction, 4),
            "feed_batches": self.batches,
            "feed_bytes": self.bytes_in,
            "padded_row_fraction": round(self.padded_row_fraction, 4),
            "wire_bytes_per_article": round(self.wire_bytes_per_article, 2),
        }


def bucket_sizes(batch_size, n_buckets=3, floor=32, multiple=1):
    """The fixed set of leading-dim shapes a pipelined epoch may compile.

    Halving buckets from `batch_size` down to `floor`: a ragged tail of any
    size pads up by at most 2x instead of compiling its own program. Returns
    an ascending tuple; len(buckets) bounds per-epoch compilations.

    `multiple` rounds every bucket up to a multiple of it (deduplicating
    collisions) — feeds driving a microbatch-accumulated step (accum_steps,
    train/step.py) or a data mesh need every compiled shape, ragged-tail
    buckets included, divisible by it.
    """
    assert int(batch_size) >= 1
    assert int(multiple) >= 1
    sizes = {int(batch_size)}
    s = int(batch_size)
    while len(sizes) < n_buckets and s // 2 >= floor:
        s //= 2
        sizes.add(s)
    m = int(multiple)
    if m > 1:
        sizes = {int(-(-sz // m) * m) for sz in sizes}
    return tuple(sorted(sizes))


def bucket_pad(batch, buckets):
    """Pad every leading-B array in `batch` up to the smallest bucket >= B.

    Padded rows follow the PaddedBatcher contract: row_valid 0 (synthesized if
    the batch lacks it), labels -1, everything else zeros — so the padded rows
    are mathematically inert in the step. Batches already at a bucket size (or
    larger than every bucket) pass through untouched.
    """
    if not buckets:
        return batch
    b = _leading_dim(batch)
    if b is None:
        return batch
    target = min((s for s in buckets if s >= b), default=None)
    if target is None or target == b:
        return batch
    out = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        if arr.ndim >= 1 and arr.shape[0] == b:
            fill = -1 if k in _PAD_MINUS_ONE else 0
            pad = np.full((target - b,) + arr.shape[1:], fill, arr.dtype)
            out[k] = np.concatenate([arr, pad])
        else:
            out[k] = v
    if "row_valid" not in out:
        rv = np.zeros(target, np.float32)
        rv[:b] = 1.0
        out["row_valid"] = rv
    return out


def _leading_dim(batch):
    """The batch's row count: row_valid's length when present, else the most
    common leading dim among the non-scalar entries."""
    rv = batch.get("row_valid")
    if rv is not None:
        return len(rv)
    dims = [np.asarray(v).shape[0] for v in batch.values()
            if getattr(np.asarray(v), "ndim", 0) >= 1]
    return max(dims) if dims else None


def _batch_nbytes(batch):
    """Wire bytes of a host batch: numeric arrays only (static entries like
    the WireSpec riding a compressed-wire batch never cross the link)."""
    total = 0
    for v in batch.values():
        arr = np.asarray(v)
        if arr.dtype != object:
            total += arr.nbytes
    return total


class EpochCache:
    """Device-resident epoch cache: pin staged batches during epoch 1, replay
    them for later epochs of a STABLE corpus — post-warm epochs ship zero
    bytes over the H2D link.

    Eligibility is the caller's job (models/estimator.py `_wire_cache_active`:
    shuffle off so the batch sequence repeats, no skip, single device, and the
    consuming step built with donate_batch=False so replayed buffers survive
    consumption). This class only enforces the byte budget: `offer` every
    consumed batch with its wire nbytes during the warm epoch; the first
    offer that would exceed `budget_bytes` flips the cache to `disabled`
    (dropping every pinned ref so HBM frees immediately) and the fit simply
    keeps paying H2D — over-budget is a fallback, never a failure. `seal()`
    after a COMPLETE warm epoch makes `ready` true; `replay()` then yields
    the pinned device batches in the original order.
    """

    def __init__(self, budget_bytes):
        self.budget_bytes = int(budget_bytes)
        self._staged = []
        self._bytes = 0
        self.ready = False
        self.disabled = False
        self.disabled_reason = None
        self.hits = 0

    @property
    def nbytes(self):
        return self._bytes

    @property
    def n_batches(self):
        return len(self._staged)

    def offer(self, staged_batch, nbytes):
        """Pin one consumed device batch (warm epoch only; no-op once ready
        or disabled). `nbytes` is the batch's wire footprint — the HBM the
        pin keeps alive."""
        if self.ready or self.disabled:
            return
        self._bytes += int(nbytes or 0)
        if self._bytes > self.budget_bytes:
            self.disable(
                f"packed corpus exceeds the cache budget "
                f"({self._bytes} > {self.budget_bytes} bytes)")
            return
        self._staged.append(staged_batch)

    def seal(self):
        """Mark the warm epoch complete: `replay()` becomes available. A
        disabled or empty cache stays not-ready."""
        if not self.disabled and self._staged:
            self.ready = True

    def disable(self, reason):
        """Drop every pinned batch (freeing their device buffers with the
        refs) and record why; the feed falls back to staging over the link."""
        self.disabled = True
        self.disabled_reason = str(reason)
        self.ready = False
        self._staged = []
        self._bytes = 0

    def replay(self):
        """Yield the pinned device batches in warm-epoch order. The consumer
        must NOT donate them (they are replayed again next epoch)."""
        assert self.ready, "EpochCache.replay() before seal()"
        for batch in self._staged:
            self.hits += 1
            yield batch


class PipelinedFeed:
    """Iterate device-resident batches, transfers running `depth` ahead.

    :param batches: iterator of host batch dicts (e.g. `batcher.epoch(...)`)
    :param depth: how many batches may be staged on device ahead of the
        consumer (2 = double buffering, 3 = triple, N = N staging slots).
        The worker blocks once `depth` transfers are in flight, bounding
        device memory at ~depth * batch_bytes beyond the consumer's working
        set. Each staged batch is tagged with its slot (`seq % depth`) and
        the `feed/pad` / `feed/h2d` telemetry spans carry the slot id, so a
        trace shows which staging slot each transfer occupied and
        `slot_summary()` reports per-slot H2D seconds — an unbalanced slot
        means the buffer rotation, not the link, is the ceiling.
    :param slots: alias for `depth` (the staging-slot framing); when given it
        wins over `depth`.
    :param place: host batch -> device batch. Defaults to `jax.device_put`
        (single device); the mesh path passes `parallel.feed.put_sharded_batch`
        so each staged batch lands row-sharded over the data axis.
    :param extremes: scalar entries (corr_min/corr_max) merged into every
        batch BEFORE placement — they ride the same transfer and may be
        donated with the rest of the batch.
    :param buckets: optional `bucket_sizes(...)` tuple; ragged batches pad up
        to the nearest bucket (see `bucket_pad`).
    :param stats: optional FeedStats; consumer wait time and staged bytes are
        recorded there.
    :param retry: optional reliability.retry.RetryPolicy; transient staging
        failures (a flaky H2D link, an injected `feed.h2d` fault) are retried
        with bounded backoff on the worker thread, every attempt recorded.

    Yielded batches are owned by the consumer alone: the pipeline drops its
    reference at hand-off, so passing them to a step with donated inputs
    (`make_train_step(donate_batch=True)`) is safe.

    Failure contract: a worker that dies for ANY reason enqueues the end
    sentinel from its `finally` (the poison pill), so a consumer blocked on
    the queue always wakes; the worker's exception is then re-raised on the
    consumer thread with its original traceback. The consumer additionally
    polls worker liveness while waiting, so even a sentinel lost to
    interpreter teardown cannot hang the fit. `stop()` (also run when the
    consumer abandons iteration) signals the worker, drains staged device
    batches, and joins the thread — shutdown leaks neither buffers nor
    threads.
    """

    def __init__(self, batches, depth=2, place=None, extremes=None,
                 buckets=None, stats=None, retry=None, slots=None):
        self._batches = batches
        self.depth = max(1, int(slots if slots is not None else depth))
        self._place = place or jax.device_put
        self._extremes = dict(extremes) if extremes else None
        self._buckets = tuple(buckets) if buckets else None
        self.stats = stats
        self.retry = retry
        self.slot_h2d_s = [0.0] * self.depth
        self.slot_batches = [0] * self.depth
        self._thread = None
        self._queue = None
        self._stop_evt = None

    def _stage(self, host_batch, slot=0):
        """Host batch -> staged device batch (runs on the worker thread).
        `slot` is the staging slot (seq % depth) this batch occupies; it tags
        the telemetry spans and the per-slot accounting."""
        _faults.fire("feed.h2d")
        if self._extremes:
            host_batch = {**host_batch, **self._extremes}
        with telemetry.span("feed/pad", fence=False,
                            args={"slot": slot}):  # host-only work
            rows_in = None
            if self.stats is not None:
                rv = host_batch.get("row_valid")
                rows_in = (int(np.asarray(rv).sum()) if rv is not None
                           else int(_leading_dim(host_batch) or 0))
            if self._buckets:
                host_batch = bucket_pad(host_batch, self._buckets)
            nbytes = None
            if self.stats is not None or telemetry.enabled():
                nbytes = _batch_nbytes(host_batch)
            if self.stats is not None:
                self.stats.note_bytes(nbytes)
                rows_out = int(_leading_dim(host_batch) or 0)
                self.stats.note_rows(rows_in, max(rows_out - rows_in, 0))
        # device_put dispatches the H2D copy asynchronously; by the time the
        # consumer's step consumes this batch, the bytes are already (or still
        # becoming) resident — that overlap is the whole point. The span fences
        # on the staged batch, so when tracing is on it measures the actual
        # copy (and feeds the transfer/h2d counter); when tracing is off the
        # dispatch stays fully async.
        with telemetry.span("feed/h2d", args={"slot": slot}) as sp:
            staged = sp.fence_on(self._place(host_batch))
        telemetry.record_transfer("h2d", sp.duration_s, nbytes)
        if sp.duration_s is not None:
            self.slot_h2d_s[slot] += sp.duration_s
        self.slot_batches[slot] += 1
        return staged

    def slot_summary(self):
        """Per-staging-slot accounting: how many batches each of the `depth`
        slots staged and the fenced H2D seconds it accumulated (0.0 when
        tracing is off — unfenced dispatch has no honest duration)."""
        return {
            "slots": self.depth,
            "batches": list(self.slot_batches),
            "h2d_s": [round(s, 4) for s in self.slot_h2d_s],
        }

    def __iter__(self):
        q = queue.Queue(maxsize=self.depth)
        end = object()
        err = []
        stop = threading.Event()
        self._queue, self._stop_evt = q, stop

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def stage(hb, slot):
            if self.retry is not None:
                return self.retry.run(self._stage, hb, slot, site="feed.h2d")
            return self._stage(hb, slot)

        def worker():
            try:
                for n, hb in enumerate(self._batches):
                    _faults.fire("feed.worker", batch=n)
                    if not put(stage(hb, n % self.depth)):
                        return
            # jaxcheck: disable=R9 (surfaced on the consumer: __iter__ re-raises err[0] after the end sentinel wakes it)
            except BaseException as e:
                err.append(e)
            finally:
                put(end)  # poison pill: a blocked consumer ALWAYS wakes

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="pipelined-feed")
        self._thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                with telemetry.span("feed/wait", fence=False):  # host block
                    item = self._next_item(q, end, err)
                if self.stats is not None and item is not end:
                    self.stats.note_wait(time.perf_counter() - t0)
                if item is end:
                    if err:
                        # err[0] keeps its original __traceback__ (the raise
                        # site inside the worker), so the consumer's stack
                        # trace points at the real failure, not the queue
                        raise err[0]
                    return
                yield item
                del item  # the consumer owns it now; keep donation safe
        finally:
            # consumer done or abandoning early: shut the worker down cleanly
            self.stop()

    def _next_item(self, q, end, err):
        """Blocking get that survives a worker which died without managing to
        enqueue its sentinel (e.g. interpreter teardown killed it between the
        exception and the finally): poll liveness while waiting."""
        while True:
            try:
                return q.get(timeout=0.2)
            except queue.Empty:
                t = self._thread
                if t is not None and not t.is_alive() and q.empty():
                    if err:
                        raise err[0]
                    return end  # worker finished; sentinel was lost

    def stop(self):
        """Shut the feed down: signal the worker, drain staged batches (their
        device buffers free with the refs), and join the thread. Idempotent;
        safe to call whether iteration finished, failed, or never started."""
        stop, q = self._stop_evt, self._queue
        if stop is None:
            return
        stop.set()
        while True:  # make room so a worker blocked on put() can exit
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        while True:  # drain anything enqueued between the drain and the join
            try:
                q.get_nowait()
            except queue.Empty:
                break
