"""Jit-compiled training and eval steps.

This is the TPU replacement for the reference's Session.run train loop
(autoencoder/autoencoder.py:206-246): one pure function computes
corrupt -> encode -> decode -> mine -> loss -> grad -> optax update, entirely
on device, traced once. Corruption happens *inside* the step from an explicit PRNG key
(the reference corrupts the whole train set per epoch on host, autoencoder.py:218 —
moving it on-device removes the host bottleneck and makes runs reproducible by key).

Batches are dicts of arrays with static shapes:
    x         [B, F] clean dense rows (sparse inputs densified into padded shards)
    labels    [B]    int32 labels (only consumed when mining)
    row_valid [B]    1.0 for real rows, 0.0 for padding
    corr_min/corr_max  scalar corruption extremes (salt_and_pepper only)

`make_train_step(config, optimizer)` returns step(params, opt_state, key, batch) ->
(params, opt_state, metrics). Metrics mirror the reference's per-batch fetches
(autoencoder.py:233): cost, autoencoder_loss, triplet_loss, fraction_triplet,
num_triplet (+ hardest pos/neg dot products for batch_hard).
"""

import jax
import jax.numpy as jnp

from .. import telemetry
from ..models import dae_core
from ..ops import corruption, losses, triplet
from ..telemetry.health import embedding_health, mining_health, sentinel_metrics


# dense key -> its sparse-ingest feed keys (single-input and triplet batches)
_SPARSE_FEED_KEYS = {
    "x": ("indices", "values"),
    "org": ("org_indices", "org_values"),
    "pos": ("pos_indices", "pos_values"),
    "neg": ("neg_indices", "neg_values"),
}


def materialize_x(batch, config):
    """Ensure the dense inputs exist: sparse-ingest feeds ship (indices, values)
    [B, K] pairs and densify ON DEVICE here (inside the jitted step), so the
    feed crosses host->device at ~nnz cost while the math stays identical.
    Covers both the single-input ('x') and precomputed-triplet
    ('org'/'pos'/'neg') batch shapes."""
    from ..ops.sparse_ingest import densify_on_device

    out = None
    for dense_key, (ik, vk) in _SPARSE_FEED_KEYS.items():
        if dense_key not in batch and ik in batch:
            if out is None:
                out = dict(batch)
            out[dense_key] = densify_on_device(out[ik], out[vk],
                                               config.n_features)
    return out if out is not None else batch


def _corrupt_batch(key, batch, config):
    x = batch["x"]
    if config.corr_type == "none":
        return x
    return corruption.corrupt(
        key,
        x,
        config.corr_type,
        config.corr_frac,
        mn=batch.get("corr_min"),
        mx=batch.get("corr_max"),
    )


def loss_and_metrics(params, batch, key, config):
    """Full training objective (reference _create_cost_function_node,
    autoencoder.py:417-442). Returns (cost, metrics_dict)."""
    batch = materialize_x(batch, config)
    x = batch["x"]
    row_valid = batch.get("row_valid")
    x_corr = batch.get("x_corr")
    if x_corr is None:
        x_corr = _corrupt_batch(key, batch, config)

    h = dae_core.encode(params, x_corr, config)
    y = dae_core.decode(params, h, config)

    if config.triplet_strategy != "none":
        if config.triplet_strategy == "batch_all":
            t_loss, data_weight, fraction, num, extras = triplet.batch_all_triplet_loss(
                batch["labels"], h, row_valid=row_valid
            )
        else:
            t_loss, data_weight, fraction, num, extras = triplet.batch_hard_triplet_loss(
                batch["labels"], h, row_valid=row_valid
            )
        if config.label2_alpha > 0.0 and "labels2" in batch:
            # joint two-label mining: a second batch_all term over labels2
            # (always batch_all — batch_hard's max/min would let one label's
            # hardest pair dominate both objectives). Rows active in either
            # term keep their reconstruction weight. labels2 < 0 means "no
            # secondary label" (pd.factorize maps missing stories to -1);
            # those rows sit out this term — without the mask every
            # storyless row would mine as one giant -1 'story'.
            lab2 = batch["labels2"]
            has2 = (lab2 >= 0).astype(h.dtype)
            rv2 = has2 if row_valid is None else row_valid * has2
            t2_loss, data_weight2, _, _, _ = triplet.batch_all_triplet_loss(
                lab2, h, row_valid=rv2
            )
            t_loss = t_loss + config.label2_alpha * t2_loss
            data_weight = jnp.maximum(data_weight, data_weight2)
        ae_loss = losses.weighted_loss(
            x, y, config.loss_func, weight=data_weight, row_valid=row_valid
        )
        cost = ae_loss + config.alpha * t_loss
        metrics = {
            "cost": cost,
            "autoencoder_loss": ae_loss,
            "triplet_loss": t_loss,
            "fraction_triplet": fraction,
            "num_triplet": num,
            **extras,
            # in-graph mining/embedding health (telemetry/health.py): rides
            # the same metric fetch, no extra host sync
            **mining_health(data_weight, fraction, row_valid=row_valid),
        }
    else:
        cost = losses.weighted_loss(x, y, config.loss_func, row_valid=row_valid)
        metrics = {"cost": cost}
    metrics.update(embedding_health(h, row_valid=row_valid))
    return cost, metrics


def triplet_loss_and_metrics(params, batch, key, config):
    """Precomputed-triplet objective (reference autoencoder_triplet.py:296-315):
    three weight-sharing towers — in JAX simply the same pure fn applied thrice —
    summed reconstruction losses + alpha * softplus margin loss.

    Batch keys: org, pos, neg (clean [B,F] each) + row_valid — or their
    sparse-ingest (indices, values) pairs, densified on device here.
    """
    batch = materialize_x(batch, config)
    row_valid = batch.get("row_valid")
    keys = jax.random.split(key, 3)
    hs, ys = {}, {}
    for i, name in enumerate(("org", "pos", "neg")):
        x_corr = batch.get(f"{name}_corr")
        if x_corr is None:
            sub = dict(batch, x=batch[name])
            x_corr = _corrupt_batch(keys[i], sub, config)
        hs[name] = dae_core.encode(params, x_corr, config)
        ys[name] = dae_core.decode(params, hs[name], config)

    tower_loss = {
        n: losses.weighted_loss(batch[n], ys[n], config.loss_func,
                                row_valid=row_valid)
        for n in ("org", "pos", "neg")
    }
    ae_loss = tower_loss["org"] + tower_loss["pos"] + tower_loss["neg"]
    t_loss = triplet.precomputed_triplet_loss(
        hs["org"], hs["pos"], hs["neg"], row_valid=row_valid
    )
    cost = ae_loss + config.alpha * t_loss
    # margin-violation rate for the precomputed path: fraction of valid rows
    # whose anchor sits closer (by dot product) to its negative than to its
    # positive — the precomputed twin of the mining paths' fraction_triplet
    margin = jnp.sum(hs["org"] * hs["pos"] - hs["org"] * hs["neg"], axis=1)
    rv = (jnp.ones_like(margin) if row_valid is None
          else row_valid.astype(margin.dtype))
    violation = jnp.sum((margin < 0.0).astype(margin.dtype) * rv) \
        / jnp.maximum(jnp.sum(rv), 1.0)
    return cost, {
        "cost": cost,
        "autoencoder_loss": ae_loss,
        "triplet_loss": t_loss,
        # per-tower reconstruction trajectory (the reference only exposes the
        # sum as a scalar + per-tower histograms, autoencoder_triplet.py:296-315;
        # the anchor/pos/neg split makes margin-vs-reconstruction dynamics
        # visible in the committed evidence)
        "autoencoder_loss_anchor": tower_loss["org"],
        "autoencoder_loss_pos": tower_loss["pos"],
        "autoencoder_loss_neg": tower_loss["neg"],
        "health/margin_violation_rate": violation,
        # embedding health on the anchor tower (telemetry/health.py)
        **embedding_health(hs["org"], row_valid=row_valid),
    }


def make_train_step(config, optimizer, loss_fn=loss_and_metrics, donate=True,
                    donate_batch=False, health=True):
    """Build the jitted train step. `config` is static; params/opt_state are donated
    so XLA updates them in place in HBM.

    `donate_batch=True` additionally donates the batch dict — for feeds that
    hand the step DEVICE-RESIDENT buffers they will never touch again (the
    pipelined feed, train/pipeline.py): XLA recycles each consumed batch's
    HBM into the next allocation instead of churning fresh buffers per step.
    The streaming path must keep it False (it hands jit host arrays, and the
    prefetch queue may still hold references).

    `health=True` merges the in-graph numeric sentinel
    (telemetry/health.py: isfinite flags, grad/param norms, update ratio)
    into the returned metrics — same fetch, no extra sync; `health=False` is
    the plain step (the overhead baseline in tests/test_health.py)."""

    def step(params, opt_state, key, batch):
        (cost, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, key, config
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if health:
            metrics = {**metrics,
                       **sentinel_metrics(cost, grads, updates, params)}
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, metrics

    donate_argnums = (0, 1) if donate else ()
    if donate_batch:
        donate_argnums = donate_argnums + (3,)
        # Donating the batch frees its buffers either way, but XLA may not be
        # able to RECYCLE every one into an output (e.g. CPU layouts, or the
        # uint16 indices with no same-shaped output); that best-effort case
        # warns once per compile and would pollute every pipelined fit.
        import warnings

        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
    # instrument() fences each call on its result (the returned params/opt
    # state/metrics), so a traced span measures compute, not dispatch; when
    # tracing is off the wrapper is one `if` per call
    return telemetry.instrument(
        jax.jit(step, donate_argnums=donate_argnums), "train/step")


def make_eval_step(config, loss_fn=loss_and_metrics):
    """Validation step: no corruption (the reference feeds the clean set as both
    inputs, autoencoder.py:300-304), no parameter update."""

    def step(params, batch):
        eval_cfg = config
        batch = materialize_x(dict(batch), config)
        # feed clean data as the "corrupted" input, like the reference
        if "org" in batch:
            for n in ("org", "pos", "neg"):
                batch[f"{n}_corr"] = batch[n]
        else:
            batch["x_corr"] = batch["x"]
        _, metrics = loss_fn(params, batch, jax.random.PRNGKey(0), eval_cfg)
        return metrics

    return telemetry.instrument(jax.jit(step), "train/eval_step")


def make_encode_fn(config, donate=False):
    """Jitted encode pass (the reference's transform, autoencoder.py:479-505)."""

    def run(params, x):
        return dae_core.encode(params, x, config)

    return telemetry.instrument(jax.jit(run), "train/encode")
