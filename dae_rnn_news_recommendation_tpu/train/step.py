"""Jit-compiled training and eval steps.

This is the TPU replacement for the reference's Session.run train loop
(autoencoder/autoencoder.py:206-246): one pure function computes
corrupt -> encode -> decode -> mine -> loss -> grad -> optax update, entirely
on device, traced once. Corruption happens *inside* the step from an explicit PRNG key
(the reference corrupts the whole train set per epoch on host, autoencoder.py:218 —
moving it on-device removes the host bottleneck and makes runs reproducible by key).

Batches are dicts of arrays with static shapes:
    x         [B, F] clean dense rows (sparse inputs densified into padded shards)
    labels    [B]    int32 labels (only consumed when mining)
    row_valid [B]    1.0 for real rows, 0.0 for padding
    corr_min/corr_max  scalar corruption extremes (salt_and_pepper only)

`make_train_step(config, optimizer)` returns step(params, opt_state, key, batch) ->
(params, opt_state, metrics). Metrics mirror the reference's per-batch fetches
(autoencoder.py:233): cost, autoencoder_loss, triplet_loss, fraction_triplet,
num_triplet (+ hardest pos/neg dot products for batch_hard).
"""

import jax
import jax.numpy as jnp

from .. import telemetry
from ..models import dae_core
from ..ops import corruption, losses, triplet
from ..telemetry.health import embedding_health, mining_health, sentinel_metrics


# dense key -> its sparse-ingest feed keys (single-input and triplet batches)
_SPARSE_FEED_KEYS = {
    "x": ("indices", "values"),
    "org": ("org_indices", "org_values"),
    "pos": ("pos_indices", "pos_values"),
    "neg": ("neg_indices", "neg_values"),
}

# largest batch "auto" keeps on the dense O(B^3) reference path. At the
# repo's record shapes (B=800, D=500) dense XLA wins — its fusion never
# materializes the cube either (ops/pallas_kernels.py STATUS) — and keeping
# small batches there leaves every existing CPU record byte-stable. Past
# this, the cube's footprint (and at 8k+, its address space) is the binding
# constraint, which is exactly what the tiled paths remove.
_DENSE_AUTO_MAX_ROWS = 1024

MINING_IMPLS = ("auto", "dense", "blockwise", "pallas")


def resolve_mining_impl(mining_impl, batch_rows):
    """Resolve a `mining_impl` config knob to a concrete implementation.

    Static (trace-time) decision: `batch_rows` is a shape and the backend
    query touches no tracers, so the jitted step bakes in exactly one path.

    auto -> "dense" at small batch (<= _DENSE_AUTO_MAX_ROWS: the measured-
    fastest path, and byte-stable with prior records), else "pallas" on TPU
    (VMEM-tiled kernels, ops/pallas_kernels.py) and "blockwise" anywhere
    else (anchor-tiled O(B^2) scan, ops/triplet_blockwise.py — CPU tier-1
    can mine batches the dense cube cannot represent).
    """
    if mining_impl not in MINING_IMPLS:
        raise ValueError(
            f"mining_impl must be one of {MINING_IMPLS}, got {mining_impl!r}")
    if mining_impl != "auto":
        return mining_impl
    if batch_rows <= _DENSE_AUTO_MAX_ROWS:
        return "dense"
    if jax.default_backend() == "tpu":
        return "pallas"
    return "blockwise"


def mine_triplets(strategy, labels, encode, row_valid=None,
                  mining_impl="auto"):
    """Dispatch one mining term to its implementation.

    Returns the shared tuple (loss, data_weight[B], fraction, num, extras)
    whichever path runs; all three implementations are parity-tested against
    each other (tests/test_mining_dispatch.py).
    """
    impl = resolve_mining_impl(mining_impl, encode.shape[0])
    if strategy == "batch_all":
        if impl == "dense":
            return triplet.batch_all_triplet_loss(labels, encode,
                                                  row_valid=row_valid)
        if impl == "blockwise":
            from ..ops.triplet_blockwise import batch_all_triplet_loss_blockwise
            return batch_all_triplet_loss_blockwise(labels, encode,
                                                    row_valid=row_valid)
        from ..ops.pallas_kernels import batch_all_triplet_loss_pallas
        return batch_all_triplet_loss_pallas(labels, encode,
                                             row_valid=row_valid)
    if strategy == "batch_hard":
        if impl == "dense":
            return triplet.batch_hard_triplet_loss(labels, encode,
                                                   row_valid=row_valid)
        if impl == "blockwise":
            from ..ops.triplet_blockwise import batch_hard_triplet_loss_blockwise
            return batch_hard_triplet_loss_blockwise(labels, encode,
                                                     row_valid=row_valid)
        from ..ops.pallas_kernels import batch_hard_triplet_loss_pallas
        return batch_hard_triplet_loss_pallas(labels, encode,
                                              row_valid=row_valid)
    raise ValueError(f"unknown mining strategy: {strategy!r}")


def _unpack_wire_keys(batch):
    """Expand compressed-wire feed keys (`{base}_wire_*`, emitted by
    data/batcher.WireSparseIngestBatcher) back into the padded (indices,
    values) pairs the sparse-ingest path consumes. Runs INSIDE the jitted
    step: the bit-unpack + delta prefix-sum is device work (ops/wire.
    unpack_wire — Pallas on TPU, jnp elsewhere), so the host only ever ships
    the packed words. The `{base}_wire_spec` entry is a static empty-pytree
    WireSpec, so it never hits the wire and keys the compile cache."""
    from ..ops import wire as _wire

    out = None
    for base, (ik, vk) in _SPARSE_FEED_KEYS.items():
        wk = f"{base}_wire_words"
        if base in batch or ik in batch or wk not in batch:
            continue
        if out is None:
            out = dict(batch)
        idx, vals = _wire.unpack_wire(
            out.pop(wk),
            out.pop(f"{base}_wire_first"),
            out.pop(f"{base}_wire_nnz"),
            out.pop(f"{base}_wire_spec"),
            values=out.pop(f"{base}_wire_values", None),
            scale=out.pop(f"{base}_wire_scale", None),
        )
        out[ik], out[vk] = idx, vals
    return out if out is not None else batch


def materialize_x(batch, config):
    """Ensure the dense inputs exist: sparse-ingest feeds ship (indices, values)
    [B, K] pairs and densify ON DEVICE here (inside the jitted step), so the
    feed crosses host->device at ~nnz cost while the math stays identical.
    Compressed-wire feeds first expand their packed words into those same
    pairs (`_unpack_wire_keys`), then share the densify. Covers both the
    single-input ('x') and precomputed-triplet ('org'/'pos'/'neg') batch
    shapes."""
    from ..ops.sparse_ingest import densify_on_device

    batch = _unpack_wire_keys(batch)
    out = None
    for dense_key, (ik, vk) in _SPARSE_FEED_KEYS.items():
        if dense_key not in batch and ik in batch:
            if out is None:
                out = dict(batch)
            out[dense_key] = densify_on_device(out[ik], out[vk],
                                               config.n_features)
    return out if out is not None else batch


def _corrupt_batch(key, batch, config):
    x = batch["x"]
    if config.corr_type == "none":
        return x
    return corruption.corrupt(
        key,
        x,
        config.corr_type,
        config.corr_frac,
        mn=batch.get("corr_min"),
        mx=batch.get("corr_max"),
    )


def loss_and_metrics(params, batch, key, config):
    """Full training objective (reference _create_cost_function_node,
    autoencoder.py:417-442). Returns (cost, metrics_dict)."""
    with jax.named_scope("train/materialize"):
        batch = materialize_x(batch, config)
    x = batch["x"]
    row_valid = batch.get("row_valid")
    x_corr = batch.get("x_corr")
    if x_corr is None:
        with jax.named_scope("train/corrupt"):
            x_corr = _corrupt_batch(key, batch, config)

    with jax.named_scope("train/encode_decode"):
        h = dae_core.encode(params, x_corr, config)
        y = dae_core.decode(params, h, config)

    if config.triplet_strategy != "none":
        mining_impl = getattr(config, "mining_impl", "auto")
        with jax.named_scope("train/mine"):
            t_loss, data_weight, fraction, num, extras = mine_triplets(
                config.triplet_strategy, batch["labels"], h,
                row_valid=row_valid, mining_impl=mining_impl
            )
        if config.label2_alpha > 0.0 and "labels2" in batch:
            # joint two-label mining: a second batch_all term over labels2
            # (always batch_all — batch_hard's max/min would let one label's
            # hardest pair dominate both objectives). Rows active in either
            # term keep their reconstruction weight. labels2 < 0 means "no
            # secondary label" (pd.factorize maps missing stories to -1);
            # those rows sit out this term — without the mask every
            # storyless row would mine as one giant -1 'story'.
            lab2 = batch["labels2"]
            has2 = (lab2 >= 0).astype(h.dtype)
            rv2 = has2 if row_valid is None else row_valid * has2
            t2_loss, data_weight2, _, _, _ = mine_triplets(
                "batch_all", lab2, h, row_valid=rv2, mining_impl=mining_impl
            )
            t_loss = t_loss + config.label2_alpha * t2_loss
            data_weight = jnp.maximum(data_weight, data_weight2)
        ae_loss = losses.weighted_loss(
            x, y, config.loss_func, weight=data_weight, row_valid=row_valid
        )
        cost = ae_loss + config.alpha * t_loss
        metrics = {
            "cost": cost,
            "autoencoder_loss": ae_loss,
            "triplet_loss": t_loss,
            "fraction_triplet": fraction,
            "num_triplet": num,
            **extras,
            # in-graph mining/embedding health (telemetry/health.py): rides
            # the same metric fetch, no extra host sync
            **mining_health(data_weight, fraction, row_valid=row_valid),
        }
    else:
        cost = losses.weighted_loss(x, y, config.loss_func, row_valid=row_valid)
        metrics = {"cost": cost}
    metrics.update(embedding_health(h, row_valid=row_valid))
    return cost, metrics


def triplet_loss_and_metrics(params, batch, key, config):
    """Precomputed-triplet objective (reference autoencoder_triplet.py:296-315):
    three weight-sharing towers — in JAX simply the same pure fn applied thrice —
    summed reconstruction losses + alpha * softplus margin loss.

    Batch keys: org, pos, neg (clean [B,F] each) + row_valid — or their
    sparse-ingest (indices, values) pairs, densified on device here.
    """
    batch = materialize_x(batch, config)
    row_valid = batch.get("row_valid")
    keys = jax.random.split(key, 3)
    hs, ys = {}, {}
    for i, name in enumerate(("org", "pos", "neg")):
        x_corr = batch.get(f"{name}_corr")
        if x_corr is None:
            sub = dict(batch, x=batch[name])
            x_corr = _corrupt_batch(keys[i], sub, config)
        hs[name] = dae_core.encode(params, x_corr, config)
        ys[name] = dae_core.decode(params, hs[name], config)

    tower_loss = {
        n: losses.weighted_loss(batch[n], ys[n], config.loss_func,
                                row_valid=row_valid)
        for n in ("org", "pos", "neg")
    }
    ae_loss = tower_loss["org"] + tower_loss["pos"] + tower_loss["neg"]
    t_loss = triplet.precomputed_triplet_loss(
        hs["org"], hs["pos"], hs["neg"], row_valid=row_valid
    )
    cost = ae_loss + config.alpha * t_loss
    # margin-violation rate for the precomputed path: fraction of valid rows
    # whose anchor sits closer (by dot product) to its negative than to its
    # positive — the precomputed twin of the mining paths' fraction_triplet
    margin = jnp.sum(hs["org"] * hs["pos"] - hs["org"] * hs["neg"], axis=1)
    rv = (jnp.ones_like(margin) if row_valid is None
          else row_valid.astype(margin.dtype))
    violation = jnp.sum((margin < 0.0).astype(margin.dtype) * rv) \
        / jnp.maximum(jnp.sum(rv), 1.0)
    return cost, {
        "cost": cost,
        "autoencoder_loss": ae_loss,
        "triplet_loss": t_loss,
        # per-tower reconstruction trajectory (the reference only exposes the
        # sum as a scalar + per-tower histograms, autoencoder_triplet.py:296-315;
        # the anchor/pos/neg split makes margin-vs-reconstruction dynamics
        # visible in the committed evidence)
        "autoencoder_loss_anchor": tower_loss["org"],
        "autoencoder_loss_pos": tower_loss["pos"],
        "autoencoder_loss_neg": tower_loss["neg"],
        "health/margin_violation_rate": violation,
        # embedding health on the anchor tower (telemetry/health.py)
        **embedding_health(hs["org"], row_valid=row_valid),
    }


def _batch_rows(batch):
    """Static leading batch dimension of a feed dict."""
    if "row_valid" in batch:
        return batch["row_valid"].shape[0]
    return max(v.shape[0] for v in batch.values()
               if getattr(v, "ndim", 0) >= 1)


def split_microbatches(batch, accum_steps):
    """Split a batch dict into scan inputs for gradient accumulation.

    Returns (xs, shared): `xs` holds every array with the batch's leading
    dimension reshaped to [accum_steps, rows/accum_steps, ...] (a free
    relayout — row-major means microbatches are contiguous row slices);
    `shared` holds everything else (the corr_min/corr_max scalars), passed
    to every microbatch unchanged. Trace-time static; raises if accum_steps
    does not divide the batch rows (the estimator's batch-multiple rounding
    guarantees it on its feeds)."""
    rows = _batch_rows(batch)
    if rows % accum_steps != 0:
        raise ValueError(
            f"accum_steps={accum_steps} must divide the batch rows ({rows}); "
            "round the batch size up to a multiple (the estimator's batcher "
            "does this automatically)")
    micro = rows // accum_steps
    xs, shared = {}, {}
    for k, v in batch.items():
        if getattr(v, "ndim", 0) >= 1 and v.shape[0] == rows:
            xs[k] = v.reshape((accum_steps, micro) + tuple(v.shape[1:]))
        else:
            shared[k] = v
    return xs, shared


def grads_and_metrics(loss_fn, config, params, batch, key, accum_steps=1):
    """value_and_grad of `loss_fn`, optionally accumulated over microbatches.

    The one gradient producer shared by the streaming/pipelined step
    (make_train_step), the resident epoch scan (train/resident.py), and the
    mesh-parallel global step (parallel/dp.py). With accum_steps > 1 the
    batch splits into `accum_steps` row-contiguous microbatches and a
    `lax.scan` accumulates their gradients in a donated carry — one traced
    program regardless of accum_steps (no per-microbatch retrace;
    tests/test_accum.py pins the compile count), peak activation memory
    that of ONE microbatch. Each microbatch corrupts under its own key
    (split from the step key), mirroring how the same rows fed as separate
    batches would draw distinct corruption.

    Returns (cost, metrics, grads) with cost/grads MEANED over microbatches
    — identical in expectation to one huge-batch step (every loss term is a
    batch mean) — and each scalar metric averaged the same way. Mining note:
    mining is per-microbatch (triplets never cross microbatch boundaries),
    so at accum_steps>1 the mined population is the microbatch, not the
    effective batch — docs/mining.md covers the tradeoff.
    """
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if accum_steps <= 1:
        (cost, metrics), grads = grad_fn(params, batch, key, config)
        return cost, metrics, grads

    xs, shared = split_microbatches(batch, accum_steps)
    keys = jax.random.split(key, accum_steps)

    def body(carry, sl):
        g_acc, c_acc = carry
        mb, sub = sl
        (cost, metrics), grads = grad_fn(params, {**shared, **mb}, sub,
                                         config)
        g_acc = jax.tree_util.tree_map(lambda a, g: a + g, g_acc, grads)
        return (g_acc, c_acc + cost), metrics

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (g_sum, c_sum), stacked = jax.lax.scan(
        body, (zeros, jnp.zeros((), jnp.float32)), (xs, keys))
    inv = 1.0 / accum_steps
    grads = jax.tree_util.tree_map(lambda g: g * inv, g_sum)
    metrics = jax.tree_util.tree_map(lambda m: jnp.mean(m, axis=0), stacked)
    return c_sum * inv, metrics, grads


def make_train_step(config, optimizer, loss_fn=loss_and_metrics, donate=True,
                    donate_batch=False, health=True, accum_steps=1):
    """Build the jitted train step. `config` is static; params/opt_state are donated
    so XLA updates them in place in HBM.

    `donate_batch=True` additionally donates the batch dict — for feeds that
    hand the step DEVICE-RESIDENT buffers they will never touch again (the
    pipelined feed, train/pipeline.py): XLA recycles each consumed batch's
    HBM into the next allocation instead of churning fresh buffers per step.
    The streaming path must keep it False (it hands jit host arrays, and the
    prefetch queue may still hold references).

    `health=True` merges the in-graph numeric sentinel
    (telemetry/health.py: isfinite flags, grad/param norms, update ratio)
    into the returned metrics — same fetch, no extra sync; `health=False` is
    the plain step (the overhead baseline in tests/test_health.py).

    `accum_steps>1` accumulates gradients over that many row-contiguous
    microbatches inside this SAME jitted program (grads_and_metrics):
    one optimizer update per call, one compile total, sentinel computed on
    the accumulated gradient outside the inner scan. Keeping the whole
    accumulation inside ONE jitted call is also a reliability invariant:
    the host only ever observes params/opt_state between full steps, so a
    crash can never checkpoint a half-accumulated phase — the step cursor
    in docs/reliability.md counts these atomic calls, which is what makes
    crash-exact resume possible without persisting any intra-step state."""
    # Load the autotuner cache now, on the host, before the first trace:
    # the Pallas kernel wrappers inside the step (mining, masking
    # corruption, wire unpack) resolve their tile configs at trace time
    # through tuning.resolve(), and priming here keeps that resolution a
    # warm dict lookup instead of a DB file read mid-trace. The manifest
    # then records each kernel's resolved config + provenance.
    from .. import tuning

    tuning.prime()

    def step(params, opt_state, key, batch):
        with jax.named_scope("train/grads"):
            cost, metrics, grads = grads_and_metrics(loss_fn, config, params,
                                                     batch, key, accum_steps)
        with jax.named_scope("train/update"):
            updates, opt_state = optimizer.update(grads, opt_state, params)
            if health:
                metrics = {**metrics,
                           **sentinel_metrics(cost, grads, updates, params)}
            params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
        return params, opt_state, metrics

    donate_argnums = (0, 1) if donate else ()
    if donate_batch:
        donate_argnums = donate_argnums + (3,)
        # Donating the batch frees its buffers either way, but XLA may not be
        # able to RECYCLE every one into an output (e.g. CPU layouts, or the
        # uint16 indices with no same-shaped output); that best-effort case
        # warns once per compile and would pollute every pipelined fit.
        import warnings

        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
    # instrument() fences each call on its result (the returned params/opt
    # state/metrics), so a traced span measures compute, not dispatch; when
    # tracing is off the wrapper is one `if` per call
    return telemetry.instrument(
        jax.jit(step, donate_argnums=donate_argnums), "train/step")


def make_eval_step(config, loss_fn=loss_and_metrics):
    """Validation step: no corruption (the reference feeds the clean set as both
    inputs, autoencoder.py:300-304), no parameter update."""

    def step(params, batch):
        eval_cfg = config
        batch = materialize_x(dict(batch), config)
        # feed clean data as the "corrupted" input, like the reference
        if "org" in batch:
            for n in ("org", "pos", "neg"):
                batch[f"{n}_corr"] = batch[n]
        else:
            batch["x_corr"] = batch["x"]
        _, metrics = loss_fn(params, batch, jax.random.PRNGKey(0), eval_cfg)
        return metrics

    return telemetry.instrument(jax.jit(step), "train/eval_step")


def make_encode_fn(config, donate=False):
    """Jitted encode pass (the reference's transform, autoencoder.py:479-505)."""

    def run(params, x):
        return dae_core.encode(params, x, config)

    return telemetry.instrument(jax.jit(run), "train/encode")
