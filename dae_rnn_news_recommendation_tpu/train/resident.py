"""One-dispatch-per-epoch training: resident dataset + lax.scan over minibatches.

Why this exists: each jitted call pays a host->device dispatch round trip. Over
a high-latency link (the axon TPU tunnel here: ~23-70 ms per call, measured
2026-08-02 — see bench.py:_hard_sync) a per-batch dispatch leaves the chip ~99%
idle at reference shapes. The TPU-idiomatic fix is to keep the training set
resident in HBM and compile the whole epoch as ONE XLA program: `lax.scan`
gathers each permuted minibatch from the resident arrays, corrupts, mines, and
updates donated params in place. Host traffic per epoch drops to one [S, B]
int32 permutation upload and one stacked-metrics download.

Semantics match the streaming path (models/estimator.py _train_loop_inner)
exactly:
  - the permutation/padding comes from the same PaddedBatcher bookkeeping
    (`_index_batches`), so batch composition per epoch is identical;
  - the per-step PRNG chain is the same `key, sub = jax.random.split(key)`
    sequence, carried through the scan;
  - padded rows are zeroed (x * row_valid) and their labels set to -1, exactly
    as the host batcher emits them.
tests/test_resident.py asserts parameter parity between the two paths.

No reference counterpart: the reference dispatches one Session.run per batch
and corrupts on host once per epoch (autoencoder/autoencoder.py:218, :233).
"""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from .. import telemetry
from ..telemetry.health import sentinel_metrics
from .step import grads_and_metrics, loss_and_metrics

# resident sparse feeds reuse the streaming feed's padded layout
_DENSE_BYTES_PER_VAL = 4


def resident_bytes(train_set, labels=None, labels2=None):
    """Device-memory estimate for keeping `train_set` resident (feed layout).

    Mirrors build_resident's ACTUAL device allocation, not the raw csr
    geometry: pad_csr_rows rounds the pad width up to a multiple of 64
    (k=5 -> kk=64) and switches to uint32 indices when the feature count
    outgrows uint16 — an estimate using the raw k and fixed 2-byte indices
    underestimates ~13x at low density, and resident_feed="auto" would admit
    a feed that OOMs the chip. Labels upload as int32 per row."""
    label_bytes = sum(4 * train_set.shape[0]
                      for lab in (labels, labels2) if lab is not None)
    if sp.issparse(train_set):
        n, f = train_set.shape
        k = int(np.diff(train_set.tocsr().indptr).max(initial=1))
        # same layout rules as ops/sparse_ingest.pad_csr_rows (k_multiple=64,
        # non-binary pad index 0 so the u16->u32 flip happens past f=65536)
        kk = max(64, int(np.ceil(k / 64) * 64))
        idx_bytes = 2 if f <= np.iinfo(np.uint16).max + 1 else 4
        return n * kk * (idx_bytes + 4) + label_bytes
    n, f = train_set.shape
    return n * f * _DENSE_BYTES_PER_VAL + label_bytes


def build_resident(train_set, labels=None, labels2=None, device_put=None):
    """Upload the training set (and labels) to the device once.

    Sparse input keeps the sparse-ingest layout ({indices [N,K] u16/u32,
    values [N,K] f32}, same padded K the streaming SparseIngestBatcher uses),
    densified on device per minibatch; dense input uploads [N, F] float32.
    """
    put = device_put or jax.device_put
    resident = {}
    if sp.issparse(train_set):
        from ..ops.sparse_ingest import pad_csr_rows

        csr = train_set.tocsr()
        if csr.data.dtype != np.float32:
            csr = csr.astype(np.float32)
        k = int(np.diff(csr.indptr).max(initial=1))
        packed = pad_csr_rows(csr, np.arange(csr.shape[0]), k=k)
        resident["indices"] = put(packed["indices"])
        resident["values"] = put(packed["values"])
    else:
        x = np.asarray(train_set, dtype=np.float32)
        resident["x"] = put(x)
    if labels is not None:
        resident["labels"] = put(
            np.asarray(labels).reshape(-1).astype(np.int32))
    if labels2 is not None:
        resident["labels2"] = put(
            np.asarray(labels2).reshape(-1).astype(np.int32))
    return resident


def stack_epoch_indices(batcher, n_rows):
    """One epoch of the batcher's shuffle/pad bookkeeping, stacked for the scan:
    (perm [S, B] int32, row_valid [S, B] f32). Advances the batcher RNG exactly
    like a streaming epoch does, so the two paths see identical batches."""
    perms, valids = [], []
    for idx, _n_real, valid in batcher._index_batches(n_rows):
        perms.append(idx.astype(np.int32))
        valids.append(valid)
    return np.stack(perms), np.stack(valids)


def make_epoch_fn(config, optimizer, loss_fn=loss_and_metrics, health=True,
                  accum_steps=1):
    """Build the jitted whole-epoch function.

    epoch_fn(params, opt_state, key, resident, perm, row_valid, extremes)
      -> (params, opt_state, key, metrics_stacked)

    `perm`/`row_valid` are [S, B]; `metrics_stacked` maps each metric name to a
    [S] array (one entry per step, same order as the streaming loop's per-batch
    metrics). params/opt_state are donated: XLA updates them in place in HBM.

    `loss_fn` is the estimator's `_loss_fn` hook — a subclass overriding the
    objective (e.g. the MoE mixture) must NOT silently train the default one
    here; the estimator additionally gates resident execution on the default
    objective (`_resident_eligible`) because subclass params may not match
    this scan's gather layout.

    `health=True` merges the numeric sentinel (telemetry/health.py) into each
    scan step's metrics slot — stacked [S] like every other metric, fetched
    in the same once-per-epoch download.

    `accum_steps>1` runs each scan step as a microbatch-accumulated update
    (train/step.py grads_and_metrics): an inner scan over row-contiguous
    microbatch slices of the gathered batch, one optimizer update per outer
    step, sentinel on the accumulated gradient — still one compile for the
    whole epoch.
    """

    def gather_batch(resident, idx, rv, extremes):
        batch = dict(extremes)
        batch["row_valid"] = rv
        if "x" in resident:
            # zero padded rows: bit-parity with the host batcher's x[n_real:]=0
            batch["x"] = jnp.take(resident["x"], idx, axis=0) * rv[:, None]
        else:
            batch["indices"] = jnp.take(resident["indices"], idx, axis=0)
            batch["values"] = jnp.take(resident["values"], idx, axis=0) * rv[:, None]
        valid = rv > 0
        if "labels" in resident:
            batch["labels"] = jnp.where(
                valid, jnp.take(resident["labels"], idx), -1)
        if "labels2" in resident:
            batch["labels2"] = jnp.where(
                valid, jnp.take(resident["labels2"], idx), -1)
        return batch

    def epoch_fn(params, opt_state, key, resident, perm, row_valid, extremes):
        def body(carry, sl):
            params, opt_state, key = carry
            idx, rv = sl
            with jax.named_scope("resident/gather"):
                batch = gather_batch(resident, idx, rv, extremes)
            key, sub = jax.random.split(key)
            with jax.named_scope("resident/grads"):
                cost, metrics, grads = grads_and_metrics(
                    loss_fn, config, params, batch, sub, accum_steps)
            with jax.named_scope("resident/update"):
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                if health:
                    metrics = {**metrics, **sentinel_metrics(cost, grads,
                                                             updates, params)}
                params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                                updates)
            return (params, opt_state, key), metrics

        (params, opt_state, key), metrics = jax.lax.scan(
            body, (params, opt_state, key), (perm, row_valid))
        return params, opt_state, key, metrics

    return telemetry.instrument(
        jax.jit(epoch_fn, donate_argnums=(0, 1)), "train/resident_epoch")
