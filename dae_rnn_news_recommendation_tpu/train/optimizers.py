"""Optimizer zoo via optax.

Twin of reference autoencoder/autoencoder.py:444-477 (_create_train_step_node), keeping
the reference's names and hyperparameter semantics:

  gradient_descent -> plain SGD
  ada_grad         -> Adagrad with TF1's default initial accumulator 0.1
  momentum         -> SGD + heavy-ball momentum (TF MomentumOptimizer semantics)
  adam             -> Adam (the reference's latent fourth path, autoencoder.py:471-472)
"""

import optax

OPTIMIZERS = ("gradient_descent", "ada_grad", "momentum", "adam")


def make_optimizer(opt, learning_rate, momentum=0.5):
    if opt == "gradient_descent":
        return optax.sgd(learning_rate)
    if opt == "ada_grad":
        # TF1 AdagradOptimizer initializes its accumulator to 0.1, not 0
        return optax.adagrad(learning_rate, initial_accumulator_value=0.1)
    if opt == "momentum":
        return optax.sgd(learning_rate, momentum=momentum, nesterov=False)
    if opt == "adam":
        return optax.adam(learning_rate)
    raise ValueError(f"unknown optimizer: {opt!r} (want one of {OPTIMIZERS})")
