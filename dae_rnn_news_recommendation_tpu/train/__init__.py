from .optimizers import make_optimizer, OPTIMIZERS  # noqa: F401
from .step import make_train_step, make_eval_step, loss_and_metrics  # noqa: F401
