"""Autotuned kernel configs: one resolve() between the cache and every kernel.

The dispatch contract (ROADMAP item 4a, r20):

    config, provenance = tuning.resolve(op, shape, dtype)

Cache **hit** — a ProfileDB row recorded by the measured search
(tuning/search.py) whose ``config`` passes today's legality laws
(space.validate) — returns the tuned config with provenance ``"tuned"``.
**Miss** — no DB, no row for this op|shape|dtype|device_kind key, a stale or
foreign row, an interpreter-tuned row on a real TPU host — falls back to the
hand-picked defaults (ops/tile_defaults.py) with provenance ``"default"``,
bit-for-bit the pre-r20 behavior. Either way the resolution is memoized per
process and logged, so the run manifest records exactly which config every
kernel dispatched with and where it came from.

Zero-recompile discipline: resolve() is pure host work — one DB file read
per process (at first resolve or at ``prime()``), then dict lookups. For a
fixed key it always returns the same config, so jit caches keyed on the
resolved tile sizes never see a second value; `ServingCorpus`/service
``warmup()`` call ``prime()`` before compiling the serving variants, and the
r09/r19 zero-post-warm-recompile contract holds with tuning enabled (pinned
by tests/test_tuning.py).

Off switch: ``DAE_TUNING=0`` (or ``configure(enabled=False)``) makes every
resolution a default-provenance miss — the bench's default leg and the
fallback story in one line. ``DAE_TUNING_DB`` points resolution at a
specific capture (defaults to the repo ProfileDB next to the evidence,
``DAE_PROFILE_DB`` honored as the shared location).
"""

import os
import threading
import warnings

from ..ops import tile_defaults as td
from . import space

__all__ = ["resolve", "prime", "reset", "configure", "resolutions",
           "resolution_manifest", "cap_multiple_hint", "default_db_path",
           "tune_op", "tune_default_shapes", "space"]


def tune_op(*args, **kwargs):
    from . import search

    return search.tune_op(*args, **kwargs)


def tune_default_shapes(*args, **kwargs):
    from . import search

    return search.tune_default_shapes(*args, **kwargs)


def default_db_path():
    """The ProfileDB resolution reads, first match wins: ``DAE_TUNING_DB``,
    ``DAE_PROFILE_DB``, the repo's evidence DB (where bench.py records)."""
    for var in ("DAE_TUNING_DB", "DAE_PROFILE_DB"):
        p = os.environ.get(var)
        if p:
            return p
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "evidence", "profile_db.json")


_lock = threading.Lock()
_state = {
    "enabled": None,      # None: read DAE_TUNING at first resolve
    "db_path": None,      # None: default_db_path() at first load
    "rows": None,         # key -> row, loaded once per process
    "cache": {},          # resolve key -> (config, provenance)
    "log": {},            # resolve key -> resolution record (insert-ordered)
}


def _enabled_locked():
    if _state["enabled"] is None:
        _state["enabled"] = os.environ.get("DAE_TUNING", "1") not in (
            "0", "false", "no", "off")
    return _state["enabled"]


def _rows_locked():
    if _state["rows"] is None:
        rows = {}
        path = _state["db_path"] or default_db_path()
        _state["db_path"] = path
        if os.path.exists(path):
            try:
                from ..telemetry.profile_db import ProfileDB, row_key

                for row in ProfileDB(path).rows():
                    rows[row_key(row["op"], row["shape"], row["dtype"],
                                 row["device_kind"])] = row
            except Exception as exc:
                # a corrupt DB degrades to defaults, never raises — but the
                # operator should hear about it (a tuned fleet silently
                # running hand-picked defaults is a perf regression)
                rows = {}
                warnings.warn(f"tuning: could not load ProfileDB at {path} "
                              f"({exc!r}); kernels fall back to defaults",
                              RuntimeWarning, stacklevel=3)
        _state["rows"] = rows
    return _state["rows"]


def _device_kind():
    from ..telemetry.devprof import _device_kind as dk

    return dk()


def _tuned_config_locked(op, shape, dtype, device_kind):
    """The tuned config for one key, or None on any admission doubt."""
    from ..telemetry.profile_db import row_key

    shape_str = "x".join(str(int(s)) for s in shape)
    row = _rows_locked().get(row_key(op, shape_str, str(dtype), device_kind))
    if row is None:
        return None
    config = row.get("config")
    tuner = row.get("tuner")
    if not isinstance(config, dict) or not isinstance(tuner, dict):
        return None  # pre-r20 profile row (plain measurement, no tuning)
    if not tuner.get("admitted"):
        return None
    if tuner.get("interpret") and "tpu" in (device_kind or "").lower():
        return None  # interpreter capture is not a hardware config
    if not space.validate(op, config, shape, dtype):
        return None  # stale/foreign row vs today's legality laws
    return {k: int(v) for k, v in config.items()}


def resolve(op, shape, dtype, device_kind=None):
    """(config dict, provenance) for one kernel dispatch — see module
    docstring. `shape` follows the per-op key conventions documented in
    tuning/space.py; `dtype` is the str/np dtype name the key was tuned
    under."""
    shape = tuple(int(s) for s in shape)
    dtype = str(dtype)
    device_kind = device_kind or _device_kind()
    key = (op, shape, dtype, device_kind)
    with _lock:
        hit = _state["cache"].get(key)
        if hit is not None:
            return dict(hit[0]), hit[1]
        config = (_tuned_config_locked(op, shape, dtype, device_kind)
                  if _enabled_locked() else None)
        provenance = "tuned" if config is not None else "default"
        if config is None:
            config = td.default_config(op, shape)
        _state["cache"][key] = (config, provenance)
        _state["log"][key] = {
            "op": op, "shape": "x".join(str(s) for s in shape),
            "dtype": dtype, "device_kind": device_kind,
            "config": dict(config), "provenance": provenance,
        }
        return dict(config), provenance


def cap_multiple_hint(device_kind=None):
    """The IVF layout capacity multiple a tuned capture recommends for this
    device, else the hand-picked default. Layout build happens before k and
    probes are known, so this scans every admitted ivf_topk row for the
    device and takes the most common winning ``cap_multiple`` (ties: the
    smallest — least padding). The choice is logged like any resolution."""
    device_kind = device_kind or _device_kind()
    with _lock:
        votes = {}
        if _enabled_locked():
            for row in _rows_locked().values():
                if row.get("op") != "ivf_topk":
                    continue
                if row.get("device_kind") != device_kind:
                    continue
                config = row.get("config")
                tuner = row.get("tuner")
                if not isinstance(config, dict) or not isinstance(tuner, dict):
                    continue
                if not tuner.get("admitted") or tuner.get("alias_of"):
                    continue
                if tuner.get("interpret") and "tpu" in device_kind.lower():
                    continue
                mult = int(config.get("cap_multiple", 0))
                if mult >= 32 and mult % 32 == 0:
                    votes[mult] = votes.get(mult, 0) + 1
        if votes:
            mult, provenance = min(
                votes, key=lambda m: (-votes[m], m)), "tuned"
        else:
            mult, provenance = td.IVF_CAP_MULTIPLE, "default"
        key = ("ivf_layout", (), "", device_kind)
        _state["log"][key] = {
            "op": "ivf_layout", "shape": "", "dtype": "",
            "device_kind": device_kind,
            "config": {"cap_multiple": mult}, "provenance": provenance,
        }
        return mult


def prime(db_path=None):
    """Load the tuning DB now (one disk read), so every later resolve() is
    pure dict work — called by service warmup() before compiling serving
    variants. Returns the number of tuned rows available."""
    with _lock:
        if db_path is not None and db_path != _state["db_path"]:
            _state["db_path"] = db_path
            _state["rows"] = None
            _state["cache"].clear()
        rows = _rows_locked()
        return sum(1 for r in rows.values()
                   if isinstance(r.get("config"), dict)
                   and isinstance(r.get("tuner"), dict)
                   and r["tuner"].get("admitted"))


def configure(enabled=None, db_path=None):
    """Process-wide tuning switches (tests, bench default leg, CLI)."""
    with _lock:
        if enabled is not None:
            _state["enabled"] = bool(enabled)
            _state["cache"].clear()
            _state["log"].clear()
        if db_path is not None:
            _state["db_path"] = db_path
            _state["rows"] = None
            _state["cache"].clear()
            _state["log"].clear()


def reset():
    """Forget everything: cache, log, loaded rows, switches (back to env)."""
    with _lock:
        _state["enabled"] = None
        _state["db_path"] = None
        _state["rows"] = None
        _state["cache"].clear()
        _state["log"].clear()


def resolutions():
    """Every distinct resolution this process made, in first-use order."""
    with _lock:
        return [dict(r) for r in _state["log"].values()]


def resolution_manifest():
    """The run-manifest fragment: where configs came from, per kernel."""
    with _lock:
        recs = [dict(r) for r in _state["log"].values()]
        return {
            "enabled": bool(_state["enabled"]) if _state["enabled"] is not None
            else os.environ.get("DAE_TUNING", "1") not in (
                "0", "false", "no", "off"),
            "db_path": _state["db_path"] or default_db_path(),
            "n_tuned": sum(1 for r in recs if r["provenance"] == "tuned"),
            "n_default": sum(1 for r in recs if r["provenance"] == "default"),
            "resolutions": recs,
        }
