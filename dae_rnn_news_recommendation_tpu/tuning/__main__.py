"""Offline autotuner CLI.

    python -m dae_rnn_news_recommendation_tpu.tuning tune \
        [--select topk_fused,ivf_topk] [--budget-s 120] [--db PATH] \
        [--n 5] [--warmup 1] [--seed 0] [--shape 64x4096x512x10] \
        [--dtype float32] [--interpret]
    python -m dae_rnn_news_recommendation_tpu.tuning show  [--db PATH]
    python -m dae_rnn_news_recommendation_tpu.tuning clear [--select op] \
        [--db PATH]

``tune`` races the candidate grids for each selected op over its
representative shapes (tuning/space.default_shapes; override one key with
--shape/--dtype) and records winners into the ProfileDB. On a TPU host this
is the capture workflow: tune there, commit the DB, and every later serving/
training run resolves the tuned tiles. ``show`` renders the tuned-vs-default
table (the same renderer as ``telemetry report --tuning``); ``clear`` drops
tuned rows (plain profile measurements are left alone).
"""

import argparse
import sys

from ..ops import tile_defaults as td


def _parse_ops(select):
    if not select:
        return list(td.TUNED_OPS)
    ops = [s.strip() for s in select.split(",") if s.strip()]
    unknown = [o for o in ops if o not in td.TUNED_OPS]
    if unknown:
        raise SystemExit(f"unknown op(s) {unknown}; have {list(td.TUNED_OPS)}")
    return ops


def _cmd_tune(args):
    from ..telemetry.profile_db import ProfileDB
    from . import default_db_path
    from .search import tune_default_shapes, tune_op

    path = args.db or default_db_path()
    db = ProfileDB(path)
    ops = _parse_ops(args.select)
    budget = None if args.budget_s is None else float(args.budget_s)
    per_op = None if budget is None else max(budget / len(ops), 1.0)
    log = lambda msg: print(msg, file=sys.stderr)  # noqa: E731
    n_rows = 0
    for op in ops:
        if args.shape:
            shape = tuple(int(s) for s in args.shape.split("x"))
            row = tune_op(op, shape, args.dtype, db=db, n=args.n,
                          warmup=args.warmup, seed=args.seed,
                          budget_s=per_op, interpret=args.interpret,
                          log=log)
            rows = [row] if row is not None else []
        else:
            rows = tune_default_shapes(op, db=db, n=args.n,
                                       warmup=args.warmup, seed=args.seed,
                                       budget_s=per_op,
                                       interpret=args.interpret, log=log)
        for row in rows:
            t = row["tuner"]
            print(f"{op} {row['shape']} {row['dtype']} "
                  f"[{row['device_kind']}]: {row['config']} "
                  f"{row['best_ms']:.3f} ms "
                  f"(default {t['default_best_ms']:.3f} ms, "
                  f"x{t['speedup_vs_default']:.3f})")
        n_rows += len(rows)
    print(f"recorded {n_rows} tuned row(s) -> {path}")
    return 0


def _cmd_show(args):
    from ..telemetry.report import load_profile, render_text, tuning_summary
    from . import default_db_path

    path = args.db or default_db_path()
    try:
        dump = load_profile(path)
    except Exception as e:
        print(f"cannot read ProfileDB at {path}: {e}", file=sys.stderr)
        return 1
    print(render_text([], tuning=tuning_summary(dump)))
    return 0


def _cmd_clear(args):
    from ..telemetry.profile_db import ProfileDB
    from . import default_db_path

    path = args.db or default_db_path()
    db = ProfileDB(path)
    ops = set(_parse_ops(args.select))
    keep, dropped = {}, 0
    for key, row in db._rows.items():
        if isinstance(row.get("tuner"), dict) and row.get("op") in ops:
            dropped += 1
        else:
            keep[key] = row
    db._rows = keep
    db.save()
    print(f"dropped {dropped} tuned row(s) from {path}")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m dae_rnn_news_recommendation_tpu.tuning",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("tune", help="race candidate grids, record winners")
    t.add_argument("--select", default=None,
                   help="comma-separated ops (default: all tunable ops)")
    t.add_argument("--budget-s", default=None, type=float,
                   help="total wall-clock budget, split across selected ops")
    t.add_argument("--db", default=None, help="ProfileDB path")
    t.add_argument("--n", default=5, type=int, help="timed iterations")
    t.add_argument("--warmup", default=1, type=int)
    t.add_argument("--seed", default=0, type=int)
    t.add_argument("--shape", default=None,
                   help="one explicit AxBxC tuning shape instead of the "
                        "representative set (requires --select with one op)")
    t.add_argument("--dtype", default="float32")
    t.add_argument("--interpret", action="store_true",
                   help="force Pallas interpreter mode (parity exercising "
                        "off-TPU; timings are not hardware figures)")
    t.set_defaults(fn=_cmd_tune)

    s = sub.add_parser("show", help="tuned-vs-default table from a ProfileDB")
    s.add_argument("--db", default=None)
    s.set_defaults(fn=_cmd_show)

    c = sub.add_parser("clear", help="drop tuned rows (measurements stay)")
    c.add_argument("--select", default=None)
    c.add_argument("--db", default=None)
    c.set_defaults(fn=_cmd_clear)

    args = p.parse_args(argv)
    if getattr(args, "shape", None) and (not args.select
                                         or "," in args.select):
        p.error("--shape requires --select with exactly one op")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
