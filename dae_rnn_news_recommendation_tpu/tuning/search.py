"""Measured tile-grid search: fenced best-of-N per candidate, parity first.

One entry point — ``tune_op(op, shape, dtype)`` — races every candidate the
static pruner (tuning/space.py) admits for one tuning key, on synthetic
operands seeded from the key, and records the winner in a ProfileDB row
grown with ``config`` + ``tuner`` provenance. The discipline, in order:

  1. **Parity before admission.** Each candidate runs once and its outputs
     are compared bitwise against (a) the default config's outputs — the
     acceptance bar: a tuned tile must change NOTHING in the observed bytes
     — and (b) the op's independent dense/exact oracle where one exists
     (``_topk_reference``, the IVF jnp scorer, ``unpack_wire_jnp``). A
     faster-but-wrong candidate is a hard reject, never measured. The
     ``masking`` kernel mixes ``pl.program_id`` into its PRNG stream, so
     cross-config bytes legitimately differ: it is checked against seeded
     determinism + structural invariants instead, and only on real TPU
     hardware (space.PARITY says which discipline each op gets).

  2. **Fenced timing, compiles absorbed.** Admitted candidates go through
     ``telemetry.devprof.measure`` — every timed iteration ends in a real
     host fetch, warmup absorbs each config's compile, and any compile that
     still lands inside a timed iteration excludes that sample (``n_clean``
     travels as provenance). ``compile_guard`` budgets are never charged:
     tuning happens strictly outside guarded regions.

  3. **The default is always measured first**, so ``speedup_vs_default`` is
     an in-race figure (same operands, same fences, same best-of-N) and the
     winner can never be slower than the hand-picked default — at worst the
     default wins its own race and the row pins speedup 1.0.

The wall-clock budget uses ``time.monotonic()`` (deadline arithmetic, the
jaxcheck R2-exempt clock); the timed regions themselves are all inside
``devprof.measure``'s fenced loop.
"""

import time

import numpy as np

from ..ops import tile_defaults as td
from ..telemetry import devprof
from . import space

_TUNER_VERSION = 1

# drop fraction tolerance for the masking invariant check: 6 sigma of the
# per-element Bernoulli at the checked v (false-reject ~1e-9 per candidate)
_MASK_SIGMA = 6.0


def _log(log, msg):
    if log is not None:
        log(msg)


def _seeded(seed):
    return np.random.default_rng(int(seed) & 0xFFFFFFFF)


def _quantize_rows(embf):
    """Per-row absmax int8 quantization (the serving corpus recipe):
    int8 codes + f32 dequant scales."""
    absmax = np.maximum(np.abs(embf).max(axis=1), 1e-6)
    scale = (absmax / 127.0).astype(np.float32)
    q = np.clip(np.rint(embf / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def _cast_emb(embf, dtype):
    """(device corpus array, scales-or-None) for one tuning dtype."""
    import jax.numpy as jnp

    if str(dtype) == "int8":
        q, scale = _quantize_rows(embf)
        return jnp.asarray(q), jnp.asarray(scale)
    if str(dtype) == "bfloat16":
        return jnp.asarray(embf, jnp.bfloat16), None
    return jnp.asarray(embf, jnp.float32), None


# ----------------------------------------------------------- parity compare

def _leaves(out):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(out)]


def _bitwise_eq(got, want):
    a, b = _leaves(got), _leaves(want)
    if len(a) != len(b):
        return False
    return all(x.shape == y.shape and x.dtype == y.dtype
               and np.array_equal(x, y) for x, y in zip(a, b))


def _topk_eq(got, want, *, mask_infinite):
    """(scores, indices) equality: scores bitwise always; indices exact,
    except that slots whose reference score is -inf carry unspecified ids
    when `mask_infinite` (the IVF contract: a candidate set smaller than k
    pads with -inf rows in layout-dependent order)."""
    gs, gi = np.asarray(got[0]), np.asarray(got[1])
    ws, wi = np.asarray(want[0]), np.asarray(want[1])
    if gs.shape != ws.shape or not np.array_equal(gs, ws):
        return False
    if mask_infinite:
        finite = np.isfinite(ws)
        return bool(np.array_equal(gi[finite], wi[finite]))
    return bool(np.array_equal(gi, wi))


# -------------------------------------------------------- problem builders

def _problem_topk_fused(shape, dtype, seed, interpret):
    import jax
    import jax.numpy as jnp

    from ..ops.topk_fused import _topk_reference, topk_fused

    b, n, d, k = shape
    rng = _seeded(seed)
    q = jnp.asarray(rng.standard_normal((b, d)).astype(np.float32))
    embf = rng.standard_normal((n, d)).astype(np.float32)
    validf = np.ones((n,), np.float32)
    validf[rng.integers(0, n, size=max(1, n // 16))] = 0.0
    valid = jnp.asarray(validf)
    emb, scales = _cast_emb(embf, dtype)
    oracle = jax.device_get(_topk_reference(q, emb, valid, k, scales=scales))

    def make_fn(cfg):
        def call():
            return topk_fused(q, emb, valid, k, scales=scales, impl="pallas",
                              interpret=interpret, block=cfg["block"],
                              bq=cfg["bq"])
        return call

    def compare(got, want):
        return _topk_eq(got, want, mask_infinite=False)

    return {"key_shape": tuple(shape), "make_fn": make_fn, "oracle": oracle,
            "compare": compare}


def _problem_ivf_topk(shape, dtype, seed, interpret):
    import jax
    import jax.numpy as jnp

    from ..index.layout import build_cells
    from ..ops.ivf_topk import ivf_topk

    b, c, cap, d, k, probes = shape
    n = c * cap
    rng = _seeded(seed)
    q = jnp.asarray(rng.standard_normal((b, d)).astype(np.float32))
    embf = rng.standard_normal((n, d)).astype(np.float32)
    # row t*c + j belongs to cell j: uniform counts == cap, so the layout's
    # natural capacity is deterministic per cap_multiple
    assign = (np.arange(n) % c).astype(np.int32)
    centroids = embf.reshape(cap, c, d).mean(axis=0).astype(np.float32)
    validf = np.ones((n,), np.float32)
    validf[rng.integers(0, n, size=max(1, n // 16))] = 0.0
    valid = jnp.asarray(validf)
    emb, scales = _cast_emb(embf, dtype)
    scales_np = None if scales is None else np.asarray(scales)

    layouts = {}

    def layout(mult):
        if mult not in layouts:
            layouts[mult] = build_cells(emb, valid, scales_np, centroids,
                                        assign, cap_multiple=mult)
        return layouts[mult]

    default_cells = layout(td.IVF_CAP_MULTIPLE)
    key_shape = (b, c, int(default_cells.cell_cap), d, k, probes)
    oracle = jax.device_get(
        ivf_topk(q, emb, valid, k, cells=default_cells, probes=probes,
                 scales=scales, impl="jnp"))

    def make_fn(cfg):
        cells = layout(int(cfg.get("cap_multiple", td.IVF_CAP_MULTIPLE)))

        def call():
            return ivf_topk(q, emb, valid, k, cells=cells, probes=probes,
                            scales=scales, impl="pallas",
                            interpret=interpret, bq=cfg["bq"])
        return call

    def compare(got, want):
        return _topk_eq(got, want, mask_infinite=True)

    def winner_cap(cfg):
        return int(layout(
            int(cfg.get("cap_multiple", td.IVF_CAP_MULTIPLE))).cell_cap)

    return {"key_shape": key_shape, "make_fn": make_fn, "oracle": oracle,
            "compare": compare, "winner_cap": winner_cap}


def _problem_batch_hard(shape, dtype, seed, interpret):
    import jax.numpy as jnp

    from ..ops.pallas_kernels import batch_hard_triplet_loss_pallas

    b, d = shape
    rng = _seeded(seed)
    labels = jnp.asarray(
        rng.integers(0, max(2, b // 8), size=b).astype(np.int32))
    encf = rng.standard_normal((b, d)).astype(np.float32)
    enc = jnp.asarray(encf, jnp.bfloat16 if str(dtype) == "bfloat16"
                      else jnp.float32)
    validf = np.ones((b,), np.float32)
    validf[rng.integers(0, b, size=max(1, b // 16))] = 0.0
    row_valid = jnp.asarray(validf)

    def make_fn(cfg):
        def call():
            return batch_hard_triplet_loss_pallas(
                labels, enc, row_valid=row_valid,
                block_rows=cfg["block_rows"], interpret=interpret)
        return call

    # no independent oracle row here: the dense-reference parity of the
    # DEFAULT config is pinned by the existing kernel tests; the admission
    # bar for a tuned tile is bitwise equality with that default's output
    # (per-block f32 sums reassociate across block_rows, so any candidate
    # that changes the bytes is honestly rejected)
    return {"key_shape": tuple(shape), "make_fn": make_fn, "oracle": None,
            "compare": _bitwise_eq}


def _problem_wire_unpack(shape, dtype, seed, interpret):
    import jax
    import scipy.sparse as sp

    from ..ops.wire import pack_csr_wire, plan_wire, unpack_wire_jnp
    from ..ops.wire import unpack_wire_pallas

    b, w = shape
    rng = _seeded(seed)
    # synthesize sparse binary rows whose packed width lands near the
    # requested w: k nnz per row over a feature space sized so the gap
    # field stays 16-bit (fields_per_word 2 -> words_per_row ~ k/2)
    k_nnz = max(8, min(2 * int(w), 512))
    n_features = 8192
    dense = np.zeros((b, n_features), np.float32)
    for i in range(b):
        nnz_i = int(rng.integers(max(1, k_nnz // 2), k_nnz + 1))
        cols = rng.choice(n_features, size=nnz_i, replace=False)
        dense[i, cols] = 1.0
    m = sp.csr_matrix(dense)
    spec = plan_wire(m, mode="binary")
    wire = pack_csr_wire(m, spec=spec)
    words = jax.numpy.asarray(wire["words"])
    first = jax.numpy.asarray(wire["first"])
    nnz = jax.numpy.asarray(wire["nnz"])
    key_shape = (b, int(spec.words_per_row))
    oracle = jax.device_get(unpack_wire_jnp(words, first, nnz, spec)[0])

    def make_fn(cfg):
        def call():
            return unpack_wire_pallas(words, first, nnz, spec,
                                      interpret=interpret,
                                      block_rows=cfg["block_rows"])[0]
        return call

    def compare(got, want):
        return _bitwise_eq(got, want)

    return {"key_shape": key_shape, "make_fn": make_fn, "oracle": oracle,
            "compare": compare}


def _problem_masking(shape, dtype, seed, interpret):
    import jax.numpy as jnp

    from ..ops.pallas_kernels import masking_noise_pallas

    b, f = shape
    v = 0.2
    rng = _seeded(seed)
    xf = rng.standard_normal((b, f)).astype(np.float32)
    # keep every element nonzero so "kept" vs "dropped" is unambiguous
    xf = np.where(np.abs(xf) < 1e-3, 1e-3, xf)
    x = jnp.asarray(xf, jnp.bfloat16 if str(dtype) == "bfloat16"
                    else jnp.float32)
    xh = np.asarray(x)

    def make_fn(cfg):
        def call():
            return masking_noise_pallas(int(seed) & 0x7FFFFFFF, x, v,
                                        block_rows=cfg["block_rows"],
                                        interpret=interpret)
        return call

    def invariants(out):
        """Structural checks replacing bitwise parity (PRNG stream is a
        function of the block grid): every element is either kept exactly
        or zeroed, and the drop fraction matches v to Bernoulli noise."""
        o = np.asarray(out)
        kept = o == xh
        dropped = o == 0
        if not np.all(kept | dropped):
            return False
        frac = float(dropped.mean())
        sigma = (v * (1 - v) / o.size) ** 0.5
        return abs(frac - v) <= _MASK_SIGMA * sigma + 1e-6

    def compare(got, want):
        # cross-config outputs are legitimately different bytes; admission
        # for each config = its own invariants + per-seed determinism
        # (checked by the caller via a second run), not equality with want
        return invariants(got)

    return {"key_shape": tuple(shape), "make_fn": make_fn, "oracle": None,
            "compare": compare, "deterministic_rerun": True}


_PROBLEMS = {
    "topk_fused": _problem_topk_fused,
    "ivf_topk": _problem_ivf_topk,
    "batch_hard": _problem_batch_hard,
    "wire_unpack": _problem_wire_unpack,
    "masking": _problem_masking,
}


# ------------------------------------------------------------- search loop

def _on_tpu():
    import jax

    return jax.default_backend() == "tpu"


def tune_op(op, shape, dtype, *, db=None, n=5, warmup=1, seed=0,
            budget_s=None, interpret=None, device_kind=None, log=None):
    """Race every admissible candidate for one (op, shape, dtype) key and
    record the winner.

    :param db: ProfileDB to record into (row saved immediately); None tunes
        without persisting (the row is still returned)
    :param n/warmup: fenced best-of-N parameters per candidate
    :param budget_s: wall-clock budget; the default config is always
        measured, later candidates stop once the budget is spent (the row
        marks ``budget_exhausted``)
    :param interpret: Pallas interpreter mode (None: auto — off-TPU). The
        interpreter measures nothing real; rows tuned there are for parity
        exercising and carry ``interpret: true`` so resolve() never
        mistakes them for hardware captures on a TPU host.
    :returns: the recorded row dict, or None when the op cannot be tuned in
        this environment (masking off hardware).
    """
    if op not in _PROBLEMS:
        raise KeyError(f"unknown tunable op {op!r} (have {list(_PROBLEMS)})")
    if interpret is None:
        interpret = not _on_tpu()
    interpret = bool(interpret)
    if op == "masking" and interpret:
        _log(log, f"skip {op}: PRNG kernel tunes on real TPU hardware only")
        return None

    t0 = time.monotonic()
    shape = tuple(int(s) for s in shape)
    problem = _PROBLEMS[op](shape, dtype, seed, interpret)
    key_shape = problem["key_shape"]
    stats = {}
    cands = space.candidates(op, key_shape, dtype, stats=stats)
    default_cfg = td.default_config(op, key_shape)
    shape_str = "x".join(str(s) for s in key_shape)
    device_kind = device_kind or devprof._device_kind()

    import jax

    reports, measured, default_best = [], [], None
    n_rejected, truncated = 0, False
    default_out = None
    for i, cfg in enumerate(cands):
        if i > 0 and budget_s is not None \
                and time.monotonic() - t0 > budget_s:
            truncated = True
            _log(log, f"{op}: budget {budget_s}s spent after "
                      f"{len(reports)}/{len(cands)} candidates")
            break
        fn = problem["make_fn"](cfg)
        report = {"config": dict(cfg), "admitted": False, "best_ms": None,
                  "reject": None}
        reports.append(report)
        # parity BEFORE admission (this run also pays the config's compile,
        # outside any timed region)
        try:
            out = jax.device_get(fn())
        except Exception as e:  # illegal-at-runtime candidate: reject, go on
            report["reject"] = f"error: {e!r}"[:200]
            _log(log, f"{op}: candidate {cfg} rejected ({report['reject']})")
            n_rejected += 1
            continue
        if i == 0:
            default_out = out
        ok = problem["compare"](out, default_out)
        if ok and problem["oracle"] is not None:
            ok = problem["compare"](out, problem["oracle"])
        if ok and problem.get("deterministic_rerun"):
            ok = _bitwise_eq(jax.device_get(fn()), out)
        if not ok:
            report["reject"] = "parity"
            n_rejected += 1
            continue
        result = devprof.measure(fn, n=n, warmup=warmup, op=op,
                                 shape=shape_str, dtype=str(dtype),
                                 device_kind=device_kind, cost=False)
        report["admitted"] = True
        report["best_ms"] = round(result.best_ms, 6)
        report["n_clean"] = result.n_clean
        measured.append((cfg, result))
        if i == 0:
            default_best = result.best_ms
        _log(log, f"{op} {shape_str} {cfg}: {result.best_ms:.3f} ms"
                  f" (n_clean={result.n_clean})")

    if not measured:
        raise RuntimeError(
            f"tuning {op} {shape_str} {dtype}: no candidate survived "
            f"({n_rejected} parity/run rejects of {len(reports)} tried) — "
            "the default config itself failed, which means the synthetic "
            "problem or the kernel is broken, not the grid")

    win_cfg, win_result = min(measured, key=lambda cr: cr[1].best_ms)
    row = win_result.as_row()
    row["config"] = dict(win_cfg)
    row["tuner"] = {
        "version": _TUNER_VERSION,
        "admitted": True,
        "parity": space.PARITY[op],
        "default_config": dict(default_cfg),
        "default_best_ms": round(default_best, 6),
        "speedup_vs_default": round(default_best / win_result.best_ms, 4),
        "n_candidates": len(cands),
        "n_measured": len(measured),
        "n_rejected": n_rejected,
        "n_pruned_illegal": stats.get("n_illegal", 0),
        "n_pruned_vmem": stats.get("n_vmem", 0),
        "budget_s": budget_s,
        "budget_exhausted": truncated,
        "seed": int(seed),
        "interpret": interpret,
        "candidates": reports,
    }
    if db is not None:
        db.record(row)
        # the ivf winner's cap_multiple changes the layout capacity — and
        # with it the shape a corpus built AT that multiple resolves under.
        # Record an alias row at the winner-layout cap so the tuned bq still
        # hits once the layout itself adopts the tuned multiple.
        winner_cap = problem.get("winner_cap")
        if winner_cap is not None:
            cap = winner_cap(win_cfg)
            if cap != key_shape[2]:
                alias_shape = list(key_shape)
                alias_shape[2] = cap
                alias = dict(row)
                alias["shape"] = "x".join(str(s) for s in alias_shape)
                alias["tuner"] = dict(row["tuner"])
                alias["tuner"]["alias_of"] = shape_str
                db.record(alias)
        db.save()
    return row


def tune_default_shapes(op, *, db=None, n=5, warmup=1, seed=0, budget_s=None,
                        interpret=None, log=None):
    """Tune one op over its representative CLI shapes (space.default_shapes),
    splitting any wall-clock budget evenly. Returns the recorded rows."""
    keys = space.default_shapes(op)
    per = None if budget_s is None else max(budget_s / len(keys), 1.0)
    rows = []
    for kshape, kdtype in keys:
        row = tune_op(op, kshape, kdtype, db=db, n=n, warmup=warmup,
                      seed=seed, budget_s=per, interpret=interpret, log=log)
        if row is not None:
            rows.append(row)
    return rows
